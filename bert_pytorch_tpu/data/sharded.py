"""Streaming sharded-HDF5 pretraining input pipeline.

Reads the same container format as the reference's offline pipeline
(gzip'd HDF5 with keys input_ids / special_token_positions /
next_sentence_labels, written by utils/encode_data.py:204-210; legacy
NVIDIA premasked files with segment_ids/input_mask/masked_lm_* also accepted,
src/dataset.py:183-192), but the runtime design is different:

- **Batch-granular, not sample-granular.** The reference served one sample per
  __getitem__ through a forked DataLoader worker; on TPU-VM the host feeds a
  whole per-host batch per step, so the loader slices contiguous batches
  straight out of the in-RAM shard and masks them vectorized
  (data/masking.py). No worker processes, no per-sample Python.
- **Futures, not bare threads.** The reference handed the prefetched shard
  over via an attribute written by a raw thread with no lock
  (src/dataset.py:210-222, SURVEY §5.2); here a ThreadPoolExecutor future
  carries the result — exceptions propagate and the handoff is synchronized.
- **Per-host contiguous chunking.** Same index math as the reference's custom
  DistributedSampler (src/dataset.py:341-399): the global index space is
  padded to world_size * num_samples and each host takes a contiguous chunk so
  hosts stream different files; the cursor is checkpointable and restores
  mid-epoch (src/dataset.py:401-425 semantics, incl. skip-with-warning when
  world size or dataset size changed).
- **Optional sequence packing** (``packing=True``): each batch row is
  assembled from multiple short examples by the greedy first-fit packer in
  data/packing.py, with block-diagonal ``segment_ids`` / per-segment
  ``position_ids`` / per-segment NSP fields. The packer's carry-over buffer
  is checkpointed as a list of global sample indices alongside the sampler
  cursor, so resume replays the identical bin layout.
"""

from __future__ import annotations

import bisect
import logging
import warnings
from concurrent.futures import Future, ThreadPoolExecutor
from typing import Dict, Iterator, List, Optional, Sequence, Tuple

import numpy as np

from bert_pytorch_tpu.data import masking

logger = logging.getLogger(__name__)

REQUIRED_KEYS = ("input_ids", "next_sentence_labels")


class ShardIndex:
    """Discover + verify shard files and map global sample idx -> (file, row).

    Mirrors the reference's _verify_and_count_samples behavior
    (src/dataset.py:298-338): unreadable files or files whose per-key counts
    disagree are skipped with a warning, not fatal.
    """

    def __init__(self, files: Sequence[str]):
        import h5py

        files = sorted(str(f) for f in files)
        self.files: List[str] = []
        self.starts: List[int] = []  # cumulative start index per file
        # widest masked_lm_positions row across legacy premasked shards
        # (None = all shards are dynamic-masking); reading .shape is free
        self.premasked_width: Optional[int] = None
        total = 0
        for path in files:
            try:
                with h5py.File(path, "r") as f:
                    counts = {len(f[k]) for k in REQUIRED_KEYS}
                    width = None
                    if "masked_lm_positions" in f:
                        shape = f["masked_lm_positions"].shape
                        if len(shape) != 2:
                            warnings.warn(
                                f"skipping shard {path}: masked_lm_positions "
                                f"has shape {shape}, expected 2-D")
                            continue
                        width = int(shape[1])
            except (OSError, KeyError) as e:
                warnings.warn(f"skipping unreadable shard {path}: {e}")
                continue
            if len(counts) != 1:
                warnings.warn(f"skipping shard {path}: per-key sample counts differ")
                continue
            # only shards actually kept contribute to the premasked width
            if width is not None:
                self.premasked_width = max(self.premasked_width or 0, width)
            self.files.append(path)
            self.starts.append(total)
            total += counts.pop()
        if not self.files:
            raise RuntimeError("no valid shard files found")
        self.total = total

    def __len__(self) -> int:
        return self.total

    def locate(self, idx: int) -> Tuple[int, int]:
        """global sample idx -> (file_idx, row_within_file)."""
        if not 0 <= idx < self.total:
            raise IndexError(f"sample {idx} out of range ({self.total})")
        fi = bisect.bisect_right(self.starts, idx) - 1
        return fi, idx - self.starts[fi]

    def file_range(self, fi: int) -> Tuple[int, int]:
        start = self.starts[fi]
        end = self.starts[fi + 1] if fi + 1 < len(self.files) else self.total
        return start, end


def _load_shard(path: str) -> Dict[str, np.ndarray]:
    import h5py

    with h5py.File(path, "r") as f:
        return {k: np.asarray(f[k][:]) for k in f.keys()}


class HostShardSampler:
    """Resumable contiguous per-host index stream.

    Global index space padded (by wraparound) to world_size * num_samples;
    host r owns [r * num_samples, (r+1) * num_samples). state_dict/
    load_state_dict carry the cursor for mid-epoch resume with the same
    compatibility guards as the reference (src/dataset.py:401-425).
    """

    def __init__(self, dataset_size: int, world_size: int = 1, rank: int = 0,
                 seed: int = 0):
        if not 0 <= rank < world_size:
            raise ValueError(f"rank {rank} out of range for world {world_size}")
        self.dataset_size = dataset_size
        self.world_size = world_size
        self.rank = rank
        self.seed = seed
        self.num_samples = -(-dataset_size // world_size)  # ceil
        self.total_size = self.num_samples * self.world_size
        self.index = 0  # position within this host's chunk
        self.epoch = 0

    def __len__(self) -> int:
        return self.num_samples

    def next_indices(self, n: int) -> Optional[np.ndarray]:
        """Next n global sample indices for this host, or None at epoch end
        (partial tail batches are dropped — static shapes for jit)."""
        if self.index + n > self.num_samples:
            return None
        base = self.rank * self.num_samples + self.index
        out = (np.arange(base, base + n) % self.dataset_size)
        self.index += n
        return out

    def reset_epoch(self) -> None:
        self.index = 0
        self.epoch += 1

    def state_dict(self) -> Dict[str, int]:
        return {
            "epoch": self.epoch,
            "seed": self.seed,
            "world_size": self.world_size,
            "total_size": self.total_size,
            "index": self.index,
        }

    def load_state_dict(self, state: Dict[str, int]) -> None:
        if state.get("total_size") != self.total_size:
            warnings.warn(
                "sampler total_size changed "
                f"({state.get('total_size')} -> {self.total_size}); "
                "not restoring sampler state")
            return
        if state.get("world_size") != self.world_size:
            warnings.warn("world size changed; not restoring sampler state")
            return
        self.epoch = state["epoch"]
        self.seed = state["seed"]
        self.index = state["index"]


class PretrainingDataLoader:
    """Iterator of ready-to-device batches with background shard prefetch.

    Yields dicts of numpy arrays shaped (batch, seq):
      input_ids, token_type_ids, attention_mask, masked_lm_labels  (+
      next_sentence_labels (batch,)).

    Dynamic-masking mode applies when shards carry special_token_positions;
    legacy premasked shards are served as-is with dense labels. One shard is
    resident while the next loads on an executor thread — same ≤2-files-in-RAM
    budget as the reference (src/dataset.py docstring), minus the forked
    DataLoader workers.

    prefetch_batches > 0 moves batch assembly (row gather + dynamic masking)
    onto a dedicated executor thread with that many batches in flight, so
    batch N+1 is guaranteed — not incidentally — prepared while the device
    runs batch N (the reference's 4 DataLoader workers served the same
    purpose, run_pretraining.py:384). state_dict() then reports the sampler
    cursor as of the last batch actually YIELDED, not the last one
    assembled ahead, so checkpoint resume replays nothing and skips nothing.
    """

    def __init__(
        self,
        index: ShardIndex,
        sampler: HostShardSampler,
        batch_size: int,
        mask_token_index: Optional[int],
        max_pred_per_seq: int,
        masked_lm_prob: float,
        vocab_size: int,
        original_token_prob: float = 0.1,
        random_token_prob: float = 0.1,
        seed: Optional[int] = None,
        prefetch_batches: int = 0,
        packing: bool = False,
        packing_max_segments: int = 8,
        packing_lookahead: int = 4,
        batch_tap=None,
    ):
        if not 0 <= masked_lm_prob <= 1:
            raise ValueError("masked_lm_prob must be in [0,1]")
        if original_token_prob + random_token_prob > 1:
            raise ValueError("original_token_prob + random_token_prob > 1")
        if max_pred_per_seq < 0:
            raise ValueError("max_pred_per_seq must be >= 0")
        if (index.premasked_width is not None
                and index.premasked_width > max_pred_per_seq):
            # the gathered MLM head scores only max_pred_per_seq positions per
            # row; wider premasked shards would silently lose supervision
            raise ValueError(
                f"premasked shards carry up to {index.premasked_width} masked "
                f"positions per row but max_pred_per_seq={max_pred_per_seq}; "
                "raise --max_predictions_per_seq to at least the shard width "
                "or re-encode the data")
        self.index = index
        self.sampler = sampler
        self.batch_size = batch_size
        self.mask_token_index = mask_token_index
        self.max_pred_per_seq = max_pred_per_seq
        self.masked_lm_prob = masked_lm_prob
        self.vocab_size = vocab_size
        self.original_token_prob = original_token_prob
        self.random_token_prob = random_token_prob
        # masking rng seed: masks are a PURE FUNCTION of
        # (seed, epoch, global sample index) — per-example derivation in
        # _build_examples, the same contract the streaming plane pinned in
        # round 16 (data/streaming.py _example_rng). A resumed run (or the
        # packer rebuilding its carry-over buffer from checkpointed
        # indices) therefore re-derives BIT-identical masks, which is what
        # makes the round-17 survival drill's bit-identity hold on this
        # plane; masks still refresh every epoch (sampler.epoch feeds the
        # derivation). The pre-round-17 single stateful rng advanced with
        # consumption history, so resume replayed different masks.
        self._mask_seed = int(seed if seed is not None else sampler.seed)
        self._pool = ThreadPoolExecutor(max_workers=1,
                                        thread_name_prefix="shard-prefetch")
        self._resident_fi: Optional[int] = None
        self._resident: Optional[Dict[str, np.ndarray]] = None
        self._pending_fi: Optional[int] = None
        self._pending: Optional[Future] = None
        # batch-assembly prefetch: a SEPARATE single-worker executor (the
        # shard pool must stay free — _ensure_resident blocks on it, and
        # sharing one worker would deadlock). Only the assembler thread
        # touches sampler/rng/shard residency once prefetching starts.
        self.prefetch_batches = int(prefetch_batches)
        self._assembler: Optional[ThreadPoolExecutor] = None
        self._queue: List[Future] = []
        # sequence packing (data/packing.py): batch rows assembled from
        # multiple short examples; _pending holds global sample indices
        # fetched but not yet placed in a row (checkpointed for resume)
        self.packing = bool(packing)
        if self.packing and packing_max_segments < 1:
            raise ValueError("packing_max_segments must be >= 1")
        self.packing_max_segments = int(packing_max_segments)
        self.packing_lookahead = max(1, int(packing_lookahead))
        self._pending_examples: List[int] = []
        # built (gathered + masked) rows aligned with _pending_examples, so
        # a carried-over example is masked ONCE when fetched, not re-gathered
        # and re-masked on every batch it waits through (~lookahead x host
        # cost otherwise). None = rebuild lazily from the indices (the state
        # restored from a checkpoint carries indices only).
        self._pending_built: Optional[Dict[str, np.ndarray]] = None
        # batch_tap(batch) fires for every batch this loader YIELDS, on the
        # consumer thread — the flight recorder's capture point at the
        # loader boundary (telemetry/flight_recorder.py). Because it runs
        # at yield (not at assembly), tap order equals consumption order
        # even with the prefetch executor running ahead. Assignable after
        # construction too (run_pretraining attaches it post-peek).
        self.batch_tap = batch_tap
        self._closed = False
        self._last_state = self._state_snapshot()
        if self.prefetch_batches > 0:
            self._assembler = ThreadPoolExecutor(
                max_workers=1, thread_name_prefix="batch-assemble")

    # -- shard residency ----------------------------------------------------

    def _ensure_resident(self, fi: int) -> Dict[str, np.ndarray]:
        if fi == self._resident_fi:
            return self._resident
        if fi == self._pending_fi and self._pending is not None:
            self._resident = self._pending.result()
            self._resident_fi = fi
        else:
            self._resident = _load_shard(self.index.files[fi])
            self._resident_fi = fi
        # queue the host's next file
        nxt = (fi + 1) % len(self.index.files)
        self._pending_fi = nxt
        self._pending = self._pool.submit(_load_shard, self.index.files[nxt])
        return self._resident

    # -- batch assembly -----------------------------------------------------

    def _gather_rows(self, indices: np.ndarray) -> Dict[str, np.ndarray]:
        """Gather rows for (sorted, mostly-contiguous) global indices; may
        span a shard boundary, in which case the next shard becomes resident."""
        out: Dict[str, List[np.ndarray]] = {}
        i = 0
        while i < len(indices):
            fi, row = self.index.locate(int(indices[i]))
            data = self._ensure_resident(fi)
            _, file_end = self.index.file_range(fi)
            # rows from this file: run of indices < file_end
            j = i
            while j < len(indices) and int(indices[j]) < file_end \
                    and int(indices[j]) >= self.index.starts[fi]:
                j += 1
            rows = np.asarray(indices[i:j]) - self.index.starts[fi]
            for k, arr in data.items():
                out.setdefault(k, []).append(arr[rows])
            i = j
        return {k: np.concatenate(v, axis=0) for k, v in out.items()}

    def __iter__(self) -> Iterator[Dict[str, np.ndarray]]:
        return self

    def __next__(self) -> Dict[str, np.ndarray]:
        if self._assembler is not None:
            # pop BEFORE topping up: the batch being waited on does not count
            # against the lookahead, so prefetch_batches=1 still overlaps
            # one assembly with the device step
            if not self._queue:
                self._queue.append(self._assembler.submit(self._assemble_one))
            head = self._queue.pop(0)
            while len(self._queue) < self.prefetch_batches:
                self._queue.append(self._assembler.submit(self._assemble_one))
            batch, state = head.result()
            if batch is None:  # epoch end: drain queued end-markers
                self._drain_queue()
                raise StopIteration
            self._last_state = state
            if self.batch_tap is not None:
                self.batch_tap(batch)
            return batch
        batch = self._assemble_sync()
        if batch is None:
            raise StopIteration
        self._last_state = self._state_snapshot()
        if self.batch_tap is not None:
            self.batch_tap(batch)
        return batch

    def _assemble_one(self):
        """Assembler-thread task: (batch, loader_state_after) or (None, _)
        at epoch end."""
        batch = self._assemble_sync()
        return batch, self._state_snapshot()

    def _assemble_sync(self) -> Optional[Dict[str, np.ndarray]]:
        if self.packing:
            return self._assemble_packed()
        indices = self.sampler.next_indices(self.batch_size)
        if indices is None:
            return None
        return self._build_examples(indices)

    def _assemble_packed(self) -> Optional[Dict[str, np.ndarray]]:
        """One packed batch: top the pending-example buffer up to
        batch_size * packing_lookahead indices, first-fit their real lengths
        into batch_size rows, and emit the packed arrays. Unplaced examples
        stay pending (bounded: the first batch_size pending always place, so
        the buffer never exceeds the lookahead window) WITH their built rows
        cached — each example is gathered and masked exactly once no matter
        how many batches it waits through. At epoch end a batch is only
        emitted if every row holds at least one example — the packed
        analogue of the unpacked loader's dropped partial tail."""
        from bert_pytorch_tpu.data import packing as packing_lib

        def concat(a, b):
            return ({k: np.concatenate([a[k], b[k]]) for k in a}
                    if a is not None else b)

        if self._pending_built is None and self._pending_examples:
            # restored from a checkpoint: indices only — rebuild once
            self._pending_built = self._build_examples(
                np.asarray(self._pending_examples, np.int64))

        target = self.batch_size * self.packing_lookahead
        exhausted = False
        while len(self._pending_examples) < target:
            idx = self.sampler.next_indices(self.batch_size)
            if idx is None:
                exhausted = True
                break
            self._pending_examples.extend(int(i) for i in idx)
            self._pending_built = concat(self._pending_built,
                                         self._build_examples(idx))
        if not self._pending_examples:
            return None
        examples = self._pending_built
        seq_len = examples["input_ids"].shape[1]
        lengths = packing_lib.example_lengths(examples["attention_mask"])
        bins = packing_lib.first_fit(lengths, self.batch_size, seq_len,
                                     self.packing_max_segments)
        if exhausted and any(not members for members in bins):
            # dropped tail, like the unpacked loader
            self._pending_examples = []
            self._pending_built = None
            return None
        batch = packing_lib.pack_examples(examples, bins, seq_len,
                                          self.packing_max_segments)
        placed = {i for members in bins for i in members}
        keep = [pos for pos in range(len(self._pending_examples))
                if pos not in placed]
        self._pending_examples = [self._pending_examples[pos]
                                  for pos in keep]
        self._pending_built = ({k: v[keep] for k, v in examples.items()}
                               if keep else None)
        return batch

    def _build_examples(self, indices: np.ndarray
                        ) -> Dict[str, np.ndarray]:
        raw = self._gather_rows(indices)
        input_ids = raw["input_ids"].astype(np.int32)
        batch: Dict[str, np.ndarray] = {}

        if "special_token_positions" in raw:
            specials = raw["special_token_positions"]
            batch["token_type_ids"] = masking.segment_ids_from_specials(
                input_ids, specials).astype(np.int32)
            batch["attention_mask"] = masking.input_mask_from_specials(
                input_ids, specials).astype(np.int32)
            # per-example cursor-derived rng: resume and the packer's
            # carry-over rebuild re-derive identical masks regardless of
            # how examples were grouped into assembly windows. Only the
            # per-row DRAWS come from per-row generators; the masking
            # logic itself stays one vectorized batch call (a per-row
            # dynamic_mask_batch loop would scale the host assembly cost
            # with batch_size — ruinous at production host batches)
            epoch = self.sampler.epoch
            rngs = [np.random.default_rng(
                        [self._mask_seed, epoch, int(i)])
                    for i in indices]
            masked, labels = masking.dynamic_mask_batch(
                input_ids, specials,
                mask_token_index=self.mask_token_index,
                max_pred_per_seq=self.max_pred_per_seq,
                masked_lm_prob=self.masked_lm_prob,
                vocab_size=self.vocab_size,
                draws=masking.per_row_mask_draws(
                    rngs, input_ids.shape[1], self.vocab_size),
                original_token_prob=self.original_token_prob,
                random_token_prob=self.random_token_prob)
            batch["input_ids"] = masked.astype(np.int32)
            batch["masked_lm_labels"] = labels.astype(np.int32)
        else:  # legacy premasked NVIDIA format
            batch["input_ids"] = input_ids
            batch["token_type_ids"] = raw["segment_ids"].astype(np.int32)
            batch["attention_mask"] = raw["input_mask"].astype(np.int32)
            batch["masked_lm_labels"] = masking.labels_from_premasked(
                input_ids, raw["masked_lm_positions"],
                raw["masked_lm_ids"]).astype(np.int32)

        batch["next_sentence_labels"] = (
            raw["next_sentence_labels"].reshape(-1).astype(np.int32))
        return batch

    def _state_snapshot(self):
        """Live loader state: the sampler cursor plus (under packing) the
        pending-example indices not yet placed in a row. Flat dict, JSON
        serializable — rides in the checkpoint 'extra' payload."""
        state = self.sampler.state_dict()
        if self.packing:
            state["pending"] = list(self._pending_examples)
        return state

    def state_dict(self):
        """Loader state as of the last YIELDED batch — safe to checkpoint
        even with assembly running ahead (prefetch_batches > 0). Without
        prefetch the sampler is never ahead, so its live state is identical
        and callers that mutate the sampler directly stay coherent."""
        if self._assembler is None:
            return self._state_snapshot()
        return dict(self._last_state)

    def load_state_dict(self, state):
        self._drain_queue()
        self.sampler.load_state_dict(state)
        # packed carry-over buffer: restored as global indices (re-gathered
        # on the next assembly); absent in unpacked/legacy checkpoints.
        # Only restored when the SAMPLER accepted its state — if it refused
        # (dataset/world-size changed, warned and reset), the checkpointed
        # indices belong to the old index space and must be dropped with it
        sampler_restored = (
            state.get("total_size") == self.sampler.total_size
            and state.get("world_size") == self.sampler.world_size)
        self._pending_examples = ([int(i) for i in state.get("pending", [])]
                                  if sampler_restored else [])
        self._pending_built = None
        self._last_state = self._state_snapshot()

    def _drain_queue(self):
        """Wait out in-flight assemblies and drop their results (their
        sampler advances are superseded by the restore/reset that follows)."""
        for f in self._queue:
            try:
                f.result()
            except Exception:
                pass
        self._queue.clear()

    def reset_epoch(self):
        """Epoch rollover that is safe under prefetch (the bare
        sampler.reset_epoch remains correct when prefetching is off)."""
        self._drain_queue()
        self.sampler.reset_epoch()
        self._pending_examples = []
        self._pending_built = None
        self._last_state = self._state_snapshot()

    def close(self):
        """Shut both executors down. Idempotent — run_pretraining's
        try/finally, __del__ on an early-aborted iteration (the consuming
        generator dropped mid-epoch), and an explicit user close may all
        fire; only the first does work, and none of them waits on an
        in-flight prefetch future."""
        if self._closed:
            return
        self._closed = True
        # cancel first — waiting out in-flight assemblies whose results are
        # about to be discarded would stall teardown behind a shard load
        if self._assembler is not None:
            self._assembler.shutdown(wait=False, cancel_futures=True)
        self._queue.clear()
        self._pool.shutdown(wait=False, cancel_futures=True)

    def __del__(self):  # pragma: no cover - GC timing dependent
        try:
            self.close()
        except Exception:
            pass


class DevicePrefetcher:
    """Double-buffered host->device staging over a batch iterator.

    Wraps an iterator of per-host numpy batches and keeps `depth` of them
    already PUT to the device (put_fn: numpy batch -> device-resident form,
    typically stack_microbatches + mesh.host_to_device_batch). jax transfers
    are issued asynchronously, so putting batch N+1 before batch N's step is
    dispatched lets the copy ride the wire while the device computes —
    the h2d StepWatch bucket then measures only the (cheap) issue, and the
    device never idles waiting for input at a step boundary. With depth=0
    this degenerates to a synchronous map (the pre-round-11 behavior).

    Iteration yields (numpy_batch, device_batch) pairs so the consumer
    keeps its host-side uses (token counting, recorder) without a D2H trip.

    Checkpoint coherence: pulling ahead advances the upstream loader past
    what the consumer has dispatched, so `state_fn` (e.g.
    loader.state_dict) is snapshotted right after each upstream pull and
    `state_dict()` reports the snapshot of the last pair YIELDED — a resume
    replays nothing and skips nothing, same contract the loader's own
    assembly prefetch keeps.

    Flight-recorder coherence: the loader's batch_tap fires at the
    loader's yield, which under prefetch is one batch AHEAD of dispatch —
    the ring would bind the wrong batch to a step. Callers move the tap
    here (`prefetcher.batch_tap = recorder.capture_batch`); it fires when
    a pair is yielded to the consumer, i.e. in dispatch order.
    """

    def __init__(self, source, put_fn, depth: int = 1, state_fn=None,
                 batch_tap=None):
        self._source = iter(source)
        self._put = put_fn
        self.depth = max(0, int(depth))
        self._state_fn = state_fn
        self.batch_tap = batch_tap
        self._buf: List[tuple] = []  # (np_batch, device_batch, state)
        self._last_state = state_fn() if state_fn is not None else None
        self._exhausted = False

    def _pull(self) -> bool:
        try:
            batch = next(self._source)
        except StopIteration:
            self._exhausted = True
            return False
        state = self._state_fn() if self._state_fn is not None else None
        self._buf.append((batch, self._put(batch), state))
        return True

    def __iter__(self):
        return self

    def __next__(self):
        while not self._exhausted and len(self._buf) < self.depth + 1:
            if not self._pull():
                break
        if not self._buf:
            raise StopIteration
        batch, device_batch, state = self._buf.pop(0)
        self._last_state = state
        if self.batch_tap is not None:
            self.batch_tap(batch)
        return batch, device_batch

    def state_dict(self):
        """Upstream state as of the last yielded pair (None when no
        state_fn was given)."""
        return self._last_state
