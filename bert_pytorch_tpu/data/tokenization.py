"""Tokenization: WordPiece + byte-level BPE, self-contained.

The reference delegated encoding to HF `tokenizers` (Rust) via two factories
(src/tokenization.py:42-57) and kept the canonical pure-Python
BasicTokenizer/WordpieceTokenizer for SQuAD text alignment
(src/tokenization.py:60-229). This framework has no Rust dependency: the
canonical algorithms are implemented here in Python as the behavioral spec,
and `bert_pytorch_tpu.native` provides the C++ fast path (same results,
batch-parallel) selected automatically by the factories when the shared
library has been built.

Algorithms (all standard, per the original Google BERT release):
- BasicTokenizer: control-char cleanup, CJK spacing, optional lowercase +
  NFD accent stripping, punctuation splitting.
- WordpieceTokenizer: greedy longest-match-first over '##' continuations.
- ByteLevelBPE: GPT-2-style byte-to-unicode mapping + merge ranks.
"""

from __future__ import annotations

import collections
import json
import unicodedata
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

SPECIAL_TOKENS = ("[PAD]", "[UNK]", "[CLS]", "[SEP]", "[MASK]")


def load_vocab(vocab_file: str) -> "collections.OrderedDict[str, int]":
    """One token per line -> token->id, line order (reference
    src/tokenization.py:18-30)."""
    vocab = collections.OrderedDict()
    with open(vocab_file, "r", encoding="utf-8") as f:
        for i, line in enumerate(f):
            vocab[line.strip()] = i  # strip(), not rstrip('\n'): CRLF vocabs
    return vocab


def whitespace_tokenize(text: str) -> List[str]:
    return text.split()


# ---------------------------------------------------------------------------
# character classes (Unicode categories per the original BERT definition)
# ---------------------------------------------------------------------------

def _is_whitespace(ch: str) -> bool:
    if ch in (" ", "\t", "\n", "\r"):
        return True
    return unicodedata.category(ch) == "Zs"


def _is_control(ch: str) -> bool:
    if ch in ("\t", "\n", "\r"):
        return False
    return unicodedata.category(ch).startswith("C")


def _is_punctuation(ch: str) -> bool:
    cp = ord(ch)
    # ASCII ranges treated as punctuation even where Unicode disagrees
    # (e.g. '$', '`') — standard BERT behavior.
    if (33 <= cp <= 47) or (58 <= cp <= 64) or (91 <= cp <= 96) \
            or (123 <= cp <= 126):
        return True
    return unicodedata.category(ch).startswith("P")


def _is_cjk(cp: int) -> bool:
    return ((0x4E00 <= cp <= 0x9FFF) or (0x3400 <= cp <= 0x4DBF)
            or (0x20000 <= cp <= 0x2A6DF) or (0x2A700 <= cp <= 0x2B73F)
            or (0x2B740 <= cp <= 0x2B81F) or (0x2B820 <= cp <= 0x2CEAF)
            or (0xF900 <= cp <= 0xFAFF) or (0x2F800 <= cp <= 0x2FA1F))


class BasicTokenizer:
    """Whitespace/punctuation/CJK pre-tokenizer with optional lowercasing
    (spec: reference src/tokenization.py:60-174)."""

    def __init__(self, do_lower_case: bool = True,
                 never_split: Sequence[str] = SPECIAL_TOKENS):
        self.do_lower_case = do_lower_case
        self.never_split = tuple(never_split)

    def tokenize(self, text: str) -> List[str]:
        out: List[str] = []
        for token in whitespace_tokenize(self._clean(text)):
            if token in self.never_split:
                out.append(token)
                continue
            if self.do_lower_case:
                token = self._strip_accents(token.lower())
            out.extend(self._split_punc(token))
        return [t for t in out if t]

    def _clean(self, text: str) -> str:
        chars = []
        for ch in text:
            cp = ord(ch)
            if cp == 0 or cp == 0xFFFD or _is_control(ch):
                continue
            if _is_cjk(cp):
                chars.append(f" {ch} ")
            elif _is_whitespace(ch):
                chars.append(" ")
            else:
                chars.append(ch)
        return "".join(chars)

    @staticmethod
    def _strip_accents(text: str) -> str:
        return "".join(ch for ch in unicodedata.normalize("NFD", text)
                       if unicodedata.category(ch) != "Mn")

    @staticmethod
    def _split_punc(token: str) -> List[str]:
        pieces: List[str] = []
        current = ""
        for ch in token:
            if _is_punctuation(ch):
                if current:
                    pieces.append(current)
                    current = ""
                pieces.append(ch)
            else:
                current += ch
        if current:
            pieces.append(current)
        return pieces


class WordpieceTokenizer:
    """Greedy longest-match-first subword split (spec: reference
    src/tokenization.py:176-229)."""

    def __init__(self, vocab: Dict[str, int], unk_token: str = "[UNK]",
                 max_input_chars_per_word: int = 200):
        self.vocab = vocab
        self.unk_token = unk_token
        self.max_input_chars_per_word = max_input_chars_per_word

    def tokenize(self, text: str) -> List[str]:
        out: List[str] = []
        for word in whitespace_tokenize(text):
            if len(word) > self.max_input_chars_per_word:
                out.append(self.unk_token)
                continue
            subs = self._split_word(word)
            out.extend(subs if subs is not None else [self.unk_token])
        return out

    def _split_word(self, word: str) -> Optional[List[str]]:
        subs: List[str] = []
        start = 0
        while start < len(word):
            end = len(word)
            piece = None
            while start < end:
                cand = word[start:end]
                if start > 0:
                    cand = "##" + cand
                if cand in self.vocab:
                    piece = cand
                    break
                end -= 1
            if piece is None:
                return None
            subs.append(piece)
            start = end
        return subs


@dataclass
class Encoding:
    """Minimal analogue of the HF tokenizers Encoding the reference consumed:
    ids, tokens, per-token char offsets into the *original* text, and
    type_ids for pairs."""

    ids: List[int] = field(default_factory=list)
    tokens: List[str] = field(default_factory=list)
    offsets: List[Tuple[int, int]] = field(default_factory=list)
    type_ids: List[int] = field(default_factory=list)


class BertWordPieceTokenizer:
    """End-to-end WordPiece encoder: basic-tokenize (tracking offsets) then
    wordpiece, with [CLS]/[SEP] framing — the in-framework replacement for
    tokenizers.BertWordPieceTokenizer (reference src/tokenization.py:42-49).
    """

    def __init__(self, vocab: Dict[str, int], lowercase: bool = True,
                 unk_token: str = "[UNK]", cls_token: str = "[CLS]",
                 sep_token: str = "[SEP]", pad_token: str = "[PAD]",
                 mask_token: str = "[MASK]"):
        if isinstance(vocab, str):
            vocab = load_vocab(vocab)
        self.vocab = dict(vocab)
        self.ids_to_tokens = {i: t for t, i in self.vocab.items()}
        self.basic = BasicTokenizer(do_lower_case=lowercase)
        self.wordpiece = WordpieceTokenizer(self.vocab, unk_token=unk_token)
        self.unk_token = unk_token
        self.cls_token = cls_token
        self.sep_token = sep_token
        self.pad_token = pad_token
        self.mask_token = mask_token

    # -- HF-compatible surface ---------------------------------------------

    def token_to_id(self, token: str) -> Optional[int]:
        return self.vocab.get(token)

    def id_to_token(self, idx: int) -> Optional[str]:
        return self.ids_to_tokens.get(idx)

    def get_vocab_size(self) -> int:
        return len(self.vocab)

    def tokenize(self, text: str) -> List[str]:
        return [wp for tok in self.basic.tokenize(text)
                for wp in self.wordpiece.tokenize(tok)]

    def convert_tokens_to_ids(self, tokens: Sequence[str]) -> List[int]:
        unk = self.vocab.get(self.unk_token, 0)
        return [self.vocab.get(t, unk) for t in tokens]

    def convert_ids_to_tokens(self, ids: Sequence[int]) -> List[str]:
        return [self.ids_to_tokens.get(i, self.unk_token) for i in ids]

    def encode(self, text: str, pair: Optional[str] = None,
               add_special_tokens: bool = True) -> Encoding:
        enc = Encoding()
        cls_id = self.vocab.get(self.cls_token)
        sep_id = self.vocab.get(self.sep_token)

        def add(token: str, tid: int, span: Tuple[int, int], type_id: int):
            enc.tokens.append(token)
            enc.ids.append(tid)
            enc.offsets.append(span)
            enc.type_ids.append(type_id)

        if add_special_tokens:
            add(self.cls_token, cls_id, (0, 0), 0)
        for seq_idx, seq in enumerate([text] + ([pair] if pair else [])):
            for word, span in self._words_with_offsets(seq):
                for wp in self.wordpiece.tokenize(word):
                    tid = self.vocab.get(wp, self.vocab.get(self.unk_token, 0))
                    add(wp, tid, span, seq_idx)
            if add_special_tokens:
                add(self.sep_token, sep_id, (0, 0), seq_idx)
        return enc

    def _words_with_offsets(self, text: str) -> List[Tuple[str, Tuple[int, int]]]:
        """basic-tokenize while tracking each word's (start, end) char span in
        the original text. Offsets point at the pre-normalization word, which
        is what SQuAD answer realignment needs."""
        out = []
        n = len(text)
        i = 0
        while i < n:
            ch = text[i]
            if _is_whitespace(ch) or _is_control(ch) or ord(ch) in (0, 0xFFFD):
                i += 1
                continue
            if _is_punctuation(ch) or _is_cjk(ord(ch)):
                out.append((self._norm(ch), (i, i + 1)))
                i += 1
                continue
            j = i
            while j < n and not (_is_whitespace(text[j]) or _is_control(text[j])
                                 or _is_punctuation(text[j])
                                 or _is_cjk(ord(text[j]))):
                j += 1
            word = text[i:j]
            out.append((self._norm(word), (i, j)))
            i = j
        return [(w, s) for w, s in out if w]

    def _norm(self, word: str) -> str:
        if self.basic.do_lower_case:
            return BasicTokenizer._strip_accents(word.lower())
        return word


# ---------------------------------------------------------------------------
# Byte-level BPE (RoBERTa path)
# ---------------------------------------------------------------------------

def bytes_to_unicode() -> Dict[int, str]:
    """GPT-2 byte<->printable-unicode bijection (standard table)."""
    bs = (list(range(ord("!"), ord("~") + 1))
          + list(range(ord("\xa1"), ord("\xac") + 1))
          + list(range(ord("\xae"), ord("\xff") + 1)))
    cs = bs[:]
    n = 0
    for b in range(256):
        if b not in bs:
            bs.append(b)
            cs.append(256 + n)
            n += 1
    return dict(zip(bs, [chr(c) for c in cs]))


class ByteLevelBPETokenizer:
    """Byte-level BPE encoder — replacement for
    tokenizers.ByteLevelBPETokenizer (reference src/tokenization.py:51-57).

    vocab: token->id json/dict; merges: ranked merge pairs. add_prefix_space
    matches the reference factory's True default.
    """

    def __init__(self, vocab, merges, lowercase: bool = False,
                 add_prefix_space: bool = True,
                 unk_token: str = "<unk>"):
        if isinstance(vocab, str):
            with open(vocab, "r", encoding="utf-8") as f:
                vocab = json.load(f)
        self.vocab: Dict[str, int] = dict(vocab)
        self.ids_to_tokens = {i: t for t, i in self.vocab.items()}
        if isinstance(merges, str):
            with open(merges, "r", encoding="utf-8") as f:
                lines = [l.rstrip("\n") for l in f
                         if l.strip() and not l.startswith("#")]
            merges = [tuple(l.split()) for l in lines]
        self.bpe_ranks = {tuple(m): i for i, m in enumerate(merges)}
        self.byte_encoder = bytes_to_unicode()
        self.byte_decoder = {v: k for k, v in self.byte_encoder.items()}
        self.lowercase = lowercase
        self.add_prefix_space = add_prefix_space
        self.unk_token = unk_token
        self._cache: Dict[str, List[str]] = {}

    def token_to_id(self, token: str) -> Optional[int]:
        return self.vocab.get(token)

    def id_to_token(self, idx: int) -> Optional[str]:
        return self.ids_to_tokens.get(idx)

    def get_vocab_size(self) -> int:
        return len(self.vocab)

    def _bpe(self, token: str) -> List[str]:
        if token in self._cache:
            return self._cache[token]
        word: List[str] = list(token)
        while len(word) > 1:
            pairs = {(word[i], word[i + 1]) for i in range(len(word) - 1)}
            best = min(pairs, key=lambda p: self.bpe_ranks.get(p, 1 << 30))
            if best not in self.bpe_ranks:
                break
            merged: List[str] = []
            i = 0
            while i < len(word):
                if (i < len(word) - 1
                        and (word[i], word[i + 1]) == best):
                    merged.append(word[i] + word[i + 1])
                    i += 2
                else:
                    merged.append(word[i])
                    i += 1
            word = merged
        self._cache[token] = word
        return word

    _CONTRACTIONS = ("'s", "'t", "'re", "'ve", "'m", "'ll", "'d")

    def _pretokenize(self, text: str) -> List[str]:
        """GPT-2 pre-tokenization: contractions, unicode letter runs, number
        runs, other-char runs — each with an optional single leading space —
        and whitespace runs. Hand-rolled scanner because `re` lacks \\p{L}."""
        out: List[str] = []
        i, n = 0, len(text)
        while i < n:
            # contraction ('s 't 're 've 'm 'll 'd), lowercase only (GPT-2)
            if text[i] == "'":
                for c in self._CONTRACTIONS:
                    if text.startswith(c, i):
                        out.append(c)
                        i += len(c)
                        break
                else:
                    j = i + 1
                    while j < n and not (text[j].isspace() or
                                         text[j].isalpha() or
                                         text[j].isnumeric()):
                        j += 1
                    out.append(text[i:j])
                    i = j
                continue
            start = i
            lead_space = False
            if text[i] == " " and i + 1 < n and not text[i + 1].isspace():
                lead_space = True
                i += 1
            if i < n and text[i].isalpha():
                while i < n and text[i].isalpha():
                    i += 1
            elif i < n and text[i].isnumeric():
                while i < n and text[i].isnumeric():
                    i += 1
            elif i < n and text[i].isspace():
                while i < n and text[i].isspace():
                    i += 1
            else:
                while i < n and not (text[i].isspace() or text[i].isalpha()
                                     or text[i].isnumeric()
                                     or text[i] == "'"):
                    i += 1
                if i == start + (1 if lead_space else 0):
                    i += 1  # lone apostrophe fallthrough safety
            out.append(text[start:i])
        return [c for c in out if c]

    def encode(self, text: str, add_special_tokens: bool = True) -> Encoding:
        if self.lowercase:
            text = text.lower()
        if self.add_prefix_space and text and not text.startswith(" "):
            text = " " + text
        enc = Encoding()
        for chunk in self._pretokenize(text):
            if chunk.isspace() and chunk != " ":
                chunk = " "
            mapped = "".join(self.byte_encoder[b]
                             for b in chunk.encode("utf-8"))
            for piece in self._bpe(mapped):
                tid = self.vocab.get(piece)
                if tid is None:
                    tid = self.vocab.get(self.unk_token, 0)
                enc.tokens.append(piece)
                enc.ids.append(tid)
                enc.offsets.append((0, 0))
                enc.type_ids.append(0)
        return enc

    def decode(self, ids: Sequence[int]) -> str:
        text = "".join(self.ids_to_tokens.get(i, "") for i in ids)
        raw = bytearray(self.byte_decoder.get(ch, 32) for ch in text)
        return raw.decode("utf-8", errors="replace")


# ---------------------------------------------------------------------------
# factories (reference src/tokenization.py:42-57 surface)
# ---------------------------------------------------------------------------

def get_wordpiece_tokenizer(vocab, uppercase: bool = False):
    """WordPiece tokenizer from a vocab file/dict. Prefers the C++ native
    encoder (bert_pytorch_tpu.native) when its shared library is built —
    identical output, batch-parallel."""
    try:
        from bert_pytorch_tpu.native import (
            NativeWordPieceTokenizer, native_available)

        if native_available():
            return NativeWordPieceTokenizer(vocab, lowercase=not uppercase)
    except ImportError:
        pass
    return BertWordPieceTokenizer(vocab, lowercase=not uppercase)


def get_bpe_tokenizer(vocab, merges=None, uppercase: bool = False):
    """Byte-level BPE tokenizer (RoBERTa). vocab may be a .json path; merges
    defaults to merges.txt next to it. Prefers the C++ native encoder
    (bert_pytorch_tpu.native) when its shared library is built — identical
    ids, batch-parallel."""
    if merges is None and isinstance(vocab, str):
        import os

        merges = os.path.join(os.path.dirname(vocab), "merges.txt")
    try:
        from bert_pytorch_tpu.native import (
            NativeByteLevelBPETokenizer, native_bpe_available)

        if native_bpe_available():
            return NativeByteLevelBPETokenizer(vocab, merges,
                                               lowercase=not uppercase)
    except ImportError:
        pass
    return ByteLevelBPETokenizer(vocab, merges, lowercase=not uppercase)


TOKENIZERS = {
    "wordpiece": get_wordpiece_tokenizer,
    "bpe": get_bpe_tokenizer,
}
