"""Sequence packing: assemble fixed-length rows from multiple short examples.

Every pretraining batch the reference (and this framework through round 8)
feeds the device is padded dense to max_seq_length, so the attention and
matmul FLOPs spent on pad tokens — 10-60% of the row depending on corpus
length statistics — are pure waste ("Boosting Distributed Training
Performance of the Unpadded BERT Model", PAPERS.md). GPUs can un-pad with
ragged/varlen kernels; on TPU/XLA shapes must stay static, so the canonical
form of the win is *packing*: concatenate several short examples into one
(S,) row and keep them from attending to each other with a block-diagonal
mask.

This module is the host-side half of that path:

- `first_fit(lengths, ...)`  — the greedy first-fit bin packer (deterministic,
  order-preserving: examples are placed in arrival order into the first row
  with room, the property the resumable loader state depends on).
- `pack_examples(...)`       — turn a list of already-masked examples into the
  packed batch dict the model consumes.

Packed-batch contract (consumed by models/bert.py + training/pretrain.py):

  input_ids        (B, S)  concatenated example tokens, 0-padded tail
  token_type_ids   (B, S)  each example's NSP A/B ids, concatenated
  attention_mask   (B, S)  1 on real tokens (== segment_ids > 0)
  segment_ids      (B, S)  int32 packing segment index: 1..n per row, 0 = pad.
                           Attention is masked to q_seg == k_seg blocks.
  position_ids     (B, S)  positions RESET per segment (each example keeps the
                           position-embedding stream it would have unpacked)
  masked_lm_labels (B, S)  concatenated per-example labels, -1 = unsupervised
  next_sentence_labels (B, G) per-segment NSP labels, -1 = empty slot
  nsp_positions    (B, G)  row position of each segment's first token ([CLS]);
                           0 for empty slots (their label is -1, so the loss
                           ignores whatever position 0 gathers)

G (`max_segments`) bounds segments per row so the NSP arrays stay static.
"""

from __future__ import annotations

from typing import Dict, List, Sequence

import numpy as np


def first_fit(lengths: Sequence[int], n_bins: int, capacity: int,
              max_segments: int, segs_per_unit: int = 1) -> List[List[int]]:
    """Greedy first-fit: place each example (arrival order) into the first of
    `n_bins` bins with `capacity` token slots and `max_segments` example slots
    free. Returns per-bin lists of example indices; examples that fit nowhere
    are simply absent (the loader keeps them pending for the next batch).

    Deterministic and order-preserving by construction — no sorting — so the
    bin layout is a pure function of the example stream, which is what makes
    the sampler-cursor + pending-indices checkpoint sufficient for bit-exact
    resume.

    `segs_per_unit` > 1 places multi-segment units (the finetune driver's
    multiple-choice groups: one unit = C choice rows that must stay in one
    bin, training/finetune.py) — each placement consumes that many of the
    bin's `max_segments` slots. The default 1 is the pretraining/serving
    per-example path, byte-identical to the pre-round-18 behavior; ONE
    implementation serves both so training packing and serving packing
    cannot drift.
    """
    used = [0] * n_bins
    segs = [0] * n_bins
    bins: List[List[int]] = [[] for _ in range(n_bins)]
    for i, ln in enumerate(lengths):
        ln = int(ln)
        if ln > capacity:
            raise ValueError(f"example length {ln} exceeds row capacity "
                             f"{capacity}")
        for b in range(n_bins):
            if used[b] + ln <= capacity \
                    and segs[b] + segs_per_unit <= max_segments:
                used[b] += ln
                segs[b] += segs_per_unit
                bins[b].append(i)
                break
    return bins


def example_lengths(attention_mask: np.ndarray) -> np.ndarray:
    """(N, S) {0,1} mask -> (N,) real lengths. Packing assumes the valid
    tokens are a prefix (true for the HDF5 schema: content then pad tail)."""
    return attention_mask.astype(np.int64).sum(axis=1)


def pack_examples(examples: Dict[str, np.ndarray],
                  bins: List[List[int]],
                  seq_len: int,
                  max_segments: int) -> Dict[str, np.ndarray]:
    """Assemble the packed batch from per-example arrays + a bin layout.

    `examples` is an unpacked batch dict (the loader's usual per-example
    fields, already masked): input_ids / token_type_ids / attention_mask /
    masked_lm_labels, all (N, S), plus next_sentence_labels (N,). `bins` maps
    each output row to the example indices packed into it (first_fit output).
    """
    ids = examples["input_ids"]
    toktype = examples["token_type_ids"]
    mask = examples["attention_mask"]
    labels = examples["masked_lm_labels"]
    nsp = examples["next_sentence_labels"]
    lengths = example_lengths(mask)

    B = len(bins)
    out = {
        "input_ids": np.zeros((B, seq_len), np.int32),
        "token_type_ids": np.zeros((B, seq_len), np.int32),
        "attention_mask": np.zeros((B, seq_len), np.int32),
        "segment_ids": np.zeros((B, seq_len), np.int32),
        "position_ids": np.zeros((B, seq_len), np.int32),
        "masked_lm_labels": np.full((B, seq_len), -1, np.int32),
        "next_sentence_labels": np.full((B, max_segments), -1, np.int32),
        "nsp_positions": np.zeros((B, max_segments), np.int32),
    }
    for b, members in enumerate(bins):
        cursor = 0
        for g, ei in enumerate(members):
            ln = int(lengths[ei])
            sl = slice(cursor, cursor + ln)
            out["input_ids"][b, sl] = ids[ei, :ln]
            out["token_type_ids"][b, sl] = toktype[ei, :ln]
            out["attention_mask"][b, sl] = 1
            out["segment_ids"][b, sl] = g + 1
            out["position_ids"][b, sl] = np.arange(ln, dtype=np.int32)
            out["masked_lm_labels"][b, sl] = labels[ei, :ln]
            out["next_sentence_labels"][b, g] = nsp[ei]
            out["nsp_positions"][b, g] = cursor
            cursor += ln
    return out


def packing_efficiency(segment_ids: np.ndarray) -> float:
    """real tokens / slot tokens for a packed (or plain-masked) batch."""
    seg = np.asarray(segment_ids)
    return float((seg > 0).mean()) if seg.size else 0.0
