"""Streaming data plane: tokenize-on-the-fly ingestion with resumable cursors.

The offline plane (pipeline/ download→format→shard→encode, then
data/sharded.py) requires a full re-encode cycle before any new text can be
trained on — a real cost at pod scale ("Multi-node BERT-pretraining:
Cost-efficient Approach", PAPERS.md) and a hard blocker for continual
pretraining on live corpora (ROADMAP item 5). This module is the second,
online plane: raw text goes in, ready-to-device batches come out, and the
train loop is byte-for-byte unaware of which plane fed it.

Design, and the invariants that make it production-grade:

- **Sources are an interface** (`StreamSource`): anything that can enumerate
  (record_idx, text) pairs in a stable order. `FileSource` reads blank-line-
  delimited documents from local text files (the pipeline/format.py contract);
  object-store sources slot in later without touching the loader.
- **Deterministic enumeration.** Records are numbered globally across the
  sorted source list (source 0's records, then source 1's, ...); host r owns
  records with ``global_seq % world_size == rank`` — disjoint by construction,
  and independent of worker count, queue sizes, or scheduling.
- **Tokenize-on-the-fly worker pool.** A reader thread walks this host's
  records and fans tokenize work out to a ThreadPoolExecutor; results are
  consumed IN SUBMISSION ORDER, so parallelism changes pacing only, never the
  example stream. Each record chunks into fixed-length examples
  ([CLS] chunk [SEP], RoBERTa-style single segment, NSP label 0).
- **Masking is a pure function of the cursor.** data/masking.py's dynamic
  80/10/10 masking is applied per example with an rng seeded from
  ``(seed, epoch, global_seq, example_idx)`` — a fresh mask every epoch pass
  (the RoBERTa property) AND bit-identical replay after resume. (Round 17
  ported the same contract to the offline loader — masks there are now a
  pure function of ``(seed, epoch, global index)`` — so both planes resume
  bit-identically, the property the survival drill proves.) Batches,
  masks included, are a pure function of (sources, seed, epoch, cursor).
- **Resumable cursors, the packer's template.** ``state_dict()`` carries the
  (source, record, global_seq, example-skip) cursor of the last example
  consumed — lagged to the last YIELDED batch under assembly prefetch, same
  contract as data/sharded.py — plus, under ``--packing``, the cursors of the
  examples still pending in the packer's carry-over buffer. Resume re-reads
  from the earliest pending record, re-tokenizes forward (dropping what was
  already consumed), and the deterministic first-fit packer rebuilds the
  identical bin layout: the resumed stream is bit-identical to an unbroken
  run, proven by tests/test_streaming.py.
- **Backpressure is bounded and visible.** Examples flow through a bounded
  queue; when the train loop falls behind, the queue fills and the tokenize
  workers stall on ``put`` (bounded RAM); when the producers fall behind, the
  consumer blocks in ``next()`` — which the train loop already times as the
  ``data_wait`` StepWatch bucket. A MetricsRegistry (pass ``registry=``)
  additionally exports live gauges: ``bert_stream_queue_depth``,
  ``bert_stream_tokens_total``, ``bert_stream_records_total``,
  ``bert_stream_records_dropped_total``, ``bert_stream_worker_restarts_total``
  and per-worker ``bert_stream_worker_tokens_per_sec{worker=...}``.
- **Fault drills built in** (``inject=``): ``slow_producer`` sleeps in the
  worker (starves the consumer -> data_wait), ``corrupt_record``
  deterministically poisons every 7th owned record (skipped-and-counted with
  a loud warning — the stream stays deterministic because the drop is a pure
  function of the record id), ``worker_crash`` kills the tokenize task once
  per 5th record (detected, counted, and re-submitted with its cursor intact
  — the output stream is bit-identical to an uninjected run).

No jax imports anywhere: like data/sharded.py this is plain host Python, so
the two-process shard tests and the input bench stay backend-free.

docs/DATA.md is the operator guide; run_pretraining.py --stream_dir is the
entry point.
"""

from __future__ import annotations

import glob as glob_lib
import hashlib
import os
import queue as queue_lib
import threading
import time
import warnings
from collections import deque
from concurrent.futures import ThreadPoolExecutor
from typing import Dict, Iterator, List, Optional, Sequence, Tuple

import numpy as np

from bert_pytorch_tpu.data import masking

STREAM_STATE_VERSION = 1

# fault-injection constants (deterministic by record id, so an injected run's
# *surviving* stream is still a pure function of the cursor)
INJECT_SLOW_SLEEP_S = 0.05
INJECT_CORRUPT_EVERY, INJECT_CORRUPT_PHASE = 7, 3
INJECT_CRASH_EVERY, INJECT_CRASH_PHASE = 5, 2
INJECT_MODES = ("slow_producer", "corrupt_record", "worker_crash")

_MAX_TASK_RETRIES = 2  # re-submissions before a record is dropped as corrupt


class CorruptRecordError(RuntimeError):
    """A record that cannot be tokenized; skipped-and-counted, never fatal."""


class StreamSource:
    """One ordered record stream. Records must enumerate identically on every
    pass — that stability is what the whole cursor contract rests on."""

    name: str

    def iter_records(self, start: int = 0) -> Iterator[Tuple[int, str]]:
        raise NotImplementedError


class FileSource(StreamSource):
    """Blank-line-delimited documents in one local text file (the
    pipeline/format.py corpus contract: one sentence per line, blank line
    between documents). ``start`` skips records without tokenizing them —
    resume seeks by scanning document boundaries, not by re-encoding."""

    def __init__(self, path: str):
        self.name = str(path)

    def iter_records(self, start: int = 0) -> Iterator[Tuple[int, str]]:
        idx = 0
        buf: List[str] = []
        # errors="replace": a torn byte sequence becomes U+FFFD and flows to
        # the tokenizer as [UNK] rather than killing the plane mid-epoch
        with open(self.name, "r", encoding="utf-8", errors="replace") as f:
            for line in f:
                line = line.strip()
                if line:
                    buf.append(line)
                    continue
                if buf:
                    if idx >= start:
                        yield idx, "\n".join(buf)
                    idx += 1
                    buf = []
        if buf and idx >= start:
            yield idx, "\n".join(buf)


def discover_sources(path_or_glob: str) -> List[FileSource]:
    """Directory -> every *.txt under it (recursive); otherwise treated as a
    glob pattern; a plain file path is its own one-element glob. Sorted, so
    the global record enumeration is stable across hosts and sessions."""
    if os.path.isdir(path_or_glob):
        paths = glob_lib.glob(os.path.join(path_or_glob, "**", "*.txt"),
                              recursive=True)
    else:
        paths = glob_lib.glob(path_or_glob)
    return [FileSource(p) for p in sorted(paths)]


def sources_fingerprint(sources: Sequence[StreamSource]) -> str:
    """Identity of the source LIST (names + sizes + mtimes when stat-able).
    A resume against a different corpus must be detected and refused — the
    checkpointed cursor indexes into this enumeration and no other. mtime
    is included so a same-length in-place edit cannot silently shift the
    enumeration; the cost is that a benign touch/copy also refuses (with
    the loud warning) and restarts the stream — the safe direction."""
    h = hashlib.sha256()
    for s in sources:
        h.update(s.name.encode("utf-8", errors="replace"))
        try:
            stat = os.stat(s.name)
            h.update(f"{stat.st_size}:{stat.st_mtime_ns}".encode())
        except OSError:
            h.update(b"?")
        h.update(b"\0")
    return h.hexdigest()[:16]


# [CLS]/[SEP] naming differs by tokenizer family: WordPiece vocabs use the
# BERT names, the repo's BPE trainer emits RoBERTa-style <s>/</s>
# (pipeline/vocab.py). The loader accepts either.
_CLS_TOKENS = ("[CLS]", "<s>")
_SEP_TOKENS = ("[SEP]", "</s>")
MASK_TOKENS = ("[MASK]", "<mask>")


def _first_id(tokenizer, candidates: Sequence[str]) -> Optional[int]:
    for tok in candidates:
        tid = tokenizer.token_to_id(tok)
        if tid is not None:
            return int(tid)
    return None


def resolve_mask_id(tokenizer) -> Optional[int]:
    """The [MASK]/<mask> id straight from the stream tokenizer — the
    authoritative lookup for stream mode (line-parsing a BPE .json vocab
    with load_vocab would silently miss)."""
    return _first_id(tokenizer, MASK_TOKENS)


def _example_rng(seed: int, epoch: int, global_seq: int,
                 example_idx: int) -> np.random.Generator:
    """THE masking rng: a pure function of the example's cursor. This single
    line is what upgrades resume from 'rng-independent fields match' (the
    offline loader's contract) to full bit-identity, masks included."""
    return np.random.default_rng(
        (int(seed), int(epoch), int(global_seq), int(example_idx)))


def tokenize_record(
    text: str,
    tokenizer,
    seq_len: int,
    cls_id: int,
    sep_id: int,
    mask_token_index: int,
    max_pred_per_seq: int,
    masked_lm_prob: float,
    vocab_size: int,
    seed: int,
    epoch: int,
    global_seq: int,
    original_token_prob: float = 0.1,
    random_token_prob: float = 0.1,
) -> List[Dict[str, np.ndarray]]:
    """One record -> its masked examples, deterministically.

    Chunking: the record's token ids split into runs of (seq_len - 2), each
    framed [CLS] ... [SEP] and zero-padded. Single segment (token_type_ids
    all 0, next_sentence_labels 0 — RoBERTa mode; the NSP head trains on a
    constant 'is next' and contributes nothing, same as next_seq_prob=0
    offline shards). Masking via data/masking.dynamic_mask_batch with the
    cursor-derived rng."""
    enc = tokenizer.encode(text, add_special_tokens=False)
    ids = list(enc.ids)
    out: List[Dict[str, np.ndarray]] = []
    body = max(1, seq_len - 2)
    for j in range(0, len(ids), body):
        chunk = ids[j:j + body]
        example_idx = j // body
        row = np.zeros((1, seq_len), np.int32)
        row[0, 0] = cls_id
        row[0, 1:1 + len(chunk)] = chunk
        row[0, 1 + len(chunk)] = sep_id
        specials = np.array([[0, 1 + len(chunk)]], np.int32)
        attention_mask = masking.input_mask_from_specials(row, specials)
        rng = _example_rng(seed, epoch, global_seq, example_idx)
        masked, labels = masking.dynamic_mask_batch(
            row, specials,
            mask_token_index=mask_token_index,
            max_pred_per_seq=max_pred_per_seq,
            masked_lm_prob=masked_lm_prob,
            vocab_size=vocab_size,
            rng=rng,
            original_token_prob=original_token_prob,
            random_token_prob=random_token_prob)
        out.append({
            "input_ids": masked[0].astype(np.int32),
            "token_type_ids": np.zeros((seq_len,), np.int32),
            "attention_mask": attention_mask[0].astype(np.int32),
            "masked_lm_labels": labels[0].astype(np.int32),
            "next_sentence_labels": np.int32(0),
        })
    return out


class _WorkerStats:
    """Per-worker tokenize accounting, updated from the pool threads and
    read by the producer when it refreshes the registry gauges.

    Rates are computed over ~2 s wall-clock windows, not as a lifetime
    average: a worker that stalls must read 0 on the gauge within a
    window, not keep reporting its historical healthy rate forever (the
    'flat-lined worker' diagnostic docs/OBSERVABILITY.md teaches). Until
    the first window completes, the running busy-time average is
    reported so short-lived runs still export a number."""

    WINDOW_S = 2.0

    def __init__(self):
        self._lock = threading.Lock()
        self._win: Dict[str, List[float]] = {}  # name -> [tokens, secs]
        self._win_start = time.perf_counter()
        self._last: Dict[str, float] = {}

    def note(self, tokens: int, secs: float) -> None:
        name = threading.current_thread().name
        with self._lock:
            acc = self._win.setdefault(name, [0.0, 0.0])
            acc[0] += tokens
            acc[1] += secs

    def rates(self) -> Dict[str, float]:
        with self._lock:
            now = time.perf_counter()
            wall = now - self._win_start
            if wall >= self.WINDOW_S:
                known = set(self._last) | set(self._win)
                self._last = {
                    name: self._win.get(name, (0.0, 0.0))[0] / wall
                    for name in known}
                self._win = {}
                self._win_start = now
            if not self._last:  # first window still filling
                return {name: (acc[0] / acc[1] if acc[1] > 0 else 0.0)
                        for name, acc in self._win.items()}
            return dict(self._last)


class StreamingPretrainingLoader:
    """Iterator of ready-to-device batches tokenized on the fly.

    Same surface as data/sharded.PretrainingDataLoader — ``__next__`` yields
    the identical batch dict contract (packed fields included when
    ``packing=True``), ``state_dict``/``load_state_dict`` checkpoint the
    cursor, ``reset_epoch`` rolls the epoch, ``batch_tap`` fires at the yield
    boundary, ``prefetch_batches`` runs batch assembly on an executor — so
    run_pretraining's train loop, DevicePrefetcher staging and flight
    recorder compose without knowing which plane feeds them.
    """

    def __init__(
        self,
        sources: Sequence[StreamSource],
        tokenizer,
        batch_size: int,
        seq_len: int,
        mask_token_index: int,
        max_pred_per_seq: int,
        masked_lm_prob: float,
        vocab_size: int,
        seed: int = 0,
        world_size: int = 1,
        rank: int = 0,
        num_workers: int = 2,
        queue_batches: int = 4,
        prefetch_batches: int = 0,
        packing: bool = False,
        packing_max_segments: int = 8,
        packing_lookahead: int = 4,
        original_token_prob: float = 0.1,
        random_token_prob: float = 0.1,
        registry=None,
        inject: Optional[str] = None,
        batch_tap=None,
    ):
        if not sources:
            raise ValueError("no stream sources")
        if not 0 <= rank < world_size:
            raise ValueError(f"rank {rank} out of range for world "
                             f"{world_size}")
        if not 0 <= masked_lm_prob <= 1:
            raise ValueError("masked_lm_prob must be in [0,1]")
        if original_token_prob + random_token_prob > 1:
            raise ValueError("original_token_prob + random_token_prob > 1")
        if seq_len < 3:
            raise ValueError("seq_len must fit [CLS] + 1 token + [SEP]")
        if inject is not None and inject not in INJECT_MODES:
            raise ValueError(f"inject must be one of {INJECT_MODES}")
        self.sources = list(sources)
        self.sources_hash = sources_fingerprint(self.sources)
        self.tokenizer = tokenizer
        cls_id = _first_id(tokenizer, _CLS_TOKENS)
        sep_id = _first_id(tokenizer, _SEP_TOKENS)
        if cls_id is None or sep_id is None:
            raise ValueError(
                f"tokenizer vocab has none of {_CLS_TOKENS} / none of "
                f"{_SEP_TOKENS} — cannot frame examples")
        self._cls_id, self._sep_id = cls_id, sep_id
        self.batch_size = int(batch_size)
        self.seq_len = int(seq_len)
        self.mask_token_index = int(mask_token_index)
        self.max_pred_per_seq = int(max_pred_per_seq)
        self.masked_lm_prob = float(masked_lm_prob)
        self.vocab_size = int(vocab_size)
        self.seed = int(seed)
        self.world_size = int(world_size)
        self.rank = int(rank)
        self.num_workers = max(1, int(num_workers))
        self.queue_examples = max(
            self.batch_size, self.batch_size * max(1, int(queue_batches)))
        self.original_token_prob = float(original_token_prob)
        self.random_token_prob = float(random_token_prob)
        self.inject = inject
        self.packing = bool(packing)
        if self.packing and packing_max_segments < 1:
            raise ValueError("packing_max_segments must be >= 1")
        self.packing_max_segments = int(packing_max_segments)
        self.packing_lookahead = max(1, int(packing_lookahead))
        # batch_tap(batch) fires for every YIELDED batch on the consumer
        # thread — the flight recorder's capture point, identical contract
        # to the offline loader (and to DevicePrefetcher under h2d prefetch)
        self.batch_tap = batch_tap

        # -- cursor state (the resume contract) -----------------------------
        self.epoch = 0
        self._batches = 0  # batches yielded this epoch (bookkeeping)
        # cursor of the last example CONSUMED from the stream: (source_idx,
        # record_in_source, record global_seq, next-example skip). Fresh
        # loaders start one-before-the-beginning.
        self._cursor = (0, 0, 0, 0)
        # packing carry-over: [(source, record, global_seq, example_idx,
        # example_dict)] — metas checkpoint, payloads rebuild on resume
        self._pending: List[Tuple[Tuple[int, int, int, int],
                                  Dict[str, np.ndarray]]] = []
        # resume replay filter: re-derived examples at-or-before the feed
        # cursor are kept only if their meta is in the pending set
        self._resume_keep: Optional[set] = None
        self._resume_until: Optional[Tuple[int, int]] = None
        # per-source record counts as discovered (None = not yet finished);
        # the flight-recorder manifest's "per-source offsets"
        self._source_records: List[Optional[int]] = [None] * len(self.sources)
        # record range feeding each recent yielded batch, for the manifest
        self.recent_windows: deque = deque(maxlen=32)

        # -- plumbing --------------------------------------------------------
        self._pool = ThreadPoolExecutor(
            max_workers=self.num_workers,
            thread_name_prefix="stream-tokenize")
        self._stats = _WorkerStats()
        self._queue: Optional[queue_lib.Queue] = None
        self._producer: Optional[threading.Thread] = None
        self._producer_stop = threading.Event()
        self._epoch_done = False  # end sentinel seen; sticky until reset
        self._window_snapshot: Optional[Dict[str, int]] = None
        self._crashed_once: set = set()
        self._closed = False

        # batch-assembly prefetch: same separate single-worker executor
        # discipline as the offline loader (one consumer of the example
        # queue at a time, assembly serialized in order)
        self.prefetch_batches = max(0, int(prefetch_batches))
        self._assembler: Optional[ThreadPoolExecutor] = None
        self._assembly_queue: List = []
        if self.prefetch_batches > 0:
            self._assembler = ThreadPoolExecutor(
                max_workers=1, thread_name_prefix="stream-assemble")

        # -- registry instruments -------------------------------------------
        self._g_depth = self._c_tokens = self._c_records = None
        self._c_dropped = self._c_restarts = self._c_examples = None
        self._g_worker_rate = None
        if registry is not None:
            self._g_depth = registry.gauge(
                "bert_stream_queue_depth",
                "tokenized examples buffered between the stream workers "
                "and the train loop (0 under producer starvation, full "
                "under consumer backpressure)")
            self._c_tokens = registry.counter(
                "bert_stream_tokens_total",
                "raw tokens tokenized by the streaming plane")
            self._c_records = registry.counter(
                "bert_stream_records_total",
                "source records tokenized (this host's shard)")
            self._c_dropped = registry.counter(
                "bert_stream_records_dropped_total",
                "corrupt source records skipped-and-counted")
            self._c_restarts = registry.counter(
                "bert_stream_worker_restarts_total",
                "tokenize tasks that died and were re-submitted with "
                "their cursor intact")
            self._c_examples = registry.counter(
                "bert_stream_examples_total",
                "fixed-length examples emitted by the tokenize workers")
            self._g_worker_rate = registry.gauge(
                "bert_stream_worker_tokens_per_sec",
                "per-worker tokenize throughput (tokens/sec over ~2s "
                "windows; 0 = stalled or idle worker)",
                labels=("worker",))
        self._last_state = self._state_snapshot()

    # -- record enumeration ---------------------------------------------------

    def _owned_records(self, start_source: int, start_record: int,
                       start_seq: int, stop: threading.Event
                       ) -> Iterator[Tuple[int, int, int, str]]:
        """(source_idx, record_idx, global_seq, text) for every record this
        host owns, from the given cursor. global_seq numbers ALL records
        (owned or not) so masking seeds and ownership stay host-invariant."""
        gs = start_seq
        for si in range(start_source, len(self.sources)):
            first = start_record if si == start_source else 0
            n_seen = first
            for ri, text in self.sources[si].iter_records(start=first):
                if stop.is_set():
                    return
                n_seen = ri + 1
                if gs % self.world_size == self.rank:
                    yield si, ri, gs, text
                gs += 1
            self._source_records[si] = n_seen

    # -- producer -------------------------------------------------------------

    def _tokenize_task(self, text: str, epoch: int, global_seq: int
                       ) -> List[Dict[str, np.ndarray]]:
        """Pool-thread work unit: injection hooks + timed tokenize."""
        if self.inject == "slow_producer":
            time.sleep(INJECT_SLOW_SLEEP_S)
        if (self.inject == "corrupt_record"
                and global_seq % INJECT_CORRUPT_EVERY
                == INJECT_CORRUPT_PHASE):
            raise CorruptRecordError(
                f"injected corrupt record (global_seq={global_seq})")
        if (self.inject == "worker_crash"
                and global_seq % INJECT_CRASH_EVERY == INJECT_CRASH_PHASE
                and (epoch, global_seq) not in self._crashed_once):
            self._crashed_once.add((epoch, global_seq))
            raise RuntimeError(
                f"injected worker crash (global_seq={global_seq})")
        t0 = time.perf_counter()
        try:
            examples = tokenize_record(
                text, self.tokenizer, self.seq_len, self._cls_id,
                self._sep_id, self.mask_token_index, self.max_pred_per_seq,
                self.masked_lm_prob, self.vocab_size, self.seed, epoch,
                global_seq, self.original_token_prob,
                self.random_token_prob)
        except (CorruptRecordError, RuntimeError):
            raise
        except Exception as e:
            # anything the tokenizer chokes on is a corrupt record, not a
            # dead plane
            raise CorruptRecordError(f"tokenize failed: {e}") from e
        n_tokens = sum(int(ex["attention_mask"].sum()) for ex in examples)
        self._stats.note(n_tokens, time.perf_counter() - t0)
        if self._c_tokens is not None:
            self._c_tokens.inc(n_tokens)
        return examples

    def _produce(self, epoch: int, start_source: int, start_record: int,
                 start_seq: int, skip_first: int, q: queue_lib.Queue,
                 stop: threading.Event) -> None:
        """Reader thread: submit records to the pool in order, consume
        futures in order, push examples through the bounded queue. Ordering
        by submission index is the determinism guarantee — worker count and
        finish order cannot reorder the stream."""
        inflight: deque = deque()  # (si, ri, gs, text, future, retries)
        records = self._owned_records(start_source, start_record, start_seq,
                                      stop)
        exhausted = False

        def put(item) -> bool:
            while not stop.is_set():
                try:
                    q.put(item, timeout=0.1)
                    return True
                except queue_lib.Full:
                    continue
            return False

        try:
            while not stop.is_set():
                while not exhausted and len(inflight) < 2 * self.num_workers:
                    try:
                        si, ri, gs, text = next(records)
                    except StopIteration:
                        exhausted = True
                        break
                    fut = self._pool.submit(self._tokenize_task, text,
                                            epoch, gs)
                    inflight.append((si, ri, gs, text, fut, 0))
                if not inflight:
                    break
                si, ri, gs, text, fut, retries = inflight.popleft()
                try:
                    examples = fut.result()
                except CorruptRecordError as e:
                    warnings.warn(
                        f"stream: DROPPING corrupt record {ri} of "
                        f"{self.sources[si].name} (global_seq={gs}): {e}")
                    if self._c_dropped is not None:
                        self._c_dropped.inc()
                    continue
                except Exception as e:
                    if retries < _MAX_TASK_RETRIES:
                        warnings.warn(
                            f"stream: tokenize worker died on record {ri} "
                            f"of {self.sources[si].name} "
                            f"(global_seq={gs}): {e} — restarting with "
                            "its cursor intact "
                            f"(retry {retries + 1}/{_MAX_TASK_RETRIES})")
                        if self._c_restarts is not None:
                            self._c_restarts.inc()
                        fut = self._pool.submit(self._tokenize_task, text,
                                                epoch, gs)
                        inflight.appendleft((si, ri, gs, text, fut,
                                             retries + 1))
                        continue
                    # persistent failure: drop the one record loudly (the
                    # corrupt path) rather than take the training run down
                    warnings.warn(
                        f"stream: DROPPING record {ri} of "
                        f"{self.sources[si].name} (global_seq={gs}) after "
                        f"{_MAX_TASK_RETRIES} failed restarts: {e}")
                    if self._c_dropped is not None:
                        self._c_dropped.inc()
                    continue
                if self._c_records is not None:
                    self._c_records.inc()
                if self._c_examples is not None:
                    self._c_examples.inc(len(examples))
                if self._g_worker_rate is not None:
                    for worker, rate in self._stats.rates().items():
                        self._g_worker_rate.set(rate, worker=worker)
                first_j = skip_first if (si, ri) == (start_source,
                                                     start_record) else 0
                for j, ex in enumerate(examples):
                    if j < first_j:
                        continue  # consumed before the checkpoint
                    if not put(("ex", (si, ri, gs, j), ex)):
                        return
            put(("end",))
        except BaseException as e:  # pragma: no cover - defensive
            put(("err", e))

    def _start_producer(self) -> None:
        if self._producer is not None or self._closed:
            return
        si, ri, gs, skip = self._resume_start()
        self._queue = queue_lib.Queue(maxsize=self.queue_examples)
        self._epoch_done = False
        self._producer_stop = threading.Event()
        self._producer = threading.Thread(
            target=self._produce,
            args=(self.epoch, si, ri, gs, skip, self._queue,
                  self._producer_stop),
            name="stream-reader", daemon=True)
        self._producer.start()

    def _resume_start(self) -> Tuple[int, int, int, int]:
        """Where the producer must (re)start: the consumed cursor's next
        example — or, under packing, the earliest record still holding a
        pending example (the replay filter then drops what was consumed)."""
        si, ri, gs, skip = self._cursor
        starts = [(si, ri, gs, skip)]
        starts += [(m[0], m[1], m[2], m[3]) for m in self._resume_pending()]
        si, ri, gs, skip = min(starts, key=lambda c: (c[2], c[3]))
        return si, ri, gs, skip

    def _resume_pending(self) -> List[Tuple[int, int, int, int]]:
        return [meta for meta, _ in self._pending] \
            if self._pending and all(ex is None for _, ex in self._pending) \
            else []

    def _stop_producer(self) -> None:
        if self._producer is None:
            return
        self._producer_stop.set()
        # unblock a producer stalled on a full queue
        q = self._queue
        if q is not None:
            try:
                while True:
                    q.get_nowait()
            except queue_lib.Empty:
                pass
        self._producer.join(timeout=10.0)
        self._producer = None
        self._queue = None

    # -- consumer -------------------------------------------------------------

    def _next_example(self):
        """One (meta, example) off the queue, honoring the resume replay
        filter; None at epoch end. The blocking get — the caller's time
        here IS the data_wait signal."""
        if self._epoch_done or self._closed:
            # sticky: assemblies queued ahead at epoch end (or during
            # teardown) must all see the end, not block on an empty queue
            return None
        self._start_producer()
        while True:
            try:
                item = self._queue.get(timeout=0.2)
            except queue_lib.Empty:
                if self._closed:
                    return None
                if self._producer is not None \
                        and not self._producer.is_alive() \
                        and self._queue.empty():
                    # defensive: a producer that died without its sentinel
                    # must not strand the consumer
                    raise RuntimeError("stream producer thread died")
                continue
            if self._g_depth is not None:
                self._g_depth.set(self._queue.qsize())
            kind = item[0]
            if kind == "end":
                self._epoch_done = True
                return None
            if kind == "err":
                raise RuntimeError(
                    "stream producer failed after retries") from item[1]
            _, meta, ex = item
            if self._resume_until is not None:
                key = (meta[2], meta[3])  # (global_seq, example_idx)
                if key <= self._resume_until:
                    if meta in self._resume_keep:
                        # a pending packer example: re-materialized
                        for i, (m, old) in enumerate(self._pending):
                            if m == meta:
                                self._pending[i] = (m, ex)
                        continue
                    continue  # consumed before the checkpoint: drop
                self._resume_until = None
                self._resume_keep = None
            return meta, ex

    def __iter__(self) -> Iterator[Dict[str, np.ndarray]]:
        return self

    def __next__(self) -> Dict[str, np.ndarray]:
        if self._assembler is not None:
            if not self._assembly_queue:
                self._assembly_queue.append(
                    self._assembler.submit(self._assemble_one))
            head = self._assembly_queue.pop(0)
            while len(self._assembly_queue) < self.prefetch_batches:
                self._assembly_queue.append(
                    self._assembler.submit(self._assemble_one))
            batch, state, window = head.result()
            if batch is None:
                self._drain_assembly()
                raise StopIteration
            self._last_state = state
        else:
            batch = self._assemble_sync()
            if batch is None:
                raise StopIteration
            self._last_state = self._state_snapshot()
            window = self._window_snapshot
        self._batches += 1
        if window is not None:
            self.recent_windows.append(dict(window, batch=self._batches))
        if self.batch_tap is not None:
            self.batch_tap(batch)
        return batch

    def _assemble_one(self):
        batch = self._assemble_sync()
        return batch, self._state_snapshot(), self._window_snapshot

    def _assemble_sync(self) -> Optional[Dict[str, np.ndarray]]:
        self._window_snapshot = None
        if self.packing:
            return self._assemble_packed()
        rows: List[Tuple[Tuple[int, int, int, int],
                         Dict[str, np.ndarray]]] = []
        while len(rows) < self.batch_size:
            nxt = self._next_example()
            if nxt is None:
                return None  # partial tail dropped (static shapes)
            rows.append(nxt)
            self._cursor = (nxt[0][0], nxt[0][1], nxt[0][2], nxt[0][3] + 1)
        self._window_snapshot = self._window_of([m for m, _ in rows])
        return self._stack([ex for _, ex in rows])

    def _assemble_packed(self) -> Optional[Dict[str, np.ndarray]]:
        """Packed batch via the SAME greedy first-fit as the offline plane
        (data/packing.py): top pending up to batch_size * lookahead
        examples, first-fit, emit; unplaced examples stay pending with
        their payloads cached. Epoch end emits only full-coverage batches
        (every row holds >= 1 example), like the offline packer."""
        from bert_pytorch_tpu.data import packing as packing_lib

        target = self.batch_size * self.packing_lookahead
        exhausted = False
        # the second clause drives the resume replay filter to completion
        # even when the restored pending buffer alone meets the target
        # (e.g. a smaller lookahead on resume) — its payloads are not
        # materialized until the filter has run
        while len(self._pending) < target or self._resume_until is not None:
            nxt = self._next_example()
            if nxt is None:
                exhausted = True
                break
            self._pending.append(nxt)
            self._cursor = (nxt[0][0], nxt[0][1], nxt[0][2], nxt[0][3] + 1)
        if not self._pending:
            return None
        missing = [m for m, ex in self._pending if ex is None]
        if missing:
            # a checkpointed pending example never came back from the
            # resume replay (its record now drops or fails tokenization):
            # name it loudly instead of dying in np.stack
            raise RuntimeError(
                "stream resume: checkpointed pending example(s) "
                f"{missing} (source, record, global_seq, example_idx) "
                "vanished from the stream — the corpus or the injection "
                "config changed since the checkpoint")
        examples = self._stack([ex for _, ex in self._pending])
        lengths = packing_lib.example_lengths(examples["attention_mask"])
        bins = packing_lib.first_fit(lengths, self.batch_size, self.seq_len,
                                     self.packing_max_segments)
        if exhausted and any(not members for members in bins):
            self._pending = []  # dropped tail
            return None
        batch = packing_lib.pack_examples(examples, bins, self.seq_len,
                                          self.packing_max_segments)
        placed = {i for members in bins for i in members}
        self._window_snapshot = self._window_of(
            [self._pending[i][0] for i in sorted(placed)])
        self._pending = [self._pending[i]
                         for i in range(len(self._pending))
                         if i not in placed]
        return batch

    @staticmethod
    def _stack(examples: List[Dict[str, np.ndarray]]
               ) -> Dict[str, np.ndarray]:
        out = {k: np.stack([ex[k] for ex in examples])
               for k in examples[0]}
        out["next_sentence_labels"] = \
            out["next_sentence_labels"].reshape(-1).astype(np.int32)
        return out

    @staticmethod
    def _window_of(metas) -> Optional[Dict[str, int]]:
        if not metas:
            return None
        seqs = [m[2] for m in metas]
        return {"record_lo": int(min(seqs)), "record_hi": int(max(seqs))}

    # -- state ----------------------------------------------------------------

    def _state_snapshot(self) -> Dict:
        si, ri, gs, skip = self._cursor
        state = {
            "stream": STREAM_STATE_VERSION,
            "epoch": self.epoch,
            "seed": self.seed,
            "world_size": self.world_size,
            "rank": self.rank,
            "sources_hash": self.sources_hash,
            "seq_len": self.seq_len,
            "source": si, "record": ri, "global_seq": gs, "skip": skip,
            "batches": self._batches,
        }
        if self.packing:
            state["pending"] = [list(meta) for meta, _ in self._pending]
        return state

    def initial_state(self) -> Dict:
        """The fresh-loader state: load_state_dict(initial_state()) rewinds
        to the epoch start (run_pretraining's peek-for-shapes rewind)."""
        return {
            "stream": STREAM_STATE_VERSION, "epoch": 0, "seed": self.seed,
            "world_size": self.world_size, "rank": self.rank,
            "sources_hash": self.sources_hash, "seq_len": self.seq_len,
            "source": 0, "record": 0, "global_seq": 0, "skip": 0,
            "batches": 0, "pending": [],
        }

    def state_dict(self) -> Dict:
        """Cursor as of the last YIELDED batch — safe to checkpoint with
        assembly running ahead (prefetch_batches > 0), same lag contract as
        the offline loader."""
        if self._assembler is None:
            return self._state_snapshot()
        return dict(self._last_state)

    def load_state_dict(self, state: Dict) -> None:
        """Restore the cursor (stopping any live producer). Refused — with
        a loud warning and a fresh start — when the state belongs to a
        different plane, corpus, shard layout, or sequence length: a cursor
        indexes one enumeration and no other."""
        self._drain_assembly()
        self._stop_producer()
        self._epoch_done = False
        self._pending = []
        self._resume_keep = self._resume_until = None
        refuse = None
        if not isinstance(state, dict) or "stream" not in state:
            refuse = "not a streaming-plane state (offline sampler state?)"
        elif state.get("sources_hash") != self.sources_hash:
            refuse = (f"source list changed ({state.get('sources_hash')} "
                      f"-> {self.sources_hash})")
        elif state.get("world_size") != self.world_size \
                or state.get("rank") != self.rank:
            refuse = "world size / rank changed"
        elif state.get("seq_len") != self.seq_len:
            refuse = (f"seq_len changed ({state.get('seq_len')} -> "
                      f"{self.seq_len})")
        elif state.get("seed") != self.seed:
            # the masking rng is f(seed, cursor): a different seed would
            # silently break the bit-identical-resume contract mid-stream
            refuse = (f"seed changed ({state.get('seed')} -> {self.seed})")
        elif state.get("pending") and not self.packing:
            # a packed checkpoint's carry-over examples have nowhere to go
            # in an unpacked loader — dropping them silently would lose
            # training data
            refuse = ("checkpoint carries packed pending examples but "
                      "packing is off")
        if refuse is not None:
            warnings.warn(f"stream: not restoring cursor state: {refuse}; "
                          "starting from the beginning")
            self.epoch = 0
            self._batches = 0
            self._cursor = (0, 0, 0, 0)
            self._last_state = self._state_snapshot()
            return
        self.epoch = int(state["epoch"])
        self._batches = int(state.get("batches", 0))
        self._cursor = (int(state["source"]), int(state["record"]),
                        int(state["global_seq"]), int(state["skip"]))
        pending_meta = [tuple(int(x) for x in m)
                        for m in state.get("pending", [])]
        if pending_meta:
            # payloads rebuild on the next assembly: the producer restarts
            # at the earliest pending record and the replay filter keeps
            # exactly these examples (everything else consumed pre-ckpt)
            self._pending = [(m, None) for m in pending_meta]
            self._resume_keep = set(pending_meta)
        if pending_meta or self._cursor[3] or self._cursor[2]:
            gs, skip = self._cursor[2], self._cursor[3]
            self._resume_until = (gs, skip - 1) if skip else (gs - 1, 1 << 60)
            self._resume_keep = set(pending_meta)
        self._last_state = self._state_snapshot()

    def reset_epoch(self) -> None:
        self._drain_assembly()
        self._stop_producer()
        self._epoch_done = False
        self.epoch += 1
        self._batches = 0
        self._cursor = (0, 0, 0, 0)
        self._pending = []
        self._resume_keep = self._resume_until = None
        self._last_state = self._state_snapshot()

    def _drain_assembly(self) -> None:
        for f in self._assembly_queue:
            try:
                f.result()
            except Exception:
                pass
        self._assembly_queue.clear()

    # -- flight-recorder manifest hook ---------------------------------------

    def stream_info(self) -> Dict:
        """The manifest's optional 'stream' key: enough for replay to name
        the exact records in the recorded window and for an operator to
        re-point the plane at the same corpus position."""
        si = self._cursor[0]
        offsets = []
        for i, n in enumerate(self._source_records):
            if n is not None:
                offsets.append(int(n))
            elif i == si:
                offsets.append(int(self._cursor[1]))
            elif i < si:
                offsets.append(-1)  # passed but count unseen (resumed past)
            else:
                offsets.append(0)
        return {
            "sources_hash": self.sources_hash,
            "sources": [s.name for s in self.sources],
            "source_offsets": offsets,
            "cursor": self.state_dict(),
            "recent_batches": list(self.recent_windows),
        }

    # -- teardown -------------------------------------------------------------

    def close(self) -> None:
        """Idempotent shutdown of producer + pool + assembler; never waits
        on an in-flight tokenize."""
        if self._closed:
            return
        self._closed = True
        if self._assembler is not None:
            self._assembler.shutdown(wait=False, cancel_futures=True)
        self._assembly_queue.clear()
        self._stop_producer()
        self._pool.shutdown(wait=False, cancel_futures=True)

    def __del__(self):  # pragma: no cover - GC timing dependent
        try:
            self.close()
        except Exception:
            pass
