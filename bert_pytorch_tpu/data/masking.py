"""Dynamic (RoBERTa-style) masking and derived features, batch-vectorized.

The reference derives segment ids / input mask / 80-10-10 dynamic masking
per-sample in Python inside Dataset.__getitem__ (src/dataset.py:224-296). At
pod scale the host CPU becomes the bottleneck doing that one sample at a time,
so here every transform is a vectorized numpy op over the whole batch; a batch
of 512 seq-512 samples masks in one pass.

Semantics preserved from the reference (and covered by golden tests):
- segment_ids: 0 everywhere; 1 between the 2nd and 3rd special token
  (inclusive) when the sample has 3 specials, i.e. an NSP pair
  (src/dataset.py:224-238).
- input_mask: 1 up to and including the last special token, 0 on padding
  (src/dataset.py:240-252).
- masking: choose  min(max_pred, max(1, round_down(n_maskable * prob)))
  positions among non-special, non-padding tokens; label = original token at
  chosen positions, -1 elsewhere; of chosen positions 80% -> [MASK], 10% ->
  random token in [0, vocab_size-1), 10% unchanged (src/dataset.py:277-296).

Deliberate deviation: the reference draws mask positions *with* replacement
(np.random.choice default, src/dataset.py:286), which silently yields fewer
distinct masked tokens than requested. We sample without replacement — the
documented 15% is actually achieved; the quirk is not worth reproducing.
"""

from __future__ import annotations

from typing import Optional, Tuple

import numpy as np


def segment_ids_from_specials(input_ids: np.ndarray,
                              special_positions: np.ndarray) -> np.ndarray:
    """(B, S) ids + (B, K) special-token positions -> (B, S) segment ids.

    K is 2 for single-segment samples ([CLS] a [SEP]) and 3 for NSP pairs
    ([CLS] a [SEP] b [SEP]). Rows with K==2 (padded position col) get all 0s.
    """
    B, S = input_ids.shape
    seg = np.zeros((B, S), dtype=input_ids.dtype)
    if special_positions.shape[1] == 3:
        pos = np.arange(S)[None, :]
        start = special_positions[:, 1:2] + 1  # token after 1st [SEP]
        end = special_positions[:, 2:3] + 1    # incl. 2nd [SEP]
        seg = ((pos >= start) & (pos < end)).astype(input_ids.dtype)
    return seg


def input_mask_from_specials(input_ids: np.ndarray,
                             special_positions: np.ndarray) -> np.ndarray:
    """1 through the last special token, 0 on the padding tail."""
    B, S = input_ids.shape
    pos = np.arange(S)[None, :]
    last = special_positions[:, -1][:, None]
    return (pos <= last).astype(input_ids.dtype)


def per_row_mask_draws(rngs, seq_len: int, vocab_size: int
                       ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Pre-draw the three random fields dynamic_mask_batch consumes, one
    independent generator per row — the resume-deterministic offline
    plane derives each row's rng from (seed, epoch, global index)
    (data/sharded.py round 17), so draws must come from per-row streams,
    while the masking LOGIC below stays one vectorized batch call. The
    draw order per generator (scores, action, random_tokens) matches a
    1-row dynamic_mask_batch(rng=...) call bit-for-bit."""
    S = int(seq_len)
    scores = np.stack([r.random((S,)) for r in rngs])
    action = np.stack([r.random((S,)) for r in rngs])
    random_tokens = np.stack([r.integers(0, vocab_size - 1, (S,))
                              for r in rngs])
    return scores, action, random_tokens


def dynamic_mask_batch(
    input_ids: np.ndarray,            # (B, S), NOT modified
    special_positions: np.ndarray,    # (B, K)
    mask_token_index: int,
    max_pred_per_seq: int,
    masked_lm_prob: float,
    vocab_size: int,
    rng: Optional[np.random.Generator] = None,
    original_token_prob: float = 0.1,
    random_token_prob: float = 0.1,
    draws: Optional[Tuple[np.ndarray, np.ndarray, np.ndarray]] = None,
) -> Tuple[np.ndarray, np.ndarray]:
    """Whole-batch 80/10/10 masking. Returns (masked_ids, labels), labels -1
    on unmasked positions.

    Vectorization strategy: draw one uniform score per position, push
    non-maskable positions (specials, padding) to +inf, argsort each row and
    take the first `mask_count` — equivalent to a uniform draw without
    replacement per row, but a single numpy call for the batch.

    Randomness comes from `rng` (one generator for the whole batch) OR
    `draws` (pre-drawn (scores, action, random_tokens) arrays, e.g. from
    per_row_mask_draws when every row needs its own deterministic
    stream); exactly one must be given.
    """
    if (rng is None) == (draws is None):
        raise ValueError("pass exactly one of rng= or draws=")
    B, S = input_ids.shape
    pos = np.arange(S)[None, :]

    maskable = pos < special_positions[:, -1][:, None]  # excludes pad + last special
    for k in range(special_positions.shape[1]):
        maskable &= pos != special_positions[:, k][:, None]

    n_maskable = maskable.sum(axis=1)
    mask_count = np.minimum(max_pred_per_seq,
                            np.maximum(1, (n_maskable * masked_lm_prob)
                                       .astype(np.int64)))

    if draws is not None:
        scores, action, random_tokens = draws
        scores = np.array(scores, dtype=np.float64, copy=True)
    else:
        scores = rng.random((B, S))
    scores[~maskable] = np.inf
    order = np.argsort(scores, axis=1)            # maskable positions first
    rank_of_pos = np.empty_like(order)
    np.put_along_axis(rank_of_pos, order, pos.repeat(B, axis=0), axis=1)
    chosen = rank_of_pos < mask_count[:, None]
    chosen &= maskable

    labels = np.where(chosen, input_ids, -1).astype(np.int64)

    if draws is None:
        action = rng.random((B, S))
    keep = action < original_token_prob
    randomize = (~keep) & (action < original_token_prob + random_token_prob)
    # random replacement token in [0, vocab_size-1) — matches the reference's
    # np.random.randint(0, vocab_size - 1) bound (src/dataset.py:293)
    if draws is None:
        random_tokens = rng.integers(0, vocab_size - 1, (B, S))

    masked = input_ids.copy()
    do_mask = chosen & ~keep & ~randomize
    do_rand = chosen & randomize
    masked[do_mask] = mask_token_index
    masked[do_rand] = random_tokens[do_rand]
    return masked, labels


def labels_from_premasked(input_ids: np.ndarray,
                          masked_lm_positions: np.ndarray,
                          masked_lm_ids: np.ndarray) -> np.ndarray:
    """Legacy NVIDIA premasked format -> dense (B, S) labels with -1 fill
    (src/dataset.py:254-275). A zero in masked_lm_positions terminates the
    valid prefix (position 0 is [CLS], never maskable)."""
    B, S = input_ids.shape
    labels = np.full((B, S), -1, dtype=np.int64)
    for b in range(B):  # ragged prefix lengths; B is a host-side batch, cheap
        positions = masked_lm_positions[b]
        n = positions.shape[0]
        zeros = np.nonzero(positions == 0)[0]
        if zeros.size:
            n = zeros[0]
        labels[b, positions[:n]] = masked_lm_ids[b, :n]
    return labels
