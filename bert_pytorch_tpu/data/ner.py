"""CoNLL-format NER dataset: parse, per-word tokenize with label
propagation, fixed-length encode.

Parity with the reference src/ner_dataset.py: sentences split on blank lines
and -DOCSTART records (:73-84), token from column 0 and label from column 3
(:80-82), labels propagated to every subword piece (:16-20), [CLS]/[SEP]
framed with the [SPC] sentinel mapping to -100 (ignored by the loss, :30-35),
label ids start at 1 (0 is the padding label, run_ner.py:63-66 label_to_idx
start=1), padded to max_seq_len (:38-42) with IGNORE_LABEL on padding
positions so the loss sees only real tokens (the reference achieved the same
by masking its loss to attention_mask==1 positions).
"""

from __future__ import annotations

import re
from dataclasses import dataclass
from typing import Dict, List, Sequence, Tuple

import numpy as np

IGNORE_LABEL = -100  # [SPC] positions — torch CE ignore_index default


@dataclass
class NERSample:
    words: List[str]
    labels: List[str]

    def __post_init__(self):
        if len(self.words) != len(self.labels):
            raise ValueError("words/labels length mismatch")

    def encode(self, tokenizer, label_to_id: Dict[str, int],
               max_seq_len: int) -> Tuple[List[int], List[int], List[int]]:
        """-> (input_ids, label_ids, mask), each max_seq_len long."""
        pieces: List[str] = []
        piece_labels: List[str] = []
        for word, label in zip(self.words, self.labels):
            subs = tokenizer.encode(word, add_special_tokens=False).tokens
            pieces.extend(subs)
            piece_labels.extend([label] * len(subs))

        pieces = pieces[:max_seq_len - 2]
        piece_labels = piece_labels[:max_seq_len - 2]

        tokens = ["[CLS]"] + pieces + ["[SEP]"]
        labels = [IGNORE_LABEL] + [label_to_id[l] for l in piece_labels] \
            + [IGNORE_LABEL]
        unk = tokenizer.token_to_id("[UNK]") or 0
        ids = [tokenizer.token_to_id(t) if tokenizer.token_to_id(t)
               is not None else unk for t in tokens]
        mask = [1] * len(ids)

        pad = max_seq_len - len(ids)
        ids += [0] * pad
        # Padding positions carry IGNORE_LABEL so the loss never trains them.
        # (The reference pads with label id 0 but equivalently restricts its
        # loss to attention_mask==1 positions, src/modeling.py
        # BertForTokenClassification — ignore-labels express that here.)
        labels += [IGNORE_LABEL] * pad
        mask += [0] * pad
        return ids, labels, mask


def parse_conll(filename: str) -> List[NERSample]:
    samples: List[NERSample] = []
    words: List[str] = []
    labels: List[str] = []
    with open(filename, "r", encoding="utf-8") as f:
        for line in f:
            if not line.strip() or line.startswith("-DOCSTART"):
                if words:
                    samples.append(NERSample(words, labels))
                    words, labels = [], []
                continue
            cols = [c.strip() for c in re.split(r"[ \t]", line) if c.strip()]
            if len(cols) < 4:
                continue
            words.append(cols[0])
            labels.append(cols[3])
    if words:
        samples.append(NERSample(words, labels))
    return samples


class NERDataset:
    """Encoded CoNLL dataset as numpy arrays. label ids: 0 = padding,
    1..len(labels) = entity tags (reference run_ner.py:66), -100 ignored."""

    def __init__(self, filename: str, tokenizer, labels: Sequence[str],
                 max_seq_len: int = 128):
        self.samples = parse_conll(filename)
        self.label_to_id = {l: i for i, l in enumerate(labels, start=1)}
        self.id_to_label = {i: l for l, i in self.label_to_id.items()}
        self.tokenizer = tokenizer
        self.max_seq_len = max_seq_len

    def __len__(self) -> int:
        return len(self.samples)

    def arrays(self) -> Dict[str, np.ndarray]:
        ids, labels, masks = [], [], []
        for s in self.samples:
            i, l, m = s.encode(self.tokenizer, self.label_to_id,
                               self.max_seq_len)
            ids.append(i)
            labels.append(l)
            masks.append(m)
        return {
            "input_ids": np.asarray(ids, np.int32),
            "labels": np.asarray(labels, np.int32),
            "attention_mask": np.asarray(masks, np.int32),
        }


def macro_f1(logits: np.ndarray, labels: np.ndarray) -> float:
    """Macro F1 over non-padding, non-ignored positions (reference
    compute_metrics, run_ner.py:127-142 — positions with label > 0)."""
    from sklearn.metrics import f1_score

    preds = np.argmax(logits, axis=-1)
    keep = labels > 0
    return float(f1_score(labels[keep], preds[keep], average="macro"))


def classification_diagnostics(logits: np.ndarray, labels: np.ndarray,
                               label_names=None) -> dict:
    """Per-class F1 + prediction/label histograms over scored positions.

    Distinguishes majority-class collapse (every prediction lands in one
    class: its pred count ~= total, other classes' F1 = 0) from a weak but
    spread classifier (all classes predicted, low-but-nonzero F1s) — the
    diagnosis the flat round-3 NER curve needed."""
    from sklearn.metrics import f1_score

    preds = np.argmax(logits, axis=-1)
    keep = labels > 0
    p, l = preds[keep], labels[keep]
    classes = sorted(set(np.unique(l)) | set(np.unique(p)))
    per_f1 = f1_score(l, p, labels=classes, average=None, zero_division=0)
    name = (lambda c: label_names[c - 1]
            if label_names and 1 <= c <= len(label_names) else str(c))
    return {
        "per_class_f1": {name(c): round(float(f), 4)
                         for c, f in zip(classes, per_f1)},
        "pred_histogram": {name(c): int((p == c).sum()) for c in classes},
        "label_histogram": {name(c): int((l == c).sum()) for c in classes},
        "n_scored": int(keep.sum()),
    }
