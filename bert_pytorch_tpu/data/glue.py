"""GLUE/SWAG-style finetune datasets: pair classification, multiple
choice, labeled sentences for the embedding probe.

The reference repo ships no data loaders for its classification heads
(`BertForSequenceClassification` / `BertForMultipleChoice` exist in
modeling.py:1053-1179 but no run_* wires them); these loaders close that
gap with deliberately plain formats:

- pair classification / embedding: TSV lines ``label<TAB>text_a`` or
  ``label<TAB>text_a<TAB>text_b`` (GLUE two-sentence tasks);
- multiple choice: JSONL objects ``{"question": str, "choices": [str],
  "label": int}`` (SWAG-style, a fixed choice count per file).

Featurization delegates to `tasks.predict.encode_pair`, the SAME
function the serving frontend featurizes live requests with — training
data and traffic cannot tokenize differently (the tasks/predict.py
no-fork rule extended to inputs).
"""

from __future__ import annotations

import json
from typing import Dict, List, Sequence, Tuple

import numpy as np

from bert_pytorch_tpu.tasks.predict import encode_pair


def _to_row(ids: List[int], types: List[int], max_seq_len: int
            ) -> Tuple[List[int], List[int], List[int]]:
    pad = max_seq_len - len(ids)
    mask = [1] * len(ids) + [0] * pad
    return ids + [0] * pad, types + [0] * pad, mask


def parse_pair_tsv(filename: str) -> List[Tuple[str, str, str]]:
    """-> [(label, text_a, text_b-or-'')]; blank/comment lines skipped."""
    rows = []
    with open(filename, encoding="utf-8") as f:
        for line in f:
            line = line.rstrip("\n")
            if not line.strip() or line.startswith("#"):
                continue
            cols = line.split("\t")
            if len(cols) < 2:
                raise ValueError(f"{filename}: want label<TAB>text_a"
                                 f"[<TAB>text_b], got {line!r}")
            rows.append((cols[0].strip(), cols[1],
                         cols[2] if len(cols) > 2 else ""))
    return rows


class PairClassificationDataset:
    """TSV pair-classification corpus as fixed-length numpy arrays.

    `labels` fixes the label-name -> id order (ids start at 0 — unlike
    NER there is no padding class; empty packed slots use -1, which the
    loss ignores). Also the loader for the embedding task's probe
    objective (single-sentence rows, proxy labels)."""

    def __init__(self, filename: str, tokenizer, labels: Sequence[str],
                 max_seq_len: int = 128):
        self.rows = parse_pair_tsv(filename)
        self.label_to_id = {l: i for i, l in enumerate(labels)}
        self.id_to_label = {i: l for l, i in self.label_to_id.items()}
        self.tokenizer = tokenizer
        self.max_seq_len = int(max_seq_len)
        unknown = sorted({l for l, _, _ in self.rows}
                         - set(self.label_to_id))
        if unknown:
            raise ValueError(f"{filename}: labels {unknown} not in "
                             f"--labels {list(labels)}")

    def __len__(self) -> int:
        return len(self.rows)

    def arrays(self) -> Dict[str, np.ndarray]:
        ids_, types_, masks_, labels_ = [], [], [], []
        for label, a, b in self.rows:
            ids, types = encode_pair(self.tokenizer, a, b or None,
                                     max_pieces=self.max_seq_len)
            ids, types, mask = _to_row(ids, types, self.max_seq_len)
            ids_.append(ids)
            types_.append(types)
            masks_.append(mask)
            labels_.append(self.label_to_id[label])
        return {
            "input_ids": np.asarray(ids_, np.int32),
            "token_type_ids": np.asarray(types_, np.int32),
            "attention_mask": np.asarray(masks_, np.int32),
            "labels": np.asarray(labels_, np.int32),
        }


class MultipleChoiceDataset:
    """JSONL multiple-choice corpus -> (N, C, S) arrays.

    Every record must carry exactly `num_choices` choices (static shapes
    are the TPU contract — a variable choice count would retrace); each
    choice encodes as the pair ([CLS] question [SEP] choice [SEP])."""

    def __init__(self, filename: str, tokenizer, num_choices: int,
                 max_seq_len: int = 128):
        self.records = []
        with open(filename, encoding="utf-8") as f:
            for ln, line in enumerate(f, start=1):
                if not line.strip():
                    continue
                rec = json.loads(line)
                choices = rec.get("choices")
                if not isinstance(choices, list) \
                        or len(choices) != num_choices:
                    raise ValueError(
                        f"{filename}:{ln}: want exactly {num_choices} "
                        f"choices, got {choices!r}")
                label = int(rec.get("label", -1))
                if not 0 <= label < num_choices:
                    raise ValueError(f"{filename}:{ln}: label {label} "
                                     f"outside [0, {num_choices})")
                self.records.append((rec.get("question", ""), choices,
                                     label))
        self.tokenizer = tokenizer
        self.num_choices = int(num_choices)
        self.max_seq_len = int(max_seq_len)

    def __len__(self) -> int:
        return len(self.records)

    def arrays(self) -> Dict[str, np.ndarray]:
        N, C, S = len(self.records), self.num_choices, self.max_seq_len
        out = {
            "input_ids": np.zeros((N, C, S), np.int32),
            "token_type_ids": np.zeros((N, C, S), np.int32),
            "attention_mask": np.zeros((N, C, S), np.int32),
            "labels": np.zeros((N,), np.int32),
        }
        for i, (question, choices, label) in enumerate(self.records):
            for c, choice in enumerate(choices):
                ids, types = encode_pair(self.tokenizer, question or choice,
                                         choice if question else None,
                                         max_pieces=S)
                ids, types, mask = _to_row(ids, types, S)
                out["input_ids"][i, c] = ids
                out["token_type_ids"][i, c] = types
                out["attention_mask"][i, c] = mask
            out["labels"][i] = label
        return out


def accuracy(logits: np.ndarray, labels: np.ndarray) -> float:
    """argmax accuracy over rows with label >= 0 (padded eval tails carry
    -1)."""
    logits = np.asarray(logits)
    labels = np.asarray(labels)
    keep = labels >= 0
    if not keep.any():
        return 0.0
    return float((np.argmax(logits[keep], axis=-1)
                  == labels[keep]).mean())
