from bert_pytorch_tpu.data.masking import (  # noqa: F401
    dynamic_mask_batch,
    input_mask_from_specials,
    labels_from_premasked,
    segment_ids_from_specials,
)
from bert_pytorch_tpu.data.sharded import (  # noqa: F401
    HostShardSampler,
    PretrainingDataLoader,
    ShardIndex,
)
from bert_pytorch_tpu.data.streaming import (  # noqa: F401
    FileSource,
    StreamingPretrainingLoader,
    discover_sources,
    sources_fingerprint,
)
