"""URL/file -> local-cache resolution for model archives.

Re-implements the capability of the reference's src/file_utils.py:97-263
(AllenNLP-lineage `cached_path`: download a URL once into a content-addressed
cache keyed by URL+ETag, then serve the local copy) without the S3/boto3
machinery — plain HTTPS + file:// are enough for the Google checkpoint zips
the pipeline uses (pipeline/download.py). Local paths pass through untouched.
"""

from __future__ import annotations

import hashlib
import json
import os
import shutil
import tempfile
import urllib.parse
import urllib.request
from typing import Optional

DEFAULT_CACHE = os.path.join(
    os.path.expanduser("~"), ".cache", "bert_pytorch_tpu")


def url_to_filename(url: str, etag: Optional[str] = None) -> str:
    """Content-addressed cache name: sha256(url) [+ '.' + sha256(etag)]
    (same scheme as reference src/file_utils.py:57-72)."""
    name = hashlib.sha256(url.encode("utf-8")).hexdigest()
    if etag:
        name += "." + hashlib.sha256(etag.encode("utf-8")).hexdigest()
    return name


def cached_path(url_or_filename: str,
                cache_dir: Optional[str] = None) -> str:
    """Resolve a URL or local path to a local file path.

    - existing local path: returned as-is;
    - http(s):// or file:// URL: downloaded into the cache (once per
      URL+ETag) and the cached path returned (reference
      src/file_utils.py:97-131).
    """
    parsed = urllib.parse.urlparse(url_or_filename)
    if parsed.scheme in ("http", "https", "file"):
        return get_from_cache(url_or_filename, cache_dir)
    if os.path.exists(url_or_filename):
        return url_or_filename
    raise FileNotFoundError(
        f"{url_or_filename} is neither a URL nor an existing local path")


def get_from_cache(url: str, cache_dir: Optional[str] = None) -> str:
    """Download `url` into the cache unless an up-to-date copy exists;
    return the cached path (reference src/file_utils.py:188-263).

    Offline behavior: when the ETag revalidation round-trip fails but any
    prior download of this URL exists (any ETag), the newest cached copy is
    served instead of crashing — a cache that only works online defeats its
    purpose."""
    cache_dir = cache_dir or DEFAULT_CACHE
    os.makedirs(cache_dir, exist_ok=True)
    url_key = url_to_filename(url)

    etag = None
    head_failed = False
    if urllib.parse.urlparse(url).scheme in ("http", "https"):
        try:
            req = urllib.request.Request(url, method="HEAD")
            with urllib.request.urlopen(req, timeout=30) as resp:
                etag = resp.headers.get("ETag")
        except Exception:
            head_failed = True

    if head_failed:
        cached = sorted(
            (f for f in os.listdir(cache_dir)
             if f.startswith(url_key) and not f.endswith(".json")),
            key=lambda f: os.path.getmtime(os.path.join(cache_dir, f)))
        if cached:
            return os.path.join(cache_dir, cached[-1])

    cache_path = os.path.join(cache_dir, url_to_filename(url, etag))
    if os.path.exists(cache_path):
        return cache_path

    # download to a temp file, then atomic-rename into place so a crashed
    # download never leaves a half-written cache entry
    fd, tmp = tempfile.mkstemp(dir=cache_dir)
    try:
        with os.fdopen(fd, "wb") as out, \
                urllib.request.urlopen(url, timeout=300) as resp:
            shutil.copyfileobj(resp, out)
        os.replace(tmp, cache_path)
    finally:
        if os.path.exists(tmp):
            os.unlink(tmp)

    meta = {"url": url, "etag": etag}
    with open(cache_path + ".json", "w", encoding="utf-8") as f:
        json.dump(meta, f)
    return cache_path
