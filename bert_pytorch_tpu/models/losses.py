"""Loss functions for every task head.

The reference computed losses inside each head's forward when labels were
given (e.g. BertPretrainingCriterion at run_pretraining.py:53-67, SQuAD loss at
run_squad.py:1089-1092). Functional JAX separates them: heads return logits,
these functions turn (logits, labels) into scalars. All cross-entropies are
computed in fp32 with masked mean semantics identical to torch's
CrossEntropyLoss(ignore_index=...) — sum over valid positions divided by the
count of valid positions.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp


def cross_entropy(logits: jax.Array, labels: jax.Array,
                  ignore_index: int = -1) -> jax.Array:
    """Mean CE over positions where labels != ignore_index.

    logits: (..., C) fp32; labels: (...) int. Matches
    torch.nn.CrossEntropyLoss(ignore_index=) mean reduction, returning 0.0
    when no positions are valid (torch returns NaN there; 0 keeps grad clean
    when a microbatch happens to contain no masked tokens).
    """
    logits = logits.astype(jnp.float32)
    valid = labels != ignore_index
    safe_labels = jnp.where(valid, labels, 0)
    logp = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(logp, safe_labels[..., None], axis=-1)[..., 0]
    nll = jnp.where(valid, nll, 0.0)
    count = jnp.maximum(valid.sum(), 1)
    return nll.sum() / count


def cross_entropy_terms(logits: jax.Array, labels: jax.Array,
                        ignore_index: int = -1
                        ) -> Tuple[jax.Array, jax.Array]:
    """(nll sum, valid count) — `cross_entropy` stopped before the final
    max/divide, for callers that must reduce across devices BEFORE the
    normalization (the ZeRO-1 reduce-scatter gradient path wraps the
    fwd/bwd in a shard_map region, psums these local sums, and applies
    maximum(count, 1) after the psum — the exact grouping the GSPMD
    lowering of `cross_entropy` uses, so the metric stays bit-identical).
    The per-position arithmetic is _nll's, which is cross_entropy's."""
    nll, valid = _nll(logits, labels, ignore_index)
    return nll.sum(), valid.sum()


def pretraining_loss_terms(
    mlm_logits: jax.Array,
    masked_lm_labels: jax.Array,
    nsp_logits: Optional[jax.Array] = None,
    next_sentence_labels: Optional[jax.Array] = None,
) -> Tuple[Tuple[jax.Array, jax.Array],
           Optional[Tuple[jax.Array, jax.Array]]]:
    """pretraining_loss decomposed into its per-term (nll sum, count)
    pairs: ((mlm_sum, mlm_count), (nsp_sum, nsp_count) | None). The
    caller owns the cross-device reduction and the
    sum/maximum(count, 1) division per term — summing the two finished
    quotients reproduces `pretraining_loss` exactly."""
    mlm = cross_entropy_terms(mlm_logits, masked_lm_labels, ignore_index=-1)
    nsp = None
    if nsp_logits is not None and next_sentence_labels is not None:
        nsp = cross_entropy_terms(nsp_logits, next_sentence_labels,
                                  ignore_index=-1)
    return mlm, nsp


def pretraining_loss(
    mlm_logits: jax.Array,                    # (B, S, V)
    masked_lm_labels: jax.Array,              # (B, S), -1 = unmasked
    nsp_logits: Optional[jax.Array] = None,   # (B, 2) or packed (B, G, 2)
    next_sentence_labels: Optional[jax.Array] = None,  # (B,) or (B, G)
) -> jax.Array:
    """MLM + NSP summed, ignore_index=-1 (reference BertPretrainingCriterion,
    run_pretraining.py:53-67).

    Packed batches (--packing) arrive with per-segment NSP terms: logits
    (B, G, 2) against labels (B, G), -1 marking empty segment slots. The
    masked-mean reduction weights every real segment equally — a packed
    batch's MLM+NSP loss equals its unpacked equivalent's exactly, because
    both pool the same masked-token set and the same NSP example set (the
    invariant tests/test_packing.py pins down)."""
    loss = cross_entropy(mlm_logits, masked_lm_labels, ignore_index=-1)
    if nsp_logits is not None and next_sentence_labels is not None:
        loss = loss + cross_entropy(nsp_logits, next_sentence_labels,
                                    ignore_index=-1)
    return loss


def qa_loss(start_logits: jax.Array, end_logits: jax.Array,
            start_positions: jax.Array, end_positions: jax.Array
            ) -> jax.Array:
    """(CE(start) + CE(end)) / 2; answer positions outside [0, S) contribute
    no loss — the reference clamps them to ignored_index=seq_len and uses
    CrossEntropyLoss(ignore_index=seq_len) (run_squad.py:1080-1092), so
    truncated-answer windows are ignored, not trained toward a wrong token."""
    seq_len = start_logits.shape[-1]

    def drop_out_of_window(pos):
        return jnp.where((pos >= 0) & (pos < seq_len), pos, -1)

    loss_s = cross_entropy(start_logits, drop_out_of_window(start_positions),
                           ignore_index=-1)
    loss_e = cross_entropy(end_logits, drop_out_of_window(end_positions),
                           ignore_index=-1)
    return (loss_s + loss_e) / 2.0


def token_classification_loss(logits: jax.Array, labels: jax.Array,
                              ignore_index: int = -100) -> jax.Array:
    """Per-token CE; -100 ignores subword/[SPC] positions
    (reference src/ner_dataset.py label propagation + torch default)."""
    return cross_entropy(logits, labels, ignore_index=ignore_index)


def classification_loss(logits: jax.Array, labels: jax.Array) -> jax.Array:
    return cross_entropy(logits, labels, ignore_index=-1)


def _ordered_sum(x: jax.Array) -> jax.Array:
    """Strict left-to-right (row-major flat) sequential sum via lax.scan.

    jnp.sum's reduction grouping depends on the array SHAPE, so a packed
    batch and its one-segment-per-row equivalent — identical loss terms,
    different shapes — drift in the last float32 bits under the default
    reduce. Empty slots add exact zeros, so the sequential partial-sum
    sequence is a pure function of the real values in traversal order —
    the property the packed-vs-unpadded bit-equality pin rests on
    (tests/test_finetune_packing.py). Only ever used on tiny
    per-segment aggregates ((B, G)-sized), where a sequential loop is
    free; the big (B, S, V)-scale reductions keep the fast default."""
    flat = x.reshape(-1)
    total, _ = jax.lax.scan(lambda acc, v: (acc + v, None),
                            jnp.zeros((), flat.dtype), flat)
    return total


def _nll(logits: jax.Array, labels: jax.Array, ignore_index: int
         ) -> Tuple[jax.Array, jax.Array]:
    """(per-position nll with ignored slots exactly 0, valid mask)."""
    logits = logits.astype(jnp.float32)
    valid = labels != ignore_index
    safe = jnp.where(valid, labels, 0)
    logp = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(logp, safe[..., None], axis=-1)[..., 0]
    return jnp.where(valid, nll, 0.0), valid


def segment_onehot(segment_ids: jax.Array, max_segments: int) -> jax.Array:
    """(B, S) packed segment ids (1..G, 0 = pad) -> (B, G, S) boolean
    segment-membership mask. The ONE construction every packed gather and
    reduction shares — the [CLS] position gather
    (models/bert.positions_from_segment_ids), the sentence-embedding mean,
    and the packed token/QA losses below. Packed-vs-unpadded bit-equality
    (tests/test_finetune_packing.py) depends on all of them masking with
    identical bits, so build the mask here, never inline."""
    want = jnp.arange(1, max_segments + 1, dtype=segment_ids.dtype)
    return segment_ids[:, None, :] == want[None, :, None]


def segment_classification_loss(logits: jax.Array, labels: jax.Array
                                ) -> jax.Array:
    """Classification CE over per-segment pooled logits ((B, G, C)
    against (B, G) labels, -1 = empty slot), reduced with the
    order-canonical sequential sum so packed and one-segment-per-row
    batches produce the same bits. Degenerates to plain classification
    on (B, C)/(B,) shapes."""
    nll, valid = _nll(logits, labels, ignore_index=-1)
    return _ordered_sum(nll) / jnp.maximum(valid.sum(), 1)


def choice_loss(scores: jax.Array, labels: jax.Array,
                num_choices: int) -> jax.Array:
    """Multiple-choice CE. `scores` is (B, C) (the reference shape,
    src/modeling.py:1112-1179) or packed (B, G) with each example's C
    choices in C consecutive segments — regrouped to (B, G/C, C) here.
    `labels` is the matching (B,) / (B, G/C) chosen-index array, -1 for
    empty packed groups. Ordered-sum reduction: packed and plain batches
    of the same examples agree bit-for-bit.

    Shape rule: labels with the SAME rank as scores mark the packed
    per-segment form (scores (B, G) vs labels (B, G/C) — even when G/C
    happens to equal num_choices), so scores regroup to (B, G/C, C);
    labels one rank below scores mean the choice axis is already last
    (the plain (B, C)/(B,) pair)."""
    if labels.ndim == scores.ndim:
        scores = scores.reshape(*scores.shape[:-1], -1, num_choices)
    return segment_classification_loss(scores, labels)


def packed_token_loss(logits: jax.Array, labels: jax.Array,
                      segment_ids: jax.Array, max_segments: int,
                      ignore_index: int = -100) -> jax.Array:
    """Per-token CE for packed rows, reduced SEGMENT-FIRST: per-token
    nll is contracted against the segment one-hot (an einsum whose
    zero-slot terms are exactly 0.0) before the tiny (B, G) sum, so a
    packed batch's scalar equals the same examples one-segment-per-row
    bit-for-bit — a flat (B, S) sum regroups the reduction tree when the
    tokens move and drifts in the last float32 bits (per-token values
    are identical; only the summation grouping moved)."""
    nll, valid = _nll(logits, labels, ignore_index)
    onehot = segment_onehot(segment_ids, max_segments).astype(jnp.float32)
    seg_nll = jnp.einsum("bgs,bs->bg", onehot, nll)
    return _ordered_sum(seg_nll) / jnp.maximum(valid.sum(), 1)


def packed_qa_loss(start_logits: jax.Array, end_logits: jax.Array,
                   start_positions: jax.Array, end_positions: jax.Array,
                   segment_ids: jax.Array, max_segments: int) -> jax.Array:
    """Per-segment span CE for packed rows: each segment's softmax runs
    over ITS OWN positions only (cross-segment and pad logits are exactly
    excluded via a -inf mask, exp(-inf) == 0.0), so a packed row's loss
    equals the same examples' loss one-segment-per-row bit-for-bit —
    a full-row softmax would mix denominators across co-packed strangers.

    start/end_positions are (B, G) ABSOLUTE row positions (example
    position + packing offset), -1 for empty slots or answers outside
    the window (the qa_loss clamp, reference run_squad.py:1080-1092).
    """
    seg_mask = segment_onehot(segment_ids, max_segments)       # (B, G, S)

    def seg_ce(logits, positions):
        logits = logits.astype(jnp.float32)[:, None, :]        # (B, 1, S)
        masked = jnp.where(seg_mask, logits, -jnp.inf)
        logp = jax.nn.log_softmax(masked, axis=-1)             # (B, G, S)
        valid = positions >= 0
        safe = jnp.where(valid, positions, 0)
        nll = -jnp.take_along_axis(logp, safe[..., None],
                                   axis=-1)[..., 0]
        nll = jnp.where(valid, nll, 0.0)
        return _ordered_sum(nll) / jnp.maximum(valid.sum(), 1)

    return (seg_ce(start_logits, start_positions)
            + seg_ce(end_logits, end_positions)) / 2.0


def mlm_accuracy(mlm_logits: jax.Array, labels: jax.Array
                 ) -> Tuple[jax.Array, jax.Array]:
    """(num_correct, num_masked) for masked-token accuracy tracking."""
    valid = labels != -1
    pred = jnp.argmax(mlm_logits, axis=-1)
    correct = jnp.logical_and(pred == labels, valid)
    return correct.sum(), valid.sum()
