"""Loss functions for every task head.

The reference computed losses inside each head's forward when labels were
given (e.g. BertPretrainingCriterion at run_pretraining.py:53-67, SQuAD loss at
run_squad.py:1089-1092). Functional JAX separates them: heads return logits,
these functions turn (logits, labels) into scalars. All cross-entropies are
computed in fp32 with masked mean semantics identical to torch's
CrossEntropyLoss(ignore_index=...) — sum over valid positions divided by the
count of valid positions.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp


def cross_entropy(logits: jax.Array, labels: jax.Array,
                  ignore_index: int = -1) -> jax.Array:
    """Mean CE over positions where labels != ignore_index.

    logits: (..., C) fp32; labels: (...) int. Matches
    torch.nn.CrossEntropyLoss(ignore_index=) mean reduction, returning 0.0
    when no positions are valid (torch returns NaN there; 0 keeps grad clean
    when a microbatch happens to contain no masked tokens).
    """
    logits = logits.astype(jnp.float32)
    valid = labels != ignore_index
    safe_labels = jnp.where(valid, labels, 0)
    logp = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(logp, safe_labels[..., None], axis=-1)[..., 0]
    nll = jnp.where(valid, nll, 0.0)
    count = jnp.maximum(valid.sum(), 1)
    return nll.sum() / count


def pretraining_loss(
    mlm_logits: jax.Array,                    # (B, S, V)
    masked_lm_labels: jax.Array,              # (B, S), -1 = unmasked
    nsp_logits: Optional[jax.Array] = None,   # (B, 2) or packed (B, G, 2)
    next_sentence_labels: Optional[jax.Array] = None,  # (B,) or (B, G)
) -> jax.Array:
    """MLM + NSP summed, ignore_index=-1 (reference BertPretrainingCriterion,
    run_pretraining.py:53-67).

    Packed batches (--packing) arrive with per-segment NSP terms: logits
    (B, G, 2) against labels (B, G), -1 marking empty segment slots. The
    masked-mean reduction weights every real segment equally — a packed
    batch's MLM+NSP loss equals its unpacked equivalent's exactly, because
    both pool the same masked-token set and the same NSP example set (the
    invariant tests/test_packing.py pins down)."""
    loss = cross_entropy(mlm_logits, masked_lm_labels, ignore_index=-1)
    if nsp_logits is not None and next_sentence_labels is not None:
        loss = loss + cross_entropy(nsp_logits, next_sentence_labels,
                                    ignore_index=-1)
    return loss


def qa_loss(start_logits: jax.Array, end_logits: jax.Array,
            start_positions: jax.Array, end_positions: jax.Array
            ) -> jax.Array:
    """(CE(start) + CE(end)) / 2; answer positions outside [0, S) contribute
    no loss — the reference clamps them to ignored_index=seq_len and uses
    CrossEntropyLoss(ignore_index=seq_len) (run_squad.py:1080-1092), so
    truncated-answer windows are ignored, not trained toward a wrong token."""
    seq_len = start_logits.shape[-1]

    def drop_out_of_window(pos):
        return jnp.where((pos >= 0) & (pos < seq_len), pos, -1)

    loss_s = cross_entropy(start_logits, drop_out_of_window(start_positions),
                           ignore_index=-1)
    loss_e = cross_entropy(end_logits, drop_out_of_window(end_positions),
                           ignore_index=-1)
    return (loss_s + loss_e) / 2.0


def token_classification_loss(logits: jax.Array, labels: jax.Array,
                              ignore_index: int = -100) -> jax.Array:
    """Per-token CE; -100 ignores subword/[SPC] positions
    (reference src/ner_dataset.py label propagation + torch default)."""
    return cross_entropy(logits, labels, ignore_index=ignore_index)


def classification_loss(logits: jax.Array, labels: jax.Array) -> jax.Array:
    return cross_entropy(logits, labels, ignore_index=-1)


def mlm_accuracy(mlm_logits: jax.Array, labels: jax.Array
                 ) -> Tuple[jax.Array, jax.Array]:
    """(num_correct, num_masked) for masked-token accuracy tracking."""
    valid = labels != -1
    pred = jnp.argmax(mlm_logits, axis=-1)
    correct = jnp.logical_and(pred == labels, valid)
    return correct.sum(), valid.sum()
