"""BERT model zoo, TPU-first.

Capability parity with the reference's src/modeling.py (BertModel + 7 task
heads, config-driven NSP/pooler/token-type, tied MLM decoder, activation
checkpointing), re-designed for XLA rather than translated:

- Every kernel init is wrapped in `nn.with_logical_partitioning`, so the same
  module runs replicated, FSDP-sharded, or tensor-parallel purely by changing
  the logical-axis rules in `bert_pytorch_tpu.parallel.sharding` — no NCCL-era
  module wrappers (reference wrapped with DDP at run_pretraining.py:260).
- The encoder stack is a `nn.scan` over one BertLayer (layer-stacked params),
  which keeps compile time O(1) in depth; activation checkpointing is
  `nn.remat` around the scanned layer (reference: torch.utils.checkpoint in
  sqrt(L) chunks, src/modeling.py:495-520). `config.stacked_params=False`
  swaps the scan for L per-layer modules (params under encoder/layer_{i});
  backward wgrads then write per-layer leaves directly instead of
  dynamic_update_slice into the (L, ...) stack — the perf trade is
  documented on BertEncoder.
- Compute dtype is bf16 with fp32 params and fp32 softmax/LayerNorm
  statistics; there is no GradScaler anywhere (reference: apex AMP O2 +
  dynamic loss scaling).
- Attention-mask handling matches the reference's additive (1-mask)*-1e4 bias
  (src/modeling.py:843-851).

Shape glossary: B batch, S sequence, H heads, D head_dim, E hidden, F mlp.
"""

from __future__ import annotations

from typing import Any, Optional, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax.ad_checkpoint import checkpoint_name

from bert_pytorch_tpu.config import BertConfig
from bert_pytorch_tpu.models.losses import segment_onehot
from bert_pytorch_tpu.ops.activations import ACT2FN
from bert_pytorch_tpu.ops.attention import dot_product_attention, make_attention_bias
from bert_pytorch_tpu.ops.layernorm import add_dropout_layer_norm, layer_norm

Dtype = Any


def _dense_init(config: BertConfig):
    return nn.initializers.normal(stddev=config.initializer_range)


class LayerNorm(nn.Module):
    """Affine LayerNorm, eps 1e-12 (reference src/modeling.py:311-335); params
    fp32, dispatches to the fused Pallas kernel on TPU when config asks."""

    epsilon: float = 1e-12
    fused: bool = True

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        dim = x.shape[-1]
        scale = self.param(
            "scale",
            nn.with_logical_partitioning(nn.initializers.ones, ("norm",)),
            (dim,), jnp.float32)
        bias = self.param(
            "bias",
            nn.with_logical_partitioning(nn.initializers.zeros, ("norm",)),
            (dim,), jnp.float32)
        return layer_norm(x, scale, bias, eps=self.epsilon, fused=self.fused)


class ResidualDropoutLayerNorm(nn.Module):
    """LN(residual + dropout(x)) as one op — the tail of both residual
    sites in every BertLayer (reference src/modeling.py:439-487). The
    dropout mask comes from a counter hash (seeded from the 'dropout' rng
    per call site), evaluated inside the fused kernel in forward AND
    backward so it never exists in HBM (ops/layernorm.add_dropout_layer_norm
    — measured +13 MFU points at seq128 over nn.Dropout + LN). Param names
    match LayerNorm so checkpoints are interchangeable."""

    rate: float
    epsilon: float = 1e-12
    fused: bool = True
    fused_dropout: bool = True

    @nn.compact
    def __call__(self, x: jax.Array, residual: jax.Array,
                 deterministic: bool = True) -> jax.Array:
        dim = x.shape[-1]
        scale = self.param(
            "scale",
            nn.with_logical_partitioning(nn.initializers.ones, ("norm",)),
            (dim,), jnp.float32)
        bias = self.param(
            "bias",
            nn.with_logical_partitioning(nn.initializers.zeros, ("norm",)),
            (dim,), jnp.float32)
        if deterministic or self.rate == 0.0:
            return layer_norm(residual + x, scale, bias, eps=self.epsilon,
                              fused=self.fused)
        if not self.fused_dropout:
            x = nn.Dropout(self.rate)(x, deterministic=False)
            return layer_norm(residual + x, scale, bias, eps=self.epsilon,
                              fused=self.fused)
        # one u32 of randomness per call site per step seeds the whole mask
        seed = jax.random.bits(self.make_rng("dropout"), (),
                               jnp.uint32).astype(jnp.int32)
        return add_dropout_layer_norm(x, residual, scale, bias, seed,
                                      rate=self.rate, eps=self.epsilon,
                                      fused=self.fused)


class BertEmbeddings(nn.Module):
    """word + position (+ token-type iff config.next_sentence) embeddings,
    then LayerNorm and dropout (reference src/modeling.py:338-373).

    `position_ids` (B, S) overrides the default arange positions — packed
    rows (data/packing.py) reset positions per segment so every example
    keeps the position-embedding stream it would see unpacked."""

    config: BertConfig
    dtype: Dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, input_ids: jax.Array,
                 token_type_ids: Optional[jax.Array],
                 deterministic: bool = True,
                 position_ids: Optional[jax.Array] = None) -> jax.Array:
        cfg = self.config
        # tables shard on vocab only; an embed-sharded table turns every
        # lookup into an involuntary XLA reshard against batch-sharded
        # activations (see parallel/mesh.py DEFAULT_LOGICAL_AXIS_RULES)
        word = nn.Embed(
            cfg.vocab_size, cfg.hidden_size,
            embedding_init=nn.with_logical_partitioning(
                _dense_init(cfg), ("vocab", "embed_out")),
            dtype=self.dtype, param_dtype=jnp.float32,
            name="word_embeddings")
        pos = nn.Embed(
            cfg.max_position_embeddings, cfg.hidden_size,
            embedding_init=nn.with_logical_partitioning(
                _dense_init(cfg), (None, "embed_out")),
            dtype=self.dtype, param_dtype=jnp.float32,
            name="position_embeddings")

        seq_len = input_ids.shape[-1]
        if position_ids is None:
            position_ids = jnp.arange(seq_len, dtype=jnp.int32)[None, :]
        x = word(input_ids) + pos(position_ids)

        # Token-type embeddings exist only in NSP mode — the reference skips
        # them entirely for RoBERTa-style runs (src/modeling.py:345-348).
        if cfg.next_sentence:
            tok_type = nn.Embed(
                cfg.type_vocab_size, cfg.hidden_size,
                embedding_init=nn.with_logical_partitioning(
                    _dense_init(cfg), (None, "embed_out")),
                dtype=self.dtype, param_dtype=jnp.float32,
                name="token_type_embeddings")
            if token_type_ids is None:
                token_type_ids = jnp.zeros_like(input_ids)
            x = x + tok_type(token_type_ids)

        x = LayerNorm(fused=cfg.fused_ops, name="layer_norm")(x)
        if (cfg.fused_dropout_ln and not deterministic
                and cfg.hidden_dropout_prob > 0.0):
            # same regenerate-in-backward hash dropout as the attention
            # probs and the residual sites — no saved mask tensor
            from bert_pytorch_tpu.ops.attention import hash_dropout

            seed = jax.random.bits(self.make_rng("dropout"), (),
                                   jnp.uint32).astype(jnp.int32)
            x = hash_dropout(x, seed, cfg.hidden_dropout_prob)
        else:
            x = nn.Dropout(cfg.hidden_dropout_prob)(
                x, deterministic=deterministic)
        return x


class BertSelfAttention(nn.Module):
    """Self-attention with a single fused QKV projection.

    The reference used three separate Q/K/V Linears (src/modeling.py:388-392);
    one (E, 3, H, D) projection keeps the MXU busy with a single large matmul
    and makes tensor-parallel sharding a one-axis annotation.
    """

    config: BertConfig
    dtype: Dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, hidden: jax.Array, attention_bias: jax.Array,
                 segment_ids: Optional[jax.Array] = None,
                 deterministic: bool = True) -> jax.Array:
        cfg = self.config
        n_heads, head_dim = cfg.num_attention_heads, cfg.head_dim

        if cfg.kfac_taps:
            self.sow("kfac_in", "qkv_tap", hidden)
        qkv = nn.DenseGeneral(
            features=(3, n_heads, head_dim), axis=-1,
            kernel_init=nn.with_logical_partitioning(
                _dense_init(cfg), ("embed", None, "heads", "kv")),
            bias_init=nn.with_logical_partitioning(
                nn.initializers.zeros, (None, "heads", "kv")),
            dtype=self.dtype, param_dtype=jnp.float32,
            name="qkv")(hidden)
        if cfg.kfac_taps:
            qkv = self.perturb("qkv_tap", qkv)
        q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]

        # "auto" resolves by sequence length inside dot_product_attention
        # (XLA attention through seq 256, Pallas flash beyond).
        # fused_ops=False is the no-Pallas escape hatch (config.py): long
        # sequences then get attention-only recompute, which has flash-like
        # activation memory without the Pallas kernel.
        impl = cfg.attention_impl
        if impl == "auto" and not cfg.fused_ops:
            impl = "xla_checkpoint" if hidden.shape[1] > 256 else "xla"
        dropout_rng = None
        if not deterministic and cfg.attention_probs_dropout_prob > 0.0:
            dropout_rng = self.make_rng("dropout")
        ctx = dot_product_attention(
            q, k, v, bias=attention_bias,
            segment_ids=segment_ids,
            dropout_rng=dropout_rng,
            dropout_rate=cfg.attention_probs_dropout_prob,
            deterministic=deterministic,
            impl=impl,
            hash_dropout_impl=cfg.fused_dropout_ln)

        if cfg.kfac_taps:
            self.sow("kfac_in", "output_tap", ctx)
        out = nn.DenseGeneral(
            features=cfg.hidden_size, axis=(-2, -1),
            kernel_init=nn.with_logical_partitioning(
                _dense_init(cfg), ("heads", "kv", "embed")),
            bias_init=nn.with_logical_partitioning(
                nn.initializers.zeros, ("embed",)),
            dtype=self.dtype, param_dtype=jnp.float32,
            name="output")(ctx)
        if cfg.kfac_taps:
            out = self.perturb("output_tap", out)
        return out


class BertLayer(nn.Module):
    """attention -> add&LN -> MLP(bias_gelu) -> add&LN
    (reference src/modeling.py:439-493)."""

    config: BertConfig
    dtype: Dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, hidden: jax.Array, attention_bias: jax.Array,
                 segment_ids: Optional[jax.Array] = None,
                 deterministic: bool = True) -> jax.Array:
        cfg = self.config

        # named_scope tags every op in the block with a stable prefix so a
        # profiler trace maps buckets to code (attention vs mlp vs head)
        # instead of fused-op soup — the per-phase attribution that made
        # docs/PERF.md's budget hunting possible ("Demystifying BERT")
        with jax.named_scope("attention"):
            attn_out = BertSelfAttention(cfg, dtype=self.dtype,
                                         name="attention")(
                hidden, attention_bias, segment_ids, deterministic)
            hidden = ResidualDropoutLayerNorm(
                rate=cfg.hidden_dropout_prob, fused=cfg.fused_ops,
                fused_dropout=cfg.fused_dropout_ln,
                name="attention_layer_norm")(attn_out, hidden, deterministic)
            if cfg.debug_taps:
                self.sow("debug_taps", "attention_out", hidden)

        # MLP. Activation applied on the pre-bias output + bias, mirroring the
        # reference's fused LinearActivation bias_gelu (src/modeling.py:141-180)
        # — on TPU, XLA fuses this into the matmul epilogue.
        with jax.named_scope("mlp"):
            act = ACT2FN[cfg.hidden_act]
            if cfg.kfac_taps:
                self.sow("kfac_in", "intermediate_tap", hidden)
            inter = nn.Dense(
                cfg.intermediate_size,
                kernel_init=nn.with_logical_partitioning(
                    _dense_init(cfg), ("embed", "mlp")),
                bias_init=nn.with_logical_partitioning(
                    nn.initializers.zeros, ("mlp",)),
                dtype=self.dtype, param_dtype=jnp.float32,
                name="intermediate")(hidden)
            if cfg.kfac_taps:
                inter = self.perturb("intermediate_tap", inter)
            # Tag the (B, S, F) wide activations so remat_policy="mlp_only"
            # can drop just these (4x hidden width — the bulk of per-layer
            # activation memory) and keep attention saved. No-op without
            # nn.remat.
            inter = checkpoint_name(inter, "mlp_wide")
            inter = act(inter)
            inter = checkpoint_name(inter, "mlp_wide")
            if cfg.kfac_taps:
                self.sow("kfac_in", "mlp_output_tap", inter)
            mlp_out = nn.Dense(
                cfg.hidden_size,
                kernel_init=nn.with_logical_partitioning(
                    _dense_init(cfg), ("mlp", "embed")),
                bias_init=nn.with_logical_partitioning(
                    nn.initializers.zeros, ("embed",)),
                dtype=self.dtype, param_dtype=jnp.float32,
                name="mlp_output")(inter)
            if cfg.kfac_taps:
                mlp_out = self.perturb("mlp_output_tap", mlp_out)
            hidden = ResidualDropoutLayerNorm(
                rate=cfg.hidden_dropout_prob, fused=cfg.fused_ops,
                fused_dropout=cfg.fused_dropout_ln,
                name="output_layer_norm")(mlp_out, hidden, deterministic)
            if cfg.debug_taps:
                self.sow("debug_taps", "mlp_out", hidden)
        return hidden


class _EncoderBody(nn.Module):
    """Scan body: one BertLayer returning flax-scan's (carry, ys) shape."""

    config: BertConfig
    dtype: Dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, hidden: jax.Array, attention_bias: jax.Array,
                 segment_ids: Optional[jax.Array] = None,
                 deterministic: bool = True):
        hidden = BertLayer(self.config, dtype=self.dtype, name="layer")(
            hidden, attention_bias, segment_ids, deterministic)
        return hidden, None


_REMAT_POLICIES = {
    "nothing": jax.checkpoint_policies.nothing_saveable,
    "dots": jax.checkpoint_policies.dots_saveable,
    # recompute ONLY the (B, S, F) wide-MLP activations (tagged
    # checkpoint_name "mlp_wide" in BertLayer); attention stays
    # saved — cheapest-recompute way to shed the largest buffers
    "mlp_only": jax.checkpoint_policies
    .save_anything_except_these_names("mlp_wide"),
}


class BertEncoder(nn.Module):
    """N stacked BertLayers via nn.scan (layer-stacked params), or — with
    config.stacked_params=False — a fully-unrolled Python loop over L
    separate BertLayer modules (per-layer params).

    Stacked: compile time stays constant in depth and XLA sees one loop
    body — the TPU-correct replacement for the reference's Python loop over
    24 modules (src/modeling.py:495-536), but backward wgrads accumulate by
    dynamic_update_slice into the (L, ...) stacked grad buffers even at full
    scan_unroll. Unstacked: params live under encoder/layer_{i} with no
    leading L axis, wgrads write straight into per-layer leaves (no DUS
    traffic — docs/PERF.md seq512 budget), compile time O(L).
    checkpoint_activations=True wraps the (scanned or per-layer) body in
    nn.remat (reference: torch checkpointing in sqrt(L) chunks).
    """

    config: BertConfig
    dtype: Dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, hidden: jax.Array, attention_bias: jax.Array,
                 segment_ids: Optional[jax.Array] = None,
                 deterministic: bool = True) -> jax.Array:
        cfg = self.config

        if not cfg.stacked_params:
            layer_cls = BertLayer
            if cfg.checkpoint_activations:
                layer_cls = nn.remat(
                    BertLayer,
                    static_argnums=(4,),  # (self, hidden, bias, seg, det.)
                    policy=_REMAT_POLICIES[cfg.remat_policy],
                )
            for i in range(cfg.num_hidden_layers):
                hidden = layer_cls(cfg, dtype=self.dtype,
                                   name=f"layer_{i}")(
                    hidden, attention_bias, segment_ids, deterministic)
            return hidden

        body_cls = _EncoderBody
        if cfg.checkpoint_activations:
            body_cls = nn.remat(
                _EncoderBody,
                static_argnums=(4,),  # (self, hidden, bias, seg, det.)
                policy=_REMAT_POLICIES[cfg.remat_policy],
            )

        ScannedLayers = nn.scan(
            body_cls,
            variable_axes={"params": 0, "perturbations": 0, "kfac_in": 0,
                           "debug_taps": 0},
            split_rngs={"params": True, "dropout": True},
            in_axes=(nn.broadcast, nn.broadcast, nn.broadcast),
            length=cfg.num_hidden_layers,
            metadata_params={nn.PARTITION_NAME: "layers"},
            unroll=min(cfg.scan_unroll, cfg.num_hidden_layers),
        )
        hidden, _ = ScannedLayers(cfg, dtype=self.dtype, name="layers")(
            hidden, attention_bias, segment_ids, deterministic)
        return hidden


class BertPooler(nn.Module):
    """tanh(dense([CLS])) (reference src/modeling.py:538-552).

    `positions` (B, G) int32: gather each of G tokens per row instead of
    row position 0 — packed rows hold several examples, each with its own
    [CLS] (data/packing.py nsp_positions), so the pooled output becomes
    (B, G, E). Empty slots gather position 0; their NSP label is -1 and the
    loss ignores them."""

    config: BertConfig
    dtype: Dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, hidden: jax.Array,
                 positions: Optional[jax.Array] = None) -> jax.Array:
        if positions is None:
            cls = hidden[:, 0]
        else:
            cls = jnp.take_along_axis(hidden, positions[..., None], axis=1)
        if self.config.kfac_taps:
            self.sow("kfac_in", "dense_tap", cls)
        out = nn.Dense(
            self.config.hidden_size,
            # 'embed_head': replicated contracting dim, like _head_dense —
            # an fsdp-sharded (E, E) pooler kernel forces the same
            # involuntary batch->embed reshard of the (B, E) cls slice
            kernel_init=nn.with_logical_partitioning(
                _dense_init(self.config), ("embed_head", "embed_out")),
            dtype=self.dtype, param_dtype=jnp.float32,
            name="dense")(cls)
        if self.config.kfac_taps:
            # tapped pre-activation (K-FAC's G is grad w.r.t. Wa+b, not tanh)
            out = self.perturb("dense_tap", out)
        return jnp.tanh(out)


class BertModel(nn.Module):
    """Encoder trunk: embeddings -> encoder -> (optional) pooler.

    Returns (sequence_output, pooled_output); pooled_output is None unless
    config.next_sentence (reference src/modeling.py:837-864: pooler only runs
    in NSP mode).

    Packed sequences (--packing): `position_ids` resets positions per
    segment, `segment_ids` (1..n per row, 0 = pad) restricts attention to
    block-diagonal q_seg == k_seg blocks, and `nsp_positions` (B, G) makes
    the pooler gather each segment's first token instead of row position 0
    (pooled becomes (B, G, E)).
    """

    config: BertConfig
    dtype: Dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, input_ids: jax.Array,
                 token_type_ids: Optional[jax.Array] = None,
                 attention_mask: Optional[jax.Array] = None,
                 deterministic: bool = True,
                 position_ids: Optional[jax.Array] = None,
                 segment_ids: Optional[jax.Array] = None,
                 nsp_positions: Optional[jax.Array] = None,
                 ) -> Tuple[jax.Array, Optional[jax.Array]]:
        cfg = self.config
        if attention_mask is None:
            attention_mask = (segment_ids > 0 if segment_ids is not None
                              else jnp.ones_like(input_ids))
        bias = make_attention_bias(attention_mask, dtype=jnp.float32)

        with jax.named_scope("embeddings"):
            x = BertEmbeddings(cfg, dtype=self.dtype, name="embeddings")(
                input_ids, token_type_ids, deterministic, position_ids)
        if cfg.debug_taps:
            # "_out" suffix: a sow name must not collide with a child
            # module name ("embeddings" is the BertEmbeddings submodule)
            self.sow("debug_taps", "embeddings_out", x)
        x = nn.with_logical_constraint(x, ("data", "seq", "embed_act"))
        x = BertEncoder(cfg, dtype=self.dtype, name="encoder")(
            x, bias, segment_ids, deterministic)
        x = nn.with_logical_constraint(x, ("data", "seq", "embed_act"))

        pooled = None
        if cfg.next_sentence:
            with jax.named_scope("pooler"):
                pooled = BertPooler(cfg, dtype=self.dtype, name="pooler")(
                    x, nsp_positions)
            if cfg.debug_taps:
                self.sow("debug_taps", "pooled", pooled)
        return x, pooled


class BertMLMHead(nn.Module):
    """transform (dense+act+LN) then decode against the tied word-embedding
    matrix plus a free bias (reference src/modeling.py:555-600)."""

    config: BertConfig
    dtype: Dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, hidden: jax.Array,
                 word_embedding: jax.Array) -> jax.Array:
        cfg = self.config
        x = nn.Dense(
            cfg.hidden_size,
            # 'embed_head' (replicated), not 'embed' (fsdp): an fsdp-sharded
            # contracting dim on this (E, E) kernel makes GSPMD reshard the
            # batch-sharded (B, S/P, E) hidden embed-major — the involuntary
            # full rematerialization the 2x2-mesh gate catches; the ZeRO
            # memory saved (E*E/N) is noise next to the (V, E) tables that
            # stay properly sharded
            kernel_init=nn.with_logical_partitioning(
                _dense_init(cfg), ("embed_head", "embed_out")),
            dtype=self.dtype, param_dtype=jnp.float32,
            name="transform")(hidden)
        act = cfg.hidden_act if cfg.hidden_act != "bias_gelu" else "gelu"
        x = ACT2FN[act](x)
        x = LayerNorm(fused=cfg.fused_ops, name="layer_norm")(x)

        # Tied decoder: logits = x @ E^T + b (reference ties decoder.weight to
        # word embeddings at src/modeling.py:563-574).
        logits = jnp.einsum("bse,ve->bsv", x,
                            word_embedding.astype(self.dtype),
                            preferred_element_type=jnp.float32)
        bias = self.param(
            "bias",
            nn.with_logical_partitioning(nn.initializers.zeros, ("vocab",)),
            (cfg.vocab_size,), jnp.float32)
        return logits + bias


def _head_dense(cfg: BertConfig, features: int, name: str, dtype: Dtype):
    # 'embed_head' (replicated), NOT 'embed' (fsdp): these are few-KB
    # classifier kernels whose fsdp-sharded contracting dim makes GSPMD
    # reshard the batch-sharded pooled activations embed-major — an
    # involuntary full rematerialization on (data x fsdp) meshes for a
    # memory win of kilobytes (same reasoning as the replicated norm/pos
    # tables in parallel/mesh.py; caught by the 2x2-mesh reshard gate)
    return nn.Dense(
        features,
        kernel_init=nn.with_logical_partitioning(
            _dense_init(cfg), ("embed_head", None)),
        dtype=dtype, param_dtype=jnp.float32, name=name)


class BertForPreTraining(nn.Module):
    """MLM + NSP heads (reference src/modeling.py:867-929).

    masked_positions=None (dense): prediction_logits are fp32 (B, S, V) — the
    reference's shape. masked_positions=(B, P) int32: hidden states are
    gathered at those positions BEFORE the MLM transform/decoder, so logits
    are (B, P, V). Phase 1 scores at most max_predictions_per_seq=20 of 128
    positions, so the gathered head does ~6x less vocab-matmul work and never
    materializes the (B, S, V) fp32 logits — the dominant memory/FLOP cost on
    TPU. Returns (prediction_logits, seq_relationship_logits (B,2) | None).

    Packed batches (position_ids/segment_ids/nsp_positions, see BertModel):
    the NSP head scores every packed segment — seq_relationship_logits
    become (B, G, 2), paired with the loader's (B, G) per-segment labels.
    """

    config: BertConfig
    dtype: Dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, input_ids, token_type_ids=None, attention_mask=None,
                 deterministic: bool = True, masked_positions=None,
                 position_ids=None, segment_ids=None, nsp_positions=None):
        cfg = self.config
        bert = BertModel(cfg, dtype=self.dtype, name="bert")
        seq_out, pooled = bert(input_ids, token_type_ids, attention_mask,
                               deterministic, position_ids=position_ids,
                               segment_ids=segment_ids,
                               nsp_positions=nsp_positions)
        word_emb = bert.variables["params"]["embeddings"]["word_embeddings"][
            "embedding"]
        word_emb = _unbox(word_emb)
        with jax.named_scope("mlm_head"):
            if masked_positions is not None:
                seq_out = jnp.take_along_axis(
                    seq_out, masked_positions[..., None], axis=1)
                # the gather drops the encoder output's layout annotation;
                # without re-constraining, SPMD propagates a vocab-major
                # layout back through the tied decoder and the embedding
                # grad scatter-add pays a replicate-then-repartition
                # (involuntary reshard)
                seq_out = nn.with_logical_constraint(
                    seq_out, ("data", None, "embed_act"))
            mlm_logits = BertMLMHead(cfg, dtype=self.dtype,
                                     name="cls_predictions")(
                seq_out, word_emb)
        if cfg.debug_taps:
            self.sow("debug_taps", "mlm_logits", mlm_logits)
        nsp_logits = None
        if cfg.next_sentence:
            with jax.named_scope("nsp_head"):
                if cfg.kfac_taps:
                    self.sow("kfac_in", "cls_seq_relationship_tap", pooled)
                nsp_logits = _head_dense(cfg, 2, "cls_seq_relationship",
                                         self.dtype)(pooled)
                if cfg.kfac_taps:
                    nsp_logits = self.perturb("cls_seq_relationship_tap",
                                              nsp_logits)
                nsp_logits = nsp_logits.astype(jnp.float32)
            if cfg.debug_taps:
                self.sow("debug_taps", "nsp_logits", nsp_logits)
        return mlm_logits.astype(jnp.float32), nsp_logits


class BertForMaskedLM(nn.Module):
    """MLM head only (reference src/modeling.py:931-990)."""

    config: BertConfig
    dtype: Dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, input_ids, token_type_ids=None, attention_mask=None,
                 deterministic: bool = True, masked_positions=None,
                 position_ids=None, segment_ids=None):
        cfg = self.config.replace(next_sentence=False)
        bert = BertModel(cfg, dtype=self.dtype, name="bert")
        seq_out, _ = bert(input_ids, token_type_ids, attention_mask,
                          deterministic, position_ids=position_ids,
                          segment_ids=segment_ids)
        word_emb = _unbox(
            bert.variables["params"]["embeddings"]["word_embeddings"][
                "embedding"])
        if masked_positions is not None:
            seq_out = jnp.take_along_axis(
                seq_out, masked_positions[..., None], axis=1)
            seq_out = nn.with_logical_constraint(
                seq_out, ("data", None, "embed_act"))
        logits = BertMLMHead(cfg, dtype=self.dtype, name="cls_predictions")(
            seq_out, word_emb)
        return logits.astype(jnp.float32)


class BertForNextSentencePrediction(nn.Module):
    """NSP head only (reference src/modeling.py:992-1051)."""

    config: BertConfig
    dtype: Dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, input_ids, token_type_ids=None, attention_mask=None,
                 deterministic: bool = True):
        cfg = self.config.replace(next_sentence=True)
        _, pooled = BertModel(cfg, dtype=self.dtype, name="bert")(
            input_ids, token_type_ids, attention_mask, deterministic)
        return _head_dense(cfg, 2, "cls_seq_relationship", self.dtype)(
            pooled).astype(jnp.float32)


def positions_from_segment_ids(segment_ids: jax.Array,
                               max_segments: int) -> jax.Array:
    """(B, S) packed segment ids (1..G, 0 = pad) -> (B, G) row position of
    each segment's FIRST token — the per-segment [CLS] every pooled head
    gathers. Computed in-graph so a serving batch needs no extra host
    field beyond the packing contract (serving/engine.BATCH_FIELDS); an
    empty segment slot resolves to position 0, whose gathered output is
    ignored because its label/placement is absent."""
    hits = segment_onehot(segment_ids, max_segments)          # (B, G, S)
    return jnp.argmax(hits, axis=-1).astype(jnp.int32)


class BertForSequenceClassification(nn.Module):
    """Pooled -> dropout -> linear(num_labels)
    (reference src/modeling.py:1053-1110).

    Packed rows (`position_ids`/`segment_ids`, data/packing.py contract):
    each row holds up to `max_segments` independent (pair) examples; the
    pooler gathers every segment's first token ([CLS]) instead of row
    position 0, so logits become (B, G, num_labels) — per-segment labels
    (-1 = empty slot) pair with them in the packed finetune loss. The
    plain path (segment_ids=None) is byte-identical to the pre-packing
    module: (B, num_labels) from the row-0 pool."""

    config: BertConfig
    num_labels: int = 2
    max_segments: int = 8
    dtype: Dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, input_ids, token_type_ids=None, attention_mask=None,
                 deterministic: bool = True, position_ids=None,
                 segment_ids=None):
        cfg = self.config.replace(next_sentence=True)  # pooler required
        pooled_positions = None
        if segment_ids is not None:
            pooled_positions = positions_from_segment_ids(
                segment_ids, self.max_segments)
        _, pooled = BertModel(cfg, dtype=self.dtype, name="bert")(
            input_ids, token_type_ids, attention_mask, deterministic,
            position_ids=position_ids, segment_ids=segment_ids,
            nsp_positions=pooled_positions)
        pooled = nn.Dropout(cfg.hidden_dropout_prob)(
            pooled, deterministic=deterministic)
        return _head_dense(cfg, self.num_labels, "classifier", self.dtype)(
            pooled).astype(jnp.float32)


class BertForMultipleChoice(nn.Module):
    """(B, C, S) inputs flattened to (B*C, S), scored, reshaped to (B, C)
    (reference src/modeling.py:1112-1179).

    Packed rows: 2-D `input_ids` with `segment_ids` score every packed
    segment independently — (B, G) scalar scores, one per segment. The
    finetune packer places each example's C choices as C CONSECUTIVE
    segments of one row, so the loss regroups (B, G) -> (B, G/C, C) and
    softmaxes within each group; serving submits one segment per choice
    and softmaxes host-side. Same head params either way."""

    config: BertConfig
    num_choices: int = 2
    max_segments: int = 8
    dtype: Dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, input_ids, token_type_ids=None, attention_mask=None,
                 deterministic: bool = True, position_ids=None,
                 segment_ids=None):
        cfg = self.config.replace(next_sentence=True)
        if input_ids.ndim == 2:  # packed / per-segment scoring path
            pooled_positions = None
            if segment_ids is not None:
                pooled_positions = positions_from_segment_ids(
                    segment_ids, self.max_segments)
            _, pooled = BertModel(cfg, dtype=self.dtype, name="bert")(
                input_ids, token_type_ids, attention_mask, deterministic,
                position_ids=position_ids, segment_ids=segment_ids,
                nsp_positions=pooled_positions)
            pooled = nn.Dropout(cfg.hidden_dropout_prob)(
                pooled, deterministic=deterministic)
            scores = _head_dense(cfg, 1, "classifier", self.dtype)(pooled)
            return scores[..., 0].astype(jnp.float32)  # (B,) or (B, G)
        B, C, S = input_ids.shape
        flat = lambda t: None if t is None else t.reshape(B * C, S)
        _, pooled = BertModel(cfg, dtype=self.dtype, name="bert")(
            flat(input_ids), flat(token_type_ids), flat(attention_mask),
            deterministic)
        pooled = nn.Dropout(cfg.hidden_dropout_prob)(
            pooled, deterministic=deterministic)
        scores = _head_dense(cfg, 1, "classifier", self.dtype)(pooled)
        return scores.reshape(B, C).astype(jnp.float32)


class BertForSentenceEmbedding(nn.Module):
    """Mean-pooled sentence embedding + a linear probe head.

    No reference equivalent — this head opens the batch-embed/retrieval
    serving workload (ROADMAP item 3): `embeddings` are the L2-normalized
    fp32 mean of the encoder outputs over each example's REAL tokens
    (mask-weighted einsum, so the contraction is structurally identical
    packed and unpacked), `logits` are a linear probe over the same mean
    — the supervised objective that finetunes the encoder toward
    separable embeddings (classification-style CE on proxy labels).

    Plain path: attention_mask defines one segment per row ->
    (B, E) embeddings, (B, num_labels) logits. Packed path (segment_ids):
    one embedding per segment -> (B, G, E) / (B, G, num_labels)."""

    config: BertConfig
    num_labels: int = 2
    max_segments: int = 8
    normalize: bool = True
    dtype: Dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, input_ids, token_type_ids=None, attention_mask=None,
                 deterministic: bool = True, position_ids=None,
                 segment_ids=None):
        cfg = self.config.replace(next_sentence=False)
        if attention_mask is None:
            attention_mask = (segment_ids > 0 if segment_ids is not None
                              else jnp.ones_like(input_ids))
        seq_out, _ = BertModel(cfg, dtype=self.dtype, name="bert")(
            input_ids, token_type_ids, attention_mask, deterministic,
            position_ids=position_ids, segment_ids=segment_ids)
        packed = segment_ids is not None
        if packed:
            onehot = segment_onehot(segment_ids, self.max_segments)
        else:
            onehot = (attention_mask > 0)[:, None, :]        # (B, 1, S)
        onehot = onehot.astype(jnp.float32)
        # fp32 mask-weighted mean: pad/foreign slots contribute exactly 0
        # to the contraction, which is what makes the packed and unpacked
        # means the same bits (tests/test_finetune_packing.py pins it)
        sums = jnp.einsum("bgs,bse->bge", onehot,
                          seq_out.astype(jnp.float32))
        counts = jnp.maximum(onehot.sum(-1)[..., None], 1.0)
        mean = sums / counts                                  # (B, G, E)
        emb = mean
        if self.normalize:
            emb = emb / jnp.sqrt(
                jnp.maximum(jnp.sum(emb * emb, axis=-1, keepdims=True),
                            1e-12))
        logits = _head_dense(cfg, self.num_labels, "classifier",
                             self.dtype)(
            mean.astype(self.dtype)).astype(jnp.float32)
        if not packed:
            emb, logits = emb[:, 0], logits[:, 0]
        return emb, logits


class BertForTokenClassification(nn.Module):
    """Per-token linear head (reference src/modeling.py:1181-1253); loss uses
    ignore_index -100 on [SPC]/subword positions (reference src/ner_dataset.py).

    `position_ids`/`segment_ids` (packed rows, data/packing.py contract):
    several examples share one row with per-segment positions and
    block-diagonal attention — the per-token head is segment-local by
    construction, so a packed row's logits demux by slicing (the inference
    server's multi-tenant batching path, serving/batcher.py)."""

    config: BertConfig
    num_labels: int = 2
    dtype: Dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, input_ids, token_type_ids=None, attention_mask=None,
                 deterministic: bool = True, position_ids=None,
                 segment_ids=None):
        cfg = self.config
        seq_out, _ = BertModel(cfg, dtype=self.dtype, name="bert")(
            input_ids, token_type_ids, attention_mask, deterministic,
            position_ids=position_ids, segment_ids=segment_ids)
        seq_out = nn.Dropout(cfg.hidden_dropout_prob)(
            seq_out, deterministic=deterministic)
        return _head_dense(cfg, self.num_labels, "classifier", self.dtype)(
            seq_out).astype(jnp.float32)


class BertForQuestionAnswering(nn.Module):
    """Per-token (start, end) logits (reference src/modeling.py:1255-1308).

    `position_ids`/`segment_ids` as in BertForTokenClassification: packed
    rows hold several (question, context) requests, each attending only
    within its own segment, so per-request span logits are row slices."""

    config: BertConfig
    dtype: Dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, input_ids, token_type_ids=None, attention_mask=None,
                 deterministic: bool = True, position_ids=None,
                 segment_ids=None):
        cfg = self.config
        seq_out, _ = BertModel(cfg, dtype=self.dtype, name="bert")(
            input_ids, token_type_ids, attention_mask, deterministic,
            position_ids=position_ids, segment_ids=segment_ids)
        logits = _head_dense(cfg, 2, "qa_outputs", self.dtype)(
            seq_out).astype(jnp.float32)
        start_logits, end_logits = logits[..., 0], logits[..., 1]
        return start_logits, end_logits


def _unbox(x):
    """Strip flax Partitioned metadata boxes when reading raw variables."""
    return x.unbox() if hasattr(x, "unbox") else x
