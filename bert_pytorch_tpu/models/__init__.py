from bert_pytorch_tpu.models.bert import (  # noqa: F401
    BertEmbeddings,
    BertEncoder,
    BertForMaskedLM,
    BertForMultipleChoice,
    BertForNextSentencePrediction,
    BertForPreTraining,
    BertForQuestionAnswering,
    BertForSentenceEmbedding,
    BertForSequenceClassification,
    BertForTokenClassification,
    BertModel,
    BertPooler,
    positions_from_segment_ids,
)
from bert_pytorch_tpu.models import losses  # noqa: F401
from bert_pytorch_tpu.models.pretrained import (  # noqa: F401
    convert_tf_to_flax,
    convert_torch_to_flax,
    convert_tree_layout,
    from_pretrained,
    load_tf_weights,
    load_torch_checkpoint,
    stack_layer_tree,
    tree_layout,
    unstack_layer_tree,
)
