from bert_pytorch_tpu.models.bert import (  # noqa: F401
    BertEmbeddings,
    BertEncoder,
    BertForMaskedLM,
    BertForMultipleChoice,
    BertForNextSentencePrediction,
    BertForPreTraining,
    BertForQuestionAnswering,
    BertForSequenceClassification,
    BertForTokenClassification,
    BertModel,
    BertPooler,
)
from bert_pytorch_tpu.models import losses  # noqa: F401
