"""Pretrained-weight import: Google TF BERT checkpoints AND reference torch
checkpoints -> flax param trees.

Capability parity with the reference's `load_tf_weights_in_bert`
(src/modeling.py:58-116) and `BertPreTrainedModel.from_pretrained` archive
loading (src/modeling.py:659-742), plus the migration path a reference user
actually needs: `convert_torch_to_flax` ingests the torch state_dicts the
reference saves (`ckpt_*.pt`, run_pretraining.py:499-511) so TPU finetuning
can start from a GPU-pretrained artifact. Re-designed for this framework's
layout:

- the encoder here is an `nn.scan` stack by default, so the 12/24 per-layer
  TF trees are np.stack'ed onto the leading scan axis rather than loaded
  module-by-module; with config.stacked_params=False they load as per-layer
  `layer_{i}` subtrees instead, and stack_layer_tree/unstack_layer_tree
  convert existing trees (params, optimizer moments, K-FAC factors, abstract
  restore templates) losslessly between the two layouts;
- q/k/v are one fused (E, 3, H, Dh) projection (models/bert.py), so the three
  TF kernels are reshaped head-major and stacked on the fusion axis;
- flax Dense kernels are (in, out) like TF's — no per-matrix transposes (the
  reference transposed because torch Linear stores (out, in));
- vocab padding for the MXU: embedding rows are zero-padded to the target
  vocab and the padded MLM-bias entries get a large negative value so a
  padded token can never win argmax.

All conversion is pure numpy (testable without TF); only reading an actual
TF checkpoint file imports tensorflow, via the same public
`tf.train.load_checkpoint` API the reference used.
"""

from __future__ import annotations

import json
import os
import re
import shutil
import zipfile
from typing import Any, Dict, Optional, Tuple

import numpy as np

from bert_pytorch_tpu.config import BertConfig
from bert_pytorch_tpu.file_utils import DEFAULT_CACHE, cached_path

# Google research BERT release zips (same artifacts pipeline/download.py
# fetches; reference kept an S3 mirror map in src/modeling.py:620-657).
PRETRAINED_ARCHIVE_MAP = {
    "bert-base-uncased":
        "https://storage.googleapis.com/bert_models/2018_10_18/"
        "uncased_L-12_H-768_A-12.zip",
    "bert-large-uncased":
        "https://storage.googleapis.com/bert_models/2018_10_18/"
        "uncased_L-24_H-1024_A-16.zip",
    "bert-base-cased":
        "https://storage.googleapis.com/bert_models/2018_10_18/"
        "cased_L-12_H-768_A-12.zip",
    "bert-large-cased":
        "https://storage.googleapis.com/bert_models/2018_10_18/"
        "cased_L-24_H-1024_A-16.zip",
}

PADDED_VOCAB_BIAS = -10000.0  # MLM bias for padded vocab rows

# TF optimizer slots / bookkeeping that are never model weights.
_SKIP_SUFFIXES = ("adam_m", "adam_v", "global_step", "AdamWeightDecayOptimizer",
                  "AdamWeightDecayOptimizer_1")

# ---------------------------------------------------------------------------
# stacked <-> unstacked encoder parameter layout
# ---------------------------------------------------------------------------
#
# Two on-device layouts exist for the encoder stack (config.stacked_params):
#   stacked    .../encoder/layers/layer/<site>  — leaves carry a leading
#              (L, ...) scan axis (nn.scan module named 'layers', body
#              'layer')
#   unstacked  .../encoder/layer_{i}/<site>     — L sibling subtrees, no
#              leading axis (fully-unrolled per-layer modules)
# The converters below are pure tree surgery, so the SAME functions serve
# model params, LAMB/Adam moments (mu/nu mirror the param tree), K-FAC
# factor/inverse trees (keyed like the tap tree), and abstract
# jax.ShapeDtypeStruct templates used for orbax sharded restore. Round
# trips are bit-exact: stacking is np/jnp.stack of the exact per-layer
# slices.

_LAYER_KEY_RE = re.compile(r"^layer_(\d+)$")


def _is_scan_stack(v: Any) -> bool:
    return isinstance(v, dict) and set(v.keys()) == {"layer"}


SCAN_AXIS_NAME = "layers"  # nn.PARTITION_NAME the encoder scan prepends


def _box_types() -> tuple:
    """flax metadata boxes (nn.Partitioned / LogicallyPartitioned) whose
    logical-axis names must gain/lose the leading scan axis on conversion."""
    try:
        from flax import linen as fnn
        from flax.linen import spmd as fspmd

        return (fnn.Partitioned, fspmd.LogicallyPartitioned)
    except ImportError:  # conversion stays usable in a numpy-only context
        return ()


def _is_boxed(x: Any) -> bool:
    return isinstance(x, _box_types())


def _slice_sharding(sharding: Any):
    """Per-layer NamedSharding from a stacked leaf's: drop the leading-axis
    entry of the PartitionSpec (the 'layers' logical axis maps to None in
    the rules, so the leading entry is always un-sharded and droppable).
    None when the sharding is absent or not spec-structured — callers then
    omit sharding rather than guess."""
    try:
        from jax.sharding import NamedSharding, PartitionSpec

        if isinstance(sharding, NamedSharding):
            spec = tuple(sharding.spec)
            return NamedSharding(sharding.mesh, PartitionSpec(*spec[1:]))
    except ImportError:
        pass
    return None


def _stack_sharding(sharding: Any):
    """Inverse of _slice_sharding: prepend an un-sharded leading axis."""
    try:
        from jax.sharding import NamedSharding, PartitionSpec

        if isinstance(sharding, NamedSharding):
            spec = tuple(sharding.spec)
            return NamedSharding(sharding.mesh, PartitionSpec(None, *spec))
    except ImportError:
        pass
    return None


def _take_layer(i: int, leaf: Any) -> Any:
    import jax

    if _is_boxed(leaf):
        names = tuple(leaf.names)
        if names and names[0] == SCAN_AXIS_NAME:
            names = names[1:]
        return leaf.replace(value=_take_layer(i, leaf.value), names=names)
    if isinstance(leaf, jax.ShapeDtypeStruct):
        # keep the sharding where representable so sharded orbax restore
        # through a converted template still places arrays on-device
        sharding = _slice_sharding(getattr(leaf, "sharding", None))
        if sharding is not None:
            return jax.ShapeDtypeStruct(leaf.shape[1:], leaf.dtype,
                                        sharding=sharding)
        return jax.ShapeDtypeStruct(leaf.shape[1:], leaf.dtype)
    return leaf[i]


def _stack_leaves(*leaves: Any) -> Any:
    import jax
    import jax.numpy as jnp

    if _is_boxed(leaves[0]):
        inner = _stack_leaves(*(x.value for x in leaves))
        return leaves[0].replace(
            value=inner, names=(SCAN_AXIS_NAME,) + tuple(leaves[0].names))
    if isinstance(leaves[0], jax.ShapeDtypeStruct):
        sharding = _stack_sharding(getattr(leaves[0], "sharding", None))
        if sharding is not None:
            return jax.ShapeDtypeStruct((len(leaves),) + leaves[0].shape,
                                        leaves[0].dtype, sharding=sharding)
        return jax.ShapeDtypeStruct((len(leaves),) + leaves[0].shape,
                                    leaves[0].dtype)
    if all(isinstance(x, np.ndarray) for x in leaves):
        return np.stack(leaves, axis=0)
    return jnp.stack(leaves, axis=0)


def unstack_layer_tree(tree: Any) -> Any:
    """Replace every {"layers": {"layer": <stacked>}} node with layer_{i}
    siblings holding that layer's slice of each leaf. Non-dict nodes pass
    through; ShapeDtypeStruct leaves get shape surgery instead of slicing,
    and flax partitioning boxes lose the leading 'layers' axis name."""
    import jax

    if not isinstance(tree, dict):
        return tree
    out = {}
    for k, v in tree.items():
        if k == "layers" and _is_scan_stack(v):
            leaves = jax.tree.leaves(v["layer"], is_leaf=_is_boxed)
            if leaves and _is_boxed(leaves[0]):
                n_layers = leaves[0].value.shape[0]
            else:
                n_layers = leaves[0].shape[0] if leaves else 0
            for i in range(n_layers):
                out[f"layer_{i}"] = jax.tree.map(
                    lambda leaf, i=i: _take_layer(i, leaf), v["layer"],
                    is_leaf=_is_boxed)
        else:
            out[k] = unstack_layer_tree(v)
    return out


def stack_layer_tree(tree: Any) -> Any:
    """Inverse of unstack_layer_tree: gather layer_{0..L-1} siblings back
    into one {"layers": {"layer": <stacked>}} node (leaves stacked on a new
    leading axis; flax boxes regain the leading 'layers' axis name)."""
    import jax

    if not isinstance(tree, dict):
        return tree
    layer_keys = sorted((k for k in tree if _LAYER_KEY_RE.match(k)),
                        key=lambda k: int(k.rsplit("_", 1)[1]))
    out = {k: stack_layer_tree(v) for k, v in tree.items()
           if k not in layer_keys}
    if layer_keys:
        indices = [int(k.rsplit("_", 1)[1]) for k in layer_keys]
        if indices != list(range(len(indices))):
            raise ValueError(
                f"non-contiguous layer indices {indices}; cannot stack")
        out["layers"] = {"layer": jax.tree.map(
            _stack_leaves, *(tree[k] for k in layer_keys),
            is_leaf=_is_boxed)}
    return out


def tree_layout(tree: Any) -> Optional[str]:
    """'stacked' | 'unstacked' | None (no encoder layer subtree found)."""
    if not isinstance(tree, dict):
        return None
    for k, v in tree.items():
        if k == "layers" and _is_scan_stack(v):
            return "stacked"
        if _LAYER_KEY_RE.match(k):
            return "unstacked"
        sub = tree_layout(v)
        if sub is not None:
            return sub
    return None


def convert_tree_layout(obj: Any, stacked: bool) -> Any:
    """Convert any state-ish container to the requested encoder layout.

    Handles plain param dicts, optax NamedTuple chains (LambState etc.),
    TrainState, and KFACState (duck-typed — no training imports, keeping
    models free of circular deps). Subtrees already in the requested layout
    pass through unchanged, so calling this unconditionally is safe."""
    conv = stack_layer_tree if stacked else unstack_layer_tree

    def rec(node):
        if isinstance(node, dict):
            return conv(node)
        if hasattr(node, "factors") and hasattr(node, "inverses"):
            return node.replace(factors=rec(node.factors),
                                inverses=rec(node.inverses))
        if hasattr(node, "params") and hasattr(node, "opt_state"):
            precond = getattr(node, "precond_state", None)
            kw = ({"precond_state": rec(precond)}
                  if precond is not None else {})
            return node.replace(params=rec(node.params),
                                opt_state=rec(node.opt_state), **kw)
        if isinstance(node, tuple) and hasattr(node, "_fields"):
            return type(node)(*(rec(x) for x in node))
        if isinstance(node, (tuple, list)):
            return type(node)(rec(x) for x in node)
        return node

    return rec(obj)


def load_tf_weights(ckpt_path: str) -> Dict[str, np.ndarray]:
    """Read every variable of a TF checkpoint into numpy, skipping optimizer
    slots (reference src/modeling.py:69-86 did the same walk)."""
    import tensorflow as tf  # baked into the image; imported lazily

    reader = tf.train.load_checkpoint(ckpt_path)
    out = {}
    for name in reader.get_variable_to_shape_map():
        if any(name.split("/")[-1].startswith(s) or s in name
               for s in _SKIP_SUFFIXES):
            continue
        out[name] = np.asarray(reader.get_tensor(name))
    return out


def _pad_vocab(arr: np.ndarray, target: int, fill: float) -> np.ndarray:
    if arr.shape[0] == target:
        return arr
    if arr.shape[0] > target:
        raise ValueError(
            f"checkpoint vocab {arr.shape[0]} exceeds target {target}; "
            "pad the model config's vocab_size instead of shrinking weights")
    pad_shape = (target - arr.shape[0],) + arr.shape[1:]
    return np.concatenate([arr, np.full(pad_shape, fill, arr.dtype)], axis=0)


def convert_tf_to_flax(tf_vars: Dict[str, np.ndarray],
                       config: BertConfig) -> Dict:
    """Map Google-BERT TF variable names/layout onto this framework's
    BertForPreTraining param tree (pure numpy).

    config.vocab_size may exceed the checkpoint's (MXU padding) — embedding
    rows / MLM bias are padded. num_hidden_layers and the hidden geometry
    must match the checkpoint exactly.
    """
    E = config.hidden_size
    H = config.num_attention_heads
    Dh = config.head_dim
    L = config.num_hidden_layers
    V = config.vocab_size

    def get(name: str) -> np.ndarray:
        if name not in tf_vars:
            raise KeyError(
                f"TF checkpoint is missing variable '{name}' — not a "
                "Google-BERT checkpoint for this architecture?")
        return np.asarray(tf_vars[name], np.float32)

    def ln(prefix: str) -> Dict:
        return {"scale": get(f"{prefix}/gamma"), "bias": get(f"{prefix}/beta")}

    def dense(prefix: str) -> Dict:
        return {"kernel": get(f"{prefix}/kernel"),
                "bias": get(f"{prefix}/bias")}

    embeddings = {
        "word_embeddings": {"embedding": _pad_vocab(
            get("bert/embeddings/word_embeddings"), V, 0.0)},
        "position_embeddings": {"embedding": get(
            "bert/embeddings/position_embeddings")[
                :config.max_position_embeddings]},
        "layer_norm": ln("bert/embeddings/LayerNorm"),
    }
    if config.next_sentence:
        embeddings["token_type_embeddings"] = {"embedding": get(
            "bert/embeddings/token_type_embeddings")}

    # Per-layer trees stacked onto the scan axis. Fused QKV: TF stores three
    # (E, E) kernels; each reshapes head-major to (E, H, Dh) and they stack on
    # a new fusion axis -> (E, 3, H, Dh) matching models/bert.py's
    # DenseGeneral(features=(3, H, Dh)).
    per_layer = []
    for i in range(L):
        p = f"bert/encoder/layer_{i}"
        qkv_kernel = np.stack(
            [get(f"{p}/attention/self/{n}/kernel").reshape(E, H, Dh)
             for n in ("query", "key", "value")], axis=1)
        qkv_bias = np.stack(
            [get(f"{p}/attention/self/{n}/bias").reshape(H, Dh)
             for n in ("query", "key", "value")], axis=0)
        per_layer.append({
            "attention": {
                "qkv": {"kernel": qkv_kernel, "bias": qkv_bias},
                # context (H, Dh) -> E projection: TF kernel (E, E) rows are
                # the flattened head-major context
                "output": {
                    "kernel": get(f"{p}/attention/output/dense/kernel")
                    .reshape(H, Dh, E),
                    "bias": get(f"{p}/attention/output/dense/bias"),
                },
            },
            "attention_layer_norm": ln(f"{p}/attention/output/LayerNorm"),
            "intermediate": dense(f"{p}/intermediate/dense"),
            "mlp_output": dense(f"{p}/output/dense"),
            "output_layer_norm": ln(f"{p}/output/LayerNorm"),
        })
    if config.stacked_params:
        stacked = {}
        flat_keys = [
            ("attention", "qkv", "kernel"), ("attention", "qkv", "bias"),
            ("attention", "output", "kernel"), ("attention", "output", "bias"),
            ("attention_layer_norm", "scale"), ("attention_layer_norm", "bias"),
            ("intermediate", "kernel"), ("intermediate", "bias"),
            ("mlp_output", "kernel"), ("mlp_output", "bias"),
            ("output_layer_norm", "scale"), ("output_layer_norm", "bias"),
        ]
        for path in flat_keys:
            leaves = []
            for layer in per_layer:
                node = layer
                for k in path:
                    node = node[k]
                leaves.append(node)
            node = stacked
            for k in path[:-1]:
                node = node.setdefault(k, {})
            node[path[-1]] = np.stack(leaves, axis=0)
        encoder = {"layers": {"layer": stacked}}
    else:
        # per-layer modules: the per_layer trees ARE the target layout
        encoder = {f"layer_{i}": per_layer[i] for i in range(L)}

    bert = {"embeddings": embeddings, "encoder": encoder}
    if config.next_sentence and "bert/pooler/dense/kernel" in tf_vars:
        bert["pooler"] = {"dense": dense("bert/pooler/dense")}

    # Pretraining heads are present in Google releases and reference
    # pretraining checkpoints, but absent from finetune saves (a SQuAD
    # ckpt.pt has bert.* + qa_outputs.* only, run_squad.py:1125) — omit
    # rather than fail; load_pretrained_params reports the missing subtrees
    # and leaves them fresh-initialized.
    params = {"bert": bert}
    if "cls/predictions/transform/dense/kernel" in tf_vars:
        params["cls_predictions"] = {
            "transform": dense("cls/predictions/transform/dense"),
            "layer_norm": ln("cls/predictions/transform/LayerNorm"),
            "bias": _pad_vocab(get("cls/predictions/output_bias"), V,
                               PADDED_VOCAB_BIAS),
        }
    if config.next_sentence and "cls/seq_relationship/output_weights" in tf_vars:
        params["cls_seq_relationship"] = {
            # TF stores output_weights (2, E); flax Dense kernel is (E, 2)
            "kernel": get("cls/seq_relationship/output_weights").T,
            "bias": get("cls/seq_relationship/output_bias"),
        }
    return params


def load_torch_checkpoint(path: str) -> Dict[str, np.ndarray]:
    """Read a reference torch checkpoint into numpy.

    Accepts the reference's pretraining save format `{'model': state_dict,
    'optimizer': ..., ...}` (run_pretraining.py:499-511), its finetune save
    `{'model': state_dict}` (run_squad.py:1125), or a bare state_dict; a
    DistributedDataParallel 'module.' prefix is stripped. Only the model
    entry is read — optimizer/sampler/scaler state is torch-specific and
    does not transfer."""
    import torch  # cpu build baked into the image; imported lazily

    blob = torch.load(path, map_location="cpu", weights_only=True)
    state = blob.get("model", blob) if isinstance(blob, dict) else blob
    out = {}
    for name, tensor in state.items():
        if name.startswith("module."):
            name = name[len("module."):]
        out[name] = tensor.detach().to(torch.float32).numpy()
    return out


# torch-module path -> TF variable path, for names that differ beyond the
# mechanical rules in convert_torch_to_flax.
_TORCH_SPECIAL = {
    "cls.predictions.bias": "cls/predictions/output_bias",
    "cls.seq_relationship.weight": "cls/seq_relationship/output_weights",
    "cls.seq_relationship.bias": "cls/seq_relationship/output_bias",
}


def convert_torch_to_flax(state: Dict[str, np.ndarray],
                          config: BertConfig) -> Dict:
    """Map a reference torch state_dict (src/modeling.py module naming) onto
    this framework's param tree.

    Strategy: rename/re-lay each tensor into the Google-TF convention —
    torch Linear stores (out, in) so kernels transpose to (in, out);
    LayerNorm weight/bias become gamma/beta; `encoder.layer.{i}` becomes
    `encoder/layer_{i}` — then reuse convert_tf_to_flax for all assembly
    (fused-QKV head-major reshape, scan-axis stacking, vocab padding). The
    tied MLM decoder kernel (cls.predictions.decoder.weight) is dropped:
    models/bert.py re-ties it to the word embedding at apply time, exactly
    like the reference tied it at construction (src/modeling.py:570-575)."""
    tf_vars: Dict[str, np.ndarray] = {}
    for name, arr in state.items():
        if name.startswith("cls.predictions.decoder."):
            continue  # weight tied to embeddings; bias handled via _SPECIAL
        if name in _TORCH_SPECIAL:
            # seq_relationship.weight stays (2, E): TF's output_weights has
            # the same layout and convert_tf_to_flax transposes it
            tf_vars[_TORCH_SPECIAL[name]] = arr
            continue
        parts = name.split(".")
        leaf = parts[-1]
        mods: list = []
        for m in parts[:-1]:
            if m.isdigit():
                # torch ModuleList 'layer.{i}' -> TF 'layer_{i}'
                mods[-1] = f"{mods[-1]}_{m}"
            else:
                mods.append(m)
        if mods and mods[-1].endswith("_embeddings"):
            # torch stores embeddings.word_embeddings.weight; TF names the
            # (rows, E) table directly, no transpose
            leaf = None
        elif mods and mods[-1] == "LayerNorm":
            leaf = {"weight": "gamma", "bias": "beta"}[leaf]
        elif leaf == "weight":
            arr = arr.T  # torch Linear (out, in) -> TF kernel (in, out)
            leaf = "kernel"
        tf_vars["/".join(mods + ([leaf] if leaf else []))] = arr
    return convert_tf_to_flax(tf_vars, config)


def find_archive_files(directory: str) -> Tuple[str, str, Optional[str]]:
    """Locate (bert_config.json, ckpt_prefix, vocab.txt|None) under an
    extracted Google archive (possibly one nested directory deep)."""
    for root, _dirs, files in os.walk(directory):
        if "bert_config.json" in files:
            cfg = os.path.join(root, "bert_config.json")
            index = [f for f in files if f.endswith(".ckpt.index")]
            if not index:
                raise FileNotFoundError(
                    f"{root} has bert_config.json but no *.ckpt.index")
            prefix = os.path.join(root, index[0][:-len(".index")])
            vocab = (os.path.join(root, "vocab.txt")
                     if "vocab.txt" in files else None)
            return cfg, prefix, vocab
    raise FileNotFoundError(f"no bert_config.json found under {directory}")


def from_pretrained(
    name_or_path: str,
    cache_dir: Optional[str] = None,
    vocab_pad_multiple: int = 1,
    next_sentence: bool = True,
) -> Tuple[BertConfig, Dict]:
    """Load (config, params) from a Google BERT release.

    name_or_path: a registry name (PRETRAINED_ARCHIVE_MAP), a URL, a .zip, a
    directory containing bert_config.json + bert_model.ckpt*, or a ckpt
    prefix. The archive path mirrors the reference's from_pretrained
    (src/modeling.py:659-742): resolve -> cache -> extract -> read config ->
    load weights. vocab_pad_multiple pads vocab_size (and the embedding/bias
    rows) for the MXU.
    """
    from bert_pytorch_tpu.config import pad_vocab_size

    resolved = PRETRAINED_ARCHIVE_MAP.get(name_or_path, name_or_path)
    if not (os.path.isdir(resolved) or os.path.exists(resolved + ".index")):
        resolved = cached_path(resolved, cache_dir)

    if os.path.isfile(resolved) and resolved.endswith((".pt", ".pth", ".bin")):
        # a reference-trained torch checkpoint (ckpt_8601.pt) or an HF-style
        # pytorch_model.bin; the model config sits next to it as
        # bert_config.json (reference layout) or config.json (HF layout)
        ckpt_dir = os.path.dirname(resolved)
        for cand in ("bert_config.json", "config.json"):
            config_file = os.path.join(ckpt_dir, cand)
            if os.path.exists(config_file):
                break
        else:
            raise FileNotFoundError(
                f"no bert_config.json or config.json next to {resolved}; a "
                "torch checkpoint needs its model config in the same "
                "directory")
        vocab = os.path.join(ckpt_dir, "vocab.txt")
        vocab_file = vocab if os.path.exists(vocab) else None
        ckpt_prefix = resolved

        def load_params(config):
            return convert_torch_to_flax(load_torch_checkpoint(resolved),
                                         config)
    else:
        load_params = None

    if load_params is None and os.path.isfile(resolved) \
            and zipfile.is_zipfile(resolved):
        extract_dir = os.path.join(
            cache_dir or DEFAULT_CACHE,
            "extracted_" + os.path.basename(resolved))
        if not os.path.isdir(extract_dir):
            # extract to a temp dir then atomic-rename, so an interrupted
            # extraction is never mistaken for a complete one
            tmp_dir = extract_dir + ".tmp"
            if os.path.isdir(tmp_dir):
                shutil.rmtree(tmp_dir)
            with zipfile.ZipFile(resolved) as zf:
                zf.extractall(tmp_dir)
            os.replace(tmp_dir, extract_dir)
        resolved = extract_dir

    if load_params is None:
        if os.path.isdir(resolved):
            config_file, ckpt_prefix, vocab_file = find_archive_files(resolved)
        else:  # bare checkpoint prefix; config must sit next to it
            ckpt_prefix = resolved
            config_file = os.path.join(os.path.dirname(resolved),
                                       "bert_config.json")
            vocab = os.path.join(os.path.dirname(resolved), "vocab.txt")
            vocab_file = vocab if os.path.exists(vocab) else None

        def load_params(config):
            return convert_tf_to_flax(load_tf_weights(ckpt_prefix), config)

    with open(config_file, "r", encoding="utf-8") as f:
        cfg_dict = json.load(f)
    config = BertConfig.from_dict(cfg_dict).replace(
        next_sentence=next_sentence, vocab_file=vocab_file)
    config = config.replace(
        vocab_size=pad_vocab_size(config.vocab_size, vocab_pad_multiple))

    return config, load_params(config)
