from bert_pytorch_tpu.optim.schedulers import (  # noqa: F401
    constant_warmup_schedule,
    cosine_warmup_schedule,
    linear_warmup_schedule,
    make_schedule,
    poly_warmup_schedule,
)
from bert_pytorch_tpu.optim.lamb import lamb  # noqa: F401
from bert_pytorch_tpu.optim.adam import bert_adam, fused_adam  # noqa: F401
