"""LAMB optimizer (large-batch Adam with layerwise trust ratio).

The reference's large-batch path is apex `FusedLAMB` (run_pretraining.py:285),
a fused CUDA multi-tensor implementation of NVLAMB. Semantics reproduced here
as a pure optax GradientTransformation, jitted into the train step so XLA
fuses the whole update. An optional multi-tensor Pallas path
(`fused=True` -> ops/pallas/fused_optim.py, the amp_C stage1/stage2
analogue) flattens the leaves into size-capped flat buckets and runs one
launch per bucket per stage; off-TPU it auto-selects an XLA fallback that
is bit-identical to the unfused chain (the kernel itself agrees to within
a few ulps — see the numerics contract in fused_optim.py, pinned in
tests/test_fused_optim.py). NVLAMB specifics honored:

1. optional pre-normalization of the *global* gradient by
   max(1, ||g||_global / max_grad_norm)  (apex FusedLAMB max_grad_norm=1.0),
2. Adam moments with bias correction,
3. per-tensor update u = m_hat/(sqrt(v_hat)+eps) + wd*p,
4. trust ratio ||p|| / ||u||, taken as 1 when either norm is zero,
5. p <- p - lr * ratio * u.

Weight-decay masking (bias / LayerNorm params excluded) follows the
reference's two param groups (run_pretraining.py:268-276); the mask fn lives
with the trainer so this transform stays group-agnostic.

Layer-stacked parameters (the nn.scan encoder stores each weight as one
[L, ...] tensor) get PER-LAYER trust ratios via `trust_batch_axes`: apex
FusedLAMB saw 24 separate tensors and computed 24 ratios, so norms here
reduce over all but the leading stack axis and the ratio broadcasts back.
Collapsing the stack into one ratio would silently change the optimizer.
Gradients may arrive in bf16 (the train step accumulates microbatch grads in
the compute dtype — the reference's apex O2 kept fp16 grads); moments are
computed and stored fp32 regardless.
"""

from __future__ import annotations

from typing import Any, Callable, NamedTuple, Optional, Union

import jax
import jax.numpy as jnp
import optax


class LambState(NamedTuple):
    count: jax.Array
    mu: Any
    nu: Any


def lamb(
    learning_rate: Union[float, optax.Schedule],
    b1: float = 0.9,
    b2: float = 0.999,
    eps: float = 1e-6,
    weight_decay: float = 0.01,
    weight_decay_mask: Optional[Callable[[Any], Any]] = None,
    max_grad_norm: Optional[float] = 1.0,
    bias_correction: bool = True,
    trust_batch_axes: Optional[Callable[[Any], Any]] = None,
    norm_reducer: Optional[Any] = None,
    fused: bool = False,
    fused_impl: str = "auto",
) -> optax.GradientTransformation:
    """apex-FusedLAMB-semantics LAMB. `weight_decay_mask(params)` returns a
    pytree of bools — True where decay applies. `trust_batch_axes(params)`
    returns a pytree of ints: the number of leading "stack" axes a leaf
    carries (1 for the nn.scan [L, ...] encoder weights, 0 otherwise); trust
    norms reduce over the remaining axes so each stacked layer gets its own
    ratio, exactly as apex saw L separate tensors.

    `norm_reducer` (parallel/coalesce.NormReducer, built from the same
    sharding layout the train step constrains params/updates to): compute
    the per-tensor trust norms through BUCKETED cross-device reductions —
    a handful of vector all-reduces instead of two scalar all-reduces per
    parameter leaf (the dominant all-reduce COUNT in the sharded steps,
    see graph_report kfac_zero1_dp8). Values are bit-identical to the
    per-tensor path (same local reduce, same per-element cross-device
    sum — pinned in tests); None keeps the original per-tensor code
    byte-for-byte.

    `fused=True` routes the elementwise update chain (moment update +
    update direction, then the trust-ratio apply) through the bucketed
    multi-tensor kernels in ops/pallas/fused_optim.py — one launch per
    size-capped flat bucket per stage instead of one fusion per leaf. The
    trust NORMS stay in this module's existing per-tensor/norm_reducer
    path, so all reduction grouping is untouched. `fused_impl`: "auto"
    (Pallas kernel on TPU; elsewhere an XLA fallback that evaluates the
    same expressions per leaf and is BIT-identical to fused=False), or
    "pallas"/"xla" to force — the kernel agrees with the fallback to
    within a few ulps (cross-program FMA-contraction ambiguity; see the
    numerics contract in fused_optim.py). Both pinned in
    tests/test_fused_optim.py. With a ZeRO-1-sharded state
    also pass `norm_reducer`: the fused stages reuse its mesh + leaf
    specs to run shard_mapped on local shards (zero extra collectives);
    without it GSPMD would reshard the leaves around each bucket
    concat."""

    def init(params):
        zeros = lambda: jax.tree.map(
            lambda p: jnp.zeros(p.shape, jnp.float32), params)
        return LambState(count=jnp.zeros([], jnp.int32), mu=zeros(), nu=zeros())

    def update(grads, state, params):
        if params is None:
            raise ValueError("lamb requires params")
        count = state.count + 1
        cf = count.astype(jnp.float32)

        if max_grad_norm is not None:
            # upcast leaves BEFORE the reduce: grads may arrive bf16 and a
            # sum of ~3e8 squares in 8 mantissa bits is garbage; the cast
            # fuses into the reduction (no extra HBM pass). With a
            # norm_reducer the per-leaf scalar all-reduces coalesce into
            # one bucketed reduction — same upcast, same fold order,
            # bit-identical norm
            if norm_reducer is not None:
                gnorm = norm_reducer.global_norm_f32(grads)
            else:
                gnorm = optax.global_norm(
                    jax.tree.map(lambda g: g.astype(jnp.float32), grads))
            denom = jnp.maximum(1.0, gnorm / max_grad_norm)
        else:
            denom = None

        def norm_g(g):
            g = g.astype(jnp.float32)
            return g / denom if denom is not None else g

        if not fused:
            # two traversals, one HLO: XLA CSEs the shared g/denom
            # subexpression (the fused path computes the moments inside
            # the stage1 bucket kernels instead)
            mu = jax.tree.map(lambda m, g: b1 * m + (1 - b1) * norm_g(g),
                              state.mu, grads)
            nu = jax.tree.map(
                lambda v, g: b2 * v + (1 - b2) * jnp.square(norm_g(g)),
                state.nu, grads)

        if bias_correction:
            c1 = 1.0 - b1 ** cf
            c2 = 1.0 - b2 ** cf
        else:
            c1 = c2 = 1.0

        if weight_decay_mask is not None:
            wd_tree = jax.tree.map(
                lambda use: weight_decay if use else 0.0,
                weight_decay_mask(params))
        else:
            wd_tree = jax.tree.map(lambda _: weight_decay, params)
        if trust_batch_axes is not None:
            ba_tree = trust_batch_axes(params)
        else:
            ba_tree = jax.tree.map(lambda _: 0, params)

        lr = learning_rate(count - 1) if callable(learning_rate) else learning_rate

        if fused:
            from bert_pytorch_tpu.ops.pallas import fused_optim

            flat_g, treedef = jax.tree_util.tree_flatten(grads)
            flat_p = jax.tree.leaves(params)
            gf = [g.astype(jnp.float32) for g in flat_g]
            pf_l = [p.astype(jnp.float32) for p in flat_p]
            # a NormReducer carries the mesh + per-leaf specs the train
            # step constrains everything to; reuse them so the bucket
            # kernels run shard_mapped on local shards
            mesh = getattr(norm_reducer, "mesh", None)
            specs = getattr(norm_reducer, "_specs", None)
            mu_l, nu_l, u_l = fused_optim.lamb_stage1(
                gf, jax.tree.leaves(state.mu), jax.tree.leaves(state.nu),
                pf_l, jax.tree.leaves(wd_tree),
                denom=denom if denom is not None else 1.0,
                c1=c1, c2=c2, b1=b1, b2=b2, eps=eps,
                impl=fused_impl, mesh=mesh, specs=specs)
            unf = lambda ls: jax.tree_util.tree_unflatten(treedef, ls)
            pf_tree, u_tree = unf(pf_l), unf(u_l)
            if norm_reducer is not None:
                pn_tree, un_tree = norm_reducer.trust_norms(
                    pf_tree, u_tree, ba_tree)
            else:
                def tnorm(x, nbatch):
                    axes = tuple(range(nbatch, x.ndim))
                    return jnp.sqrt(jnp.sum(jnp.square(x), axis=axes,
                                            keepdims=True))

                pn_tree = jax.tree.map(tnorm, pf_tree, ba_tree)
                un_tree = jax.tree.map(tnorm, u_tree, ba_tree)

            def ratio_t(u, pn, un):
                ratio = jnp.where((pn > 0) & (un > 0),
                                  pn / jnp.maximum(un, 1e-30), 1.0)
                return jnp.broadcast_to(-lr * ratio, u.shape)

            t_l = [ratio_t(u, pn, un) for u, pn, un in
                   zip(u_l, jax.tree.leaves(pn_tree),
                       jax.tree.leaves(un_tree))]
            upd_l = fused_optim.lamb_stage2(t_l, u_l, impl=fused_impl,
                                            mesh=mesh, specs=specs)
            updates = unf([u.astype(p.dtype)
                           for u, p in zip(upd_l, flat_p)])
            return updates, LambState(count=count, mu=unf(mu_l),
                                      nu=unf(nu_l))

        def per_tensor(p, m, v, wd, nbatch):
            pf = p.astype(jnp.float32)
            u = (m / c1) / (jnp.sqrt(v / c2) + eps) + wd * pf
            axes = tuple(range(nbatch, u.ndim))
            pn = jnp.sqrt(jnp.sum(jnp.square(pf), axis=axes, keepdims=True))
            un = jnp.sqrt(jnp.sum(jnp.square(u), axis=axes, keepdims=True))
            ratio = jnp.where((pn > 0) & (un > 0), pn / jnp.maximum(un, 1e-30),
                              1.0)
            return (-lr * ratio * u).astype(p.dtype)

        if norm_reducer is None:
            updates = jax.tree.map(per_tensor, params, mu, nu, wd_tree,
                                   ba_tree)
        else:
            # same u, same ratio formula — only the pn/un REDUCTIONS are
            # routed through the bucketed reducer (one vector all-reduce
            # per bucket instead of two scalars per leaf)
            pf_tree = jax.tree.map(lambda p: p.astype(jnp.float32), params)
            u_tree = jax.tree.map(
                lambda pf, m, v, wd: (m / c1) / (jnp.sqrt(v / c2) + eps)
                + wd * pf, pf_tree, mu, nu, wd_tree)
            pn_tree, un_tree = norm_reducer.trust_norms(pf_tree, u_tree,
                                                        ba_tree)

            def apply_ratio(p, u, pn, un):
                ratio = jnp.where((pn > 0) & (un > 0),
                                  pn / jnp.maximum(un, 1e-30), 1.0)
                return (-lr * ratio * u).astype(p.dtype)

            updates = jax.tree.map(apply_ratio, params, u_tree, pn_tree,
                                   un_tree)
        return updates, LambState(count=count, mu=mu, nu=nu)

    return optax.GradientTransformation(init, update)


def default_trust_batch_axes(params: Any) -> Any:
    """1 for encoder weights stacked by nn.scan along a leading [L, ...]
    layer axis (path contains the scan collection name 'layers'), else 0.
    Gives layer-stacked tensors per-layer trust ratios (apex parity — it saw
    L separate tensors, run_pretraining.py:268-286). Under the unstacked
    layout (config.stacked_params=False) encoder paths are 'layer_{i}', not
    'layers', so every leaf gets 0 batch axes — one ratio per tensor, which
    IS a per-layer ratio there: both layouts optimize identically."""

    def n_batch(path: tuple) -> int:
        keys = [getattr(k, "key", str(k)) for k in path]
        return 1 if "layers" in keys else 0

    return jax.tree_util.tree_map_with_path(lambda p, _: n_batch(p), params)


def default_weight_decay_mask(params: Any) -> Any:
    """True for params that get weight decay: everything except biases and
    LayerNorm scale/bias (reference no_decay list ['bias','gamma','beta',
    'LayerNorm'], run_pretraining.py:268-276)."""

    def is_decay(path: tuple) -> bool:
        keys = [getattr(k, "key", str(k)) for k in path]
        joined = "/".join(str(k) for k in keys).lower()
        if joined.endswith("/bias") or joined == "bias":
            return False
        if "layer_norm" in joined or "layernorm" in joined:
            return False
        return True

    return jax.tree_util.tree_map_with_path(lambda p, _: is_decay(p), params)
