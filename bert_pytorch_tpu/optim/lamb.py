"""LAMB optimizer (large-batch Adam with layerwise trust ratio).

The reference's large-batch path is apex `FusedLAMB` (run_pretraining.py:285),
a fused CUDA multi-tensor implementation of NVLAMB. Semantics reproduced here
as a pure optax GradientTransformation, jitted into the train step so XLA
fuses the whole update; the Pallas multi-block variant for very large param
counts lives in ops/pallas/. NVLAMB specifics honored:

1. optional pre-normalization of the *global* gradient by
   max(1, ||g||_global / max_grad_norm)  (apex FusedLAMB max_grad_norm=1.0),
2. Adam moments with bias correction,
3. per-tensor update u = m_hat/(sqrt(v_hat)+eps) + wd*p,
4. trust ratio ||p|| / ||u||, taken as 1 when either norm is zero,
5. p <- p - lr * ratio * u.

Weight-decay masking (bias / LayerNorm params excluded) follows the
reference's two param groups (run_pretraining.py:268-276); the mask fn lives
with the trainer so this transform stays group-agnostic.
"""

from __future__ import annotations

from typing import Any, Callable, NamedTuple, Optional, Union

import jax
import jax.numpy as jnp
import optax


class LambState(NamedTuple):
    count: jax.Array
    mu: Any
    nu: Any


def lamb(
    learning_rate: Union[float, optax.Schedule],
    b1: float = 0.9,
    b2: float = 0.999,
    eps: float = 1e-6,
    weight_decay: float = 0.01,
    weight_decay_mask: Optional[Callable[[Any], Any]] = None,
    max_grad_norm: Optional[float] = 1.0,
    bias_correction: bool = True,
) -> optax.GradientTransformation:
    """apex-FusedLAMB-semantics LAMB. `weight_decay_mask(params)` returns a
    pytree of bools — True where decay applies."""

    def init(params):
        zeros = lambda: jax.tree.map(jnp.zeros_like, params)
        return LambState(count=jnp.zeros([], jnp.int32), mu=zeros(), nu=zeros())

    def update(grads, state, params):
        if params is None:
            raise ValueError("lamb requires params")
        count = state.count + 1
        cf = count.astype(jnp.float32)

        if max_grad_norm is not None:
            gnorm = optax.global_norm(grads)
            denom = jnp.maximum(1.0, gnorm / max_grad_norm)
            grads = jax.tree.map(lambda g: g / denom, grads)

        mu = jax.tree.map(lambda m, g: b1 * m + (1 - b1) * g, state.mu, grads)
        nu = jax.tree.map(lambda v, g: b2 * v + (1 - b2) * jnp.square(g),
                          state.nu, grads)

        if bias_correction:
            c1 = 1.0 - b1 ** cf
            c2 = 1.0 - b2 ** cf
        else:
            c1 = c2 = 1.0

        if weight_decay_mask is not None:
            wd_tree = jax.tree.map(
                lambda use: weight_decay if use else 0.0,
                weight_decay_mask(params))
        else:
            wd_tree = jax.tree.map(lambda _: weight_decay, params)

        def per_tensor(p, m, v, wd):
            u = (m / c1) / (jnp.sqrt(v / c2) + eps) + wd * p
            pn = jnp.linalg.norm(p.astype(jnp.float32))
            un = jnp.linalg.norm(u.astype(jnp.float32))
            ratio = jnp.where((pn > 0) & (un > 0), pn / jnp.maximum(un, 1e-30),
                              1.0)
            return ratio * u

        updates = jax.tree.map(per_tensor, params, mu, nu, wd_tree)
        lr = learning_rate(count - 1) if callable(learning_rate) else learning_rate
        updates = jax.tree.map(lambda u: (-lr * u).astype(u.dtype), updates)
        return updates, LambState(count=count, mu=mu, nu=nu)

    return optax.GradientTransformation(init, update)


def default_weight_decay_mask(params: Any) -> Any:
    """True for params that get weight decay: everything except biases and
    LayerNorm scale/bias (reference no_decay list ['bias','gamma','beta',
    'LayerNorm'], run_pretraining.py:268-276)."""

    def is_decay(path: tuple) -> bool:
        keys = [getattr(k, "key", str(k)) for k in path]
        joined = "/".join(str(k) for k in keys).lower()
        if joined.endswith("/bias") or joined == "bias":
            return False
        if "layer_norm" in joined or "layernorm" in joined:
            return False
        return True

    return jax.tree_util.tree_map_with_path(lambda p, _: is_decay(p), params)
