"""K-FAC second-order preconditioning, in-framework and TPU-native.

The reference delegated K-FAC to the external `kfac_pytorch` library wired at
run_pretraining.py:311-345 (factor_decay 0.95, damping 0.003, kl_clip 0.001,
factor_update_freq 1, inv_update_freq 10, skip-list
['BertLMPredictionHead','embedding'], fp16 inverses, NCCL factor
communication). SURVEY §2.2/§2.3 requires it re-implemented in-framework.

TPU-native design (no hooks, no NCCL):
- **Taps, not hooks.** The model sows each encoder linear layer's input
  (collection 'kfac_in') and adds a flax `perturb` on its output; the grad of
  the loss w.r.t. the perturbation IS the layer's output gradient, obtained
  from the same backward pass as the parameter grads — no separate autograd
  machinery (reference lib attached fwd/bwd torch hooks).
- **Layer-stacked factors.** Encoder taps arrive stacked over the scanned
  layer axis (L, ...); factor statistics, EMA updates, Cholesky inverses, and
  preconditioning are vmapped over L — one XLA op per tap *site*, 24x fewer
  kernels than per-layer Python loops. Under the unstacked encoder layout
  (config.stacked_params=False) taps arrive per layer (one 2D site per
  layer_{i}); every code path below already handles both ranks — per-layer
  sites simply take the non-vmapped branch, and the L-axis distributed
  factor ownership does not apply (2D factors stay replicated; they are
  small). Checkpointed KFACState converts between layouts with
  models/pretrained.convert_tree_layout like every other state subtree.
- **Communication is compiled.** Activations/output-grads are batch-sharded;
  the (rows, in)^T @ (rows, in) factor contraction reduces over the sharded
  row axis, so XLA inserts the factor all-reduce over ICI automatically —
  the reference's explicit factor allreduce/HYBRID_OPT machinery dissolves.
- **Factored Tikhonov damping** (pi-correction) and kl_clip rescaling follow
  the standard K-FAC formulation the reference lib implements.
- Kernel and bias are preconditioned jointly via homogeneous-coordinate
  augmentation of A (append-1 activation column).

Scope parity note: taps cover the 96 encoder linears of BERT-Large (4 per
layer x 24) plus the pooler and NSP-head linears — every layer the reference
library preconditioned (it hooked all supported modules minus the skip-list,
run_pretraining.py:311-345). Embeddings and the MLM head are skipped per the
reference's skip-list.
"""

from __future__ import annotations

import dataclasses
import re
from typing import Any, Callable, Optional, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np
from flax import struct


@dataclasses.dataclass(frozen=True)
class KFACConfig:
    inv_interval: int = 10          # kfac_inv_interval (reference CLI :132)
    factor_interval: int = 1        # kfac_factor_interval (:134)
    stat_decay: float = 0.95        # kfac_stat_decay (:136)
    damping: float = 0.003          # kfac_damping (:138)
    kl_clip: float = 0.001          # kfac_kl_clip (:140)
    skip_layers: Tuple[str, ...] = ("cls_predictions", "embeddings")
    learning_rate: Union[float, Callable] = 1.0  # for kl_clip scaling
    factor_dtype: Any = jnp.float32
    inverse_dtype: Any = jnp.bfloat16  # reference used fp16 inverses
    # --kfac_stats_dtype: dtype of the per-microbatch factor STATISTICS —
    # the tensors the factor collectives move every factor_interval step.
    # bf16 halves that wire traffic (in bucketed mode the coalesced psums
    # genuinely move bf16 vectors); the EMA still accumulates in f32
    # (_update_factors upcasts into factor_dtype, and _reduce_stats
    # upcasts before the /rows normalization), which is what keeps the
    # trajectory within the f32-parity gate in tests/test_kfac.py.
    # None = factor_dtype (the exact round-15 program, bit for bit).
    stats_dtype: Any = None


@struct.dataclass
class KFACState:
    """factors/inverses are pytrees keyed like the tap tree; each leaf is a
    dict {'A': (..., in+1, in+1), 'G': (..., out, out)} with optional leading
    stacked-layer axes."""

    factors: Any
    inverses: Any
    count: jax.Array  # optimization steps seen


class KFAC:
    """Functional K-FAC: state in a pytree, all updates inside the jitted
    train step. Usage (training/pretrain.py wires this):

        kfac = KFAC(config)
        state0 = kfac.init(acts, pert_grads)
        stats  = kfac.compute_stats(acts, pert_grads)   # per microbatch
        new_state, grads = kfac.step(state, stats, grads, lr)
    """

    def __init__(self, config: KFACConfig, mesh=None,
                 shard_axes: Optional[Tuple[str, ...]] = None,
                 factor_bucket_bytes: Optional[int] = None,
                 factor_sync_freq: int = 1):
        """mesh + shard_axes turn on distributed factor/inverse ownership:
        every layer-stacked site (leaves with a leading L axis) stores its
        factors and inverses sharded over `shard_axes` on the L axis, the
        vmapped Cholesky inversion runs only on each device's L-shard, and
        preconditioning is computed shard-local before XLA re-gathers the
        preconditioned grads to the params' sharding. This is the TPU
        equivalent of the reference K-FAC's distributed inverse ownership
        (comm_method=HYBRID_OPT, grad_worker_fraction=0.5,
        run_pretraining.py:325-327) — except the collectives are compiled
        into the step instead of hand-scheduled NCCL broadcasts. mesh=None
        (single chip) keeps everything replicated. shard_axes defaults to
        the rules table's KFAC_SHARD_AXES (parallel/rules.py — the one
        logical-axis table every sharding derivation routes through).

        `factor_bucket_bytes` (--kfac_bucket_mb) turns on COALESCED
        factor reductions: compute_stats returns per-device PARTIAL
        factor contractions (a leading batch-shard axis, zero collectives
        — the same local matmul GSPMD's partial-dot lowering performs),
        and `step` reduces them in a handful of deterministic size-capped
        buckets (one psum per bucket) instead of one all-reduce per
        factor, dividing the compiled all-reduce count while keeping the
        update bit-identical at accum_steps=1 (same local contraction,
        same per-element cross-device sum, normalization after the
        reduction in both paths — tests/test_kfac.py pins it; at
        accum>1 the partial accumulation reorders the normalization,
        mathematically equal but not bit-equal). The assignment is
        recorded in `self.bucket_assignment` after the first trace (run
        headers log it). Batches whose global rows don't divide the
        batch-shard count fall back to the per-factor path with a loud
        warning.

        `factor_sync_freq` N>1 skips the factor-statistic reduction AND
        the EMA update on steps where count % N != 0 — the statistics are
        EMA-smoothed anyway, so syncing every step buys little once the
        factors have burned in; with bucketed stats the off-step skips
        the psums at runtime, not just the EMA. 1 (the default) compiles
        the exact freq-free program (parity-tested)."""
        from bert_pytorch_tpu.parallel import rules as rules_lib

        self.config = config
        self.mesh = mesh
        self.shard_axes = (tuple(shard_axes) if shard_axes is not None
                           else rules_lib.KFAC_SHARD_AXES)
        self.factor_bucket_bytes = factor_bucket_bytes
        self.factor_sync_freq = int(factor_sync_freq)
        self._batch_axes = tuple(rules_lib.batch_axes(mesh)) \
            if mesh is not None else ()
        self._batch_shards = rules_lib.shard_count(mesh, self._batch_axes) \
            if mesh is not None else 1
        self.bucketed = bool(factor_bucket_bytes) and self._batch_shards > 1
        self.bucket_assignment: Optional[list] = None
        self._site_norms: dict = {}
        self._warned_fallback = False

    def _stats_dtype(self):
        return (self.config.stats_dtype
                if self.config.stats_dtype is not None
                else self.config.factor_dtype)

    def _shard_count(self) -> int:
        from bert_pytorch_tpu.parallel import rules as rules_lib

        # missing axes count as size 1 so custom meshes lacking data/fsdp
        # degrade to the replicated layout instead of raising KeyError
        # (rules.shard_count implements exactly that)
        return rules_lib.shard_count(self.mesh, self.shard_axes)

    def _stacked_sharding(self, n_layers: int):
        """NamedSharding splitting a leading stacked-layer axis of size
        n_layers, or None when there is no mesh / the axis does not divide
        evenly over the shards — parallel/rules.stacked_spec, the same
        derivation the graph gate and scripts/kfac_shard_audit.py verify
        the live state against."""
        from bert_pytorch_tpu.parallel import rules as rules_lib

        return rules_lib.stacked_spec(self.mesh, n_layers, self.shard_axes)

    def _constrain_stacked(self, tree: Any) -> Any:
        """Apply the L-axis sharding constraint to every stacked array
        leaf of a factor/inverse tree (state_shardings decides which —
        the shared placement derivation); 2D (pooler/NSP) leaves stay
        replicated — their inverses are tiny."""
        if self.mesh is None:
            return tree
        leaves, treedef = jax.tree_util.tree_flatten(tree)
        placements = state_shardings(tree, self.mesh, self.shard_axes)
        return jax.tree_util.tree_unflatten(treedef, [
            x if s is None else jax.lax.with_sharding_constraint(x, s)
            for x, s in zip(leaves, placements)])

    # -- tap plumbing -------------------------------------------------------

    @staticmethod
    def _path_is_stacked(path, ndim: int) -> bool:
        """Does this tap ride a leading (L, ...) scan axis? The tree path
        decides where it can: 'layers' (the scan module) => stacked,
        'layer_{i}' (an unstacked per-layer module) => NOT stacked even at
        high rank — an unstacked qkv tap is (B, S, 3, H, Dh), the same ndim
        range a stacked dense tap occupies, so rank alone would misread it.
        Bare trees without either marker (unit tests, ad-hoc callers) keep
        the legacy rank>=4 heuristic."""
        keys = [getattr(k, "key", str(k)) for k in path]
        if "layers" in keys:
            return True
        if any(_LAYER_I_RE.match(k) for k in keys):
            return False
        return ndim >= 4

    @staticmethod
    def _flatten_acts(a: jax.Array, stacked: bool) -> jax.Array:
        """stacked: (L, B, S, F...) -> (L, rows, F_flat); else
        (B, S, F...) -> (rows, F_flat); (B, F) passes through (pooler/NSP
        taps have no sequence axis)."""
        if stacked:
            L = a.shape[0]
            feat = int(np.prod(a.shape[3:])) if a.ndim > 3 else a.shape[-1]
            return a.reshape(L, a.shape[1] * a.shape[2], feat)
        if a.ndim == 2:
            return a
        feat = int(np.prod(a.shape[2:]))
        return a.reshape(a.shape[0] * a.shape[1], feat)

    @staticmethod
    def _site_map(acts: Any, perts: Any):
        """Align the two tap trees: returns pytree of (a, g) leaf pairs with
        the same structure as perts. Sown values arrive as 1-tuples."""
        def unwrap(x):
            return x[0] if isinstance(x, tuple) else x

        acts = jax.tree.map(unwrap, acts, is_leaf=lambda x: isinstance(x, tuple))
        return acts, perts

    # -- statistics ---------------------------------------------------------

    def compute_stats(self, acts: Any, pert_grads: Any) -> Any:
        """One microbatch's factor statistics: A = aug(a)^T aug(a) / rows,
        G = rows * g^T g  (undoes the mean-loss 1/N in g, kfac convention).

        Bucketed mode (factor_bucket_bytes set, batch sharded): returns
        PARTIAL statistics instead — each leaf grows a leading
        batch-shard axis holding the per-device local contraction,
        computed under shard_map with ZERO collectives; `step` reduces
        them bucketed (see _reduce_stats). Falls back to the reduced
        path, loudly, when the batch rows don't divide the shard
        count."""
        acts, perts = self._site_map(acts, pert_grads)
        if self.bucketed:
            sites = self._collect_sites(acts, perts)
            bad = [self._pathkey(p) for p, a, g, stacked in sites
                   if a.shape[1 if stacked else 0] % self._batch_shards]
            if not bad:
                return self._partial_stats(acts, perts, sites)
            if not self._warned_fallback:
                import sys

                print("WARNING: kfac: bucketed factor reductions DISABLED"
                      f" — batch dim of site(s) {', '.join(bad[:4])} not "
                      f"divisible by the {self._batch_shards}-way batch "
                      "sharding; falling back to one all-reduce per "
                      "factor", file=sys.stderr)
                self._warned_fallback = True
            self.bucketed = False
        sdt = self._stats_dtype()

        def stat(path, a, g):
            stacked = self._path_is_stacked(path, a.ndim)
            a = self._flatten_acts(a, stacked).astype(jnp.float32)
            g = self._flatten_acts(g, stacked).astype(jnp.float32)

            def one(a2, g2):
                rows = a2.shape[0]
                ones = jnp.ones((rows, 1), jnp.float32)
                a_aug = jnp.concatenate([a2, ones], axis=1)
                A = (a_aug.T @ a_aug) / rows
                G = (g2.T @ g2) * rows
                return {"A": A.astype(sdt),
                        "G": G.astype(sdt)}

            if stacked:
                return jax.vmap(one)(a, g)
            return one(a, g)

        return jax.tree_util.tree_map_with_path(
            stat, acts, perts, is_leaf=lambda x: isinstance(x, jax.Array))

    # -- bucketed factor reductions (round 15) ------------------------------

    @staticmethod
    def _pathkey(path) -> str:
        return jax.tree_util.keystr(path)

    def _collect_sites(self, acts: Any, perts: Any) -> list:
        """Flat [(path, a, g, stacked)] site list in deterministic tree
        order — the order every bucket assignment derives from."""
        out = []

        def collect(path, a, g):
            out.append((path, a, g, self._path_is_stacked(path, a.ndim)))
            return a

        jax.tree_util.tree_map_with_path(
            collect, acts, perts, is_leaf=lambda x: isinstance(x, jax.Array))
        return out

    def _partial_stats(self, acts: Any, perts: Any, sites: list) -> Any:
        """Per-device PARTIAL factor contractions under shard_map: each
        site's local rows contracted exactly as GSPMD's partial-dot
        lowering would (same local matmul, bit for bit), returned with a
        leading batch-shard axis and NO collective. Normalization (A /
        rows, G * rows) is deferred to _reduce_stats so it lands AFTER
        the cross-device sum, matching the unbucketed program's
        divide-after-all-reduce order."""
        from jax.sharding import PartitionSpec as P

        from bert_pytorch_tpu.ops.shard_map_compat import shard_map

        in_specs, args = [], []
        for path, a, g, stacked in sites:
            bdim = 1 if stacked else 0
            for x in (a, g):
                spec = [None] * x.ndim
                spec[bdim] = self._batch_axes
                in_specs.append(P(*spec))
                args.append(x)
            # rows of the GLOBAL flattened contraction (B*S, or B for the
            # 2D pooler/NSP taps) — the /rows, *rows normalization
            # constants _reduce_stats applies post-psum
            self._site_norms[self._pathkey(path)] = (
                a.shape[1] * a.shape[2] if stacked
                else (a.shape[0] if a.ndim == 2
                      else a.shape[0] * a.shape[1]))

        sdt = self._stats_dtype()

        def local_contract(*blocks):
            outs = []
            for i, (path, _a, _g, stacked) in enumerate(sites):
                a2 = self._flatten_acts(blocks[2 * i],
                                        stacked).astype(jnp.float32)
                g2 = self._flatten_acts(blocks[2 * i + 1],
                                        stacked).astype(jnp.float32)

                def one(a3, g3):
                    ones = jnp.ones((a3.shape[0], 1), jnp.float32)
                    a_aug = jnp.concatenate([a3, ones], axis=1)
                    return a_aug.T @ a_aug, g3.T @ g3

                A, G = (jax.vmap(one)(a2, g2) if stacked else one(a2, g2))
                # the stats_dtype cast lands BEFORE the bucketed psums in
                # _reduce_stats — bf16 stats halve the factor bytes the
                # coalesced reductions actually move (f32 default: no-op)
                outs += [A[None].astype(sdt), G[None].astype(sdt)]
            return tuple(outs)

        out_specs = []
        for path, a, g, stacked in sites:
            for _ in range(2):
                nd = (4 if stacked else 3)  # (1, [L,] d, d) local blocks
                out_specs.append(P(self._batch_axes,
                                   *([None] * (nd - 1))))
        outs = shard_map(local_contract, mesh=self.mesh,
                         in_specs=tuple(in_specs),
                         out_specs=tuple(out_specs),
                         check_rep=False)(*args)

        results = {self._pathkey(p): {"A": outs[2 * i], "G": outs[2 * i + 1]}
                   for i, (p, _a, _g, _s) in enumerate(sites)}
        return jax.tree_util.tree_map_with_path(
            lambda path, a, g: results[self._pathkey(path)],
            acts, perts, is_leaf=lambda x: isinstance(x, jax.Array))

    def local_partial_stats(self, acts: Any, pert_grads: Any,
                            record_norms: bool = True) -> Any:
        """_partial_stats' per-site local contraction for callers that are
        ALREADY inside a shard_map region (the ZeRO-1 reduce-scatter step
        wraps the whole microbatch fwd/bwd in one): same matmuls, same
        (1, [L,] d, d) leading-partial-axis layout, NO shard_map wrapper —
        the caller's out_specs put the leading axis back on the batch
        axes, so `step`'s bucketed _reduce_stats consumes the result
        unchanged. Tap arrays here are LOCAL shards, so the recorded
        /rows, *rows normalization constants are scaled to the GLOBAL row
        counts _reduce_stats divides by (local rows x batch shards —
        exact, because the region's batch in_specs split the rows evenly
        by construction). record_norms=False skips that bookkeeping for
        shape-only probes (the eval_shape pass that derives the region's
        stats out_specs traces this OUTSIDE shard_map, where shapes are
        global and the constants would be 8x wrong)."""
        acts, perts = self._site_map(acts, pert_grads)
        sites = self._collect_sites(acts, perts)
        sdt = self._stats_dtype()
        results = {}
        for path, a, g, stacked in sites:
            if record_norms:
                local_rows = (a.shape[1] * a.shape[2] if stacked
                              else (a.shape[0] if a.ndim == 2
                                    else a.shape[0] * a.shape[1]))
                self._site_norms[self._pathkey(path)] = (
                    local_rows * self._batch_shards)
            a2 = self._flatten_acts(a, stacked).astype(jnp.float32)
            g2 = self._flatten_acts(g, stacked).astype(jnp.float32)

            def one(a3, g3):
                ones = jnp.ones((a3.shape[0], 1), jnp.float32)
                a_aug = jnp.concatenate([a3, ones], axis=1)
                return a_aug.T @ a_aug, g3.T @ g3

            A, G = (jax.vmap(one)(a2, g2) if stacked else one(a2, g2))
            results[self._pathkey(path)] = {"A": A[None].astype(sdt),
                                            "G": G[None].astype(sdt)}
        return jax.tree_util.tree_map_with_path(
            lambda path, a, g: results[self._pathkey(path)],
            acts, perts, is_leaf=lambda x: isinstance(x, jax.Array))

    def _reduce_stats(self, stats: Any) -> Any:
        """Partial stats -> reduced stats through deterministic
        size-capped buckets: ONE psum per bucket over the batch axes
        (the whole point — a handful of all-reduces instead of one per
        factor), then per-site normalization and the factor-dtype cast,
        both AFTER the reduction exactly where the unbucketed program
        puts them. Records self.bucket_assignment (run-header
        material). No-op passthrough for already-reduced trees."""
        from jax.sharding import PartitionSpec as P

        from bert_pytorch_tpu.parallel.coalesce import _bucketize
        from bert_pytorch_tpu.ops.shard_map_compat import shard_map

        cfg = self.config
        flat = jax.tree_util.tree_flatten_with_path(stats)
        leaves, treedef = flat[0], flat[1]
        sizes = [int(np.prod(x.shape[1:])) for _p, x in leaves]
        buckets = _bucketize(sizes, int(self.factor_bucket_bytes))
        self.bucket_assignment = [
            {"factors": [self._pathkey(leaves[j][0]) for j in b],
             "elems": sum(sizes[j] for j in b)}
            for b in buckets]

        in_specs = tuple(P(self._batch_axes, *([None] * (x.ndim - 1)))
                         for _p, x in leaves)

        def reduce_buckets(*blocks):
            flats = [b.reshape(-1) for b in blocks]
            out = [None] * len(flats)
            for b in buckets:
                vec = (jnp.concatenate([flats[j] for j in b])
                       if len(b) > 1 else flats[b[0]])
                red = jax.lax.psum(vec, self._batch_axes)
                off = 0
                for j in b:
                    out[j] = red[off:off + sizes[j]]
                    off += sizes[j]
            return tuple(out)

        outs = shard_map(reduce_buckets, mesh=self.mesh,
                         in_specs=in_specs,
                         out_specs=tuple(P() for _ in leaves),
                         check_rep=False)(*[x for _p, x in leaves])

        reduced = []
        for (path, x), vec in zip(leaves, outs):
            site_key = self._pathkey(path[:-1])
            kind = getattr(path[-1], "key", str(path[-1]))
            rows = self._site_norms[site_key]
            if vec.dtype != jnp.float32:
                # bf16 stats: normalize (and EMA-accumulate downstream) in
                # f32 — the trace-time guard keeps the f32-default program
                # free of any convert node, i.e. byte-identical to round 15
                vec = vec.astype(jnp.float32)
            full = vec.reshape(x.shape[1:])
            full = full / rows if kind == "A" else full * rows
            reduced.append(full.astype(cfg.factor_dtype))
        return jax.tree_util.tree_unflatten(treedef, reduced)

    def init(self, acts: Any, pert_grads: Any) -> KFACState:
        """Zero factors/identity inverses shaped from one tap evaluation.
        With a mesh, stacked leaves are placed sharded on their layer axis —
        the distributed-ownership layout every later step preserves."""
        stats = self.compute_stats(acts, pert_grads)
        if self.bucketed:
            stats = self._reduce_stats(stats)
        # factors always rest in factor_dtype — stats_dtype only thins the
        # per-step statistics on the wire, never the EMA accumulator.
        # zeros_like (not zeros): it inherits each stat's placement, which
        # is what keeps the compiled step's factor-input layouts — and
        # therefore its donation aliasing — identical to round 15
        factors = jax.tree.map(
            lambda s: jnp.zeros_like(s, dtype=self.config.factor_dtype),
            stats)

        def eye_like(f):
            n = f.shape[-1]
            e = jnp.broadcast_to(jnp.eye(n, dtype=self.config.inverse_dtype),
                                 f.shape)
            return e

        inverses = jax.tree.map(eye_like, factors)
        if self.mesh is not None:
            def place(tree):
                leaves, treedef = jax.tree_util.tree_flatten(tree)
                placements = state_shardings(tree, self.mesh,
                                             self.shard_axes)
                return jax.tree_util.tree_unflatten(treedef, [
                    x if s is None else jax.device_put(x, s)
                    for x, s in zip(leaves, placements)])

            factors = place(factors)
            inverses = place(inverses)
        return KFACState(factors=factors, inverses=inverses,
                         count=jnp.zeros([], jnp.int32))

    # -- factor EMA + inversion --------------------------------------------

    def _update_factors(self, factors: Any, stats: Any) -> Any:
        d = self.config.stat_decay
        new = jax.tree.map(lambda f, s: d * f + (1.0 - d) * s.astype(f.dtype),
                           factors, stats)
        # stats arrive replicated (the batch-axis psum yields the full
        # contraction on every device); constraining the EMA output keeps
        # the stored factors shard-owned — each device updates only its
        # L-slice, the replicated stats are sliced for free
        return self._constrain_stacked(new)

    def _invert(self, factors: Any) -> Any:
        lam = self.config.damping
        out_dtype = self.config.inverse_dtype

        def inv_site(site):
            A, G = site["A"].astype(jnp.float32), site["G"].astype(jnp.float32)

            def one(A2, G2):
                # factored Tikhonov: pi = sqrt((tr(A)/dA) / (tr(G)/dG))
                tr_a = jnp.trace(A2) / A2.shape[-1]
                tr_g = jnp.trace(G2) / G2.shape[-1]
                pi = jnp.sqrt(jnp.maximum(tr_a, 1e-12)
                              / jnp.maximum(tr_g, 1e-12))
                sqrt_lam = jnp.sqrt(lam)
                eye_a = jnp.eye(A2.shape[-1], dtype=jnp.float32)
                eye_g = jnp.eye(G2.shape[-1], dtype=jnp.float32)
                A_inv = _chol_inverse(A2 + sqrt_lam * pi * eye_a)
                G_inv = _chol_inverse(G2 + sqrt_lam / pi * eye_g)
                return A_inv, G_inv

            if A.ndim == 3:
                A_inv, G_inv = jax.vmap(one)(A, G)
            else:
                A_inv, G_inv = one(A, G)
            return {"A": A_inv.astype(out_dtype), "G": G_inv.astype(out_dtype)}

        # the factors are stored L-sharded (distributed ownership): the
        # constraints pin both the input slices and the output layout, so
        # the vmapped Cholesky of a 24-layer stack runs 1/shards of the
        # work per device instead of replicating the whole inversion —
        # the reference's HYBRID_OPT work partitioning, compiled
        inverted = jax.tree.map(inv_site, self._constrain_stacked(factors),
                                is_leaf=lambda x: isinstance(x, dict)
                                and "A" in x)
        return self._constrain_stacked(inverted)

    # -- preconditioning ----------------------------------------------------

    def _precondition_site(self, inv_site, kernel_grad, bias_grad):
        """Jointly precondition (kernel, bias) via the augmented-A inverse.
        kernel (in, F...) in flax layout; bias (F...,)."""
        A_inv = inv_site["A"].astype(jnp.float32)
        G_inv = inv_site["G"].astype(jnp.float32)

        kshape, bshape = kernel_grad.shape, bias_grad.shape

        def one(A_inv2, G_inv2, kg, bg):
            din = A_inv2.shape[-1] - 1
            dout = G_inv2.shape[-1]
            kg2 = kg.reshape(din, dout).astype(jnp.float32)
            bg2 = bg.reshape(dout).astype(jnp.float32)
            aug = jnp.concatenate([kg2, bg2[None, :]], axis=0)  # (in+1, out)
            pre = A_inv2 @ aug @ G_inv2
            return pre[:-1], pre[-1]

        if A_inv.ndim == 3:  # stacked layers: kernel (L, in, F...)
            L = kshape[0]
            pk, pb = jax.vmap(one)(A_inv, G_inv,
                                   kernel_grad.reshape(L, kshape[1], -1),
                                   bias_grad.reshape(L, -1))
        else:
            pk, pb = one(A_inv, G_inv, kernel_grad, bias_grad)
        return pk.reshape(kshape).astype(kernel_grad.dtype), \
            pb.reshape(bshape).astype(bias_grad.dtype)

    def precondition(self, state: KFACState, grads: Any, lr) -> Any:
        """Replace tapped-site grads with F^{-1} g, then kl_clip-rescale the
        preconditioned sites (reference lib's grad scaling).

        Tap variables are named '<dense>_tap' (flax forbids a perturb variable
        sharing its Dense submodule's name); the trailing suffix is stripped
        to address the corresponding {kernel, bias} grads. Sites whose path
        contains any skip_layers token keep their first-order grads
        (reference skip-list semantics, run_pretraining.py:141-144)."""
        skip = self.config.skip_layers
        flat_inv = [(tuple(p[:-1]) + (_strip_tap(p[-1]),), site)
                    for p, site in _flatten_with_path(state.inverses)
                    if not any(tok in "/".join(p) for tok in skip)]
        sq_sum = jnp.zeros([], jnp.float32)
        pre_by_path = {}
        for path, inv_site in flat_inv:
            sub = _tree_get(grads, path)
            sharding = (self._stacked_sharding(inv_site["A"].shape[0])
                        if inv_site["A"].ndim == 3 else None)
            if sharding is not None:
                # move the stacked grads onto the inverse owners' layout so
                # A^-1 @ g @ G^-1 is shard-local; XLA re-shards the
                # preconditioned result back to the params' layout for the
                # optimizer update (one compiled all-to-all each way)
                sub = {
                    "kernel": jax.lax.with_sharding_constraint(
                        sub["kernel"], sharding),
                    "bias": jax.lax.with_sharding_constraint(
                        sub["bias"], sharding),
                }
            pk, pb = self._precondition_site(inv_site, sub["kernel"],
                                             sub["bias"])
            pre_by_path[path] = {"kernel": pk, "bias": pb}
            sq_sum = sq_sum + jnp.sum(pk.astype(jnp.float32)
                                      * sub["kernel"].astype(jnp.float32))
            sq_sum = sq_sum + jnp.sum(pb.astype(jnp.float32)
                                      * sub["bias"].astype(jnp.float32))

        lr_val = jnp.asarray(lr, jnp.float32)
        nu = jnp.minimum(
            1.0,
            jnp.sqrt(self.config.kl_clip
                     / jnp.maximum(lr_val ** 2 * jnp.abs(sq_sum), 1e-30)))
        for path, pre in pre_by_path.items():
            pre = jax.tree.map(lambda x: (x * nu).astype(x.dtype), pre)
            grads = _tree_set(grads, path, pre)
        return grads

    # -- one optimization step ---------------------------------------------

    def step(self, state: KFACState, stats: Any, grads: Any, lr) -> Tuple[
            KFACState, Any]:
        cfg = self.config
        count = state.count + 1

        do_factor = (state.count % cfg.factor_interval) == 0
        if self.factor_sync_freq > 1:
            # --kfac_factor_sync_freq: sync (reduce + EMA) the factor
            # statistics only every N steps — they are EMA-smoothed, so
            # off-steps skip the factor collectives entirely (with
            # bucketed stats the psums live INSIDE this cond's true
            # branch and genuinely don't execute). freq=1 compiles the
            # exact freq-free predicate (parity-pinned in tests).
            do_factor = jnp.logical_and(
                do_factor, (state.count % self.factor_sync_freq) == 0)
        reduce = self._reduce_stats if self.bucketed else (lambda s: s)
        factors = jax.lax.cond(
            do_factor,
            lambda f: self._update_factors(f, reduce(stats)),
            lambda f: f,
            state.factors)

        do_inv = (state.count % cfg.inv_interval) == 0
        inverses = jax.lax.cond(
            do_inv,
            lambda _: self._invert(factors),
            lambda inv: inv,
            state.inverses)

        grads = self.precondition(
            KFACState(factors=factors, inverses=inverses, count=count),
            grads, lr)
        # re-pin the carried state AFTER the lax.conds: the cond output's
        # sharding is whatever GSPMD merges from the two branches, and on
        # some mesh shapes (observed at data=4, fsdp=1) it resolves a
        # subset of sites to replicated — silently undoing the distributed
        # ownership the train step's output then stores. The constraint is
        # free when the merge already chose the owned layout.
        return KFACState(factors=self._constrain_stacked(factors),
                         inverses=self._constrain_stacked(inverses),
                         count=count), grads


def state_shardings(tree: Any, mesh, shard_axes=None) -> list:
    """Flat per-leaf placement list (jax.tree.leaves order) for a K-FAC
    factor/inverse tree: a NamedSharding splitting the leading
    stacked-layer axis where the rules table distributes ownership
    (parallel/rules.stacked_spec — leaves with a leading (L, d, d) stack
    whose L divides the shard count), None where the leaf stays
    replicated by design (2D pooler/NSP factors, scalars, non-divisible
    stacks). The ONE placement derivation shared by KFAC.init,
    KFAC._constrain_stacked, scripts/kfac_shard_audit.py's expectations,
    and tools/graphcheck.py's sharding_rules pass — the audit's former
    private rank>=3 heuristic retired into it."""
    from bert_pytorch_tpu.parallel import rules as rules_lib

    if shard_axes is None:
        shard_axes = rules_lib.KFAC_SHARD_AXES

    def one(x):
        if getattr(x, "ndim", 0) < 3:
            return None
        return rules_lib.stacked_spec(mesh, x.shape[0], shard_axes)

    return [one(x) for x in jax.tree.leaves(tree)]


TAP_SUFFIX = "_tap"
_LAYER_I_RE = re.compile(r"^layer_\d+$")


def _strip_tap(name: str) -> str:
    return name[:-len(TAP_SUFFIX)] if name.endswith(TAP_SUFFIX) else name


def _chol_inverse(mat: jax.Array) -> jax.Array:
    """Inverse of an SPD matrix via Cholesky (XLA-native; the reference
    needed MAGMA on GPU for this — README.md:181-187)."""
    chol = jnp.linalg.cholesky(mat)
    eye = jnp.eye(mat.shape[-1], dtype=mat.dtype)
    inv_l = jax.scipy.linalg.solve_triangular(chol, eye, lower=True)
    return inv_l.T @ inv_l


def _flatten_with_path(tree: Any):
    """[(path_tuple, site_dict)] for every {'A','G'} site."""
    out = []

    def walk(node, path):
        if isinstance(node, dict) and "A" in node and "G" in node:
            out.append((path, node))
            return
        if isinstance(node, dict):
            for k, v in node.items():
                walk(v, path + (k,))

    walk(tree, ())
    return out


def _tree_get(tree: Any, path: Tuple[str, ...]) -> Any:
    node = tree
    for k in path:
        node = node[k]
    return node


def _tree_set(tree: Any, path: Tuple[str, ...], value: Any) -> Any:
    """Non-mutating nested-dict set."""
    if not path:
        return value
    head, rest = path[0], path[1:]
    new = dict(tree)
    new[head] = _tree_set(tree[head], rest, value)
    return new
