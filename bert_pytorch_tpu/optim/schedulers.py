"""Warmup + decay learning-rate schedules.

Parity with the reference's scheduler zoo (src/schedulers.py:51-141 —
Cosine/Constant/Linear/Poly warmup) and the inline schedule formulas in
src/optimization.py:36-54. In JAX a schedule is a pure fn step->lr consumed by
optax; resume needs no state rewriting (the reference had to resync via
param_group['step'], schedulers.py:97-102,126-131) because the optimizer step
counter rides inside the optax state pytree and is checkpointed with it.

All schedules take `total_steps` and `warmup` (proportion, as the reference's
warmup_proportion) and optionally `offset` for two-phase resume: phase 2
passes offset=previous_phase_end_step so the schedule sees phase-local steps
(reference run_pretraining.py:288-299 rewrote optimizer hyperparams instead).
"""

from __future__ import annotations

import jax.numpy as jnp
import optax


def _phase(step, total_steps: int, warmup: float, offset: int):
    step = jnp.maximum(step - offset, 0).astype(jnp.float32)
    progress = step / float(max(total_steps, 1))
    warmup_steps = warmup * total_steps
    return step, progress, warmup_steps


def poly_warmup_schedule(base_lr: float, total_steps: int,
                         warmup: float = 0.01, degree: float = 0.5,
                         offset: int = 0) -> optax.Schedule:
    """Linear warmup then polynomial decay (1-progress)**degree; degree 0.5
    matches the reference's PolyWarmUpScheduler (src/schedulers.py:115-141)."""

    def schedule(step):
        step, progress, warmup_steps = _phase(step, total_steps, warmup, offset)
        warm = jnp.where(warmup_steps > 0, step / jnp.maximum(warmup_steps, 1e-9), 1.0)
        decay = (1.0 - jnp.clip(progress, 0.0, 1.0)) ** degree
        return base_lr * jnp.where(progress < warmup, warm, decay)

    return schedule


def linear_warmup_schedule(base_lr: float, total_steps: int,
                           warmup: float = 0.01, offset: int = 0
                           ) -> optax.Schedule:
    """Linear warmup then linear decay to 0 (src/schedulers.py:87-113)."""

    def schedule(step):
        step, progress, warmup_steps = _phase(step, total_steps, warmup, offset)
        warm = jnp.where(warmup_steps > 0, step / jnp.maximum(warmup_steps, 1e-9), 1.0)
        decay = jnp.maximum(1.0 - jnp.clip(progress, 0.0, 1.0), 0.0)
        return base_lr * jnp.where(progress < warmup, warm, decay)

    return schedule


def cosine_warmup_schedule(base_lr: float, total_steps: int,
                           warmup: float = 0.01, offset: int = 0
                           ) -> optax.Schedule:
    """Linear warmup then 0.5*(1+cos(pi*progress)) decay
    (src/schedulers.py:51-67; src/optimization.py:36-41)."""

    def schedule(step):
        step, progress, warmup_steps = _phase(step, total_steps, warmup, offset)
        warm = jnp.where(warmup_steps > 0, step / jnp.maximum(warmup_steps, 1e-9), 1.0)
        decay = 0.5 * (1.0 + jnp.cos(jnp.pi * jnp.clip(progress, 0.0, 1.0)))
        return base_lr * jnp.where(progress < warmup, warm, decay)

    return schedule


def constant_warmup_schedule(base_lr: float, total_steps: int,
                             warmup: float = 0.01, offset: int = 0
                             ) -> optax.Schedule:
    """Linear warmup then constant (src/schedulers.py:69-85)."""

    def schedule(step):
        step, progress, warmup_steps = _phase(step, total_steps, warmup, offset)
        warm = jnp.where(warmup_steps > 0, step / jnp.maximum(warmup_steps, 1e-9), 1.0)
        return base_lr * jnp.where(progress < warmup, warm, 1.0)

    return schedule


SCHEDULES = {
    "poly": poly_warmup_schedule,
    "linear": linear_warmup_schedule,
    "cosine": cosine_warmup_schedule,
    "constant": constant_warmup_schedule,
}


def make_schedule(name: str, base_lr: float, total_steps: int,
                  warmup: float = 0.01, offset: int = 0) -> optax.Schedule:
    """Factory keyed by the reference's lr_decay config value
    (run_pretraining.py lr_decay flag; SCHEDULES at optimization.py:57)."""
    if name not in SCHEDULES:
        raise ValueError(f"unknown schedule '{name}'; choose from {sorted(SCHEDULES)}")
    return SCHEDULES[name](base_lr, total_steps, warmup=warmup, offset=offset)
