"""Adam variants matching the reference's two finetuning optimizers.

- `bert_adam`: the reference's pure-python BertAdam (src/optimization.py:64-174)
  — Adam **without bias correction**, decoupled weight decay added to the
  update *before* the lr multiply, optional per-group grad-norm clip (the
  reference clips each param group to max_grad_norm=1.0 inside step()).
- `fused_adam`: apex FusedAdam as used by SQuAD/NER (run_squad.py:982-988 with
  bias_correction=False; run_ner.py:243-244) — AdamW-style decoupled decay,
  bias correction switchable.

Both are optax transforms so they compose with clipping/accumulation wrappers.
"""

from __future__ import annotations

from typing import Any, Callable, NamedTuple, Optional, Union

import jax
import jax.numpy as jnp
import optax


class AdamState(NamedTuple):
    count: jax.Array
    mu: Any
    nu: Any


def _adam_core(grads, state, b1, b2):
    mu = jax.tree.map(lambda m, g: b1 * m + (1 - b1) * g, state.mu, grads)
    nu = jax.tree.map(lambda v, g: b2 * v + (1 - b2) * jnp.square(g),
                      state.nu, grads)
    return mu, nu


def bert_adam(
    learning_rate: Union[float, optax.Schedule],
    b1: float = 0.9,
    b2: float = 0.999,
    eps: float = 1e-6,
    weight_decay: float = 0.01,
    weight_decay_mask: Optional[Callable[[Any], Any]] = None,
    max_grad_norm: Optional[float] = 1.0,
) -> optax.GradientTransformation:
    """BertAdam: no bias correction (reference notes this explicitly,
    src/optimization.py:64-76); update = m/(sqrt(v)+eps) + wd*p; global-norm
    clip approximates the reference's per-group clip (single group in
    practice)."""

    def init(params):
        zeros = lambda: jax.tree.map(jnp.zeros_like, params)
        return AdamState(count=jnp.zeros([], jnp.int32), mu=zeros(),
                         nu=zeros())

    def update(grads, state, params):
        if max_grad_norm is not None:
            # upcast leaves before the reduce: grads may arrive bf16 (see
            # lamb.py) and the sum of squares must accumulate in fp32
            gnorm = optax.global_norm(
                jax.tree.map(lambda g: g.astype(jnp.float32), grads))
            denom = jnp.maximum(1.0, gnorm / max_grad_norm)
            grads = jax.tree.map(lambda g: g / denom, grads)
        count = state.count + 1
        mu, nu = _adam_core(grads, state, b1, b2)

        if weight_decay_mask is not None:
            wd_tree = jax.tree.map(lambda use: weight_decay if use else 0.0,
                                   weight_decay_mask(params))
        else:
            wd_tree = jax.tree.map(lambda _: weight_decay, params)

        lr = learning_rate(count - 1) if callable(learning_rate) else learning_rate
        updates = jax.tree.map(
            lambda p, m, v, wd: (-lr * (m / (jnp.sqrt(v) + eps) + wd * p)
                                 ).astype(p.dtype),
            params, mu, nu, wd_tree)
        return updates, AdamState(count=count, mu=mu, nu=nu)

    return optax.GradientTransformation(init, update)


def fused_adam(
    learning_rate: Union[float, optax.Schedule],
    b1: float = 0.9,
    b2: float = 0.999,
    eps: float = 1e-8,
    weight_decay: float = 0.0,
    weight_decay_mask: Optional[Callable[[Any], Any]] = None,
    bias_correction: bool = False,
) -> optax.GradientTransformation:
    """apex-FusedAdam semantics (adam_w_mode decoupled decay); SQuAD/NER used
    bias_correction=False. weight_decay_mask(params)->bool tree supports the
    reference's two param groups (decay vs bias/LayerNorm, run_ner.py:231-241).
    """

    def init(params):
        zeros = lambda: jax.tree.map(jnp.zeros_like, params)
        return AdamState(count=jnp.zeros([], jnp.int32), mu=zeros(),
                         nu=zeros())

    def update(grads, state, params):
        count = state.count + 1
        cf = count.astype(jnp.float32)
        mu, nu = _adam_core(grads, state, b1, b2)
        if bias_correction:
            c1 = 1.0 - b1 ** cf
            c2 = 1.0 - b2 ** cf
        else:
            c1 = c2 = 1.0
        if weight_decay_mask is not None:
            wd_tree = jax.tree.map(lambda use: weight_decay if use else 0.0,
                                   weight_decay_mask(params))
        else:
            wd_tree = jax.tree.map(lambda _: weight_decay, params)
        lr = learning_rate(count - 1) if callable(learning_rate) else learning_rate
        updates = jax.tree.map(
            lambda p, m, v, wd: (-lr * ((m / c1) / (jnp.sqrt(v / c2) + eps)
                                        + wd * p)).astype(p.dtype),
            params, mu, nu, wd_tree)
        return updates, AdamState(count=count, mu=mu, nu=nu)

    return optax.GradientTransformation(init, update)
