// Native byte-level BPE batch encoder — the C++ fast path behind
// bert_pytorch_tpu.data.tokenization.get_bpe_tokenizer.
//
// Byte-identical to the Python spec (data/tokenization.py:
// ByteLevelBPETokenizer): same GPT-2 pre-tokenization scanner (contractions,
// unicode letter/number runs with optional leading space, whitespace runs),
// same bytes<->printable-unicode mapping, same lowest-rank-first merge loop.
// Character classes (isalpha/isnumeric/isspace) come from tables generated
// from the SAME Python unicodedata (gen_unicode_tables.py), so the two
// scanners agree by construction. The reference got byte-level BPE from the
// Rust `tokenizers` crate (reference src/tokenization.py:51-57,
// utils/build_vocab.py:39-58); this closes the last native-tokenizer gap.
//
// C ABI only (consumed via ctypes) — no pybind11 in this environment.

#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <string>
#include <thread>
#include <unordered_map>
#include <vector>

#include "unicode_tables.h"

namespace {

bool in_ranges(const CpRange* r, size_t n, uint32_t cp) {
  size_t lo = 0, hi = n;
  while (lo < hi) {
    size_t mid = (lo + hi) / 2;
    if (cp < r[mid].lo) {
      hi = mid;
    } else if (cp > r[mid].hi) {
      lo = mid + 1;
    } else {
      return true;
    }
  }
  return false;
}

const CpMapEntry* find_map(const CpMapEntry* m, size_t n, uint32_t cp) {
  size_t lo = 0, hi = n;
  while (lo < hi) {
    size_t mid = (lo + hi) / 2;
    if (cp < m[mid].cp) {
      hi = mid;
    } else if (cp > m[mid].cp) {
      lo = mid + 1;
    } else {
      return &m[mid];
    }
  }
  return nullptr;
}

inline bool is_alpha(uint32_t cp) { return in_ranges(kAlpha, kAlpha_len, cp); }
inline bool is_numeric(uint32_t cp) {
  return in_ranges(kNumeric, kNumeric_len, cp);
}
inline bool is_space(uint32_t cp) {
  return in_ranges(kPySpace, kPySpace_len, cp);
}

uint32_t next_cp(const char* s, size_t len, size_t& i) {
  unsigned char c = s[i];
  if (c < 0x80) {
    i += 1;
    return c;
  }
  if ((c >> 5) == 0x6 && i + 1 < len) {
    uint32_t cp = ((c & 0x1F) << 6) | (s[i + 1] & 0x3F);
    i += 2;
    return cp;
  }
  if ((c >> 4) == 0xE && i + 2 < len) {
    uint32_t cp = ((c & 0x0F) << 12) | ((s[i + 1] & 0x3F) << 6) |
                  (s[i + 2] & 0x3F);
    i += 3;
    return cp;
  }
  if ((c >> 3) == 0x1E && i + 3 < len) {
    uint32_t cp = ((c & 0x07) << 18) | ((s[i + 1] & 0x3F) << 12) |
                  ((s[i + 2] & 0x3F) << 6) | (s[i + 3] & 0x3F);
    i += 4;
    return cp;
  }
  i += 1;
  return 0xFFFD;
}

void append_utf8(std::string& out, uint32_t cp) {
  if (cp < 0x80) {
    out.push_back(static_cast<char>(cp));
  } else if (cp < 0x800) {
    out.push_back(static_cast<char>(0xC0 | (cp >> 6)));
    out.push_back(static_cast<char>(0x80 | (cp & 0x3F)));
  } else if (cp < 0x10000) {
    out.push_back(static_cast<char>(0xE0 | (cp >> 12)));
    out.push_back(static_cast<char>(0x80 | ((cp >> 6) & 0x3F)));
    out.push_back(static_cast<char>(0x80 | (cp & 0x3F)));
  } else {
    out.push_back(static_cast<char>(0xF0 | (cp >> 18)));
    out.push_back(static_cast<char>(0x80 | ((cp >> 12) & 0x3F)));
    out.push_back(static_cast<char>(0x80 | ((cp >> 6) & 0x3F)));
    out.push_back(static_cast<char>(0x80 | (cp & 0x3F)));
  }
}

// Python str.lower() of one codepoint via the generated kLower map.
void lower_cp(uint32_t cp, std::vector<uint32_t>& out) {
  const CpMapEntry* e = find_map(kLower, kLower_len, cp);
  if (e == nullptr) {
    out.push_back(cp);
  } else {
    for (uint16_t k = 0; k < e->len; ++k)
      out.push_back(kLower_pool[e->offset + k]);
  }
}

inline bool is_cased(uint32_t cp) { return in_ranges(kCased, kCased_len, cp); }
inline bool is_case_ignorable(uint32_t cp) {
  return in_ranges(kCaseIgnorable, kCaseIgnorable_len, cp);
}

// str.lower() of a whole codepoint sequence, including its one
// context-sensitive rule: Greek capital sigma (U+03A3) lowers to final
// sigma U+03C2 when preceded by a cased codepoint (skipping
// case-ignorables) and not followed by one (CPython handle_capital_sigma).
void lower_seq(const std::vector<uint32_t>& in, std::vector<uint32_t>& out) {
  for (size_t i = 0; i < in.size(); ++i) {
    if (in[i] == 0x03A3) {
      bool before_cased = false;
      for (size_t j = i; j-- > 0;) {
        if (is_case_ignorable(in[j])) continue;
        before_cased = is_cased(in[j]);
        break;
      }
      bool after_cased = false;
      for (size_t j = i + 1; j < in.size(); ++j) {
        if (is_case_ignorable(in[j])) continue;
        after_cased = is_cased(in[j]);
        break;
      }
      out.push_back(before_cased && !after_cased ? 0x03C2 : 0x03C3);
      continue;
    }
    lower_cp(in[i], out);
  }
}

struct PairHash {
  size_t operator()(const std::pair<std::string, std::string>& p) const {
    return std::hash<std::string>()(p.first) * 1000003 ^
           std::hash<std::string>()(p.second);
  }
};

struct Tokenizer {
  std::unordered_map<std::string, int32_t> vocab;
  std::unordered_map<std::pair<std::string, std::string>, int32_t, PairHash>
      ranks;
  std::string byte_enc[256];  // byte -> mapped unicode char (UTF-8)
  bool lowercase = false;
  bool add_prefix_space = true;
  int32_t unk_id = 0;
};

// GPT-2 bytes_to_unicode bijection (data/tokenization.py bytes_to_unicode).
void build_byte_encoder(Tokenizer& t) {
  bool direct[256] = {false};
  for (int b = int('!'); b <= int('~'); ++b) direct[b] = true;
  for (int b = 0xa1; b <= 0xac; ++b) direct[b] = true;
  for (int b = 0xae; b <= 0xff; ++b) direct[b] = true;
  int n = 0;
  for (int b = 0; b < 256; ++b) {
    uint32_t cp;
    if (direct[b]) {
      cp = static_cast<uint32_t>(b);
    } else {
      cp = 256 + n;
      ++n;
    }
    std::string s;
    append_utf8(s, cp);
    t.byte_enc[b] = s;
  }
}

const char* kContractions[] = {"'s", "'t", "'re", "'ve", "'m", "'ll", "'d"};

// The hand-rolled GPT-2 scanner from ByteLevelBPETokenizer._pretokenize,
// ported codepoint-for-codepoint. Operates on a decoded codepoint array;
// emits [start, end) codepoint index chunks.
void pretokenize(const std::vector<uint32_t>& cps,
                 std::vector<std::pair<size_t, size_t>>& chunks) {
  size_t i = 0, n = cps.size();
  while (i < n) {
    if (cps[i] == '\'') {
      bool matched = false;
      for (const char* c : kContractions) {
        size_t len = std::strlen(c);
        if (i + len <= n) {
          bool ok = true;
          for (size_t k = 0; k < len; ++k)
            if (cps[i + k] != static_cast<uint32_t>(c[k])) {
              ok = false;
              break;
            }
          if (ok) {
            chunks.emplace_back(i, i + len);
            i += len;
            matched = true;
            break;
          }
        }
      }
      if (matched) continue;
      size_t j = i + 1;
      while (j < n && !(is_space(cps[j]) || is_alpha(cps[j]) ||
                        is_numeric(cps[j])))
        ++j;
      chunks.emplace_back(i, j);
      i = j;
      continue;
    }
    size_t start = i;
    bool lead_space = false;
    if (cps[i] == ' ' && i + 1 < n && !is_space(cps[i + 1])) {
      lead_space = true;
      ++i;
    }
    if (i < n && is_alpha(cps[i])) {
      while (i < n && is_alpha(cps[i])) ++i;
    } else if (i < n && is_numeric(cps[i])) {
      while (i < n && is_numeric(cps[i])) ++i;
    } else if (i < n && is_space(cps[i])) {
      while (i < n && is_space(cps[i])) ++i;
    } else {
      while (i < n && !(is_space(cps[i]) || is_alpha(cps[i]) ||
                        is_numeric(cps[i]) || cps[i] == '\''))
        ++i;
      if (i == start + (lead_space ? 1u : 0u)) ++i;  // safety fallthrough
    }
    if (i > start) chunks.emplace_back(start, i);
  }
}

// Lowest-rank-first merge loop (ByteLevelBPETokenizer._bpe), with a
// per-thread cache keyed by the mapped token.
void bpe_merge(const Tokenizer& t, const std::string& token,
               std::unordered_map<std::string, std::vector<std::string>>&
                   cache,
               std::vector<std::string>& out) {
  auto hit = cache.find(token);
  if (hit != cache.end()) {
    out = hit->second;
    return;
  }
  std::vector<std::string> word;
  size_t i = 0;
  while (i < token.size()) {
    size_t j = i;
    next_cp(token.data(), token.size(), j);
    word.emplace_back(token.substr(i, j - i));
    i = j;
  }
  const int32_t kNoRank = INT32_MAX;
  while (word.size() > 1) {
    int32_t best_rank = kNoRank;
    size_t best_i = 0;
    for (size_t k = 0; k + 1 < word.size(); ++k) {
      auto it = t.ranks.find({word[k], word[k + 1]});
      if (it != t.ranks.end() && it->second < best_rank) {
        best_rank = it->second;
        best_i = k;
      }
    }
    if (best_rank == kNoRank) break;
    const std::string left = word[best_i], right = word[best_i + 1];
    std::vector<std::string> merged;
    merged.reserve(word.size());
    size_t k = 0;
    while (k < word.size()) {
      if (k + 1 < word.size() && word[k] == left && word[k + 1] == right) {
        merged.push_back(left + right);
        k += 2;
      } else {
        merged.push_back(word[k]);
        k += 1;
      }
    }
    word.swap(merged);
  }
  cache.emplace(token, word);
  out = word;
}

void encode_one(const Tokenizer& t, const char* text, size_t len,
                std::unordered_map<std::string, std::vector<std::string>>&
                    cache,
                std::vector<int32_t>& ids) {
  std::vector<uint32_t> cps;
  cps.reserve(len + 1);
  {
    std::vector<uint32_t> raw;
    raw.reserve(len);
    size_t i = 0;
    while (i < len) raw.push_back(next_cp(text, len, i));
    if (t.lowercase) {
      lower_seq(raw, cps);
    } else {
      cps = std::move(raw);
    }
  }
  if (t.add_prefix_space && !cps.empty() && cps[0] != ' ')
    cps.insert(cps.begin(), ' ');

  std::vector<std::pair<size_t, size_t>> chunks;
  pretokenize(cps, chunks);

  std::string chunk_utf8, mapped;
  std::vector<std::string> pieces;
  for (auto [a, b] : chunks) {
    // whitespace runs other than a single space collapse to " "
    bool all_space = true;
    for (size_t k = a; k < b; ++k)
      if (!is_space(cps[k])) {
        all_space = false;
        break;
      }
    chunk_utf8.clear();
    if (all_space && !(b - a == 1 && cps[a] == ' ')) {
      chunk_utf8 = " ";
    } else {
      for (size_t k = a; k < b; ++k) append_utf8(chunk_utf8, cps[k]);
    }
    mapped.clear();
    for (unsigned char byte : chunk_utf8) mapped += t.byte_enc[byte];
    pieces.clear();
    bpe_merge(t, mapped, cache, pieces);
    for (const std::string& p : pieces) {
      auto it = t.vocab.find(p);
      ids.push_back(it == t.vocab.end() ? t.unk_id : it->second);
    }
  }
}

}  // namespace

extern "C" {

// vocab_blob: '\n'-joined "id<TAB>token" lines (explicit ids — a filtered
// or hand-edited vocab.json may have gaps, which a positional format would
// silently remap). merges_blob: '\n'-joined "left right" pairs in rank
// order. unk_id: id for unknown pieces.
void* bpe_create(const char* vocab_blob, const char* merges_blob,
                 int32_t lowercase, int32_t add_prefix_space,
                 int32_t unk_id) {
  auto* t = new Tokenizer();
  t->lowercase = lowercase != 0;
  t->add_prefix_space = add_prefix_space != 0;
  t->unk_id = unk_id;
  build_byte_encoder(*t);
  {
    const char* p = vocab_blob;
    while (*p) {
      const char* nl = std::strchr(p, '\n');
      size_t len = nl ? static_cast<size_t>(nl - p) : std::strlen(p);
      std::string line(p, len);
      size_t tab = line.find('\t');
      if (tab != std::string::npos) {
        t->vocab.emplace(line.substr(tab + 1),
                         static_cast<int32_t>(
                             std::strtol(line.c_str(), nullptr, 10)));
      }
      if (!nl) break;
      p = nl + 1;
    }
  }
  {
    const char* p = merges_blob;
    int32_t rank = 0;
    while (*p) {
      const char* nl = std::strchr(p, '\n');
      size_t len = nl ? static_cast<size_t>(nl - p) : std::strlen(p);
      std::string line(p, len);
      size_t sp = line.find(' ');
      if (sp != std::string::npos) {
        t->ranks.emplace(
            std::make_pair(line.substr(0, sp), line.substr(sp + 1)), rank++);
      }
      if (!nl) break;
      p = nl + 1;
    }
  }
  return t;
}

void bpe_destroy(void* h) { delete static_cast<Tokenizer*>(h); }

// texts/text_lens: n UTF-8 strings with explicit byte lengths. Outputs:
// out_lens (n int32), out_ids (total int32); caller frees both via
// bpe_free. Returns 0 on success.
int32_t bpe_encode_batch(void* h, const char** texts,
                         const int64_t* text_lens, int32_t n,
                         int32_t nthreads, int32_t** out_lens,
                         int32_t** out_ids, int64_t* out_total) {
  const Tokenizer& t = *static_cast<Tokenizer*>(h);
  std::vector<std::vector<int32_t>> results(n);

  auto work = [&](int32_t lo, int32_t hi) {
    std::unordered_map<std::string, std::vector<std::string>> cache;
    for (int32_t k = lo; k < hi; ++k) {
      encode_one(t, texts[k], static_cast<size_t>(text_lens[k]), cache,
                 results[k]);
    }
  };
  if (nthreads <= 1 || n < 2) {
    work(0, n);
  } else {
    int32_t nt = nthreads < n ? nthreads : n;
    std::vector<std::thread> threads;
    int32_t chunk = (n + nt - 1) / nt;
    for (int32_t w = 0; w < nt; ++w) {
      int32_t lo = w * chunk;
      int32_t hi = lo + chunk < n ? lo + chunk : n;
      if (lo >= hi) break;
      threads.emplace_back(work, lo, hi);
    }
    for (auto& th : threads) th.join();
  }

  int64_t total = 0;
  for (auto& r : results) total += static_cast<int64_t>(r.size());
  // malloc(0) may legally return NULL; allocate at least one element
  int64_t alloc = total > 0 ? total : 1;
  *out_lens = static_cast<int32_t*>(malloc(sizeof(int32_t) * (n > 0 ? n : 1)));
  *out_ids = static_cast<int32_t*>(malloc(sizeof(int32_t) * alloc));
  if (!*out_lens || !*out_ids) {
    free(*out_lens);
    free(*out_ids);
    *out_lens = nullptr;
    *out_ids = nullptr;
    return 1;
  }
  int64_t off = 0;
  for (int32_t k = 0; k < n; ++k) {
    (*out_lens)[k] = static_cast<int32_t>(results[k].size());
    std::memcpy(*out_ids + off, results[k].data(),
                results[k].size() * sizeof(int32_t));
    off += static_cast<int64_t>(results[k].size());
  }
  *out_total = total;
  return 0;
}

void bpe_free(void* p) { free(p); }

}  // extern "C"
