"""Build the native WordPiece shared library.

Usage: python -m bert_pytorch_tpu.native.build
Also invoked lazily (once) by bert_pytorch_tpu.native when the library is
missing and a C++ toolchain is available. No pybind11 in this environment —
the library exposes a plain C ABI consumed via ctypes.
"""

from __future__ import annotations

import hashlib
import os
import shutil
import subprocess
import sys

HERE = os.path.dirname(os.path.abspath(__file__))
SRC = os.path.join(HERE, "wordpiece.cc")
HDR = os.path.join(HERE, "unicode_tables.h")
LIB = os.path.join(HERE, "_wordpiece.so")
STAMP = LIB + ".sha256"  # content hash of the sources the .so was built from


def _source_digest() -> str:
    h = hashlib.sha256()
    for path in (SRC, HDR):
        with open(path, "rb") as f:
            h.update(f.read())
    return h.hexdigest()


def build(force: bool = False) -> str:
    """Compile wordpiece.cc -> _wordpiece.so; returns the library path.

    Staleness is decided by CONTENT (sha256 of wordpiece.cc +
    unicode_tables.h recorded in a sidecar at build time), not mtime — a
    fresh checkout gives sources and any leftover binary identical mtimes,
    and a binary with no sidecar is treated as stale. Raises RuntimeError
    when no compiler is available or compilation fails."""
    digest = _source_digest()
    if os.path.exists(LIB) and not force:
        try:
            with open(STAMP) as f:
                if f.read().strip() == digest:
                    return LIB
        except OSError:
            pass  # no/unreadable stamp: rebuild
    cxx = os.environ.get("CXX") or shutil.which("g++") or shutil.which("c++")
    if not cxx:
        raise RuntimeError("no C++ compiler found (set CXX or install g++)")
    tmp = LIB + ".tmp.so"
    cmd = [cxx, "-O3", "-std=c++17", "-shared", "-fPIC", "-pthread",
           SRC, "-o", tmp]
    proc = subprocess.run(cmd, capture_output=True, text=True, timeout=600)
    if proc.returncode != 0:
        raise RuntimeError(
            f"native build failed ({' '.join(cmd)}):\n{proc.stderr[-4000:]}")
    os.replace(tmp, LIB)  # atomic: a crashed build never leaves a half .so
    with open(STAMP + ".tmp", "w") as f:
        f.write(digest + "\n")
    os.replace(STAMP + ".tmp", STAMP)
    return LIB


if __name__ == "__main__":
    print(build(force="--force" in sys.argv))
