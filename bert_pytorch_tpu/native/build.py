"""Build the native tokenizer shared libraries (WordPiece + byte-level BPE).

Usage: python -m bert_pytorch_tpu.native.build
Also invoked lazily (once) by bert_pytorch_tpu.native when a library is
missing and a C++ toolchain is available. No pybind11 in this environment —
the libraries expose a plain C ABI consumed via ctypes.
"""

from __future__ import annotations

import hashlib
import os
import shutil
import subprocess
import sys

HERE = os.path.dirname(os.path.abspath(__file__))
HDR = os.path.join(HERE, "unicode_tables.h")
TARGETS = {
    "wordpiece": (os.path.join(HERE, "wordpiece.cc"),
                  os.path.join(HERE, "_wordpiece.so")),
    "bpe": (os.path.join(HERE, "bpe.cc"), os.path.join(HERE, "_bpe.so")),
    "vocab_trainer": (os.path.join(HERE, "vocab_trainer.cc"),
                      os.path.join(HERE, "_vocab_trainer.so")),
}


def _source_digest(src: str) -> str:
    h = hashlib.sha256()
    for path in (src, HDR):
        with open(path, "rb") as f:
            h.update(f.read())
    return h.hexdigest()


def build(force: bool = False, target: str = "wordpiece") -> str:
    """Compile one target's .cc -> .so; returns the library path.

    Staleness is decided by CONTENT (sha256 of the source +
    unicode_tables.h recorded in a sidecar at build time), not mtime — a
    fresh checkout gives sources and any leftover binary identical mtimes,
    and a binary with no sidecar is treated as stale. Raises RuntimeError
    when no compiler is available or compilation fails."""
    src, lib = TARGETS[target]
    stamp = lib + ".sha256"
    digest = _source_digest(src)
    if os.path.exists(lib) and not force:
        try:
            with open(stamp) as f:
                if f.read().strip() == digest:
                    return lib
        except OSError:
            pass  # no/unreadable stamp: rebuild
    cxx = os.environ.get("CXX") or shutil.which("g++") or shutil.which("c++")
    if not cxx:
        raise RuntimeError("no C++ compiler found (set CXX or install g++)")
    # per-process tmp name: concurrent first-use builds (dataloader workers)
    # must not interleave writes into one tmp file — os.replace keeps the
    # install atomic, last writer wins with a complete library
    tmp = f"{lib}.tmp.{os.getpid()}.so"
    cmd = [cxx, "-O3", "-std=c++17", "-shared", "-fPIC", "-pthread",
           src, "-o", tmp]
    proc = subprocess.run(cmd, capture_output=True, text=True, timeout=600)
    if proc.returncode != 0:
        raise RuntimeError(
            f"native build failed ({' '.join(cmd)}):\n{proc.stderr[-4000:]}")
    os.replace(tmp, lib)  # atomic: a crashed build never leaves a half .so
    with open(stamp + ".tmp", "w") as f:
        f.write(digest + "\n")
    os.replace(stamp + ".tmp", stamp)
    return lib


if __name__ == "__main__":
    for name in TARGETS:
        print(build(force="--force" in sys.argv, target=name))
