"""Build the native WordPiece shared library.

Usage: python -m bert_pytorch_tpu.native.build
Also invoked lazily (once) by bert_pytorch_tpu.native when the library is
missing and a C++ toolchain is available. No pybind11 in this environment —
the library exposes a plain C ABI consumed via ctypes.
"""

from __future__ import annotations

import os
import shutil
import subprocess
import sys

HERE = os.path.dirname(os.path.abspath(__file__))
SRC = os.path.join(HERE, "wordpiece.cc")
LIB = os.path.join(HERE, "_wordpiece.so")


def build(force: bool = False) -> str:
    """Compile wordpiece.cc -> _wordpiece.so; returns the library path.
    Raises RuntimeError when no compiler is available or compilation fails."""
    if os.path.exists(LIB) and not force \
            and os.path.getmtime(LIB) >= os.path.getmtime(SRC):
        return LIB
    cxx = os.environ.get("CXX") or shutil.which("g++") or shutil.which("c++")
    if not cxx:
        raise RuntimeError("no C++ compiler found (set CXX or install g++)")
    tmp = LIB + ".tmp.so"
    cmd = [cxx, "-O3", "-std=c++17", "-shared", "-fPIC", "-pthread",
           SRC, "-o", tmp]
    proc = subprocess.run(cmd, capture_output=True, text=True, timeout=600)
    if proc.returncode != 0:
        raise RuntimeError(
            f"native build failed ({' '.join(cmd)}):\n{proc.stderr[-4000:]}")
    os.replace(tmp, LIB)  # atomic: a crashed build never leaves a half .so
    return LIB


if __name__ == "__main__":
    print(build(force="--force" in sys.argv))
