// Native vocabulary-trainer merge engine (WordPiece + byte-level BPE).
//
// The reference delegated vocab training to the HF tokenizers Rust trainers
// (utils/build_vocab.py:39-58); bert_pytorch_tpu/pipeline/vocab.py is the
// in-framework behavioral spec (pure Python). This module is the fast path
// for the spec's hot loop — greedy pair-merge selection — and is held to
// BITWISE-IDENTICAL selection order:
//   - scores are computed with the exact double-precision expression shape
//     the Python engine uses (left-to-right log sums, one final multiply),
//   - pair tiebreaks compare UTF-8 bytes (UTF-8 byte order == code-point
//     order, which is Python's str comparison),
//   - the WordPiece "-len(merged)" tiebreak counts CODE POINTS, as Python
//     len() does.
// Unicode normalization / pre-tokenization stays in Python (count_words);
// the boundary passes symbol sequences, so this file needs no unicode
// tables. Parity is enforced by tests/test_vocab_trainer.py against the
// Python engine on identical inputs.
//
// C ABI (ctypes, no pybind11 in this environment):
//   vt_train(words_tsv, len, init_vocab, len, vocab_size, wordpiece_mode,
//            min_pair_frequency, &out, &out_len) -> 0/-1
//     words_tsv:  "freq\tsym sym sym...\n" per (deduplicated) word
//     init_vocab: "token\n" per initial vocab entry (specials + alphabet),
//                 in final order
//     out: wordpiece -> "V\ttoken\n" lines (merged tokens appended in
//          selection order); bpe -> "M\ta b\n" merge lines interleaved with
//          "V\ttoken\n" for tokens that entered the vocab. The caller
//          replays these onto its initial vocab.
//   vt_free(ptr)

#include <cmath>
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <string>
#include <unordered_map>
#include <unordered_set>
#include <vector>

namespace {

using std::string;
using std::vector;

struct PairHash {
  size_t operator()(const std::pair<int, int>& p) const {
    return (static_cast<size_t>(p.first) << 32) ^
           static_cast<uint32_t>(p.second);
  }
};

// log(n) memo for integer arguments: counts/totals are exact ints and
// repeat heavily across the scan; the memo turns ~4 libm calls per
// candidate per iteration into table lookups. std::log(double) == libm log
// == what CPython's math.log calls, so memoization cannot change bits.
struct LogMemo {
  vector<double> small;  // n < 1<<20
  std::unordered_map<int64_t, double> big;
  LogMemo() : small(1 << 20, -1.0) {}
  double operator()(int64_t n) {
    if (n > 0 && n < (1 << 20)) {
      double& v = small[n];
      if (v < 0) v = std::log(static_cast<double>(n));
      return v;
    }
    auto it = big.find(n);
    if (it != big.end()) return it->second;
    double v = std::log(static_cast<double>(n));
    big.emplace(n, v);
    return v;
  }
};

int utf8_codepoints(const string& s) {
  int n = 0;
  for (unsigned char c : s)
    if ((c & 0xC0) != 0x80) n++;
  return n;
}

struct Engine {
  vector<string> sym_names;                       // id -> symbol text
  std::unordered_map<string, int> sym_ids;
  vector<vector<int>> words;                      // symbol ids per word
  vector<int64_t> freqs;
  std::unordered_map<std::pair<int, int>, int64_t, PairHash> pairs;
  std::unordered_map<std::pair<int, int>, std::unordered_set<int>, PairHash>
      index;
  vector<int64_t> singles;                        // per symbol id
  int64_t total_singles = 0;

  int intern(const string& s) {
    auto it = sym_ids.find(s);
    if (it != sym_ids.end()) return it->second;
    int id = static_cast<int>(sym_names.size());
    sym_names.push_back(s);
    sym_ids.emplace(s, id);
    singles.push_back(0);
    return id;
  }

  void add_word(int idx) {
    const auto& syms = words[idx];
    int64_t f = freqs[idx];
    for (int s : syms) {
      singles[s] += f;
      total_singles += f;
    }
    for (size_t i = 0; i + 1 < syms.size(); ++i) {
      auto p = std::make_pair(syms[i], syms[i + 1]);
      pairs[p] += f;
      index[p].insert(idx);
    }
  }

  void remove_word(int idx) {
    const auto& syms = words[idx];
    int64_t f = freqs[idx];
    for (int s : syms) {
      singles[s] -= f;
      total_singles -= f;
    }
    for (size_t i = 0; i + 1 < syms.size(); ++i) {
      auto p = std::make_pair(syms[i], syms[i + 1]);
      auto it = pairs.find(p);
      if (it != pairs.end()) {
        it->second -= f;
        if (it->second <= 0) {
          pairs.erase(it);
          index.erase(p);
        } else {
          auto ix = index.find(p);
          if (ix != index.end()) ix->second.erase(idx);
        }
      }
    }
  }

  void merge(std::pair<int, int> best, int merged_id) {
    auto ix = index.find(best);
    if (ix != index.end()) {
      // copy: remove_word/add_word mutate the index sets
      vector<int> touched(ix->second.begin(), ix->second.end());
      for (int idx : touched) {
        remove_word(idx);
        auto& syms = words[idx];
        vector<int> merged;
        merged.reserve(syms.size());
        size_t i = 0;
        while (i < syms.size()) {
          if (i + 1 < syms.size() && syms[i] == best.first &&
              syms[i + 1] == best.second) {
            merged.push_back(merged_id);
            i += 2;
          } else {
            merged.push_back(syms[i]);
            i += 1;
          }
        }
        syms = std::move(merged);
        add_word(idx);
      }
    }
    // self-overlap residue: the merged pair must never be selected again
    pairs.erase(best);
    index.erase(best);
  }
};

// Python-tuple-comparison tiebreak on (sym_a, sym_b) as strings: byte-wise
// compare == code-point compare for UTF-8. Returns true when p > q.
bool pair_greater(const Engine& e, std::pair<int, int> p,
                  std::pair<int, int> q) {
  int c = e.sym_names[p.first].compare(e.sym_names[q.first]);
  if (c != 0) return c > 0;
  return e.sym_names[p.second].compare(e.sym_names[q.second]) > 0;
}

string wp_merged_name(const Engine& e, std::pair<int, int> p) {
  const string& a = e.sym_names[p.first];
  const string& b = e.sym_names[p.second];
  if (b.size() >= 2 && b[0] == '#' && b[1] == '#') return a + b.substr(2);
  return a + b;
}

}  // namespace

extern "C" {

int vt_train(const char* words_tsv, size_t words_len, const char* init_vocab,
             size_t init_len, int vocab_size, int wordpiece_mode,
             long min_pair_frequency, char** out_buf, size_t* out_len) {
  Engine e;
  // parse words: "freq\tsym sym ...\n"
  {
    const char* p = words_tsv;
    const char* end = words_tsv + words_len;
    while (p < end) {
      const char* nl = static_cast<const char*>(
          memchr(p, '\n', static_cast<size_t>(end - p)));
      if (!nl) nl = end;
      const char* tab = static_cast<const char*>(
          memchr(p, '\t', static_cast<size_t>(nl - p)));
      if (tab) {
        int64_t f = strtoll(p, nullptr, 10);
        vector<int> syms;
        const char* s = tab + 1;
        while (s < nl) {
          const char* sp = static_cast<const char*>(
              memchr(s, ' ', static_cast<size_t>(nl - s)));
          if (!sp) sp = nl;
          if (sp > s)
            syms.push_back(
                e.intern(string(s, static_cast<size_t>(sp - s))));
          s = sp + 1;
        }
        if (!syms.empty() && f > 0) {
          int idx = static_cast<int>(e.words.size());
          e.words.push_back(std::move(syms));
          e.freqs.push_back(f);
          e.add_word(idx);
        }
      }
      p = nl + 1;
    }
  }

  // seen-set seeded with the caller's initial vocab (specials + alphabet)
  std::unordered_set<string> seen;
  int cur_vocab = 0;
  {
    const char* p = init_vocab;
    const char* end = init_vocab + init_len;
    while (p < end) {
      const char* nl = static_cast<const char*>(
          memchr(p, '\n', static_cast<size_t>(end - p)));
      if (!nl) nl = end;
      if (nl > p) {
        if (seen.insert(string(p, static_cast<size_t>(nl - p))).second)
          cur_vocab++;
      }
      p = nl + 1;
    }
  }

  LogMemo lg;
  string out;
  out.reserve(1 << 20);

  while (cur_vocab < vocab_size) {
    bool have = false;
    std::pair<int, int> best{0, 0};
    double best_score = 0.0;
    int64_t best_count = 0;
    int best_len = 0;
    if (wordpiece_mode) {
      double log_total = lg(e.total_singles);
      for (const auto& kv : e.pairs) {
        int64_t c = kv.second;
        if (c < min_pair_frequency) continue;
        // EXACT Python expression shape:
        // c * (log(c) + log(total) - log(sa) - log(sb))
        double score =
            static_cast<double>(c) *
            (((lg(c) + log_total) - lg(e.singles[kv.first.first])) -
             lg(e.singles[kv.first.second]));
        int mlen = 0;
        if (have) {
          if (score < best_score) continue;
          if (score == best_score) {
            // tiebreak: larger -len(merged) i.e. SHORTER merged wins;
            // then lexicographically greater pair
            mlen = utf8_codepoints(wp_merged_name(e, kv.first));
            if (mlen > best_len) continue;
            if (mlen == best_len && !pair_greater(e, kv.first, best))
              continue;
          }
        }
        if (mlen == 0) mlen = utf8_codepoints(wp_merged_name(e, kv.first));
        best = kv.first;
        best_score = score;
        best_len = mlen;
        have = true;
      }
    } else {
      for (const auto& kv : e.pairs) {
        int64_t c = kv.second;
        if (have) {
          if (c < best_count) continue;
          if (c == best_count && !pair_greater(e, kv.first, best)) continue;
        }
        best = kv.first;
        best_count = c;
        have = true;
      }
    }
    if (!have) break;

    string new_symbol = wordpiece_mode
                            ? wp_merged_name(e, best)
                            : e.sym_names[best.first] + e.sym_names[best.second];
    if (!wordpiece_mode) {
      out += "M\t";
      out += e.sym_names[best.first];
      out += ' ';
      out += e.sym_names[best.second];
      out += '\n';
    }
    int merged_id = e.intern(new_symbol);
    e.merge(best, merged_id);
    if (seen.insert(new_symbol).second) {
      out += "V\t";
      out += new_symbol;
      out += '\n';
      cur_vocab++;
    }
  }

  char* buf = static_cast<char*>(malloc(out.size() + 1));
  if (!buf) return -1;
  memcpy(buf, out.data(), out.size());
  buf[out.size()] = '\0';
  *out_buf = buf;
  *out_len = out.size();
  return 0;
}

void vt_free(void* p) { free(p); }

}  // extern "C"
