// Native WordPiece batch encoder — the C++ fast path behind
// bert_pytorch_tpu.data.tokenization.get_wordpiece_tokenizer.
//
// Byte-identical to the Python spec (data/tokenization.py:
// BertWordPieceTokenizer.encode/_words_with_offsets + WordpieceTokenizer):
// same pre-tokenization walk, same normalization (lowercase + NFD-minus-Mn
// via tables generated from the SAME Python unicodedata), same greedy
// longest-match, same (start, end) codepoint spans into the original text.
// The reference got this throughput from the Rust `tokenizers` crate
// (reference src/tokenization.py:42-57, utils/encode_data.py:280); here the
// offline-encode hot loop is plain C++ + std::thread over the batch.
//
// C ABI only (consumed via ctypes) — no pybind11 in this environment.

#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <string>
#include <thread>
#include <unordered_map>
#include <vector>

#include "unicode_tables.h"

namespace {

bool in_ranges(const CpRange* r, size_t n, uint32_t cp) {
  size_t lo = 0, hi = n;
  while (lo < hi) {
    size_t mid = (lo + hi) / 2;
    if (cp < r[mid].lo) {
      hi = mid;
    } else if (cp > r[mid].hi) {
      lo = mid + 1;
    } else {
      return true;
    }
  }
  return false;
}

const CpMapEntry* find_map(const CpMapEntry* m, size_t n, uint32_t cp) {
  size_t lo = 0, hi = n;
  while (lo < hi) {
    size_t mid = (lo + hi) / 2;
    if (cp < m[mid].cp) {
      hi = mid;
    } else if (cp > m[mid].cp) {
      lo = mid + 1;
    } else {
      return &m[mid];
    }
  }
  return nullptr;
}

inline bool is_whitespace(uint32_t cp) {
  return in_ranges(kWhitespace, kWhitespace_len, cp);
}
inline bool is_control(uint32_t cp) {
  return in_ranges(kControl, kControl_len, cp);
}
inline bool is_punct(uint32_t cp) { return in_ranges(kPunct, kPunct_len, cp); }
inline bool is_mn(uint32_t cp) { return in_ranges(kMn, kMn_len, cp); }

inline bool is_cjk(uint32_t cp) {
  return (cp >= 0x4E00 && cp <= 0x9FFF) || (cp >= 0x3400 && cp <= 0x4DBF) ||
         (cp >= 0x20000 && cp <= 0x2A6DF) || (cp >= 0x2A700 && cp <= 0x2B73F) ||
         (cp >= 0x2B740 && cp <= 0x2B81F) || (cp >= 0x2B820 && cp <= 0x2CEAF) ||
         (cp >= 0xF900 && cp <= 0xFAFF) || (cp >= 0x2F800 && cp <= 0x2FA1F);
}

// Decode one UTF-8 codepoint at s[i]; advances i. Invalid bytes decode as
// 0xFFFD and advance one byte (matches Python's handling of already-decoded
// str input: the wrapper passes well-formed UTF-8, so this is a safety net).
uint32_t next_cp(const char* s, size_t len, size_t& i) {
  unsigned char c = s[i];
  if (c < 0x80) {
    i += 1;
    return c;
  }
  if ((c >> 5) == 0x6 && i + 1 < len) {
    uint32_t cp = ((c & 0x1F) << 6) | (s[i + 1] & 0x3F);
    i += 2;
    return cp;
  }
  if ((c >> 4) == 0xE && i + 2 < len) {
    uint32_t cp = ((c & 0x0F) << 12) | ((s[i + 1] & 0x3F) << 6) |
                  (s[i + 2] & 0x3F);
    i += 3;
    return cp;
  }
  if ((c >> 3) == 0x1E && i + 3 < len) {
    uint32_t cp = ((c & 0x07) << 18) | ((s[i + 1] & 0x3F) << 12) |
                  ((s[i + 2] & 0x3F) << 6) | (s[i + 3] & 0x3F);
    i += 4;
    return cp;
  }
  i += 1;
  return 0xFFFD;
}

void append_utf8(std::string& out, uint32_t cp) {
  if (cp < 0x80) {
    out.push_back(static_cast<char>(cp));
  } else if (cp < 0x800) {
    out.push_back(static_cast<char>(0xC0 | (cp >> 6)));
    out.push_back(static_cast<char>(0x80 | (cp & 0x3F)));
  } else if (cp < 0x10000) {
    out.push_back(static_cast<char>(0xE0 | (cp >> 12)));
    out.push_back(static_cast<char>(0x80 | ((cp >> 6) & 0x3F)));
    out.push_back(static_cast<char>(0x80 | (cp & 0x3F)));
  } else {
    out.push_back(static_cast<char>(0xF0 | (cp >> 18)));
    out.push_back(static_cast<char>(0x80 | ((cp >> 12) & 0x3F)));
    out.push_back(static_cast<char>(0x80 | ((cp >> 6) & 0x3F)));
    out.push_back(static_cast<char>(0x80 | (cp & 0x3F)));
  }
}

// lower() then NFD then drop Mn — the Python _norm() pipeline. Returns the
// normalized word as a codepoint sequence (wordpiece slices by codepoint).
void normalize(const std::vector<uint32_t>& word, bool lowercase,
               std::vector<uint32_t>& out) {
  out.clear();
  std::vector<uint32_t> lowered;
  const std::vector<uint32_t>* src = &word;
  if (lowercase) {
    lowered.reserve(word.size());
    for (uint32_t cp : word) {
      const CpMapEntry* e = find_map(kLower, kLower_len, cp);
      if (e) {
        for (uint16_t k = 0; k < e->len; ++k)
          lowered.push_back(kLower_pool[e->offset + k]);
      } else {
        lowered.push_back(cp);
      }
    }
    src = &lowered;
    // NFD + drop Mn (strip_accents) runs only in lowercase mode, matching
    // BasicTokenizer.tokenize / BertWordPieceTokenizer._norm
    for (uint32_t cp : *src) {
      const CpMapEntry* e = find_map(kNFD, kNFD_len, cp);
      if (e) {
        for (uint16_t k = 0; k < e->len; ++k) {
          uint32_t d = kNFD_pool[e->offset + k];
          if (!is_mn(d)) out.push_back(d);
        }
      } else if (!is_mn(cp)) {
        out.push_back(cp);
      }
    }
  } else {
    out = word;
  }
}

struct Tokenizer {
  std::unordered_map<std::string, int32_t> vocab;
  bool lowercase = true;
  int32_t unk_id = 0;
  int32_t cls_id = -1;
  int32_t sep_id = -1;
  size_t max_chars_per_word = 200;
};

struct TextResult {
  std::vector<int32_t> ids;
  std::vector<int32_t> type_ids;
  std::vector<int32_t> starts;
  std::vector<int32_t> ends;
};

// Greedy longest-match-first over '##' continuations
// (WordpieceTokenizer._split_word). cps = normalized word.
// Appends token ids, or unk_id when the word cannot be split.
void wordpiece(const Tokenizer& t, const std::vector<uint32_t>& cps,
               std::vector<int32_t>& out_ids) {
  if (cps.size() > t.max_chars_per_word) {
    out_ids.push_back(t.unk_id);
    return;
  }
  // byte offsets of each codepoint in the utf8 rendering
  std::string utf8;
  std::vector<size_t> byte_at;
  byte_at.reserve(cps.size() + 1);
  for (uint32_t cp : cps) {
    byte_at.push_back(utf8.size());
    append_utf8(utf8, cp);
  }
  byte_at.push_back(utf8.size());

  std::vector<int32_t> pieces;
  size_t start = 0;
  std::string cand;
  while (start < cps.size()) {
    size_t end = cps.size();
    int32_t match = -1;
    while (start < end) {
      cand.clear();
      if (start > 0) cand = "##";
      cand.append(utf8, byte_at[start], byte_at[end] - byte_at[start]);
      auto it = t.vocab.find(cand);
      if (it != t.vocab.end()) {
        match = it->second;
        break;
      }
      --end;
    }
    if (match < 0) {
      out_ids.push_back(t.unk_id);
      return;
    }
    pieces.push_back(match);
    start = end;
  }
  out_ids.insert(out_ids.end(), pieces.begin(), pieces.end());
}

// _words_with_offsets + wordpiece + framing for one sequence; appends into r.
void encode_sequence(const Tokenizer& t, const char* text, size_t len,
                     int32_t type_id, TextResult& r) {
  size_t i = 0;      // byte cursor
  size_t cp_idx = 0; // codepoint cursor (Python str indices)
  std::vector<uint32_t> word;
  std::vector<uint32_t> norm;
  std::vector<int32_t> word_ids;
  while (i < len) {
    size_t save_i = i;
    uint32_t cp = next_cp(text, len, i);
    if (is_whitespace(cp) || is_control(cp) || cp == 0 || cp == 0xFFFD) {
      ++cp_idx;
      continue;
    }
    size_t start_cp = cp_idx;
    word.clear();
    if (is_punct(cp) || is_cjk(cp)) {
      word.push_back(cp);
      ++cp_idx;
    } else {
      // word run: scan until whitespace/control/punct/CJK
      word.push_back(cp);
      ++cp_idx;
      while (i < len) {
        size_t peek_i = i;
        uint32_t nxt = next_cp(text, len, peek_i);
        if (is_whitespace(nxt) || is_control(nxt) || is_punct(nxt) ||
            is_cjk(nxt))
          break;
        word.push_back(nxt);
        i = peek_i;
        ++cp_idx;
      }
    }
    (void)save_i;
    normalize(word, t.lowercase, norm);
    if (norm.empty()) continue;  // e.g. pure combining marks
    word_ids.clear();
    wordpiece(t, norm, word_ids);
    for (int32_t id : word_ids) {
      r.ids.push_back(id);
      r.type_ids.push_back(type_id);
      r.starts.push_back(static_cast<int32_t>(start_cp));
      r.ends.push_back(static_cast<int32_t>(cp_idx));
    }
  }
}

void encode_one(const Tokenizer& t, const char* text, size_t text_len,
                const char* pair, size_t pair_len, bool add_special,
                TextResult& r) {
  if (add_special) {
    r.ids.push_back(t.cls_id);
    r.type_ids.push_back(0);
    r.starts.push_back(0);
    r.ends.push_back(0);
  }
  encode_sequence(t, text, text_len, 0, r);
  if (add_special) {
    r.ids.push_back(t.sep_id);
    r.type_ids.push_back(0);
    r.starts.push_back(0);
    r.ends.push_back(0);
  }
  if (pair != nullptr) {
    encode_sequence(t, pair, pair_len, 1, r);
    if (add_special) {
      r.ids.push_back(t.sep_id);
      r.type_ids.push_back(1);
      r.starts.push_back(0);
      r.ends.push_back(0);
    }
  }
}

}  // namespace

extern "C" {

// vocab_text: '\n'-joined tokens in id order (same contract as vocab files;
// tokens are stripped by the Python loader before the call).
void* wp_create(const char* vocab_text, int32_t lowercase) {
  auto* t = new Tokenizer();
  t->lowercase = lowercase != 0;
  const char* p = vocab_text;
  int32_t id = 0;
  while (*p) {
    const char* nl = std::strchr(p, '\n');
    size_t n = nl ? static_cast<size_t>(nl - p) : std::strlen(p);
    // operator[] so a duplicated token keeps the LAST id, matching the
    // Python load_vocab dict assignment semantics
    t->vocab[std::string(p, n)] = id++;
    if (!nl) break;
    p = nl + 1;
  }
  auto unk = t->vocab.find("[UNK]");
  t->unk_id = unk == t->vocab.end() ? 0 : unk->second;
  auto cls = t->vocab.find("[CLS]");
  t->cls_id = cls == t->vocab.end() ? -1 : cls->second;
  auto sep = t->vocab.find("[SEP]");
  t->sep_id = sep == t->vocab.end() ? -1 : sep->second;
  return t;
}

void wp_destroy(void* h) { delete static_cast<Tokenizer*>(h); }

// Encode n texts (pairs[i] may be NULL; pairs itself may be NULL).
// Outputs are malloc'd flat arrays; *out_lens has n entries, the others
// sum(lens). Returns 0 on success. Caller frees each with wp_free().
// text_lens/pair_lens: explicit byte lengths (texts may contain NUL bytes,
// which the spec skips but must not truncate at).
int32_t wp_encode_batch(void* h, const char** texts, const int64_t* text_lens,
                        const char** pairs, const int64_t* pair_lens,
                        int32_t n, int32_t add_special, int32_t nthreads,
                        int32_t** out_lens, int32_t** out_ids,
                        int32_t** out_type_ids, int32_t** out_starts,
                        int32_t** out_ends, int64_t* out_total) {
  const Tokenizer& t = *static_cast<Tokenizer*>(h);
  std::vector<TextResult> results(n);

  auto work = [&](int32_t lo, int32_t hi) {
    for (int32_t k = lo; k < hi; ++k) {
      encode_one(t, texts[k], static_cast<size_t>(text_lens[k]),
                 pairs ? pairs[k] : nullptr,
                 pairs && pairs[k] ? static_cast<size_t>(pair_lens[k]) : 0,
                 add_special != 0, results[k]);
    }
  };
  if (nthreads <= 1 || n < 2) {
    work(0, n);
  } else {
    int32_t nt = nthreads < n ? nthreads : n;
    std::vector<std::thread> threads;
    int32_t chunk = (n + nt - 1) / nt;
    for (int32_t w = 0; w < nt; ++w) {
      int32_t lo = w * chunk;
      int32_t hi = lo + chunk < n ? lo + chunk : n;
      if (lo >= hi) break;
      threads.emplace_back(work, lo, hi);
    }
    for (auto& th : threads) th.join();
  }

  int64_t total = 0;
  for (auto& r : results) total += static_cast<int64_t>(r.ids.size());
  // malloc(0) may legally return NULL (non-glibc); allocate at least one
  // element so an all-empty batch is distinguishable from allocation failure
  int64_t alloc = total > 0 ? total : 1;
  *out_lens = static_cast<int32_t*>(malloc(sizeof(int32_t) * (n > 0 ? n : 1)));
  *out_ids = static_cast<int32_t*>(malloc(sizeof(int32_t) * alloc));
  *out_type_ids = static_cast<int32_t*>(malloc(sizeof(int32_t) * alloc));
  *out_starts = static_cast<int32_t*>(malloc(sizeof(int32_t) * alloc));
  *out_ends = static_cast<int32_t*>(malloc(sizeof(int32_t) * alloc));
  if (!*out_lens || !*out_ids || !*out_type_ids || !*out_starts ||
      !*out_ends) {
    // free the ones that did succeed — the caller sees rc!=0 and never calls
    // wp_free on any output
    int32_t** outs[] = {out_lens, out_ids, out_type_ids, out_starts,
                        out_ends};
    for (auto o : outs) {
      free(*o);
      *o = nullptr;
    }
    return 1;
  }
  int64_t off = 0;
  for (int32_t k = 0; k < n; ++k) {
    const TextResult& r = results[k];
    (*out_lens)[k] = static_cast<int32_t>(r.ids.size());
    std::memcpy(*out_ids + off, r.ids.data(), r.ids.size() * 4);
    std::memcpy(*out_type_ids + off, r.type_ids.data(), r.ids.size() * 4);
    std::memcpy(*out_starts + off, r.starts.data(), r.ids.size() * 4);
    std::memcpy(*out_ends + off, r.ends.data(), r.ids.size() * 4);
    off += static_cast<int64_t>(r.ids.size());
  }
  *out_total = total;
  return 0;
}

void wp_free(void* p) { free(p); }

}  // extern "C"
