"""bert_pytorch_tpu.native — C++ fast paths behind the Python behavioral
specs (SURVEY §2.3#7: the reference's encode throughput came from the Rust
`tokenizers` crate; this framework's comes from here).

- NativeWordPieceTokenizer: batch-parallel WordPiece encoder byte-identical
  to data/tokenization.BertWordPieceTokenizer (parity-tested in
  tests/test_native_tokenizer.py).
- NativeByteLevelBPETokenizer: batch-parallel byte-level BPE encoder
  id-identical to data/tokenization.ByteLevelBPETokenizer (parity-tested in
  tests/test_native_bpe.py).

Each shared library builds on demand from its .cc the first time it is
requested (python -m bert_pytorch_tpu.native.build to prebuild both).
"""

from __future__ import annotations

import ctypes
import os
from typing import Dict, List, Optional, Sequence

from bert_pytorch_tpu.data.tokenization import (
    BertWordPieceTokenizer,
    ByteLevelBPETokenizer,
    Encoding,
)

I32P = ctypes.POINTER(ctypes.c_int32)


def _configure_wp(lib):
    lib.wp_create.restype = ctypes.c_void_p
    lib.wp_create.argtypes = [ctypes.c_char_p, ctypes.c_int32]
    lib.wp_destroy.argtypes = [ctypes.c_void_p]
    lib.wp_encode_batch.restype = ctypes.c_int32
    lib.wp_encode_batch.argtypes = [
        ctypes.c_void_p,
        ctypes.POINTER(ctypes.c_char_p), ctypes.POINTER(ctypes.c_int64),
        ctypes.POINTER(ctypes.c_char_p), ctypes.POINTER(ctypes.c_int64),
        ctypes.c_int32, ctypes.c_int32, ctypes.c_int32,
        ctypes.POINTER(I32P), ctypes.POINTER(I32P), ctypes.POINTER(I32P),
        ctypes.POINTER(I32P), ctypes.POINTER(I32P),
        ctypes.POINTER(ctypes.c_int64),
    ]
    lib.wp_free.argtypes = [ctypes.c_void_p]


def _configure_bpe(lib):
    lib.bpe_create.restype = ctypes.c_void_p
    lib.bpe_create.argtypes = [ctypes.c_char_p, ctypes.c_char_p,
                               ctypes.c_int32, ctypes.c_int32,
                               ctypes.c_int32]
    lib.bpe_destroy.argtypes = [ctypes.c_void_p]
    lib.bpe_encode_batch.restype = ctypes.c_int32
    lib.bpe_encode_batch.argtypes = [
        ctypes.c_void_p,
        ctypes.POINTER(ctypes.c_char_p), ctypes.POINTER(ctypes.c_int64),
        ctypes.c_int32, ctypes.c_int32,
        ctypes.POINTER(I32P), ctypes.POINTER(I32P),
        ctypes.POINTER(ctypes.c_int64),
    ]
    lib.bpe_free.argtypes = [ctypes.c_void_p]


def _configure_vt(lib):
    lib.vt_train.restype = ctypes.c_int32
    lib.vt_train.argtypes = [
        ctypes.c_char_p, ctypes.c_size_t,
        ctypes.c_char_p, ctypes.c_size_t,
        ctypes.c_int32, ctypes.c_int32, ctypes.c_long,
        ctypes.POINTER(ctypes.c_void_p), ctypes.POINTER(ctypes.c_size_t),
    ]
    lib.vt_free.argtypes = [ctypes.c_void_p]


# target -> {lib, error} lazy-load cache
_libs: Dict[str, Dict[str, object]] = {}
_CONFIGURE = {"wordpiece": _configure_wp, "bpe": _configure_bpe,
              "vocab_trainer": _configure_vt}


def _load_lib(target: str):
    state = _libs.setdefault(target, {})
    if "lib" in state or "error" in state:
        return state.get("lib")
    try:
        from bert_pytorch_tpu.native.build import build

        lib = ctypes.CDLL(build(target=target))
        _CONFIGURE[target](lib)
        state["lib"] = lib
    except Exception as e:  # noqa: BLE001 — any failure = no native path
        state["error"] = str(e)
    return state.get("lib")


def _load_error(target: str) -> Optional[str]:
    return _libs.get(target, {}).get("error")


def native_available() -> bool:
    """True when the C++ WordPiece library is built (or buildable now)."""
    return _load_lib("wordpiece") is not None


def native_bpe_available() -> bool:
    """True when the C++ BPE library is built (or buildable right now)."""
    return _load_lib("bpe") is not None


class NativeWordPieceTokenizer(BertWordPieceTokenizer):
    """Drop-in BertWordPieceTokenizer whose encode()/encode_batch() run in
    C++ (same results; the batch path releases the GIL and threads across
    texts). Everything else — tokenize(), token_to_id(), vocab surface —
    inherits the Python implementation."""

    def __init__(self, vocab, lowercase: bool = True, **kw):
        super().__init__(vocab, lowercase=lowercase, **kw)
        lib = _load_lib("wordpiece")
        if lib is None:
            raise RuntimeError(
                f"native tokenizer unavailable: {_load_error('wordpiece')}")
        self._lib = lib
        # id-ordered '\n'-joined vocab (ids are dense by construction of
        # load_vocab; defend against sparse dicts anyway)
        items = sorted(self.vocab.items(), key=lambda kv: kv[1])
        blob = "\n".join(tok for tok, _ in items).encode("utf-8")
        self._handle = lib.wp_create(blob, 1 if lowercase else 0)

    def __del__(self):
        handle = getattr(self, "_handle", None)
        if handle and getattr(self, "_lib", None) is not None:
            self._lib.wp_destroy(handle)
            self._handle = None

    # -- fast paths --------------------------------------------------------

    def encode(self, text: str, pair: Optional[str] = None,
               add_special_tokens: bool = True) -> Encoding:
        return self.encode_batch([text], [pair] if pair else None,
                                 add_special_tokens=add_special_tokens,
                                 nthreads=1)[0]

    def encode_batch_arrays(self, texts: Sequence[str],
                            pairs: Optional[Sequence[Optional[str]]] = None,
                            add_special_tokens: bool = True,
                            nthreads: Optional[int] = None):
        """Zero-copy-ish batch encode -> numpy arrays
        (lens, ids, type_ids, starts, ends); ids et al are flat with
        np.cumsum(lens) boundaries. ~13x the Python encoder single-core on
        wiki-like text (the Encoding-object path below pays most of its time
        building Python lists; the offline HDF5 encode pipeline only needs
        these arrays)."""
        import numpy as np

        n = len(texts)
        if n == 0:
            z = np.zeros((0,), np.int32)
            return z, z, z, z, z
        raw = self._encode_raw(texts, pairs, add_special_tokens, nthreads)
        lens, ids, type_ids, starts, ends = raw
        try:
            tot = int(np.sum(np.ctypeslib.as_array(lens, (n,))))
            return (np.ctypeslib.as_array(lens, (n,)).copy(),
                    np.ctypeslib.as_array(ids, (tot,)).copy(),
                    np.ctypeslib.as_array(type_ids, (tot,)).copy(),
                    np.ctypeslib.as_array(starts, (tot,)).copy(),
                    np.ctypeslib.as_array(ends, (tot,)).copy())
        finally:
            for p in raw:
                self._lib.wp_free(p)

    def _encode_raw(self, texts, pairs, add_special_tokens, nthreads):
        """ctypes call; returns the 5 malloc'd int32 pointers (caller frees
        each with self._lib.wp_free)."""
        n = len(texts)
        if nthreads is None:
            nthreads = min(os.cpu_count() or 1, 16)
        arr_t = ctypes.c_char_p * n
        len_t = ctypes.c_int64 * n
        tbytes = [t.encode("utf-8") for t in texts]
        texts_c = arr_t(*tbytes)
        text_lens = len_t(*[len(b) for b in tbytes])
        pairs_c = None
        pair_lens = len_t(*([0] * n))
        if pairs is not None:
            pbytes = [p.encode("utf-8") if p else None for p in pairs]
            pairs_c = arr_t(*pbytes)
            pair_lens = len_t(*[len(b) if b else 0 for b in pbytes])
        lens = I32P()
        ids = I32P()
        type_ids = I32P()
        starts = I32P()
        ends = I32P()
        total = ctypes.c_int64()
        rc = self._lib.wp_encode_batch(
            self._handle, texts_c, text_lens, pairs_c, pair_lens, n,
            1 if add_special_tokens else 0, nthreads,
            ctypes.byref(lens), ctypes.byref(ids), ctypes.byref(type_ids),
            ctypes.byref(starts), ctypes.byref(ends), ctypes.byref(total))
        if rc != 0:
            raise RuntimeError("wp_encode_batch failed")
        return lens, ids, type_ids, starts, ends

    def encode_batch(self, texts: Sequence[str],
                     pairs: Optional[Sequence[Optional[str]]] = None,
                     add_special_tokens: bool = True,
                     nthreads: Optional[int] = None) -> List[Encoding]:
        n = len(texts)
        if n == 0:
            return []
        raw = self._encode_raw(texts, pairs, add_special_tokens, nthreads)
        lens, ids, type_ids, starts, ends = raw
        try:
            import numpy as np

            lens_np = np.ctypeslib.as_array(lens, (n,))
            tot = int(np.sum(lens_np))
            ids_l = np.ctypeslib.as_array(ids, (tot,)).tolist()
            types_l = np.ctypeslib.as_array(type_ids, (tot,)).tolist()
            starts_l = np.ctypeslib.as_array(starts, (tot,)).tolist()
            ends_l = np.ctypeslib.as_array(ends, (tot,)).tolist()
            # dense id -> token table (ids come from the vocab by
            # construction; anything else maps to unk)
            size = max(self.ids_to_tokens, default=-1) + 1
            tok_tab = [self.unk_token] * size
            for i, t in self.ids_to_tokens.items():
                tok_tab[i] = t
            out: List[Encoding] = []
            off = 0
            for k in range(n):
                ln = int(lens_np[k])
                sl = slice(off, off + ln)
                row_ids = ids_l[sl]
                out.append(Encoding(
                    ids=row_ids,
                    tokens=[tok_tab[i] if 0 <= i < size else self.unk_token
                            for i in row_ids],
                    offsets=list(zip(starts_l[sl], ends_l[sl])),
                    type_ids=types_l[sl],
                ))
                off += ln
            return out
        finally:
            for p in raw:
                self._lib.wp_free(p)


class NativeByteLevelBPETokenizer(ByteLevelBPETokenizer):
    """Drop-in ByteLevelBPETokenizer whose encode()/encode_batch() run in
    C++ (identical results; the batch path releases the GIL and threads
    across texts). A text whose native encoding contains the unk id is
    re-encoded through the Python path, so Encoding.tokens keeps the raw
    piece string for out-of-vocab pieces exactly like the spec (the
    downstream pipeline consumes tokens, pipeline/encode.py:63-66)."""

    def __init__(self, vocab, merges, lowercase: bool = False,
                 add_prefix_space: bool = True, unk_token: str = "<unk>"):
        super().__init__(vocab, merges, lowercase=lowercase,
                         add_prefix_space=add_prefix_space,
                         unk_token=unk_token)
        lib = _load_lib("bpe")
        if lib is None:
            raise RuntimeError(f"native BPE unavailable: {_load_error('bpe')}")
        self._lib = lib
        # explicit "id\ttoken" lines — a filtered/hand-edited vocab.json may
        # have id gaps, which a positional format would silently remap
        vocab_blob = "\n".join(
            f"{i}\t{tok}" for tok, i in self.vocab.items()).encode("utf-8")
        merges_sorted = sorted(self.bpe_ranks.items(), key=lambda kv: kv[1])
        merges_blob = "\n".join(f"{a} {b}" for (a, b), _ in
                                merges_sorted).encode("utf-8")
        # sentinel distinct from every real id, so unk rows are detectable
        # even when unk_token itself is a real vocab entry
        self._unk_sentinel = min(self.vocab.values(), default=0) - 1
        self._handle = lib.bpe_create(vocab_blob, merges_blob,
                                      1 if lowercase else 0,
                                      1 if add_prefix_space else 0,
                                      self._unk_sentinel)

    def __del__(self):
        handle = getattr(self, "_handle", None)
        if handle and getattr(self, "_lib", None) is not None:
            self._lib.bpe_destroy(handle)
            self._handle = None

    # -- fast paths --------------------------------------------------------

    def encode(self, text: str, add_special_tokens: bool = True) -> Encoding:
        return self.encode_batch([text], nthreads=1)[0]

    def encode_batch_arrays(self, texts: Sequence[str],
                            add_special_tokens: bool = True,
                            nthreads: Optional[int] = None):
        """Batch encode -> (lens, ids) numpy arrays; ids is flat with
        np.cumsum(lens) boundaries (the shape the offline HDF5 encode
        pipeline consumes). add_special_tokens is accepted for call-site
        compatibility and ignored — byte-level BPE adds no specials (same
        as the Python spec's encode)."""
        import numpy as np

        n = len(texts)
        if n == 0:
            z = np.zeros((0,), np.int32)
            return z, z
        lens, ids, tot = self._encode_raw(texts, nthreads)
        try:
            lens_np = np.ctypeslib.as_array(lens, (n,)).copy()
            ids_np = np.ctypeslib.as_array(ids, (tot,)).copy()
        finally:
            self._lib.bpe_free(lens)
            self._lib.bpe_free(ids)
        if (ids_np == self._unk_sentinel).any():
            # rare OOV piece: re-encode affected rows via the Python spec
            rows = np.split(ids_np, np.cumsum(lens_np)[:-1])
            fixed = [
                (np.asarray(ByteLevelBPETokenizer.encode(self, t).ids,
                            np.int32)
                 if (row == self._unk_sentinel).any() else row)
                for t, row in zip(texts, rows)]
            lens_np = np.asarray([len(r) for r in fixed], np.int32)
            ids_np = (np.concatenate(fixed) if fixed
                      else np.zeros((0,), np.int32))
        return lens_np, ids_np

    def _encode_raw(self, texts, nthreads):
        n = len(texts)
        if nthreads is None:
            nthreads = min(os.cpu_count() or 1, 16)
        arr_t = ctypes.c_char_p * n
        len_t = ctypes.c_int64 * n
        tbytes = [t.encode("utf-8") for t in texts]
        texts_c = arr_t(*tbytes)
        text_lens = len_t(*[len(b) for b in tbytes])
        lens = I32P()
        ids = I32P()
        total = ctypes.c_int64()
        rc = self._lib.bpe_encode_batch(
            self._handle, texts_c, text_lens, n, nthreads,
            ctypes.byref(lens), ctypes.byref(ids), ctypes.byref(total))
        if rc != 0:
            raise RuntimeError("bpe_encode_batch failed")
        return lens, ids, int(total.value)

    def encode_batch(self, texts: Sequence[str],
                     add_special_tokens: bool = True,
                     nthreads: Optional[int] = None) -> List[Encoding]:
        # add_special_tokens accepted for call-site compatibility; byte-level
        # BPE adds no specials (same as the Python spec's encode)
        n = len(texts)
        if n == 0:
            return []
        import numpy as np

        lens, ids, tot = self._encode_raw(texts, nthreads)
        try:
            lens_l = np.ctypeslib.as_array(lens, (n,)).tolist()
            ids_l = np.ctypeslib.as_array(ids, (tot,)).tolist()
        finally:
            self._lib.bpe_free(lens)
            self._lib.bpe_free(ids)
        out: List[Encoding] = []
        off = 0
        for txt, ln in zip(texts, lens_l):
            row = ids_l[off:off + ln]
            off += ln
            if self._unk_sentinel in row:
                # rare OOV piece: the Python spec keeps the raw piece string
                # in tokens (and maps its id to unk); delegate for parity
                out.append(ByteLevelBPETokenizer.encode(self, txt))
                continue
            out.append(Encoding(
                ids=row,
                tokens=[self.ids_to_tokens[i] for i in row],
                offsets=[(0, 0)] * ln,
                type_ids=[0] * ln,
            ))
        return out


def native_vocab_trainer_available() -> bool:
    """True when the native vocab-trainer merge engine can be used."""
    return _load_lib("vocab_trainer") is not None


def vocab_trainer_merge(words, init_vocab, vocab_size: int,
                        wordpiece_mode: bool, min_pair_frequency: int = 1):
    """Run the native greedy merge loop.

    words: iterable of (symbols_tuple, freq) — pre-deduplicated, exactly what
    the Python _MergeEngine receives. init_vocab: ordered initial vocab
    (specials + alphabet). Returns (new_vocab_tokens, merges): tokens to
    append (in selection order) and, for BPE, the ordered merge pairs.
    Selection order is bitwise-identical to the pipeline.vocab Python engine
    (enforced by tests/test_vocab_trainer.py)."""
    lib = _load_lib("vocab_trainer")
    if lib is None:
        raise RuntimeError(
            f"native vocab trainer unavailable: {_load_error('vocab_trainer')}")
    words_tsv = "".join(
        f"{freq}\t{' '.join(symbols)}\n" for symbols, freq in words
    ).encode("utf-8")
    init_buf = "".join(t + "\n" for t in init_vocab).encode("utf-8")
    out = ctypes.c_void_p()
    out_len = ctypes.c_size_t()
    rc = lib.vt_train(words_tsv, len(words_tsv), init_buf, len(init_buf),
                      vocab_size, 1 if wordpiece_mode else 0,
                      min_pair_frequency, ctypes.byref(out),
                      ctypes.byref(out_len))
    if rc != 0:
        raise RuntimeError("vt_train failed")
    try:
        text = ctypes.string_at(out.value, out_len.value).decode("utf-8")
    finally:
        lib.vt_free(out)
    new_tokens, merges = [], []
    # split on '\n' only: str.splitlines() also splits on U+2028/U+2029,
    # which are legal INSIDE tokens (BasicTokenizer passes category Zl/Zp
    # through) and must not truncate them
    for line in text.split("\n"):
        if line.startswith("V\t"):
            new_tokens.append(line[2:])
        elif line.startswith("M\t"):
            a, _, b = line[2:].partition(" ")
            merges.append((a, b))
    return new_tokens, merges
