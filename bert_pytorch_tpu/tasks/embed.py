"""`embed` task: mean-pooled sentence embeddings (batch-embed/retrieval).

Head: BertForSentenceEmbedding — no reference equivalent; it opens the
retrieval serving workload (ROADMAP item 3): POST /v1/embed returns the
L2-normalized fp32 mean-of-real-tokens embedding for one text or a
batch of texts. Training finetunes the encoder through a linear probe
(classification CE over proxy labels on TSV ``label<TAB>text`` rows —
data/glue.py); serving drops the probe and ships the embedding.
"""

from __future__ import annotations

from typing import Any, Dict

import numpy as np

from bert_pytorch_tpu.tasks import registry
from bert_pytorch_tpu.training.finetune import (
    segment_scalar_pack_labels as pack_labels)


def parse_arguments(argv=None):
    from bert_pytorch_tpu.training.finetune import base_finetune_parser

    p = base_finetune_parser(__doc__)
    p.add_argument("--labels", type=str, nargs="+",
                   default=["negative", "positive"],
                   help="probe class names in label-id order (training "
                        "objective only; serving returns embeddings)")
    return p.parse_args(argv)


def build_serving_model(config, dtype, opts: Dict[str, Any]):
    from bert_pytorch_tpu.models import BertForSentenceEmbedding

    return BertForSentenceEmbedding(
        config, num_labels=int(opts.get("embed_labels", 2)),
        max_segments=int(opts.get("max_segments", 8)), dtype=dtype)


def make_service(scheduler, tokenizer, opts: Dict[str, Any]):
    from bert_pytorch_tpu.serving.frontend import EmbedService

    return EmbedService(scheduler, tokenizer,
                        tok_lock=opts.get("tok_lock"))


def _forward_builder(model):
    from bert_pytorch_tpu.tasks import predict

    return predict.build_embed_forward(model)


def setup(args, config, tel):
    import jax
    import jax.numpy as jnp

    from bert_pytorch_tpu.data import glue
    from bert_pytorch_tpu.models import BertForSentenceEmbedding, losses
    from bert_pytorch_tpu.tasks import predict
    from bert_pytorch_tpu.training.finetune import (TaskRun, accuracy_evals,
                                                    bucketed_eval_batches,
                                                    dataset_splits,
                                                    epoch_steps,
                                                    eval_buckets,
                                                    eval_closures,
                                                    finetune_optimizer,
                                                    resolve_tokenizer)

    tokenizer = resolve_tokenizer(args, config)
    compute_dtype = (jnp.bfloat16 if args.dtype == "bfloat16"
                     else jnp.float32)
    model = BertForSentenceEmbedding(
        config, num_labels=len(args.labels),
        max_segments=args.packing_max_segments, dtype=compute_dtype)

    datasets = dataset_splits(args, lambda path: glue.PairClassificationDataset(
        path, tokenizer, args.labels, max_seq_len=args.max_seq_len).arrays())
    train = datasets.get("train")
    steps_per_epoch, total_steps = epoch_steps(train, args)
    sched, tx = finetune_optimizer(args, total_steps)

    sample = jnp.zeros((2, args.max_seq_len), jnp.int32)
    init_fn = lambda r: model.init(r, sample, sample, sample)

    def _probe_loss(model, packed):
        def loss_fn(params, batch, rng, deterministic=False):
            kw = ({"position_ids": batch["position_ids"],
                   "segment_ids": batch["segment_ids"]} if packed else {})
            _, logits = model.apply(
                {"params": params}, batch["input_ids"],
                batch.get("token_type_ids"), batch["attention_mask"],
                deterministic=deterministic,
                rngs=None if deterministic else {"dropout": rng}, **kw)
            return losses.segment_classification_loss(
                logits, batch["labels"]), {}
        return loss_fn

    buckets = eval_buckets(args.max_seq_len)
    probe_fwd = jax.jit(lambda params, feats: model.apply(
        {"params": params}, feats["input_ids"],
        feats.get("token_type_ids"), feats["attention_mask"],
        deterministic=True))
    evals = accuracy_evals(datasets, args.batch_size, buckets,
                           lambda params, feats: probe_fwd(params, feats)[1])
    epoch_eval, base_finalize = eval_closures(evals, tel,
                                              metric="probe_accuracy")

    def finalize(params, results):
        out = base_finalize(params, results)
        # embedding sanity on whichever split exists: unit norms
        split = ("test" if "test" in datasets else
                 "val" if "val" in datasets else
                 "train" if train is not None else None)
        if split is not None:
            arrays = datasets[split]
            fwd = jax.jit(predict.build_embed_forward(model))
            for batch, idx, _b in bucketed_eval_batches(
                    arrays, args.batch_size, buckets,
                    label_ignore={"labels": -1}):
                feats = {k: jnp.asarray(v) for k, v in batch.items()
                         if k != "labels"}
                emb = np.asarray(fwd(params, feats))[:len(idx)]
                out["embedding_dim"] = int(emb.shape[-1])
                out["embedding_norm_err"] = float(
                    np.abs(np.linalg.norm(emb, axis=-1) - 1.0).max())
                break
        return out

    return TaskRun(
        model=model, tx=tx, init_fn=init_fn, schedule=sched,
        seq_len=args.max_seq_len, batch_size=args.batch_size,
        total_steps=total_steps, epochs=args.epochs,
        train_arrays=train,
        loss_builder=lambda m: _probe_loss(m, packed=False),
        packed_loss_builder=lambda m: _probe_loss(m, packed=True),
        pack_labels=pack_labels, label_ignore={"labels": -1},
        perf_log_freq=max(1, steps_per_epoch),
        log_every=max(1, steps_per_epoch),
        init_checkpoint=args.init_checkpoint,
        epoch_eval=epoch_eval,
        finalize=finalize)


registry.register(registry.TaskSpec(
    name="embed",
    title="mean-pooled sentence embeddings (batch-embed/retrieval)",
    head="BertForSentenceEmbedding",
    output_kind="segment",
    metric="probe_accuracy",
    request_schema={"text": "str (single text)",
                    "texts": "list[str] (batch embed, <=32)"},
    parse_arguments=parse_arguments,
    setup=setup,
    build_serving_model=build_serving_model,
    forward_builder=_forward_builder,
    make_service=make_service,
    reference_heads=("BertForMaskedLM (encoder reuse)",),
))
