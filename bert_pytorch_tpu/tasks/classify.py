"""`classify` task: GLUE-style sequence (pair) classification.

Head: BertForSequenceClassification (reference modeling.py:1053-1110 —
shipped there but never wired to an entry point; registered here it
finetunes through run_finetune.py and serves on POST /v1/classify).
Data: TSV ``label<TAB>text_a[<TAB>text_b]`` (data/glue.py). Packed
training gathers every segment's [CLS] through the pooler
(per-segment pooled-classification gather) so logits are (B, G, C)
against (B, G) labels.
"""

from __future__ import annotations

from typing import Any, Dict

from bert_pytorch_tpu.tasks import registry
from bert_pytorch_tpu.training.finetune import (
    segment_scalar_pack_labels as pack_labels)


def parse_arguments(argv=None):
    from bert_pytorch_tpu.training.finetune import base_finetune_parser

    p = base_finetune_parser(__doc__)
    p.add_argument("--labels", type=str, nargs="+",
                   default=["negative", "positive"],
                   help="class names in label-id order")
    return p.parse_args(argv)


def build_serving_model(config, dtype, opts: Dict[str, Any]):
    from bert_pytorch_tpu.models import BertForSequenceClassification

    return BertForSequenceClassification(
        config, num_labels=len(opts.get("class_names") or ["0", "1"]),
        max_segments=int(opts.get("max_segments", 8)), dtype=dtype)


def make_service(scheduler, tokenizer, opts: Dict[str, Any]):
    from bert_pytorch_tpu.serving.frontend import ClassifyService

    return ClassifyService(scheduler, tokenizer,
                           class_names=list(opts.get("class_names")
                                            or ["0", "1"]),
                           tok_lock=opts.get("tok_lock"))


def _forward_builder(model):
    from bert_pytorch_tpu.tasks import predict

    return predict.build_classify_forward(model)


def packed_loss_builder(model):
    """Packed classification loss for build_pretrain_step — module-level
    so tools/graphcheck.py compiles the EXACT production finetune step
    (finetune_cls_dp8 combo), not a re-implementation."""
    from bert_pytorch_tpu.models import losses

    def loss_fn(params, batch, rng, deterministic=False):
        logits = model.apply(
            {"params": params}, batch["input_ids"],
            batch.get("token_type_ids"), batch["attention_mask"],
            deterministic=deterministic,
            position_ids=batch["position_ids"],
            segment_ids=batch["segment_ids"],
            rngs=None if deterministic else {"dropout": rng})
        return losses.segment_classification_loss(
            logits, batch["labels"]), {}
    return loss_fn


def setup(args, config, tel):
    import jax
    import jax.numpy as jnp

    from bert_pytorch_tpu.data import glue
    from bert_pytorch_tpu.models import (BertForSequenceClassification,
                                         losses)
    from bert_pytorch_tpu.tasks import predict
    from bert_pytorch_tpu.training.finetune import (TaskRun, accuracy_evals,
                                                    dataset_splits,
                                                    epoch_steps,
                                                    eval_buckets,
                                                    eval_closures,
                                                    finetune_optimizer,
                                                    resolve_tokenizer)

    tokenizer = resolve_tokenizer(args, config)
    compute_dtype = (jnp.bfloat16 if args.dtype == "bfloat16"
                     else jnp.float32)
    model = BertForSequenceClassification(
        config, num_labels=len(args.labels),
        max_segments=args.packing_max_segments, dtype=compute_dtype)

    datasets = dataset_splits(args, lambda path: glue.PairClassificationDataset(
        path, tokenizer, args.labels, max_seq_len=args.max_seq_len).arrays())
    train = datasets.get("train")
    steps_per_epoch, total_steps = epoch_steps(train, args)
    sched, tx = finetune_optimizer(args, total_steps)

    sample = jnp.zeros((2, args.max_seq_len), jnp.int32)
    init_fn = lambda r: model.init(r, sample, sample, sample)

    def loss_builder(model):
        def loss_fn(params, batch, rng, deterministic=False):
            logits = model.apply(
                {"params": params}, batch["input_ids"],
                batch.get("token_type_ids"), batch["attention_mask"],
                deterministic=deterministic,
                rngs=None if deterministic else {"dropout": rng})
            return losses.segment_classification_loss(
                logits, batch["labels"]), {}
        return loss_fn

    evals = accuracy_evals(datasets, args.batch_size,
                           eval_buckets(args.max_seq_len),
                           jax.jit(predict.build_classify_forward(model)))
    epoch_eval, finalize = eval_closures(evals, tel)

    return TaskRun(
        model=model, tx=tx, init_fn=init_fn, schedule=sched,
        seq_len=args.max_seq_len, batch_size=args.batch_size,
        total_steps=total_steps, epochs=args.epochs,
        train_arrays=train, loss_builder=loss_builder,
        packed_loss_builder=packed_loss_builder, pack_labels=pack_labels,
        label_ignore={"labels": -1},
        perf_log_freq=max(1, steps_per_epoch),
        log_every=max(1, steps_per_epoch),
        init_checkpoint=args.init_checkpoint,
        epoch_eval=epoch_eval,
        finalize=finalize)


registry.register(registry.TaskSpec(
    name="classify",
    title="GLUE-style sequence (pair) classification",
    head="BertForSequenceClassification",
    output_kind="segment",
    metric="accuracy",
    request_schema={"text": "str (required)",
                    "text_pair": "str (optional second sentence)"},
    parse_arguments=parse_arguments,
    setup=setup,
    build_serving_model=build_serving_model,
    forward_builder=_forward_builder,
    make_service=make_service,
    reference_heads=("BertForSequenceClassification",
                     "BertForNextSentencePrediction"),
))
