"""Task registry: one declarative TaskSpec per scenario, every task served.

The reference ships seven task heads (modeling.py:1053-1308) but wires
only two end to end; through round 13 this repo was the same — run_squad
and run_ner each hand-rolled an entry point, and adding a scenario meant
copying one. The registry makes a scenario O(1): register a TaskSpec and
the task automatically gains

- the shared finetune driver (`run_finetune.py --task <name>`, or its
  thin aliases run_squad.py / run_ner.py), with packed training and
  length-bucketed eval (training/finetune.py);
- a `POST /v1/<name>` serving route (run_server.py builds services by
  iterating this registry), AOT bucketed engine forwards
  (serving/engine.py), and the per-segment demux matching the head's
  `output_kind`;
- CI serving coverage: scripts/check_serve.sh diffs the live server's
  task set against `all_tasks()`, so a registered-but-unserved (or
  served-but-unregistered) task fails the gate;
- graph-lint eligibility (tools/graphcheck.py serve/finetune combos
  derive expectations from the specs) and perfboard-indexed finetune
  perf records.

A TaskSpec is data, not subclassing: callables for the model head, loss,
featurizer, predict/decode, metric, and serving service, plus the
serving request schema (docs/TASKS.md documents the contract and the
add-a-task walkthrough).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Mapping, Tuple

_REGISTRY: Dict[str, "TaskSpec"] = {}
_LOADED = False


@dataclass(frozen=True)
class TaskSpec:
    """One registered scenario. Field groups:

    finetune driver —
      `parse_arguments(argv) -> args`: the task's CLI (run_squad/run_ner
      keep their historical flags; new tasks share the driver's base
      parser); `setup(args, config, tel) -> training.finetune.TaskRun`.

    serving —
      `build_serving_model(config, dtype, opts) -> nn.Module` (opts is
      run_server's per-task option dict: labels, class_names,
      max_segments, ...); `forward_builder(model)` the pure fn the
      engine AOT-compiles per bucket (tasks/predict.py builders);
      `make_service(scheduler, tokenizer, opts)` the HTTP handler
      callable; `output_kind` picks the batcher demux — "token" heads
      slice `[row, offset:offset+len]`, "segment" heads index
      `[row, segment]` of per-segment pooled outputs;
      `request_schema` documents the POST body (served on /healthz and
      in docs/TASKS.md).

    bookkeeping —
      `head`: the models/bert.py class; `reference_heads`: the reference
      modeling.py classes this task covers (docs/MIGRATION.md mapping);
      `metric`: the task's headline eval metric name.
    """

    name: str
    title: str
    head: str
    output_kind: str                     # "token" | "segment"
    metric: str
    request_schema: Mapping[str, str]
    parse_arguments: Callable[..., Any]
    setup: Callable[..., Any]
    build_serving_model: Callable[..., Any]
    forward_builder: Callable[[Any], Callable]
    make_service: Callable[..., Callable]
    tokenizer_kind: str = "wordpiece"
    reference_heads: Tuple[str, ...] = ()
    serving_defaults: Mapping[str, Any] = field(default_factory=dict)


def register(spec: TaskSpec) -> TaskSpec:
    if spec.output_kind not in ("token", "segment"):
        raise ValueError(f"task '{spec.name}': output_kind "
                         f"{spec.output_kind!r} not in ('token', 'segment')")
    if spec.name in _REGISTRY:
        raise ValueError(f"task '{spec.name}' already registered")
    _REGISTRY[spec.name] = spec
    return spec


def _ensure_loaded() -> None:
    """Import the built-in task modules (each registers itself on
    import). Lazy so `all_tasks()` works without jax having been
    configured and so task modules can import registry freely."""
    global _LOADED
    if _LOADED:
        return
    # mark loaded only AFTER every module imported: a failed task import
    # must stay loud on every later call, never leave a silently partial
    # registry behind a one-time error
    from bert_pytorch_tpu.tasks import (choice, classify,  # noqa: F401
                                        embed, ner_task, squad_task)
    _LOADED = True


def get(name: str) -> TaskSpec:
    _ensure_loaded()
    try:
        return _REGISTRY[name]
    except KeyError:
        raise KeyError(f"unknown task {name!r}; registered: "
                       f"{', '.join(all_tasks())}")


def all_tasks() -> Tuple[str, ...]:
    """Sorted names of every registered task — the single source the
    finetune CLI, run_server, check_serve, and graphcheck iterate."""
    _ensure_loaded()
    return tuple(sorted(_REGISTRY))


def specs() -> Tuple[TaskSpec, ...]:
    _ensure_loaded()
    return tuple(_REGISTRY[n] for n in all_tasks())
