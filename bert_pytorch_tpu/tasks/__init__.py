"""Task layer: the scenario registry plus per-task featurize/predict code.

`tasks.registry` is the single wiring point: one declarative `TaskSpec`
per scenario (squad, ner, classify, choice, embed), consumed by the
shared finetune driver (run_finetune.py + training/finetune.py), the
serving stack (run_server.py builds a `POST /v1/<task>` route per
registered task), and the CI gates (scripts/check_serve.sh,
tools/graphcheck.py). Reference entry points covered: run_squad.py
(1,229 LoC) and run_ner.py (261 LoC), plus the modeling.py:1053-1255
heads the reference shipped without wiring.

`tasks.predict` holds the pure forward + postprocess functions shared by
the in-loop eval paths and the serving stack (bert_pytorch_tpu/serving)
— one logits→answer code path, not a fork per consumer.
"""
