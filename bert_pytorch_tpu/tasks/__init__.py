"""Task runners (finetuning): SQuAD question answering, CoNLL NER.

Reference entry points: run_squad.py (1,229 LoC) and run_ner.py (261 LoC);
here the task logic lives in the library so the repo-root scripts stay thin.

`tasks.predict` holds the pure forward + postprocess functions shared by
the in-loop eval paths and the serving stack (bert_pytorch_tpu/serving) —
one logits→answer code path, not a fork per consumer.
"""
