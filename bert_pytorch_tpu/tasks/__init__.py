"""Task runners (finetuning): SQuAD question answering, CoNLL NER.

Reference entry points: run_squad.py (1,229 LoC) and run_ner.py (261 LoC);
here the task logic lives in the library so the repo-root scripts stay thin.
"""
