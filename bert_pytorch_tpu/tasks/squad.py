"""SQuAD v1.1/v2.0: example reading, sliding-window featurization, n-best
answer extraction with original-text realignment, and in-process evaluation.

Behavioral parity with the reference's run_squad.py (reading :131, feature
conversion :209-346, answer span improvement :349, max-context bookkeeping
:386-420, get_answers :427-506, get_final_text :570-656) — the canonical
Google-BERT SQuAD pipeline — re-expressed with dataclasses and numpy batch
assembly. Deviation: evaluation runs in-process (the official v1.1
normalize/EM/F1 math) instead of shelling out to evaluate-v1.1.py
(reference run_squad.py:1197-1204); same numbers, no subprocess.
"""

from __future__ import annotations

import collections
import json
import math
import pickle
import re
import string
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

import numpy as np

from bert_pytorch_tpu.data.tokenization import BasicTokenizer


@dataclass
class SquadExample:
    qas_id: str
    question_text: str
    doc_tokens: List[str]
    orig_answer_text: Optional[str] = None
    start_position: Optional[int] = None
    end_position: Optional[int] = None
    is_impossible: bool = False


@dataclass
class InputFeatures:
    unique_id: int
    example_index: int
    doc_span_index: int
    tokens: List[str]
    token_to_orig_map: Dict[int, int]
    token_is_max_context: Dict[int, bool]
    input_ids: List[int]
    input_mask: List[int]
    segment_ids: List[int]
    start_position: Optional[int] = None
    end_position: Optional[int] = None
    is_impossible: bool = False


RawResult = collections.namedtuple(
    "RawResult", ["unique_id", "start_logits", "end_logits"])


def _is_squad_whitespace(c: str) -> bool:
    return c in (" ", "\t", "\r", "\n") or ord(c) == 0x202F


def text_to_doc_tokens(context: str) -> Tuple[List[str], List[int]]:
    """Whitespace-split a context into doc tokens plus the char->word map —
    the exact tokenization read_squad_examples applies (reference
    run_squad.py:141-157). Shared with the serving path
    (tasks/predict.make_squad_example) so an HTTP request's context is
    split identically to a dataset file's."""
    doc_tokens: List[str] = []
    char_to_word: List[int] = []
    prev_ws = True
    for c in context:
        if _is_squad_whitespace(c):
            prev_ws = True
        else:
            if prev_ws:
                doc_tokens.append(c)
            else:
                doc_tokens[-1] += c
            prev_ws = False
        char_to_word.append(len(doc_tokens) - 1)
    return doc_tokens, char_to_word


def read_squad_examples(input_file: str, is_training: bool,
                        version_2_with_negative: bool = False
                        ) -> List[SquadExample]:
    """SQuAD JSON -> SquadExample list with char->word offset mapping
    (reference run_squad.py:131-207). Training examples whose answer text
    cannot be recovered from the context are skipped with the same rule."""
    with open(input_file, "r", encoding="utf-8") as f:
        data = json.load(f)["data"]

    examples: List[SquadExample] = []
    for entry in data:
        for paragraph in entry["paragraphs"]:
            context = paragraph["context"]
            doc_tokens, char_to_word = text_to_doc_tokens(context)

            for qa in paragraph["qas"]:
                start = end = None
                answer_text = None
                impossible = False
                if is_training:
                    if version_2_with_negative:
                        impossible = qa["is_impossible"]
                    if len(qa["answers"]) != 1 and not impossible:
                        raise ValueError(
                            "training questions need exactly 1 answer")
                    if impossible:
                        start, end, answer_text = -1, -1, ""
                    else:
                        ans = qa["answers"][0]
                        answer_text = ans["text"]
                        off = ans["answer_start"]
                        start = char_to_word[off]
                        end = char_to_word[off + len(answer_text) - 1]
                        recovered = " ".join(doc_tokens[start:end + 1])
                        cleaned = " ".join(answer_text.split())
                        if recovered.find(cleaned) == -1:
                            continue  # unrecoverable (unicode drift) — skip
                examples.append(SquadExample(
                    qas_id=qa["id"], question_text=qa["question"],
                    doc_tokens=doc_tokens, orig_answer_text=answer_text,
                    start_position=start, end_position=end,
                    is_impossible=impossible))
    return examples


def improve_answer_span(doc_tokens: List[str], start: int, end: int,
                        tokenizer, orig_answer_text: str
                        ) -> Tuple[int, int]:
    """Shrink the span to exactly match the tokenized answer when possible
    (reference :349-384)."""
    tok_answer = " ".join(
        tokenizer.encode(orig_answer_text, add_special_tokens=False).tokens)
    for new_start in range(start, end + 1):
        for new_end in range(end, new_start - 1, -1):
            span = " ".join(doc_tokens[new_start:new_end + 1])
            if span == tok_answer:
                return new_start, new_end
    return start, end


def check_is_max_context(doc_spans, cur_index: int, position: int) -> bool:
    """True iff this span gives `position` its maximal min(left,right)
    context among all spans containing it (reference :386-420)."""
    best_score, best_index = None, None
    for idx, span in enumerate(doc_spans):
        end = span.start + span.length - 1
        if position < span.start or position > end:
            continue
        left = position - span.start
        right = end - position
        score = min(left, right) + 0.01 * span.length
        if best_score is None or score > best_score:
            best_score, best_index = score, idx
    return cur_index == best_index


_DocSpan = collections.namedtuple("DocSpan", ["start", "length"])


def convert_examples_to_features(
    examples: List[SquadExample], tokenizer, max_seq_length: int,
    doc_stride: int, max_query_length: int, is_training: bool,
) -> List[InputFeatures]:
    """Sliding-window featurization (reference :209-346). Windows without the
    answer get (0, 0) targets — the [CLS] position — same as the reference."""
    features: List[InputFeatures] = []
    unique_id = 1_000_000_000

    unk_id = tokenizer.token_to_id("[UNK]") or 0

    for ex_idx, ex in enumerate(examples):
        query = tokenizer.encode(ex.question_text,
                                 add_special_tokens=False).tokens
        query = query[:max_query_length]

        tok_to_orig: List[int] = []
        orig_to_tok: List[int] = []
        all_doc_tokens: List[str] = []
        for i, word in enumerate(ex.doc_tokens):
            orig_to_tok.append(len(all_doc_tokens))
            for sub in tokenizer.encode(word,
                                        add_special_tokens=False).tokens:
                tok_to_orig.append(i)
                all_doc_tokens.append(sub)

        tok_start = tok_end = None
        if is_training:
            if ex.is_impossible:
                tok_start = tok_end = -1
            else:
                tok_start = orig_to_tok[ex.start_position]
                if ex.end_position < len(ex.doc_tokens) - 1:
                    tok_end = orig_to_tok[ex.end_position + 1] - 1
                else:
                    tok_end = len(all_doc_tokens) - 1
                tok_start, tok_end = improve_answer_span(
                    all_doc_tokens, tok_start, tok_end, tokenizer,
                    ex.orig_answer_text)

        max_doc = max_seq_length - len(query) - 3  # [CLS] q [SEP] d [SEP]
        spans: List[_DocSpan] = []
        offset = 0
        while offset < len(all_doc_tokens):
            length = min(len(all_doc_tokens) - offset, max_doc)
            spans.append(_DocSpan(offset, length))
            if offset + length == len(all_doc_tokens):
                break
            offset += min(length, doc_stride)

        for span_idx, span in enumerate(spans):
            tokens = ["[CLS]"] + query + ["[SEP]"]
            segment_ids = [0] * len(tokens)
            token_to_orig_map: Dict[int, int] = {}
            token_is_max_context: Dict[int, bool] = {}
            for i in range(span.length):
                pos = span.start + i
                token_to_orig_map[len(tokens)] = tok_to_orig[pos]
                token_is_max_context[len(tokens)] = check_is_max_context(
                    spans, span_idx, pos)
                tokens.append(all_doc_tokens[pos])
                segment_ids.append(1)
            tokens.append("[SEP]")
            segment_ids.append(1)

            ids = [tokenizer.token_to_id(t) if tokenizer.token_to_id(t)
                   is not None else unk_id for t in tokens]
            mask = [1] * len(ids)
            pad = max_seq_length - len(ids)
            ids += [0] * pad
            mask += [0] * pad
            segment_ids += [0] * pad

            start_pos = end_pos = None
            if is_training:
                if ex.is_impossible:
                    start_pos = end_pos = 0
                else:
                    doc_lo = span.start
                    doc_hi = span.start + span.length - 1
                    if tok_start >= doc_lo and tok_end <= doc_hi:
                        shift = len(query) + 2
                        start_pos = tok_start - doc_lo + shift
                        end_pos = tok_end - doc_lo + shift
                    else:
                        start_pos = end_pos = 0  # answer outside this window

            features.append(InputFeatures(
                unique_id=unique_id, example_index=ex_idx,
                doc_span_index=span_idx, tokens=tokens,
                token_to_orig_map=token_to_orig_map,
                token_is_max_context=token_is_max_context,
                input_ids=ids, input_mask=mask, segment_ids=segment_ids,
                start_position=start_pos, end_position=end_pos,
                is_impossible=ex.is_impossible))
            unique_id += 1
    return features


def cached_features(cache_path: str, builder) -> List[InputFeatures]:
    """Pickle cache around featurization (reference :1018-1043)."""
    import os

    if os.path.exists(cache_path):
        with open(cache_path, "rb") as f:
            return pickle.load(f)
    feats = builder()
    with open(cache_path, "wb") as f:
        pickle.dump(feats, f)
    return feats


# ---------------------------------------------------------------------------
# answer extraction
# ---------------------------------------------------------------------------

@dataclass
class AnswerConfig:
    n_best_size: int = 20
    max_answer_length: int = 30
    do_lower_case: bool = True
    version_2_with_negative: bool = False
    null_score_diff_threshold: float = 0.0
    verbose_logging: bool = False


_Prelim = collections.namedtuple(
    "Prelim", ["start_index", "end_index", "start_logit", "end_logit"])
_Pred = collections.namedtuple("Pred", ["text", "start_logit", "end_logit"])


def _best_indices(logits, n: int) -> List[int]:
    return [i for i, _ in sorted(enumerate(logits), key=lambda x: -x[1])[:n]]


def _valid_prelims(starts, ends, feat: InputFeatures, result,
                   cfg: AnswerConfig) -> List[_Prelim]:
    out = []
    for si in starts:
        for ei in ends:
            if si >= len(feat.tokens) or ei >= len(feat.tokens):
                continue
            if si not in feat.token_to_orig_map:
                continue
            if ei not in feat.token_to_orig_map:
                continue
            if not feat.token_is_max_context.get(si, False):
                continue
            if ei < si or ei - si + 1 > cfg.max_answer_length:
                continue
            out.append(_Prelim(si, ei, result.start_logits[si],
                               result.end_logits[ei]))
    return out


def _answer_text(ex: SquadExample, feat: InputFeatures, pred: _Prelim,
                 cfg: AnswerConfig) -> str:
    tok_text = " ".join(feat.tokens[pred.start_index:pred.end_index + 1])
    tok_text = tok_text.replace(" ##", "").replace("##", "")
    tok_text = " ".join(tok_text.split())
    lo = feat.token_to_orig_map[pred.start_index]
    hi = feat.token_to_orig_map[pred.end_index]
    orig_text = " ".join(ex.doc_tokens[lo:hi + 1])
    return get_final_text(tok_text, orig_text, cfg.do_lower_case,
                          cfg.verbose_logging)


def get_answers(examples: List[SquadExample], features: List[InputFeatures],
                results: List[RawResult], cfg: AnswerConfig
                ) -> Tuple[Dict[str, str], Dict[str, list]]:
    """n-best answers per question (reference get_answers :427-506).
    Returns (answers, nbest_answers)."""
    by_qid: Dict[str, List[_Pred]] = collections.defaultdict(list)
    null_vals: Dict[str, Tuple[float, float, float]] = collections.defaultdict(
        lambda: (float("inf"), 0.0, 0.0))

    results_by_id = {r.unique_id: r for r in results}
    for feat in sorted(features, key=lambda f: f.unique_id):
        result = results_by_id.get(feat.unique_id)
        if result is None:
            continue
        ex = examples[feat.example_index]
        starts = _best_indices(result.start_logits, cfg.n_best_size)
        ends = _best_indices(result.end_logits, cfg.n_best_size)
        prelims = sorted(_valid_prelims(starts, ends, feat, result, cfg),
                         key=lambda p: -(p.start_logit + p.end_logit))

        if cfg.version_2_with_negative:
            null_score = result.start_logits[0] + result.end_logits[0]
            if null_score < null_vals[ex.qas_id][0]:
                null_vals[ex.qas_id] = (null_score, result.start_logits[0],
                                        result.end_logits[0])

        seen: List[str] = []
        kept: List[_Pred] = []
        for p in prelims:
            if len(kept) == cfg.n_best_size:
                break
            if p.start_index > 0:
                text = _answer_text(ex, feat, p, cfg)
                if text in seen:
                    continue
            else:
                text = ""
            seen.append(text)
            kept.append(_Pred(text, p.start_logit, p.end_logit))
        by_qid[ex.qas_id] += kept

    if cfg.version_2_with_negative:
        for qid in by_qid:
            _, s0, e0 = null_vals[qid]
            by_qid[qid].append(_Pred("", s0, e0))

    answers: Dict[str, str] = {}
    nbest_answers: Dict[str, list] = collections.defaultdict(list)
    for qid, preds in by_qid.items():
        nbest = sorted(preds,
                       key=lambda p: -(p.start_logit + p.end_logit)
                       )[:cfg.n_best_size]
        if not nbest:
            nbest = [_Pred("empty", 0.0, 0.0)]
        scores = [p.start_logit + p.end_logit for p in nbest]
        probs = _softmax(scores)
        best_non_null = next((p for p in nbest if p.text), None)
        for p, prob in zip(nbest, probs):
            nbest_answers[qid].append({
                "text": p.text, "probability": prob,
                "start_logit": float(p.start_logit),
                "end_logit": float(p.end_logit)})
        if cfg.version_2_with_negative:
            if best_non_null is None:
                answers[qid] = ""
            else:
                diff = (null_vals[qid][0] - best_non_null.start_logit
                        - best_non_null.end_logit)
                answers[qid] = ("" if diff > cfg.null_score_diff_threshold
                                else best_non_null.text)
        else:
            answers[qid] = nbest[0].text
    return answers, nbest_answers


def _softmax(scores: List[float]) -> List[float]:
    if not scores:
        return []
    mx = max(scores)
    exps = [math.exp(s - mx) for s in scores]
    z = sum(exps)
    return [e / z for e in exps]


def get_final_text(pred_text: str, orig_text: str, do_lower_case: bool,
                   verbose: bool = False) -> str:
    """Project the normalized predicted span back onto the original document
    text via character alignment (reference :570-656)."""

    def strip_spaces(text):
        chars, mapping = [], collections.OrderedDict()
        for i, c in enumerate(text):
            if c == " ":
                continue
            mapping[len(chars)] = i
            chars.append(c)
        return "".join(chars), mapping

    basic = BasicTokenizer(do_lower_case=do_lower_case)
    tok_text = " ".join(basic.tokenize(orig_text))

    start = tok_text.find(pred_text)
    if start == -1:
        return orig_text
    end = start + len(pred_text) - 1

    orig_ns, orig_map = strip_spaces(orig_text)
    tok_ns, tok_map = strip_spaces(tok_text)
    if len(orig_ns) != len(tok_ns):
        return orig_text

    tok_s_to_ns = {v: k for k, v in tok_map.items()}

    def project(pos):
        ns = tok_s_to_ns.get(pos)
        if ns is None:
            return None
        return orig_map.get(ns)

    o_start, o_end = project(start), project(end)
    if o_start is None or o_end is None:
        return orig_text
    return orig_text[o_start:o_end + 1]


# ---------------------------------------------------------------------------
# evaluation (official SQuAD v1.1 metric, in-process)
# ---------------------------------------------------------------------------

def _normalize_answer(s: str) -> str:
    s = s.lower()
    s = "".join(c for c in s if c not in set(string.punctuation))
    s = re.sub(r"\b(a|an|the)\b", " ", s)
    return " ".join(s.split())


def _f1(pred: str, gold: str) -> float:
    pred_toks = _normalize_answer(pred).split()
    gold_toks = _normalize_answer(gold).split()
    common = collections.Counter(pred_toks) & collections.Counter(gold_toks)
    overlap = sum(common.values())
    if overlap == 0:
        return 0.0
    precision = overlap / len(pred_toks)
    recall = overlap / len(gold_toks)
    return 2 * precision * recall / (precision + recall)


def evaluate_v1(dataset_file: str, predictions: Dict[str, str]
                ) -> Dict[str, float]:
    """exact_match / F1 over the dev set, same math as the official
    evaluate-v1.1.py the reference subprocesses (run_squad.py:1197-1204)."""
    with open(dataset_file, "r", encoding="utf-8") as f:
        dataset = json.load(f)["data"]
    em_total = f1_total = count = 0.0
    for entry in dataset:
        for paragraph in entry["paragraphs"]:
            for qa in paragraph["qas"]:
                count += 1
                if qa["id"] not in predictions:
                    continue
                pred = predictions[qa["id"]]
                golds = [a["text"] for a in qa["answers"]] or [""]
                em_total += max(
                    float(_normalize_answer(pred) == _normalize_answer(g))
                    for g in golds)
                f1_total += max(_f1(pred, g) for g in golds)
    return {"exact_match": 100.0 * em_total / max(count, 1),
            "f1": 100.0 * f1_total / max(count, 1)}


def evaluate_v2(dataset_file: str, predictions: Dict[str, str]
                ) -> Dict[str, float]:
    """exact / F1 with no-answer handling, the official SQuAD v2.0 metric
    math. The reference never evaluates v2 in-process (its --do_eval shells
    out to the v1.1 script only, run_squad.py:1197-1204, and the v2 flag
    affects reading/prediction alone); this goes beyond it so a
    --version_2_with_negative run reports meaningful numbers: a question
    whose gold is no-answer scores 1.0 iff the prediction is empty, and
    span F1 degenerates to exact match whenever either side is no-answer.
    Also reports HasAns/NoAns splits like the official script.

    Deviation from the official v2.0 script when predictions are INCOMPLETE:
    a missing qid counts 0 in the denominator here (an absent prediction
    must not read as a correct abstention), while the official script drops
    missing qids from the total. Numbers therefore only compare to
    official-script output when the returned dict carries no
    'missing_predictions' key (it is emitted only when nonzero)."""
    with open(dataset_file, "r", encoding="utf-8") as f:
        dataset = json.load(f)["data"]
    em = collections.defaultdict(float)
    f1 = collections.defaultdict(float)
    n = collections.Counter()
    for entry in dataset:
        for paragraph in entry["paragraphs"]:
            for qa in paragraph["qas"]:
                golds = [a["text"] for a in qa["answers"]
                         if _normalize_answer(a["text"])]
                kind = "HasAns" if golds else "NoAns"
                n["total"] += 1
                n[kind] += 1
                if not golds:
                    golds = [""]
                if qa["id"] not in predictions:
                    # same convention as evaluate_v1: a missing prediction
                    # earns 0 (an absent pred must not read as a correct
                    # no-answer abstention); surfaced in the output below
                    n["missing"] += 1
                    continue
                pred = predictions[qa["id"]]
                q_em = max(float(_normalize_answer(pred)
                                 == _normalize_answer(g)) for g in golds)
                q_f1 = max((q_em if not _normalize_answer(g)
                            or not _normalize_answer(pred)
                            else _f1(pred, g)) for g in golds)
                for d, v in ((em, q_em), (f1, q_f1)):
                    d["total"] += v
                    d[kind] += v
    out = {"exact_match": 100.0 * em["total"] / max(n["total"], 1),
           "f1": 100.0 * f1["total"] / max(n["total"], 1)}
    for kind in ("HasAns", "NoAns"):
        if n[kind]:
            out[f"{kind}_exact"] = 100.0 * em[kind] / n[kind]
            out[f"{kind}_f1"] = 100.0 * f1[kind] / n[kind]
    if n["missing"]:
        out["missing_predictions"] = float(n["missing"])
    return out


# ---------------------------------------------------------------------------
# batch assembly
# ---------------------------------------------------------------------------

def features_to_arrays(features: List[InputFeatures], is_training: bool
                       ) -> Dict[str, np.ndarray]:
    out = {
        "input_ids": np.array([f.input_ids for f in features], np.int32),
        "token_type_ids": np.array([f.segment_ids for f in features],
                                   np.int32),
        "attention_mask": np.array([f.input_mask for f in features],
                                   np.int32),
        "unique_ids": np.array([f.unique_id for f in features], np.int64),
    }
    if is_training:
        out["start_positions"] = np.array(
            [f.start_position for f in features], np.int32)
        out["end_positions"] = np.array(
            [f.end_position for f in features], np.int32)
    return out


def batches(arrays: Dict[str, np.ndarray], batch_size: int,
            shuffle: bool = False, seed: int = 0, pad_to_full: bool = True):
    """Yield fixed-size batches (tail padded with rows whose positions are -1
    so they contribute no loss — keeps jit shapes static)."""
    n = len(arrays["input_ids"])
    order = np.arange(n)
    if shuffle:
        np.random.RandomState(seed).shuffle(order)
    for lo in range(0, n, batch_size):
        idx = order[lo:lo + batch_size]
        real = len(idx)
        if real < batch_size and pad_to_full:
            idx = np.concatenate([idx, np.zeros(batch_size - real, np.int64)])
        batch = {k: v[idx] for k, v in arrays.items()}
        if real < batch_size and pad_to_full:
            for k in ("start_positions", "end_positions"):
                if k in batch:
                    batch[k][real:] = -1
        yield batch, real
