"""`squad` task: SQuAD v1.1/v2.0 extractive question answering.

The run_squad.py entry point's task-shaped half, registered: CLI parity
with the reference run_squad.py (:729-859), featurize/train/predict/
n-best/eval through tasks/squad.py, serving on POST /v1/squad. The
training/eval loop itself lives in training/finetune.py (run_squad.py is
a thin alias of run_finetune.py --task squad).

Packed training (--packing): spans shift by each segment's packing
offset and the packed QA loss softmaxes per segment
(losses.packed_qa_loss) — a full-row softmax would mix denominators
across co-packed strangers. Prediction rides length-bucketed eval
batches (windows grouped by real length instead of always padding to
--max_seq_length).
"""

from __future__ import annotations

import json
import os
import time
from typing import Any, Dict

import numpy as np

from bert_pytorch_tpu.tasks import registry


def parse_arguments(argv=None):
    import argparse

    from bert_pytorch_tpu.training.finetune import add_common_finetune_flags

    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--config_file", default=None, type=str)
    p.add_argument("--bert_model", default="bert-large-uncased", type=str)
    p.add_argument("--output_dir", required=False, default=None, type=str)
    p.add_argument("--train_file", default=None, type=str)
    p.add_argument("--predict_file", default=None, type=str)
    p.add_argument("--init_checkpoint", default=None, type=str,
                   help="pretraining checkpoint dir (orbax) or none")
    p.add_argument("--model_config_file", default=None, type=str)
    p.add_argument("--vocab_file", default=None, type=str)
    p.add_argument("--do_train", action="store_true")
    p.add_argument("--do_predict", action="store_true")
    p.add_argument("--do_eval", action="store_true")
    p.add_argument("--do_lower_case", action="store_true", default=True)
    p.add_argument("--max_seq_length", default=384, type=int)
    p.add_argument("--doc_stride", default=128, type=int)
    p.add_argument("--max_query_length", default=64, type=int)
    p.add_argument("--train_batch_size", default=32, type=int)
    p.add_argument("--predict_batch_size", default=8, type=int)
    p.add_argument("--learning_rate", default=3e-5, type=float,
                   help="peak LR. The finetune optimizer keeps apex "
                        "FusedAdam's bias_correction=False semantics "
                        "(reference run_squad.py:982-988), which amplifies "
                        "early updates ~(1/sqrt(1-b2))x; measured on v5e, "
                        "3e-4 diverges the encoder to chance while 5e-5 "
                        "reaches 100 F1 on an overfit probe — stay near the "
                        "reference's 3e-5 scale")
    p.add_argument("--num_train_epochs", default=2.0, type=float)
    p.add_argument("--max_steps", default=-1.0, type=float,
                   help="early exit for benchmarking (reference :1070-1073)")
    p.add_argument("--warmup_proportion", default=0.1, type=float)
    p.add_argument("--n_best_size", default=20, type=int)
    p.add_argument("--max_answer_length", default=30, type=int)
    p.add_argument("--verbose_logging", action="store_true")
    p.add_argument("--seed", type=int, default=42)
    p.add_argument("--gradient_accumulation_steps", type=int, default=1)
    p.add_argument("--version_2_with_negative", action="store_true")
    p.add_argument("--null_score_diff_threshold", type=float, default=0.0)
    p.add_argument("--max_grad_norm", type=float, default=1.0)
    p.add_argument("--dtype", type=str, default="bfloat16",
                   choices=["bfloat16", "float32"])
    p.add_argument("--log_prefix", type=str, default="squad_log")
    p.add_argument("--watchdog_timeout", type=float, default=0.0,
                   help="hung-step watchdog (resilience/watchdog.py): a "
                        "host phase exceeding this many seconds dumps "
                        "all-thread stacks and acts per "
                        "--watchdog_action; 0 = off (docs/RESILIENCE.md)")
    p.add_argument("--watchdog_action", type=str, default="abort",
                   choices=["abort", "warn"])
    p.add_argument("--metrics_port", type=int, default=None,
                   help="serve live /metrics + /healthz on this port while "
                        "the run is alive (telemetry/exporter.py; 0 = "
                        "ephemeral). Default: off")
    p.add_argument("--eval_script", default=None, type=str,
                   help="unused (in-process eval); kept for CLI parity")
    add_common_finetune_flags(p)

    from bert_pytorch_tpu.config import merge_args_with_config

    return merge_args_with_config(p, argv)


def build_serving_model(config, dtype, opts: Dict[str, Any]):
    from bert_pytorch_tpu.models import BertForQuestionAnswering

    return BertForQuestionAnswering(config, dtype=dtype)


def make_service(scheduler, tokenizer, opts: Dict[str, Any]):
    from bert_pytorch_tpu.serving.frontend import SquadService
    from bert_pytorch_tpu.tasks import squad

    return SquadService(
        scheduler, tokenizer,
        answer_cfg=opts.get("answer_cfg") or squad.AnswerConfig(),
        doc_stride=int(opts.get("doc_stride", 128)),
        max_query_length=int(opts.get("max_query_length", 64)),
        tok_lock=opts.get("tok_lock"))


def _forward_builder(model):
    from bert_pytorch_tpu.tasks import predict

    return predict.build_qa_forward(model)


def pack_labels(arrays, placements, n_rows, seq_len, max_segments):
    """Per-segment ABSOLUTE span positions: (n_rows, G) start/end, -1 for
    empty slots and for answers clamped out of the window (the qa_loss
    convention, reference run_squad.py:1080-1092)."""
    out = {k: np.full((n_rows, max_segments), -1, np.int32)
           for k in ("start_positions", "end_positions")}
    for p in placements:
        ln, off = p.lengths[0], p.offsets[0]
        for k in ("start_positions", "end_positions"):
            pos = int(arrays[k][p.unit])
            if 0 <= pos < ln:
                out[k][p.row, p.seg0] = pos + off
    return out


def setup(args, config, tel):
    import jax
    import jax.numpy as jnp

    from bert_pytorch_tpu.data.tokenization import get_wordpiece_tokenizer
    from bert_pytorch_tpu.models import BertForQuestionAnswering, losses
    from bert_pytorch_tpu.optim import schedulers
    from bert_pytorch_tpu.optim.adam import fused_adam
    from bert_pytorch_tpu.optim.lamb import default_weight_decay_mask
    from bert_pytorch_tpu.tasks import predict, squad
    from bert_pytorch_tpu.training.finetune import (TaskRun,
                                                    bucketed_eval_batches,
                                                    eval_buckets)

    vocab_file = args.vocab_file or config.vocab_file
    compute_dtype = (jnp.bfloat16 if args.dtype == "bfloat16"
                     else jnp.float32)
    model = BertForQuestionAnswering(config, dtype=compute_dtype)
    tokenizer = get_wordpiece_tokenizer(vocab_file,
                                        uppercase=not config.lowercase)
    logger = tel.logger

    train_arrays = None
    total_steps = 0
    if args.do_train:
        examples = squad.read_squad_examples(
            args.train_file, is_training=True,
            version_2_with_negative=args.version_2_with_negative)
        cache = os.path.join(
            args.output_dir,
            f"train_feats_{args.max_seq_length}_{args.doc_stride}.pkl")
        feats = squad.cached_features(cache, lambda: (
            squad.convert_examples_to_features(
                examples, tokenizer, args.max_seq_length,
                args.doc_stride, args.max_query_length,
                is_training=True)))
        train_arrays = squad.features_to_arrays(feats, is_training=True)
        train_arrays.pop("unique_ids", None)
        if getattr(args, "packing", False):
            # a packed step consumes a data-dependent number of examples;
            # count the actual per-epoch first-fit stream so total_steps
            # (and the schedule) cover num_train_epochs real data passes
            from bert_pytorch_tpu.training.finetune import (
                packed_epoch_step_counts)

            total_steps = sum(packed_epoch_step_counts(
                train_arrays, n_rows=args.train_batch_size,
                seq_len=args.max_seq_length,
                max_segments=getattr(args, "packing_max_segments", 8),
                seed=args.seed, epochs=args.num_train_epochs))
        else:
            # optimizer steps per epoch: each step consumes batch*accum
            # examples (reference divides num_train_optimization_steps
            # the same way, run_squad.py:966-970)
            examples_per_step = (args.train_batch_size
                                 * args.gradient_accumulation_steps)
            steps_per_epoch = len(feats) // examples_per_step
            total_steps = int(steps_per_epoch * args.num_train_epochs)
        if args.max_steps > 0:
            total_steps = min(total_steps, int(args.max_steps))

    sched = schedulers.linear_warmup_schedule(
        args.learning_rate, max(total_steps, 1),
        warmup=args.warmup_proportion)
    import optax

    # two param groups: wd 0.01 everywhere except bias/LayerNorm
    # (reference run_squad.py:974-986)
    tx = fused_adam(sched, weight_decay=0.01,
                    weight_decay_mask=default_weight_decay_mask,
                    bias_correction=False)
    if args.max_grad_norm and args.max_grad_norm > 0:
        # reference GradientClipper global-norm clip before the step
        # (run_squad.py:703-725,1104)
        tx = optax.chain(
            optax.clip_by_global_norm(args.max_grad_norm), tx)

    sample_ids = jnp.zeros((2, args.max_seq_length), jnp.int32)
    init_fn = lambda r: model.init(r, sample_ids, sample_ids, sample_ids)

    def loss_builder(model):
        def loss_fn(params, batch, rng, deterministic=False):
            start, end = model.apply(
                {"params": params}, batch["input_ids"],
                batch["token_type_ids"], batch["attention_mask"],
                deterministic=deterministic,
                rngs=None if deterministic else {"dropout": rng})
            loss = losses.qa_loss(start, end,
                                  batch["start_positions"],
                                  batch["end_positions"])
            return loss, {}
        return loss_fn

    max_segments = args.packing_max_segments

    def packed_loss_builder(model):
        def loss_fn(params, batch, rng, deterministic=False):
            start, end = model.apply(
                {"params": params}, batch["input_ids"],
                batch["token_type_ids"], batch["attention_mask"],
                deterministic=deterministic,
                position_ids=batch["position_ids"],
                segment_ids=batch["segment_ids"],
                rngs=None if deterministic else {"dropout": rng})
            loss = losses.packed_qa_loss(
                start, end, batch["start_positions"],
                batch["end_positions"], batch["segment_ids"],
                max_segments)
            return loss, {}
        return loss_fn

    def finalize(params, results):
        out: Dict[str, Any] = {}
        if not args.do_predict:
            return out
        eval_examples = squad.read_squad_examples(
            args.predict_file, is_training=False,
            version_2_with_negative=args.version_2_with_negative)
        eval_feats = squad.convert_examples_to_features(
            eval_examples, tokenizer, args.max_seq_length,
            args.doc_stride, args.max_query_length, is_training=False)
        eval_arrays = squad.features_to_arrays(eval_feats,
                                               is_training=False)
        uids_all = eval_arrays.pop("unique_ids")

        # the SAME pure forward + RawResult assembly the serving engine
        # compiles (tasks/predict.py), dispatched over length-bucketed
        # batches: each window rides the smallest bucket that fits it
        predict_step = jax.jit(predict.build_qa_forward(model))
        buckets = eval_buckets(args.max_seq_length)

        raw_results = []
        t0 = time.time()
        for batch, idx, _bucket in bucketed_eval_batches(
                eval_arrays, args.predict_batch_size, buckets):
            feats_dev = {k: jnp.asarray(v) for k, v in batch.items()}
            start, end = predict_step(params, feats_dev)
            raw_results.extend(predict.qa_raw_results(
                uids_all[idx], start, end, len(idx)))
        infer_time = time.time() - t0
        out["e2e_inference_time"] = infer_time
        out["inference_sequences_per_second"] = (
            len(eval_feats) / max(infer_time, 1e-9))

        answers, nbest = squad.get_answers(
            eval_examples, eval_feats, raw_results,
            squad.AnswerConfig(
                n_best_size=args.n_best_size,
                max_answer_length=args.max_answer_length,
                do_lower_case=config.lowercase,
                version_2_with_negative=args.version_2_with_negative,
                null_score_diff_threshold=args.null_score_diff_threshold,
                verbose_logging=args.verbose_logging))
        pred_file = os.path.join(args.output_dir, "predictions.json")
        with open(pred_file, "w", encoding="utf-8") as f:
            json.dump(answers, f, indent=2)
        with open(os.path.join(args.output_dir,
                               "nbest_predictions.json"),
                  "w", encoding="utf-8") as f:
            json.dump(nbest, f, indent=2)

        if args.do_eval:
            # v1.1 runs the official evaluate-v1.1 math; v2 needs the
            # no-answer-aware metric (the reference's --do_eval only ever
            # shells out to the v1.1 script, run_squad.py:1197-1204)
            eval_fn = (squad.evaluate_v2 if args.version_2_with_negative
                       else squad.evaluate_v1)
            out.update(eval_fn(args.predict_file, answers))
        logger.info(f"predict: wrote {pred_file}")
        return out

    return TaskRun(
        model=model, tx=tx, init_fn=init_fn, schedule=sched,
        seq_len=args.max_seq_length,
        batch_size=args.train_batch_size,
        accum_steps=args.gradient_accumulation_steps,
        total_steps=total_steps, epochs=None,
        train_arrays=train_arrays,
        loss_builder=loss_builder,
        packed_loss_builder=packed_loss_builder,
        pack_labels=pack_labels,
        label_ignore={"start_positions": -1, "end_positions": -1},
        log_every=50, perf_log_freq=50,
        init_checkpoint=args.init_checkpoint,
        finalize=finalize)


registry.register(registry.TaskSpec(
    name="squad",
    title="SQuAD v1.1/v2.0 extractive question answering",
    head="BertForQuestionAnswering",
    output_kind="token",
    metric="f1",
    request_schema={"question": "str (required)",
                    "context": "str (required)"},
    parse_arguments=parse_arguments,
    setup=setup,
    build_serving_model=build_serving_model,
    forward_builder=_forward_builder,
    make_service=make_service,
    serving_defaults={"doc_stride": 128, "max_query_length": 64},
    reference_heads=("BertForQuestionAnswering",),
))
