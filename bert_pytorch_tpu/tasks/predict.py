"""Pure task predict functions: forward + postprocess, no loop state.

Before this module, the logits→answer logic lived inline in the
run_squad.py predict loop and the run_ner.py eval loop — fine while those
loops were the only consumers, but the serving path (serving/engine.py)
needs the exact same forward and the exact same decode without dragging a
training loop along. Everything here is a pure function of
(params, batch) or of plain host data, so one code path serves three
callers: in-loop eval, the batch predict entry points, and the HTTP
server. Forking this logic is how a server quietly drifts from the
numbers the eval harness reports.

Two layers:

- forward builders (`build_qa_forward`, `build_ner_forward`): deterministic
  model applications, packed-batch aware — `position_ids`/`segment_ids`
  pass through when present (data/packing.py contract), absent fields
  trace the plain padded program. These are what the serving engine
  AOT-compiles per bucket and what the eval loops jit.
- host-side postprocess: SQuAD RawResult assembly + n-best answer decode
  (delegating to tasks/squad.get_answers — the canonical Google-BERT
  math), NER per-word label decode with the first-subword convention, and
  the request featurizers the HTTP frontend uses (`make_squad_example`,
  `ner_encode_tokens`) which reuse the dataset featurization primitives.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from bert_pytorch_tpu.tasks import squad


def _packed_kwargs(batch: Dict[str, Any]) -> Dict[str, Any]:
    """position_ids/segment_ids pass-through (mirrors
    training/pretrain._packed_kwargs): absent fields keep the traced
    program identical to the pre-packing one."""
    return {k: batch[k] for k in ("position_ids", "segment_ids")
            if k in batch}


def build_qa_forward(model) -> Callable:
    """fwd(params, batch) -> (start_logits, end_logits), each (B, S) fp32.
    Deterministic; batch carries input_ids/token_type_ids/attention_mask
    (+ packed fields). The single forward run_squad's predict loop jits
    and the serving engine AOT-compiles per bucket."""

    def forward(params, batch):
        return model.apply(
            {"params": params}, batch["input_ids"],
            batch["token_type_ids"], batch["attention_mask"],
            deterministic=True, **_packed_kwargs(batch))

    return forward


def build_ner_forward(model) -> Callable:
    """fwd(params, batch) -> (B, S, num_labels) fp32 logits, deterministic.
    run_ner's eval computes its loss FROM these logits (the reference ran
    a second forward for that, run_ner.py:187-191); serving decodes them
    per segment."""

    def forward(params, batch):
        return model.apply(
            {"params": params}, batch["input_ids"],
            batch.get("token_type_ids"), batch["attention_mask"],
            deterministic=True, **_packed_kwargs(batch))

    return forward


def build_classify_forward(model) -> Callable:
    """fwd(params, batch) -> fp32 classification logits: (B, num_labels)
    plain, (B, G, num_labels) packed (per-segment pooled gather inside
    BertForSequenceClassification). One forward for bucketed finetune
    eval AND the /v1/classify serving engine."""

    def forward(params, batch):
        return model.apply(
            {"params": params}, batch["input_ids"],
            batch.get("token_type_ids"), batch.get("attention_mask"),
            deterministic=True, **_packed_kwargs(batch))

    return forward


def build_choice_forward(model) -> Callable:
    """fwd(params, batch) -> fp32 per-segment choice scores: (B, G)
    packed / (B,) plain 2-D rows, or (B, C) for the reference-shaped
    (B, C, S) eval batch. Serving submits one segment per choice and
    softmaxes host-side (choice_decode)."""

    def forward(params, batch):
        return model.apply(
            {"params": params}, batch["input_ids"],
            batch.get("token_type_ids"), batch.get("attention_mask"),
            deterministic=True, **_packed_kwargs(batch))

    return forward


def build_embed_forward(model) -> Callable:
    """fwd(params, batch) -> L2-normalized fp32 embeddings, (B, E) plain /
    (B, G, E) packed — the batch-embed serving workload's program (the
    training-only probe logits are dropped here)."""

    def forward(params, batch):
        emb, _ = model.apply(
            {"params": params}, batch["input_ids"],
            batch.get("token_type_ids"), batch.get("attention_mask"),
            deterministic=True, **_packed_kwargs(batch))
        return emb

    return forward


# ---------------------------------------------------------------------------
# SQuAD postprocess + request featurization
# ---------------------------------------------------------------------------


def qa_raw_results(unique_ids: Sequence[int], start_logits: np.ndarray,
                   end_logits: np.ndarray,
                   n_real: Optional[int] = None) -> List[squad.RawResult]:
    """Batch logits -> per-feature RawResults (what get_answers consumes).
    `n_real` drops the tail-padding rows a fixed-size predict batch
    carries (tasks/squad.batches pad_to_full contract)."""
    start = np.asarray(start_logits)
    end = np.asarray(end_logits)
    n = len(unique_ids) if n_real is None else int(n_real)
    return [squad.RawResult(unique_id=int(unique_ids[i]),
                            start_logits=start[i].tolist(),
                            end_logits=end[i].tolist())
            for i in range(n)]


def make_squad_example(qas_id: str, question: str,
                       context: str) -> squad.SquadExample:
    """One serving request -> a SquadExample, split exactly as
    read_squad_examples splits dataset contexts (squad.text_to_doc_tokens)."""
    doc_tokens, _ = squad.text_to_doc_tokens(context)
    if not doc_tokens:
        raise ValueError("empty context")
    return squad.SquadExample(qas_id=qas_id, question_text=question,
                              doc_tokens=doc_tokens)


def qa_featurize(example: squad.SquadExample, tokenizer, max_seq_length: int,
                 doc_stride: int, max_query_length: int
                 ) -> List[squad.InputFeatures]:
    """Sliding-window features for one example — the dataset featurizer on
    a single example (long contexts still produce several windows, each an
    independent forward whose results merge in qa_decode)."""
    return squad.convert_examples_to_features(
        [example], tokenizer, max_seq_length, doc_stride, max_query_length,
        is_training=False)


def feature_length(feat: squad.InputFeatures) -> int:
    """Real token count of a feature (= sum of its attention mask) — the
    packing length the scheduler bins by."""
    return int(sum(feat.input_mask))


def qa_decode(example: squad.SquadExample,
              features: List[squad.InputFeatures],
              raw_results: List[squad.RawResult],
              cfg: Optional[squad.AnswerConfig] = None,
              n_best: int = 5) -> Dict[str, Any]:
    """(example, its features, their RawResults) -> {'answer', 'nbest'}
    through squad.get_answers — the same n-best extraction + original-text
    realignment the eval path runs, on one example."""
    cfg = cfg or squad.AnswerConfig()
    answers, nbest = squad.get_answers([example], features, raw_results, cfg)
    return {"answer": answers.get(example.qas_id, ""),
            "nbest": nbest.get(example.qas_id, [])[:n_best]}


# ---------------------------------------------------------------------------
# NER postprocess + request featurization
# ---------------------------------------------------------------------------


def ner_encode_tokens(tokens: Sequence[str], tokenizer, max_pieces: int
                      ) -> Tuple[List[int], List[int]]:
    """Pre-split words -> ([CLS] pieces [SEP] ids, piece->word map).

    The per-word subword expansion matches data/ner.NERSample.encode
    (labels propagate per piece there; here we keep the piece->word map so
    the decode can apply the first-subword convention). `max_pieces`
    bounds the piece count ([CLS]/[SEP] included) — the serving caller
    passes the largest bucket so an over-long request is rejected before
    it reaches the queue."""
    pieces: List[str] = []
    piece_word: List[int] = []
    for wi, word in enumerate(tokens):
        for sub in tokenizer.encode(word, add_special_tokens=False).tokens:
            pieces.append(sub)
            piece_word.append(wi)
    if len(pieces) > max_pieces - 2:
        raise ValueError(
            f"request tokenizes to {len(pieces)} pieces, exceeding the "
            f"largest bucket ({max_pieces} incl. [CLS]/[SEP])")
    unk = tokenizer.token_to_id("[UNK]") or 0
    ids = [tokenizer.token_to_id(t) if tokenizer.token_to_id(t) is not None
           else unk for t in ["[CLS]"] + pieces + ["[SEP]"]]
    return ids, piece_word


def encode_pair(tokenizer, text: str, text_pair: Optional[str] = None,
                max_pieces: int = 128) -> Tuple[List[int], List[int]]:
    """(text, optional pair) -> ([CLS] A [SEP] (B [SEP]) ids, type ids)
    with longest-first truncation into `max_pieces` — the GLUE-style pair
    encoding shared by the classify/choice/embed dataset featurizers
    (data/glue.py) AND their serving request paths, so training data and
    live traffic cannot tokenize differently."""
    a = list(tokenizer.encode(text, add_special_tokens=False).tokens)
    b = (list(tokenizer.encode(text_pair, add_special_tokens=False).tokens)
         if text_pair else [])
    budget = max_pieces - (3 if b else 2)
    if budget < 1:
        raise ValueError(f"max_pieces {max_pieces} leaves no room for "
                         "content tokens")
    while len(a) + len(b) > budget:  # reference _truncate_seq_pair
        (a if len(a) >= len(b) else b).pop()
    if not a:
        raise ValueError("empty text after tokenization")
    tokens = ["[CLS]"] + a + ["[SEP]"]
    types = [0] * len(tokens)
    if b:
        tokens += b + ["[SEP]"]
        types += [1] * (len(b) + 1)
    unk = tokenizer.token_to_id("[UNK]") or 0
    ids = [tokenizer.token_to_id(t) if tokenizer.token_to_id(t) is not None
           else unk for t in tokens]
    return ids, types


def _softmax_np(x: np.ndarray) -> np.ndarray:
    x = np.asarray(x, np.float64)
    x = x - x.max()
    e = np.exp(x)
    return e / e.sum()


def classify_decode(logits: np.ndarray,
                    class_names: Sequence[str]) -> Dict[str, Any]:
    """(num_labels,) segment logits -> {'label', 'scores'} — argmax class
    plus the full softmax distribution keyed by class name."""
    probs = _softmax_np(np.asarray(logits).reshape(-1))
    idx = int(np.argmax(probs))
    names = [class_names[i] if i < len(class_names) else str(i)
             for i in range(len(probs))]
    return {"label": names[idx],
            "scores": {n: round(float(p), 6)
                       for n, p in zip(names, probs)}}


def choice_decode(scores: Sequence[float]) -> Dict[str, Any]:
    """Per-choice scalar scores (one forward segment each) ->
    {'choice', 'scores'} via a host-side softmax across the choices."""
    probs = _softmax_np(np.asarray(scores, np.float64))
    return {"choice": int(np.argmax(probs)),
            "scores": [round(float(p), 6) for p in probs]}


def ner_decode(logits: np.ndarray, piece_word: Sequence[int],
               id_to_label: Dict[int, str], n_words: int) -> List[str]:
    """(L, num_labels) segment logits -> one label per original word.

    Position 0 is [CLS] and the last real position is [SEP]; piece i maps
    to logits position i+1. Each word takes its FIRST subword's argmax
    (the convention the CoNLL eval uses — data/ner.py propagates the word
    label to every piece in training, so the first piece is the head).
    Label id 0 is the padding class; it decodes to 'O' (no entity)."""
    preds = np.argmax(np.asarray(logits), axis=-1)
    out = ["O"] * n_words
    seen = set()
    for i, wi in enumerate(piece_word):
        if wi in seen:
            continue
        seen.add(wi)
        out[wi] = id_to_label.get(int(preds[i + 1]), "O")
    return out
