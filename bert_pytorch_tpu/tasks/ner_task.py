"""`ner` task: CoNLL named-entity recognition.

The run_ner.py entry point's task-shaped half, registered: CLI parity
with the reference run_ner.py (:19-261) — BertForTokenClassification
with len(labels)+1 classes, FusedAdam (no bias correction) with the
bias/LayerNorm no-decay split, per-epoch 1/(1+0.05*epoch) LR decay,
grad-norm clip 5.0, macro-F1 on val/test — loop shared via
training/finetune.py. Eval is length-bucketed; packed training places
token labels at each segment's packing offset (the per-token head is
segment-local by construction).
"""

from __future__ import annotations

import json
from typing import Any, Dict

import numpy as np

from bert_pytorch_tpu.tasks import registry


def parse_arguments(argv=None):
    import argparse

    from bert_pytorch_tpu.training.finetune import add_common_finetune_flags

    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--train_file", type=str, required=True)
    p.add_argument("--val_file", default=None, type=str)
    p.add_argument("--test_file", default=None, type=str)
    p.add_argument("--labels", type=str, nargs="+", required=True)
    p.add_argument("--model_config_file", type=str, required=True)
    p.add_argument("--model_checkpoint", type=str, default=None,
                   help="pretraining checkpoint dir (orbax); optional")
    p.add_argument("--vocab_file", default=None, type=str)
    p.add_argument("--uppercase", action="store_true", default=False)
    p.add_argument("--tokenizer", type=str, default=None,
                   choices=["wordpiece", "bpe"])
    p.add_argument("--epochs", type=int, default=10)
    p.add_argument("--lr", type=float, default=5e-6)
    p.add_argument("--clip_grad", type=float, default=5.0)
    p.add_argument("--batch_size", type=int, default=32)
    p.add_argument("--max_seq_len", type=int, default=128)
    p.add_argument("--seed", type=int, default=42)
    p.add_argument("--output_dir", type=str, default="results/ner")
    p.add_argument("--metrics_port", type=int, default=None,
                   help="serve live /metrics + /healthz on this port while "
                        "the run is alive (telemetry/exporter.py; 0 = "
                        "ephemeral). Default: off")
    p.add_argument("--dtype", type=str, default="bfloat16",
                   choices=["bfloat16", "float32"])
    p.add_argument("--watchdog_timeout", type=float, default=0.0,
                   help="hung-step watchdog (resilience/watchdog.py): a "
                        "host phase exceeding this many seconds dumps "
                        "all-thread stacks and acts per "
                        "--watchdog_action; 0 = off (docs/RESILIENCE.md)")
    p.add_argument("--watchdog_action", type=str, default="abort",
                   choices=["abort", "warn"])
    add_common_finetune_flags(p)
    return p.parse_args(argv)


def build_serving_model(config, dtype, opts: Dict[str, Any]):
    from bert_pytorch_tpu.models import BertForTokenClassification

    labels = opts.get("labels") or []
    return BertForTokenClassification(config, num_labels=len(labels) + 1,
                                      dtype=dtype)


def make_service(scheduler, tokenizer, opts: Dict[str, Any]):
    from bert_pytorch_tpu.serving.frontend import NerService

    labels = opts.get("labels") or []
    id_to_label = {i: l for i, l in enumerate(labels, start=1)}
    return NerService(scheduler, tokenizer, id_to_label,
                      tok_lock=opts.get("tok_lock"))


def _forward_builder(model):
    from bert_pytorch_tpu.tasks import predict

    return predict.build_ner_forward(model)


def pack_labels(arrays, placements, n_rows, seq_len, max_segments):
    """Token labels at each segment's packing offset, IGNORE elsewhere."""
    from bert_pytorch_tpu.data.ner import IGNORE_LABEL

    labels = np.full((n_rows, seq_len), IGNORE_LABEL, np.int32)
    for p in placements:
        ln, off = p.lengths[0], p.offsets[0]
        labels[p.row, off:off + ln] = arrays["labels"][p.unit, :ln]
    return {"labels": labels}


def setup(args, config, tel):
    import jax
    import jax.numpy as jnp

    from bert_pytorch_tpu.data import ner
    from bert_pytorch_tpu.data.tokenization import (get_bpe_tokenizer,
                                                    get_wordpiece_tokenizer)
    from bert_pytorch_tpu.models import BertForTokenClassification, losses
    from bert_pytorch_tpu.optim.adam import fused_adam
    from bert_pytorch_tpu.optim.lamb import default_weight_decay_mask
    from bert_pytorch_tpu.tasks import predict
    from bert_pytorch_tpu.training.finetune import (TaskRun,
                                                    bucketed_eval_batches,
                                                    eval_buckets)

    vocab_file = args.vocab_file or config.vocab_file
    tok_kind = args.tokenizer or config.tokenizer
    if not vocab_file:
        raise SystemExit("vocab_file required (CLI or model config)")
    if tok_kind == "bpe":
        tokenizer = get_bpe_tokenizer(vocab_file,
                                      uppercase=args.uppercase)
    else:
        tokenizer = get_wordpiece_tokenizer(vocab_file,
                                            uppercase=args.uppercase)

    num_labels = len(args.labels) + 1  # + padding label 0 (reference :224)
    compute_dtype = (jnp.bfloat16 if args.dtype == "bfloat16"
                     else jnp.float32)
    model = BertForTokenClassification(config, num_labels=num_labels,
                                       dtype=compute_dtype)

    datasets = {}
    for split, path in (("train", args.train_file),
                        ("val", args.val_file),
                        ("test", args.test_file)):
        if path:
            datasets[split] = ner.NERDataset(
                path, tokenizer, args.labels,
                max_seq_len=args.max_seq_len).arrays()
    train_arrays = datasets["train"]
    if getattr(args, "packing", False):
        # size steps to the packed stream (see packed_epoch_step_counts);
        # counts[0] anchors the per-epoch decay schedule below — later
        # epochs' shuffles may pack ±a step, negligible against the
        # 5%-per-epoch decay
        from bert_pytorch_tpu.training.finetune import (
            packed_epoch_step_counts)

        counts = packed_epoch_step_counts(
            train_arrays, n_rows=args.batch_size,
            seq_len=args.max_seq_len,
            max_segments=getattr(args, "packing_max_segments", 8),
            seed=args.seed, epochs=args.epochs)
        steps_per_epoch = max(1, counts[0]) if counts else 1
        total_steps = sum(counts)
    else:
        steps_per_epoch = max(1, -(-len(train_arrays["input_ids"])
                                   // args.batch_size))
        total_steps = steps_per_epoch * args.epochs

    # per-epoch decay lr/(1+0.05*epoch) (reference LambdaLR,
    # run_ner.py:245)
    def schedule(step):
        epoch = step // steps_per_epoch
        return args.lr / (1.0 + 0.05 * epoch)

    import optax

    tx = fused_adam(schedule, weight_decay=0.01,
                    weight_decay_mask=default_weight_decay_mask,
                    bias_correction=False)
    if args.clip_grad and args.clip_grad > 0:
        tx = optax.chain(optax.clip_by_global_norm(args.clip_grad), tx)

    sample = jnp.zeros((2, args.max_seq_len), jnp.int32)
    init_fn = lambda r: model.init(r, sample, sample, sample)

    def loss_builder(model):
        def loss_fn(params, batch, rng, deterministic=False):
            logits = model.apply(
                {"params": params}, batch["input_ids"],
                None, batch["attention_mask"],
                deterministic=deterministic,
                rngs=None if deterministic else {"dropout": rng})
            loss = losses.token_classification_loss(
                logits, batch["labels"], ignore_index=ner.IGNORE_LABEL)
            return loss, {}
        return loss_fn

    max_segments = args.packing_max_segments

    def packed_loss_builder(model):
        def loss_fn(params, batch, rng, deterministic=False):
            logits = model.apply(
                {"params": params}, batch["input_ids"],
                None, batch["attention_mask"],
                deterministic=deterministic,
                position_ids=batch["position_ids"],
                segment_ids=batch["segment_ids"],
                rngs=None if deterministic else {"dropout": rng})
            loss = losses.packed_token_loss(
                logits, batch["labels"], batch["segment_ids"],
                max_segments, ignore_index=ner.IGNORE_LABEL)
            return loss, {}
        return loss_fn

    # eval logits come from the SAME pure forward the serving engine
    # compiles (tasks/predict.py), over length-bucketed batches
    ner_forward = jax.jit(predict.build_ner_forward(model))
    buckets = eval_buckets(args.max_seq_len)

    def run_eval(params, split):
        arrays = datasets[split]
        loss_sum, loss_w = 0.0, 0.0
        logits_, labels_ = [], []
        for batch, idx, bucket in bucketed_eval_batches(
                arrays, args.batch_size, buckets,
                label_ignore={"labels": ner.IGNORE_LABEL}):
            feats = {k: jnp.asarray(v) for k, v in batch.items()
                     if k != "labels"}
            logits = np.asarray(ner_forward(params, feats))
            keep = len(idx)
            # masked mean CE on host from the already-transferred logits
            # (losses.cross_entropy semantics) — no second h2d round-trip
            # plus eager dispatch per eval batch
            lg = logits[:keep].astype(np.float32)
            lb = batch["labels"][:keep]
            valid = lb != ner.IGNORE_LABEL
            shifted = lg - lg.max(axis=-1, keepdims=True)
            logp = shifted - np.log(np.exp(shifted).sum(-1, keepdims=True))
            nll = -np.take_along_axis(
                logp, np.where(valid, lb, 0)[..., None], axis=-1)[..., 0]
            loss = float((nll * valid).sum() / max(int(valid.sum()), 1))
            loss_sum += loss * keep
            loss_w += keep
            # re-inflate trimmed logits to the full S so splits concat
            full = np.zeros((keep, arrays["input_ids"].shape[1],
                             logits.shape[-1]), logits.dtype)
            full[:, :bucket] = logits[:keep]
            logits_.append(full)
            labels_.append(arrays["labels"][idx])
        all_logits = np.concatenate(logits_)
        all_labels = np.concatenate(labels_)
        f1 = ner.macro_f1(all_logits, all_labels)
        diag = ner.classification_diagnostics(all_logits, all_labels,
                                              label_names=args.labels)
        return loss_sum / max(loss_w, 1.0), f1, diag

    def epoch_eval(params, epoch):
        if "val" not in datasets:
            return None
        vloss, vf1, vdiag = run_eval(params, "val")
        tel.logger.log("val", (epoch + 1) * steps_per_epoch, epoch=epoch,
                       loss=vloss, macro_f1=vf1)
        tel.logger.info("val diagnostics: " + json.dumps(vdiag))
        return {"val_f1": vf1}

    def finalize(params, results):
        out: Dict[str, Any] = {}
        if "test" in datasets:
            tloss, tf1, tdiag = run_eval(params, "test")
            tel.logger.log("test", total_steps, loss=tloss, macro_f1=tf1)
            tel.logger.info("test diagnostics: " + json.dumps(tdiag))
            out["test_f1"] = tf1
            out["test_diagnostics"] = tdiag
        return out

    return TaskRun(
        model=model, tx=tx, init_fn=init_fn, schedule=schedule,
        seq_len=args.max_seq_len, batch_size=args.batch_size,
        total_steps=total_steps, epochs=args.epochs,
        train_arrays=train_arrays,
        loss_builder=loss_builder,
        packed_loss_builder=packed_loss_builder,
        pack_labels=pack_labels,
        label_ignore={"labels": -100},
        log_every=max(1, steps_per_epoch),
        perf_log_freq=max(1, steps_per_epoch),
        log_epoch_metrics=True,
        init_checkpoint=args.model_checkpoint,
        epoch_eval=epoch_eval if "val" in datasets else None,
        finalize=finalize)


registry.register(registry.TaskSpec(
    name="ner",
    title="CoNLL named-entity recognition",
    head="BertForTokenClassification",
    output_kind="token",
    metric="macro_f1",
    request_schema={"tokens": "list[str] (pre-split words)",
                    "text": "str (whitespace-split alternative)"},
    parse_arguments=parse_arguments,
    setup=setup,
    build_serving_model=build_serving_model,
    forward_builder=_forward_builder,
    make_service=make_service,
    reference_heads=("BertForTokenClassification",),
))
