"""`choice` task: SWAG-style multiple choice.

Head: BertForMultipleChoice (reference modeling.py:1112-1179, shipped
but never wired). Data: JSONL ``{"question", "choices", "label"}`` with
a fixed choice count (data/glue.py). Packed training places each
example's C choices as C CONSECUTIVE segments of one row (one packing
unit), scores every segment through the per-segment pooled gather, and
softmaxes within each C-group — serving submits one segment per choice
and softmaxes host-side (tasks/predict.choice_decode), the same math.
"""

from __future__ import annotations

from typing import Any, Dict

import numpy as np

from bert_pytorch_tpu.tasks import registry


def parse_arguments(argv=None):
    from bert_pytorch_tpu.training.finetune import base_finetune_parser

    p = base_finetune_parser(__doc__)
    p.add_argument("--num_choices", type=int, default=4,
                   help="choices per example (fixed per file — static "
                        "shapes are the TPU contract)")
    return p.parse_args(argv)


def build_serving_model(config, dtype, opts: Dict[str, Any]):
    from bert_pytorch_tpu.models import BertForMultipleChoice

    return BertForMultipleChoice(
        config, num_choices=int(opts.get("num_choices", 4)),
        max_segments=int(opts.get("max_segments", 8)), dtype=dtype)


def make_service(scheduler, tokenizer, opts: Dict[str, Any]):
    from bert_pytorch_tpu.serving.frontend import ChoiceService

    return ChoiceService(scheduler, tokenizer,
                         tok_lock=opts.get("tok_lock"))


def _forward_builder(model):
    from bert_pytorch_tpu.tasks import predict

    return predict.build_choice_forward(model)


def make_pack_labels(num_choices: int):
    """Per-GROUP labels: (n_rows, G // C) chosen-choice indices, -1 for
    empty groups. Every unit occupies C consecutive segments, so its
    group index is seg0 // C exactly."""

    def pack_labels(arrays, placements, n_rows, seq_len, max_segments):
        labels = np.full((n_rows, max_segments // num_choices), -1,
                         np.int32)
        for p in placements:
            labels[p.row, p.seg0 // num_choices] = arrays["labels"][p.unit]
        return {"labels": labels}

    return pack_labels


def setup(args, config, tel):
    import jax
    import jax.numpy as jnp

    from bert_pytorch_tpu.data import glue
    from bert_pytorch_tpu.models import BertForMultipleChoice, losses
    from bert_pytorch_tpu.training.finetune import (TaskRun, accuracy_evals,
                                                    dataset_splits,
                                                    epoch_steps,
                                                    eval_buckets,
                                                    eval_closures,
                                                    finetune_optimizer,
                                                    resolve_tokenizer)

    C = int(args.num_choices)
    # packed groups need C consecutive segment slots: round G down to a
    # multiple of C (and at least one whole group)
    args.packing_max_segments = max(C, (args.packing_max_segments // C) * C)

    tokenizer = resolve_tokenizer(args, config)
    compute_dtype = (jnp.bfloat16 if args.dtype == "bfloat16"
                     else jnp.float32)
    model = BertForMultipleChoice(
        config, num_choices=C, max_segments=args.packing_max_segments,
        dtype=compute_dtype)

    datasets = dataset_splits(args, lambda path: glue.MultipleChoiceDataset(
        path, tokenizer, C, max_seq_len=args.max_seq_len).arrays())
    train = datasets.get("train")
    steps_per_epoch, total_steps = epoch_steps(train, args, group_size=C)
    sched, tx = finetune_optimizer(args, total_steps)

    sample = jnp.zeros((2, C, args.max_seq_len), jnp.int32)
    init_fn = lambda r: model.init(r, sample, sample, sample)

    def loss_builder(model):
        def loss_fn(params, batch, rng, deterministic=False):
            scores = model.apply(
                {"params": params}, batch["input_ids"],
                batch.get("token_type_ids"), batch["attention_mask"],
                deterministic=deterministic,
                rngs=None if deterministic else {"dropout": rng})
            return losses.choice_loss(scores, batch["labels"], C), {}
        return loss_fn

    def packed_loss_builder(model):
        def loss_fn(params, batch, rng, deterministic=False):
            scores = model.apply(
                {"params": params}, batch["input_ids"],
                batch.get("token_type_ids"), batch["attention_mask"],
                deterministic=deterministic,
                position_ids=batch["position_ids"],
                segment_ids=batch["segment_ids"],
                rngs=None if deterministic else {"dropout": rng})
            return losses.choice_loss(scores, batch["labels"], C), {}
        return loss_fn

    eval_fwd = jax.jit(lambda params, feats: model.apply(
        {"params": params}, feats["input_ids"],
        feats.get("token_type_ids"), feats["attention_mask"],
        deterministic=True))
    evals = accuracy_evals(datasets, args.batch_size,
                           eval_buckets(args.max_seq_len), eval_fwd)
    epoch_eval, finalize = eval_closures(evals, tel)

    return TaskRun(
        model=model, tx=tx, init_fn=init_fn, schedule=sched,
        seq_len=args.max_seq_len, batch_size=args.batch_size,
        total_steps=total_steps, epochs=args.epochs,
        train_arrays=train, loss_builder=loss_builder,
        packed_loss_builder=packed_loss_builder,
        pack_labels=make_pack_labels(C), group_size=C,
        label_ignore={"labels": -1},
        rows_per_step=args.batch_size * C,
        perf_log_freq=max(1, steps_per_epoch),
        log_every=max(1, steps_per_epoch),
        init_checkpoint=args.init_checkpoint,
        epoch_eval=epoch_eval,
        finalize=finalize)


registry.register(registry.TaskSpec(
    name="choice",
    title="SWAG-style multiple choice",
    head="BertForMultipleChoice",
    output_kind="segment",
    metric="accuracy",
    request_schema={"question": "str (optional premise)",
                    "choices": "list[str] (2..16 candidates)"},
    parse_arguments=parse_arguments,
    setup=setup,
    build_serving_model=build_serving_model,
    forward_builder=_forward_builder,
    make_service=make_service,
    serving_defaults={"num_choices": 4},
    reference_heads=("BertForMultipleChoice",),
))
