"""Ring attention — sequence/context parallelism over the `seq` mesh axis.

The reference handles long context only by curriculum (seq 128 -> 512 dataset
files) and sliding-window featurization (SURVEY §5.7); it has no sequence
parallelism of any kind. Here long context is first-class: when activations
are sharded along the sequence dimension of the `(data, fsdp, model, seq)`
mesh (parallel/mesh.py), attention runs as a ring — each device keeps its
local Q block resident and the K/V blocks (plus the K-side padding bias)
rotate around the `seq` axis via `lax.ppermute`, one neighbor hop per step.

Per ring step a device computes one (Sq_local, Sk_local) score tile and
folds it into streaming-softmax accumulators (running max `m`, normalizer
`l`, weighted-value sum `o` — the same fp32 statistics the Pallas flash
kernel keeps per tile, ops/pallas/flash_attention.py). No device ever
materializes a (S, S) score matrix or a gathered (S, D) K/V: per-device
attention memory is O(S_local * S_local) compute tiles and O(S_local)
state, and the K/V transfers ride nearest-neighbor ICI hops instead of an
all-gather. The final tile is unrolled out of the scan so the ring makes
exactly n-1 hops, and a bias-free call carries no bias tile at all.

Differentiation: two nested rematerializations. The whole ring is wrapped
in `jax.checkpoint` (ring_sharded), so a layer's forward saves only its
O(S_local) inputs — without this, `lax.scan` would stack its per-step
carry (the rotating K/V blocks) for EVERY layer simultaneously, i.e.
O(S_global) K/V per layer held across the whole model backward. The scan
body is additionally checkpointed so the recompute never saves score
tiles. Net: per-layer residual memory O(S_local); the K/V carry stack
(~one full-sequence K/V, still nowhere near the O(S^2) score matrix)
materializes only transiently inside a single layer's backward while
autodiff reverses the scan (`ppermute`'s transpose is the inverse
rotation).

Attention dropout follows the dense semantics `out = sum_k keep_k *
(p_k / (1-r)) * v_k` with p the *normalized* probabilities: the keep mask
scales only the value accumulation `o`, never the normalizer `l`. Keep
bits are drawn from a key folded with (q_shard, k_source_shard) so every
score tile of the global (S, S) matrix gets an independent stream and no
tile pair ever reuses masks, matching the decorrelation the sharded flash
path applies (ops/attention.py _flash_sharded).

Packed sequences (segment_ids): the per-shard (B, S_local) segment-id slab
rotates around the ring exactly like K/V — each device keeps its resident
q-side slab and masks every score tile with the same additive
`q_seg == k_seg` constant the flash kernels use (SEG_NEG = -1e30, so
cross-segment probabilities underflow to exact 0.0 in fp32 and the
no-contamination guarantee stays bit-exact on the ring path too). Pad
(segment-0) queries are zeroed after normalization, matching the flash
kernels' pad contract. A tile whose every key is foreign contributes
exp(-1e30 - m) == 0.0 to l/o once any real tile has raised the running max
m above SEG_NEG; until then the spurious mass it deposits is wiped by the
corr = exp(SEG_NEG - m_real) == 0.0 rescale — streaming softmax is
self-healing here, which is what makes segment masking compose with the
rotation without materializing any (S, S) structure.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

NEG_INF = -1e30


def ring_attention_local(
    q: jax.Array,            # (B, Sq_local, H, D) — this shard's queries
    k: jax.Array,            # (B, Sk_local, H, D) — this shard's keys
    v: jax.Array,            # (B, Sk_local, H, D)
    kbias: Optional[jax.Array],   # (B, 1, 1, Sk_local) additive K-side bias
    axis_name: str,
    dropout_key: Optional[jax.Array] = None,
    dropout_rate: float = 0.0,
    segment_ids: Optional[jax.Array] = None,  # (B, S_local) packing slab
) -> jax.Array:
    """Ring attention over `axis_name`; call inside shard_map/pmap where the
    sequence dimension is sharded across that axis. Returns (B, Sq, H, D) in
    q.dtype.

    `segment_ids` is this shard's slab of the packed-sequence ids (1..n per
    row, 0 = pad): the q-side copy stays resident while the k-side copy
    rotates with K/V, and each tile is masked to `q_seg == k_seg` with the
    flash kernels' -1e30 constant (exact-zero cross-segment probabilities)."""
    n = lax.psum(1, axis_name)
    scale = 1.0 / jnp.sqrt(q.shape[-1]).astype(jnp.float32)
    perm = [(j, (j + 1) % n) for j in range(n)]
    b, sq, h, d = q.shape

    qf = q.astype(jnp.float32)
    m0 = jnp.full((b, h, sq), NEG_INF, jnp.float32)
    l0 = jnp.zeros((b, h, sq), jnp.float32)
    o0 = jnp.zeros((b, sq, h, d), jnp.float32)
    has_bias = kbias is not None
    if has_bias:
        kbias = kbias.astype(jnp.float32)
    has_seg = segment_ids is not None
    if has_seg:
        segment_ids = segment_ids.astype(jnp.int32)
        # (B, 1, Sq, 1) resident query slab, broadcast over heads and keys
        q_seg = segment_ids[:, None, :, None]
    # ring step i sees the block that ORIGINATED at shard (my - i) mod n;
    # the (q_shard, src) pair indexes this tile of the global score matrix
    my = lax.axis_index(axis_name)
    dropping = dropout_key is not None and dropout_rate > 0.0
    if dropping:
        dropout_key = jax.random.fold_in(dropout_key, my)

    def tile(m, l, o, kc, vc, bc, sc, i):
        """Fold one (Sq_local, Sk_local) score tile into the streaming
        softmax accumulators."""
        scores = jnp.einsum("bqhd,bkhd->bhqk", qf, kc.astype(jnp.float32),
                            preferred_element_type=jnp.float32) * scale
        if bc is not None:
            scores = scores + bc                # (B,1,1,Sk) broadcasts
        if sc is not None:
            # same additive constant as the flash kernels' in-kernel mask:
            # exp(NEG_INF - m) underflows to exactly 0.0 once m is real
            allowed = (q_seg == sc[:, None, None, :]) & (q_seg > 0)
            scores = scores + jnp.where(allowed, 0.0, NEG_INF)
        blk_max = jnp.max(scores, axis=-1)      # (B, H, Sq)
        new_m = jnp.maximum(m, blk_max)
        corr = jnp.exp(m - new_m)               # (B, H, Sq)
        p = jnp.exp(scores - new_m[..., None])  # (B, H, Sq, Sk)
        new_l = l * corr + jnp.sum(p, axis=-1)
        pv = p
        if dropping:
            src = (my - i) % n
            keep = jax.random.bernoulli(
                jax.random.fold_in(dropout_key, src),
                1.0 - dropout_rate, p.shape)
            pv = jnp.where(keep, p / (1.0 - dropout_rate), 0.0)
        new_o = (o * corr.transpose(0, 2, 1)[..., None]
                 + jnp.einsum("bhqk,bkhd->bqhd", pv,
                              vc.astype(jnp.float32)))
        return new_m, new_l, new_o

    def unpack(rot):
        """carry tail -> (kc, vc, bias-or-None, seg-or-None)."""
        it = iter(rot)
        kc, vc = next(it), next(it)
        bc = next(it) if has_bias else None
        sc = next(it) if has_seg else None
        return kc, vc, bc, sc

    def body(carry, i):
        m, l, o, *rot = carry
        kc, vc, bc, sc = unpack(rot)
        m, l, o = tile(m, l, o, kc, vc, bc, sc, i)
        rotated = lax.ppermute(tuple(rot), axis_name, perm)
        return (m, l, o) + tuple(rotated), None

    body = jax.checkpoint(body,
                          policy=jax.checkpoint_policies.nothing_saveable)
    carry0 = ((m0, l0, o0, k, v) + ((kbias,) if has_bias else ())
              + ((segment_ids,) if has_seg else ()))
    # n-1 compute+rotate steps, then the last tile unrolled (no wasted hop)
    carry, _ = lax.scan(body, carry0, jnp.arange(n - 1))
    m, l, o, *rot = carry
    kc, vc, bc, sc = unpack(rot)
    m, l, o = tile(m, l, o, kc, vc, bc, sc, n - 1)
    out = o / l.transpose(0, 2, 1)[..., None]
    if has_seg:
        # pad (segment-0) queries attend nowhere; their degenerate softmax
        # is uniform garbage. Zero them — the flash kernels' pad contract.
        out = out * (segment_ids > 0).astype(out.dtype)[:, :, None, None]
    return out.astype(q.dtype)


@functools.lru_cache(maxsize=32)
def _jitted_ring(mesh, rate: float, has_bias: bool, has_drop: bool,
                 has_seg: bool):
    """Build (and cache) the jitted shard_map program for one
    (mesh, dropout, segments) configuration. The jit makes the checkpointed
    ring work when called eagerly (tests/debug) — under an outer jit the
    trace is simply inlined — and caching it keeps repeat eager calls from
    re-tracing; jax.jit's own cache handles shape changes."""
    from bert_pytorch_tpu.ops.shard_map_compat import shard_map
    from jax.sharding import PartitionSpec as P

    from bert_pytorch_tpu.ops.attention import flat_batch_head_shard

    sizes = dict(mesh.shape)
    batch_axes = ("data", "fsdp")
    spec_qkv = P(batch_axes, "seq", "model", None)
    in_specs = [spec_qkv, spec_qkv, spec_qkv]
    if has_bias:
        in_specs.append(P(batch_axes, None, None, "seq"))
    if has_seg:
        in_specs.append(P(batch_axes, "seq"))
    if has_drop:
        in_specs.append(P())

    def local(*a):
        it = iter(a)
        lq, lk, lv = next(it), next(it), next(it)
        lbias = next(it) if has_bias else None
        lseg = next(it) if has_seg else None
        lkey = next(it) if has_drop else None
        if lkey is not None:
            # decorrelate the batch/head shards; the ring loop itself folds
            # in the (q_shard, k_source_shard) tile coordinates
            lkey = jax.random.fold_in(lkey, flat_batch_head_shard(sizes))
        ring = jax.checkpoint(
            lambda q_, k_, v_, b_, s_: ring_attention_local(
                q_, k_, v_, b_, "seq", dropout_key=lkey,
                dropout_rate=rate, segment_ids=s_),
            policy=jax.checkpoint_policies.nothing_saveable)
        return ring(lq, lk, lv, lbias, lseg)

    return jax.jit(shard_map(local, mesh=mesh, in_specs=tuple(in_specs),
                             out_specs=spec_qkv, check_rep=False))


def ring_sharded(mesh, q, k, v, bias, dropout_rng, rate: float,
                 segment_ids=None):
    """shard_map wrapper: batch over (data, fsdp), heads over model,
    sequence over seq — the dispatch target ops/attention.py uses when the
    ambient mesh has a nontrivial seq axis. `segment_ids` (B, S) enables
    packed-sequence masking (the slab rotates with K/V). Returns None when
    the layout doesn't fit (caller falls back to the XLA path, which
    handles arbitrary sharding through SPMD collectives at O(S^2) memory)."""
    from bert_pytorch_tpu.ops.attention import mesh_layout

    b, s, h, d = q.shape
    sizes = mesh_layout(mesh, b, h)
    if sizes is None or s % sizes.get("seq", 1) or q.shape != k.shape:
        return None
    if bias is not None and bias.shape != (b, 1, 1, s):
        return None  # ring rotates a K-side padding bias only
    if segment_ids is not None and segment_ids.shape != (b, s):
        return None

    args = [q, k, v]
    has_bias = bias is not None
    if has_bias:
        args.append(bias)
    has_seg = segment_ids is not None
    if has_seg:
        args.append(segment_ids)
    has_drop = dropout_rng is not None and rate > 0.0
    if has_drop:
        args.append(dropout_rng)
    return _jitted_ring(mesh, rate, has_bias, has_drop, has_seg)(*args)
