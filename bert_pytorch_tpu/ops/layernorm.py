"""LayerNorm with a swappable fused (Pallas) implementation.

The reference used apex's FusedLayerNormAffineFunction CUDA kernel with a
pure-torch fallback (src/modeling.py:282-335, eps 1e-12). Here the roles are
mirrored: `_layer_norm_xla` is the always-correct reference path (XLA already
fuses it well), and `bert_pytorch_tpu.ops.pallas.layernorm` provides the
hand-tiled TPU kernel selected by ``fused=True`` on TPU backends.

Statistics are always computed in fp32 regardless of compute dtype — on TPU
bf16 accumulation of mean/variance loses enough precision to shift loss curves.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp


def _layer_norm_xla(x: jax.Array, scale: jax.Array, bias: jax.Array,
                    eps: float) -> jax.Array:
    orig_dtype = x.dtype
    x32 = x.astype(jnp.float32)
    mean = jnp.mean(x32, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(x32 - mean), axis=-1, keepdims=True)
    inv = jax.lax.rsqrt(var + eps)
    y = (x32 - mean) * inv
    y = y * scale.astype(jnp.float32) + bias.astype(jnp.float32)
    return y.astype(orig_dtype)


@functools.partial(jax.jit, static_argnames=("eps", "fused"))
def layer_norm(x: jax.Array, scale: jax.Array, bias: jax.Array,
               eps: float = 1e-12, fused: bool = False) -> jax.Array:
    """LayerNorm over the last axis. eps default matches the reference (1e-12).

    fused=True routes to the Pallas TPU kernel when the backend supports it;
    any non-TPU backend silently falls back to the XLA path so tests run on
    CPU unchanged.
    """
    if fused and x.shape[-1] % 128 == 0:
        try:
            from bert_pytorch_tpu.ops.pallas.layernorm import layer_norm_pallas

            from bert_pytorch_tpu.ops.attention import _pallas_interpret

            on_tpu = jax.default_backend() == "tpu"
            # BPT_PALLAS_INTERPRET=1: run the real kernel in interpret mode
            # on CPU so the multi-chip dryrun covers the production path
            interpret = not on_tpu and _pallas_interpret()
            if on_tpu or interpret:
                from bert_pytorch_tpu.ops.attention import active_mesh

                mesh = active_mesh()
                if mesh is None:
                    return layer_norm_pallas(x, scale, bias, eps=eps,
                                             interpret=interpret)
                out = _layer_norm_sharded(mesh, x, scale, bias, eps,
                                          interpret)
                if out is not None:
                    return out
        except ImportError:
            pass
    return _layer_norm_xla(x, scale, bias, eps)


def row_col_keep(seed, row0, rows, cols, rate: float):
    """Counter-hash keep mask over global (row, col) positions: two
    multiply-xorshift rounds on a per-position counter, integer threshold
    compare. THE single source of truth — the Pallas fused kernel
    (ops/pallas/layernorm) imports this same function, so fused and
    fallback paths draw identical masks by construction. Pure jnp (uint32
    VPU ops only), traceable inside Pallas kernels and plain XLA alike.
    Statistics rationale as flash_attention._keep_mask: two rounds keep
    rate bias < 5e-4 with chance-level cross-seed correlation."""
    r = jax.lax.broadcasted_iota(jnp.uint32, (rows, cols), 0) \
        + jnp.asarray(row0).astype(jnp.uint32)
    c = jax.lax.broadcasted_iota(jnp.uint32, (rows, cols), 1)
    x = (r * jnp.uint32(0x9E3779B1)) ^ (c * jnp.uint32(0x85EBCA77))
    x = x ^ (jnp.asarray(seed).astype(jnp.uint32) * jnp.uint32(0xC2B2AE3D))
    x = x ^ (x >> 16)
    x = x * jnp.uint32(0x7FEB352D)
    x = x ^ (x >> 15)
    x = x * jnp.uint32(0x846CA68B)
    return x > jnp.uint32(int(rate * float(2**32)))


def _hash_keep_mask(seed, shape, rate: float):
    """row_col_keep over a flattened (R, E) view of `shape`. Identical to
    the fused kernel's mask for the same seed on a SINGLE device; under a
    mesh the sharded kernel folds shard coordinates into the seed and
    numbers rows per-shard, so fused-vs-fallback runs only reproduce each
    other when unsharded."""
    R = 1
    for s in shape[:-1]:
        R *= s
    return row_col_keep(seed, 0, R, shape[-1], rate).reshape(shape)


def _add_dropout_layer_norm_xla(x, residual, scale, bias, seed, rate, eps):
    if rate > 0.0:
        keep = _hash_keep_mask(seed, x.shape, rate)
        x = jnp.where(keep, x / (1.0 - rate), jnp.zeros_like(x))
    return _layer_norm_xla(residual + x, scale, bias, eps)


def add_dropout_layer_norm(x, residual, scale, bias, seed, rate: float,
                           eps: float = 1e-12, fused: bool = False):
    """y = LayerNorm(residual + dropout(x, rate)) — the residual tail of
    every BertLayer (reference src/modeling.py:439-487: dense -> dropout ->
    LN(residual + .)), as ONE op.

    Why this exists: with dropout expressed in the XLA graph, the keep-mask
    bits and the dropped tensor are materialized to HBM and re-read by the
    backward pass, bloating the surrounding matmul fusions — measured 13 MFU
    points at seq128 (results/ablate128.jsonl). The fused path evaluates the
    mask from a counter hash of (row, col, seed) inside the kernel, forward
    and backward, so it never touches HBM. The XLA fallback uses the same
    hash, so both paths drop identical units; the difference from nn.Dropout
    is only WHICH units drop (counter hash vs threefry bits) — same
    Bernoulli(rate) statistics, same 1/(1-rate) scaling.

    seed: int32 scalar, fresh per call (derive from the step rng).
    """
    if fused and x.shape[-1] % 128 == 0:
        try:
            from bert_pytorch_tpu.ops.pallas.layernorm import (
                add_dropout_layer_norm_pallas)

            from bert_pytorch_tpu.ops.attention import _pallas_interpret

            on_tpu = jax.default_backend() == "tpu"
            interpret = not on_tpu and _pallas_interpret()
            if on_tpu or interpret:
                from bert_pytorch_tpu.ops.attention import active_mesh

                mesh = active_mesh()
                if mesh is None:
                    return add_dropout_layer_norm_pallas(
                        x, residual, scale, bias, seed, rate, eps, interpret)
                out = _adln_sharded(mesh, x, residual, scale, bias, seed,
                                    rate, eps, interpret)
                if out is not None:
                    return out
        except ImportError:
            pass
    return _add_dropout_layer_norm_xla(x, residual, scale, bias, seed, rate,
                                       eps)


def _adln_sharded(mesh, x, residual, scale, bias, seed, rate, eps,
                  interpret):
    """Fused residual-dropout-LN under shard_map (same partitioning as
    _layer_norm_sharded). Each shard folds its (data, seq) coordinates into
    the seed so shards draw decorrelated masks — without this, every batch
    shard would reuse the same (local-row, col) mask pattern."""
    from bert_pytorch_tpu.ops.shard_map_compat import shard_map
    from jax.sharding import PartitionSpec as P

    from bert_pytorch_tpu.ops.pallas.layernorm import (
        add_dropout_layer_norm_pallas)

    if not {"data", "fsdp", "seq"} <= set(mesh.axis_names) or x.ndim != 3:
        return None
    sizes = dict(mesh.shape)
    dp = sizes.get("data", 1) * sizes.get("fsdp", 1)
    sp = sizes.get("seq", 1)
    if x.shape[0] % dp or x.shape[1] % sp:
        return None
    spec_x = P(("data", "fsdp"), "seq", None)

    def local(lx, lr, ls, lb, lseed):
        di = jax.lax.axis_index("data") * sizes.get("fsdp", 1) \
            + jax.lax.axis_index("fsdp")
        si = jax.lax.axis_index("seq")
        shard_seed = (lseed.astype(jnp.int32)
                      + (di * jnp.int32(sp) + si) * jnp.int32(0x3C6EF35F))
        return add_dropout_layer_norm_pallas(lx, lr, ls, lb, shard_seed,
                                             rate, eps, interpret)

    return shard_map(
        local, mesh=mesh,
        in_specs=(spec_x, spec_x, P(None), P(None), P()),  # seed: rank-0
        out_specs=spec_x, check_rep=False)(
            x, residual, scale, bias, jnp.asarray(seed, jnp.int32))


def _layer_norm_sharded(mesh, x, scale, bias, eps, interpret):
    """Pallas LN under shard_map (rowwise kernel: batch over (data, fsdp),
    seq over seq, E local). None -> caller falls back to XLA. Same rationale
    as ops/attention._flash_sharded: an SPMD-partitioned pallas_call would
    otherwise replicate its operands."""
    from bert_pytorch_tpu.ops.shard_map_compat import shard_map
    from jax.sharding import PartitionSpec as P

    from bert_pytorch_tpu.ops.pallas.layernorm import layer_norm_pallas

    if not {"data", "fsdp", "seq"} <= set(mesh.axis_names) or x.ndim != 3:
        return None
    sizes = dict(mesh.shape)
    dp = sizes.get("data", 1) * sizes.get("fsdp", 1)
    sp = sizes.get("seq", 1)
    if x.shape[0] % dp or x.shape[1] % sp:
        return None
    spec_x = P(("data", "fsdp"), "seq", None)
    return shard_map(
        lambda lx, ls, lb: layer_norm_pallas(lx, ls, lb, eps, interpret),
        mesh=mesh, in_specs=(spec_x, P(None), P(None)), out_specs=spec_x,
        check_rep=False)(x, scale, bias)
