"""LayerNorm with a swappable fused (Pallas) implementation.

The reference used apex's FusedLayerNormAffineFunction CUDA kernel with a
pure-torch fallback (src/modeling.py:282-335, eps 1e-12). Here the roles are
mirrored: `_layer_norm_xla` is the always-correct reference path (XLA already
fuses it well), and `bert_pytorch_tpu.ops.pallas.layernorm` provides the
hand-tiled TPU kernel selected by ``fused=True`` on TPU backends.

Statistics are always computed in fp32 regardless of compute dtype — on TPU
bf16 accumulation of mean/variance loses enough precision to shift loss curves.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp


def _layer_norm_xla(x: jax.Array, scale: jax.Array, bias: jax.Array,
                    eps: float) -> jax.Array:
    orig_dtype = x.dtype
    x32 = x.astype(jnp.float32)
    mean = jnp.mean(x32, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(x32 - mean), axis=-1, keepdims=True)
    inv = jax.lax.rsqrt(var + eps)
    y = (x32 - mean) * inv
    y = y * scale.astype(jnp.float32) + bias.astype(jnp.float32)
    return y.astype(orig_dtype)


@functools.partial(jax.jit, static_argnames=("eps", "fused"))
def layer_norm(x: jax.Array, scale: jax.Array, bias: jax.Array,
               eps: float = 1e-12, fused: bool = False) -> jax.Array:
    """LayerNorm over the last axis. eps default matches the reference (1e-12).

    fused=True routes to the Pallas TPU kernel when the backend supports it;
    any non-TPU backend silently falls back to the XLA path so tests run on
    CPU unchanged.
    """
    if fused and x.shape[-1] % 128 == 0:
        try:
            from bert_pytorch_tpu.ops.pallas.layernorm import layer_norm_pallas

            from bert_pytorch_tpu.ops.attention import _pallas_interpret

            on_tpu = jax.default_backend() == "tpu"
            # BPT_PALLAS_INTERPRET=1: run the real kernel in interpret mode
            # on CPU so the multi-chip dryrun covers the production path
            interpret = not on_tpu and _pallas_interpret()
            if on_tpu or interpret:
                from bert_pytorch_tpu.ops.attention import active_mesh

                mesh = active_mesh()
                if mesh is None:
                    return layer_norm_pallas(x, scale, bias, eps=eps,
                                             interpret=interpret)
                out = _layer_norm_sharded(mesh, x, scale, bias, eps,
                                          interpret)
                if out is not None:
                    return out
        except ImportError:
            pass
    return _layer_norm_xla(x, scale, bias, eps)


def _layer_norm_sharded(mesh, x, scale, bias, eps, interpret):
    """Pallas LN under shard_map (rowwise kernel: batch over (data, fsdp),
    seq over seq, E local). None -> caller falls back to XLA. Same rationale
    as ops/attention._flash_sharded: an SPMD-partitioned pallas_call would
    otherwise replicate its operands."""
    from bert_pytorch_tpu.ops.shard_map_compat import shard_map
    from jax.sharding import PartitionSpec as P

    from bert_pytorch_tpu.ops.pallas.layernorm import layer_norm_pallas

    if not {"data", "fsdp", "seq"} <= set(mesh.axis_names) or x.ndim != 3:
        return None
    sizes = dict(mesh.shape)
    dp = sizes.get("data", 1) * sizes.get("fsdp", 1)
    sp = sizes.get("seq", 1)
    if x.shape[0] % dp or x.shape[1] % sp:
        return None
    spec_x = P(("data", "fsdp"), "seq", None)
    return shard_map(
        lambda lx, ls, lb: layer_norm_pallas(lx, ls, lb, eps, interpret),
        mesh=mesh, in_specs=(spec_x, P(None), P(None)), out_specs=spec_x,
        check_rep=False)(x, scale, bias)
