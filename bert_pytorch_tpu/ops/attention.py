"""Multi-head scaled-dot-product attention.

The reference computes attention as explicit torch matmuls with an additive
``(1-mask)*-10000`` bias (src/modeling.py:376-437, 843-851). Here the math
lives in one function with selectable implementation:

- ``xla``:    plain einsum path; XLA fuses softmax and handles MXU tiling.
  Fastest at seq 128 on v5e when the batch fits un-rematted (measured:
  b64 plain 51.7% MFU vs b64 xla_checkpoint 51.1%).
- ``xla_checkpoint``: the einsum path wrapped in jax.checkpoint so the
  (B, H, S, S) probabilities are recomputed in the backward pass instead of
  saved — XLA-attention speed with flash-like activation memory. Use it to
  fit batches the plain path OOMs on; at equal batch it loses a few percent
  to the recompute.
- ``pallas``: blockwise fused kernel (ops/pallas/flash_attention.py) that never
  materializes the (B, H, S, S) score matrix in HBM — the TPU analogue of
  flash attention. Measured fastest at seq 512 (35.7% MFU vs 30.9% plain /
  25.8% xla_checkpoint, BERT-Large b16 v5e).

Softmax is computed in fp32 regardless of compute dtype; scores in bf16
accumulate enough error at seq 512 to perturb MLM loss.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

# Additive mask bias. The reference used -10000.0 (src/modeling.py:851); that
# value is representable in bf16 and large enough at fp32 softmax precision.
MASK_BIAS = -10000.0


def make_attention_bias(attention_mask: jax.Array,
                        dtype: jnp.dtype = jnp.float32) -> jax.Array:
    """(B, S) {0,1} mask -> (B, 1, 1, S) additive bias."""
    bias = (1.0 - attention_mask.astype(jnp.float32)) * MASK_BIAS
    return bias[:, None, None, :].astype(dtype)


def dot_product_attention(
    q: jax.Array,  # (B, Sq, H, D)
    k: jax.Array,  # (B, Sk, H, D)
    v: jax.Array,  # (B, Sk, H, D)
    bias: Optional[jax.Array] = None,  # broadcastable to (B, H, Sq, Sk)
    dropout_rng: Optional[jax.Array] = None,
    dropout_rate: float = 0.0,
    deterministic: bool = True,
    impl: str = "xla",
    trainable_bias: bool = False,
) -> jax.Array:
    """Returns (B, Sq, H, D) in q.dtype.

    impl="auto" resolves by sequence length: measured on v5e, the plain XLA
    path (bf16 probs, fp32 softmax stats) beats the blockwise Pallas kernel
    up through seq 256 — the (B, H, S, S) matrix is small enough that XLA's
    fused attention wins on raw speed; the flash kernel earns its keep when
    the score matrix is too large to materialize (long-context phase 2+).

    WARNING: the pallas flash-attention path treats `bias` as a constant
    padding mask — its custom VJP returns a ZERO cotangent for bias. A caller
    differentiating through the bias (e.g. a trainable relative-position
    bias) must pass trainable_bias=True, which forces the XLA path where the
    bias gradient is exact.
    """
    seq = q.shape[1]
    if impl == "auto":
        impl = "pallas" if seq > 256 else "xla"
    if (impl == "pallas" and not trainable_bias
            and jax.default_backend() == "tpu"
            and seq % 128 == 0 and q.shape == k.shape):
        from bert_pytorch_tpu.ops.pallas.flash_attention import flash_attention

        rate = 0.0 if deterministic else dropout_rate
        seed = None
        if rate > 0.0:
            # fold the dropout key into a 32-bit positional-hash seed
            seed = jax.random.randint(dropout_rng, (), 0, 2 ** 31 - 1,
                                      dtype=jnp.int32)
        return flash_attention(q, k, v, bias=bias, dropout_seed=seed,
                               dropout_rate=rate)

    if impl == "xla_checkpoint":
        ckpt = jax.checkpoint(
            _xla_attention,
            static_argnums=(5, 6),
            policy=jax.checkpoint_policies.nothing_saveable)
        return ckpt(q, k, v, bias, dropout_rng, dropout_rate, deterministic)

    return _xla_attention(q, k, v, bias, dropout_rng, dropout_rate,
                          deterministic)


def _xla_attention(q, k, v, bias, dropout_rng, dropout_rate: float,
                   deterministic: bool) -> jax.Array:
    depth = q.shape[-1]
    scale = 1.0 / jnp.sqrt(depth).astype(jnp.float32)
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                        preferred_element_type=jnp.float32)
    scores = scores * scale
    if bias is not None:
        scores = scores + bias.astype(jnp.float32)
    # softmax statistics in fp32; the probabilities are cast to the compute
    # dtype BEFORE dropout so the (B, H, S, S) tensors XLA saves for the
    # backward pass (probs + dropped probs) are bf16 — this halves attention
    # activation memory and is what lets batch 64 fit on one v5e chip
    probs = jax.nn.softmax(scores, axis=-1).astype(q.dtype)

    if not deterministic and dropout_rate > 0.0:
        keep = jax.random.bernoulli(dropout_rng, 1.0 - dropout_rate,
                                    probs.shape)
        probs = jnp.where(keep, probs / jnp.asarray(1.0 - dropout_rate,
                                                    q.dtype),
                          jnp.zeros([], q.dtype))

    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)
