"""Multi-head scaled-dot-product attention.

The reference computes attention as explicit torch matmuls with an additive
``(1-mask)*-10000`` bias (src/modeling.py:376-437, 843-851). Here the math
lives in one function with selectable implementation:

- ``xla``:    plain einsum path; XLA fuses softmax and handles MXU tiling.
  Fastest at seq 128 on v5e when the batch fits un-rematted (measured:
  b64 plain 51.7% MFU vs b64 xla_checkpoint 51.1%).
- ``xla_checkpoint``: the einsum path wrapped in jax.checkpoint so the
  (B, H, S, S) probabilities are recomputed in the backward pass instead of
  saved — XLA-attention speed with flash-like activation memory. Use it to
  fit batches the plain path OOMs on; at equal batch it loses a few percent
  to the recompute.
- ``pallas``: blockwise fused kernel (ops/pallas/flash_attention.py) that never
  materializes the (B, H, S, S) score matrix in HBM — the TPU analogue of
  flash attention. Measured fastest at seq 512 (35.7% MFU vs 30.9% plain /
  25.8% xla_checkpoint, BERT-Large b16 v5e). Where VMEM allows (BERT-Large
  seq512 qualifies) the kernels consume the model's (B, S, H, D) layout
  directly — no (BH, S, D) transpose pass either side; longer sequences
  fall back to the transposing grid automatically.
- ``ring``:   sequence parallelism (ops/ring_attention.py) — under a mesh
  whose `seq` axis is nontrivial, K/V blocks rotate around the ring via
  ppermute while each device keeps its Q shard resident; O(S_local) memory
  per device. ``pallas`` (and so ``auto`` at long sequence lengths) also
  routes here when the ambient mesh shards the sequence axis (a Pallas
  kernel is an opaque custom-call that can't see across shards).

Softmax is computed in fp32 regardless of compute dtype; scores in bf16
accumulate enough error at seq 512 to perturb MLM loss.
"""

from __future__ import annotations

import functools
import os
from typing import Optional

import jax
import jax.numpy as jnp


def _pallas_interpret() -> bool:
    """BPT_PALLAS_INTERPRET=1 routes the Pallas kernels through interpret
    mode on non-TPU backends, so the multi-chip dryrun (virtual CPU mesh)
    exercises the production kernel path end-to-end instead of silently
    falling back to XLA. Off by default: interpret mode is orders of
    magnitude slower and only exists for validation."""
    return os.environ.get("BPT_PALLAS_INTERPRET", "0") == "1"


def active_mesh():
    """The ambient Mesh at trace time (jax.sharding.use_mesh, or the legacy
    `with mesh:` context), or None when absent/trivial. Pallas kernels are
    opaque custom-calls XLA's SPMD partitioner cannot split — calling one on
    sharded operands forces a replicate-then-repartition ("involuntary full
    rematerialization"). Under a nontrivial mesh the kernels must therefore
    go through shard_map so each device runs on its local shard."""
    # set by jax.sharding.use_mesh; trace-safe, unlike get_mesh(). Absent
    # on older jax (< 0.4.38) — fall through to the legacy context probe.
    get_am = getattr(jax.sharding, "get_abstract_mesh", None)
    m = get_am() if get_am is not None else None
    if m is None or m.empty:
        # legacy `with mesh:` context; jax._src.mesh is where the deprecated
        # jax.interpreters.pxla.thread_resources alias actually lives
        try:
            from jax._src.mesh import thread_resources

            m = thread_resources.env.physical_mesh
        except ImportError:  # pragma: no cover - future jax refactors
            return None
    if m is None or m.empty or m.size == 1:
        return None
    return m


def mesh_layout(mesh, batch: int, heads: int):
    """Validate the (data, fsdp, model, seq) mesh vocabulary against a
    (batch, heads) attention shape. Returns the axis-size dict, or None when
    the layout rules a sharded kernel out (unknown axes, or batch/head count
    not divisible by their mesh extents) — callers fall back to the XLA
    path, which SPMD can partition arbitrarily."""
    if not {"data", "fsdp", "model", "seq"} <= set(mesh.axis_names):
        return None
    sizes = dict(mesh.shape)
    if batch % (sizes.get("data", 1) * sizes.get("fsdp", 1)):
        return None
    if heads % sizes.get("model", 1):
        return None
    return sizes


def flat_batch_head_shard(sizes) -> jax.Array:
    """Flat (data, fsdp, model) shard index — the per-shard dropout
    decorrelation fold shared by the sharded flash and ring paths."""
    return ((jax.lax.axis_index("data") * sizes.get("fsdp", 1)
             + jax.lax.axis_index("fsdp")) * sizes.get("model", 1)
            + jax.lax.axis_index("model"))


def _flash_sharded(mesh, q, k, v, bias, segment_ids, seed, rate: float,
                   interpret: bool):
    """flash_attention under shard_map: batch over (data, fsdp), heads over
    model; seq/head_dim local. Returns None when the mesh layout rules out
    the kernel (caller falls back to XLA attention).

    Dropout: the positional hash seed is decorrelated per shard by folding
    in the flat shard index — without this every batch/head shard would
    reuse identical keep-masks. segment_ids (packing) shard like the bias:
    batch over (data, fsdp), sequence local."""
    from bert_pytorch_tpu.ops.shard_map_compat import shard_map
    from jax.sharding import PartitionSpec as P

    b, s, h, d = q.shape
    sizes = mesh_layout(mesh, b, h)
    if sizes is None:
        return None
    if sizes.get("seq", 1) > 1:  # S-sharded: needs ring attention, not flash
        return None

    batch_axes = ("data", "fsdp")
    spec_qkv = P(batch_axes, None, "model", None)
    in_specs = [spec_qkv, spec_qkv, spec_qkv]
    args = [q, k, v]
    has_bias = bias is not None
    if has_bias:
        in_specs.append(P(batch_axes, None, None, None))
        args.append(bias)
    has_segments = segment_ids is not None
    if has_segments:
        in_specs.append(P(batch_axes, None))
        args.append(segment_ids)
    has_seed = seed is not None
    if has_seed:
        in_specs.append(P())
        args.append(jnp.asarray(seed, jnp.int32).reshape(()))

    def local(*a):
        it = iter(a)
        lq, lk, lv = next(it), next(it), next(it)
        lbias = next(it) if has_bias else None
        lseg = next(it) if has_segments else None
        lseed = next(it) if has_seed else None
        if lseed is not None:
            shard = flat_batch_head_shard(sizes).astype(jnp.int32)
            lseed = lseed ^ (shard * jnp.int32(-1640531527))  # 0x9E3779B9
        from bert_pytorch_tpu.ops.pallas.flash_attention import flash_attention

        return flash_attention(lq, lk, lv, bias=lbias, segment_ids=lseg,
                               dropout_seed=lseed, dropout_rate=rate,
                               interpret=interpret)

    return shard_map(local, mesh=mesh, in_specs=tuple(in_specs),
                     out_specs=spec_qkv, check_rep=False)(*args)

# Additive mask bias. The reference used -10000.0 (src/modeling.py:851); that
# value is representable in bf16 and large enough at fp32 softmax precision.
MASK_BIAS = -10000.0


def make_attention_bias(attention_mask: jax.Array,
                        dtype: jnp.dtype = jnp.float32) -> jax.Array:
    """(B, S) {0,1} mask -> (B, 1, 1, S) additive bias."""
    bias = (1.0 - attention_mask.astype(jnp.float32)) * MASK_BIAS
    return bias[:, None, None, :].astype(dtype)


# Packed-sequence (block-diagonal) masking constant. Deliberately the flash
# kernels' NEG_INF, not MASK_BIAS: the XLA fallback must produce the same
# exact-zero cross-segment probabilities the kernels do (exp underflows to
# 0.0 in fp32), which is what makes the no-cross-contamination guarantee
# bit-exact on every path.
SEGMENT_MASK_BIAS = -1e30


def make_segment_attention_bias(segment_ids: jax.Array,
                                dtype: jnp.dtype = jnp.float32) -> jax.Array:
    """(B, S) int packing segments (1..n, 0 = pad) -> (B, 1, S, S) additive
    bias: 0 where q and k share a non-pad segment, SEGMENT_MASK_BIAS
    elsewhere. The XLA-path mirror of the in-kernel segment mask."""
    qs = segment_ids[:, None, :, None]
    ks = segment_ids[:, None, None, :]
    allowed = (qs == ks) & (qs > 0)
    return jnp.where(allowed, 0.0, SEGMENT_MASK_BIAS).astype(dtype)


def dot_product_attention(
    q: jax.Array,  # (B, Sq, H, D)
    k: jax.Array,  # (B, Sk, H, D)
    v: jax.Array,  # (B, Sk, H, D)
    bias: Optional[jax.Array] = None,  # broadcastable to (B, H, Sq, Sk)
    segment_ids: Optional[jax.Array] = None,  # (B, S) packing segments
    dropout_rng: Optional[jax.Array] = None,
    dropout_rate: float = 0.0,
    deterministic: bool = True,
    impl: str = "xla",
    trainable_bias: bool = False,
    hash_dropout_impl: bool = True,
) -> jax.Array:
    """Returns (B, Sq, H, D) in q.dtype.

    impl="auto" resolves by sequence length: measured on v5e, the plain XLA
    path (bf16 probs, fp32 softmax stats) beats the blockwise Pallas kernel
    up through seq 256 — the (B, H, S, S) matrix is small enough that XLA's
    fused attention wins on raw speed; the flash kernel earns its keep when
    the score matrix is too large to materialize (long-context phase 2+).

    `segment_ids` (B, S) int32, packed sequences: attention restricted to
    q_seg == k_seg blocks, 0 = pad attends nowhere. The flash kernels mask
    (and block-skip) in-kernel; the XLA paths add the dense
    make_segment_attention_bias; the ring path rotates the per-shard
    segment-id slab alongside K/V (ops/ring_attention.py) — the same
    exact-zero cross-segment probabilities on every impl, so packing
    composes with seq-sharded meshes too.

    WARNING: the pallas flash-attention path treats `bias` as a constant
    padding mask — its custom VJP returns a ZERO cotangent for bias. A caller
    differentiating through the bias (e.g. a trainable relative-position
    bias) must pass trainable_bias=True, which forces the XLA path where the
    bias gradient is exact.
    """
    seq = q.shape[1]
    if impl == "auto":
        impl = "pallas" if seq > 256 else "xla"
    interpret = jax.default_backend() != "tpu" and _pallas_interpret()
    # Sequence-sharded mesh: route to ring attention (K/V blocks rotate over
    # the seq axis via ppermute; O(S_local) memory per device) for every impl
    # except the explicitly-XLA ones, where SPMD's gather-based lowering is
    # the caller's documented choice. impl="ring" forces the ring path.
    if impl in ("ring", "pallas") and not trainable_bias:
        mesh = active_mesh()
        seq_sharded = mesh is not None and dict(mesh.shape).get("seq", 1) > 1
        if seq_sharded:
            from bert_pytorch_tpu.ops.ring_attention import ring_sharded

            rate = 0.0 if deterministic else dropout_rate
            out = ring_sharded(mesh, q, k, v, bias,
                               dropout_rng if rate > 0.0 else None, rate,
                               segment_ids=segment_ids)
            if out is not None:
                return out
        if impl == "ring":
            # no seq-sharded mesh (single chip / tests): dense math is exact
            return _xla_attention(q, k, v, bias, segment_ids, dropout_rng,
                                  dropout_rate, deterministic)
    if (impl == "pallas" and not trainable_bias
            and (jax.default_backend() == "tpu" or interpret)
            and seq % 128 == 0 and q.shape == k.shape):
        from bert_pytorch_tpu.ops.pallas.flash_attention import flash_attention

        rate = 0.0 if deterministic else dropout_rate
        seed = None
        if rate > 0.0:
            # fold the dropout key into a 32-bit positional-hash seed
            seed = jax.random.randint(dropout_rng, (), 0, 2 ** 31 - 1,
                                      dtype=jnp.int32)
        mesh = active_mesh()
        if mesh is not None:
            out = _flash_sharded(mesh, q, k, v, bias, segment_ids, seed,
                                 rate, interpret)
            if out is not None:
                return out
        else:
            return flash_attention(q, k, v, bias=bias,
                                   segment_ids=segment_ids,
                                   dropout_seed=seed, dropout_rate=rate,
                                   interpret=interpret)

    if impl == "xla_checkpoint":
        ckpt = jax.checkpoint(
            _xla_attention,
            static_argnums=(6, 7, 8),
            policy=jax.checkpoint_policies.nothing_saveable)
        return ckpt(q, k, v, bias, segment_ids, dropout_rng, dropout_rate,
                    deterministic, hash_dropout_impl)

    return _xla_attention(q, k, v, bias, segment_ids, dropout_rng,
                          dropout_rate, deterministic, hash_dropout_impl)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def hash_dropout(x, seed, rate: float):
    """Dropout whose keep mask is the positional counter hash
    (ops/layernorm.row_col_keep) over the flattened (rows, last-axis) view,
    REGENERATED in the backward pass instead of saved — the (B, H, S, S)
    bool mask the autodiff of a bernoulli+where dropout keeps for backward
    never exists in HBM. Same construction the flash kernel and the fused
    residual-dropout-LN kernel use for their in-kernel masks; Bernoulli
    statistics, different stream than nn.Dropout."""
    return _hash_dropout_apply(x, seed, rate)


def _hash_dropout_apply(x, seed, rate):
    from bert_pytorch_tpu.ops.layernorm import _hash_keep_mask

    keep = _hash_keep_mask(seed, x.shape, rate)
    return jnp.where(keep, x / jnp.asarray(1.0 - rate, x.dtype),
                     jnp.zeros([], x.dtype))


def _hash_dropout_fwd(x, seed, rate):
    return _hash_dropout_apply(x, seed, rate), seed


def _hash_dropout_bwd(rate, seed, g):
    # dropout is linear: dx is the same mask-and-scale applied to g. The
    # integer seed primal gets the float0 cotangent JAX's convention
    # requires (an int32 zeros here trips stricter custom_vjp aval checks)
    return (_hash_dropout_apply(g, seed, rate),
            jax.custom_derivatives.zero_from_primal(
                jnp.asarray(seed, jnp.int32)))


hash_dropout.defvjp(_hash_dropout_fwd, _hash_dropout_bwd)


def _xla_attention(q, k, v, bias, segment_ids, dropout_rng,
                   dropout_rate: float, deterministic: bool,
                   hash_dropout_impl: bool = True) -> jax.Array:
    depth = q.shape[-1]
    scale = 1.0 / jnp.sqrt(depth).astype(jnp.float32)
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                        preferred_element_type=jnp.float32)
    scores = scores * scale
    if bias is not None:
        scores = scores + bias.astype(jnp.float32)
    if segment_ids is not None:
        scores = scores + make_segment_attention_bias(segment_ids)
    # softmax statistics in fp32; the probabilities are cast to the compute
    # dtype BEFORE dropout so the (B, H, S, S) tensors XLA saves for the
    # backward pass (probs + dropped probs) are bf16 — this halves attention
    # activation memory and is what lets batch 64 fit on one v5e chip
    probs = jax.nn.softmax(scores, axis=-1).astype(q.dtype)

    if not deterministic and dropout_rate > 0.0:
        if hash_dropout_impl:
            # positional-hash dropout with the mask regenerated in backward:
            # no (B, H, S, S) mask tensor is saved for the bwd pass
            # (measured ~1.6 MFU points at BERT-Large seq128; the flash
            # path already generates its mask in-kernel the same way)
            seed = jax.random.bits(dropout_rng, (),
                                   jnp.uint32).astype(jnp.int32)
            probs = hash_dropout(probs, seed, dropout_rate)
        else:
            # nn.Dropout-equivalent stream (config fused_dropout_ln=False:
            # the full pre-r5 dropout behavior, for A/B isolation)
            keep = jax.random.bernoulli(dropout_rng, 1.0 - dropout_rate,
                                        probs.shape)
            probs = jnp.where(
                keep, probs / jnp.asarray(1.0 - dropout_rate, q.dtype),
                jnp.zeros([], q.dtype))

    out = jnp.einsum("bhqk,bkhd->bqhd", probs, v)
    if segment_ids is not None:
        # pad (segment-0) queries attend nowhere; their degenerate softmax
        # is uniform garbage. Zero them to match the flash kernels' pad
        # contract exactly (flash_attention.py module docstring).
        out = out * (segment_ids > 0).astype(out.dtype)[:, :, None, None]
    return out
