"""Activation functions.

Parity targets: the reference's erf-based gelu / bias_gelu / swish and its
ACT2FN registry (reference src/modeling.py:118-139). On TPU, XLA fuses the
bias-add + activation into the preceding matmul's epilogue, so `bias_gelu`
exists mainly to keep the "fused bias+act" call-shape of the reference's
LinearActivation (src/modeling.py:141-180) available to model code.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def gelu(x: jax.Array) -> jax.Array:
    """Exact (erf) GELU — matches the reference's non-approximate formula
    (src/modeling.py:118-123), not the tanh approximation."""
    return jax.nn.gelu(x, approximate=False)


def bias_gelu(bias: jax.Array, y: jax.Array) -> jax.Array:
    """Fused bias-add + exact GELU (reference src/modeling.py:126-131)."""
    return gelu(y + bias)


def swish(x: jax.Array) -> jax.Array:
    return x * jax.nn.sigmoid(x)


def relu(x: jax.Array) -> jax.Array:
    return jax.nn.relu(x)


def tanh(x: jax.Array) -> jax.Array:
    return jnp.tanh(x)


ACT2FN = {
    "gelu": gelu,
    "bias_gelu": bias_gelu,
    "relu": relu,
    "swish": swish,
    "tanh": tanh,
}
