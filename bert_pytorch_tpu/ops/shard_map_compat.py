"""Single import site for shard_map across jax versions.

jax.experimental.shard_map graduated to jax.shard_map in jax 0.8 (the
experimental path now emits a DeprecationWarning and will be removed), and
the replication-check keyword was renamed check_rep -> check_vma. Every
shard_map user in the framework imports from here so the API migration is
one edit, not a per-call conditional.
"""

from __future__ import annotations

try:
    from jax import shard_map as _shard_map  # jax >= 0.8

    def shard_map(f, *, mesh, in_specs, out_specs, check_rep):
        # check_rep is required (no default): the two jax generations default
        # it differently, so an omitted argument would change semantics with
        # the installed version
        return _shard_map(f, mesh=mesh, in_specs=in_specs,
                          out_specs=out_specs, check_vma=check_rep)
except ImportError:  # pragma: no cover — jax < 0.8
    from jax.experimental.shard_map import shard_map as _shard_map_legacy

    def shard_map(f, *, mesh, in_specs, out_specs, check_rep):
        return _shard_map_legacy(f, mesh=mesh, in_specs=in_specs,
                                 out_specs=out_specs, check_rep=check_rep)
