"""TPU op library.

Where the reference leaned on apex CUDA kernels (FusedLayerNormAffineFunction,
fused bias-GELU in LinearActivation, amp_C multi-tensor kernels — SURVEY §2.3),
this package provides:

- a pure-XLA implementation of every op (always available, used as the golden
  reference in tests), and
- Pallas TPU kernels for the hot ones, selected via ``fused=True`` /
  config.fused_ops when running on TPU.
"""

from bert_pytorch_tpu.ops.activations import ACT2FN, bias_gelu, gelu, swish  # noqa: F401
from bert_pytorch_tpu.ops.layernorm import layer_norm  # noqa: F401
