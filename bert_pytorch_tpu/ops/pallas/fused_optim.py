"""Fused multi-tensor LAMB update — the apex amp_C analogue.

Reference mapping (MIGRATION.md): apex `FusedLAMB` runs
`multi_tensor_applier` over chunked flat buckets with two CUDA kernels —
`multi_tensor_lamb` stage1 (Adam moments + update direction) and stage2
(trust-ratio apply). This module is the TPU-shaped equivalent: parameter
leaves flatten into deterministic size-capped buckets (the same greedy
assignment parallel/coalesce._bucketize uses for the norm reductions) and
each bucket runs ONE launch per stage, bounding the update to O(buckets)
kernels/fusions instead of O(leaves) — the long tail of small leaves
(biases, LayerNorm scales) rides inside the big buckets for free.

Both stages are PURELY elementwise; the trust-ratio NORMS between them
stay in optim/lamb.py's existing path (per-tensor or the bucketed
parallel/coalesce.NormReducer) so the reduction grouping is untouched.

Numerics contract (pinned in tests/test_fused_optim.py):

- The XLA fallback (`impl="xla"`, auto-selected off-TPU) evaluates the
  SAME `_stage1_math` body PER LEAF with the same scalar/constant
  producers as optim/lamb.py's unfused chain — structurally the same
  expressions, so `fused=True` off-TPU is bit-identical to
  `fused=False`.
- The Pallas kernel traces the identical math body on flat buckets.
  Between two separately COMPILED XLA programs, mul-add chains are not
  bitwise-stable on CPU — XLA/LLVM is free to contract `a*b + c*d` into
  an FMA (or factor shared operands) differently per program, a ±few-ulp
  ambiguity we measured even between interpret-mode Pallas and a
  straight-line trace of the same jaxpr. The kernel is therefore gated
  against the fallback at a few-ulp tolerance for stage1 and EXACTLY for
  stage2 (a single multiply admits no rewrite). On TPU only the Mosaic
  kernel runs, so no dual-program ambiguity exists in production.

On CPU the Pallas path runs in interpret mode so the test suite
exercises the same kernel code (repo convention, see layernorm.py).

ZeRO-1 sharded state: pass `mesh` + per-leaf `specs` (a NormReducer
carries both, derived from the plan's grad/shard layout) and each bucket
stage wraps in shard_map — local flatten/concat, zero collectives, out
under the same specs. Without specs, bucketing GSPMD-sharded leaves would
force gather/reshard traffic at the concat; values would still match.
"""

from __future__ import annotations

import functools
from typing import Any, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.sharding import PartitionSpec

from bert_pytorch_tpu.parallel.coalesce import DEFAULT_BUCKET_BYTES, _bucketize

ROWS = 256   # rows per grid step
LANES = 128  # lane width; flat buckets pad to (ROWS, LANES) tiles


def select_impl(impl: str = "auto") -> str:
    """'pallas' on TPU backends, 'xla' elsewhere; explicit values pass
    through (tests force 'pallas' to run the interpret-mode kernel on
    CPU)."""
    if impl == "auto":
        return "pallas" if jax.default_backend() == "tpu" else "xla"
    if impl not in ("pallas", "xla"):
        raise ValueError(f"impl must be auto|pallas|xla, got {impl!r}")
    return impl


# ---------------------------------------------------------------------------
# stage kernels — one math body, two dispatchers
# ---------------------------------------------------------------------------


def _stage1_math(g, mu, nu, pf, wd, denom, c1, c2, *, b1, b2, eps):
    """apex multi_tensor_lamb stage1: pre-normalized grad -> Adam moments
    -> update direction u (+ decoupled weight decay). One definition,
    traced identically by the Pallas kernel and the XLA fallback."""
    gn = g / denom
    mu = b1 * mu + (1 - b1) * gn
    nu = b2 * nu + (1 - b2) * jnp.square(gn)
    u = (mu / c1) / (jnp.sqrt(nu / c2) + eps) + wd * pf
    return mu, nu, u


def _stage1_kernel(scal_ref, g_ref, mu_ref, nu_ref, pf_ref, wd_ref,
                   mu_out, nu_out, u_out, *, b1, b2, eps):
    mu, nu, u = _stage1_math(
        g_ref[:], mu_ref[:], nu_ref[:], pf_ref[:], wd_ref[:],
        scal_ref[0, 0], scal_ref[0, 1], scal_ref[0, 2],
        b1=b1, b2=b2, eps=eps)
    mu_out[:] = mu
    nu_out[:] = nu
    u_out[:] = u


def _stage2_kernel(t_ref, u_ref, out_ref):
    # apex multi_tensor_lamb stage2: p -= lr*ratio*u, with t = -lr*ratio
    # precomputed per leaf and broadcast by the caller
    out_ref[:] = t_ref[:] * u_ref[:]


def _to_blocks(vec):
    """Pad a flat f32 vector to whole (ROWS, LANES) tiles and reshape to
    rows; returns (rows, original length). Zero padding is inert through
    both stages (u(0,...)=0/eps=0) and sliced off after the launch."""
    n = vec.shape[0]
    pad = (-n) % (ROWS * LANES)
    if pad:
        vec = jnp.concatenate([vec, jnp.zeros((pad,), vec.dtype)])
    return vec.reshape(-1, LANES), n


def _blk():
    return pl.BlockSpec((ROWS, LANES), lambda i: (i, 0))


def _stage1_flat(scal, g, mu, nu, pf, wd, *, b1, b2, eps, use_pallas):
    if not use_pallas:
        return _stage1_math(g, mu, nu, pf, wd,
                            scal[0, 0], scal[0, 1], scal[0, 2],
                            b1=b1, b2=b2, eps=eps)
    g2, n = _to_blocks(g)
    mu2, _ = _to_blocks(mu)
    nu2, _ = _to_blocks(nu)
    pf2, _ = _to_blocks(pf)
    wd2, _ = _to_blocks(wd)
    Rp = g2.shape[0]
    mu3, nu3, u3 = pl.pallas_call(
        functools.partial(_stage1_kernel, b1=b1, b2=b2, eps=eps),
        grid=(Rp // ROWS,),
        in_specs=[
            pl.BlockSpec((1, 3), lambda i: (0, 0)),  # denom, c1, c2
            _blk(), _blk(), _blk(), _blk(), _blk(),
        ],
        out_specs=[_blk(), _blk(), _blk()],
        out_shape=[jax.ShapeDtypeStruct((Rp, LANES), jnp.float32)] * 3,
        interpret=jax.default_backend() != "tpu",
    )(scal, g2, mu2, nu2, pf2, wd2)
    return (mu3.reshape(-1)[:n], nu3.reshape(-1)[:n], u3.reshape(-1)[:n])


def _stage2_flat(t, u, *, use_pallas):
    if not use_pallas:
        return t * u
    t2, n = _to_blocks(t)
    u2, _ = _to_blocks(u)
    Rp = t2.shape[0]
    out = pl.pallas_call(
        _stage2_kernel,
        grid=(Rp // ROWS,),
        in_specs=[_blk(), _blk()],
        out_specs=_blk(),
        out_shape=jax.ShapeDtypeStruct((Rp, LANES), jnp.float32),
        interpret=jax.default_backend() != "tpu",
    )(t2, u2)
    return out.reshape(-1)[:n]


# ---------------------------------------------------------------------------
# bucketed multi-tensor drivers
# ---------------------------------------------------------------------------


def _leaf_spec(s):
    return getattr(s, "spec", s)


def _maybe_shard_map(fn, mesh, specs, idxs, n_groups, outs_per_leaf):
    """Wrap a bucket fn in shard_map when a layout is given: scalar block
    replicated, every tensor group under its leaf's spec, outputs under
    the same specs (elementwise -> zero collectives inside)."""
    if mesh is None or specs is None:
        return fn
    from bert_pytorch_tpu.ops.shard_map_compat import shard_map

    sp = tuple(_leaf_spec(specs[i]) for i in idxs)
    out_specs = tuple(s for s in sp for _ in range(outs_per_leaf))
    return shard_map(fn, mesh=mesh,
                     in_specs=(PartitionSpec(),) + sp * n_groups,
                     out_specs=out_specs, check_rep=False)


def lamb_stage1(g_leaves: Sequence[Any], mu_leaves: Sequence[Any],
                nu_leaves: Sequence[Any], pf_leaves: Sequence[Any],
                wd_leaves: Sequence[float], *, denom, c1, c2,
                b1: float, b2: float, eps: float, impl: str = "auto",
                bucket_bytes: int = DEFAULT_BUCKET_BYTES,
                mesh=None, specs: Optional[Sequence[Any]] = None,
                ) -> Tuple[List[Any], List[Any], List[Any]]:
    """Bucketed stage1 over aligned leaf lists (grads pre-cast f32,
    params pre-cast f32, per-leaf weight-decay floats). denom/c1/c2 may
    be traced scalars. Returns (mu', nu', u) leaf lists in input order,
    all f32, leaf-shaped."""
    use_pallas = select_impl(impl) == "pallas"
    scal = jnp.stack([jnp.asarray(denom, jnp.float32),
                      jnp.asarray(c1, jnp.float32),
                      jnp.asarray(c2, jnp.float32)]).reshape(1, 3)
    n_leaves = len(g_leaves)
    buckets = _bucketize([int(x.size) for x in g_leaves], bucket_bytes)
    mu_out: List[Any] = [None] * n_leaves
    nu_out: List[Any] = [None] * n_leaves
    u_out: List[Any] = [None] * n_leaves
    for idxs in buckets:
        wds = tuple(float(wd_leaves[i]) for i in idxs)

        def run(scal, *args, _wds=wds, _k=len(idxs)):
            gs, mus = args[:_k], args[_k:2 * _k]
            nus, pfs = args[2 * _k:3 * _k], args[3 * _k:]
            if not use_pallas:
                # per-leaf, python-float wd: structurally the same
                # expressions as the unfused optim/lamb.py chain
                # -> bit-identical to fused=False
                outs = []
                for x, m, v, pf, w in zip(gs, mus, nus, pfs, _wds):
                    outs += list(_stage1_math(
                        x, m, v, pf, w, scal[0, 0], scal[0, 1],
                        scal[0, 2], b1=b1, b2=b2, eps=eps))
                return tuple(outs)
            cat = lambda xs: jnp.concatenate([x.reshape(-1) for x in xs])
            wdf = jnp.concatenate([
                jnp.full((x.size,), w, jnp.float32)
                for x, w in zip(gs, _wds)])
            muf, nuf, uf = _stage1_flat(
                scal, cat(gs), cat(mus), cat(nus), cat(pfs), wdf,
                b1=b1, b2=b2, eps=eps, use_pallas=True)
            outs, off = [], 0
            for x in gs:
                sz, shp = int(x.size), x.shape
                outs += [muf[off:off + sz].reshape(shp),
                         nuf[off:off + sz].reshape(shp),
                         uf[off:off + sz].reshape(shp)]
                off += sz
            return tuple(outs)

        fn = _maybe_shard_map(run, mesh, specs, idxs, n_groups=4,
                              outs_per_leaf=3)
        res = fn(scal,
                 *[g_leaves[i] for i in idxs],
                 *[mu_leaves[i] for i in idxs],
                 *[nu_leaves[i] for i in idxs],
                 *[pf_leaves[i] for i in idxs])
        if not isinstance(res, tuple):
            res = (res,)
        for j, i in enumerate(idxs):
            mu_out[i], nu_out[i], u_out[i] = res[3 * j:3 * j + 3]
    return mu_out, nu_out, u_out


def lamb_stage2(t_leaves: Sequence[Any], u_leaves: Sequence[Any], *,
                impl: str = "auto",
                bucket_bytes: int = DEFAULT_BUCKET_BYTES,
                mesh=None, specs: Optional[Sequence[Any]] = None,
                ) -> List[Any]:
    """Bucketed stage2: upd = t * u, with t = -lr*ratio already broadcast
    to each leaf's shape by the caller. Returns f32 leaf-shaped updates
    in input order (caller casts to the param dtype)."""
    use_pallas = select_impl(impl) == "pallas"
    buckets = _bucketize([int(x.size) for x in u_leaves], bucket_bytes)
    out: List[Any] = [None] * len(u_leaves)
    for idxs in buckets:

        def run(_scal, *args, _k=len(idxs)):
            ts, us = args[:_k], args[_k:]
            if not use_pallas:
                return tuple(t * u for t, u in zip(ts, us))
            cat = lambda xs: jnp.concatenate([x.reshape(-1) for x in xs])
            flat = _stage2_flat(cat(ts), cat(us), use_pallas=True)
            outs, off = [], 0
            for x in us:
                sz = int(x.size)
                outs.append(flat[off:off + sz].reshape(x.shape))
                off += sz
            return tuple(outs)

        fn = _maybe_shard_map(run, mesh, specs, idxs, n_groups=2,
                              outs_per_leaf=1)
        res = fn(jnp.zeros((1,), jnp.float32),
                 *[t_leaves[i] for i in idxs],
                 *[u_leaves[i] for i in idxs])
        if not isinstance(res, tuple):
            res = (res,)
        for j, i in enumerate(idxs):
            out[i] = res[j]
    return out
