"""Fused multi-tensor reductions/updates over a flattened parameter space.

TPU-native equivalent of the reference's amp_C CUDA multi-tensor kernels —
`multi_tensor_l2norm` and `multi_tensor_scale` (src/optimization.py:27-33;
run_squad.py:703-725 GradientClipper) — which exist to touch every gradient
tensor once, in large flat chunks, instead of launching one kernel per
tensor. Same idea here: the pytree is flattened into one 1-D buffer, a single
grid walks it in CHUNK-sized blocks, and the sum-of-squares reduction
accumulates across sequential grid steps into a (1, 1) block.

`clip_by_global_norm` composes the two into the reference GradientClipper
semantics: scale = max_norm / max(norm, max_norm) (no-op when under the
limit).
"""

from __future__ import annotations

import functools
from typing import Any, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

CHUNK = 64 * 1024  # elements per grid step (256 KB fp32 — well under VMEM)


def _sumsq_kernel(x_ref, acc_ref):
    i = pl.program_id(0)
    part = jnp.sum(jnp.square(x_ref[:].astype(jnp.float32)))

    @pl.when(i == 0)
    def _():
        acc_ref[0, 0] = part

    @pl.when(i > 0)
    def _():
        acc_ref[0, 0] = acc_ref[0, 0] + part


def _scale_kernel(x_ref, s_ref, o_ref):
    o_ref[:] = (x_ref[:].astype(jnp.float32) * s_ref[0, 0]).astype(o_ref.dtype)


def _flatten(tree: Any) -> Tuple[jax.Array, Any, Any]:
    leaves, treedef = jax.tree.flatten(tree)
    shapes = [(l.shape, l.dtype, l.size) for l in leaves]
    flat = jnp.concatenate([l.reshape(-1).astype(jnp.float32)
                            for l in leaves]) if leaves else jnp.zeros((0,))
    pad = (-flat.size) % CHUNK
    if pad:
        flat = jnp.pad(flat, (0, pad))
    return flat, treedef, shapes


def _unflatten(flat: jax.Array, treedef, shapes) -> Any:
    out = []
    offset = 0
    for shape, dtype, size in shapes:
        out.append(flat[offset:offset + size].reshape(shape).astype(dtype))
        offset += size
    return jax.tree.unflatten(treedef, out)


def global_l2_norm(tree: Any, interpret: bool = False) -> jax.Array:
    """sqrt(sum of squares over every leaf) — one fused pass
    (amp_C multi_tensor_l2norm semantics)."""
    flat, _, _ = _flatten(tree)
    if flat.size == 0:
        return jnp.zeros((), jnp.float32)
    grid = (flat.size // CHUNK,)
    sumsq = pl.pallas_call(
        _sumsq_kernel,
        grid=grid,
        in_specs=[pl.BlockSpec((CHUNK,), lambda i: (i,))],
        out_specs=pl.BlockSpec((1, 1), lambda i: (0, 0)),
        out_shape=jax.ShapeDtypeStruct((1, 1), jnp.float32),
        interpret=interpret,
    )(flat)
    return jnp.sqrt(sumsq[0, 0])


def scale_tree(tree: Any, scale: jax.Array, interpret: bool = False) -> Any:
    """tree * scale in one fused flat pass (amp_C multi_tensor_scale)."""
    flat, treedef, shapes = _flatten(tree)
    if flat.size == 0:
        return tree
    grid = (flat.size // CHUNK,)
    scaled = pl.pallas_call(
        _scale_kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((CHUNK,), lambda i: (i,)),
            pl.BlockSpec((1, 1), lambda i: (0, 0)),
        ],
        out_specs=pl.BlockSpec((CHUNK,), lambda i: (i,)),
        out_shape=jax.ShapeDtypeStruct(flat.shape, jnp.float32),
        interpret=interpret,
    )(flat, jnp.asarray(scale, jnp.float32).reshape(1, 1))
    return _unflatten(scaled, treedef, shapes)


def clip_by_global_norm(tree: Any, max_norm: float,
                        interpret: bool = False) -> Tuple[Any, jax.Array]:
    """Reference GradientClipper.step semantics (run_squad.py:703-725):
    if ||g|| > max_norm, scale all grads by max_norm/||g||. Returns
    (clipped_tree, norm)."""
    norm = global_l2_norm(tree, interpret=interpret)
    scale = jnp.where(norm > max_norm, max_norm / jnp.maximum(norm, 1e-30),
                      1.0)
    return scale_tree(tree, scale, interpret=interpret), norm
