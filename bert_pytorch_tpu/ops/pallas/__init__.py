"""Pallas TPU kernels — the framework's replacement for the reference's
native CUDA dependencies (SURVEY §2.3):

  layernorm.py        <- apex FusedLayerNormAffineFunction (modeling.py:303)
  flash_attention.py  <- (no reference equivalent; the TPU-correct way to run
                         the attention inner loop without materializing SxS)
  fused_optim.py      <- apex amp_C multi_tensor_lamb stage1+2 / FusedLAMB
                         (optimization.py:27-33, run_squad.py:703-725)

History note on fused_optim: earlier rounds deliberately skipped a
multi-tensor update kernel — measured on v5e (BERT-Large, batch 48) the
jitted optax LAMB + global-norm chain ran within ~30% of the ~11.4 ms
HBM-bandwidth floor, and the CUDA kernels existed mainly because torch
eager launched one kernel per tensor. That measurement was of the
REPLICATED update. Under ZeRO-1 the update runs on shard-shaped leaves
pinned by sharding constraints, where XLA no longer folds the long tail
of small leaves into the big fusions; the bucketed stage1/stage2 kernels
bound the update to O(buckets) launches (norm reductions stay outside, in
optim/lamb.py / parallel/coalesce.py). Off-TPU an XLA fallback evaluating
the same expressions per leaf — bit-identical to the unfused chain — is
selected automatically; see fused_optim.py's numerics contract for the
few-ulp kernel-vs-fallback bound.

Every kernel has an interpret-mode path so the test suite exercises the same
code on CPU; on-device compilation happens only on TPU backends.
"""

from bert_pytorch_tpu.ops.pallas.layernorm import layer_norm_pallas  # noqa: F401
from bert_pytorch_tpu.ops.pallas.flash_attention import flash_attention  # noqa: F401
from bert_pytorch_tpu.ops.pallas.fused_optim import (  # noqa: F401
    lamb_stage1, lamb_stage2)
