"""Pallas TPU kernels — the framework's replacement for the reference's
native CUDA dependencies (SURVEY §2.3):

  layernorm.py        <- apex FusedLayerNormAffineFunction (modeling.py:303)
  flash_attention.py  <- (no reference equivalent; the TPU-correct way to run
                         the attention inner loop without materializing SxS)
  multi_tensor.py     <- amp_C multi_tensor_l2norm / multi_tensor_scale
                         (optimization.py:27-33, run_squad.py:703-725)

Every kernel has an interpret-mode path so the test suite exercises the same
code on CPU; on-device compilation happens only on TPU backends.
"""

from bert_pytorch_tpu.ops.pallas.layernorm import layer_norm_pallas  # noqa: F401
from bert_pytorch_tpu.ops.pallas.flash_attention import flash_attention  # noqa: F401
