"""Pallas TPU kernels — the framework's replacement for the reference's
native CUDA dependencies (SURVEY §2.3):

  layernorm.py        <- apex FusedLayerNormAffineFunction (modeling.py:303)
  flash_attention.py  <- (no reference equivalent; the TPU-correct way to run
                         the attention inner loop without materializing SxS)

The reference's amp_C multi-tensor kernels (multi_tensor_l2norm /
multi_tensor_scale / lamb stage1+2, optimization.py:27-33,
run_squad.py:703-725) intentionally have NO Pallas equivalent here: measured
on v5e (BERT-Large, batch 48), the jitted optax LAMB + global-norm chain
costs ~16 ms/step against an ~11.4 ms HBM-bandwidth floor — XLA already
fuses the flat update chain to within ~30% of the physical limit, so a
hand-written multi-tensor kernel could recover at most ~1% of end-to-end
step time. The CUDA kernels existed because torch eager launched one kernel
per tensor; under jit that problem does not exist.

Every kernel has an interpret-mode path so the test suite exercises the same
code on CPU; on-device compilation happens only on TPU backends.
"""

from bert_pytorch_tpu.ops.pallas.layernorm import layer_norm_pallas  # noqa: F401
from bert_pytorch_tpu.ops.pallas.flash_attention import flash_attention  # noqa: F401
