"""Fused LayerNorm Pallas kernel with hand-written VJP.

Replaces apex's FusedLayerNormAffineFunction CUDA kernel (reference
src/modeling.py:303,320-323; eps 1e-12). One pass over rows computes
mean/rstd/normalized output; the backward kernel fuses dx with the dscale /
dbias cross-row reductions, accumulating partials across sequential grid
steps (TPU grid iteration is sequential, so '+=' into a fixed output block
is a legal reduction).

Layout: input flattened to (R, E) rows; blocks of ROWS rows; E (the hidden
size) must be a multiple of 128 (lane width) — ops/layernorm.py gates the
dispatch and falls back to the XLA path otherwise. All refs are 2D: scale /
bias ride as (1, E), row statistics as (ROWS, 1).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

ROWS = 256  # rows per grid step


def _fwd_kernel(x_ref, scale_ref, bias_ref, y_ref, mean_ref, rstd_ref, *,
                eps: float):
    x = x_ref[:].astype(jnp.float32)
    mean = jnp.mean(x, axis=-1, keepdims=True)
    centered = x - mean
    var = jnp.mean(centered * centered, axis=-1, keepdims=True)
    rstd = jax.lax.rsqrt(var + eps)
    y = centered * rstd
    y_ref[:] = (y * scale_ref[:].astype(jnp.float32)
                + bias_ref[:].astype(jnp.float32)).astype(y_ref.dtype)
    mean_ref[:] = mean
    rstd_ref[:] = rstd


def _bwd_kernel(x_ref, scale_ref, mean_ref, rstd_ref, g_ref,
                dx_ref, dscale_ref, dbias_ref):
    i = pl.program_id(0)
    x = x_ref[:].astype(jnp.float32)
    g = g_ref[:].astype(jnp.float32)
    scale = scale_ref[:].astype(jnp.float32)
    mean = mean_ref[:]
    rstd = rstd_ref[:]

    xhat = (x - mean) * rstd
    gs = g * scale
    # dx = rstd * (gs - mean(gs) - xhat * mean(gs * xhat))
    E = x.shape[-1]
    m1 = jnp.sum(gs, axis=-1, keepdims=True) / E
    m2 = jnp.sum(gs * xhat, axis=-1, keepdims=True) / E
    dx_ref[:] = (rstd * (gs - m1 - xhat * m2)).astype(dx_ref.dtype)

    part_dscale = jnp.sum(g * xhat, axis=0, keepdims=True)
    part_dbias = jnp.sum(g, axis=0, keepdims=True)

    @pl.when(i == 0)
    def _():
        dscale_ref[:] = part_dscale
        dbias_ref[:] = part_dbias

    @pl.when(i > 0)
    def _():
        dscale_ref[:] = dscale_ref[:] + part_dscale
        dbias_ref[:] = dbias_ref[:] + part_dbias


def _pad_rows(x2, rows):
    R = x2.shape[0]
    pad = (-R) % rows
    if pad:
        x2 = jnp.pad(x2, ((0, pad), (0, 0)))
    return x2, R


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def layer_norm_pallas(x, scale, bias, eps: float = 1e-12,
                      interpret: bool = False):
    y, _, _ = _forward(x, scale, bias, eps, interpret)
    return y


def _forward(x, scale, bias, eps, interpret):
    orig_shape = x.shape
    E = orig_shape[-1]
    x2, R = _pad_rows(x.reshape(-1, E), ROWS)
    Rp = x2.shape[0]
    grid = (Rp // ROWS,)
    y, mean, rstd = pl.pallas_call(
        functools.partial(_fwd_kernel, eps=eps),
        grid=grid,
        in_specs=[
            pl.BlockSpec((ROWS, E), lambda i: (i, 0)),
            pl.BlockSpec((1, E), lambda i: (0, 0)),
            pl.BlockSpec((1, E), lambda i: (0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((ROWS, E), lambda i: (i, 0)),
            pl.BlockSpec((ROWS, 1), lambda i: (i, 0)),
            pl.BlockSpec((ROWS, 1), lambda i: (i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((Rp, E), x.dtype),
            jax.ShapeDtypeStruct((Rp, 1), jnp.float32),
            jax.ShapeDtypeStruct((Rp, 1), jnp.float32),
        ],
        interpret=interpret,
    )(x2, scale.reshape(1, E), bias.reshape(1, E))
    return y[:R].reshape(orig_shape), mean, rstd


def _fwd_rule(x, scale, bias, eps, interpret):
    y, mean, rstd = _forward(x, scale, bias, eps, interpret)
    return y, (x, scale, mean, rstd)


def _bwd_rule(eps, interpret, res, g):
    x, scale, mean, rstd = res
    orig_shape = x.shape
    E = orig_shape[-1]
    x2, R = _pad_rows(x.reshape(-1, E), ROWS)
    g2, _ = _pad_rows(g.reshape(-1, E), ROWS)
    Rp = x2.shape[0]
    grid = (Rp // ROWS,)
    dx, dscale, dbias = pl.pallas_call(
        _bwd_kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((ROWS, E), lambda i: (i, 0)),
            pl.BlockSpec((1, E), lambda i: (0, 0)),
            pl.BlockSpec((ROWS, 1), lambda i: (i, 0)),
            pl.BlockSpec((ROWS, 1), lambda i: (i, 0)),
            pl.BlockSpec((ROWS, E), lambda i: (i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((ROWS, E), lambda i: (i, 0)),
            pl.BlockSpec((1, E), lambda i: (0, 0)),  # fixed block: reduction
            pl.BlockSpec((1, E), lambda i: (0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((Rp, E), x.dtype),
            jax.ShapeDtypeStruct((1, E), jnp.float32),
            jax.ShapeDtypeStruct((1, E), jnp.float32),
        ],
        interpret=interpret,
    )(x2, scale.reshape(1, E), mean, rstd, g2)
    return (dx[:R].reshape(orig_shape),
            dscale.reshape(E).astype(scale.dtype),
            dbias.reshape(E).astype(scale.dtype))


layer_norm_pallas.defvjp(_fwd_rule, _bwd_rule)


# ---------------------------------------------------------------------------
# fused residual + dropout + LayerNorm
# ---------------------------------------------------------------------------
#
# y = LN(residual + dropout(x)) is the tail of BOTH residual sites in every
# BertLayer (dense -> dropout -> LN(residual + .), reference
# src/modeling.py:439-487). Keeping dropout in the XLA graph next to a
# Pallas LN custom call forces the mask bits and the dropped tensor through
# HBM (XLA cannot fuse elementwise producers into a custom call), and even
# with the XLA LN the saved-for-backward mask traffic bloats every
# surrounding matmul fusion — measured 13 MFU points at seq128
# (results/ablate128.jsonl: no_hidden_dropout 66.1% vs baseline 53.0%).
#
# This kernel evaluates the keep-mask from a counter-based hash of the
# (global row, column, seed) — the same construction flash_attention.py uses
# for attention dropout — so the mask NEVER exists in HBM: the forward
# applies it inline, the backward regenerates it from the same counters.
# Residuals saved for backward are (x, residual, mean, rstd): no dropped
# tensor, no LN input h, no mask.


# The keep-mask hash is shared with the XLA fallback — ONE implementation
# (ops/layernorm.row_col_keep) so the two paths cannot drift. Pure jnp, so
# it traces inside the Pallas kernel unchanged.
from bert_pytorch_tpu.ops.layernorm import row_col_keep as _row_col_keep


def _adln_fwd_kernel(seed_ref, x_ref, res_ref, scale_ref, bias_ref,
                     y_ref, mean_ref, rstd_ref, *, eps: float, rate: float):
    i = pl.program_id(0)
    x = x_ref[:].astype(jnp.float32)
    if rate > 0.0:
        keep = _row_col_keep(seed_ref[0], i * x.shape[0], x.shape[0],
                             x.shape[1], rate)
        x = jnp.where(keep, x / (1.0 - rate), 0.0)
    h = res_ref[:].astype(jnp.float32) + x
    mean = jnp.mean(h, axis=-1, keepdims=True)
    centered = h - mean
    var = jnp.mean(centered * centered, axis=-1, keepdims=True)
    rstd = jax.lax.rsqrt(var + eps)
    y = centered * rstd
    y_ref[:] = (y * scale_ref[:].astype(jnp.float32)
                + bias_ref[:].astype(jnp.float32)).astype(y_ref.dtype)
    mean_ref[:] = mean
    rstd_ref[:] = rstd


def _adln_bwd_kernel(seed_ref, x_ref, res_ref, scale_ref, mean_ref, rstd_ref,
                     g_ref, dx_ref, dres_ref, dscale_ref, dbias_ref, *,
                     rate: float):
    i = pl.program_id(0)
    x = x_ref[:].astype(jnp.float32)
    g = g_ref[:].astype(jnp.float32)
    scale = scale_ref[:].astype(jnp.float32)
    mean = mean_ref[:]
    rstd = rstd_ref[:]

    if rate > 0.0:
        keep = _row_col_keep(seed_ref[0], i * x.shape[0], x.shape[0],
                             x.shape[1], rate)
        xd = jnp.where(keep, x / (1.0 - rate), 0.0)
    else:
        xd = x
    h = res_ref[:].astype(jnp.float32) + xd
    xhat = (h - mean) * rstd
    gs = g * scale
    E = x.shape[-1]
    m1 = jnp.sum(gs, axis=-1, keepdims=True) / E
    m2 = jnp.sum(gs * xhat, axis=-1, keepdims=True) / E
    dh = rstd * (gs - m1 - xhat * m2)
    dres_ref[:] = dh.astype(dres_ref.dtype)
    if rate > 0.0:
        dx_ref[:] = jnp.where(keep, dh / (1.0 - rate), 0.0).astype(
            dx_ref.dtype)
    else:
        dx_ref[:] = dh.astype(dx_ref.dtype)

    part_dscale = jnp.sum(g * xhat, axis=0, keepdims=True)
    part_dbias = jnp.sum(g, axis=0, keepdims=True)

    @pl.when(i == 0)
    def _():
        dscale_ref[:] = part_dscale
        dbias_ref[:] = part_dbias

    @pl.when(i > 0)
    def _():
        dscale_ref[:] = dscale_ref[:] + part_dscale
        dbias_ref[:] = dbias_ref[:] + part_dbias


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7))
def add_dropout_layer_norm_pallas(x, residual, scale, bias, seed,
                                  rate: float, eps: float = 1e-12,
                                  interpret: bool = False):
    """y = LayerNorm(residual + dropout(x, rate)); mask from the in-kernel
    counter hash keyed on (flat row, column, seed). seed: traced int32
    scalar (fresh per step); non-differentiable."""
    y, _, _ = _adln_forward(x, residual, scale, bias, seed, rate, eps,
                            interpret)
    return y


def _adln_forward(x, residual, scale, bias, seed, rate, eps, interpret):
    orig_shape = x.shape
    E = orig_shape[-1]
    x2, R = _pad_rows(x.reshape(-1, E), ROWS)
    r2, _ = _pad_rows(residual.reshape(-1, E), ROWS)
    Rp = x2.shape[0]
    grid = (Rp // ROWS,)
    seed_arr = jnp.asarray(seed, jnp.int32).reshape(1)
    y, mean, rstd = pl.pallas_call(
        functools.partial(_adln_fwd_kernel, eps=eps, rate=rate),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1,), lambda i: (0,)),          # seed
            pl.BlockSpec((ROWS, E), lambda i: (i, 0)),
            pl.BlockSpec((ROWS, E), lambda i: (i, 0)),
            pl.BlockSpec((1, E), lambda i: (0, 0)),
            pl.BlockSpec((1, E), lambda i: (0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((ROWS, E), lambda i: (i, 0)),
            pl.BlockSpec((ROWS, 1), lambda i: (i, 0)),
            pl.BlockSpec((ROWS, 1), lambda i: (i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((Rp, E), x.dtype),
            jax.ShapeDtypeStruct((Rp, 1), jnp.float32),
            jax.ShapeDtypeStruct((Rp, 1), jnp.float32),
        ],
        interpret=interpret,
    )(seed_arr, x2, r2, scale.reshape(1, E), bias.reshape(1, E))
    return y[:R].reshape(orig_shape), mean, rstd


def _adln_fwd_rule(x, residual, scale, bias, seed, rate, eps, interpret):
    y, mean, rstd = _adln_forward(x, residual, scale, bias, seed, rate, eps,
                                  interpret)
    return y, (x, residual, scale, mean, rstd, seed)


def _adln_bwd_rule(rate, eps, interpret, res, g):
    x, residual, scale, mean, rstd, seed = res
    orig_shape = x.shape
    E = orig_shape[-1]
    x2, R = _pad_rows(x.reshape(-1, E), ROWS)
    r2, _ = _pad_rows(residual.reshape(-1, E), ROWS)
    g2, _ = _pad_rows(g.reshape(-1, E), ROWS)
    Rp = x2.shape[0]
    grid = (Rp // ROWS,)
    seed_arr = jnp.asarray(seed, jnp.int32).reshape(1)
    dx, dres, dscale, dbias = pl.pallas_call(
        functools.partial(_adln_bwd_kernel, rate=rate),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1,), lambda i: (0,)),          # seed
            pl.BlockSpec((ROWS, E), lambda i: (i, 0)),
            pl.BlockSpec((ROWS, E), lambda i: (i, 0)),
            pl.BlockSpec((1, E), lambda i: (0, 0)),
            pl.BlockSpec((ROWS, 1), lambda i: (i, 0)),
            pl.BlockSpec((ROWS, 1), lambda i: (i, 0)),
            pl.BlockSpec((ROWS, E), lambda i: (i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((ROWS, E), lambda i: (i, 0)),
            pl.BlockSpec((ROWS, E), lambda i: (i, 0)),
            pl.BlockSpec((1, E), lambda i: (0, 0)),  # fixed block: reduction
            pl.BlockSpec((1, E), lambda i: (0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((Rp, E), x.dtype),
            jax.ShapeDtypeStruct((Rp, E), x.dtype),
            jax.ShapeDtypeStruct((1, E), jnp.float32),
            jax.ShapeDtypeStruct((1, E), jnp.float32),
        ],
        interpret=interpret,
    )(seed_arr, x2, r2, scale.reshape(1, E), mean, rstd, g2)
    return (dx[:R].reshape(orig_shape), dres[:R].reshape(orig_shape),
            dscale.reshape(E).astype(scale.dtype),
            dbias.reshape(E).astype(scale.dtype),
            # integer seed primal -> float0 cotangent (JAX convention; an
            # int32 zeros trips stricter custom_vjp aval checking)
            jax.custom_derivatives.zero_from_primal(
                jnp.asarray(seed, jnp.int32)))


add_dropout_layer_norm_pallas.defvjp(_adln_fwd_rule, _adln_bwd_rule)
