"""Blockwise fused attention (flash attention) for TPU, fwd + bwd.

The TPU answer to the reference's explicit torch matmul attention
(src/modeling.py:403-437), which materializes the (B, H, S, S) score matrix
in memory: here scores live only as (BLK_Q, BLK_K) tiles in VMEM with an
online-softmax running max/sum, so HBM traffic is O(S*D) not O(S^2). Backward
recomputes tiles from the saved logsumexp (standard flash algorithm).

Attention dropout matches the reference semantics (dropout on normalized
probs, run_pretraining hot path) and is generated *positionally*: a
counter-based hash of (seed, head, q_pos, k_pos) yields the keep mask, so
forward and both backward kernels reproduce the identical mask regardless of
tile shapes — and the implementation runs under interpret mode on CPU (TPU
PRNG primitives don't).

Layout contract: q/k/v are (B, S, H, D); bias broadcastable (B, 1, 1, S)
additive mask. S must divide by the q/k block size (ops/attention.py gates).

Two kernel-grid layouts exist behind the same public function:

- **native** (default where it fits): the kernels consume the model's
  (B, S, H, D) arrays directly — grid (B, S/BLK_Q) forward / (B,) fused
  backward, blocks span the FULL (H, D) trailing dims (Mosaic's tiling rule
  rejects head-singleton (1, D<128) blocks, so the head axis is folded into
  an in-kernel loop instead of the grid), and each program iterates heads
  internally on (S, D) slices. No (B,S,H,D)->(BH,S,D) transpose pass on
  q/k/v/do/outputs — the 4.9% layout-copy bucket in the seq512 step-time
  budget (docs/PERF.md) disappears. Per-program VMEM grows by H, so the
  path is gated on S*H*D (FLASH_NATIVE_VMEM budget, default 12 MiB for the
  ~9 resident (S, H, D) bf16 tensors of the fused backward); BERT-Large
  seq512 (S=512, H=16, D=64 -> 1 MiB/tensor) fits comfortably.
- **bh** (fallback, and FLASH_LAYOUT=bh forces it): the original
  (BH, S, D) grid with a transpose pass either side — unbounded S via the
  split backward kernels.

Both layouts draw identical dropout masks (the (batch*heads + head) counter
the native head-loop folds in equals the bh grid's program id), so they are
the same training run.

**Packed sequences** (`segment_ids`, the round-9 unpadded-pretraining path):
a (B, S) int32 array assigning each position a packing segment (1..n per
row, 0 = pad) restricts attention to `q_seg == k_seg` blocks — the static-
shape TPU form of un-padding ("Boosting Distributed Training Performance of
the Unpadded BERT Model", PAPERS.md). The mask is applied additively inside
every kernel exactly like the padding bias, and because segments occupy
contiguous position ranges, a (q, k) tile whose segment ranges don't
intersect is *skipped wholesale* (`jax.lax.cond` around the tile body — no
scores, no dropout hash, no dots), which is where the block-diagonal FLOP
saving is realized. FLASH_SEG_SKIP=0 disables the skip (mask-only, for A/B
isolation); skipped and masked-but-computed tiles contribute exactly zero
either way, so the two settings are bit-identical on every non-pad row.
Rows of all-pad positions (segment 0) have their outputs explicitly zeroed
in the forward epilogue (their degenerate softmax would otherwise emit
tile-layout-dependent garbage), so pad activations are identical across
skip settings, layouts and the XLA fallback — keeping full-(B, S, E)
consumers like the K-FAC factor taps kernel-configuration-independent.
Their gradients are zero because no loss term reads pad positions.
"""

from __future__ import annotations

import functools
import os
import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

# Block sizes (env-overridable for tuning sweeps). 512x512 measured 13%
# faster end-to-end than 128x128 at BERT-Large seq512 on v5e (bigger dots
# amortize the per-tile softmax bookkeeping; the (blk_q, blk_k) fp32 score
# tile plus q/k/v blocks is ~1.5 MB of VMEM at D=64). _pick_block halves
# the target until it divides S, falling back to one whole-sequence block
# only when no power-of-two fraction >= 128 does.


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, default))
    except (TypeError, ValueError):
        return default


DEFAULT_BLK_Q = _env_int("FLASH_BLK_Q", 512)
DEFAULT_BLK_K = _env_int("FLASH_BLK_K", 512)
NEG_INF = -1e30
_SEG_BIG = 2 ** 30  # sentinel above any real segment index


def _seg_skip_enabled() -> bool:
    """FLASH_SEG_SKIP=0 disables block-level tile skipping (the masked
    tiles are computed and contribute exact zeros instead). A/B hatch in
    the style of FLASH_LAYOUT/FLASH_BWD."""
    return os.environ.get("FLASH_SEG_SKIP", "1") != "0"


def _seg_allowed(segq, segk):
    """(bq,) q segments x (bk,) k segments -> (bq, bk) bool, True where
    attention is allowed: same segment, and not pad (segment 0)."""
    qs = segq[:, None]
    return (qs == segk[None, :]) & (qs > 0)


def _seg_overlap(segq, segk):
    """Scalar bool: does this (q, k) tile contain ANY allowed pair?
    Segments occupy contiguous, increasing position ranges within a row, so
    a tile's non-pad segment ids form a contiguous integer range — two
    tiles share a segment iff their [min, max] ranges intersect. O(bq+bk)
    compares instead of the O(bq*bk) mask."""
    qs = segq[:, None]
    ks = segk[:, None]
    big = jnp.int32(_SEG_BIG)
    qmx = jnp.max(qs)
    kmx = jnp.max(ks)
    qmn = jnp.min(jnp.where(qs > 0, qs, big))
    kmn = jnp.min(jnp.where(ks > 0, ks, big))
    return (qmx > 0) & (kmx > 0) & (qmx >= kmn) & (kmx >= qmn)


def _maybe_skip(has_segments: bool, segq, segk, tile_fn, carry):
    """Run tile_fn(carry) -> carry, skipping it when segment ranges prove
    the tile all-masked. Without segments (or with FLASH_SEG_SKIP=0) the
    tile always runs; masked tiles then contribute exact zeros, so both
    settings produce bit-identical non-pad outputs."""
    if not has_segments or not _seg_skip_enabled():
        return tile_fn(carry)
    return jax.lax.cond(_seg_overlap(segq, segk), tile_fn,
                        lambda c: c, carry)


def _pick_block(s: int, target: int) -> int:
    while target >= 128:
        if s % target == 0:
            return target
        target //= 2
    return s


def _keep_mask(seed, bh, q0, k0, bq, bk, rate: float):
    """Counter-based keep mask over global (q_pos, k_pos) — two
    multiply-xorshift rounds on a per-position counter, integer threshold
    compare. uint32 VPU ops only.

    The mask is evaluated over S^2 elements per (batch, head) in forward AND
    backward, so every op here is step-time. Two rounds are the floor that
    keeps dropout statistics clean: one round leaves 0.23 cross-seed mask
    correlation (additive seed injection is worse still — near-duplicate
    masks for some seed pairs); with two rounds keep-rate bias < 5e-4,
    cross-seed / adjacent-position correlations are chance-level (<0.015),
    verified over 24 seeds x 256^2 at rates 0.1/0.3. The final murmur
    xor-shift only feeds bits below the 23 used by the compare, and the
    int compare replaces the bitcast->f32->scale->cmp tail; both are dropped
    (~3 VPU ops/element saved, identical top-23-bit statistics)."""
    rows = jax.lax.broadcasted_iota(jnp.uint32, (bq, bk), 0) + jnp.uint32(q0)
    cols = jax.lax.broadcasted_iota(jnp.uint32, (bq, bk), 1) + jnp.uint32(k0)
    x = (rows * jnp.uint32(0x9E3779B1)) ^ (cols * jnp.uint32(0x85EBCA77))
    x = x ^ (jnp.uint32(seed) + jnp.uint32(bh) * jnp.uint32(0xC2B2AE3D))
    x = x ^ (x >> 16)
    x = x * jnp.uint32(0x7FEB352D)
    x = x ^ (x >> 15)
    x = x * jnp.uint32(0x846CA68B)
    # top 23 bits uniform in [0, 2^23); keep iff >= rate * 2^23
    return (x >> 9) >= jnp.uint32(int(rate * (1 << 23)))


def _fwd_kernel(seed_ref, q_ref, k_ref, v_ref, bias_ref, segq_ref, segk_ref,
                o_ref, lse_ref, *, scale: float, blk_k: int, rate: float,
                has_bias: bool, has_segments: bool):
    bh = pl.program_id(0)
    qi = pl.program_id(1)
    bq = q_ref.shape[1]
    d = q_ref.shape[2]
    s_len = k_ref.shape[1]
    nk = s_len // blk_k

    # matmul inputs stay in the stored dtype (bf16): the MXU multiplies
    # bf16 x bf16 into an fp32 accumulator at full rate, while fp32 inputs
    # run at a fraction of it. Softmax statistics and accumulators are fp32
    # — identical numerics to the XLA attention path (probs cast to the
    # compute dtype before the PV matmul).
    q = q_ref[0]
    segq = segq_ref[0, 0] if has_segments else None
    carry = (jnp.full((bq, 1), NEG_INF, jnp.float32),
             jnp.zeros((bq, 1), jnp.float32),
             jnp.zeros((bq, d), jnp.float32))

    for j in range(nk):
        segk = (segk_ref[0, 0, j * blk_k:(j + 1) * blk_k]
                if has_segments else None)

        def tile(carry, j=j, segk=segk):
            m, l, acc = carry
            kb = k_ref[0, j * blk_k:(j + 1) * blk_k, :]
            vb = v_ref[0, j * blk_k:(j + 1) * blk_k, :]
            s = jax.lax.dot_general(
                q, kb, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32) * scale
            if has_bias:
                s = s + bias_ref[0, 0, j * blk_k:(j + 1) * blk_k][None, :]
            if has_segments:
                s = jnp.where(_seg_allowed(segq, segk), s, NEG_INF)
            m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
            alpha = jnp.exp(m - m_new)
            p = jnp.exp(s - m_new)
            l = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
            if rate > 0.0:
                keep = _keep_mask(seed_ref[0], bh, qi * bq, j * blk_k, bq,
                                  blk_k, rate)
                p_acc = jnp.where(keep, p, 0.0)
            else:
                p_acc = p
            acc = acc * alpha + jnp.dot(p_acc.astype(vb.dtype), vb,
                                        preferred_element_type=jnp.float32)
            return m_new, l, acc

        carry = _maybe_skip(has_segments, segq, segk, tile, carry)

    m, l, acc = carry
    l_safe = jnp.maximum(l, 1e-30)
    out = acc / l_safe
    if rate > 0.0:
        out = out / (1.0 - rate)
    if has_segments:
        # pad (segment-0) rows attend nowhere; without this their softmax
        # degenerates to skip-/tile-layout-dependent garbage (uniform over
        # whatever tiles ran). Zeroing makes every path — skip on/off, both
        # layouts, XLA fallback — emit identical pad activations, which
        # keeps downstream consumers of full (B, S, E) hiddens (K-FAC
        # factor taps) bit-independent of the kernel configuration.
        out = jnp.where(segq[:, None] > 0, out, 0.0)
    o_ref[0] = out.astype(o_ref.dtype)
    lse_ref[0, 0] = (m + jnp.log(l_safe))[:, 0]


def _dq_kernel(seed_ref, q_ref, k_ref, v_ref, bias_ref, segq_ref, segk_ref,
               lse_ref, delta_ref, do_ref, dq_ref, *, scale: float,
               blk_k: int, rate: float, has_bias: bool, has_segments: bool):
    bh = pl.program_id(0)
    qi = pl.program_id(1)
    bq = q_ref.shape[1]
    s_len = k_ref.shape[1]
    nk = s_len // blk_k

    q = q_ref[0]
    do = do_ref[0]
    segq = segq_ref[0, 0] if has_segments else None
    lse = lse_ref[0, 0][:, None]
    delta = delta_ref[0, 0][:, None]
    dq = jnp.zeros((q.shape[0], q.shape[1]), jnp.float32)

    for j in range(nk):
        segk = (segk_ref[0, 0, j * blk_k:(j + 1) * blk_k]
                if has_segments else None)

        def tile(dq, j=j, segk=segk):
            kb = k_ref[0, j * blk_k:(j + 1) * blk_k, :]
            vb = v_ref[0, j * blk_k:(j + 1) * blk_k, :]
            s = jax.lax.dot_general(
                q, kb, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32) * scale
            if has_bias:
                s = s + bias_ref[0, 0, j * blk_k:(j + 1) * blk_k][None, :]
            if has_segments:
                s = jnp.where(_seg_allowed(segq, segk), s, NEG_INF)
            p = jnp.exp(s - lse)
            dp = jax.lax.dot_general(
                do, vb, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32)
            if rate > 0.0:
                keep = _keep_mask(seed_ref[0], bh, qi * bq, j * blk_k, bq,
                                  blk_k, rate)
                dp = jnp.where(keep, dp / (1.0 - rate), 0.0)
            ds = p * (dp - delta)
            return dq + jnp.dot(ds.astype(kb.dtype), kb,
                                preferred_element_type=jnp.float32) * scale

        dq = _maybe_skip(has_segments, segq, segk, tile, dq)

    dq_ref[0] = dq.astype(dq_ref.dtype)


def _dkv_kernel(seed_ref, q_ref, k_ref, v_ref, bias_ref, segq_ref, segk_ref,
                lse_ref, delta_ref, do_ref, dk_ref, dv_ref, *, scale: float,
                blk_q: int, rate: float, has_bias: bool, has_segments: bool):
    bh = pl.program_id(0)
    kj = pl.program_id(1)
    bk = k_ref.shape[1]
    s_len = q_ref.shape[1]
    nq = s_len // blk_q

    kb = k_ref[0]
    vb = v_ref[0]
    segk = segk_ref[0, 0] if has_segments else None
    if has_bias:
        bias = bias_ref[0, 0][None, :]  # (1, BLK_K)
    carry = (jnp.zeros(kb.shape, jnp.float32),
             jnp.zeros(vb.shape, jnp.float32))

    for i in range(nq):
        segq = (segq_ref[0, 0, i * blk_q:(i + 1) * blk_q]
                if has_segments else None)

        def tile(carry, i=i, segq=segq):
            dk, dv = carry
            qb = q_ref[0, i * blk_q:(i + 1) * blk_q, :]
            dob = do_ref[0, i * blk_q:(i + 1) * blk_q, :]
            lse = lse_ref[0, 0, i * blk_q:(i + 1) * blk_q][:, None]
            delta = delta_ref[0, 0, i * blk_q:(i + 1) * blk_q][:, None]
            s = jax.lax.dot_general(
                qb, kb, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32) * scale
            if has_bias:
                s = s + bias
            if has_segments:
                s = jnp.where(_seg_allowed(segq, segk), s, NEG_INF)
            p = jnp.exp(s - lse)
            if rate > 0.0:
                keep = _keep_mask(seed_ref[0], bh, i * blk_q, kj * bk, blk_q,
                                  bk, rate)
                p_drop = jnp.where(keep, p / (1.0 - rate), 0.0)
            else:
                p_drop = p
            dv = dv + jax.lax.dot_general(
                p_drop.astype(dob.dtype), dob, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
            dp = jax.lax.dot_general(
                dob, vb, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32)
            if rate > 0.0:
                dp = jnp.where(keep, dp / (1.0 - rate), 0.0)
            ds = p * (dp - delta)
            dk = dk + jax.lax.dot_general(
                ds.astype(qb.dtype), qb, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32) * scale
            return dk, dv

        carry = _maybe_skip(has_segments, segq, segk, tile, carry)

    dk, dv = carry
    dk_ref[0] = dk.astype(dk_ref.dtype)
    dv_ref[0] = dv.astype(dv_ref.dtype)


def _dqkv_kernel(seed_ref, q_ref, k_ref, v_ref, bias_ref, seg_ref, lse_ref,
                 delta_ref, do_ref, dq_ref, dk_ref, dv_ref, *, scale: float,
                 blk_q: int, blk_k: int, rate: float, has_bias: bool,
                 has_segments: bool):
    """Fused backward: one program per (batch*head) computes dq, dk and dv
    together, so the score tiles, softmax exp and dropout keep-masks are
    evaluated ONCE instead of once in _dq_kernel and again in _dkv_kernel.
    All accumulators live in VMEM — (S, D) fp32 x3 — which bounds this path
    to moderate S (the wrapper gates on S <= 2048; 3 x 2048 x 64 x 4B =
    1.5 MB); longer sequences fall back to the split kernels."""
    bh = pl.program_id(0)
    s_len = q_ref.shape[1]
    d = q_ref.shape[2]
    nq = s_len // blk_q
    nk = s_len // blk_k

    # per-k-block accumulators as plain Python lists — a (S, D) functional
    # scatter would lower to ops pallas rejects; disjoint static blocks
    # written once at the end need no scatter at all
    dk_blocks = [jnp.zeros((blk_k, d), jnp.float32) for _ in range(nk)]
    dv_blocks = [jnp.zeros((blk_k, d), jnp.float32) for _ in range(nk)]

    for i in range(nq):
        qb = q_ref[0, i * blk_q:(i + 1) * blk_q, :]
        dob = do_ref[0, i * blk_q:(i + 1) * blk_q, :]
        segq = (seg_ref[0, 0, i * blk_q:(i + 1) * blk_q]
                if has_segments else None)
        lse = lse_ref[0, 0, i * blk_q:(i + 1) * blk_q][:, None]
        delta = delta_ref[0, 0, i * blk_q:(i + 1) * blk_q][:, None]
        dq_i = jnp.zeros((blk_q, d), jnp.float32)
        for j in range(nk):
            segk = (seg_ref[0, 0, j * blk_k:(j + 1) * blk_k]
                    if has_segments else None)

            def tile(carry, i=i, j=j, qb=qb, dob=dob, segq=segq, segk=segk,
                     lse=lse, delta=delta):
                dq_i, dk_j, dv_j = carry
                kb = k_ref[0, j * blk_k:(j + 1) * blk_k, :]
                vb = v_ref[0, j * blk_k:(j + 1) * blk_k, :]
                s = jax.lax.dot_general(
                    qb, kb, (((1,), (1,)), ((), ())),
                    preferred_element_type=jnp.float32) * scale
                if has_bias:
                    s = s + bias_ref[0, 0, j * blk_k:(j + 1) * blk_k][None, :]
                if has_segments:
                    s = jnp.where(_seg_allowed(segq, segk), s, NEG_INF)
                p = jnp.exp(s - lse)
                dp = jax.lax.dot_general(
                    dob, vb, (((1,), (1,)), ((), ())),
                    preferred_element_type=jnp.float32)
                if rate > 0.0:
                    keep = _keep_mask(seed_ref[0], bh, i * blk_q, j * blk_k,
                                      blk_q, blk_k, rate)
                    p_drop = jnp.where(keep, p / (1.0 - rate), 0.0)
                    dp = jnp.where(keep, dp / (1.0 - rate), 0.0)
                else:
                    p_drop = p
                ds = (p * (dp - delta)).astype(qb.dtype)
                dq_i = dq_i + jnp.dot(
                    ds, kb, preferred_element_type=jnp.float32) * scale
                dk_j = dk_j + jax.lax.dot_general(
                    ds, qb, (((0,), (0,)), ((), ())),
                    preferred_element_type=jnp.float32) * scale
                dv_j = dv_j + jax.lax.dot_general(
                    p_drop.astype(dob.dtype), dob, (((0,), (0,)), ((), ())),
                    preferred_element_type=jnp.float32)
                return dq_i, dk_j, dv_j

            dq_i, dk_blocks[j], dv_blocks[j] = _maybe_skip(
                has_segments, segq, segk, tile,
                (dq_i, dk_blocks[j], dv_blocks[j]))
        dq_ref[0, i * blk_q:(i + 1) * blk_q, :] = dq_i.astype(dq_ref.dtype)

    for j in range(nk):
        sl = slice(j * blk_k, (j + 1) * blk_k)
        dk_ref[0, sl, :] = dk_blocks[j].astype(dk_ref.dtype)
        dv_ref[0, sl, :] = dv_blocks[j].astype(dv_ref.dtype)


# ---------------------------------------------------------------------------
# native-layout kernels: (B, S, H, D) in, no transpose pass
# ---------------------------------------------------------------------------


def _fwd_kernel_native(seed_ref, q_ref, k_ref, v_ref, bias_ref, segq_ref,
                       segk_ref, o_ref, lse_ref, *, scale: float, blk_k: int,
                       rate: float, has_bias: bool, has_segments: bool,
                       n_heads: int):
    """One program per (batch, q-block): loops heads, then k-blocks. Blocks
    span the full (H, D) trailing dims (Mosaic rejects head-singleton
    blocks); per-head (S, D) panels are static slices of the VMEM block.
    Math and dropout counters identical to _fwd_kernel — bh there is
    program_id(0) over a (B*H,) grid, here bi * n_heads + h."""
    bi = pl.program_id(0)
    qi = pl.program_id(1)
    bq = q_ref.shape[1]
    d = q_ref.shape[3]
    s_len = k_ref.shape[1]
    nk = s_len // blk_k
    segq = segq_ref[0, 0] if has_segments else None

    for hh in range(n_heads):
        q = q_ref[0, :, hh, :]
        carry = (jnp.full((bq, 1), NEG_INF, jnp.float32),
                 jnp.zeros((bq, 1), jnp.float32),
                 jnp.zeros((bq, d), jnp.float32))

        for j in range(nk):
            segk = (segk_ref[0, 0, j * blk_k:(j + 1) * blk_k]
                    if has_segments else None)

            def tile(carry, hh=hh, j=j, q=q, segk=segk):
                m, l, acc = carry
                kb = k_ref[0, j * blk_k:(j + 1) * blk_k, hh, :]
                vb = v_ref[0, j * blk_k:(j + 1) * blk_k, hh, :]
                s = jax.lax.dot_general(
                    q, kb, (((1,), (1,)), ((), ())),
                    preferred_element_type=jnp.float32) * scale
                if has_bias:
                    s = s + bias_ref[0, 0,
                                     j * blk_k:(j + 1) * blk_k][None, :]
                if has_segments:
                    s = jnp.where(_seg_allowed(segq, segk), s, NEG_INF)
                m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
                alpha = jnp.exp(m - m_new)
                p = jnp.exp(s - m_new)
                l = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
                if rate > 0.0:
                    keep = _keep_mask(seed_ref[0], bi * n_heads + hh,
                                      qi * bq, j * blk_k, bq, blk_k, rate)
                    p_acc = jnp.where(keep, p, 0.0)
                else:
                    p_acc = p
                acc = acc * alpha + jnp.dot(
                    p_acc.astype(vb.dtype), vb,
                    preferred_element_type=jnp.float32)
                return m_new, l, acc

            carry = _maybe_skip(has_segments, segq, segk, tile, carry)

        m, l, acc = carry
        l_safe = jnp.maximum(l, 1e-30)
        out = acc / l_safe
        if rate > 0.0:
            out = out / (1.0 - rate)
        if has_segments:
            # zero pad-row outputs — see _fwd_kernel
            out = jnp.where(segq[:, None] > 0, out, 0.0)
        o_ref[0, :, hh, :] = out.astype(o_ref.dtype)
        lse_ref[0, hh, :] = (m + jnp.log(l_safe))[:, 0]


def _dqkv_kernel_native(seed_ref, q_ref, k_ref, v_ref, bias_ref, seg_ref,
                        lse_ref, delta_ref, do_ref, dq_ref, dk_ref, dv_ref,
                        *, scale: float, blk_q: int, blk_k: int, rate: float,
                        has_bias: bool, has_segments: bool, n_heads: int):
    """Fused backward, one program per batch element: loops heads, then the
    (q-block, k-block) tiles of _dqkv_kernel. dq/dk/dv write straight into
    the (1, S, H, D) native-layout blocks — no epilogue transposes. VMEM
    holds ~7 (S, H, D) bf16 tensors plus per-head fp32 accumulators; the
    wrapper gates on that budget and falls back to the (BH, S, D) split
    path beyond it."""
    bi = pl.program_id(0)
    s_len = q_ref.shape[1]
    d = q_ref.shape[3]
    nq = s_len // blk_q
    nk = s_len // blk_k

    for hh in range(n_heads):
        dk_blocks = [jnp.zeros((blk_k, d), jnp.float32) for _ in range(nk)]
        dv_blocks = [jnp.zeros((blk_k, d), jnp.float32) for _ in range(nk)]

        for i in range(nq):
            qb = q_ref[0, i * blk_q:(i + 1) * blk_q, hh, :]
            dob = do_ref[0, i * blk_q:(i + 1) * blk_q, hh, :]
            segq = (seg_ref[0, 0, i * blk_q:(i + 1) * blk_q]
                    if has_segments else None)
            lse = lse_ref[0, hh, i * blk_q:(i + 1) * blk_q][:, None]
            delta = delta_ref[0, hh, i * blk_q:(i + 1) * blk_q][:, None]
            dq_i = jnp.zeros((blk_q, d), jnp.float32)
            for j in range(nk):
                segk = (seg_ref[0, 0, j * blk_k:(j + 1) * blk_k]
                        if has_segments else None)

                def tile(carry, hh=hh, i=i, j=j, qb=qb, dob=dob, segq=segq,
                         segk=segk, lse=lse, delta=delta):
                    dq_i, dk_j, dv_j = carry
                    kb = k_ref[0, j * blk_k:(j + 1) * blk_k, hh, :]
                    vb = v_ref[0, j * blk_k:(j + 1) * blk_k, hh, :]
                    s = jax.lax.dot_general(
                        qb, kb, (((1,), (1,)), ((), ())),
                        preferred_element_type=jnp.float32) * scale
                    if has_bias:
                        s = s + bias_ref[0, 0,
                                         j * blk_k:(j + 1) * blk_k][None, :]
                    if has_segments:
                        s = jnp.where(_seg_allowed(segq, segk), s, NEG_INF)
                    p = jnp.exp(s - lse)
                    dp = jax.lax.dot_general(
                        dob, vb, (((1,), (1,)), ((), ())),
                        preferred_element_type=jnp.float32)
                    if rate > 0.0:
                        keep = _keep_mask(seed_ref[0], bi * n_heads + hh,
                                          i * blk_q, j * blk_k, blk_q, blk_k,
                                          rate)
                        p_drop = jnp.where(keep, p / (1.0 - rate), 0.0)
                        dp = jnp.where(keep, dp / (1.0 - rate), 0.0)
                    else:
                        p_drop = p
                    ds = (p * (dp - delta)).astype(qb.dtype)
                    dq_i = dq_i + jnp.dot(
                        ds, kb, preferred_element_type=jnp.float32) * scale
                    dk_j = dk_j + jax.lax.dot_general(
                        ds, qb, (((0,), (0,)), ((), ())),
                        preferred_element_type=jnp.float32) * scale
                    dv_j = dv_j + jax.lax.dot_general(
                        p_drop.astype(dob.dtype), dob,
                        (((0,), (0,)), ((), ())),
                        preferred_element_type=jnp.float32)
                    return dq_i, dk_j, dv_j

                dq_i, dk_blocks[j], dv_blocks[j] = _maybe_skip(
                    has_segments, segq, segk, tile,
                    (dq_i, dk_blocks[j], dv_blocks[j]))
            dq_ref[0, i * blk_q:(i + 1) * blk_q, hh, :] = dq_i.astype(
                dq_ref.dtype)

        for j in range(nk):
            sl = slice(j * blk_k, (j + 1) * blk_k)
            dk_ref[0, sl, hh, :] = dk_blocks[j].astype(dk_ref.dtype)
            dv_ref[0, sl, hh, :] = dv_blocks[j].astype(dv_ref.dtype)


# ---------------------------------------------------------------------------
# host-side wrappers
# ---------------------------------------------------------------------------

def _to_bh(x):
    """(B, S, H, D) -> (B*H, S, D)."""
    b, s, h, d = x.shape
    return x.transpose(0, 2, 1, 3).reshape(b * h, s, d)


def _from_bh(x, b, h):
    bh, s, d = x.shape
    return x.reshape(b, h, s, d).transpose(0, 2, 1, 3)


def _use_native(s: int, h: int, d: int) -> bool:
    """Native (B, S, H, D) kernels iff the fused backward's per-program
    working set fits VMEM: ~9 resident (S, H, D)-sized tensors (7 bf16
    q/k/v/do/dq/dk/dv blocks + fp32 accumulators/score tiles rounded up).
    FLASH_LAYOUT=bh forces the transpose path (A/B isolation); FLASH_BWD=
    split implies it too (the split backward kernels only exist in bh
    layout, and they are what serves S beyond the VMEM gate anyway)."""
    if os.environ.get("FLASH_LAYOUT", "native") == "bh":
        return False
    if os.environ.get("FLASH_BWD", "fused") == "split":
        return False
    budget = _env_int("FLASH_NATIVE_VMEM", 12 * 2 ** 20)
    return 9 * s * h * d * 2 <= budget


def _seg_operand(segment_ids, b, s):
    """(B, S) int segment ids -> the (B, 1, S) kernel operand (mirrors the
    bias2 flattening so both layouts index it identically), or a (1, 1, 1)
    dummy when packing is off."""
    if segment_ids is None:
        return jnp.zeros((1, 1, 1), jnp.int32)
    return segment_ids.reshape(b, 1, s).astype(jnp.int32)


@functools.partial(jax.custom_vjp, nondiff_argnums=(6, 7))
def flash_attention(q, k, v, bias=None, segment_ids=None, dropout_seed=None,
                    dropout_rate: float = 0.0, interpret: bool = False):
    """q/k/v: (B, S, H, D); bias: (B, 1, 1, S) additive or None;
    segment_ids: (B, S) int32 packing segments (1..n, 0 = pad) or None —
    attention is restricted to q_seg == k_seg blocks, the packed-sequence
    block-diagonal mask. dropout_seed: () or (1,) int32 array (traced OK);
    required when dropout_rate > 0. Returns (B, S, H, D) in q.dtype.

    NOTE: bias is treated as NON-differentiable (its cotangent is zero) —
    it exists for padding masks, which are data, not parameters. A trainable
    additive bias (e.g. relative-position bias) must use the XLA attention
    path, which differentiates through the bias correctly. segment_ids are
    integer data (zero/float0 cotangent), like the seed."""
    out, _ = _flash_fwd(q, k, v, bias, segment_ids, dropout_seed,
                        dropout_rate, interpret)
    return out


def _flash_fwd(q, k, v, bias, segment_ids, seed, rate, interpret):
    b, s, h, d = q.shape
    blk_q = _pick_block(s, DEFAULT_BLK_Q)
    blk_k = _pick_block(s, DEFAULT_BLK_K)
    scale = 1.0 / (d ** 0.5)
    has_bias = bias is not None
    has_segments = segment_ids is not None
    # shared by both layouts: the cross-layout bit-parity contract depends
    # on identical bias flattening and seed packing, so they are built once
    bias2 = (bias.reshape(b, 1, s).astype(jnp.float32) if has_bias
             else jnp.zeros((1, 1, 1), jnp.float32))
    seg2 = _seg_operand(segment_ids, b, s)
    seed_arr = (jnp.zeros((1,), jnp.int32) if seed is None
                else jnp.asarray(seed, jnp.int32).reshape(1))

    if _use_native(s, h, d):
        bias_bs = (pl.BlockSpec((1, 1, s), lambda bi, qi: (bi, 0, 0))
                   if has_bias
                   else pl.BlockSpec((1, 1, 1), lambda bi, qi: (0, 0, 0)))
        segq_bs = (pl.BlockSpec((1, 1, blk_q), lambda bi, qi: (bi, 0, qi))
                   if has_segments
                   else pl.BlockSpec((1, 1, 1), lambda bi, qi: (0, 0, 0)))
        segk_bs = (pl.BlockSpec((1, 1, s), lambda bi, qi: (bi, 0, 0))
                   if has_segments
                   else pl.BlockSpec((1, 1, 1), lambda bi, qi: (0, 0, 0)))
        grid = (b, s // blk_q)
        out, lse = pl.pallas_call(
            functools.partial(_fwd_kernel_native, scale=scale, blk_k=blk_k,
                              rate=rate, has_bias=has_bias,
                              has_segments=has_segments, n_heads=h),
            grid=grid,
            in_specs=[
                pl.BlockSpec((1,), lambda bi, qi: (0,)),      # seed
                pl.BlockSpec((1, blk_q, h, d), lambda bi, qi: (bi, qi, 0, 0)),
                pl.BlockSpec((1, s, h, d), lambda bi, qi: (bi, 0, 0, 0)),
                pl.BlockSpec((1, s, h, d), lambda bi, qi: (bi, 0, 0, 0)),
                bias_bs,
                segq_bs,
                segk_bs,
            ],
            out_specs=[
                pl.BlockSpec((1, blk_q, h, d), lambda bi, qi: (bi, qi, 0, 0)),
                pl.BlockSpec((1, h, blk_q), lambda bi, qi: (bi, 0, qi)),
            ],
            out_shape=[
                jax.ShapeDtypeStruct((b, s, h, d), q.dtype),
                jax.ShapeDtypeStruct((b, h, s), jnp.float32),
            ],
            interpret=interpret,
        )(seed_arr, q, k, v, bias2, seg2, seg2)
        return out, (q, k, v, bias2, seg2, lse, out)

    qb, kb, vb = _to_bh(q), _to_bh(k), _to_bh(v)
    bias_blockspec = (pl.BlockSpec((1, 1, s), lambda bh, qi: (bh // h, 0, 0))
                      if has_bias
                      else pl.BlockSpec((1, 1, 1), lambda bh, qi: (0, 0, 0)))
    segq_bs = (pl.BlockSpec((1, 1, blk_q), lambda bh, qi: (bh // h, 0, qi))
               if has_segments
               else pl.BlockSpec((1, 1, 1), lambda bh, qi: (0, 0, 0)))
    segk_bs = (pl.BlockSpec((1, 1, s), lambda bh, qi: (bh // h, 0, 0))
               if has_segments
               else pl.BlockSpec((1, 1, 1), lambda bh, qi: (0, 0, 0)))

    grid = (b * h, s // blk_q)
    out, lse = pl.pallas_call(
        functools.partial(_fwd_kernel, scale=scale, blk_k=blk_k, rate=rate,
                          has_bias=has_bias, has_segments=has_segments),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1,), lambda bh, qi: (0,)),      # seed
            pl.BlockSpec((1, blk_q, d), lambda bh, qi: (bh, qi, 0)),
            pl.BlockSpec((1, s, d), lambda bh, qi: (bh, 0, 0)),
            pl.BlockSpec((1, s, d), lambda bh, qi: (bh, 0, 0)),
            bias_blockspec,
            segq_bs,
            segk_bs,
        ],
        out_specs=[
            pl.BlockSpec((1, blk_q, d), lambda bh, qi: (bh, qi, 0)),
            pl.BlockSpec((1, 1, blk_q), lambda bh, qi: (bh, 0, qi)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b * h, s, d), q.dtype),
            jax.ShapeDtypeStruct((b * h, 1, s), jnp.float32),
        ],
        interpret=interpret,
    )(seed_arr, qb, kb, vb, bias2, seg2, seg2)
    return _from_bh(out, b, h), (qb, kb, vb, bias2, seg2, lse, out)


def _flash_fwd_rule(q, k, v, bias, segment_ids, seed, rate, interpret):
    out, res = _flash_fwd(q, k, v, bias, segment_ids, seed, rate, interpret)
    return out, (res, seed, q.shape, bias is not None,
                 segment_ids is not None)


def _flash_bwd_rule(rate, interpret, saved, g):
    (qb, kb, vb, bias2, seg2, lse, outb), seed, qshape, has_bias, \
        has_segments = saved
    b, s, h, d = qshape
    blk_q = _pick_block(s, DEFAULT_BLK_Q)
    blk_k = _pick_block(s, DEFAULT_BLK_K)
    scale = 1.0 / (d ** 0.5)

    if _use_native(s, h, d):
        # residuals are in native (B, S, H, D) layout (same deterministic
        # gate as _flash_fwd); lse is (B, H, S)
        q, k, v, out = qb, kb, vb, outb
        delta = jnp.einsum("bshd,bshd->bhs", g.astype(jnp.float32),
                           out.astype(jnp.float32))
        seed_arr = (jnp.zeros((1,), jnp.int32) if seed is None
                    else jnp.asarray(seed, jnp.int32).reshape(1))
        bias_bs = (pl.BlockSpec((1, 1, s), lambda bi: (bi, 0, 0))
                   if has_bias
                   else pl.BlockSpec((1, 1, 1), lambda bi: (0, 0, 0)))
        seg_bs = (pl.BlockSpec((1, 1, s), lambda bi: (bi, 0, 0))
                  if has_segments
                  else pl.BlockSpec((1, 1, 1), lambda bi: (0, 0, 0)))
        qkv_bs = pl.BlockSpec((1, s, h, d), lambda bi: (bi, 0, 0, 0))
        hs_bs = pl.BlockSpec((1, h, s), lambda bi: (bi, 0, 0))
        dq, dk, dv = pl.pallas_call(
            functools.partial(_dqkv_kernel_native, scale=scale, blk_q=blk_q,
                              blk_k=blk_k, rate=rate, has_bias=has_bias,
                              has_segments=has_segments, n_heads=h),
            grid=(b,),
            in_specs=[
                pl.BlockSpec((1,), lambda bi: (0,)),
                qkv_bs, qkv_bs, qkv_bs, bias_bs, seg_bs, hs_bs, hs_bs,
                qkv_bs,
            ],
            out_specs=[qkv_bs, qkv_bs, qkv_bs],
            out_shape=[
                jax.ShapeDtypeStruct(q.shape, q.dtype),
                jax.ShapeDtypeStruct(k.shape, k.dtype),
                jax.ShapeDtypeStruct(v.shape, v.dtype),
            ],
            interpret=interpret,
        )(seed_arr, q, k, v, bias2, seg2, lse, delta, g)
        dbias = jnp.zeros((b, 1, 1, s), bias2.dtype) if has_bias else None
        dseg = None if not has_segments else jax.custom_derivatives \
            .zero_from_primal(seg2.reshape(b, s))
        dseed = None if seed is None else jax.custom_derivatives \
            .zero_from_primal(jnp.asarray(seed, jnp.int32))
        return dq, dk, dv, dbias, dseg, dseed

    gb = _to_bh(g)
    # delta = rowsum(dO * O) (cheap elementwise — jnp, not a kernel)
    delta = jnp.sum(gb.astype(jnp.float32) * outb.astype(jnp.float32),
                    axis=-1)[:, None, :]
    seed_arr = (jnp.zeros((1,), jnp.int32) if seed is None
                else jnp.asarray(seed, jnp.int32).reshape(1))

    # fused dq/dk/dv kernel: scores, exp and dropout masks evaluated once
    # instead of twice. VMEM-bounded by the per-program footprint — 8 (S, D)
    # input/output arrays plus 3 fp32 (S, D) accumulators — so gate on the
    # S*D byte budget (S=2048 at D=64 was the measured-safe point), not S
    # alone: D=128 heads halve the admissible S. FLASH_BWD=split forces the
    # two-kernel path.
    if s * d <= 2048 * 64 and os.environ.get("FLASH_BWD", "fused") != "split":
        bias_bs = (pl.BlockSpec((1, 1, s), lambda bh: (bh // h, 0, 0))
                   if has_bias
                   else pl.BlockSpec((1, 1, 1), lambda bh: (0, 0, 0)))
        seg_bs = (pl.BlockSpec((1, 1, s), lambda bh: (bh // h, 0, 0))
                  if has_segments
                  else pl.BlockSpec((1, 1, 1), lambda bh: (0, 0, 0)))
        dq, dk, dv = pl.pallas_call(
            functools.partial(_dqkv_kernel, scale=scale, blk_q=blk_q,
                              blk_k=blk_k, rate=rate, has_bias=has_bias,
                              has_segments=has_segments),
            grid=(b * h,),
            in_specs=[
                pl.BlockSpec((1,), lambda bh: (0,)),
                pl.BlockSpec((1, s, d), lambda bh: (bh, 0, 0)),
                pl.BlockSpec((1, s, d), lambda bh: (bh, 0, 0)),
                pl.BlockSpec((1, s, d), lambda bh: (bh, 0, 0)),
                bias_bs,
                seg_bs,
                pl.BlockSpec((1, 1, s), lambda bh: (bh, 0, 0)),
                pl.BlockSpec((1, 1, s), lambda bh: (bh, 0, 0)),
                pl.BlockSpec((1, s, d), lambda bh: (bh, 0, 0)),
            ],
            out_specs=[
                pl.BlockSpec((1, s, d), lambda bh: (bh, 0, 0)),
                pl.BlockSpec((1, s, d), lambda bh: (bh, 0, 0)),
                pl.BlockSpec((1, s, d), lambda bh: (bh, 0, 0)),
            ],
            out_shape=[
                jax.ShapeDtypeStruct(qb.shape, qb.dtype),
                jax.ShapeDtypeStruct(kb.shape, kb.dtype),
                jax.ShapeDtypeStruct(vb.shape, vb.dtype),
            ],
            interpret=interpret,
        )(seed_arr, qb, kb, vb, bias2, seg2, lse, delta, gb)
        return _bwd_epilogue(dq, dk, dv, b, h, s, bias2, has_bias, seg2,
                             has_segments, seed)

    bias_blockspec_q = (pl.BlockSpec((1, 1, s), lambda bh, qi: (bh // h, 0, 0))
                        if has_bias
                        else pl.BlockSpec((1, 1, 1), lambda bh, qi: (0, 0, 0)))
    segq_bs = (pl.BlockSpec((1, 1, blk_q), lambda bh, qi: (bh // h, 0, qi))
               if has_segments
               else pl.BlockSpec((1, 1, 1), lambda bh, qi: (0, 0, 0)))
    segk_full_bs = (pl.BlockSpec((1, 1, s), lambda bh, qi: (bh // h, 0, 0))
                    if has_segments
                    else pl.BlockSpec((1, 1, 1), lambda bh, qi: (0, 0, 0)))

    dq = pl.pallas_call(
        functools.partial(_dq_kernel, scale=scale, blk_k=blk_k, rate=rate,
                          has_bias=has_bias, has_segments=has_segments),
        grid=(b * h, s // blk_q),
        in_specs=[
            pl.BlockSpec((1,), lambda bh, qi: (0,)),
            pl.BlockSpec((1, blk_q, d), lambda bh, qi: (bh, qi, 0)),
            pl.BlockSpec((1, s, d), lambda bh, qi: (bh, 0, 0)),
            pl.BlockSpec((1, s, d), lambda bh, qi: (bh, 0, 0)),
            bias_blockspec_q,
            segq_bs,
            segk_full_bs,
            pl.BlockSpec((1, 1, blk_q), lambda bh, qi: (bh, 0, qi)),
            pl.BlockSpec((1, 1, blk_q), lambda bh, qi: (bh, 0, qi)),
            pl.BlockSpec((1, blk_q, d), lambda bh, qi: (bh, qi, 0)),
        ],
        out_specs=pl.BlockSpec((1, blk_q, d), lambda bh, qi: (bh, qi, 0)),
        out_shape=jax.ShapeDtypeStruct(qb.shape, qb.dtype),
        interpret=interpret,
    )(seed_arr, qb, kb, vb, bias2, seg2, seg2, lse, delta, gb)

    bias_blockspec_k = (pl.BlockSpec((1, 1, blk_k),
                                     lambda bh, kj: (bh // h, 0, kj))
                        if has_bias
                        else pl.BlockSpec((1, 1, 1), lambda bh, kj: (0, 0, 0)))
    segq_full_bs = (pl.BlockSpec((1, 1, s), lambda bh, kj: (bh // h, 0, 0))
                    if has_segments
                    else pl.BlockSpec((1, 1, 1), lambda bh, kj: (0, 0, 0)))
    segk_bs = (pl.BlockSpec((1, 1, blk_k), lambda bh, kj: (bh // h, 0, kj))
               if has_segments
               else pl.BlockSpec((1, 1, 1), lambda bh, kj: (0, 0, 0)))
    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, scale=scale, blk_q=blk_q, rate=rate,
                          has_bias=has_bias, has_segments=has_segments),
        grid=(b * h, s // blk_k),
        in_specs=[
            pl.BlockSpec((1,), lambda bh, kj: (0,)),
            pl.BlockSpec((1, s, d), lambda bh, kj: (bh, 0, 0)),
            pl.BlockSpec((1, blk_k, d), lambda bh, kj: (bh, kj, 0)),
            pl.BlockSpec((1, blk_k, d), lambda bh, kj: (bh, kj, 0)),
            bias_blockspec_k,
            segq_full_bs,
            segk_bs,
            pl.BlockSpec((1, 1, s), lambda bh, kj: (bh, 0, 0)),
            pl.BlockSpec((1, 1, s), lambda bh, kj: (bh, 0, 0)),
            pl.BlockSpec((1, s, d), lambda bh, kj: (bh, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, blk_k, d), lambda bh, kj: (bh, kj, 0)),
            pl.BlockSpec((1, blk_k, d), lambda bh, kj: (bh, kj, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(kb.shape, kb.dtype),
            jax.ShapeDtypeStruct(vb.shape, vb.dtype),
        ],
        interpret=interpret,
    )(seed_arr, qb, kb, vb, bias2, seg2, seg2, lse, delta, gb)

    return _bwd_epilogue(dq, dk, dv, b, h, s, bias2, has_bias, seg2,
                         has_segments, seed)


def _bwd_epilogue(dq, dk, dv, b, h, s, bias2, has_bias, seg2, has_segments,
                  seed):
    """Shared cotangent packaging: bias is non-differentiable by contract
    (zero cotangent; see flash_attention docstring), segment ids and seed
    likewise — the integer primals get float0 cotangents per JAX's
    convention (int32 zeros trip stricter custom_vjp aval checking)."""
    dbias = None
    if has_bias:
        dbias = jnp.zeros((b, 1, 1, s), bias2.dtype)
    dseg = None if not has_segments else jax.custom_derivatives \
        .zero_from_primal(seg2.reshape(b, s))
    dseed = None if seed is None else jax.custom_derivatives \
        .zero_from_primal(jnp.asarray(seed, jnp.int32))
    return (_from_bh(dq, b, h), _from_bh(dk, b, h), _from_bh(dv, b, h),
            dbias, dseg, dseed)


flash_attention.defvjp(_flash_fwd_rule, _flash_bwd_rule)
