"""Corpus -> pretraining samples -> sharded gzip'd HDF5.

Semantics match the reference utils/encode_data.py: documents are blank-line
delimited, sentences accumulate into chunks near a target length (randomly
shortened with short_seq_prob, :81-86), NSP mode splits each chunk at a
random sentence boundary and replaces the second segment with a random other
document's tail with probability next_seq_prob (rewinding the cursor over
the displaced sentences, :107-131); samples are shuffled per file and
written with the schema {input_ids i4, special_token_positions i4,
next_sentence_labels i1} (:183-210).
"""

from __future__ import annotations

import argparse
import multiprocessing as mp
import os
import random
import time
from dataclasses import dataclass, field
from pathlib import Path
from typing import List, Optional

import numpy as np


@dataclass
class TrainingSample:
    """[CLS] a [SEP] (NSP: [CLS] a [SEP] b [SEP]); special_token_positions
    records where [CLS]/[SEP]s sit (reference TrainingSample :12-37)."""

    seq_tokens: List[str]
    next_seq_tokens: Optional[List[str]] = None
    is_random_next: bool = False
    sequence: List[str] = field(init=False)
    special_token_positions: List[int] = field(init=False)

    def __post_init__(self):
        self.sequence = ["[CLS]"] + list(self.seq_tokens)
        self.special_token_positions = [0]
        if self.next_seq_tokens is not None:
            self.special_token_positions.append(len(self.sequence))
            self.sequence.append("[SEP]")
            self.sequence.extend(self.next_seq_tokens)
        self.special_token_positions.append(len(self.sequence))
        self.sequence.append("[SEP]")


def read_documents(input_file: str, tokenizer) -> List[List[List[str]]]:
    """Blank-line-delimited documents of tokenized sentences
    (reference :48-62). Uses the tokenizer's native batch path when present
    (bert_pytorch_tpu.native) — this per-sentence encode is the offline
    pipeline's hot loop."""
    raw_docs: List[List[str]] = [[]]
    with open(input_file, "r", encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if not line:
                raw_docs.append([])
                continue
            raw_docs[-1].append(line)

    if hasattr(tokenizer, "encode_batch"):
        flat = [l for d in raw_docs for l in d]
        encodings = tokenizer.encode_batch(flat, add_special_tokens=False)
        tokens_iter = iter(e.tokens for e in encodings)
    else:
        tokens_iter = iter(
            tokenizer.encode(l, add_special_tokens=False).tokens
            for d in raw_docs for l in d)

    documents: List[List[List[str]]] = []
    for d in raw_docs:
        doc: List[List[str]] = []
        for _line in d:
            tokens = next(tokens_iter)
            if tokens:
                doc.append(tokens)
        if doc:
            documents.append(doc)
    return documents


def _target_len(max_num_tokens: int, short_seq_prob: float,
                rng: random.Random) -> int:
    if rng.random() < short_seq_prob:
        return rng.randint(2, max_num_tokens)
    return max_num_tokens


def samples_from_document(doc_idx: int, documents, max_seq_len: int,
                          next_seq_prob: float, short_seq_prob: float,
                          rng: random.Random) -> List[TrainingSample]:
    """Chunking + NSP pairing (reference :65-167)."""
    nsp = next_seq_prob > 0
    max_num_tokens = max_seq_len - (3 if nsp else 2)
    target = _target_len(max_num_tokens, short_seq_prob, rng)

    document = documents[doc_idx]
    samples: List[TrainingSample] = []
    chunk: List[List[str]] = []
    chunk_len = 0
    i = 0
    while i < len(document):
        current = document[i][:target]
        if chunk and (i + 1 == len(document)
                      or chunk_len + len(current) >= target):
            if nsp:
                if len(documents) <= 1:
                    raise ValueError(
                        "NSP needs more than one document for random nexts")
                split = rng.randint(1, len(chunk) - 1) if len(chunk) >= 2 else 1
                seq = [t for s in chunk[:split] for t in s]
                if rng.random() < next_seq_prob:
                    # random next from another document; rewind the cursor
                    # over the sentences we displaced (reference :113-131)
                    is_random = True
                    other_idx = rng.randint(0, len(documents) - 1)
                    while other_idx == doc_idx:
                        other_idx = rng.randint(0, len(documents) - 1)
                    other = documents[other_idx]
                    start = rng.randint(0, len(other) - 1)
                    budget = target - len(seq)
                    nxt: List[str] = []
                    for sent in other[start:]:
                        nxt.extend(sent)
                        if len(nxt) >= budget:
                            nxt = nxt[:budget]
                            break
                    i -= len(chunk) - split
                else:
                    is_random = False
                    nxt = [t for s in chunk[split:] for t in s]
                samples.append(TrainingSample(seq, nxt, is_random))
            else:
                samples.append(TrainingSample(
                    [t for s in chunk for t in s]))
            target = _target_len(max_num_tokens, short_seq_prob, rng)
            chunk = []
            chunk_len = 0

        current = document[i][:target]
        chunk.append(current)
        chunk_len += len(current)
        i += 1
    return samples


def create_samples(input_file: str, tokenizer, max_seq_len: int,
                   next_seq_prob: float, short_seq_prob: float,
                   seed: Optional[int] = None) -> List[TrainingSample]:
    rng = random.Random(seed)
    documents = read_documents(input_file, tokenizer)
    samples: List[TrainingSample] = []
    for i in range(len(documents)):
        samples.extend(samples_from_document(
            i, documents, max_seq_len, next_seq_prob, short_seq_prob, rng))
    rng.shuffle(samples)
    return samples


def write_hdf5(output_file: str, samples: List[TrainingSample], tokenizer,
               max_seq_len: int) -> int:
    """Write the runtime-compatible shard (reference :183-210). Returns the
    sample count."""
    import h5py

    n_specials = max((len(s.special_token_positions) for s in samples),
                     default=2)
    ids_rows, spec_rows, nsl_rows = [], [], []
    for s in samples:
        row = [tokenizer.token_to_id(t) for t in s.sequence]
        if None in row:
            raise ValueError(f"token missing from vocab in {s.sequence}")
        row += [0] * (max_seq_len - len(row))
        ids_rows.append(row)
        spec = list(s.special_token_positions)
        spec += [spec[-1]] * (n_specials - len(spec))
        spec_rows.append(spec)
        nsl_rows.append(1 if s.is_random_next else 0)

    with h5py.File(output_file, "w") as f:
        f.create_dataset("input_ids", data=np.asarray(ids_rows, np.int32),
                         dtype="i4", compression="gzip")
        f.create_dataset("special_token_positions",
                         data=np.asarray(spec_rows, np.int32), dtype="i4",
                         compression="gzip")
        f.create_dataset("next_sentence_labels",
                         data=np.asarray(nsl_rows, np.int8), dtype="i1",
                         compression="gzip")
    return len(ids_rows)


def encode_file(input_file: str, output_file: str, tokenizer,
                max_seq_len: int, next_seq_prob: float, short_seq_prob: float,
                seed: Optional[int] = None) -> int:
    t0 = time.time()
    samples = create_samples(input_file, tokenizer, max_seq_len,
                             next_seq_prob, short_seq_prob, seed=seed)
    n = write_hdf5(output_file, samples, tokenizer, max_seq_len)
    print(f"[encoder] {output_file}: {n} samples ({time.time() - t0:.0f}s)")
    return n


def _encode_one(params):
    input_file, output_file, vocab_file, tokenizer_kind, uppercase, \
        max_seq_len, next_seq_prob, short_seq_prob, seed = params
    from bert_pytorch_tpu.data.tokenization import TOKENIZERS

    tokenizer = TOKENIZERS[tokenizer_kind](vocab_file, uppercase=uppercase)
    return encode_file(input_file, output_file, tokenizer, max_seq_len,
                       next_seq_prob, short_seq_prob, seed=seed)


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--input_dir", required=True,
                   help=".txt file or directory of .txt shards")
    p.add_argument("--output_dir", required=True)
    p.add_argument("--vocab_file", required=True)
    p.add_argument("--max_seq_len", default=512, type=int)
    p.add_argument("--short_seq_prob", default=0.1, type=float)
    p.add_argument("--next_seq_prob", default=0.0, type=float,
                   help="0 disables the NSP task (RoBERTa mode)")
    p.add_argument("--uppercase", action="store_true", default=False)
    p.add_argument("--tokenizer", default="wordpiece",
                   choices=["wordpiece", "bpe"])
    p.add_argument("--processes", type=int, default=4)
    p.add_argument("--seed", type=int, default=None)
    args = p.parse_args(argv)

    if os.path.isfile(args.input_dir):
        inputs = [args.input_dir]
    else:
        inputs = sorted(str(f) for f in Path(args.input_dir).rglob("*.txt"))
    if not inputs:
        raise SystemExit(f"no input .txt under {args.input_dir}")

    # output naming mirrors the reference (:263-271)
    prefix = ("sequences_"
              + ("uppercase" if args.uppercase else "lowercase")
              + f"_max_seq_len_{args.max_seq_len}"
              + f"_next_seq_task_{str(args.next_seq_prob > 0).lower()}")
    out_dir = os.path.join(args.output_dir, prefix)
    os.makedirs(out_dir, exist_ok=True)

    params = [(ifile, os.path.join(out_dir, f"train_{i}.hdf5"),
               args.vocab_file, args.tokenizer, args.uppercase,
               args.max_seq_len, args.next_seq_prob, args.short_seq_prob,
               None if args.seed is None else args.seed + i)
              for i, ifile in enumerate(inputs)]
    t0 = time.time()
    with mp.Pool(processes=args.processes) as pool:
        counts = pool.map(_encode_one, params)
    print(f"[encoder] {sum(counts)} samples in {len(inputs)} shards "
          f"({time.time() - t0:.0f}s)")


if __name__ == "__main__":
    main()
