"""Corpus / weights downloaders with integrity verification.

Reference utils/download.py: Wikipedia dump, BooksCorpus, SQuAD, GLUE, and
Google pretrained-weights downloaders with SHA256 verification of the weight
archives (:11-256). Re-expressed as one registry of datasets; checksums are
verified when known. (This build environment has no egress — downloads are
exercised in tests via file:// URLs and checksum checks on local files.)
"""

from __future__ import annotations

import argparse
import bz2
import hashlib
import os
import shutil
import urllib.request
import zipfile
from dataclasses import dataclass, field
from typing import Dict, Optional


@dataclass
class Resource:
    url: str
    filename: str
    sha256: Optional[str] = None
    extract: bool = False  # zip/bz2 archives


DATASETS: Dict[str, Dict[str, Resource]] = {
    "squad": {
        "train-v1.1.json": Resource(
            "https://rajpurkar.github.io/SQuAD-explorer/dataset/train-v1.1.json",
            "train-v1.1.json"),
        "dev-v1.1.json": Resource(
            "https://rajpurkar.github.io/SQuAD-explorer/dataset/dev-v1.1.json",
            "dev-v1.1.json"),
        "train-v2.0.json": Resource(
            "https://rajpurkar.github.io/SQuAD-explorer/dataset/train-v2.0.json",
            "train-v2.0.json"),
        "dev-v2.0.json": Resource(
            "https://rajpurkar.github.io/SQuAD-explorer/dataset/dev-v2.0.json",
            "dev-v2.0.json"),
    },
    "wikicorpus": {
        "enwiki": Resource(
            "https://dumps.wikimedia.org/enwiki/latest/"
            "enwiki-latest-pages-articles.xml.bz2",
            "enwiki-latest-pages-articles.xml.bz2", extract=True),
    },
    "google_pretrained_weights": {
        "uncased_L-24_H-1024_A-16": Resource(
            "https://storage.googleapis.com/bert_models/2018_10_18/"
            "uncased_L-24_H-1024_A-16.zip",
            "uncased_L-24_H-1024_A-16.zip", extract=True),
        "uncased_L-12_H-768_A-12": Resource(
            "https://storage.googleapis.com/bert_models/2018_10_18/"
            "uncased_L-12_H-768_A-12.zip",
            "uncased_L-12_H-768_A-12.zip", extract=True),
        "cased_L-24_H-1024_A-16": Resource(
            "https://storage.googleapis.com/bert_models/2018_10_18/"
            "cased_L-24_H-1024_A-16.zip",
            "cased_L-24_H-1024_A-16.zip", extract=True),
    },
}


def sha256_file(path: str, chunk: int = 1 << 20) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        while True:
            block = f.read(chunk)
            if not block:
                break
            h.update(block)
    return h.hexdigest()


def verify(path: str, expected_sha256: Optional[str]) -> bool:
    """True when the checksum matches (or none is pinned). The reference
    verified the Google weight archives the same way (utils/download.py:
    177-216)."""
    if expected_sha256 is None:
        return True
    return sha256_file(path) == expected_sha256


def fetch(resource: Resource, output_dir: str, force: bool = False) -> str:
    os.makedirs(output_dir, exist_ok=True)
    target = os.path.join(output_dir, resource.filename)
    if os.path.exists(target) and not force \
            and verify(target, resource.sha256):
        print(f"[download] cached: {target}")
        return target

    print(f"[download] {resource.url} -> {target}")
    with urllib.request.urlopen(resource.url) as r, open(target, "wb") as f:
        shutil.copyfileobj(r, f)
    if not verify(target, resource.sha256):
        os.remove(target)
        raise IOError(f"checksum mismatch for {resource.url}")

    if resource.extract:
        extract(target, output_dir)
    return target


def extract(path: str, output_dir: str) -> None:
    if path.endswith(".zip"):
        with zipfile.ZipFile(path) as z:
            z.extractall(output_dir)
    elif path.endswith(".bz2"):
        out = path[:-len(".bz2")]
        with bz2.open(path, "rb") as src, open(out, "wb") as dst:
            shutil.copyfileobj(src, dst)


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--dataset", required=True, choices=sorted(DATASETS))
    p.add_argument("--output_dir", required=True)
    p.add_argument("--only", default=None,
                   help="fetch a single named resource from the dataset")
    p.add_argument("--force", action="store_true")
    args = p.parse_args(argv)

    resources = DATASETS[args.dataset]
    if args.only:
        resources = {args.only: resources[args.only]}
    out = os.path.join(args.output_dir, args.dataset)
    for name, res in resources.items():
        fetch(res, out, force=args.force)


if __name__ == "__main__":
    main()
