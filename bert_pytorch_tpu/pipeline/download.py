"""Corpus / weights downloaders with integrity verification.

Reference utils/download.py: Wikipedia dump, BooksCorpus, SQuAD, GLUE, and
Google pretrained-weights downloaders with SHA256 verification of the weight
archives (:11-256). Re-expressed as one registry of datasets; checksums are
verified when known. BooksCorpus is a URL-list-driven fetch (the reference
cloned soskek/bookcorpus and ran its downloader over url_list.jsonl,
utils/download.py:59-78 — here the list-driven fetch is in-framework, no git
clone / subprocess). GLUE resolves per-task archives directly (the reference
fetched and exec'd the W4ngatang gist, :81-100). (This build environment has
no egress — downloads are exercised in tests via file:// URLs and checksum
checks on local files.)
"""

from __future__ import annotations

import argparse
import bz2
import hashlib
import json
import os
import shutil
import urllib.request
import zipfile
from dataclasses import dataclass
from typing import Dict, Iterable, Optional


@dataclass
class Resource:
    url: str
    filename: str
    sha256: Optional[str] = None
    extract: bool = False  # zip/bz2 archives


DATASETS: Dict[str, Dict[str, Resource]] = {
    "squad": {
        "train-v1.1.json": Resource(
            "https://rajpurkar.github.io/SQuAD-explorer/dataset/train-v1.1.json",
            "train-v1.1.json"),
        "dev-v1.1.json": Resource(
            "https://rajpurkar.github.io/SQuAD-explorer/dataset/dev-v1.1.json",
            "dev-v1.1.json"),
        "train-v2.0.json": Resource(
            "https://rajpurkar.github.io/SQuAD-explorer/dataset/train-v2.0.json",
            "train-v2.0.json"),
        "dev-v2.0.json": Resource(
            "https://rajpurkar.github.io/SQuAD-explorer/dataset/dev-v2.0.json",
            "dev-v2.0.json"),
    },
    "wikicorpus": {
        "enwiki": Resource(
            "https://dumps.wikimedia.org/enwiki/latest/"
            "enwiki-latest-pages-articles.xml.bz2",
            "enwiki-latest-pages-articles.xml.bz2", extract=True),
    },
    # GLUE per-task archives (the canonical hosting the W4ngatang
    # download_glue_data.py script resolves; reference defaulted to
    # tasks=['MRPC', 'SST'], utils/download.py:81-83).
    "glue": {
        "CoLA": Resource(
            "https://dl.fbaipublicfiles.com/glue/data/CoLA.zip",
            "CoLA.zip", extract=True),
        "SST": Resource(
            "https://dl.fbaipublicfiles.com/glue/data/SST-2.zip",
            "SST-2.zip", extract=True),
        "QQP": Resource(
            "https://dl.fbaipublicfiles.com/glue/data/QQP-clean.zip",
            "QQP.zip", extract=True),
        "STS": Resource(
            "https://dl.fbaipublicfiles.com/glue/data/STS-B.zip",
            "STS-B.zip", extract=True),
        "MNLI": Resource(
            "https://dl.fbaipublicfiles.com/glue/data/MNLI.zip",
            "MNLI.zip", extract=True),
        "QNLI": Resource(
            "https://dl.fbaipublicfiles.com/glue/data/QNLIv2.zip",
            "QNLI.zip", extract=True),
        "RTE": Resource(
            "https://dl.fbaipublicfiles.com/glue/data/RTE.zip",
            "RTE.zip", extract=True),
        "WNLI": Resource(
            "https://dl.fbaipublicfiles.com/glue/data/WNLI.zip",
            "WNLI.zip", extract=True),
        # MRPC ships as two raw txt files, not a zip
        "MRPC-train": Resource(
            "https://dl.fbaipublicfiles.com/senteval/senteval_data/"
            "msr_paraphrase_train.txt", "MRPC/msr_paraphrase_train.txt"),
        "MRPC-test": Resource(
            "https://dl.fbaipublicfiles.com/senteval/senteval_data/"
            "msr_paraphrase_test.txt", "MRPC/msr_paraphrase_test.txt"),
    },
    "google_pretrained_weights": {
        "uncased_L-24_H-1024_A-16": Resource(
            "https://storage.googleapis.com/bert_models/2018_10_18/"
            "uncased_L-24_H-1024_A-16.zip",
            "uncased_L-24_H-1024_A-16.zip", extract=True),
        "uncased_L-12_H-768_A-12": Resource(
            "https://storage.googleapis.com/bert_models/2018_10_18/"
            "uncased_L-12_H-768_A-12.zip",
            "uncased_L-12_H-768_A-12.zip", extract=True),
        "cased_L-24_H-1024_A-16": Resource(
            "https://storage.googleapis.com/bert_models/2018_10_18/"
            "cased_L-24_H-1024_A-16.zip",
            "cased_L-24_H-1024_A-16.zip", extract=True),
    },
}


def sha256_file(path: str, chunk: int = 1 << 20) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        while True:
            block = f.read(chunk)
            if not block:
                break
            h.update(block)
    return h.hexdigest()


def verify(path: str, expected_sha256: Optional[str]) -> bool:
    """True when the checksum matches (or none is pinned). The reference
    verified the Google weight archives the same way (utils/download.py:
    177-216)."""
    if expected_sha256 is None:
        return True
    return sha256_file(path) == expected_sha256


def fetch(resource: Resource, output_dir: str, force: bool = False) -> str:
    target = os.path.join(output_dir, resource.filename)
    os.makedirs(os.path.dirname(target) or ".", exist_ok=True)
    if os.path.exists(target) and not force \
            and verify(target, resource.sha256):
        print(f"[download] cached: {target}")
        return target

    print(f"[download] {resource.url} -> {target}")
    with urllib.request.urlopen(resource.url) as r, open(target, "wb") as f:
        shutil.copyfileobj(r, f)
    if not verify(target, resource.sha256):
        os.remove(target)
        raise IOError(f"checksum mismatch for {resource.url}")

    if resource.extract:
        extract(target, output_dir)
    return target


def extract(path: str, output_dir: str) -> None:
    if path.endswith(".zip"):
        with zipfile.ZipFile(path) as z:
            z.extractall(output_dir)
    elif path.endswith(".bz2"):
        out = path[:-len(".bz2")]
        with bz2.open(path, "rb") as src, open(out, "wb") as dst:
            shutil.copyfileobj(src, dst)


def iter_url_list(url_list_path: str) -> Iterable[str]:
    """Yield book URLs from a soskek-style url_list.jsonl (each line a JSON
    object whose 'txt' — falling back to 'url' — field is the plain-text
    download) or from a plain newline-delimited URL file."""
    with open(url_list_path, "r", encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            if line.startswith("{"):
                rec = json.loads(line)
                url = rec.get("txt") or rec.get("url")
                if url:
                    yield url
            else:
                yield line


def fetch_bookscorpus(url_list_path: str, output_dir: str,
                      min_bytes: int = 1024) -> int:
    """Download every book in the URL list into output_dir/bookscorpus.

    In-framework replacement for the reference's cloned downloader
    (utils/download.py:59-78): per-book fetch, undersized/failed files
    dropped (the reference passed --trash-bad-count for the same hygiene).
    Returns the number of books kept."""
    out = os.path.join(output_dir, "bookscorpus")
    os.makedirs(out, exist_ok=True)
    kept = 0
    for i, url in enumerate(iter_url_list(url_list_path)):
        # index prefix disambiguates distinct books whose URLs share a
        # basename (e.g. many .../download.txt links)
        base = os.path.basename(url.rstrip("/")) or "book.txt"
        name = f"{i:06d}_{base}"
        if not name.endswith(".txt"):
            name += ".txt"
        target = os.path.join(out, name)
        if os.path.exists(target) and os.path.getsize(target) >= min_bytes:
            kept += 1
            continue
        try:
            with urllib.request.urlopen(url) as r, open(target, "wb") as f:
                shutil.copyfileobj(r, f)
        except Exception as e:  # noqa: BLE001 — per-book failures are expected
            print(f"[bookscorpus] failed {url}: {e}")
            if os.path.exists(target):
                os.remove(target)
            continue
        if os.path.getsize(target) < min_bytes:
            print(f"[bookscorpus] trashing undersized {name}")
            os.remove(target)
            continue
        kept += 1
    print(f"[bookscorpus] {kept} books kept under {out}")
    return kept


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--dataset", required=True,
                   choices=sorted(DATASETS) + ["bookscorpus"])
    p.add_argument("--output_dir", required=True)
    p.add_argument("--only", default=None,
                   help="fetch a single named resource from the dataset")
    p.add_argument("--url_list", default=None,
                   help="bookscorpus: url_list.jsonl (or plain URL list)")
    p.add_argument("--force", action="store_true")
    args = p.parse_args(argv)

    if args.dataset == "bookscorpus":
        if not args.url_list:
            raise SystemExit("--dataset bookscorpus requires --url_list")
        fetch_bookscorpus(args.url_list, args.output_dir)
        return

    resources = DATASETS[args.dataset]
    if args.only:
        resources = {args.only: resources[args.only]}
    out = os.path.join(args.output_dir, args.dataset)
    for name, res in resources.items():
        fetch(res, out, force=args.force)


if __name__ == "__main__":
    main()
