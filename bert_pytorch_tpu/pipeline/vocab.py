"""Vocabulary training: WordPiece and byte-level BPE, in-framework.

The reference delegated vocab training to the HF tokenizers Rust trainers
(utils/build_vocab.py:39-58) and then post-processed the result: special
tokens forced to the front, [PAD] forced to index 0 (:62-80). Here the
trainers are implemented directly (the standard algorithms):

- BPE: merge the most frequent adjacent symbol pair until vocab_size.
- WordPiece: same loop but pairs scored by the corpus-likelihood GAIN of
  the merge under a unigram model, freq(ab) * log(freq(ab) * N /
  (freq(a) * freq(b))) — the original WordPiece objective. The plain
  likelihood RATIO (HF trainer's score) is maximized by pairs of rare
  symbols, so on small/noisy corpora it spends the whole merge budget on
  one-off junk and never forms common words; the gain weights by pair
  frequency, which fixes that while keeping the WordPiece (non-BPE)
  character.

Both operate on word frequency tables from the Basic pre-tokenizer, so the
runtime tokenizers in data/tokenization.py consume the output unmodified.
The C++ native module accelerates counting/merging when built; this module
is the behavioral spec and the fallback.
"""

from __future__ import annotations

import argparse
import collections
import math
import os
from pathlib import Path
from typing import Dict, Iterable, List, Optional, Tuple

from bert_pytorch_tpu.data.tokenization import (
    SPECIAL_TOKENS,
    BasicTokenizer,
    bytes_to_unicode,
)


def _use_native() -> bool:
    """Native merge engine opt-out: BPT_NATIVE=0 forces the pure-Python
    behavioral spec (also the automatic fallback when the .so cannot be
    built). Selection order is bitwise-identical either way
    (tests/test_vocab_trainer.py::test_native_merge_parity)."""
    if os.environ.get("BPT_NATIVE", "1") == "0":
        return False
    try:
        from bert_pytorch_tpu.native import native_vocab_trainer_available

        return native_vocab_trainer_available()
    except Exception:
        return False


def count_words(files: Iterable[str], lowercase: bool = True
                ) -> Dict[str, int]:
    basic = BasicTokenizer(do_lower_case=lowercase)
    counts: collections.Counter = collections.Counter()
    for path in files:
        with open(path, "r", encoding="utf-8") as f:
            for line in f:
                counts.update(basic.tokenize(line))
    return dict(counts)


class _MergeEngine:
    """Incremental pair/single statistics over the working word list.

    A naive trainer rescans every word per merge — O(vocab_size x corpus),
    minutes per MB. Only words that actually contain the merged pair change,
    so this keeps a pair->word-index inverted index and updates counts by
    delta; selection order is bitwise-identical to the naive loop because
    every best-pair key ends with the pair itself as the tiebreak."""

    def __init__(self, word_counts: Iterable[Tuple[Tuple[str, ...], int]]):
        self.words: List[List] = []          # [symbols list, freq]
        self.pairs: collections.Counter = collections.Counter()
        self.singles: collections.Counter = collections.Counter()
        self.index: Dict[Tuple[str, str], set] = collections.defaultdict(set)
        for symbols, freq in word_counts:
            idx = len(self.words)
            self.words.append([list(symbols), freq])
            self._add(idx)

    def _add(self, idx: int) -> None:
        symbols, freq = self.words[idx]
        for s in symbols:
            self.singles[s] += freq
        for p in zip(symbols, symbols[1:]):
            self.pairs[p] += freq
            self.index[p].add(idx)

    def _remove(self, idx: int) -> None:
        symbols, freq = self.words[idx]
        for s in symbols:
            self.singles[s] -= freq
        for p in zip(symbols, symbols[1:]):
            self.pairs[p] -= freq
            if self.pairs[p] <= 0:
                del self.pairs[p]
                self.index.pop(p, None)
            else:
                self.index[p].discard(idx)

    def merge(self, pair: Tuple[str, str], merged_symbol: str) -> None:
        a, b = pair
        for idx in list(self.index.get(pair, ())):
            self._remove(idx)
            symbols = self.words[idx][0]
            merged: List[str] = []
            i = 0
            while i < len(symbols):
                if (i + 1 < len(symbols) and symbols[i] == a
                        and symbols[i + 1] == b):
                    merged.append(merged_symbol)
                    i += 2
                else:
                    merged.append(symbols[i])
                    i += 1
            self.words[idx][0] = merged
            self._add(idx)
        # self-overlapping merges (e.g. ('a','a') in 'aaa') can leave the
        # pair re-counted from the rebuilt words; drop any residue so the
        # merged pair is never selected twice
        self.pairs.pop(pair, None)
        self.index.pop(pair, None)


def train_wordpiece(word_counts: Dict[str, int], vocab_size: int,
                    special_tokens: Tuple[str, ...] = SPECIAL_TOKENS,
                    min_frequency: int = 1,
                    min_pair_frequency: int = 2,
                    score: str = "gain") -> List[str]:
    """Greedy WordPiece training: start from characters ('##'-marked
    continuations), repeatedly merge the best-scoring pair until vocab_size.

    score="gain" (default): unigram-model corpus-likelihood gain
    freq(ab) * log(freq(ab) * N / (freq(a) * freq(b))) (see module
    docstring); min_pair_frequency additionally drops one-off pairs from
    candidacy. score="ratio": the HF-trainer likelihood ratio
    freq(ab) / (freq(a) * freq(b)) — for byte-exact reproduction of
    vocabularies built by the reference toolchain (utils/build_vocab.py:39);
    ratio runs on the pure-Python engine."""
    words: Dict[Tuple[str, ...], int] = {}
    for word, freq in word_counts.items():
        if freq < min_frequency or not word:
            continue
        symbols = tuple([word[0]] + ["##" + c for c in word[1:]])
        words[symbols] = words.get(symbols, 0) + freq

    vocab: List[str] = list(special_tokens)
    seen = set(vocab)
    for symbols in words:
        for s in symbols:
            if s not in seen:
                seen.add(s)
                vocab.append(s)

    if score not in ("gain", "ratio"):
        raise ValueError(f"unknown wordpiece score {score!r}")
    if score == "gain" and _use_native():
        from bert_pytorch_tpu.native import vocab_trainer_merge

        new_tokens, _ = vocab_trainer_merge(
            words.items(), vocab, vocab_size, wordpiece_mode=True,
            min_pair_frequency=min_pair_frequency)
        vocab.extend(new_tokens)
        return vocab[:vocab_size]

    engine = _MergeEngine(words.items())
    while len(vocab) < vocab_size:
        pairs, singles = engine.pairs, engine.singles

        def merged_name(p):
            a, b = p
            return a + (b[2:] if b.startswith("##") else b)

        candidates = [p for p, c in pairs.items()
                      if c >= min_pair_frequency]
        if not candidates:
            break
        total = sum(singles.values())

        def gain(p):
            c = pairs[p]
            if score == "ratio":
                return c / (singles[p[0]] * singles[p[1]])
            return c * (math.log(c) + math.log(total)
                        - math.log(singles[p[0]]) - math.log(singles[p[1]]))

        best = max(candidates,
                   key=lambda p: (gain(p), -len(merged_name(p)), p))
        new_symbol = merged_name(best)
        engine.merge(best, new_symbol)
        if new_symbol not in seen:
            seen.add(new_symbol)
            vocab.append(new_symbol)
    return vocab[:vocab_size]


def train_bpe(word_counts: Dict[str, int], vocab_size: int,
              special_tokens: Tuple[str, ...] = ("<pad>", "<unk>", "<s>",
                                                 "</s>", "<mask>"),
              min_frequency: int = 1
              ) -> Tuple[Dict[str, int], List[Tuple[str, str]]]:
    """Byte-level BPE training: most-frequent-pair merges over the GPT-2
    byte alphabet. Returns (vocab dict token->id, ordered merges)."""
    byte_enc = bytes_to_unicode()
    words: Dict[Tuple[str, ...], int] = {}
    sp = byte_enc[ord(" ")]
    for word, freq in word_counts.items():
        if freq < min_frequency:
            continue
        mapped = sp + "".join(byte_enc[b] for b in word.encode("utf-8"))
        words[tuple(mapped)] = words.get(tuple(mapped), 0) + freq

    vocab: List[str] = list(special_tokens) + sorted(set(byte_enc.values()))
    merges: List[Tuple[str, str]] = []
    if _use_native():
        from bert_pytorch_tpu.native import vocab_trainer_merge

        new_tokens, merges = vocab_trainer_merge(
            words.items(), vocab, vocab_size, wordpiece_mode=False)
        vocab.extend(new_tokens)
        return {t: i for i, t in enumerate(vocab[:vocab_size])}, merges

    seen = set(vocab)
    engine = _MergeEngine(words.items())
    while len(vocab) < vocab_size:
        pairs = engine.pairs
        if not pairs:
            break
        best = max(pairs, key=lambda p: (pairs[p], p))
        new_symbol = best[0] + best[1]
        merges.append(best)
        engine.merge(best, new_symbol)
        if new_symbol not in seen:
            seen.add(new_symbol)
            vocab.append(new_symbol)
    return {t: i for i, t in enumerate(vocab[:vocab_size])}, merges


def save_wordpiece_vocab(vocab: List[str], output: str,
                         special_tokens: Tuple[str, ...] = SPECIAL_TOKENS,
                         pad_token: str = "[PAD]") -> None:
    """Specials to the front, pad forced to index 0 (reference :62-80)."""
    rest = [t for t in vocab if t not in special_tokens]
    front = [t for t in special_tokens if t != pad_token]
    ordered = [pad_token] + front + rest
    os.makedirs(os.path.dirname(os.path.abspath(output)), exist_ok=True)
    with open(output, "w", encoding="utf-8") as f:
        for t in ordered:
            f.write(t + "\n")


def save_bpe(vocab: Dict[str, int], merges: List[Tuple[str, str]],
             vocab_output: str, merges_output: Optional[str] = None) -> None:
    import json

    os.makedirs(os.path.dirname(os.path.abspath(vocab_output)), exist_ok=True)
    with open(vocab_output, "w", encoding="utf-8") as f:
        json.dump(vocab, f, ensure_ascii=False)
    merges_output = merges_output or os.path.join(
        os.path.dirname(vocab_output), "merges.txt")
    with open(merges_output, "w", encoding="utf-8") as f:
        f.write("#version: bert_pytorch_tpu\n")
        for a, b in merges:
            f.write(f"{a} {b}\n")


def main(argv=None):
    p = argparse.ArgumentParser(description="Vocabulary trainer")
    p.add_argument("-i", "--input", required=True,
                   help=".txt file or directory of .txt files")
    p.add_argument("-o", "--output", required=True)
    p.add_argument("-s", "--size", type=int, default=30000)
    p.add_argument("--tokenizer", default="wordpiece",
                   choices=["wordpiece", "bpe"])
    p.add_argument("--uppercase", action="store_true", default=False)
    p.add_argument("--special_tokens", nargs="+",
                   default=list(SPECIAL_TOKENS))
    p.add_argument("--pad_token", default="[PAD]")
    p.add_argument("--min_frequency", type=int, default=1)
    p.add_argument("--min_pair_frequency", type=int, default=2,
                   help="WordPiece only: pairs rarer than this are not merge "
                        "candidates (guards the likelihood-ratio score from "
                        "spending the whole budget on singleton junk)")
    p.add_argument("--wordpiece_score", default="gain",
                   choices=["gain", "ratio"],
                   help="'gain' (default, frequency-weighted likelihood "
                        "gain) or 'ratio' (HF-trainer likelihood ratio, for "
                        "byte-exact reference-vocab reproduction)")
    args = p.parse_args(argv)

    if os.path.isfile(args.input):
        files = [args.input]
    else:
        files = sorted(str(f) for f in Path(args.input).rglob("*.txt"))
    if not files:
        raise SystemExit(f"no input files under {args.input}")

    counts = count_words(files, lowercase=not args.uppercase)
    if args.tokenizer == "wordpiece":
        vocab = train_wordpiece(counts, args.size,
                                special_tokens=tuple(args.special_tokens),
                                min_frequency=args.min_frequency,
                                min_pair_frequency=args.min_pair_frequency,
                                score=args.wordpiece_score)
        save_wordpiece_vocab(vocab, args.output,
                             special_tokens=tuple(args.special_tokens),
                             pad_token=args.pad_token)
    else:
        # same special-token list for both trainers — the reference passed
        # args.special_tokens to the BPE trainer too (utils/build_vocab.py:
        # 45-57), which is what lets the encode pipeline's [CLS]/[SEP]
        # framing work on BPE vocabs
        vocab, merges = train_bpe(counts, args.size,
                                  special_tokens=tuple(args.special_tokens),
                                  min_frequency=args.min_frequency)
        save_bpe(vocab, merges, args.output)
    print(f"vocab written to {args.output}")


if __name__ == "__main__":
    main()
