"""Raw corpora -> one-sentence-per-line shards with blank lines between
articles (the format the sharder and encoder consume).

Reference utils/format.py: nltk sent_tokenize over joined lines (:13-16),
round-robin input files across output shards, multiprocessing pool
(:28-124). WikiCorpusFormatter consumes wikiextractor output (<doc> blocks);
BooksCorpusFormatter treats each file as one article.
"""

from __future__ import annotations

import argparse
import multiprocessing as mp
import os
import re
from pathlib import Path
from typing import List


def split_sentences(lines: List[str]) -> List[str]:
    text = " ".join(lines).replace("\n", " ")
    try:
        from nltk.tokenize import sent_tokenize

        return [s.strip() for s in sent_tokenize(text)]
    except (ImportError, LookupError):
        # regex fallback: split on sentence-final punctuation + space + upper
        parts = re.split(r"(?<=[.!?])\s+(?=[A-Z\"'(])", text)
        return [s.strip() for s in parts if s.strip()]


def _write_article(out, sentences: List[str]) -> None:
    if not sentences:
        return
    for s in sentences:
        out.write(s + "\n")
    out.write("\n")


def format_wiki_files(input_files: List[str], output_file: str) -> int:
    """wikiextractor output (<doc ...> text </doc>) -> formatted shard.
    Returns article count."""
    n = 0
    with open(output_file, "w", encoding="utf-8") as out:
        for path in input_files:
            with open(path, "r", encoding="utf-8") as f:
                article: List[str] = []
                in_doc = False
                for line in f:
                    if line.startswith("<doc"):
                        in_doc = True
                        article = []
                        continue
                    if line.startswith("</doc"):
                        in_doc = False
                        # first line is the title — drop it (not prose)
                        _write_article(out, split_sentences(article[1:]))
                        n += 1
                        continue
                    if in_doc and line.strip():
                        article.append(line)
    return n


def format_text_files(input_files: List[str], output_file: str) -> int:
    """Plain text, one article per file (BooksCorpus layout)."""
    n = 0
    with open(output_file, "w", encoding="utf-8") as out:
        for path in input_files:
            with open(path, "r", encoding="utf-8", errors="ignore") as f:
                _write_article(out, split_sentences(f.readlines()))
                n += 1
    return n


_FORMATTERS = {"wiki": format_wiki_files, "text": format_text_files}


def _run_one(params):
    kind, files, output_file = params
    n = _FORMATTERS[kind](files, output_file)
    print(f"[format] {output_file}: {n} articles")
    return n


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--input_dir", required=True)
    p.add_argument("--output_dir", required=True)
    p.add_argument("--kind", default="wiki", choices=sorted(_FORMATTERS))
    p.add_argument("--shards", type=int, default=-1,
                   help="output shard count (default: one per input file)")
    p.add_argument("--processes", type=int, default=4)
    p.add_argument("--name", default="corpus")
    args = p.parse_args(argv)

    files = sorted(str(f) for f in Path(args.input_dir).rglob("*")
                   if f.is_file())
    if not files:
        raise SystemExit(f"no files under {args.input_dir}")
    shards = args.shards if args.shards > 0 else len(files)
    shards = min(shards, len(files))
    os.makedirs(args.output_dir, exist_ok=True)

    buckets: List[List[str]] = [[] for _ in range(shards)]
    for i, f in enumerate(files):
        buckets[i % shards].append(f)
    params = [
        (args.kind, bucket,
         os.path.join(args.output_dir,
                      f"{args.name}_one_sentence_per_line_{i}.txt"))
        for i, bucket in enumerate(buckets)]
    with mp.Pool(processes=args.processes) as pool:
        counts = pool.map(_run_one, params)
    print(f"[format] {sum(counts)} articles across {shards} shards")


if __name__ == "__main__":
    main()
