"""Text resharding + subsampling utilities.

- `shard`: byte-size-bounded resharding that only cuts at article boundaries
  (blank lines) — reference utils/shard.py:6-27.
- `sample_and_shard`: random article subsampling down to a sentence budget,
  then sharding — reference utils/sample_and_shard.py:83-121.
- `parse_size`: '100M'-style size strings (reference shard.py:30-38).
"""

from __future__ import annotations

import argparse
import os
import random
from typing import Iterator, List, Optional

_POSTFIX = {"K": 1_000, "M": 1_000_000, "B": 1_000_000_000}


def parse_size(value) -> int:
    if isinstance(value, (int, float)):
        return int(value)
    v = str(value).strip()
    if v.isdigit():
        return int(v)
    if len(v) > 1 and v[-1].upper() in _POSTFIX:
        return int(float(v[:-1]) * _POSTFIX[v[-1].upper()])
    raise ValueError(f"cannot parse size {value!r}")


def iter_articles(path: str) -> Iterator[List[str]]:
    """Yield articles (lists of sentence lines) from a formatted file."""
    article: List[str] = []
    with open(path, "r", encoding="utf-8") as f:
        for line in f:
            if line.strip():
                article.append(line.rstrip("\n"))
            elif article:
                yield article
                article = []
    if article:
        yield article


def shard(input_file: str, output_format: str, bytes_per_shard: int,
          max_shards: Optional[int] = None) -> int:
    """Write shards of ~bytes_per_shard, cutting only between articles.
    Returns the shard count."""
    if "{index}" not in output_format:
        raise ValueError("output_format must contain '{index}'")
    out_dir = os.path.dirname(output_format)
    if out_dir:
        os.makedirs(out_dir, exist_ok=True)

    index = 1
    out = open(output_format.format(index=index), "w", encoding="utf-8")
    written = 0
    try:
        for article in iter_articles(input_file):
            if written > bytes_per_shard:
                out.close()
                index += 1
                if max_shards is not None and index > max_shards:
                    return index - 1
                out = open(output_format.format(index=index), "w",
                           encoding="utf-8")
                written = 0
            for line in article:
                written += out.write(line + "\n")
            written += out.write("\n")
    finally:
        out.close()
    return index


def sample_and_shard(input_files: List[str], output_format: str,
                     sentence_budget: int, bytes_per_shard: int,
                     seed: int = 0) -> int:
    """Randomly keep whole articles until ~sentence_budget sentences, then
    shard the sample. Articles are shuffled across all input files."""
    rng = random.Random(seed)
    articles: List[List[str]] = []
    for path in input_files:
        articles.extend(iter_articles(path))
    rng.shuffle(articles)

    kept: List[List[str]] = []
    total = 0
    for a in articles:
        if total >= sentence_budget:
            break
        kept.append(a)
        total += len(a)

    tmp = output_format.format(index=0) + ".sample"
    with open(tmp, "w", encoding="utf-8") as f:
        for a in kept:
            for line in a:
                f.write(line + "\n")
            f.write("\n")
    n = shard(tmp, output_format, bytes_per_shard)
    os.remove(tmp)
    print(f"[sample_and_shard] kept {len(kept)} articles "
          f"({total} sentences) in {n} shards")
    return n


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("-i", "--input", required=True)
    p.add_argument("-o", "--output", required=True)
    p.add_argument("-f", "--format", default="shard_{index}.txt")
    p.add_argument("-b", "--size", default="100M")
    p.add_argument("-n", "--max_shards", type=int, default=None)
    p.add_argument("--sample_sentences", default=None,
                   help="if set, subsample to this many sentences first "
                        "(accepts 10M-style values)")
    p.add_argument("--seed", type=int, default=0)
    args = p.parse_args(argv)

    os.makedirs(args.output, exist_ok=True)
    fmt = os.path.join(args.output, args.format)
    size = parse_size(args.size)
    if args.sample_sentences:
        n = sample_and_shard([args.input], fmt,
                             parse_size(args.sample_sentences), size,
                             seed=args.seed)
    else:
        n = shard(args.input, fmt, size, args.max_shards)
    print(f"[shard] wrote {n} shards to {args.output}")


if __name__ == "__main__":
    main()
