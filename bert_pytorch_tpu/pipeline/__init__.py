"""Offline corpus pipeline: download -> format -> shard -> vocab -> encode.

Mirrors the reference's utils/ package (SURVEY §2.1 rows download/format/
encode/vocab/shard; orchestrated by scripts/create_datasets.sh). Each module
is import-usable and a CLI (python -m bert_pytorch_tpu.pipeline.<step>).
The encoder writes the same gzip'd-HDF5 schema the runtime data layer reads
(input_ids i4 / special_token_positions i4 / next_sentence_labels i1,
reference utils/encode_data.py:204-210), so datasets built by either stack
are interchangeable.
"""
