#!/usr/bin/env python
"""One finetune driver, N registered tasks.

    python run_finetune.py --task classify --train_file pairs.tsv \
        --model_config_file cfg.json --output_dir out --packing

`--task` names any entry in the task registry
(bert_pytorch_tpu/tasks/registry.py — `--list_tasks` prints them); the
rest of the CLI is the task's own parser, so
`run_finetune.py --task squad ...` accepts exactly run_squad.py's
historical flags (run_squad.py and run_ner.py are thin aliases of this
script). The shared loop (training/finetune.py) gives every task packed
training (`--packing`), length-bucketed eval, StepWatch perf records
with real_tokens_per_sec / pad_fraction, the preemption guard +
emergency save, the hung-step watchdog, and a serving-restorable final
checkpoint. docs/TASKS.md is the contract + add-a-task walkthrough.
"""

from __future__ import annotations

import sys


def main(argv=None) -> dict:
    argv = list(sys.argv[1:] if argv is None else argv)

    from bert_pytorch_tpu.tasks import registry

    if "--list_tasks" in argv:
        for name in registry.all_tasks():
            spec = registry.get(name)
            print(f"{name}: {spec.title} [{spec.head}, "
                  f"metric {spec.metric}]")
        return {}

    task = None
    for i, tok in enumerate(argv):
        if tok == "--task":
            if i + 1 >= len(argv):
                raise SystemExit("--task needs a task name")
            task = argv[i + 1]
            argv = argv[:i] + argv[i + 2:]
            break
        if tok.startswith("--task="):
            task = tok[len("--task="):]
            argv = argv[:i] + argv[i + 1:]
            break
    if not task:
        raise SystemExit(
            "--task <name> is required; registered tasks: "
            + ", ".join(registry.all_tasks())
            + " (--list_tasks for details)")
    try:
        spec = registry.get(task)
    except KeyError as e:
        raise SystemExit(str(e))

    args = spec.parse_arguments(argv)

    from bert_pytorch_tpu.training.finetune import run_task

    return run_task(spec, args)


if __name__ == "__main__":
    main()
