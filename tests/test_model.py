"""Model-layer tests: shapes, numerics, parity of LayerNorm/GELU with golden
numpy implementations, tied-decoder behavior, remat equivalence."""

import jax
import jax.numpy as jnp
import numpy as np

from bert_pytorch_tpu.config import BertConfig
from bert_pytorch_tpu.models import (
    BertForMaskedLM,
    BertForPreTraining,
    BertForQuestionAnswering,
    BertForSequenceClassification,
    BertForTokenClassification,
    BertModel,
    losses,
)
from bert_pytorch_tpu.ops import gelu, layer_norm

TINY = BertConfig(
    vocab_size=128, hidden_size=32, num_hidden_layers=2,
    num_attention_heads=4, intermediate_size=64,
    max_position_embeddings=64, next_sentence=True,
    dtype="float32", fused_ops=False, attention_impl="xla",
)


def _inputs(batch=2, seq=16, vocab=128, seed=0):
    rng = np.random.RandomState(seed)
    ids = rng.randint(0, vocab, (batch, seq)).astype(np.int32)
    types = rng.randint(0, 2, (batch, seq)).astype(np.int32)
    mask = np.ones((batch, seq), np.int32)
    mask[:, seq - 3:] = 0
    return jnp.array(ids), jnp.array(types), jnp.array(mask)


def test_layer_norm_matches_numpy():
    x = np.random.RandomState(0).randn(4, 10, 32).astype(np.float32)
    scale = np.random.RandomState(1).randn(32).astype(np.float32)
    bias = np.random.RandomState(2).randn(32).astype(np.float32)
    got = layer_norm(jnp.array(x), jnp.array(scale), jnp.array(bias))
    mean = x.mean(-1, keepdims=True)
    var = ((x - mean) ** 2).mean(-1, keepdims=True)
    want = (x - mean) / np.sqrt(var + 1e-12) * scale + bias
    np.testing.assert_allclose(np.asarray(got), want, rtol=1e-5, atol=1e-5)


def test_gelu_is_exact_erf():
    import math

    x = np.linspace(-4, 4, 101).astype(np.float32)
    want = np.array([0.5 * v * (1 + math.erf(v / math.sqrt(2))) for v in x])
    np.testing.assert_allclose(np.asarray(gelu(jnp.array(x))), want,
                               rtol=1e-5, atol=1e-6)


def test_bert_model_shapes():
    ids, types, mask = _inputs()
    model = BertModel(TINY, dtype=jnp.float32)
    params = model.init(jax.random.PRNGKey(0), ids, types, mask)
    seq_out, pooled = model.apply(params, ids, types, mask)
    assert seq_out.shape == (2, 16, 32)
    assert pooled.shape == (2, 32)


def test_pretraining_head_shapes_and_loss():
    ids, types, mask = _inputs()
    model = BertForPreTraining(TINY, dtype=jnp.float32)
    params = model.init(jax.random.PRNGKey(0), ids, types, mask)
    mlm_logits, nsp_logits = model.apply(params, ids, types, mask)
    assert mlm_logits.shape == (2, 16, 128) and mlm_logits.dtype == jnp.float32
    assert nsp_logits.shape == (2, 2)

    labels = np.full((2, 16), -1, np.int32)
    labels[0, 3] = 7
    labels[1, 5] = 11
    nsp_labels = np.array([0, 1], np.int32)
    loss = losses.pretraining_loss(mlm_logits, jnp.array(labels), nsp_logits,
                                   jnp.array(nsp_labels))
    assert np.isfinite(float(loss)) and float(loss) > 0


def test_no_nsp_config_drops_pooler_and_token_type():
    cfg = TINY.replace(next_sentence=False)
    ids, _, mask = _inputs()
    model = BertModel(cfg, dtype=jnp.float32)
    params = model.init(jax.random.PRNGKey(0), ids, None, mask)
    flat = jax.tree_util.tree_leaves_with_path(params)
    names = [jax.tree_util.keystr(p) for p, _ in flat]
    assert not any("token_type" in n for n in names)
    assert not any("pooler" in n for n in names)
    seq_out, pooled = model.apply(params, ids, None, mask)
    assert pooled is None


def test_cross_entropy_matches_torch_semantics():
    import torch

    rng = np.random.RandomState(0)
    logits = rng.randn(4, 6, 11).astype(np.float32)
    labels = rng.randint(-1, 11, (4, 6)).astype(np.int64)
    got = losses.cross_entropy(jnp.array(logits), jnp.array(labels),
                               ignore_index=-1)
    want = torch.nn.functional.cross_entropy(
        torch.tensor(logits).reshape(-1, 11), torch.tensor(labels).reshape(-1),
        ignore_index=-1)
    np.testing.assert_allclose(float(got), float(want), rtol=1e-5)


def test_tied_decoder_grads_flow_to_embedding():
    ids, types, mask = _inputs()
    model = BertForMaskedLM(TINY, dtype=jnp.float32)
    params = model.init(jax.random.PRNGKey(0), ids, types, mask)
    labels = np.full((2, 16), -1, np.int32)
    labels[0, 0] = 5

    def loss_fn(p):
        logits = model.apply(p, ids, types, mask)
        return losses.cross_entropy(logits, jnp.array(labels))

    grads = jax.grad(loss_fn)(params)
    emb_grad = grads["params"]["bert"]["embeddings"]["word_embeddings"][
        "embedding"]
    emb_grad = emb_grad.unbox() if hasattr(emb_grad, "unbox") else emb_grad
    assert float(jnp.abs(emb_grad).sum()) > 0


def test_remat_matches_no_remat():
    ids, types, mask = _inputs()
    m1 = BertModel(TINY, dtype=jnp.float32)
    m2 = BertModel(TINY.replace(checkpoint_activations=True),
                   dtype=jnp.float32)
    params = m1.init(jax.random.PRNGKey(0), ids, types, mask)
    out1, _ = m1.apply(params, ids, types, mask)
    out2, _ = m2.apply(params, ids, types, mask)
    np.testing.assert_allclose(np.asarray(out1), np.asarray(out2),
                               rtol=1e-5, atol=1e-5)


def test_scan_unroll_matches_scanned():
    """scan_unroll only changes the compiled loop structure (config.py);
    param tree stays stacked and outputs must match the while-loop scan."""
    ids, types, mask = _inputs()
    m1 = BertModel(TINY, dtype=jnp.float32)
    params = m1.init(jax.random.PRNGKey(0), ids, types, mask)
    out1, _ = m1.apply(params, ids, types, mask)
    for unroll in (2, 99):  # partial is clamped; 99 > L means full unroll
        m2 = BertModel(TINY.replace(scan_unroll=unroll), dtype=jnp.float32)
        out2, _ = m2.apply(params, ids, types, mask)
        np.testing.assert_allclose(np.asarray(out1), np.asarray(out2),
                                   rtol=1e-6, atol=1e-6)


def test_qa_and_classification_heads():
    ids, types, mask = _inputs()
    qa = BertForQuestionAnswering(TINY, dtype=jnp.float32)
    p = qa.init(jax.random.PRNGKey(0), ids, types, mask)
    start, end = qa.apply(p, ids, types, mask)
    assert start.shape == (2, 16) and end.shape == (2, 16)
    loss = losses.qa_loss(start, end, jnp.array([1, 2]), jnp.array([3, 4]))
    assert np.isfinite(float(loss))

    clf = BertForSequenceClassification(TINY, num_labels=3, dtype=jnp.float32)
    p = clf.init(jax.random.PRNGKey(0), ids, types, mask)
    logits = clf.apply(p, ids, types, mask)
    assert logits.shape == (2, 3)

    tok = BertForTokenClassification(TINY, num_labels=5, dtype=jnp.float32)
    p = tok.init(jax.random.PRNGKey(0), ids, types, mask)
    logits = tok.apply(p, ids, types, mask)
    assert logits.shape == (2, 16, 5)
    labels = np.full((2, 16), -100, np.int64)
    labels[:, :4] = 1
    l = losses.token_classification_loss(logits, jnp.array(labels))
    assert np.isfinite(float(l))


def test_attention_mask_effect():
    """Masked positions must not influence unmasked outputs."""
    ids, types, _ = _inputs()
    mask = np.ones((2, 16), np.int32)
    mask[:, 8:] = 0
    model = BertModel(TINY, dtype=jnp.float32)
    params = model.init(jax.random.PRNGKey(0), ids, types, jnp.array(mask))
    out1, _ = model.apply(params, ids, types, jnp.array(mask))
    ids2 = np.asarray(ids).copy()
    ids2[:, 12] = (ids2[:, 12] + 1) % 128  # change a masked-out token
    out2, _ = model.apply(params, jnp.array(ids2), types, jnp.array(mask))
    np.testing.assert_allclose(np.asarray(out1[:, :8]),
                               np.asarray(out2[:, :8]), rtol=1e-5, atol=1e-5)


def test_gathered_mlm_head_matches_dense():
    """masked_positions gather: logits at the gathered positions and the
    resulting loss must match the dense (B, S, V) path exactly."""
    from bert_pytorch_tpu.training.pretrain import gather_masked_labels

    ids, types, mask = _inputs(batch=3, seq=16)
    model = BertForPreTraining(TINY, dtype=jnp.float32)
    params = model.init(jax.random.PRNGKey(0), ids, types, mask)

    rng = np.random.RandomState(7)
    labels = np.full((3, 16), -1, np.int32)
    # rows with 3, 1, and 0 masked tokens; P=4 exercises the -1 fill tail
    labels[0, [2, 5, 9]] = rng.randint(0, 128, 3)
    labels[1, [11]] = rng.randint(0, 128)
    labels = jnp.asarray(labels)
    positions, glabels = gather_masked_labels(labels, 4)

    dense_logits, nsp = model.apply(params, ids, types, mask,
                                    deterministic=True)
    gath_logits, _ = model.apply(params, ids, types, mask,
                                 deterministic=True,
                                 masked_positions=positions)
    assert gath_logits.shape == (3, 4, TINY.vocab_size)
    want = jnp.take_along_axis(dense_logits, positions[..., None], axis=1)
    np.testing.assert_allclose(np.asarray(gath_logits), np.asarray(want),
                               rtol=1e-6, atol=1e-6)

    # gathered labels: tail fill positions carry -1 (ignored by the loss)
    assert int((glabels == -1).sum()) == 12 - 3 - 1

    nsl = jnp.asarray(rng.randint(0, 2, (3,)).astype(np.int32))
    dense_loss = losses.pretraining_loss(dense_logits, labels, nsp, nsl)
    gath_loss = losses.pretraining_loss(gath_logits, glabels, nsp, nsl)
    np.testing.assert_allclose(float(gath_loss), float(dense_loss),
                               rtol=1e-6)
