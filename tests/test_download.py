"""Downloader tests (egress-free via file:// URLs): registry coverage,
fetch + checksum + extract, BooksCorpus URL-list fetch with bad-file
hygiene, GLUE per-task resolution (reference utils/download.py:59-216)."""

import json
import os
import zipfile

import pytest

from bert_pytorch_tpu.pipeline import download


def test_registry_covers_reference_datasets():
    # every dataset family the reference's downloader knew (utils/download.py)
    assert {"squad", "wikicorpus", "glue",
            "google_pretrained_weights"} <= set(download.DATASETS)
    glue = download.DATASETS["glue"]
    for task in ("CoLA", "SST", "QQP", "STS", "MNLI", "QNLI", "RTE", "WNLI"):
        assert task in glue, task
    assert "MRPC-train" in glue and "MRPC-test" in glue


def test_fetch_file_url_with_checksum_and_extract(tmp_path):
    payload_dir = tmp_path / "src"
    payload_dir.mkdir()
    inner = payload_dir / "data.tsv"
    inner.write_text("a\t1\nb\t2\n")
    zip_path = tmp_path / "task.zip"
    with zipfile.ZipFile(zip_path, "w") as zf:
        zf.write(inner, arcname="TASK/data.tsv")

    res = download.Resource(f"file://{zip_path}", "task.zip",
                            sha256=download.sha256_file(str(zip_path)),
                            extract=True)
    out = tmp_path / "out"
    target = download.fetch(res, str(out))
    assert os.path.exists(target)
    assert (out / "TASK" / "data.tsv").read_text() == "a\t1\nb\t2\n"

    # checksum mismatch is fatal and removes the bad file
    bad = download.Resource(f"file://{zip_path}", "bad.zip", sha256="0" * 64)
    with pytest.raises(IOError):
        download.fetch(bad, str(out))
    assert not (out / "bad.zip").exists()


def test_fetch_nested_filename_creates_dirs(tmp_path):
    f = tmp_path / "m.txt"
    f.write_text("x" * 10)
    res = download.Resource(f"file://{f}", "MRPC/msr_paraphrase_train.txt")
    target = download.fetch(res, str(tmp_path / "glue"))
    assert target.endswith("MRPC/msr_paraphrase_train.txt")
    assert os.path.exists(target)


def test_bookscorpus_url_list_fetch(tmp_path):
    books = tmp_path / "books"
    books.mkdir()
    good1 = books / "book_a.txt"
    good1.write_text("sentence. " * 500)       # big enough
    good2 = books / "book_b.txt"
    good2.write_text("words words. " * 500)
    tiny = books / "stub.txt"
    tiny.write_text("too small")               # must be trashed

    url_list = tmp_path / "url_list.jsonl"
    lines = [
        json.dumps({"txt": f"file://{good1}", "page": "p1"}),
        json.dumps({"txt": f"file://{good2}"}),
        json.dumps({"txt": f"file://{tiny}"}),
        json.dumps({"epub": "ignored-no-txt-field"}),
        f"file://{books}/missing.txt",          # plain-line URL, 404s
    ]
    url_list.write_text("\n".join(lines) + "\n")

    out = tmp_path / "corpus"
    kept = download.fetch_bookscorpus(str(url_list), str(out), min_bytes=1024)
    assert kept == 2
    got = sorted(os.listdir(out / "bookscorpus"))
    assert got == ["000000_book_a.txt", "000001_book_b.txt"]

    # idempotent: second run keeps the same two without re-downloading
    assert download.fetch_bookscorpus(str(url_list), str(out),
                                      min_bytes=1024) == 2


def test_cli_bookscorpus(tmp_path):
    book = tmp_path / "x.txt"
    book.write_text("line. " * 400)
    url_list = tmp_path / "urls.txt"
    url_list.write_text(f"file://{book}\n")
    download.main(["--dataset", "bookscorpus", "--url_list", str(url_list),
                   "--output_dir", str(tmp_path / "o")])
    assert (tmp_path / "o" / "bookscorpus" / "000000_x.txt").exists()
