"""Two-process multi-host feed test.

The reference validated its distributed data path by launching multiple local
CPU processes in a gloo process group (/root/reference/src/dataset.py:431-506).
This is the JAX analogue: two real OS processes, each exposing 4 virtual CPU
devices, joined through jax.distributed.initialize into one 8-device
platform. It exercises the one seam single-process virtual-mesh tests cannot:
per-process feeding through jax.make_array_from_process_local_data +
HostShardSampler chunk math (parallel/mesh.py, data/sharded.py).
"""

import os
import socket
import subprocess
import sys

import pytest

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


@pytest.mark.slow
def test_two_process_host_feed(tmp_path):
    port = _free_port()
    coordinator = f"127.0.0.1:{port}"
    num_procs = 2
    ckpt_dir = str(tmp_path / "ckpt")

    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    # the conftest's 8-device setting must not leak into the children
    procs = [
        subprocess.Popen(
            [sys.executable, os.path.join(HERE, "multihost_child.py"),
             coordinator, str(num_procs), str(i), ckpt_dir],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True)
        for i in range(num_procs)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=300)
            outs.append(out)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, (
            f"multihost child {i} failed (rc={p.returncode}):\n{out[-4000:]}")
        assert f"MULTIHOST_CHILD_OK proc={i}" in out, out[-4000:]


def test_initialize_autodetects_cluster(monkeypatch):
    """dist.initialize() must bring up jax.distributed by itself when a
    cluster environment is detectable — the reference called
    init_process_group unconditionally (run_pretraining.py:175); a pod run
    that silently skips initialization breaks orbax multi-host coordination.
    Simulated here: the detector is forced true and jax.distributed.initialize
    is stubbed to record the call."""
    import jax

    from bert_pytorch_tpu.parallel import dist

    calls = []
    monkeypatch.setattr(dist, "_cluster_env_present", lambda: True)
    # raising=False: jax < 0.5 has no is_initialized attribute at all —
    # dist.is_initialized() probes it with getattr and falls back to the
    # private global-state check, so injecting it here covers both paths
    monkeypatch.setattr(jax.distributed, "is_initialized", lambda: False,
                        raising=False)
    monkeypatch.setattr(jax.distributed, "initialize",
                        lambda *a, **k: calls.append((a, k)))
    dist.initialize()
    assert calls == [((), {})]  # argless auto-detect path

    # explicit-args path (CPU clusters) still forwards the args
    calls.clear()
    dist.initialize(coordinator_address="127.0.0.1:1234",
                    num_processes=2, process_id=1)
    assert calls and calls[0][1]["num_processes"] == 2

    # single host, no cluster env: stays a no-op
    calls.clear()
    monkeypatch.setattr(dist, "_cluster_env_present", lambda: False)
    dist.initialize()
    assert calls == []


def test_initialize_noop_when_already_up(monkeypatch):
    import jax

    from bert_pytorch_tpu.parallel import dist

    monkeypatch.setattr(jax.distributed, "is_initialized", lambda: True,
                        raising=False)
    monkeypatch.setattr(
        jax.distributed, "initialize",
        lambda *a, **k: (_ for _ in ()).throw(AssertionError("re-init")))
    dist.initialize(num_processes=2)  # must not raise
