"""ZeRO-1 optimizer-state sharding (parallel/zero.py) on the 8-device CPU
mesh: spec derivation units, sharded-vs-replicated update parity (params
bit-close over multiple steps, trust ratios preserved), moments born AND
kept sharded, checkpoint round-trip of sharded moments, the promoted
zero-reshard compile gate (2x2 mesh), the overlap flag pack, and the
dryrun's known-noise stderr filter."""

import os
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from bert_pytorch_tpu.config import BertConfig
from bert_pytorch_tpu.models import BertForPreTraining
from bert_pytorch_tpu.optim import schedulers
from bert_pytorch_tpu.optim.lamb import (default_trust_batch_axes,
                                         default_weight_decay_mask, lamb)
from bert_pytorch_tpu.parallel import mesh as mesh_lib
from bert_pytorch_tpu.parallel.zero import (assert_moments_sharded,
                                            make_zero1_plan, zero1_spec)
from bert_pytorch_tpu.training import (CheckpointManager,
                                       build_pretrain_step,
                                       make_sharded_state)
from bert_pytorch_tpu.training.pretrain import stack_microbatches

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

TINY = BertConfig(
    vocab_size=128, hidden_size=32, num_hidden_layers=2,
    num_attention_heads=4, intermediate_size=64,
    max_position_embeddings=64, next_sentence=True,
    dtype="float32", fused_ops=False, attention_impl="xla",
    hidden_dropout_prob=0.0, attention_probs_dropout_prob=0.0,
)


def _batch(global_batch=16, seq=16, vocab=128, seed=0):
    rng = np.random.RandomState(seed)
    ids = rng.randint(5, vocab, (global_batch, seq)).astype(np.int32)
    labels = np.full((global_batch, seq), -1, np.int32)
    for b in range(global_batch):
        for p in rng.randint(1, seq - 1, (2,)):
            labels[b, p] = ids[b, p]
            ids[b, p] = 3
    return stack_microbatches({
        "input_ids": ids,
        "token_type_ids": np.zeros((global_batch, seq), np.int32),
        "attention_mask": np.ones((global_batch, seq), np.int32),
        "masked_lm_labels": labels,
        "next_sentence_labels": rng.randint(0, 2, (global_batch,)).astype(
            np.int32),
    }, 1)


def _tx():
    sched = schedulers.poly_warmup_schedule(1e-3, total_steps=100, warmup=0.1)
    return lamb(sched, weight_decay=0.01,
                weight_decay_mask=default_weight_decay_mask,
                trust_batch_axes=default_trust_batch_axes), sched


def _setup(mesh, zero1):
    model = BertForPreTraining(TINY, dtype=jnp.float32)
    tx, sched = _tx()
    sample = _batch()
    init_fn = lambda r: model.init(
        r, jnp.asarray(sample["input_ids"][0]),
        jnp.asarray(sample["token_type_ids"][0]),
        jnp.asarray(sample["attention_mask"][0]))
    with mesh_lib.logical_rules():
        state, shardings = make_sharded_state(
            jax.random.PRNGKey(0), init_fn, tx, mesh=mesh, zero1=zero1)
    plan = (make_zero1_plan(state.params, shardings.params, mesh)
            if zero1 else None)
    step_fn = build_pretrain_step(model, tx, schedule=sched, zero1=plan)
    return state, plan, jax.jit(step_fn, donate_argnums=(0,))


# --- spec derivation units ---------------------------------------------


def test_zero1_spec_picks_largest_divisible_dim():
    mesh = mesh_lib.make_mesh()  # data=8
    assert zero1_spec((64, 16), P(None, None), mesh) == P("data", None)
    # dim0 not divisible by 8 -> falls to dim1
    assert zero1_spec((12, 32), P(None, None), mesh) == P(None, "data")
    # nothing divisible -> unchanged
    assert zero1_spec((3, 5), P(None, None), mesh) == P(None, None)
    # scalar untouched
    assert zero1_spec((), P(), mesh) == P()


def test_zero1_spec_composes_with_existing_axes():
    mesh = mesh_lib.make_mesh({"data": 2, "fsdp": 4})
    # a FREE dim that divides is preferred over stacking onto the fsdp dim
    # (an everything-sharded grad layout costs involuntary reshards against
    # the batch-sharded backward residuals)
    assert zero1_spec((64, 8), P("fsdp", None), mesh) == P("fsdp", "data")
    # no free dim divides -> data stacks onto the already-sharded dim
    assert zero1_spec((64, 3), P("fsdp", None), mesh) == \
        P(("fsdp", "data"), None)
    # axis already used anywhere -> unchanged
    assert zero1_spec((64, 8), P("data", None), mesh) == P("data", None)
    # size-1 mesh axes occupying an entry count as free (nothing is
    # actually sharded there), so the biggest dim still wins
    mesh_dp = mesh_lib.make_mesh()  # data=8, fsdp/model size 1
    got = zero1_spec((64, 8), P(("model", "fsdp"), None), mesh_dp)
    assert got == P(("model", "fsdp", "data"), None)


def test_make_zero1_plan_none_when_trivial():
    one = mesh_lib.make_mesh({"data": 1, "fsdp": 8})
    params = {"w": jnp.zeros((16, 16))}
    from jax.sharding import NamedSharding

    base = {"w": NamedSharding(one, P(None, None))}
    assert make_zero1_plan(params, base, one) is None
    assert make_zero1_plan(params, base, None) is None


def test_zero1_spec_prime_and_odd_dims_fall_back():
    """Leaves with no evenly-divisible dim keep their base spec — a ragged
    split would cost GSPMD padding every step, and the small leaves this
    hits (norm scales, odd biases) are cheap to keep replicated."""
    mesh = mesh_lib.make_mesh()  # data=8
    # primes and odds against n=8: nothing divides -> unchanged
    assert zero1_spec((7, 13), P(None, None), mesh) == P(None, None)
    assert zero1_spec((17,), P(None), mesh) == P(None)
    assert zero1_spec((3, 3, 5), P(None, None, None), mesh) == \
        P(None, None, None)
    # mixed: the odd dim is skipped, the divisible one takes the split
    assert zero1_spec((7, 24), P(None, None), mesh) == P(None, "data")
    # divisible by a FACTOR of n but not n itself (4 % 8): no ragged split
    assert zero1_spec((4, 3), P(None, None), mesh) == P(None, None)


def test_zero1_spec_stacking_needs_joint_divisibility():
    """Stacking data onto an fsdp-sharded dim requires divisibility by the
    JOINT factor (fsdp * data), not just data — otherwise fall back."""
    mesh = mesh_lib.make_mesh({"data": 2, "fsdp": 4})
    # 12 % (4*2) != 0: cannot stack onto the fsdp dim; 5 is indivisible
    # by 2 -> whole leaf falls back to base
    assert zero1_spec((12, 5), P("fsdp", None), mesh) == P("fsdp", None)
    # 16 % (4*2) == 0: stacking is legal when no free dim divides
    assert zero1_spec((16, 5), P("fsdp", None), mesh) == \
        P(("fsdp", "data"), None)


def test_zero1_spec_vocab_dim_never_double_stacks_over_free_dim():
    """The tied-embedding shape: vocab dim already (model, fsdp)-sharded.
    With ANY divisible free dim present, data must land there — an
    everything-on-one-dim grad layout costs involuntary reshards against
    the batch-sharded backward residuals (the round-7 reshard gate)."""
    mesh = mesh_lib.make_mesh({"data": 2, "fsdp": 2, "model": 2})
    # 64 divides the joint (model*fsdp*data) factor, so stacking WOULD be
    # legal — but the divisible free dim must win
    got = zero1_spec((64, 16), P(("model", "fsdp"), None), mesh)
    assert got == P(("model", "fsdp"), "data")


# --- parity + sharded state --------------------------------------------


def test_zero1_parity_and_moments_stay_sharded(tmp_path):
    """Same grads through the replicated and the ZeRO-1-sharded LAMB update
    on the 8-way data mesh: params bit-close after several steps (trust
    ratios are a function of the update, so parity of params across steps
    implies per-tensor/per-layer ratios matched), moments genuinely sharded
    before and after stepping, and the sharded moments survive a checkpoint
    round-trip."""
    mesh = mesh_lib.make_mesh()  # data=8
    state_r, _, step_r = _setup(mesh, zero1=False)
    state_z, plan, step_z = _setup(mesh, zero1=True)
    assert plan is not None

    # EVERY planned moment leaf born sharded (per-leaf plan walk, not a
    # spot check — partial replication must fail)
    assert_moments_sharded(state_z.opt_state.mu, plan, "at init")
    assert_moments_sharded(state_z.opt_state.nu, plan, "at init (nu)")
    # the replicated arm really is replicated (the contrast under test)
    emb_r = state_r.opt_state.mu["bert"]["embeddings"]["word_embeddings"][
        "embedding"]
    assert emb_r.sharding.is_fully_replicated

    batch = mesh_lib.host_to_device_batch(mesh, _batch())
    with mesh, mesh_lib.logical_rules():
        for i in range(4):
            state_r, m_r = step_r(state_r, batch, jax.random.PRNGKey(i))
            state_z, m_z = step_z(state_z, batch, jax.random.PRNGKey(i))
    np.testing.assert_allclose(float(m_r["loss"]), float(m_z["loss"]),
                               rtol=1e-6)
    for a, b in zip(jax.tree.leaves(state_r.params),
                    jax.tree.leaves(state_z.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-5, atol=1e-7)
    # moments numerically identical too (mu/nu are linear in the grads; the
    # only difference is reduction order) and still sharded after stepping
    for a, b in zip(jax.tree.leaves(state_r.opt_state.mu),
                    jax.tree.leaves(state_z.opt_state.mu)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-5, atol=1e-8)
    assert_moments_sharded(state_z.opt_state.mu, plan, "post-step")
    emb2 = state_z.opt_state.mu["bert"]["embeddings"]["word_embeddings"][
        "embedding"]

    # checkpoint round-trip of the SHARDED moments: orbax restores into the
    # zero1 layout from the abstract template's shardings
    mgr = CheckpointManager(str(tmp_path / "ckpts"), max_to_keep=2)
    assert mgr.save(4, state_z, extra={"epoch": 0})
    mgr.wait()
    abstract = jax.tree.map(
        lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=x.sharding),
        state_z)
    restored, extra, step = mgr.restore(abstract)
    assert step == 4 and extra["epoch"] == 0
    r_emb = restored.opt_state.mu["bert"]["embeddings"]["word_embeddings"][
        "embedding"]
    assert r_emb.sharding == emb2.sharding
    for a, b in zip(jax.tree.leaves(state_z.opt_state.mu),
                    jax.tree.leaves(restored.opt_state.mu)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # and training continues identically from the restored sharded state
    with mesh, mesh_lib.logical_rules():
        cont, _ = step_z(state_z, batch, jax.random.PRNGKey(9))
        cont_r, _ = step_z(restored, batch, jax.random.PRNGKey(9))
    for a, b in zip(jax.tree.leaves(cont.params),
                    jax.tree.leaves(cont_r.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    mgr.close()


# --- gather-on-use ZeRO-1 (--zero1_overlap, round 11) -------------------


@pytest.mark.slow  # both arms: tier-1's 870s budget; the compiled
# collective structure stays tier-1-pinned via the graph-budget gate
@pytest.mark.parametrize(
    "stacked",
    [True,
     # the unstacked arm re-proves the same claims at per-layer scatter
     # granularity — an extra XLA compile, so (like the fsdp/rs siblings
     # below) it rides outside tier-1's wall-clock budget
     pytest.param(False, marks=pytest.mark.slow)],
    ids=["stacked", "unstacked"])
def test_zero1_overlap_bit_identical(stacked):
    """gather_on_use=True must be the SAME training run as the round-7
    path — params, mu, nu, and loss bit-identical over several steps —
    while the params genuinely rest in the 1/N shard layout between steps
    and the step's all-gather count stays flat (the gathers MOVED from
    trailing the update to leading the forward; none were added). Both
    encoder layouts, because the per-leaf gather granularity differs:
    whole (L, ...) stacks vs per-layer kernels."""
    from bert_pytorch_tpu.analysis import collective_counts

    cfg = TINY if stacked else TINY.replace(stacked_params=False)
    mesh = mesh_lib.make_mesh()  # data=8
    model = BertForPreTraining(cfg, dtype=jnp.float32)
    tx, sched = _tx()
    sample = _batch()
    init_fn = lambda r: model.init(
        r, jnp.asarray(sample["input_ids"][0]),
        jnp.asarray(sample["token_type_ids"][0]),
        jnp.asarray(sample["attention_mask"][0]))

    def make(overlap):
        with mesh_lib.logical_rules():
            state, shardings = make_sharded_state(
                jax.random.PRNGKey(0), init_fn, tx, mesh=mesh, zero1=True,
                zero1_params=overlap)
        plan = make_zero1_plan(state.params, shardings.params, mesh,
                               gather_on_use=overlap)
        assert plan is not None and plan.gather_on_use == overlap
        step = build_pretrain_step(model, tx, schedule=sched, zero1=plan)
        return state, jax.jit(step, donate_argnums=(0,))

    s_base, step_base = make(False)
    s_ovl, step_ovl = make(True)

    # the feature's storage claim: params born (and kept) shard-resident
    n_sharded = sum(1 for l in jax.tree.leaves(s_ovl.params)
                    if not l.sharding.is_fully_replicated)
    assert n_sharded >= 10, f"only {n_sharded} param leaves rest sharded"

    batch = mesh_lib.host_to_device_batch(mesh, _batch())
    gathers = {}
    with mesh, mesh_lib.logical_rules():
        for name, st, fn in (("base", s_base, step_base),
                             ("ovl", s_ovl, step_ovl)):
            # one compile serves both the HLO inspection and the run; the
            # counter is the analyzer's (shared with the graphcheck budget
            # pass and bench --multichip), not a per-test regex
            compiled = fn.lower(st, batch, jax.random.PRNGKey(0)).compile()
            gathers[name] = collective_counts(
                compiled.as_text())["all-gather"]
        for i in range(3):
            s_base, m_b = step_base(s_base, batch, jax.random.PRNGKey(i))
            s_ovl, m_o = step_ovl(s_ovl, batch, jax.random.PRNGKey(i))
            assert float(m_b["loss"]) == float(m_o["loss"]), f"step {i}"

    assert gathers["ovl"] == gathers["base"], (
        f"overlap program changed the all-gather count: {gathers} — the "
        "gathers must MOVE (update tail -> point of use), not multiply")

    for tree_b, tree_o, what in (
            (s_base.params, s_ovl.params, "params"),
            (s_base.opt_state.mu, s_ovl.opt_state.mu, "mu"),
            (s_base.opt_state.nu, s_ovl.opt_state.nu, "nu")):
        for a, b in zip(jax.tree.leaves(tree_b), jax.tree.leaves(tree_o)):
            np.testing.assert_array_equal(
                np.asarray(a), np.asarray(b),
                err_msg=f"{what} not bit-identical after 3 steps")
    # ...and the overlap params STILL rest sharded after stepping
    n_sharded = sum(1 for l in jax.tree.leaves(s_ovl.params)
                    if not l.sharding.is_fully_replicated)
    assert n_sharded >= 10


# --- reduce-scatter gradients (--zero1_rs, round 16) --------------------


def test_zero1_rs_plan_validation_and_scatter_dims():
    """The rs plan's guard rails: reduce_scatter refuses without
    gather_on_use (the region consumes replicated params and emits
    sharded grads) and on any mesh with a second non-trivial axis (inside
    shard_map every axis is manual — a model-sharded forward would
    silently compute garbage). scatter_dims reads the appended-axis
    derivation back per leaf: the dim carrying plan.axis, None for
    divisibility-fallback leaves."""
    from jax.sharding import NamedSharding

    from bert_pytorch_tpu.parallel.zero import rs_supported, scatter_dims

    mesh = mesh_lib.make_mesh()  # data=8, other axes trivial
    params = {"big": jnp.zeros((64, 16)), "odd": jnp.zeros((7, 13))}
    base = {k: NamedSharding(mesh, P(None, None)) for k in params}
    with pytest.raises(ValueError, match="gather_on_use"):
        make_zero1_plan(params, base, mesh, reduce_scatter=True,
                        warn_skipped=False)

    mixed = mesh_lib.make_mesh({"data": 2, "model": 4})
    base_m = {k: NamedSharding(mixed, P(None, None)) for k in params}
    assert rs_supported(mesh) and not rs_supported(mixed)
    with pytest.raises(ValueError, match="data-only"):
        make_zero1_plan(params, base_m, mixed, gather_on_use=True,
                        reduce_scatter=True, warn_skipped=False)

    plan = make_zero1_plan(params, base, mesh, gather_on_use=True,
                           reduce_scatter=True, warn_skipped=False)
    assert plan.reduce_scatter and plan.rs_mode == "scatter"
    dims = dict(zip(sorted(params), scatter_dims(plan)))
    assert dims["big"] == 0        # (64, 16): data landed on dim 0
    assert dims["odd"] is None     # prime dims: replicated fallback


@pytest.mark.slow  # both arms: tier-1's 870s budget; the compiled
# collective structure stays tier-1-pinned via the graph-budget gate
@pytest.mark.parametrize(
    "stacked",
    [True,
     # the unstacked arm re-proves the claims at per-layer scatter
     # granularity and adds the legacy-GSPMD reference arm — two more XLA
     # compiles, so it rides outside tier-1's wall-clock budget
     pytest.param(False, marks=pytest.mark.slow)],
    ids=["stacked", "unstacked"])
def test_zero1_rs_bit_identical(stacked):
    """--zero1_rs: the shard_map region whose gradients exit through
    psum_scatter vs the SAME region with rs_mode='allreduce' (psum +
    slice-own-shard — the 2x-bytes pattern the path exists to kill):
    params, mu, nu, loss and grad_norm BIT-identical over 3 steps, while
    the compiled HLO swaps all-reduces for reduce-scatters (counted via
    the shared analyzer, same as the graphcheck zero1_rs_dp8 budget). The
    legacy GSPMD lowering (slow arm) agrees to reduction-reorder
    tolerance only — GSPMD regroups sums on its own, which is exactly why
    the exact parity gate is scatter-vs-allreduce, not scatter-vs-legacy."""
    from bert_pytorch_tpu.analysis import collective_counts

    cfg = TINY if stacked else TINY.replace(stacked_params=False)
    mesh = mesh_lib.make_mesh()  # data=8
    model = BertForPreTraining(cfg, dtype=jnp.float32)
    sample = _batch()
    init_fn = lambda r: model.init(
        r, jnp.asarray(sample["input_ids"][0]),
        jnp.asarray(sample["token_type_ids"][0]),
        jnp.asarray(sample["attention_mask"][0]))

    def make(mode):
        tx, sched = _tx()
        with mesh_lib.logical_rules():
            state, shardings = make_sharded_state(
                jax.random.PRNGKey(0), init_fn, tx, mesh=mesh, zero1=True,
                zero1_params=True)
        plan = make_zero1_plan(state.params, shardings.params, mesh,
                               gather_on_use=True,
                               reduce_scatter=mode is not None,
                               warn_skipped=False)
        assert plan is not None
        if mode is not None:
            plan = plan._replace(rs_mode=mode)
        step = build_pretrain_step(model, tx, schedule=sched,
                                   max_predictions=4, zero1=plan)
        return state, jax.jit(step, donate_argnums=(0,))

    modes = ("scatter", "allreduce") + (() if stacked else (None,))
    states, steps, counts, metrics = {}, {}, {}, {}
    batch = mesh_lib.host_to_device_batch(mesh, _batch())
    with mesh, mesh_lib.logical_rules():
        for mode in modes:
            st, fn = make(mode)
            compiled = fn.lower(st, batch, jax.random.PRNGKey(0)).compile()
            counts[mode] = collective_counts(compiled.as_text())
            states[mode], steps[mode] = st, fn
        for i in range(3):
            for mode in states:
                states[mode], m = steps[mode](states[mode], batch,
                                              jax.random.PRNGKey(i))
                metrics.setdefault(mode, []).append(
                    (float(m["loss"]), float(m["grad_norm"])))

    # the structural claim: grads leave through reduce-scatter, and the
    # all-reduces that carried them are gone — not merely renamed
    assert counts["scatter"]["reduce-scatter"] > 0, counts["scatter"]
    assert counts["allreduce"]["reduce-scatter"] == 0, counts["allreduce"]
    assert counts["scatter"]["all-reduce"] < \
        counts["allreduce"]["all-reduce"], (counts["scatter"],
                                            counts["allreduce"])
    # ...at an unchanged all-gather count (the params path is untouched)
    assert counts["scatter"]["all-gather"] == \
        counts["allreduce"]["all-gather"]

    # the value claim: same training run, bit for bit
    assert metrics["scatter"] == metrics["allreduce"]
    for what, sel in (("params", lambda s: s.params),
                      ("mu", lambda s: s.opt_state.mu),
                      ("nu", lambda s: s.opt_state.nu)):
        for a, b in zip(jax.tree.leaves(sel(states["scatter"])),
                        jax.tree.leaves(sel(states["allreduce"]))):
            np.testing.assert_array_equal(
                np.asarray(a), np.asarray(b),
                err_msg=f"{what} not bit-identical after 3 steps")
    # params still rest 1/N-sharded (the gather-on-use contract rs rides)
    n_sharded = sum(1 for leaf in jax.tree.leaves(states["scatter"].params)
                    if not leaf.sharding.is_fully_replicated)
    assert n_sharded >= 10
    if None in states:
        for a, b in zip(jax.tree.leaves(states[None].params),
                        jax.tree.leaves(states["scatter"].params)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=2e-5, atol=1e-6)


# --- fsdp gather-on-use (--fsdp_overlap, round 15) ----------------------


@pytest.mark.slow  # both arms: tier-1's 870s budget; the compiled
# collective structure stays tier-1-pinned via the graph-budget gate
@pytest.mark.parametrize(
    "stacked",
    [True,
     # the unstacked arm re-proves the same claims at per-layer gather
     # granularity — two more XLA compiles, so it rides outside tier-1's
     # wall-clock budget (same split as the graph-gate's slow full run)
     pytest.param(False, marks=pytest.mark.slow)],
    ids=["stacked", "unstacked"])
def test_fsdp_overlap_bit_identical(stacked):
    """The fsdp-axis restatement of the zero1 overlap contract: the
    BLOCKING layout (same per-leaf gather nodes fused behind one
    whole-tree barrier — FSDP-without-prefetch semantics) and the
    OVERLAP layout (independent per-leaf barriers the scheduler can
    interleave) must be the SAME training run — loss and params
    bit-identical over several steps — with the compiled all-gather
    count flat between them (the gathers change dependence structure,
    not count). Versus the no-plan program (GSPMD's implicit
    re-materialization, which may sink gathers into contracting-dim
    matmuls) the explicit layouts agree to reduction-reorder tolerance —
    pinned allclose, deliberately not bit-equal. Both encoder layouts:
    whole-(L,...)-stack gathers vs per-layer-kernel gathers."""
    from bert_pytorch_tpu.analysis import collective_counts
    from bert_pytorch_tpu.parallel.zero import make_fsdp_plan

    cfg = TINY if stacked else TINY.replace(stacked_params=False)
    mesh = mesh_lib.make_mesh({"fsdp": 8})
    model = BertForPreTraining(cfg, dtype=jnp.float32)
    tx, sched = _tx()
    sample = _batch()
    init_fn = lambda r: model.init(
        r, jnp.asarray(sample["input_ids"][0]),
        jnp.asarray(sample["token_type_ids"][0]),
        jnp.asarray(sample["attention_mask"][0]))

    def make(mode):
        with mesh_lib.logical_rules():
            state, shardings = make_sharded_state(
                jax.random.PRNGKey(0), init_fn, tx, mesh=mesh)
        plan = None
        if mode is not None:
            plan = make_fsdp_plan(state.params, shardings.params, mesh,
                                  blocking=(mode == "blocking"))
            assert plan is not None and plan.axis == "fsdp"
            assert plan.gather_on_use and \
                plan.blocking_gather == (mode == "blocking")
        step = build_pretrain_step(model, tx, schedule=sched, zero1=plan)
        return state, jax.jit(step, donate_argnums=(0,))

    states, steps, gathers = {}, {}, {}
    batch = mesh_lib.host_to_device_batch(mesh, _batch())
    # the implicit-GSPMD reference arm is compiled once, in the SLOW
    # (unstacked) variant only — the allclose claim is layout-independent
    # and every extra XLA compile is real tier-1 wall time; the tier-1
    # stacked arm pins the bit-identity + flat-gather-count core
    modes = ("blocking", "overlap") + (() if stacked else (None,))
    with mesh, mesh_lib.logical_rules():
        for mode in modes:
            st, fn = make(mode)
            compiled = fn.lower(st, batch, jax.random.PRNGKey(0)).compile()
            gathers[mode] = collective_counts(
                compiled.as_text())["all-gather"]
            states[mode], steps[mode] = st, fn
        # params genuinely rest fsdp-sharded in every mode
        n_sharded = sum(
            1 for leaf in jax.tree.leaves(states["overlap"].params)
            if not leaf.sharding.is_fully_replicated)
        assert n_sharded >= 8, f"only {n_sharded} param leaves sharded"
        for i in range(3):
            for mode in states:
                states[mode], _m = steps[mode](states[mode], batch,
                                               jax.random.PRNGKey(i))

    assert gathers["overlap"] == gathers["blocking"], (
        f"overlap changed the all-gather count: {gathers} — per-leaf "
        "barriers must re-schedule the same gathers, not multiply them")
    for a, b in zip(jax.tree.leaves(states["blocking"].params),
                    jax.tree.leaves(states["overlap"].params)):
        np.testing.assert_array_equal(
            np.asarray(a), np.asarray(b),
            err_msg="blocking vs overlap not bit-identical after 3 steps")
    if None in states:
        # explicit-gather vs implicit-GSPMD: reduction-reorder tolerance
        for a, b in zip(jax.tree.leaves(states[None].params),
                        jax.tree.leaves(states["overlap"].params)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=2e-5, atol=1e-6)
    # ...and the overlap params still rest sharded after stepping
    n_sharded = sum(1 for leaf in jax.tree.leaves(states["overlap"].params)
                    if not leaf.sharding.is_fully_replicated)
    assert n_sharded >= 8


def test_coalesced_norms_bit_identical():
    """--coalesce_reductions on the plain ZeRO-1 step: LAMB's per-tensor
    trust norms, the pre-normalization global norm and the logged
    grad_norm route through bucketed reductions (parallel/coalesce.py) —
    params, mu, nu and the loss trajectory BIT-identical to the
    per-tensor program (same local reduce, same per-element cross-device
    sum)."""
    from bert_pytorch_tpu.parallel.coalesce import NormReducer

    mesh = mesh_lib.make_mesh()  # data=8
    model = BertForPreTraining(TINY, dtype=jnp.float32)
    sample = _batch()
    init_fn = lambda r: model.init(
        r, jnp.asarray(sample["input_ids"][0]),
        jnp.asarray(sample["token_type_ids"][0]),
        jnp.asarray(sample["attention_mask"][0]))

    def make(coalesce):
        tx, sched = _tx()
        with mesh_lib.logical_rules():
            state, shardings = make_sharded_state(
                jax.random.PRNGKey(0), init_fn, tx, mesh=mesh, zero1=True)
        plan = make_zero1_plan(state.params, shardings.params, mesh,
                               warn_skipped=False)
        reducer = None
        if coalesce:
            from bert_pytorch_tpu.optim.lamb import (
                default_trust_batch_axes, default_weight_decay_mask, lamb)

            reducer = NormReducer(plan.grad_shardings, mesh)
            tx = lamb(sched, weight_decay=0.01,
                      weight_decay_mask=default_weight_decay_mask,
                      trust_batch_axes=default_trust_batch_axes,
                      norm_reducer=reducer)
        step = build_pretrain_step(model, tx, schedule=sched, zero1=plan,
                                   norm_reducer=reducer)
        return state, jax.jit(step, donate_argnums=(0,)), reducer

    s_base, step_base, _ = make(False)
    s_co, step_co, reducer = make(True)
    batch = mesh_lib.host_to_device_batch(mesh, _batch())
    # (the compiled all-reduce REDUCTION is enforced elsewhere — the
    # checked-in kfac_zero1_dp8_bucketed budget and the slow kfac parity
    # test count it; re-compiling both programs here just for the count
    # would double this test's tier-1 wall time)
    with mesh, mesh_lib.logical_rules():
        for i in range(3):
            s_base, m_b = step_base(s_base, batch, jax.random.PRNGKey(i))
            s_co, m_c = step_co(s_co, batch, jax.random.PRNGKey(i))
            assert float(m_b["loss"]) == float(m_c["loss"]), f"step {i}"
            assert float(m_b["grad_norm"]) == float(m_c["grad_norm"])
    for what, ta, tb in ((("params"), s_base.params, s_co.params),
                         ("mu", s_base.opt_state.mu, s_co.opt_state.mu),
                         ("nu", s_base.opt_state.nu, s_co.opt_state.nu)):
        for a, b in zip(jax.tree.leaves(ta), jax.tree.leaves(tb)):
            np.testing.assert_array_equal(
                np.asarray(a), np.asarray(b),
                err_msg=f"{what} not bit-identical with coalesced norms")
    # the deterministic bucket assignment is recorded for the run header
    summary = reducer.summary()
    assert summary is not None and summary["groups"], summary
    assert summary["groups"][0]["axes"] == ["data"]


def test_zero1_replicated_leaf_warning_and_plan_field(capsys):
    """The round-15 silent-skip bugfix: leaves the appended-axis
    derivation leaves on their base layout are (a) recorded on the plan
    (run_pretraining exports the count as bert_zero1_replicated_leaves)
    and (b) named in ONE counted warning — a layout regression can no
    longer hide in a quiet fallback."""
    mesh = mesh_lib.make_mesh()  # data=8
    # one shardable leaf, one prime-sized leaf the derivation must skip
    from jax.sharding import NamedSharding

    params = {"big": jnp.zeros((64, 16)), "odd": jnp.zeros((7, 13))}
    base = {"big": NamedSharding(mesh, P(None, None)),
            "odd": NamedSharding(mesh, P(None, None))}
    plan = make_zero1_plan(params, base, mesh)
    err = capsys.readouterr().err
    assert plan is not None
    assert len(plan.replicated_leaves) == 1
    assert "odd" in plan.replicated_leaves[0]
    assert "[7, 13]" in plan.replicated_leaves[0]
    assert "WARNING: zero1[data]: 1 param leaves" in err
    assert "odd" in err
    # warn_skipped=False silences the print but keeps the record
    plan2 = make_zero1_plan(params, base, mesh, warn_skipped=False)
    assert capsys.readouterr().err == ""
    assert plan2.replicated_leaves == plan.replicated_leaves


# --- the promoted zero-reshard gate (tier-1) ----------------------------


def test_no_involuntary_reshard_on_2x2_mesh(capfd):
    """The dryrun's `spmd_involuntary_reshard_warnings=0` gate as a pytest:
    compile (don't just trace) the production train step — gathered MLM
    head, NSP, ZeRO-1 sharded LAMB — under a 2x2 (data x model) CPU mesh
    and assert XLA's SPMD partitioner emitted zero 'Involuntary full
    rematerialization' warnings, so sharding regressions fail CI instead of
    only the bench driver's MULTICHIP run.

    The mesh is data x model (DP+TP), the combination where every
    annotated tensor has a consistent home; data x fsdp at this tiny size
    is a known pre-existing GSPMD tension (fsdp serves both the batch axes
    and the vocab/embed param axes, so (B, .., V)-shaped loss tensors have
    two irreconcilable preferred layouts on a 4-device mesh) — the
    production 4-axis mesh {data,fsdp,model} stays gated at zero by the
    driver's dryrun, which this test complements, not replaces."""
    import __graft_entry__ as graft

    # the gate greps for a literal XLA log message; keep the canary that
    # the installed XLA still contains those bytes (fail-open protection)
    graft._assert_reshard_gate_alive()

    mesh = mesh_lib.make_mesh({"data": 2, "model": 2},
                              devices=jax.devices()[:4])
    state, plan, _ = _setup(mesh, zero1=True)
    assert plan is not None
    model = BertForPreTraining(TINY, dtype=jnp.float32)
    tx, sched = _tx()
    step_fn = build_pretrain_step(model, tx, schedule=sched, zero1=plan,
                                  max_predictions=4)
    batch = mesh_lib.host_to_device_batch(mesh, _batch())
    capfd.readouterr()  # drop anything buffered before the compile
    with mesh, mesh_lib.logical_rules():
        state, metrics = jax.jit(step_fn, donate_argnums=(0,))(
            state, batch, jax.random.PRNGKey(0))
        assert np.isfinite(float(metrics["loss"]))
    err = capfd.readouterr().err
    n = err.count(graft._RESHARD_WARNING)
    assert n == 0, (
        f"{n} involuntary-reshard warning(s) compiling the 2x2-mesh ZeRO-1 "
        f"step:\n{err[-2000:]}")


# --- overlap flag pack + noise filter -----------------------------------


def test_overlap_flag_pack_env_semantics():
    from bert_pytorch_tpu.parallel.xla_flags import (OVERLAP_FLAG_PACK,
                                                     apply_overlap_flags,
                                                     overlap_flags_active)

    env = {}
    added = apply_overlap_flags(env)
    assert added == list(OVERLAP_FLAG_PACK)
    assert overlap_flags_active(env)
    # idempotent
    assert apply_overlap_flags(env) == []
    # an operator's explicit polarity wins over the pack
    env2 = {"LIBTPU_INIT_ARGS":
            "--xla_tpu_enable_async_collective_fusion=false"}
    added2 = apply_overlap_flags(env2)
    assert "--xla_tpu_enable_async_collective_fusion=true" not in added2
    assert ("--xla_tpu_enable_async_collective_fusion=false"
            in env2["LIBTPU_INIT_ARGS"])
    assert overlap_flags_active(env2)


def test_filter_known_noise_keeps_signal():
    import __graft_entry__ as graft

    spam = ("E0803 02:23:37 25287 cpu_aot_loader.cc:210] Loading XLA:CPU "
            "AOT result. Target machine feature +prefer-no-gather ...\n")
    signal_line = "dryrun_multichip spmd_involuntary_reshard_warnings=0\n"
    warn = f"blah {graft._RESHARD_WARNING} of op %foo\n"
    out = graft.filter_known_noise(spam * 40 + warn + signal_line)
    assert "cpu_aot_loader.cc" not in out
    assert signal_line in out
    assert warn in out  # the gate's warning text is NEVER filtered
    assert "filtered 40 known-noise" in out
    # clean streams pass through untouched
    assert graft.filter_known_noise(signal_line) == signal_line
