"""Distillation factory tests (training/distill.py, run_distill.py,
the DISTILL artifact chain, and the debug_taps layer contract).

The acceptance pins:

- packed distillation loss — KD + hard + layer-matched tap terms with
  width-bridging projections — equals the same examples
  one-example-per-row BIT-for-bit (the PR 13 standard, extended to the
  teacher-in-the-graph loss);
- the teacher runs under stop_gradient: student gradients with the
  teacher forward IN the graph are bit-identical to gradients against
  precomputed teacher logits (tree-exact);
- `debug_taps` sows keep their names and shapes under BOTH encoder
  layouts (stacked scan and unstacked) — the contract the distillation
  layer map rides;
- the strict serving restore names expected-vs-found encoder depth and
  points at run_distill.py's student model_config.json on a
  student-checkpoint-under-teacher-config mismatch;
- the jax-free artifact chain: loadtest --assemble --kind distill
  computes accuracy deltas + vs_teacher_per_chip, perfboard indexes the
  artifact and `--check_distill` trips on a student below the accuracy
  floor (and passes a student that beats its teacher).
"""

import json
import os
import sys

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from bert_pytorch_tpu.config import (  # noqa: E402
    BertConfig, is_student_preset, student_config)
from tests.test_finetune_packing import (  # noqa: E402
    _examples, _pack_both)


def _teacher_config(**kw):
    base = dict(
        vocab_size=64, hidden_size=32, num_hidden_layers=2,
        num_attention_heads=4, intermediate_size=64,
        max_position_embeddings=64, hidden_dropout_prob=0.0,
        attention_probs_dropout_prob=0.0, fused_ops=False,
        attention_impl="xla", debug_taps=True)
    base.update(kw)
    return BertConfig(**base)


# -- student presets ----------------------------------------------------------


def test_student_presets():
    teacher = _teacher_config(hidden_size=768, num_hidden_layers=12,
                              num_attention_heads=12,
                              intermediate_size=3072)
    s6 = student_config("student_6l_768", teacher)
    assert (s6.num_hidden_layers, s6.hidden_size,
            s6.num_attention_heads, s6.intermediate_size) \
        == (6, 768, 12, 3072)
    s4 = student_config("student_4l_512", teacher)
    assert (s4.num_hidden_layers, s4.hidden_size,
            s4.num_attention_heads, s4.intermediate_size) \
        == (4, 512, 8, 2048)
    # everything not depth/width related is inherited from the teacher
    assert s4.vocab_size == teacher.vocab_size
    assert s4.max_position_embeddings == teacher.max_position_embeddings
    # head count divides the hidden size even for odd widths
    s = student_config("student_2l_100", teacher)
    assert s.hidden_size % s.num_attention_heads == 0
    assert is_student_preset("student_6l_768")
    assert not is_student_preset("bert_base")
    with pytest.raises(ValueError, match="student_<L>l_<H>"):
        student_config("student_768", teacher)


def test_layer_map():
    from bert_pytorch_tpu.training import distill

    assert distill.default_layer_map(6, 12) == (
        (0, 1), (1, 3), (2, 5), (3, 7), (4, 9), (5, 11))
    assert distill.default_layer_map(2, 2) == ((0, 0), (1, 1))
    assert distill.parse_layer_map("0:0,1:11", 2, 12) == ((0, 0), (1, 11))
    assert distill.parse_layer_map(None, 6, 12) \
        == distill.default_layer_map(6, 12)
    with pytest.raises(ValueError, match="out of range"):
        distill.parse_layer_map("0:12", 2, 12)
    with pytest.raises(ValueError, match="student:teacher"):
        distill.parse_layer_map("0-3", 2, 12)


# -- debug_taps layout contract (the layer map's substrate) -------------------


@pytest.mark.parametrize("stacked", [True, False],
                         ids=["stacked", "unstacked"])
def test_debug_taps_names_and_shapes_both_layouts(stacked):
    """Pin the sow names and shapes the distillation tap losses consume,
    under both encoder layouts: per layer {attention_out, mlp_out} of
    (B, S, H), plus the trunk-level embeddings_out/pooled sows."""
    import jax
    import jax.numpy as jnp

    from bert_pytorch_tpu.models import BertForSequenceClassification
    from bert_pytorch_tpu.training.distill import layer_taps

    cfg = _teacher_config(stacked_params=stacked)
    model = BertForSequenceClassification(cfg, num_labels=2,
                                          max_segments=4,
                                          dtype=jnp.float32)
    x = jnp.zeros((2, 16), jnp.int32)
    variables = model.init(jax.random.PRNGKey(0), x, x, x)
    _, vs = model.apply({"params": variables["params"]}, x, x, x,
                        deterministic=True, mutable=["debug_taps"])
    taps = vs["debug_taps"]["bert"]

    def leaf(v):
        return v[0] if isinstance(v, (tuple, list)) else v

    assert leaf(taps["embeddings_out"]).shape == (2, 16, 32)
    assert leaf(taps["pooled"]).shape == (2, 32)
    enc = taps["encoder"]
    if stacked:
        per = enc["layers"]["layer"]
        assert set(per) == {"attention_out", "mlp_out"}
        for v in per.values():
            assert leaf(v).shape == (2, 2, 16, 32)  # (L, B, S, H)
    else:
        assert set(enc) == {"layer_0", "layer_1"}
        for layer in enc.values():
            assert set(layer) == {"attention_out", "mlp_out"}
            for v in layer.values():
                assert leaf(v).shape == (2, 16, 32)

    layers = layer_taps(vs["debug_taps"], cfg)
    assert len(layers) == cfg.num_hidden_layers
    for lt in layers:
        assert set(lt) == {"attention_out", "mlp_out"}
        assert lt["attention_out"].shape == (2, 16, 32)
        assert lt["mlp_out"].shape == (2, 16, 32)


def test_debug_taps_cross_layout_parity():
    """The same weights produce the same per-layer tap values under both
    layouts (convert_tree_layout), so a layer map trained against one
    layout means the same thing against the other."""
    import jax
    import jax.numpy as jnp

    from bert_pytorch_tpu.models import BertForSequenceClassification
    from bert_pytorch_tpu.models.pretrained import convert_tree_layout
    from bert_pytorch_tpu.training.distill import layer_taps

    cfg_s = _teacher_config(stacked_params=True)
    cfg_u = cfg_s.replace(stacked_params=False)
    m_s = BertForSequenceClassification(cfg_s, num_labels=2,
                                        max_segments=4, dtype=jnp.float32)
    m_u = BertForSequenceClassification(cfg_u, num_labels=2,
                                        max_segments=4, dtype=jnp.float32)
    x = jnp.zeros((2, 16), jnp.int32)
    ids = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 5, 64)
    mask = jnp.ones((2, 16), jnp.int32)
    p_s = m_s.init(jax.random.PRNGKey(0), x, x, x)["params"]
    p_u = convert_tree_layout(p_s, stacked=False)
    _, vs_s = m_s.apply({"params": p_s}, ids, x, mask,
                        deterministic=True, mutable=["debug_taps"])
    _, vs_u = m_u.apply({"params": p_u}, ids, x, mask,
                        deterministic=True, mutable=["debug_taps"])
    for ls, lu in zip(layer_taps(vs_s["debug_taps"], cfg_s),
                      layer_taps(vs_u["debug_taps"], cfg_u)):
        for k in ("attention_out", "mlp_out"):
            np.testing.assert_allclose(np.asarray(ls[k]),
                                       np.asarray(lu[k]),
                                       rtol=1e-5, atol=1e-5)


# -- the distillation loss: packed bit-equality + stop_gradient ---------------


def _distill_setup(alpha_hidden=1.0, alpha_attn=0.5):
    """(student_model, teacher_model, student_params+proj,
    teacher_params, dcfg) on a width-differing pair so the projections
    are exercised."""
    import jax
    import jax.numpy as jnp

    from bert_pytorch_tpu.models import BertForSequenceClassification
    from bert_pytorch_tpu.training import distill

    t_cfg = _teacher_config()
    s_cfg = student_config("student_1l_16", t_cfg)
    dcfg = distill.DistillConfig(
        temperature=2.0, alpha_kd=1.0, alpha_ce=0.5,
        alpha_hidden=alpha_hidden, alpha_attn=alpha_attn,
        layer_map=distill.default_layer_map(1, 2), max_segments=4)
    teacher = BertForSequenceClassification(t_cfg, num_labels=2,
                                            max_segments=4,
                                            dtype=jnp.float32)
    student = BertForSequenceClassification(s_cfg, num_labels=2,
                                            max_segments=4,
                                            dtype=jnp.float32)
    x = jnp.zeros((1, 48), jnp.int32)
    t_params = teacher.init(jax.random.PRNGKey(0), x, x, x)["params"]
    s_params = dict(student.init(jax.random.PRNGKey(1), x, x, x)["params"])
    proj = distill.init_projections(jax.random.PRNGKey(2), dcfg,
                                    s_cfg, t_cfg)
    if proj:
        s_params["distill_proj"] = proj
    return student, teacher, s_params, t_params, dcfg


def test_packed_distill_loss_bit_equal():
    """The tentpole pin: the full distillation mix (KD + hard + both tap
    terms through a width-bridging projection) on a multi-segment packed
    batch equals the one-example-per-row baseline bit-for-bit."""
    import jax

    from bert_pytorch_tpu.tasks.classify import pack_labels
    from bert_pytorch_tpu.training import distill

    student, teacher, s_params, t_params, dcfg = _distill_setup()
    proj = distill.init_projections(jax.random.PRNGKey(2), dcfg,
                                    student.config, teacher.config)
    assert proj, "fixture must exercise the projection path"

    arrays, _ = _examples()
    arrays["labels"] = np.array([0, 1, 1, 0, 1], np.int32)
    multi, single, _ = _pack_both(arrays, pack_labels)

    loss_fn = distill.make_distill_loss_builder(
        teacher_model=teacher, teacher_params=t_params, dcfg=dcfg,
        output_kind="segment", packed=True,
        label_ignore={"labels": -1})(student)
    rng = jax.random.PRNGKey(3)
    l_multi, _ = loss_fn(s_params, multi, rng, deterministic=True)
    l_single, _ = loss_fn(s_params, single, rng, deterministic=True)
    assert float(l_multi) == float(l_single)  # BIT-equal
    assert np.isfinite(float(l_multi)) and float(l_multi) > 0.0


def test_teacher_stop_gradient_precomputed_equivalence():
    """Teacher-under-stop_gradient proven: student grads with the
    teacher forward in the SAME graph are bit-identical (tree-exact) to
    grads against precomputed teacher logits — i.e. the teacher
    contributes values, never gradients."""
    import jax
    import jax.numpy as jnp

    from bert_pytorch_tpu.tasks.classify import pack_labels
    from bert_pytorch_tpu.training import distill

    student, teacher, s_params, t_params, dcfg = _distill_setup(
        alpha_hidden=0.0, alpha_attn=0.0)  # tap-free: logits-only KD
    s_params.pop("distill_proj", None)

    arrays, _ = _examples()
    arrays["labels"] = np.array([0, 1, 1, 0, 1], np.int32)
    multi, _, _ = _pack_both(arrays, pack_labels)

    loss_fn = distill.make_distill_loss_builder(
        teacher_model=teacher, teacher_params=t_params, dcfg=dcfg,
        output_kind="segment", packed=True,
        label_ignore={"labels": -1})(student)
    rng = jax.random.PRNGKey(3)

    def loss(params, batch):
        return loss_fn(params, batch, rng, deterministic=True)[0]

    g_ingraph = jax.grad(loss)(s_params, multi)

    t_logits = teacher.apply(
        {"params": t_params}, jnp.asarray(multi["input_ids"]),
        jnp.asarray(multi["token_type_ids"]),
        jnp.asarray(multi["attention_mask"]), deterministic=True,
        position_ids=jnp.asarray(multi["position_ids"]),
        segment_ids=jnp.asarray(multi["segment_ids"]))
    pre = dict(multi)
    pre["teacher_logits"] = t_logits
    g_pre = jax.grad(loss)(s_params, pre)

    flat_a = jax.tree_util.tree_leaves_with_path(g_ingraph)
    flat_b = jax.tree_util.tree_leaves_with_path(g_pre)
    assert len(flat_a) == len(flat_b)
    nonzero = 0.0
    for (pa, a), (pb, b) in zip(flat_a, flat_b):
        assert pa == pb
        assert np.array_equal(np.asarray(a), np.asarray(b)), pa
        nonzero += float(jnp.abs(a).sum())
    assert nonzero > 0.0, "degenerate fixture: all-zero gradients"


# -- strict restore: depth-mismatch error (satellite 1) -----------------------


def test_strict_merge_depth_mismatch_hint():
    import jax
    import jax.numpy as jnp

    from bert_pytorch_tpu.models import BertForSequenceClassification
    from bert_pytorch_tpu.serving.engine import _strict_merge
    from bert_pytorch_tpu.training.state import unbox

    def params_for(layers, stacked):
        cfg = _teacher_config(num_hidden_layers=layers, debug_taps=False,
                              stacked_params=stacked)
        m = BertForSequenceClassification(cfg, num_labels=2,
                                          max_segments=4,
                                          dtype=jnp.float32)
        x = jnp.zeros((1, 16), jnp.int32)
        return unbox(m.init(jax.random.PRNGKey(0), x, x, x)["params"])

    for stacked in (True, False):
        teacher_tree = params_for(2, stacked)
        student_tree = params_for(1, stacked)
        with pytest.raises(ValueError) as ei:
            _strict_merge(teacher_tree, student_tree)
        msg = str(ei.value)
        assert "expects 2 encoder layer(s)" in msg, msg
        assert "carries 1" in msg, msg
        assert "--student" in msg and "model_config.json" in msg, msg
        if stacked:
            # reverse direction: under the stacked layout the scanned
            # leaves' leading axis mis-shapes, and the error names the
            # reverse counts. (Unstacked, a DEEPER checkpoint restores
            # into a shallower model fine — every model leaf exists and
            # extra checkpoint subtrees are ignored by contract.)
            with pytest.raises(ValueError,
                               match=r"expects 1 encoder layer"):
                _strict_merge(student_tree, teacher_tree)


# -- jax-free artifact chain: loadtest assemble + perfboard gate --------------


def _mode_doc(label, tag, dtype, rps, n_chips=1):
    return {"schema_version": 1, "kind": "serve_mode", "label": label,
            "time_unix": 5.0,
            "rates": {"10": {"p50_ms": 4.0, "p95_ms": 8.0, "p99_ms": 20.0,
                             "req_per_sec": rps,
                             "real_tokens_per_sec": 900.0,
                             "batch_occupancy": 0.8, "n": 300,
                             "n_2xx": 300, "n_err": 0,
                             "duration_s": 30.0,
                             "cost_per_1k_tokens": 0.01}},
            "meta": {"model_tag": tag, "dtype": dtype,
                     "n_chips": n_chips},
            "saturation": {"req_per_sec": rps, "at_rate": 10.0,
                           "p99_ms": 20.0, "cost_per_1k_tokens": 0.01}}


def _write_distill_artifact(tmp_path, accuracies):
    from tools.loadtest import assemble, validate_serve

    paths = []
    legs = [("teacher_f32", "teacher", "f32", 10.0),
            ("s6_f32", "student_6l_768", "f32", 21.0),
            ("s6_int8", "student_6l_768", "int8", 30.0),
            ("s4_f32", "student_4l_512", "f32", 40.0, 2)]
    for leg in legs:
        p = tmp_path / f"{leg[0]}.json"
        p.write_text(json.dumps(_mode_doc(*leg)))
        paths.append(str(p))
    doc = assemble(paths, kind="distill", accuracies=accuracies)
    assert validate_serve(doc) == []
    out = tmp_path / "DISTILL_r99.json"
    out.write_text(json.dumps(doc, sort_keys=True))
    return doc, out


def test_loadtest_distill_assemble(tmp_path):
    doc, _ = _write_distill_artifact(
        tmp_path, {"teacher": 0.92, "student_6l_768": 0.90,
                   "student_4l_512": 0.93})
    assert doc["kind"] == "distill"
    m = doc["modes"]
    assert m["teacher_f32"]["accuracy"] == 0.92
    assert m["teacher_f32"]["accuracy_delta"] == 0.0
    assert m["s6_f32"]["accuracy_delta"] == pytest.approx(0.02)
    # student beating the teacher yields a NEGATIVE delta
    assert m["s4_f32"]["accuracy_delta"] == pytest.approx(-0.01)
    # per-chip ratio vs the same-dtype teacher leg; int8 student falls
    # back to the f32 teacher (only teacher available); s4 runs on 2
    # chips so its per-chip ratio halves
    assert m["s6_f32"]["saturation"]["vs_teacher_per_chip"] == 2.1
    assert m["s6_int8"]["saturation"]["vs_teacher_per_chip"] == 3.0
    assert m["s4_f32"]["saturation"]["vs_teacher_per_chip"] == 2.0
    assert "vs_teacher_per_chip" not in m["teacher_f32"]["saturation"]


def test_perfboard_distill_index_and_gate(tmp_path):
    from tools import perfboard

    _, artifact = _write_distill_artifact(
        tmp_path, {"teacher": 0.92, "student_6l_768": 0.90,
                   "student_4l_512": 0.93})
    kind, metrics, _ = perfboard.extract(str(artifact))
    assert kind == "distill"
    assert metrics["s6_f32.accuracy_delta"] == pytest.approx(0.02)
    assert metrics["s6_f32.saturation.vs_teacher_per_chip"] == 2.1
    assert metrics["teacher_f32.accuracy"] == 0.92
    # gate directions: delta lower-better, ratio + accuracy higher-better
    assert perfboard.metric_direction("x.accuracy_delta") == "lower"
    assert perfboard.metric_direction(
        "x.saturation.vs_teacher_per_chip") == "higher"
    assert perfboard.metric_direction("x.accuracy") == "higher"

    # index: the distill table lands in RUNS.md with model tags
    records = perfboard.index_records(str(tmp_path))
    distills = [r for r in records if r["kind"] == "distill"]
    assert len(distills) == 1 and distills[0]["measured"]
    md = perfboard.render_markdown(records)
    assert "## Distillation" in md
    assert "student_6l_768" in md and "student_4l_512" in md

    # the accuracy floor: 0.02 passes at 0.05, trips at 0.01; the
    # teacher-beating student never trips; rc via the CLI path
    assert perfboard.main(["--check_distill", str(artifact),
                           "--distill_max_delta", "0.05"]) == 0
    assert perfboard.main(["--check_distill", str(artifact),
                           "--distill_max_delta", "0.01"]) == 1
    failures, notes = perfboard.check_distill(str(artifact), 0.01)
    assert [f for f in failures if "s6" in f]
    assert not [f for f in failures if "s4_f32" in f]
    # an unmeasured student fails loudly
    doc2, art2 = _write_distill_artifact(tmp_path, {"teacher": 0.92})
    failures, _ = perfboard.check_distill(str(art2), 0.5)
    assert failures and "no accuracy_delta" in " ".join(failures)
