"""Pretrained-weight import tests: TF-checkpoint conversion parity (against
the independent HuggingFace TF loader + torch BERT), vocab padding, archive /
URL loading through the cache (reference src/modeling.py:58-116,659-742 and
src/file_utils.py)."""

import json
import os
import zipfile

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from bert_pytorch_tpu.config import BertConfig
from bert_pytorch_tpu.file_utils import cached_path
from bert_pytorch_tpu.models import (
    BertForPreTraining,
    convert_tf_to_flax,
    from_pretrained,
)
from bert_pytorch_tpu.training.state import unbox

E, H, L, F, V, MP = 32, 4, 2, 64, 100, 64

CFG = BertConfig(
    vocab_size=V, hidden_size=E, num_hidden_layers=L,
    num_attention_heads=H, intermediate_size=F,
    max_position_embeddings=MP, next_sentence=True,
    hidden_act="gelu", hidden_dropout_prob=0.0,
    attention_probs_dropout_prob=0.0,
    dtype="float32", fused_ops=False, attention_impl="xla",
)


def make_tf_vars(seed=0):
    rng = np.random.RandomState(seed)

    def rnd(*s):
        return rng.randn(*s).astype(np.float32) * 0.05

    tf_vars = {
        "bert/embeddings/word_embeddings": rnd(V, E),
        "bert/embeddings/position_embeddings": rnd(MP, E),
        "bert/embeddings/token_type_embeddings": rnd(2, E),
        "bert/embeddings/LayerNorm/gamma": 1 + rnd(E),
        "bert/embeddings/LayerNorm/beta": rnd(E),
        "bert/pooler/dense/kernel": rnd(E, E),
        "bert/pooler/dense/bias": rnd(E),
        "cls/predictions/transform/dense/kernel": rnd(E, E),
        "cls/predictions/transform/dense/bias": rnd(E),
        "cls/predictions/transform/LayerNorm/gamma": 1 + rnd(E),
        "cls/predictions/transform/LayerNorm/beta": rnd(E),
        "cls/predictions/output_bias": rnd(V),
        "cls/seq_relationship/output_weights": rnd(2, E),
        "cls/seq_relationship/output_bias": rnd(2),
        # optimizer slots the loader must skip
        "global_step": np.array(7, np.int64),
    }
    for i in range(L):
        p = f"bert/encoder/layer_{i}"
        for n in ("query", "key", "value"):
            tf_vars[f"{p}/attention/self/{n}/kernel"] = rnd(E, E)
            tf_vars[f"{p}/attention/self/{n}/bias"] = rnd(E)
        tf_vars[f"{p}/attention/output/dense/kernel"] = rnd(E, E)
        tf_vars[f"{p}/attention/output/dense/bias"] = rnd(E)
        tf_vars[f"{p}/attention/output/LayerNorm/gamma"] = 1 + rnd(E)
        tf_vars[f"{p}/attention/output/LayerNorm/beta"] = rnd(E)
        tf_vars[f"{p}/intermediate/dense/kernel"] = rnd(E, F)
        tf_vars[f"{p}/intermediate/dense/bias"] = rnd(F)
        tf_vars[f"{p}/output/dense/kernel"] = rnd(F, E)
        tf_vars[f"{p}/output/dense/bias"] = rnd(E)
        tf_vars[f"{p}/output/LayerNorm/gamma"] = 1 + rnd(E)
        tf_vars[f"{p}/output/LayerNorm/beta"] = rnd(E)
    return tf_vars


@pytest.fixture(scope="module")
def tf_vars():
    return make_tf_vars()


@pytest.fixture(scope="module")
def ckpt_dir(tf_vars, tmp_path_factory):
    """A directory shaped like an extracted Google release: bert_config.json
    + vocab.txt + bert_model.ckpt.* written through real TF."""
    tf = pytest.importorskip("tensorflow")
    tf1 = tf.compat.v1
    d = tmp_path_factory.mktemp("google_release")
    with tf.Graph().as_default():
        for name, arr in tf_vars.items():
            tf1.Variable(initial_value=arr, name=name)
        saver = tf1.train.Saver()
        with tf1.Session() as sess:
            sess.run(tf1.global_variables_initializer())
            saver.save(sess, os.path.join(str(d), "bert_model.ckpt"),
                       write_meta_graph=False)
    cfg = dict(vocab_size=V, hidden_size=E, num_hidden_layers=L,
               num_attention_heads=H, intermediate_size=F,
               max_position_embeddings=MP, type_vocab_size=2,
               hidden_act="gelu", hidden_dropout_prob=0.0,
               attention_probs_dropout_prob=0.0, initializer_range=0.02)
    (d / "bert_config.json").write_text(json.dumps(cfg))
    (d / "vocab.txt").write_text(
        "\n".join(["[PAD]", "[UNK]", "[CLS]", "[SEP]", "[MASK]"]
                  + [f"tok{i}" for i in range(V - 5)]))
    return str(d)


def _inputs(seed=1):
    rng = np.random.RandomState(seed)
    ids = rng.randint(0, V, (2, 12)).astype(np.int32)
    types = rng.randint(0, 2, (2, 12)).astype(np.int32)
    mask = np.ones((2, 12), np.int32)
    return ids, types, mask


def test_convert_tree_matches_model_init(tf_vars):
    params = convert_tf_to_flax(tf_vars, CFG)
    model = BertForPreTraining(CFG, dtype=jnp.float32)
    ids, types, mask = _inputs()
    want = unbox(model.init(jax.random.PRNGKey(0), jnp.asarray(ids),
                            jnp.asarray(types), jnp.asarray(mask))["params"])
    assert (jax.tree_util.tree_structure(params)
            == jax.tree_util.tree_structure(want))
    for (pw, w), (pg, g) in zip(
            jax.tree_util.tree_flatten_with_path(want)[0],
            jax.tree_util.tree_flatten_with_path(params)[0]):
        assert w.shape == g.shape, (jax.tree_util.keystr(pw), w.shape, g.shape)
    # spot-check the fused-QKV mapping: slot 0 is the query projection
    qkv = params["bert"]["encoder"]["layers"]["layer"]["attention"]["qkv"]
    np.testing.assert_array_equal(
        qkv["kernel"][0][:, 0].reshape(E, E),
        tf_vars["bert/encoder/layer_0/attention/self/query/kernel"])
    # NSP head: TF (2, E) output_weights transposed into flax (E, 2)
    np.testing.assert_array_equal(
        params["cls_seq_relationship"]["kernel"],
        tf_vars["cls/seq_relationship/output_weights"].T)


def test_forward_parity_with_hf_tf_loader(ckpt_dir):
    """Strongest check: our converted model's forward must match torch BERT
    loaded from the SAME TF checkpoint by transformers' independent
    load_tf_weights_in_bert implementation."""
    torch = pytest.importorskip("torch")
    transformers = pytest.importorskip("transformers")
    from transformers.models.bert.modeling_bert import (
        BertForPreTraining as HFBertForPreTraining, load_tf_weights_in_bert)

    config, params = from_pretrained(ckpt_dir, next_sentence=True)
    config = config.replace(dtype="float32", fused_ops=False,
                            attention_impl="xla", hidden_dropout_prob=0.0,
                            attention_probs_dropout_prob=0.0)
    model = BertForPreTraining(config, dtype=jnp.float32)
    ids, types, mask = _inputs()
    mlm, nsp = model.apply({"params": params}, jnp.asarray(ids),
                           jnp.asarray(types), jnp.asarray(mask),
                           deterministic=True)

    hf_cfg = transformers.BertConfig(
        vocab_size=V, hidden_size=E, num_hidden_layers=L,
        num_attention_heads=H, intermediate_size=F,
        max_position_embeddings=MP, type_vocab_size=2, hidden_act="gelu",
        hidden_dropout_prob=0.0, attention_probs_dropout_prob=0.0,
        layer_norm_eps=1e-12)
    hf = HFBertForPreTraining(hf_cfg)
    load_tf_weights_in_bert(hf, hf_cfg,
                            os.path.join(ckpt_dir, "bert_model.ckpt"))
    hf.eval()
    with torch.no_grad():
        out = hf(input_ids=torch.tensor(ids.astype(np.int64)),
                 token_type_ids=torch.tensor(types.astype(np.int64)),
                 attention_mask=torch.tensor(mask.astype(np.int64)))
    np.testing.assert_allclose(np.asarray(mlm),
                               out.prediction_logits.numpy(),
                               rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(np.asarray(nsp),
                               out.seq_relationship_logits.numpy(),
                               rtol=1e-4, atol=1e-5)


def tf_vars_to_torch_state(tf_vars):
    """Re-lay make_tf_vars' variables as the torch state_dict the reference
    saves (src/modeling.py module naming): Linear kernels transpose to
    (out, in), LayerNorm gamma/beta become weight/bias, layer_{i} becomes
    layer.{i}, and the head specials get their torch names."""
    state = {}
    for name, arr in tf_vars.items():
        if name == "global_step":
            continue
        if name == "cls/predictions/output_bias":
            state["cls.predictions.bias"] = arr
            continue
        if name == "cls/seq_relationship/output_weights":
            state["cls.seq_relationship.weight"] = arr  # (2, E) both sides
            continue
        if name == "cls/seq_relationship/output_bias":
            state["cls.seq_relationship.bias"] = arr
            continue
        parts = []
        for p in name.split("/"):
            if p.startswith("layer_") and p[len("layer_"):].isdigit():
                parts += ["layer", p[len("layer_"):]]
            else:
                parts.append(p)
        if parts[-1] == "gamma":
            parts[-1] = "weight"
        elif parts[-1] == "beta":
            parts[-1] = "bias"
        elif parts[-1] == "kernel":
            parts[-1] = "weight"
            arr = arr.T
        elif parts[-1].endswith("_embeddings"):
            parts.append("weight")
        state[".".join(parts)] = arr
    return state


def test_torch_converter_matches_tf_converter(tf_vars):
    """convert_torch_to_flax on the torch re-layout of the same weights must
    produce the exact tree convert_tf_to_flax produces."""
    from bert_pytorch_tpu.models.pretrained import convert_torch_to_flax

    state = tf_vars_to_torch_state(tf_vars)
    # the reference additionally stores the tied MLM decoder kernel; the
    # converter must drop it (models/bert.py re-ties at apply time)
    state["cls.predictions.decoder.weight"] = (
        tf_vars["bert/embeddings/word_embeddings"])
    got = convert_torch_to_flax(state, CFG)
    want = convert_tf_to_flax(tf_vars, CFG)
    assert (jax.tree_util.tree_structure(got)
            == jax.tree_util.tree_structure(want))
    for (pw, w), (_, g) in zip(
            jax.tree_util.tree_flatten_with_path(want)[0],
            jax.tree_util.tree_flatten_with_path(got)[0]):
        np.testing.assert_array_equal(w, g, err_msg=jax.tree_util.keystr(pw))


def test_from_pretrained_torch_checkpoint(tf_vars, tmp_path):
    """A reference pretraining checkpoint (ckpt_*.pt: {'model': state_dict,
    'optimizer': ...}, DDP 'module.' prefixes) loads through from_pretrained
    and the resulting model runs forward."""
    torch = pytest.importorskip("torch")

    state = {f"module.{k}": torch.tensor(v)
             for k, v in tf_vars_to_torch_state(tf_vars).items()}
    ckpt = tmp_path / "ckpt_8601.pt"
    torch.save({"model": state, "optimizer": {"ignored": True}}, ckpt)
    cfg = dict(vocab_size=V, hidden_size=E, num_hidden_layers=L,
               num_attention_heads=H, intermediate_size=F,
               max_position_embeddings=MP, type_vocab_size=2,
               hidden_act="gelu", hidden_dropout_prob=0.0,
               attention_probs_dropout_prob=0.0, initializer_range=0.02)
    (tmp_path / "bert_config.json").write_text(json.dumps(cfg))

    config, params = from_pretrained(str(ckpt), vocab_pad_multiple=8)
    assert config.vocab_size == 104
    emb = params["bert"]["embeddings"]["word_embeddings"]["embedding"]
    assert emb.shape == (104, E)
    model = BertForPreTraining(
        config.replace(dtype="float32", fused_ops=False,
                       attention_impl="xla", hidden_dropout_prob=0.0,
                       attention_probs_dropout_prob=0.0),
        dtype=jnp.float32)
    ids, types, mask = _inputs()
    mlm, nsp = model.apply({"params": params}, jnp.asarray(ids),
                           jnp.asarray(types), jnp.asarray(mask),
                           deterministic=True)
    assert mlm.shape == (2, 12, 104) and nsp.shape == (2, 2)
    # padded rows can't win argmax, same contract as the TF path
    assert int(jnp.max(jnp.argmax(mlm, -1))) < V


def test_load_pretrained_params_from_torch_ckpt(tf_vars, tmp_path):
    """run_squad's --init_checkpoint also accepts a reference ckpt_*.pt:
    encoder loads, the QA head stays fresh."""
    torch = pytest.importorskip("torch")
    from run_squad import load_pretrained_params
    from bert_pytorch_tpu.models import BertForQuestionAnswering

    state = {k: torch.tensor(v)
             for k, v in tf_vars_to_torch_state(tf_vars).items()}
    ckpt = tmp_path / "ckpt_8601.pt"
    torch.save({"model": state}, ckpt)
    cfg = dict(vocab_size=V, hidden_size=E, num_hidden_layers=L,
               num_attention_heads=H, intermediate_size=F,
               max_position_embeddings=MP, type_vocab_size=2,
               hidden_act="gelu", hidden_dropout_prob=0.0,
               attention_probs_dropout_prob=0.0, initializer_range=0.02)
    (tmp_path / "bert_config.json").write_text(json.dumps(cfg))

    qa_cfg = CFG.replace(vocab_size=104, next_sentence=False)
    model = BertForQuestionAnswering(qa_cfg, dtype=jnp.float32)
    ids = jnp.zeros((2, 12), jnp.int32)
    abstract = unbox(model.init(jax.random.PRNGKey(0), ids, ids,
                                jnp.ones((2, 12), jnp.int32))["params"])
    messages = []
    merged = load_pretrained_params(str(ckpt), abstract, log=messages.append)
    emb = merged["bert"]["embeddings"]["word_embeddings"]["embedding"]
    assert np.shape(emb) == (104, E)
    np.testing.assert_array_equal(
        np.asarray(emb)[:V], tf_vars["bert/embeddings/word_embeddings"])
    assert any("WARNING" in m and "qa_outputs" in m for m in messages)


def test_torch_finetune_checkpoint_without_heads(tf_vars, tmp_path):
    """A reference finetune save ({'model': ...} with bert.* + qa_outputs.*
    but no cls.* heads, run_squad.py:1125) converts without error: encoder
    loads, pretraining heads are simply absent."""
    torch = pytest.importorskip("torch")
    from bert_pytorch_tpu.models.pretrained import (convert_torch_to_flax,
                                                    load_torch_checkpoint)

    state = {k: v for k, v in tf_vars_to_torch_state(tf_vars).items()
             if k.startswith("bert.")}
    state["qa_outputs.weight"] = np.zeros((2, E), np.float32)
    state["qa_outputs.bias"] = np.zeros((2,), np.float32)
    ckpt = tmp_path / "squad_finetuned.pt"
    torch.save({"model": {k: torch.tensor(v) for k, v in state.items()}},
               ckpt)
    params = convert_torch_to_flax(load_torch_checkpoint(str(ckpt)), CFG)
    assert "cls_predictions" not in params
    assert "cls_seq_relationship" not in params
    np.testing.assert_array_equal(
        params["bert"]["embeddings"]["word_embeddings"]["embedding"],
        tf_vars["bert/embeddings/word_embeddings"])


def test_vocab_padding(tf_vars):
    padded = CFG.replace(vocab_size=112)  # pad 100 -> 112
    params = convert_tf_to_flax(tf_vars, padded)
    emb = params["bert"]["embeddings"]["word_embeddings"]["embedding"]
    bias = params["cls_predictions"]["bias"]
    assert emb.shape == (112, E) and bias.shape == (112,)
    np.testing.assert_array_equal(emb[V:], 0.0)
    assert (bias[V:] <= -1e4).all()
    # a padded id can never win the MLM argmax
    model = BertForPreTraining(padded, dtype=jnp.float32)
    ids, types, mask = _inputs()
    mlm, _ = model.apply({"params": params}, jnp.asarray(ids),
                         jnp.asarray(types), jnp.asarray(mask),
                         deterministic=True)
    assert int(jnp.max(jnp.argmax(mlm, -1))) < V


def test_from_pretrained_zip_via_file_url(ckpt_dir, tmp_path):
    """Archive path end to end: zip -> file:// URL -> cache -> extract ->
    config+weights (egress-free stand-in for the Google download)."""
    zip_path = tmp_path / "release.zip"
    with zipfile.ZipFile(zip_path, "w") as zf:
        for fn in os.listdir(ckpt_dir):
            if fn == "checkpoint":
                continue
            zf.write(os.path.join(ckpt_dir, fn), arcname=f"tiny_bert/{fn}")
    cache = tmp_path / "cache"
    config, params = from_pretrained(f"file://{zip_path}",
                                     cache_dir=str(cache),
                                     vocab_pad_multiple=8)
    assert config.vocab_size == 104  # 100 padded to %8
    assert config.vocab_file and config.vocab_file.endswith("vocab.txt")
    emb = params["bert"]["embeddings"]["word_embeddings"]["embedding"]
    assert emb.shape == (104, E)
    # weights identical to loading the unzipped dir directly
    _, params_dir = from_pretrained(ckpt_dir, vocab_pad_multiple=8)
    for a, b in zip(jax.tree.leaves(params), jax.tree.leaves(params_dir)):
        np.testing.assert_array_equal(a, b)


def test_cached_path_local_and_missing(tmp_path):
    f = tmp_path / "x.bin"
    f.write_bytes(b"abc")
    assert cached_path(str(f)) == str(f)
    with pytest.raises(FileNotFoundError):
        cached_path(str(tmp_path / "nope.bin"))
    # file:// URLs are cached by content address and stable across calls
    p1 = cached_path(f"file://{f}", cache_dir=str(tmp_path / "c"))
    p2 = cached_path(f"file://{f}", cache_dir=str(tmp_path / "c"))
    assert p1 == p2 and open(p1, "rb").read() == b"abc"


def test_load_pretrained_params_from_tf_release(ckpt_dir):
    """run_squad's --init_checkpoint accepts a Google TF release: encoder
    loads, task head stays fresh, and the fresh subtrees are reported."""
    from run_squad import load_pretrained_params
    from bert_pytorch_tpu.models import BertForQuestionAnswering

    qa_cfg = CFG.replace(vocab_size=104, next_sentence=False)
    model = BertForQuestionAnswering(qa_cfg, dtype=jnp.float32)
    ids = jnp.zeros((2, 12), jnp.int32)
    abstract = unbox(model.init(jax.random.PRNGKey(0), ids, ids,
                                jnp.ones((2, 12), jnp.int32))["params"])
    messages = []
    merged = load_pretrained_params(ckpt_dir, abstract, log=messages.append)
    # encoder weights came across (embedding re-padded 100 -> 104)
    emb = merged["bert"]["embeddings"]["word_embeddings"]["embedding"]
    assert np.shape(emb) == (104, E)
    # encoder weights genuinely replaced the fresh init (a broken qkv name
    # mapping would silently leave the init object in place)
    qkv = merged["bert"]["encoder"]["layers"]["layer"]["attention"]["qkv"]
    assert qkv["kernel"] is not (
        abstract["bert"]["encoder"]["layers"]["layer"]["attention"]["qkv"]
        ["kernel"])
    # the QA head was NOT in the release: the returned tree keeps the very
    # leaf objects of the fresh init, and the gap is warned about
    assert merged["qa_outputs"]["kernel"] is abstract["qa_outputs"]["kernel"]
    warn = [m for m in messages if "WARNING" in m]
    assert warn and "qa_outputs" in warn[0]
    assert "encoder" not in warn[0]  # nothing in the encoder stayed fresh
