"""Vocab trainers: the incremental merge engine must produce bit-identical
results to the naive recount-everything loop it replaced (the spec below),
for both WordPiece scoring and BPE most-frequent scoring."""

import collections
import math
import random

from bert_pytorch_tpu.pipeline.vocab import train_bpe, train_wordpiece


# -- naive reference implementation (the engine's spec) ----------------------

def _pair_counts(words):
    pairs = collections.Counter()
    singles = collections.Counter()
    for symbols, freq in words.items():
        for s in symbols:
            singles[s] += freq
        for a, b in zip(symbols, symbols[1:]):
            pairs[(a, b)] += freq
    return pairs, singles


def _merge_pair(words, pair, merged_symbol):
    out = {}
    a, b = pair
    for symbols, freq in words.items():
        merged = []
        i = 0
        while i < len(symbols):
            if i + 1 < len(symbols) and symbols[i] == a and symbols[i + 1] == b:
                merged.append(merged_symbol)
                i += 2
            else:
                merged.append(symbols[i])
                i += 1
        out[tuple(merged)] = out.get(tuple(merged), 0) + freq
    return out


def naive_wordpiece(word_counts, vocab_size, special_tokens=("[PAD]",)):
    words = {}
    for word, freq in word_counts.items():
        if not word:
            continue
        symbols = tuple([word[0]] + ["##" + c for c in word[1:]])
        words[symbols] = words.get(symbols, 0) + freq
    vocab = list(special_tokens)
    seen = set(vocab)
    for symbols in words:
        for s in symbols:
            if s not in seen:
                seen.add(s)
                vocab.append(s)
    while len(vocab) < vocab_size:
        pairs, singles = _pair_counts(words)

        def merged_name(p):
            a, b = p
            return a + (b[2:] if b.startswith("##") else b)

        candidates = [p for p, c in pairs.items() if c >= 2]
        if not candidates:
            break
        total = sum(singles.values())

        def gain(p):
            c = pairs[p]
            return c * (math.log(c) + math.log(total)
                        - math.log(singles[p[0]]) - math.log(singles[p[1]]))

        best = max(candidates,
                   key=lambda p: (gain(p), -len(merged_name(p)), p))
        new_symbol = merged_name(best)
        words = _merge_pair(words, best, new_symbol)
        if new_symbol not in seen:
            seen.add(new_symbol)
            vocab.append(new_symbol)
    return vocab[:vocab_size]


def _random_corpus(seed, n_words=300):
    rng = random.Random(seed)
    out = {}
    for _ in range(n_words):
        w = "".join(rng.choice("abcdefgh") for _ in range(rng.randrange(1, 9)))
        out[w] = out.get(w, 0) + rng.randrange(1, 50)
    # adversarial repeats: self-overlapping merges ('aaaa') and singletons
    out.update({"aaaa": 40, "aaaaaa": 7, "a": 99, "zz": 3})
    return out


def test_wordpiece_matches_naive():
    for seed in range(3):
        counts = _random_corpus(seed)
        fast = train_wordpiece(counts, 120, special_tokens=("[PAD]",),
                               min_frequency=1)
        slow = naive_wordpiece(counts, 120)
        assert fast == slow


def test_bpe_matches_naive():
    # naive BPE spec: most-frequent pair, pair tuple as tiebreak
    from bert_pytorch_tpu.data.tokenization import bytes_to_unicode

    for seed in range(3):
        counts = _random_corpus(seed)
        fast_vocab, fast_merges = train_bpe(counts, 400,
                                            special_tokens=("<unk>",),
                                            min_frequency=1)
        byte_enc = bytes_to_unicode()
        sp = byte_enc[ord(" ")]
        words = {}
        for word, freq in counts.items():
            mapped = sp + "".join(byte_enc[b] for b in word.encode("utf-8"))
            words[tuple(mapped)] = words.get(tuple(mapped), 0) + freq
        vocab = ["<unk>"] + sorted(set(byte_enc.values()))
        merges = []
        seen = set(vocab)
        while len(vocab) < 400:
            pairs, _ = _pair_counts(words)
            if not pairs:
                break
            best = max(pairs, key=lambda p: (pairs[p], p))
            new_symbol = best[0] + best[1]
            merges.append(best)
            words = _merge_pair(words, best, new_symbol)
            if new_symbol not in seen:
                seen.add(new_symbol)
                vocab.append(new_symbol)
        slow_vocab = {t: i for i, t in enumerate(vocab[:400])}
        assert fast_vocab == slow_vocab
        assert fast_merges == merges


def test_native_merge_parity():
    """The C++ merge engine must reproduce the Python engine's selection
    order BITWISE — identical vocab lists (wordpiece) and identical
    (vocab, merges) (bpe) on a real-text word distribution with ties,
    unicode, and self-overlapping pairs."""
    import os

    import pytest

    from bert_pytorch_tpu.native import (native_vocab_trainer_available)
    from bert_pytorch_tpu.pipeline import vocab as V

    if not native_vocab_trainer_available():
        pytest.skip("native vocab trainer not built")

    text = (
        "the quick brown fox jumps over the lazy dog "
        "aaa aaaa aaaaa banana bananas cafe caffe café caffè "
        "ThE THE the thee them theme schema schemas scheme "
        "日本語 токенизация naïve coöperate zzz zz z "
    ) * 7 + "rare1 rare2 rare3 onlyonce "
    counts = {}
    for w in text.split():
        w = w.lower()
        counts[w] = counts.get(w, 0) + 1

    prior = os.environ.get("BPT_NATIVE")
    os.environ["BPT_NATIVE"] = "0"
    try:
        wp_py = V.train_wordpiece(counts, 220)
        bpe_py = V.train_bpe(counts, 320)
    finally:
        if prior is None:
            os.environ.pop("BPT_NATIVE", None)
        else:
            os.environ["BPT_NATIVE"] = prior
    if os.environ.get("BPT_NATIVE") == "0":
        pytest.skip("BPT_NATIVE=0: native path disabled by the environment")
    wp_nat = V.train_wordpiece(counts, 220)
    bpe_nat = V.train_bpe(counts, 320)

    assert wp_py == wp_nat
    assert bpe_py[0] == bpe_nat[0]
    assert bpe_py[1] == bpe_nat[1]
