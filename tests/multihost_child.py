"""Child process for the two-process multi-host feed test.

Invoked by tests/test_multihost.py as
    python multihost_child.py <coordinator> <num_procs> <proc_id>
with JAX_PLATFORMS=cpu and --xla_force_host_platform_device_count=4, so the
pair of processes forms a 2-host x 4-device cluster — the JAX analogue of the
reference's gloo multi-process dataset harness
(/root/reference/src/dataset.py:431-506).

Asserts, from inside each process:
  1. jax.distributed wires 2 processes into one 8-device platform.
  2. HostShardSampler gives each host its contiguous global chunk.
  3. make_array_from_process_local_data (parallel/mesh.host_to_device_batch)
     lands each host's chunk in the right global shard — verified by
     allgathering the assembled global array and comparing to the exact
     expected global ordering.
  4. A jitted psum over the mesh sees every host's data exactly once.
  5. Mid-epoch state_dict/load_state_dict resume continues the stream.
  6. (argv[4] = shared dir) orbax CheckpointManager saves a sharded pytree
     with cross-process coordination and restores it sharded — the path
     run_pretraining relies on for pod-scale checkpointing, which only works
     when jax.distributed is initialized (parallel/dist.initialize).
  7. Multi-host metrics aggregation (telemetry/multihost.py): both processes
     publish per-host StepWatch-style records into the shared dir; process 0
     folds cross-host min/mean/max step time + data_wait and flags the slow
     host as a straggler — the wiring run_pretraining enables via
     init_run(multihost_dir=...) when process_count > 1.
"""

import os
import sys

import numpy as np


def main() -> None:
    coordinator, num_procs, proc_id = (
        sys.argv[1], int(sys.argv[2]), int(sys.argv[3]))
    ckpt_dir = sys.argv[4] if len(sys.argv) > 4 else None

    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_cpu_collectives_implementation", "gloo")
    jax.distributed.initialize(coordinator_address=coordinator,
                               num_processes=num_procs,
                               process_id=proc_id)

    assert jax.process_count() == num_procs, jax.process_count()
    assert jax.process_index() == proc_id
    assert jax.local_device_count() == 4, jax.local_device_count()
    assert jax.device_count() == 4 * num_procs, jax.device_count()

    import jax.numpy as jnp
    from jax.experimental import multihost_utils

    from bert_pytorch_tpu.data.sharded import HostShardSampler
    from bert_pytorch_tpu.parallel import mesh as mesh_lib

    mesh = mesh_lib.make_mesh({"fsdp": 2})  # data=4 absorbed, fsdp=2 -> 8 way

    dataset_size = 32
    sampler = HostShardSampler(dataset_size, world_size=num_procs,
                               rank=jax.process_index())
    assert sampler.num_samples == 16

    # --- per-host chunk math -------------------------------------------------
    per_host_batch = 8
    idx = sampler.next_indices(per_host_batch)
    expected = np.arange(proc_id * 16, proc_id * 16 + 8) % dataset_size
    np.testing.assert_array_equal(idx, expected)

    # --- host feed seam: local chunk -> correct global shard -----------------
    batch = mesh_lib.host_to_device_batch(
        mesh, {"x": idx.astype(np.int32)}, stacked=False)
    global_x = batch["x"]
    assert global_x.shape == (per_host_batch * num_procs,)
    gathered = np.asarray(
        multihost_utils.process_allgather(global_x, tiled=True))
    # global order must be host0's chunk then host1's chunk — exactly the
    # contiguous per-rank layout the reference's DistributedSampler produced
    want_global = np.concatenate(
        [np.arange(r * 16, r * 16 + 8) for r in range(num_procs)])
    np.testing.assert_array_equal(gathered, want_global)

    # --- a compiled reduction sees every host's data exactly once ------------
    total = jax.jit(jnp.sum, out_shardings=None)(global_x)
    assert int(total) == int(want_global.sum()), (int(total), want_global.sum())

    # --- mid-epoch resume ----------------------------------------------------
    state = sampler.state_dict()
    idx2_a = sampler.next_indices(per_host_batch)
    fresh = HostShardSampler(dataset_size, world_size=num_procs,
                             rank=jax.process_index())
    fresh.load_state_dict(state)
    idx2_b = fresh.next_indices(per_host_batch)
    np.testing.assert_array_equal(idx2_a, idx2_b)
    assert fresh.next_indices(per_host_batch) is None  # epoch exhausted

    # --- cross-host metrics fold + straggler detection -----------------------
    if ckpt_dir is not None:
        from bert_pytorch_tpu.telemetry.multihost import \
            HostMetricsAggregator

        # process 1 publishes a 3x slower step; with 2 hosts the worst
        # z-score is exactly 1.0, so threshold 0.5 must flag it
        mdir = os.path.join(os.path.dirname(ckpt_dir), "metrics_hosts")
        agg = HostMetricsAggregator(mdir, process_index=proc_id,
                                    process_count=num_procs,
                                    z_threshold=0.5)
        agg.publish(7, {"step_time_ms": 100.0 * (1 + 2 * proc_id),
                        "data_wait_ms": 1.0 + proc_id,
                        "seq_per_sec": 8.0})
        multihost_utils.sync_global_devices("metrics_published")
        if proc_id == 0:
            folded, warning = agg.fold()
            assert folded["hosts_reporting"] == num_procs, folded
            assert folded["hosts_step_min"] == 7
            assert folded["step_time_ms_host_min"] == 100.0
            assert folded["step_time_ms_host_max"] == 300.0
            assert folded["step_time_ms_host_mean"] == 200.0
            assert folded["data_wait_ms_host_max"] == 2.0
            assert folded["straggler_host"] == 1, folded
            assert warning is not None and "host 1" in warning, warning
        agg.close()
        multihost_utils.sync_global_devices("metrics_folded")

    # --- cross-process sharded checkpoint save + restore ---------------------
    if ckpt_dir is not None:
        from jax.sharding import NamedSharding, PartitionSpec as P

        from bert_pytorch_tpu.training.checkpoint import CheckpointManager

        sharded = NamedSharding(mesh, P(("data", "fsdp")))
        state = {
            "w": jax.device_put(jnp.arange(64, dtype=jnp.float32), sharded),
            "step": jax.device_put(jnp.asarray(7, jnp.int32),
                                   NamedSharding(mesh, P())),
        }
        mgr = CheckpointManager(ckpt_dir, max_to_keep=2)
        assert mgr.save(7, state, extra={"sampler_index": 16, "epoch": 0})
        mgr.wait()

        abstract = jax.tree.map(
            lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype,
                                           sharding=a.sharding), state)
        restored, extra, step = mgr.restore(abstract)
        assert step == 7
        assert extra == {"sampler_index": 16, "epoch": 0}, extra
        assert restored["w"].sharding == sharded
        got = np.asarray(
            multihost_utils.process_allgather(restored["w"], tiled=True))
        np.testing.assert_array_equal(got, np.arange(64, dtype=np.float32))
        assert int(restored["step"]) == 7
        mgr.close()

    print(f"MULTIHOST_CHILD_OK proc={proc_id}")


if __name__ == "__main__":
    main()
