"""Test harness: fake an 8-device TPU-like mesh on CPU.

The reference tested distributed behavior by spinning up a gloo process group
on CPU (src/dataset.py:455); the JAX-native analogue is a single process with
XLA's host platform forced to expose 8 devices, letting every sharding /
collective path compile and run without hardware.

Note: this environment's sitecustomize registers a remote TPU PJRT plugin and
programmatically sets jax_platforms, so the JAX_PLATFORMS env var alone is not
enough — we must override via jax.config AFTER importing jax, BEFORE any
backend initialization.
"""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.setdefault("JAX_ENABLE_X64", "0")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: long-running test (multi-process cluster spin-up)")


@pytest.fixture(scope="session")
def n_devices():
    return jax.device_count()
