"""Test harness: fake an 8-device TPU-like mesh on CPU.

The reference tested distributed behavior by spinning up a gloo process group
on CPU (src/dataset.py:455); the JAX-native analogue is a single process with
XLA's host platform forced to expose 8 devices, letting every sharding /
collective path compile and run without hardware.

Note: this environment's sitecustomize registers a remote TPU PJRT plugin and
programmatically sets jax_platforms, so the JAX_PLATFORMS env var alone is not
enough — we must override via jax.config AFTER importing jax, BEFORE any
backend initialization.
"""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    flags = (flags + " --xla_force_host_platform_device_count=8").strip()
if "xla_backend_optimization_level" not in flags:
    # CPU-backend compile is the tier-1 suite's dominant cost and level 0
    # compiles ~3x faster (the test_resilience subprocess sessions have
    # always run with it). Every claim the suite pins — parity, bit-
    # identity, collective counts, donation, budgets — compares programs
    # compiled under the SAME flags, so the level only moves wall-clock.
    # Export XLA_FLAGS with an explicit level to override.
    flags = (flags + " --xla_backend_optimization_level=0").strip()
os.environ["XLA_FLAGS"] = flags
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.setdefault("JAX_ENABLE_X64", "0")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: long-running test (multi-process cluster spin-up)")


@pytest.fixture(scope="session")
def n_devices():
    return jax.device_count()


@pytest.fixture(scope="session")
def serving_fixture(tmp_path_factory):
    """One shared serving-fixture build (a checkpoint per registered task
    + serve_args.txt) for every module that starts a live server — the
    build costs ~10s, so test_serving and test_slo must not each pay it.
    Servers only read the checkpoints, so sharing is safe. Returns
    (make_serving_fixture module, fixture root, paths dict)."""
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "make_serving_fixture",
        os.path.join(os.path.dirname(os.path.dirname(__file__)),
                     "scripts", "make_serving_fixture.py"))
    msf = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(msf)
    root = tmp_path_factory.mktemp("serving_fixture")
    paths = msf.build(str(root), max_pos=64)
    return msf, str(root), paths
