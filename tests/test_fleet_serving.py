"""Fleet-scale serving: replica scale-out + work stealing + int8 weights.

Pins the PR-17 acceptance surface:
- multi-replica responses are BIT-identical to single-engine serving for
  the same request set, for every registered task, through the
  work-stealing dispatcher;
- an idle replica actually steals queued waves from a busy one (and the
  steal shows up in replica_stats / the metrics registry);
- the compile count stays flat across mixed-bucket multi-replica traffic
  once steady is armed AFTER every replica's warmup (the
  mark-steady-once-globally bugfix);
- int8 weight quantization round-trips within the accuracy gate, and a
  corrupted scale trips it;
- the sharded-serve graphcheck combo carries nonzero collective ceilings
  and a passing sharding_rules floor;
- the measured SERVE_r02 artifact holds the >=1.6x 2-replica saturation
  ratio the perfboard gates.
"""

import json
import os
import sys
import threading
import time

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

from bert_pytorch_tpu.serving.batcher import Scheduler  # noqa: E402
from bert_pytorch_tpu.serving.engine import (  # noqa: E402
    ServingEngine, zero_batch)

SERVE_OPTS = {
    "labels": ["B-X", "I-X", "O"],
    "class_names": ["0", "1"],
    "num_choices": 2,
    "embed_labels": 2,
    "max_segments": 4,
}


def _tiny_config():
    from bert_pytorch_tpu.config import BertConfig

    return BertConfig(vocab_size=64, hidden_size=32, num_hidden_layers=2,
                      num_attention_heads=4, intermediate_size=64,
                      max_position_embeddings=64, hidden_dropout_prob=0.0,
                      attention_probs_dropout_prob=0.0, fused_ops=False,
                      attention_impl="xla")


def _all_task_stack():
    """(forwards, params, output_kinds) over EVERY registered task at a
    tiny config — the same construction run_server.serve() does."""
    import jax
    import jax.numpy as jnp

    from bert_pytorch_tpu.tasks import registry
    from bert_pytorch_tpu.training.state import unbox

    config = _tiny_config()
    forwards, params, kinds = {}, {}, {}
    for task in registry.all_tasks():
        spec = registry.get(task)
        model = spec.build_serving_model(config, jnp.float32, SERVE_OPTS)
        s = jnp.zeros((1, 16), jnp.int32)
        params[task] = unbox(
            model.init(jax.random.PRNGKey(3), s, s, s)["params"])
        forwards[task] = spec.forward_builder(model)
        kinds[task] = spec.output_kind
    return forwards, params, kinds


@pytest.fixture(scope="module")
def fleet():
    """Two identical replicas (the fleet) plus their shared stack."""
    forwards, params, kinds = _all_task_stack()
    engines = []
    for i in range(2):
        eng = ServingEngine(forwards, params, buckets=(16, 32),
                            batch_rows=2, max_segments=2,
                            output_kinds=kinds, name=f"r{i}")
        eng.warmup()
        engines.append(eng)
    return engines


def _reference(engine, task, ids):
    """Serve one request alone on ONE engine — the fleet's bit-identity
    reference (same demux the batcher applies)."""
    bucket = engine.select_bucket(len(ids))
    batch = zero_batch(engine.batch_rows, bucket)
    batch["input_ids"][0, :len(ids)] = ids
    batch["attention_mask"][0, :len(ids)] = 1
    batch["segment_ids"][0, :len(ids)] = 1
    batch["position_ids"][0, :len(ids)] = np.arange(len(ids))
    outputs = engine.forward(task, batch)
    return Scheduler._demux(outputs, 0, 0, len(ids), 0,
                            engine.output_kind(task))


def _assert_same(a, b, ctx):
    a = a if isinstance(a, tuple) else (a,)
    b = b if isinstance(b, tuple) else (b,)
    assert len(a) == len(b), ctx
    for x, y in zip(a, b):
        assert np.array_equal(np.asarray(x), np.asarray(y)), ctx


def test_multi_replica_bit_identical_all_tasks(fleet):
    """Replica choice must not change a single bit: every registered
    task's responses through the 2-replica work-stealing dispatcher equal
    the single-engine single-request reference."""
    from bert_pytorch_tpu.tasks import registry

    rng = np.random.RandomState(7)
    requests = []  # (task, ids)
    for task in registry.all_tasks():
        for ln in (5, 16, 11, 32, 8):
            requests.append(
                (task, rng.randint(5, 64, (ln,)).astype(np.int32)))
    refs = [_reference(fleet[0], task, ids) for task, ids in requests]

    sch = Scheduler(fleet, packing=True, batch_wait_ms=1.0).start()
    try:
        handles = [sch.submit(task, ids) for task, ids in requests]
        got = [sch.result(h, timeout=120) for h in handles]
        stats = sch.replica_stats()
    finally:
        sch.close()
    for (task, ids), ref, out in zip(requests, refs, got):
        _assert_same(ref, out, f"{task} len {len(ids)} differs "
                               "fleet vs single-engine")
    # both replicas exist in the stats table; all waves accounted for
    assert [s["replica"] for s in stats] == [0, 1]
    assert sum(s["dispatched"] for s in stats) > 0
    assert all(s["compiled_buckets"] == [16, 32] for s in stats)


class _GatedEngine:
    """Engine stub whose forward can be blocked per-instance — makes the
    steal deterministic: replica 0 jams, replica 1 must steal its queue."""

    buckets = (16,)
    batch_rows = 2
    max_segments = 2
    max_bucket = 16

    def __init__(self, name, gate=None):
        self.name = name
        self.gate = gate
        self.served = []

    def select_bucket(self, length):
        return 16 if length <= 16 else None

    def forward(self, task, batch):
        if self.gate is not None:
            assert self.gate.wait(timeout=30)
        self.served.append(task)
        b, s = np.shape(batch["input_ids"])
        return np.zeros((b, s)), np.zeros((b, s))


def test_idle_replica_steals_from_deepest_queue():
    # BOTH engines gated: whichever worker picks a wave jams on it. An
    # idle worker may legally steal a queued wave before its owner wakes
    # (that's the whole point of the dispatcher), so "r0 holds wave 1"
    # cannot be assumed — probe until r0 is the jammed holder, releasing
    # any probe r1 happened to grab first.
    gate0, gate1 = threading.Event(), threading.Event()
    jammed, free = _GatedEngine("r0", gate0), _GatedEngine("r1", gate1)
    sch = Scheduler([jammed, free], packing=True, batch_wait_ms=0.0).start()
    try:
        ids = np.arange(8, dtype=np.int32)
        first = None
        deadline = time.time() + 30
        while first is None and time.time() < deadline:
            # quiesce: a just-flushed probe decrements _inflight[1] only
            # after its result resolves — don't misread it as the next one
            while ((sch._inflight[0] or sch._inflight[1])
                   and time.time() < deadline):
                time.sleep(0.005)
            h = sch.submit("squad", ids)
            while (not sch._inflight[0] and not sch._inflight[1]
                   and time.time() < deadline):
                time.sleep(0.005)
            if sch._inflight[0]:
                first = h                  # r0 jams on this wave
            else:                          # r1 grabbed the probe: flush it
                gate1.set()
                sch.result(h, timeout=30)
                gate1.clear()
        assert first is not None, "replica 0 never held a jammed wave"
        gate1.set()                        # r1 free for the rest of the test
        gate = gate0
        # r0 busy, its queue is the deepest; idle r1 must steal these
        later = [sch.submit("squad", ids) for _ in range(3)]
        for h in later:
            sch.result(h, timeout=30)      # resolves while r0 still jammed
        assert not first.done.is_set()
        gate.set()
        sch.result(first, timeout=30)
        stats = sch.replica_stats()
    finally:
        gate0.set()
        gate1.set()
        sch.close()
    assert stats[1]["steals"] >= 1, stats
    # the 3 later requests coalesce into wave(s) r1 stole and ran
    assert stats[1]["dispatched"] >= 1
    assert sch.registry.counter(
        "bert_serve_steals_total",
        labels=("replica",)).value(replica="1") >= 1
    # per-replica gauges exist for both replicas
    for i in ("0", "1"):
        assert sch.registry.gauge(
            "bert_serve_replica_queue_depth",
            labels=("replica",)).value(replica=i) == 0


def test_fleet_compile_flat_after_global_steady():
    """The mark-steady bugfix pin: steady is armed ONCE, after EVERY
    replica finished warmup — then mixed-bucket multi-replica traffic
    never touches the compiler again (compiles flat, zero post-steady)."""
    import jax.numpy as jnp

    from bert_pytorch_tpu.models import BertForQuestionAnswering
    from bert_pytorch_tpu.tasks import predict
    from bert_pytorch_tpu.telemetry.compile_watch import CompileWatch
    from bert_pytorch_tpu.training.state import unbox

    cw = CompileWatch().install()
    try:
        import jax

        model = BertForQuestionAnswering(_tiny_config(), dtype=jnp.float32)
        s = jnp.zeros((1, 16), jnp.int32)
        params = unbox(
            model.init(jax.random.PRNGKey(0), s, s, s)["params"])
        engines = []
        for i in range(2):
            eng = ServingEngine({"squad": predict.build_qa_forward(model)},
                                {"squad": params}, buckets=(16, 32),
                                batch_rows=2, max_segments=2,
                                compile_watch=cw, name=f"r{i}")
            # the fixed contract: replicas warm WITHOUT arming steady
            eng.warmup(mark_steady=False)
            engines.append(eng)
        warm = cw.compiles
        assert warm >= 4  # 2 buckets x 2 replicas actually compiled
        cw.mark_steady()  # armed once, after the WHOLE fleet is warm
        sch = Scheduler(engines, packing=True, batch_wait_ms=0.5).start()
        try:
            rng = np.random.RandomState(5)
            for _ in range(3):
                handles = [
                    sch.submit("squad",
                               rng.randint(5, 64, (ln,)).astype(np.int32))
                    for ln in (3, 16, 9, 32, 12, 7)]  # hits BOTH buckets
                for h in handles:
                    sch.result(h, timeout=60)
        finally:
            sch.close()
        assert cw.compiles == warm, (
            f"multi-replica steady-state traffic recompiled: {warm} "
            f"after fleet warmup, {cw.compiles} after serving")
        assert cw.compiles_after_steady == 0
    finally:
        cw.uninstall()


# -- int8 quantization --------------------------------------------------------


def test_int8_roundtrip_under_gate_and_broken_scale_trips():
    import jax
    import jax.numpy as jnp

    from bert_pytorch_tpu.models import BertForQuestionAnswering
    from bert_pytorch_tpu.serving import quantize as quant_lib
    from bert_pytorch_tpu.tasks import predict
    from bert_pytorch_tpu.training.state import unbox

    config = _tiny_config()
    model = BertForQuestionAnswering(config, dtype=jnp.float32)
    s = jnp.zeros((1, 16), jnp.int32)
    params = unbox(model.init(jax.random.PRNGKey(1), s, s, s)["params"])
    forward = predict.build_qa_forward(model)

    qparams, stats = quant_lib.quantize_tree(jax.device_get(params))
    assert stats["quantized_leaves"] > 0
    assert stats["bytes_after"] < stats["bytes_before"]

    serve_model = BertForQuestionAnswering(config, dtype=jnp.bfloat16)
    q_forward = quant_lib.wrap_forward(
        predict.build_qa_forward(serve_model), jnp.bfloat16)
    probe = quant_lib.probe_batch(2, 32, config.vocab_size)
    delta = quant_lib.decode_delta(forward, params, q_forward, qparams,
                                   probe)
    # the serving gate criterion (argmax agreement is reported but not
    # asserted: random-init logits are near-ties, so argmax flips on
    # noise a real checkpoint's margins never would)
    assert delta["rel_delta"] <= 0.1, delta

    broken = quant_lib.corrupt_scales(qparams)
    bad = quant_lib.decode_delta(forward, params, q_forward, broken,
                                 probe)
    assert bad["rel_delta"] > 0.1, (
        f"corrupted scales slipped under the gate: {bad}")


# -- sharded-serve graphcheck combo (jax-free artifact pins) ------------------


def test_sharded_serve_combo_has_nonzero_collective_ceilings():
    with open(os.path.join(REPO, "results", "graph_budgets.json"),
              encoding="utf-8") as f:
        budgets = json.load(f)
    combo = budgets["combos"]["serve_qa_b4_s64_mp2"]["expect"]
    ceilings = combo["collective_budget"]
    assert sum(ceilings.values()) > 0, (
        "the sharded serve combo must carry NONZERO collective ceilings "
        "— a zero-collective pin would assert the mesh does nothing")
    assert combo["sharding_rules"]["min_verified"] > 0
    assert combo["replication"]["min_sharded_inputs"] > 0

    with open(os.path.join(REPO, "results", "graph_report.json"),
              encoding="utf-8") as f:
        report = json.load(f)
    rep = report["combos"]["serve_qa_b4_s64_mp2"]
    assert sum(rep["collective_counts"].values()) > 0
    mismatched = [i["path"] for i in rep["inputs"]
                  if not i.get("matches_expected", True)]
    assert not mismatched, mismatched


# -- the measured SERVE_r02 artifact ------------------------------------------


def test_serve_r02_scaleout_artifact():
    """The landed fleet sweep: schema-valid, all three legs present, and
    the 2-replica leg saturates >= 1.6x the single-replica leg at the
    same p99 bound (the PR-17 acceptance ratio perfboard gates)."""
    sys.path.insert(0, os.path.join(REPO, "tools"))
    import loadtest

    path = os.path.join(REPO, "SERVE_r02.json")
    with open(path, encoding="utf-8") as f:
        doc = json.load(f)
    assert loadtest.validate_serve(doc) == []
    modes = doc["modes"]
    assert set(modes) == {"r1_f32", "r2_f32", "r1_int8"}
    for label, mode in modes.items():
        meta = mode["meta"]
        assert meta["replicas"] in (1, 2)
        assert meta["dtype"] in ("f32", "int8")
        sat = mode["saturation"]
        assert sat["req_per_sec"] > 0, f"{label} never met the p99 bound"
        assert sat["p99_bound_ms"] == modes["r1_f32"]["saturation"][
            "p99_bound_ms"], "legs must share one p99 bound"
    ratio = modes["r2_f32"]["saturation"]["vs_single_replica"]
    assert ratio >= 1.6, (
        f"2-replica saturation only {ratio}x single-replica (want >=1.6)")
