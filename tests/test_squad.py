"""SQuAD task tests: example reading, sliding-window featurization with
max-context flags, answer-span improvement, n-best extraction, text
realignment, the v1.1 metric, and the end-to-end runner on a tiny model."""

import json
import os
import sys

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from bert_pytorch_tpu.data.tokenization import BertWordPieceTokenizer
from bert_pytorch_tpu.tasks import squad

VOCAB = ["[PAD]", "[UNK]", "[CLS]", "[SEP]", "[MASK]",
         "the", "cat", "sat", "on", "mat", "who", "what", "where", "did",
         "dog", "run", "a", "in", "park", "было", ".", ",", "?"]


@pytest.fixture
def tokenizer(tmp_path):
    p = tmp_path / "vocab.txt"
    p.write_text("\n".join(VOCAB) + "\n")
    return BertWordPieceTokenizer(str(p), lowercase=True)


@pytest.fixture
def squad_file(tmp_path):
    data = {
        "version": "1.1",
        "data": [{
            "title": "t",
            "paragraphs": [{
                "context": "The cat sat on the mat. A dog did run in the park.",
                "qas": [
                    {"id": "q1", "question": "Who sat on the mat?",
                     "answers": [{"text": "The cat", "answer_start": 0}]},
                    {"id": "q2", "question": "Where did a dog run?",
                     "answers": [{"text": "the park",
                                  "answer_start": 42}]},
                ],
            }],
        }],
    }
    p = tmp_path / "train.json"
    p.write_text(json.dumps(data))
    return str(p)


def test_read_examples(squad_file):
    examples = squad.read_squad_examples(squad_file, is_training=True)
    assert len(examples) == 2
    ex = examples[0]
    assert ex.doc_tokens[0] == "The" and ex.doc_tokens[1] == "cat"
    assert ex.start_position == 0 and ex.end_position == 1
    ex2 = examples[1]
    assert " ".join(ex2.doc_tokens[ex2.start_position:ex2.end_position + 1]) \
        == "the park."  # word-level span includes attached punctuation


def test_features_answer_positions(squad_file, tokenizer):
    examples = squad.read_squad_examples(squad_file, is_training=True)
    feats = squad.convert_examples_to_features(
        examples, tokenizer, max_seq_length=64, doc_stride=32,
        max_query_length=16, is_training=True)
    f = feats[0]
    # answer tokens at the labeled span must be "the cat"
    assert f.tokens[f.start_position:f.end_position + 1] == ["the", "cat"]
    assert f.tokens[0] == "[CLS]" and "[SEP]" in f.tokens
    assert len(f.input_ids) == 64 and len(f.segment_ids) == 64
    # segment 1 on doc tokens
    first_sep = f.tokens.index("[SEP]")
    assert f.segment_ids[first_sep + 1] == 1


def test_sliding_window_and_max_context(tokenizer):
    ctx = " ".join(["the cat sat on the mat"] * 12)  # long doc
    ex = squad.SquadExample(qas_id="x", question_text="who sat",
                            doc_tokens=ctx.split())
    feats = squad.convert_examples_to_features(
        [ex], tokenizer, max_seq_length=32, doc_stride=8,
        max_query_length=8, is_training=False)
    assert len(feats) > 1  # window slid
    # every doc token position is max-context in at least one span
    spans_per_token = {}
    for f in feats:
        for pos, flag in f.token_is_max_context.items():
            # count max-context claims per absolute doc-token index
            doc_pos = f.token_to_orig_map[pos]
            spans_per_token.setdefault(
                (doc_pos, f.tokens[pos]), []).append(flag)
    for claims in spans_per_token.values():
        assert sum(claims) >= 1


def test_get_final_text_projection():
    # pred normalized, orig has extra suffix: project back cleanly
    got = squad.get_final_text("steve smith", "Steve Smith's",
                               do_lower_case=True)
    assert got == "Steve Smith"
    # failure path returns orig
    got2 = squad.get_final_text("nonexistent", "Steve Smith's",
                                do_lower_case=True)
    assert got2 == "Steve Smith's"


def test_get_answers_picks_correct_span(squad_file, tokenizer):
    examples = squad.read_squad_examples(squad_file, is_training=False)
    feats = squad.convert_examples_to_features(
        examples, tokenizer, max_seq_length=64, doc_stride=32,
        max_query_length=16, is_training=False)
    # fabricate logits: peak at the true "the cat" span for q1
    results = []
    for f in feats:
        start = np.full(64, -10.0)
        end = np.full(64, -10.0)
        if f.example_index == 0:
            # find "the cat" in doc segment
            first_sep = f.tokens.index("[SEP]")
            for i in range(first_sep + 1, len(f.tokens) - 1):
                if f.tokens[i] == "the" and f.tokens[i + 1] == "cat":
                    start[i] = 5.0
                    end[i + 1] = 5.0
                    break
        else:
            start[1] = 1.0
            end[1] = 1.0
        results.append(squad.RawResult(f.unique_id, start.tolist(),
                                       end.tolist()))
    answers, nbest = squad.get_answers(
        examples, feats, results, squad.AnswerConfig(do_lower_case=True))
    assert answers["q1"] == "The cat"
    assert len(nbest["q1"]) >= 1
    assert abs(sum(p["probability"] for p in nbest["q1"]) - 1.0) < 1e-6


def test_evaluate_v1(squad_file):
    metrics = squad.evaluate_v1(squad_file,
                                {"q1": "the cat", "q2": "the park"})
    assert metrics["exact_match"] == 100.0
    assert metrics["f1"] == 100.0
    metrics2 = squad.evaluate_v1(squad_file,
                                 {"q1": "the cat sat", "q2": "wrong"})
    assert 0 < metrics2["f1"] < 100.0


@pytest.fixture
def squad_v2_file(tmp_path):
    """Same paragraph as squad_file plus an unanswerable question (SQuAD
    v2.0 schema: is_impossible, empty answers)."""
    data = {
        "version": "2.0",
        "data": [{
            "title": "t",
            "paragraphs": [{
                "context": "The cat sat on the mat. A dog did run in the park.",
                "qas": [
                    {"id": "q1", "question": "Who sat on the mat?",
                     "is_impossible": False,
                     "answers": [{"text": "The cat", "answer_start": 0}]},
                    {"id": "q3", "question": "What did the bird eat?",
                     "is_impossible": True, "answers": []},
                ],
            }],
        }],
    }
    p = tmp_path / "train_v2.json"
    p.write_text(json.dumps(data))
    return str(p)


def test_read_examples_v2(squad_v2_file):
    examples = squad.read_squad_examples(squad_v2_file, is_training=True,
                                         version_2_with_negative=True)
    assert len(examples) == 2
    assert not examples[0].is_impossible
    assert examples[0].start_position == 0
    ex = examples[1]
    assert ex.is_impossible
    assert ex.start_position == -1 and ex.end_position == -1
    assert ex.orig_answer_text == ""


def test_features_v2_impossible_targets_cls(squad_v2_file, tokenizer):
    examples = squad.read_squad_examples(squad_v2_file, is_training=True,
                                         version_2_with_negative=True)
    feats = squad.convert_examples_to_features(
        examples, tokenizer, max_seq_length=64, doc_stride=32,
        max_query_length=16, is_training=True)
    impossible = [f for f in feats if f.is_impossible]
    assert impossible
    for f in impossible:
        # no-answer trains toward the [CLS] position, reference :272-276
        assert f.start_position == 0 and f.end_position == 0
    answerable = [f for f in feats if not f.is_impossible]
    assert answerable and answerable[0].start_position > 0


def test_get_answers_v2_null_threshold(squad_v2_file, tokenizer):
    """The null (CLS) score competes with the best span; the threshold
    decides which side wins (reference get_answers v2 branches :431-506)."""
    examples = squad.read_squad_examples(squad_v2_file, is_training=False,
                                         version_2_with_negative=True)
    feats = squad.convert_examples_to_features(
        examples, tokenizer, max_seq_length=64, doc_stride=32,
        max_query_length=16, is_training=False)
    results = []
    for f in feats:
        start = np.full(64, -10.0)
        end = np.full(64, -10.0)
        if f.example_index == 0:
            # strong span ("the cat"), weak null
            start[0], end[0] = -5.0, -5.0
            first_sep = f.tokens.index("[SEP]")
            for i in range(first_sep + 1, len(f.tokens) - 1):
                if f.tokens[i] == "the" and f.tokens[i + 1] == "cat":
                    start[i], end[i + 1] = 5.0, 5.0
                    break
        else:
            # strong null, weak best span
            start[0], end[0] = 6.0, 6.0
            first_sep = f.tokens.index("[SEP]")
            start[first_sep + 1], end[first_sep + 1] = 1.0, 1.0
        results.append(squad.RawResult(f.unique_id, start.tolist(),
                                       end.tolist()))

    cfg = squad.AnswerConfig(do_lower_case=True,
                             version_2_with_negative=True,
                             null_score_diff_threshold=0.0)
    answers, nbest = squad.get_answers(examples, feats, results, cfg)
    assert answers["q1"] == "The cat"     # span beats null
    assert answers["q3"] == ""            # null beats span
    # every question's n-best includes the null candidate
    assert any(p["text"] == "" for p in nbest["q3"])

    # a huge threshold forces every question to keep its best span
    cfg_keep = squad.AnswerConfig(do_lower_case=True,
                                  version_2_with_negative=True,
                                  null_score_diff_threshold=100.0)
    answers_keep, _ = squad.get_answers(examples, feats, results, cfg_keep)
    assert answers_keep["q3"] != ""
    # and a hugely negative one forces null everywhere
    cfg_null = squad.AnswerConfig(do_lower_case=True,
                                  version_2_with_negative=True,
                                  null_score_diff_threshold=-100.0)
    answers_null, _ = squad.get_answers(examples, feats, results, cfg_null)
    assert answers_null["q1"] == "" and answers_null["q3"] == ""


def test_evaluate_v2(squad_v2_file):
    # both right: answerable span + correctly-abstained no-answer
    m = squad.evaluate_v2(squad_v2_file, {"q1": "the cat", "q3": ""})
    assert m["exact_match"] == 100.0 and m["f1"] == 100.0
    assert m["HasAns_f1"] == 100.0 and m["NoAns_f1"] == 100.0
    # wrongly answering the unanswerable question scores 0 on it (the
    # degenerate-F1 rule: either side no-answer -> exact match only)
    m2 = squad.evaluate_v2(squad_v2_file, {"q1": "the cat", "q3": "a dog"})
    assert m2["NoAns_f1"] == 0.0 and m2["f1"] == 50.0
    # abstaining on the answerable question likewise
    m3 = squad.evaluate_v2(squad_v2_file, {"q1": "", "q3": ""})
    assert m3["HasAns_f1"] == 0.0 and m3["NoAns_f1"] == 100.0
    # partial span overlap still earns partial F1 on HasAns
    m4 = squad.evaluate_v2(squad_v2_file, {"q1": "the cat sat", "q3": ""})
    assert 0.0 < m4["HasAns_f1"] < 100.0
    # a missing prediction earns 0, not a free no-answer match
    m5 = squad.evaluate_v2(squad_v2_file, {"q1": "the cat"})
    assert m5["missing_predictions"] == 1.0
    assert m5["NoAns_exact"] == 0.0 and m5["exact_match"] == 50.0


def test_run_squad_v2_end_to_end(tmp_path, squad_v2_file):
    """Tiny model through the runner with --version_2_with_negative: the
    null path exercised in training targets, prediction, and the v2 metric."""
    vocab_path = tmp_path / "vocab.txt"
    vocab_path.write_text("\n".join(VOCAB) + "\n")
    model_cfg = {
        "vocab_size": len(VOCAB), "hidden_size": 32, "num_hidden_layers": 2,
        "num_attention_heads": 4, "intermediate_size": 64,
        "max_position_embeddings": 64, "next_sentence": True,
        "hidden_dropout_prob": 0.0, "attention_probs_dropout_prob": 0.0,
        "fused_ops": False, "attention_impl": "xla", "lowercase": True,
        "vocab_file": str(vocab_path),
    }
    cfg_path = tmp_path / "model_config.json"
    cfg_path.write_text(json.dumps(model_cfg))

    import run_squad

    out = tmp_path / "out_v2"
    results = run_squad.main([
        "--do_train", "--do_predict", "--do_eval",
        "--version_2_with_negative",
        "--train_file", squad_v2_file, "--predict_file", squad_v2_file,
        "--model_config_file", str(cfg_path),
        "--output_dir", str(out),
        "--max_seq_length", "64", "--doc_stride", "32",
        "--train_batch_size", "2", "--predict_batch_size", "2",
        "--num_train_epochs", "2", "--learning_rate", "1e-4",
        "--dtype", "float32",
    ])
    assert "NoAns_exact" in results and "f1" in results
    preds = json.loads((out / "predictions.json").read_text())
    assert set(preds) == {"q1", "q3"}

    # phase-agnostic perf schema (telemetry/run.py init_run): the squad
    # phase's StepWatch interval records carry the same core keys the
    # pretrain and ner e2e tests assert on
    from bert_pytorch_tpu.telemetry import PERF_RECORD_CORE_KEYS

    perf = [json.loads(line)
            for line in (out / "squad_log.jsonl").read_text().splitlines()
            if json.loads(line).get("tag") == "perf"]
    assert perf, "no perf records reached the squad jsonl sink"
    assert set(PERF_RECORD_CORE_KEYS) <= set(perf[-1]), perf[-1]


def test_make_synthetic_squad_v2(tmp_path):
    """--negative_frac emits schema-valid unanswerable questions that the
    v2 reader accepts."""
    import subprocess
    import sys as _sys

    corpus = tmp_path / "corpus"
    corpus.mkdir()
    rng = np.random.RandomState(0)
    words = ["alpha", "beta", "gamma", "delta", "epsilon", "zeta", "eta",
             "theta", "iota", "kappa", "lamda", "mu", "nu", "xi"]
    docs = []
    for d in range(30):
        para = " ".join(rng.choice(words, 60))
        docs.append(para + "\n")
    (corpus / "docs.txt").write_text("\n".join(docs))
    out = tmp_path / "sq2"
    script = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "scripts", "make_synthetic_squad.py")
    r = subprocess.run(
        [_sys.executable, script, str(corpus), str(out),
         "--train", "10", "--dev", "5", "--negative_frac", "0.5"],
        capture_output=True, text=True)
    assert r.returncode == 0, r.stderr
    data = json.loads((out / "train.json").read_text())
    assert data["version"].startswith("2.0")
    qas = [qa for para in data["data"][0]["paragraphs"]
           for qa in para["qas"]]
    assert all("is_impossible" in qa for qa in qas)
    negs = [qa for qa in qas if qa["is_impossible"]]
    assert negs and all(qa["answers"] == [] for qa in negs)
    # reader round-trip
    examples = squad.read_squad_examples(
        str(out / "train.json"), is_training=True,
        version_2_with_negative=True)
    assert any(e.is_impossible for e in examples)
    assert any(not e.is_impossible for e in examples)


def test_batches_pads_tail():
    arrays = {"input_ids": np.arange(10 * 4).reshape(10, 4).astype(np.int32),
              "start_positions": np.arange(10, dtype=np.int32),
              "end_positions": np.arange(10, dtype=np.int32)}
    got = list(squad.batches(arrays, 4))
    assert len(got) == 3
    last, real = got[-1]
    assert real == 2
    assert last["input_ids"].shape == (4, 4)
    assert (last["start_positions"][2:] == -1).all()


def test_run_squad_end_to_end(tmp_path, squad_file):
    """Tiny model + tiny data through the full runner: train, predict, eval."""
    vocab_path = tmp_path / "vocab.txt"
    vocab_path.write_text("\n".join(VOCAB) + "\n")
    model_cfg = {
        "vocab_size": len(VOCAB), "hidden_size": 32, "num_hidden_layers": 2,
        "num_attention_heads": 4, "intermediate_size": 64,
        "max_position_embeddings": 64, "next_sentence": True,
        "hidden_dropout_prob": 0.0, "attention_probs_dropout_prob": 0.0,
        "fused_ops": False, "attention_impl": "xla", "lowercase": True,
        "vocab_file": str(vocab_path),
    }
    cfg_path = tmp_path / "model_config.json"
    cfg_path.write_text(json.dumps(model_cfg))

    import run_squad

    out = tmp_path / "out"
    results = run_squad.main([
        "--do_train", "--do_predict", "--do_eval",
        "--train_file", squad_file, "--predict_file", squad_file,
        "--model_config_file", str(cfg_path),
        "--output_dir", str(out),
        "--max_seq_length", "64", "--doc_stride", "32",
        "--train_batch_size", "2", "--predict_batch_size", "2",
        "--num_train_epochs", "2", "--learning_rate", "1e-4",
        "--dtype", "float32",
    ])
    assert "f1" in results and "e2e_train_time" in results
    preds = json.loads((out / "predictions.json").read_text())
    assert set(preds) == {"q1", "q2"}
    assert (out / "nbest_predictions.json").exists()
