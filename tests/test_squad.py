"""SQuAD task tests: example reading, sliding-window featurization with
max-context flags, answer-span improvement, n-best extraction, text
realignment, the v1.1 metric, and the end-to-end runner on a tiny model."""

import json
import os
import sys

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from bert_pytorch_tpu.data.tokenization import BertWordPieceTokenizer
from bert_pytorch_tpu.tasks import squad

VOCAB = ["[PAD]", "[UNK]", "[CLS]", "[SEP]", "[MASK]",
         "the", "cat", "sat", "on", "mat", "who", "what", "where", "did",
         "dog", "run", "a", "in", "park", "было", ".", ",", "?"]


@pytest.fixture
def tokenizer(tmp_path):
    p = tmp_path / "vocab.txt"
    p.write_text("\n".join(VOCAB) + "\n")
    return BertWordPieceTokenizer(str(p), lowercase=True)


@pytest.fixture
def squad_file(tmp_path):
    data = {
        "version": "1.1",
        "data": [{
            "title": "t",
            "paragraphs": [{
                "context": "The cat sat on the mat. A dog did run in the park.",
                "qas": [
                    {"id": "q1", "question": "Who sat on the mat?",
                     "answers": [{"text": "The cat", "answer_start": 0}]},
                    {"id": "q2", "question": "Where did a dog run?",
                     "answers": [{"text": "the park",
                                  "answer_start": 42}]},
                ],
            }],
        }],
    }
    p = tmp_path / "train.json"
    p.write_text(json.dumps(data))
    return str(p)


def test_read_examples(squad_file):
    examples = squad.read_squad_examples(squad_file, is_training=True)
    assert len(examples) == 2
    ex = examples[0]
    assert ex.doc_tokens[0] == "The" and ex.doc_tokens[1] == "cat"
    assert ex.start_position == 0 and ex.end_position == 1
    ex2 = examples[1]
    assert " ".join(ex2.doc_tokens[ex2.start_position:ex2.end_position + 1]) \
        == "the park."  # word-level span includes attached punctuation


def test_features_answer_positions(squad_file, tokenizer):
    examples = squad.read_squad_examples(squad_file, is_training=True)
    feats = squad.convert_examples_to_features(
        examples, tokenizer, max_seq_length=64, doc_stride=32,
        max_query_length=16, is_training=True)
    f = feats[0]
    # answer tokens at the labeled span must be "the cat"
    assert f.tokens[f.start_position:f.end_position + 1] == ["the", "cat"]
    assert f.tokens[0] == "[CLS]" and "[SEP]" in f.tokens
    assert len(f.input_ids) == 64 and len(f.segment_ids) == 64
    # segment 1 on doc tokens
    first_sep = f.tokens.index("[SEP]")
    assert f.segment_ids[first_sep + 1] == 1


def test_sliding_window_and_max_context(tokenizer):
    ctx = " ".join(["the cat sat on the mat"] * 12)  # long doc
    ex = squad.SquadExample(qas_id="x", question_text="who sat",
                            doc_tokens=ctx.split())
    feats = squad.convert_examples_to_features(
        [ex], tokenizer, max_seq_length=32, doc_stride=8,
        max_query_length=8, is_training=False)
    assert len(feats) > 1  # window slid
    # every doc token position is max-context in exactly one span
    max_ct = {}
    for f in feats:
        for pos, flag in f.token_is_max_context.items():
            orig = f.token_to_orig_map[pos]
            tok_idx = (f.doc_span_index, pos)
            if flag:
                key = (orig, f.tokens[pos])
                max_ct.setdefault((f.unique_id, pos), 0)
    spans_per_token = {}
    for f in feats:
        for pos, flag in f.token_is_max_context.items():
            # count max-context claims per absolute doc-token index
            doc_pos = f.token_to_orig_map[pos]
            split_idx = None
            spans_per_token.setdefault(
                (doc_pos, f.tokens[pos]), []).append(flag)
    for claims in spans_per_token.values():
        assert sum(claims) >= 1


def test_get_final_text_projection():
    # pred normalized, orig has extra suffix: project back cleanly
    got = squad.get_final_text("steve smith", "Steve Smith's",
                               do_lower_case=True)
    assert got == "Steve Smith"
    # failure path returns orig
    got2 = squad.get_final_text("nonexistent", "Steve Smith's",
                                do_lower_case=True)
    assert got2 == "Steve Smith's"


def test_get_answers_picks_correct_span(squad_file, tokenizer):
    examples = squad.read_squad_examples(squad_file, is_training=False)
    feats = squad.convert_examples_to_features(
        examples, tokenizer, max_seq_length=64, doc_stride=32,
        max_query_length=16, is_training=False)
    # fabricate logits: peak at the true "the cat" span for q1
    results = []
    for f in feats:
        start = np.full(64, -10.0)
        end = np.full(64, -10.0)
        if f.example_index == 0:
            # find "the cat" in doc segment
            first_sep = f.tokens.index("[SEP]")
            for i in range(first_sep + 1, len(f.tokens) - 1):
                if f.tokens[i] == "the" and f.tokens[i + 1] == "cat":
                    start[i] = 5.0
                    end[i + 1] = 5.0
                    break
        else:
            start[1] = 1.0
            end[1] = 1.0
        results.append(squad.RawResult(f.unique_id, start.tolist(),
                                       end.tolist()))
    answers, nbest = squad.get_answers(
        examples, feats, results, squad.AnswerConfig(do_lower_case=True))
    assert answers["q1"] == "The cat"
    assert len(nbest["q1"]) >= 1
    assert abs(sum(p["probability"] for p in nbest["q1"]) - 1.0) < 1e-6


def test_evaluate_v1(squad_file):
    metrics = squad.evaluate_v1(squad_file,
                                {"q1": "the cat", "q2": "the park"})
    assert metrics["exact_match"] == 100.0
    assert metrics["f1"] == 100.0
    metrics2 = squad.evaluate_v1(squad_file,
                                 {"q1": "the cat sat", "q2": "wrong"})
    assert 0 < metrics2["f1"] < 100.0


def test_batches_pads_tail():
    arrays = {"input_ids": np.arange(10 * 4).reshape(10, 4).astype(np.int32),
              "start_positions": np.arange(10, dtype=np.int32),
              "end_positions": np.arange(10, dtype=np.int32)}
    got = list(squad.batches(arrays, 4))
    assert len(got) == 3
    last, real = got[-1]
    assert real == 2
    assert last["input_ids"].shape == (4, 4)
    assert (last["start_positions"][2:] == -1).all()


def test_run_squad_end_to_end(tmp_path, squad_file):
    """Tiny model + tiny data through the full runner: train, predict, eval."""
    vocab_path = tmp_path / "vocab.txt"
    vocab_path.write_text("\n".join(VOCAB) + "\n")
    model_cfg = {
        "vocab_size": len(VOCAB), "hidden_size": 32, "num_hidden_layers": 2,
        "num_attention_heads": 4, "intermediate_size": 64,
        "max_position_embeddings": 64, "next_sentence": True,
        "hidden_dropout_prob": 0.0, "attention_probs_dropout_prob": 0.0,
        "fused_ops": False, "attention_impl": "xla", "lowercase": True,
        "vocab_file": str(vocab_path),
    }
    cfg_path = tmp_path / "model_config.json"
    cfg_path.write_text(json.dumps(model_cfg))

    import run_squad

    out = tmp_path / "out"
    results = run_squad.main([
        "--do_train", "--do_predict", "--do_eval",
        "--train_file", squad_file, "--predict_file", squad_file,
        "--model_config_file", str(cfg_path),
        "--output_dir", str(out),
        "--max_seq_length", "64", "--doc_stride", "32",
        "--train_batch_size", "2", "--predict_batch_size", "2",
        "--num_train_epochs", "2", "--learning_rate", "1e-4",
        "--dtype", "float32",
    ])
    assert "f1" in results and "e2e_train_time" in results
    preds = json.loads((out / "predictions.json").read_text())
    assert set(preds) == {"q1", "q2"}
    assert (out / "nbest_predictions.json").exists()
