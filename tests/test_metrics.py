"""Direct tests for training/metrics.MetricLogger: CSV header-expansion
rewrite, jsonl sink, resume-append into an existing CSV, verbose=False
gating, context-manager close, and the provenance run header."""

import csv
import io
import json
import os

from bert_pytorch_tpu.training.metrics import MetricLogger


def _read_csv(path):
    with open(path, newline="", encoding="utf-8") as f:
        return list(csv.DictReader(f))


def test_csv_header_expansion_rewrites_old_rows(tmp_path):
    """A later record with new keys must widen the header and realign the
    already-written rows — no metric silently dropped, no column shear."""
    prefix = str(tmp_path / "log")
    logger = MetricLogger(log_prefix=prefix, stream=io.StringIO())
    logger.log("train", 1, loss=1.0)
    logger.log("train", 2, loss=0.9, mfu=0.5)   # new key -> rewrite
    logger.close()

    rows = _read_csv(prefix + "_metrics.csv")
    assert len(rows) == 2
    assert rows[0]["loss"] == "1.0" and rows[0]["mfu"] == ""
    assert rows[1]["loss"] == "0.9" and rows[1]["mfu"] == "0.5"


def test_jsonl_sink_records(tmp_path):
    prefix = str(tmp_path / "log")
    logger = MetricLogger(log_prefix=prefix, stream=io.StringIO(),
                          jsonl=True)
    logger.log("train", 3, loss=2.5, seq_per_sec=10.0)
    logger.log("perf", 3, step_time_ms=12.0)
    logger.close()

    records = [json.loads(l) for l in
               open(prefix + ".jsonl", encoding="utf-8")]
    assert [r["tag"] for r in records] == ["train", "perf"]
    assert records[0]["loss"] == 2.5 and records[0]["step"] == 3
    assert "time" in records[0]
    assert records[1]["step_time_ms"] == 12.0


def test_resume_appends_to_existing_csv(tmp_path):
    """A second run with the same prefix (auto-resume) must adopt the
    existing header and append — one header line, rows aligned."""
    prefix = str(tmp_path / "log")
    with MetricLogger(log_prefix=prefix, stream=io.StringIO()) as logger:
        logger.log("train", 1, loss=1.0, learning_rate=1e-3)

    with MetricLogger(log_prefix=prefix, stream=io.StringIO()) as logger:
        logger.log("train", 2, loss=0.8, learning_rate=9e-4)

    raw = open(prefix + "_metrics.csv", encoding="utf-8").read()
    assert raw.count("loss") == 1  # header written once
    rows = _read_csv(prefix + "_metrics.csv")
    assert [r["step"] for r in rows] == ["1", "2"]
    assert rows[1]["learning_rate"] == "0.0009"

    # text file appended too (MetricLogger opens it in append mode)
    txt = open(prefix + ".txt", encoding="utf-8").read()
    assert "step 1" in txt and "step 2" in txt


def test_verbose_false_gates_every_sink(tmp_path):
    prefix = str(tmp_path / "quiet")
    stream = io.StringIO()
    logger = MetricLogger(log_prefix=prefix, verbose=False, stream=stream,
                          jsonl=True)
    logger.log("train", 1, loss=1.0)
    logger.info("hello")
    logger.log_header(git_sha="deadbeef")
    logger.close()

    assert stream.getvalue() == ""
    assert not os.path.exists(prefix + ".txt")
    assert not os.path.exists(prefix + "_metrics.csv")
    assert not os.path.exists(prefix + ".jsonl")


def test_context_manager_closes_sinks(tmp_path):
    prefix = str(tmp_path / "ctx")
    with MetricLogger(log_prefix=prefix, stream=io.StringIO()) as logger:
        logger.log("train", 1, loss=1.0)
        f = logger._file
    assert f.closed
    # close() is idempotent (context exit after an explicit close)
    logger.close()
    # logging after close is a consistent no-op across ALL sinks — in
    # particular the CSV path must not silently reopen its file
    logger.log("train", 2, loss=0.5)
    logger.info("late")
    assert logger._csv_file is None
    rows = _read_csv(prefix + "_metrics.csv")
    assert len(rows) == 1


def test_log_header_stamps_text_and_jsonl_not_csv(tmp_path):
    prefix = str(tmp_path / "log")
    with MetricLogger(log_prefix=prefix, stream=io.StringIO(),
                      jsonl=True) as logger:
        logger.log_header(git_sha="abc123", jax_version="0.4.37",
                          mesh={"data": 8})
        logger.log("train", 1, loss=1.0)

    txt = open(prefix + ".txt", encoding="utf-8").read()
    assert "[header]" in txt and "git_sha=abc123" in txt
    records = [json.loads(l) for l in
               open(prefix + ".jsonl", encoding="utf-8")]
    assert records[0]["tag"] == "header"
    assert records[0]["mesh"] == {"data": 8}
    # header fields must NOT leak into the metrics CSV schema
    rows = _read_csv(prefix + "_metrics.csv")
    assert "git_sha" not in rows[0]


def _headers(prefix):
    jsonl = [json.loads(l) for l in
             open(prefix + ".jsonl", encoding="utf-8")]
    txt = open(prefix + ".txt", encoding="utf-8").read()
    return ([r for r in jsonl if r.get("tag") == "header"],
            txt.count("[header]"))


def test_log_header_dedup_on_resume_append(tmp_path):
    """A resumed run re-collects identical provenance; the header must not
    be appended a second time into the same jsonl/txt (wall-clock stamps
    excluded from the comparison)."""
    prefix = str(tmp_path / "log")
    fields = dict(git_sha="abc123", jax_version="0.4.37",
                  mesh={"data": 8})
    with MetricLogger(log_prefix=prefix, stream=io.StringIO(),
                      jsonl=True) as logger:
        logger.log_header(time_unix=1000.0, **fields)
        logger.log("train", 1, loss=1.0)
    # resume: same provenance, new wall clock -> deduplicated
    with MetricLogger(log_prefix=prefix, stream=io.StringIO(),
                      jsonl=True) as logger:
        logger.log_header(time_unix=2000.0, **fields)
        logger.log("train", 2, loss=0.9)
    headers, txt_count = _headers(prefix)
    assert len(headers) == 1
    assert txt_count == 1
    # second resume under a NEW sha: that difference is what the header
    # records — it must land
    with MetricLogger(log_prefix=prefix, stream=io.StringIO(),
                      jsonl=True) as logger:
        logger.log_header(time_unix=3000.0,
                          **dict(fields, git_sha="def456"))
    headers, txt_count = _headers(prefix)
    assert len(headers) == 2
    assert txt_count == 2
    assert headers[-1]["git_sha"] == "def456"


def test_log_header_extension_then_resume_then_flipback(tmp_path):
    """The round-13 two-header contract: a run logs the base provenance
    stamp, then the program-fingerprint EXTENSION (base fields + extras).
    A resume re-logging the base stamp must dedup against the extension
    (subset coverage) — but a flip-back to an OLDER provenance value
    (sha A -> B -> A across resumes) must land every time: the jsonl's
    last header must always describe the live run."""
    prefix = str(tmp_path / "log")
    base_a = dict(git_sha="aaa", mesh={"data": 8})
    with MetricLogger(log_prefix=prefix, stream=io.StringIO(),
                      jsonl=True) as logger:
        logger.log_header(time_unix=1.0, **base_a)
        logger.log_header(time_unix=2.0, **base_a,
                          program_fingerprint="fp-aaa")  # the extension
    headers, _ = _headers(prefix)
    assert len(headers) == 2
    # resume, same sha: base stamp covered by the extension -> dedup;
    # the re-logged extension is covered too
    with MetricLogger(log_prefix=prefix, stream=io.StringIO(),
                      jsonl=True) as logger:
        logger.log_header(time_unix=3.0, **base_a)
        logger.log_header(time_unix=4.0, **base_a,
                          program_fingerprint="fp-aaa")
    assert len(_headers(prefix)[0]) == 2
    # resume at sha bbb, then FLIP BACK to aaa: all of them land
    for sha, fp in (("bbb", "fp-bbb"), ("aaa", "fp-aaa")):
        with MetricLogger(log_prefix=prefix, stream=io.StringIO(),
                          jsonl=True) as logger:
            logger.log_header(time_unix=5.0, git_sha=sha,
                              mesh={"data": 8})
            logger.log_header(time_unix=6.0, git_sha=sha,
                              mesh={"data": 8}, program_fingerprint=fp)
    headers, _ = _headers(prefix)
    assert [h.get("git_sha") for h in headers] == \
        ["aaa", "aaa", "bbb", "bbb", "aaa", "aaa"]
    assert headers[-1]["program_fingerprint"] == "fp-aaa"


def test_log_header_dedup_within_one_process(tmp_path):
    prefix = str(tmp_path / "log")
    with MetricLogger(log_prefix=prefix, stream=io.StringIO(),
                      jsonl=True) as logger:
        logger.log_header(git_sha="abc", time_unix=1.0)
        logger.log_header(git_sha="abc", time_unix=2.0)  # duplicate
        logger.log_header(git_sha="xyz", time_unix=3.0)  # changed
    headers, txt_count = _headers(prefix)
    assert [h["git_sha"] for h in headers] == ["abc", "xyz"]
    assert txt_count == 2


def test_log_header_dedup_publishes_to_registry_regardless(tmp_path):
    """Dedup drops the file append, not the liveness: the registry (if
    wired) and stream still see that the run (re)started."""
    from bert_pytorch_tpu.telemetry.registry import MetricsRegistry

    prefix = str(tmp_path / "log")
    reg = MetricsRegistry()
    stream = io.StringIO()
    with MetricLogger(log_prefix=prefix, stream=stream, jsonl=True,
                      registry=reg) as logger:
        logger.log_header(git_sha="abc", time_unix=1.0)
        logger.log_header(git_sha="abc", time_unix=2.0)
    assert "unchanged on resume" in stream.getvalue()
    # metric records still publish through the registry
    with MetricLogger(log_prefix=prefix, stream=io.StringIO(),
                      jsonl=True, registry=reg) as logger:
        logger.log("train", 5, loss=2.5)
    assert reg.gauge("bert_metric", labels=("tag", "name")).value(
        tag="train", name="loss") == 2.5
