"""Training-subsystem tests on the 8-device CPU mesh (conftest.py): sharded
state init, train-step convergence, accumulation equivalence, checkpoint
roundtrip + rolling window, logger sinks, schedule shapes."""

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from bert_pytorch_tpu.config import BertConfig
from bert_pytorch_tpu.models import BertForPreTraining
from bert_pytorch_tpu.optim import lamb, schedulers
from bert_pytorch_tpu.optim.lamb import default_weight_decay_mask
from bert_pytorch_tpu.parallel import mesh as mesh_lib
from bert_pytorch_tpu.training import (
    CheckpointManager,
    MetricLogger,
    TrainState,
    build_pretrain_step,
    make_sharded_state,
)
from bert_pytorch_tpu.training.pretrain import stack_microbatches

TINY = BertConfig(
    vocab_size=128, hidden_size=32, num_hidden_layers=2,
    num_attention_heads=4, intermediate_size=64,
    max_position_embeddings=64, next_sentence=True,
    dtype="float32", fused_ops=False, attention_impl="xla",
    hidden_dropout_prob=0.0, attention_probs_dropout_prob=0.0,
)


def _batch(global_batch=16, seq=16, vocab=128, seed=0, accum=1):
    rng = np.random.RandomState(seed)
    ids = rng.randint(5, vocab, (global_batch, seq)).astype(np.int32)
    labels = np.full((global_batch, seq), -1, np.int32)
    mask_pos = rng.randint(1, seq - 1, (global_batch, 2))
    for b in range(global_batch):
        for p in mask_pos[b]:
            labels[b, p] = ids[b, p]
            ids[b, p] = 3  # pretend mask token
    batch = {
        "input_ids": ids,
        "token_type_ids": np.zeros((global_batch, seq), np.int32),
        "attention_mask": np.ones((global_batch, seq), np.int32),
        "masked_lm_labels": labels,
        "next_sentence_labels": rng.randint(0, 2, (global_batch,)).astype(np.int32),
    }
    return stack_microbatches(batch, accum)


def _make(model_cfg=TINY, lr=1e-3, accum=1):
    model = BertForPreTraining(model_cfg, dtype=jnp.float32)
    sched = schedulers.poly_warmup_schedule(lr, total_steps=100, warmup=0.1)
    tx = lamb(sched, weight_decay=0.01,
              weight_decay_mask=default_weight_decay_mask)
    step_fn = build_pretrain_step(model, tx, schedule=sched,
                                  accum_steps=accum)
    sample = _batch(accum=accum)
    init_fn = lambda rng: model.init(
        rng, jnp.asarray(sample["input_ids"][0]),
        jnp.asarray(sample["token_type_ids"][0]),
        jnp.asarray(sample["attention_mask"][0]))
    return model, tx, step_fn, init_fn


def test_sharded_state_init_and_steps_reduce_loss():
    m = mesh_lib.make_mesh()  # all 8 devices on data
    _, _, step_fn, init_fn = _make()
    with mesh_lib.logical_rules():
        state, shardings = make_sharded_state(
            jax.random.PRNGKey(0), init_fn, _make()[1], mesh=m)
    assert int(state.step) == 0
    # state actually sharded over the mesh (replicated params but mesh-placed)
    leaf = jax.tree.leaves(state.params)[0]
    assert leaf.sharding.mesh.shape["data"] == 8 or leaf.sharding.is_fully_replicated

    jit_step = jax.jit(step_fn, donate_argnums=(0,))
    batch = {k: jnp.asarray(v) for k, v in _batch().items()}
    losses = []
    with m:
        for i in range(5):
            state, metrics = jit_step(state, batch, jax.random.PRNGKey(i))
            losses.append(float(metrics["loss"]))
    assert int(state.step) == 5
    assert losses[-1] < losses[0], losses
    assert np.isfinite(losses).all()


def test_accumulation_matches_full_batch():
    """accum=2 over the same 16 samples must produce the same update as
    accum=1 (dropout off). The reference's accumulation loop pre-divided the
    loss (run_pretraining.py:436); here grads are averaged — same math."""
    _, tx1, step1, init_fn = _make(accum=1)
    _, tx2, step2, _ = _make(accum=2)

    state1, _ = make_sharded_state(jax.random.PRNGKey(0), init_fn, tx1)
    state2 = TrainState(step=state1.step, params=state1.params,
                        opt_state=state1.opt_state)

    b1 = {k: jnp.asarray(v) for k, v in _batch(accum=1).items()}
    b2 = {k: jnp.asarray(v) for k, v in _batch(accum=2).items()}
    s1, m1 = jax.jit(step1)(state1, b1, jax.random.PRNGKey(7))
    s2, m2 = jax.jit(step2)(state2, b2, jax.random.PRNGKey(7))

    np.testing.assert_allclose(float(m1["loss"]), float(m2["loss"]),
                               rtol=1e-5)
    p1 = jax.tree.leaves(s1.params)
    p2 = jax.tree.leaves(s2.params)
    for a, b in zip(p1, p2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-6)


def test_checkpoint_roundtrip_and_rolling_window(tmp_path):
    _, tx, step_fn, init_fn = _make()
    state, _ = make_sharded_state(jax.random.PRNGKey(0), init_fn, tx)
    batch = {k: jnp.asarray(v) for k, v in _batch().items()}
    jit_step = jax.jit(step_fn)
    for i in range(2):
        state, _ = jit_step(state, batch, jax.random.PRNGKey(i))

    mgr = CheckpointManager(str(tmp_path / "ckpts"), max_to_keep=3)
    sampler_state = {"epoch": 1, "index": 32, "world_size": 1,
                     "total_size": 64, "seed": 0}
    for step in (2, 4, 6, 8):
        mgr.save(step, state, extra={"sampler": sampler_state, "epoch": 1})
    mgr.wait()
    assert mgr.latest_step() == 8

    abstract = jax.eval_shape(lambda: state)
    restored, extra, step = mgr.restore(abstract)
    assert step == 8
    assert extra["sampler"]["index"] == 32
    for a, b in zip(jax.tree.leaves(state.params),
                    jax.tree.leaves(restored.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    # rolling window: only 3 most recent kept (reference kept 3,
    # run_pretraining.py:513-516)
    steps = sorted(mgr._mgr.all_steps())
    assert steps == [4, 6, 8]
    mgr.close()


def test_resume_missing_dir_raises(tmp_path):
    mgr = CheckpointManager(str(tmp_path / "empty"))
    assert mgr.latest_step() is None
    with pytest.raises(FileNotFoundError):
        mgr.restore(None)
    mgr.close()


def test_metric_logger_sinks(tmp_path):
    prefix = str(tmp_path / "run")
    lg = MetricLogger(log_prefix=prefix, verbose=True, jsonl=True,
                      stream=open(os.devnull, "w"))
    lg.log("train", 1, loss=2.5, learning_rate=1e-3)
    lg.log("train", 2, loss=2.0, learning_rate=2e-3)
    lg.info("hello")
    lg.close()

    txt = open(prefix + ".txt").read()
    assert "step 1" in txt and "hello" in txt
    rows = open(prefix + "_metrics.csv").read().strip().splitlines()
    assert len(rows) == 3  # header + 2
    recs = [json.loads(l) for l in open(prefix + ".jsonl")]
    assert recs[0]["loss"] == 2.5 and recs[1]["step"] == 2

    silent = MetricLogger(log_prefix=str(tmp_path / "no"), verbose=False)
    silent.log("train", 1, loss=1.0)
    assert not os.path.exists(str(tmp_path / "no.txt"))


def test_schedules_shapes_and_offset():
    s = schedulers.poly_warmup_schedule(6e-3, total_steps=100, warmup=0.1)
    assert float(s(0)) < float(s(9))          # warming up
    # at progress == warmup the decay branch applies (reference semantics:
    # `if progress < warmup` warm else decay, src/schedulers.py:126-139)
    np.testing.assert_allclose(float(s(10)), 6e-3 * (1 - 0.1) ** 0.5,
                               rtol=1e-3)
    assert float(s(50)) < float(s(10))        # decaying
    np.testing.assert_allclose(float(s(50)), 6e-3 * (1 - 0.5) ** 0.5,
                               rtol=1e-2)

    # two-phase: offset shifts the schedule so phase-2 restarts its warmup
    # (replaces the reference's optimizer-state rewrite,
    # run_pretraining.py:288-299)
    s2 = schedulers.poly_warmup_schedule(4e-3, total_steps=100, warmup=0.1,
                                         offset=7038)
    np.testing.assert_allclose(float(s2(7038)), float(
        schedulers.poly_warmup_schedule(4e-3, 100, warmup=0.1)(0)))
    for name in ("linear", "cosine", "constant"):
        sc = schedulers.make_schedule(name, 1e-3, 100, warmup=0.1)
        assert np.isfinite(float(sc(0))) and np.isfinite(float(sc(99)))


def test_gathered_step_matches_dense_step():
    """A train step with max_predictions (gathered MLM head) must produce the
    same loss/metrics/update as the dense step (dropout off, P >= masked)."""
    model, tx, dense_step, init_fn = _make()
    gath_step = build_pretrain_step(
        model, tx, schedule=schedulers.poly_warmup_schedule(
            1e-3, total_steps=100, warmup=0.1),
        accum_steps=1, max_predictions=4)

    state0 = make_sharded_state(jax.random.PRNGKey(0), init_fn, tx)[0]
    state1 = make_sharded_state(jax.random.PRNGKey(0), init_fn, tx)[0]
    batch = {k: jnp.asarray(v) for k, v in _batch().items()}

    sd, md = jax.jit(dense_step)(state0, batch, jax.random.PRNGKey(1))
    sg, mg = jax.jit(gath_step)(state1, batch, jax.random.PRNGKey(1))
    np.testing.assert_allclose(float(mg["loss"]), float(md["loss"]),
                               rtol=1e-5)
    np.testing.assert_allclose(float(mg["mlm_accuracy"]),
                               float(md["mlm_accuracy"]), rtol=1e-6)
    for pd, pg in zip(jax.tree.leaves(sd.params), jax.tree.leaves(sg.params)):
        np.testing.assert_allclose(np.asarray(pg), np.asarray(pd),
                                   rtol=2e-4, atol=2e-5)


def test_chain_steps_matches_sequential():
    """chain_steps(k) (the device-side --steps_per_loop fori_loop) must
    produce the same state and final metrics as k sequential dispatches
    driven with the same fold_in rng derivation."""
    from bert_pytorch_tpu.training.pretrain import chain_steps

    _, tx, step_fn, init_fn = _make()
    base = jax.random.PRNGKey(7)

    state_a, _ = make_sharded_state(jax.random.PRNGKey(0), init_fn, tx)
    batch = {k: jnp.asarray(v) for k, v in _batch().items()}
    for i in range(3):
        state_a, metrics_a = jax.jit(step_fn)(
            state_a, batch, jax.random.fold_in(base, i))

    state_b, _ = make_sharded_state(jax.random.PRNGKey(0), init_fn, tx)
    chained = jax.jit(chain_steps(step_fn, 3))
    state_b, metrics_b = chained(state_b, batch, base)

    assert int(state_b.step) == 3
    np.testing.assert_allclose(float(metrics_a["loss"]),
                               float(metrics_b["loss"]), rtol=1e-5)
    jax.tree.map(lambda a, b: np.testing.assert_allclose(a, b, rtol=1e-5,
                                                         atol=1e-6),
                 state_a.params, state_b.params)


def test_chain_steps_per_step_batch():
    """per_step_batch=True consumes a (k, accum, micro, ...) stack — each
    inner step must see ITS slice (verify against manual sequential feed)."""
    from bert_pytorch_tpu.training.pretrain import chain_steps

    _, tx, step_fn, init_fn = _make()
    base = jax.random.PRNGKey(11)
    batches = [_batch(seed=s) for s in range(3)]
    stacked3 = {k: jnp.asarray(np.stack([b[k] for b in batches]))
                for k in batches[0]}

    state_a, _ = make_sharded_state(jax.random.PRNGKey(0), init_fn, tx)
    for i, b in enumerate(batches):
        state_a, metrics_a = jax.jit(step_fn)(
            state_a, {k: jnp.asarray(v) for k, v in b.items()},
            jax.random.fold_in(base, i))

    state_b, _ = make_sharded_state(jax.random.PRNGKey(0), init_fn, tx)
    chained = jax.jit(chain_steps(step_fn, 3, per_step_batch=True))
    state_b, metrics_b = chained(state_b, stacked3, base)

    np.testing.assert_allclose(float(metrics_a["loss"]),
                               float(metrics_b["loss"]), rtol=1e-5)
    jax.tree.map(lambda a, b: np.testing.assert_allclose(a, b, rtol=1e-5,
                                                         atol=1e-6),
                 state_a.params, state_b.params)


def test_bf16_grad_step_tracks_fp32():
    """grad_dtype=bfloat16 (grads accumulated in compute dtype against fp32
    masters, the apex-O2 equivalent) must track the fp32-grad trajectory:
    same descending loss within bf16 tolerance after several steps."""
    model = BertForPreTraining(TINY, dtype=jnp.float32)
    sched = schedulers.poly_warmup_schedule(1e-3, total_steps=100, warmup=0.1)
    tx = lamb(sched, weight_decay=0.01,
              weight_decay_mask=default_weight_decay_mask)
    step32 = build_pretrain_step(model, tx, schedule=sched)
    step16 = build_pretrain_step(model, tx, schedule=sched,
                                 grad_dtype=jnp.bfloat16)
    sample = _batch()
    init_fn = lambda rng: model.init(
        rng, jnp.asarray(sample["input_ids"][0]),
        jnp.asarray(sample["token_type_ids"][0]),
        jnp.asarray(sample["attention_mask"][0]))
    batch = {k: jnp.asarray(v) for k, v in sample.items()}

    s32, _ = make_sharded_state(jax.random.PRNGKey(0), init_fn, tx)
    s16, _ = make_sharded_state(jax.random.PRNGKey(0), init_fn, tx)
    l32 = l16 = None
    for i in range(6):
        s32, m32 = jax.jit(step32)(s32, batch, jax.random.PRNGKey(i))
        s16, m16 = jax.jit(step16)(s16, batch, jax.random.PRNGKey(i))
        l32, l16 = float(m32["loss"]), float(m16["loss"])
    # params stay fp32 masters in both cases
    assert jax.tree.leaves(s16.params)[0].dtype == jnp.float32
    assert abs(l32 - l16) / abs(l32) < 0.02, (l32, l16)


def test_lamb_per_layer_trust_ratio():
    """A [L, ...] stacked tensor with trust_batch_axes=1 must get the same
    update as L separate tensors fed through LAMB individually (apex saw L
    tensors; the scan encoder stores one stacked tensor)."""
    from bert_pytorch_tpu.optim.lamb import lamb as make_lamb

    rng = np.random.RandomState(0)
    stacked_p = jnp.asarray(rng.randn(3, 4, 5).astype(np.float32))
    stacked_g = jnp.asarray(rng.randn(3, 4, 5).astype(np.float32) * 0.1)

    tx_stacked = make_lamb(0.1, max_grad_norm=None,
                           trust_batch_axes=lambda p: jax.tree.map(
                               lambda _: 1, p))
    st = tx_stacked.init({"w": stacked_p})
    upd_stacked, _ = tx_stacked.update({"w": stacked_g}, st, {"w": stacked_p})

    tx_single = make_lamb(0.1, max_grad_norm=None)
    for i in range(3):
        sti = tx_single.init({"w": stacked_p[i]})
        upd_i, _ = tx_single.update({"w": stacked_g[i]}, sti,
                                    {"w": stacked_p[i]})
        np.testing.assert_allclose(np.asarray(upd_stacked["w"][i]),
                                   np.asarray(upd_i["w"]), rtol=1e-6)
