"""Packed-finetune tests: per-task packed-vs-unpadded loss parity (the
acceptance pin — BIT-equal for all five registered tasks), the finetune
packer's layout contract, length-bucketed eval, and the shared driver
end-to-end on the three new heads (run_finetune.py --packing with
real_tokens_per_sec perf records).

"Unpadded" is the degenerate packing — every example in its own row of
the SAME packed program (exactly how the serving scheduler defines
packing off); the single-segment baseline is built in the multi-segment
batch's row-major traversal order so the ordered-sum loss reductions
(models/losses._ordered_sum) see identical partial-sum sequences.
"""

import json
import os
import sys

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from bert_pytorch_tpu.data.packing import first_fit  # noqa: E402
from bert_pytorch_tpu.training.finetune import (  # noqa: E402
    bucketed_eval_batches, eval_buckets, pack_finetune_batch)

VOCAB = ["[PAD]", "[UNK]", "[CLS]", "[SEP]", "[MASK]"] + (
    "the cat sat on mat a dog did run in park bert serves packed "
    "rows red blue green fast slow").split()


def _tiny_config():
    from bert_pytorch_tpu.config import BertConfig

    return BertConfig(
        vocab_size=64, hidden_size=32, num_hidden_layers=1,
        num_attention_heads=4, intermediate_size=64,
        max_position_embeddings=64, hidden_dropout_prob=0.0,
        attention_probs_dropout_prob=0.0, fused_ops=False,
        attention_impl="xla")


def _examples(n=5, seq=48, group=1, seed=0):
    """Varied-length synthetic examples: (unit, [group]) arrays with a
    real-token prefix per sub-row."""
    rng = np.random.RandomState(seed)
    shape = (n, seq) if group == 1 else (n, group, seq)
    arrays = {
        "input_ids": np.zeros(shape, np.int32),
        "token_type_ids": np.zeros(shape, np.int32),
        "attention_mask": np.zeros(shape, np.int32),
    }
    lens = 4 + rng.randint(0, 10, (n, group))
    for i in range(n):
        for c in range(group):
            ln = int(lens[i, c])
            row = (i,) if group == 1 else (i, c)
            arrays["input_ids"][row][:ln] = rng.randint(5, 64, ln)
            arrays["token_type_ids"][row][ln // 2:ln] = 1
            arrays["attention_mask"][row][:ln] = 1
    return arrays, lens


def _pack_both(arrays, pack_labels, group=1, seq=48, max_segments=4):
    """(multi-segment packed batch, single-segment baseline) with the
    baseline's units in the multi batch's row-major traversal order, so
    ordered reductions see the same value sequence."""
    n = len(arrays["input_ids"])
    multi, placements = pack_finetune_batch(
        arrays, list(range(n)), n_rows=2, seq_len=seq,
        max_segments=max_segments, group_size=group)
    assert len(placements) == n, "fixture must fully pack"
    multi.update(pack_labels(arrays, placements, 2, seq, max_segments))
    order = [p.unit for p in sorted(placements,
                                    key=lambda p: (p.row, p.seg0))]
    single, sp = pack_finetune_batch(
        arrays, order, n_rows=n, seq_len=seq, max_segments=group,
        group_size=group)
    assert len(sp) == n and all(p.seg0 == 0 for p in sp)
    # label arrays keep the MULTI batch's G so both batches run the
    # SAME compiled program (one example per row = degenerate packing,
    # exactly the serving scheduler's packing-off mode)
    single.update(pack_labels(arrays, sp, n, seq, max_segments))
    return multi, single, order


def _apply(model, params, batch, extract=None):
    import jax.numpy as jnp

    out = model.apply(
        {"params": params}, jnp.asarray(batch["input_ids"]),
        jnp.asarray(batch["token_type_ids"]),
        jnp.asarray(batch["attention_mask"]), deterministic=True,
        position_ids=jnp.asarray(batch["position_ids"]),
        segment_ids=jnp.asarray(batch["segment_ids"]))
    return out if extract is None else extract(out)


# -- per-task parity: packed loss == unpadded loss, bit for bit ---------------


def test_parity_classify():
    import jax
    import jax.numpy as jnp

    from bert_pytorch_tpu.models import (BertForSequenceClassification,
                                         losses)
    from bert_pytorch_tpu.tasks.classify import pack_labels

    cfg = _tiny_config()
    arrays, _ = _examples()
    arrays["labels"] = np.array([0, 1, 1, 0, 1], np.int32)
    multi, single, order = _pack_both(arrays, pack_labels)

    model4 = BertForSequenceClassification(cfg, num_labels=2,
                                           max_segments=4,
                                           dtype=jnp.float32)
    s = jnp.zeros((1, 48), jnp.int32)
    params = model4.init(jax.random.PRNGKey(0), s, s, s)["params"]
    l_multi = float(losses.segment_classification_loss(
        _apply(model4, params, multi), jnp.asarray(multi["labels"])))
    l_single = float(losses.segment_classification_loss(
        _apply(model4, params, single), jnp.asarray(single["labels"])))
    assert l_multi == l_single  # BIT-equal, the acceptance pin
    # and the plain (no packing fields at all) path agrees to fp noise
    plain = model4.apply(
        {"params": params}, jnp.asarray(arrays["input_ids"]),
        jnp.asarray(arrays["token_type_ids"]),
        jnp.asarray(arrays["attention_mask"]), deterministic=True)
    l_plain = float(losses.segment_classification_loss(
        plain, jnp.asarray(arrays["labels"])))
    assert l_multi == pytest.approx(l_plain, abs=1e-6)


def test_parity_embed():
    import jax
    import jax.numpy as jnp

    from bert_pytorch_tpu.models import BertForSentenceEmbedding, losses
    from bert_pytorch_tpu.tasks.embed import pack_labels

    cfg = _tiny_config()
    arrays, _ = _examples(seed=1)
    arrays["labels"] = np.array([1, 0, 1, 0, 0], np.int32)
    multi, single, order = _pack_both(arrays, pack_labels)

    model4 = BertForSentenceEmbedding(cfg, num_labels=2, max_segments=4,
                                      dtype=jnp.float32)
    s = jnp.zeros((1, 48), jnp.int32)
    params = model4.init(jax.random.PRNGKey(0), s, s, s)["params"]
    take = lambda out: out[1]
    l_multi = float(losses.segment_classification_loss(
        _apply(model4, params, multi, take),
        jnp.asarray(multi["labels"])))
    l_single = float(losses.segment_classification_loss(
        _apply(model4, params, single, take),
        jnp.asarray(single["labels"])))
    assert l_multi == l_single
    # packed and single-segment embeddings are bit-equal row for row
    # (same (B, G, S) einsum structure, values merely offset); the
    # plain (B, 1, S) program agrees to fp noise and stays unit-norm
    emb_multi = np.asarray(_apply(model4, params, multi, lambda o: o[0]))
    emb_single = np.asarray(_apply(model4, params, single,
                                   lambda o: o[0]))
    seg_of = {}
    for row in range(multi["segment_ids"].shape[0]):
        for g in sorted(set(multi["segment_ids"][row]) - {0}):
            seg_of[(row, g)] = emb_multi[row, g - 1]
    flat = [seg_of[k] for k in sorted(seg_of)]  # traversal order
    assert len(flat) == 5
    # the un-normalized mean (and so the probe LOSS above) is bit-equal;
    # the final L2-norm reduces over E with a batch-shape-dependent
    # grouping, so cross-shape embeddings agree to last-bit noise only
    # (same-shape packed-vs-single bit-identity is pinned through the
    # serving demux in tests/test_task_registry.py)
    for i in range(5):
        np.testing.assert_allclose(flat[i], emb_single[i, 0],
                                   atol=1e-6, rtol=0)
    emb_plain, _ = model4.apply(
        {"params": params}, jnp.asarray(arrays["input_ids"]),
        jnp.asarray(arrays["token_type_ids"]),
        jnp.asarray(arrays["attention_mask"]), deterministic=True)
    emb_plain = np.asarray(emb_plain)
    for unit_emb in emb_plain:
        assert abs(np.linalg.norm(unit_emb) - 1.0) < 1e-5
    np.testing.assert_allclose(
        emb_single[:, 0], emb_plain[order], atol=1e-6, rtol=0)


def test_parity_choice():
    import jax
    import jax.numpy as jnp

    from bert_pytorch_tpu.models import BertForMultipleChoice, losses
    from bert_pytorch_tpu.tasks.choice import make_pack_labels

    cfg = _tiny_config()
    C = 2
    arrays, _ = _examples(n=4, group=C, seed=2)
    arrays["labels"] = np.array([1, 0, 0, 1], np.int32)
    multi, single, order = _pack_both(arrays, make_pack_labels(C),
                                      group=C)

    model4 = BertForMultipleChoice(cfg, num_choices=C, max_segments=4,
                                   dtype=jnp.float32)
    s = jnp.zeros((1, C, 48), jnp.int32)
    params = model4.init(jax.random.PRNGKey(0), s, s, s)["params"]
    l_multi = float(losses.choice_loss(
        _apply(model4, params, multi), jnp.asarray(multi["labels"]), C))
    l_single = float(losses.choice_loss(
        _apply(model4, params, single), jnp.asarray(single["labels"]), C))
    assert l_multi == l_single
    # the reference-shaped (B, C, S) path agrees to fp noise
    plain = model4.apply(
        {"params": params}, jnp.asarray(arrays["input_ids"]),
        jnp.asarray(arrays["token_type_ids"]),
        jnp.asarray(arrays["attention_mask"]), deterministic=True)
    l_plain = float(losses.choice_loss(plain, jnp.asarray(arrays["labels"]),
                                       C))
    assert l_multi == pytest.approx(l_plain, abs=1e-6)


def test_parity_squad():
    import jax
    import jax.numpy as jnp

    from bert_pytorch_tpu.models import BertForQuestionAnswering, losses
    from bert_pytorch_tpu.tasks.squad_task import pack_labels

    cfg = _tiny_config()
    arrays, lens = _examples(seed=3)
    rng = np.random.RandomState(3)
    n = len(arrays["input_ids"])
    arrays["start_positions"] = np.array(
        [rng.randint(1, lens[i, 0] - 1) for i in range(n)], np.int32)
    arrays["end_positions"] = np.minimum(
        arrays["start_positions"] + 2, lens[:, 0] - 1).astype(np.int32)
    multi, single, order = _pack_both(arrays, pack_labels)

    model = BertForQuestionAnswering(cfg, dtype=jnp.float32)
    s = jnp.zeros((1, 48), jnp.int32)
    params = model.init(jax.random.PRNGKey(0), s, s, s)["params"]

    def loss(batch, G):
        start, end = _apply(model, params, batch)
        return float(losses.packed_qa_loss(
            start, end, jnp.asarray(batch["start_positions"]),
            jnp.asarray(batch["end_positions"]),
            jnp.asarray(batch["segment_ids"]), G))

    assert loss(multi, 4) == loss(single, 4)


def test_parity_ner():
    import jax
    import jax.numpy as jnp

    from bert_pytorch_tpu.data.ner import IGNORE_LABEL
    from bert_pytorch_tpu.models import BertForTokenClassification, losses
    from bert_pytorch_tpu.tasks.ner_task import pack_labels

    cfg = _tiny_config()
    arrays, lens = _examples(seed=4)
    rng = np.random.RandomState(4)
    n, seq = arrays["input_ids"].shape
    labels = np.full((n, seq), IGNORE_LABEL, np.int32)
    for i in range(n):
        labels[i, 1:lens[i, 0] - 1] = rng.randint(1, 4, lens[i, 0] - 2)
    arrays["labels"] = labels
    multi, single, order = _pack_both(arrays, pack_labels)

    model = BertForTokenClassification(cfg, num_labels=4,
                                       dtype=jnp.float32)
    s = jnp.zeros((1, 48), jnp.int32)
    params = model.init(jax.random.PRNGKey(0), s, s, s)["params"]

    def loss(batch, G):
        logits = _apply(model, params, batch)
        return float(losses.packed_token_loss(
            logits, jnp.asarray(batch["labels"]),
            jnp.asarray(batch["segment_ids"]), G,
            ignore_index=IGNORE_LABEL))

    assert loss(multi, 4) == loss(single, 4)


# -- packer + bucketed eval mechanics -----------------------------------------


def test_first_fit_group_costs():
    # groups of 2 segments: 3 units of length 10 into rows of capacity
    # 24 with max_segments 4 -> two per row by segment quota
    bins = first_fit([10, 10, 10], n_bins=2, capacity=24,
                     max_segments=4, segs_per_unit=2)
    assert bins == [[0, 1], [2]]
    with pytest.raises(ValueError, match="capacity"):
        first_fit([30], n_bins=1, capacity=24, max_segments=4)


def test_pack_finetune_batch_layout():
    arrays, lens = _examples(n=4, seq=32, seed=5)
    batch, placements = pack_finetune_batch(
        arrays, [0, 1, 2, 3], n_rows=2, seq_len=32, max_segments=4)
    assert sorted(p.unit for p in placements) == [0, 1, 2, 3]
    for p in placements:
        ln = int(lens[p.unit, 0])
        sl = slice(p.offsets[0], p.offsets[0] + ln)
        np.testing.assert_array_equal(
            batch["input_ids"][p.row, sl],
            arrays["input_ids"][p.unit, :ln])
        np.testing.assert_array_equal(
            batch["segment_ids"][p.row, sl], p.seg0 + 1)
        np.testing.assert_array_equal(
            batch["position_ids"][p.row, sl], np.arange(ln))
    # mask == segment > 0 everywhere
    np.testing.assert_array_equal(batch["attention_mask"],
                                  (batch["segment_ids"] > 0).astype(np.int32))


def test_bucketed_eval_batches_trim_and_pad():
    arrays, lens = _examples(n=7, seq=48, seed=6)
    arrays["labels"] = np.arange(7, dtype=np.int32)
    buckets = eval_buckets(48, floor=8)
    seen = []
    for batch, idx, bucket in bucketed_eval_batches(
            arrays, 4, buckets, label_ignore={"labels": -1}):
        assert batch["input_ids"].shape == (4, bucket)
        assert int(lens[idx, 0].max()) <= bucket
        if len(idx) < 4:  # padded tail rows carry ignored labels
            assert (batch["labels"][len(idx):] == -1).all()
        seen.extend(int(i) for i in idx)
    assert sorted(seen) == list(range(7))


# -- driver e2e on the new heads ----------------------------------------------


@pytest.fixture
def finetune_env(tmp_path):
    vocab = tmp_path / "vocab.txt"
    vocab.write_text("\n".join(VOCAB) + "\n")
    cfg = {
        "vocab_size": len(VOCAB), "hidden_size": 32,
        "num_hidden_layers": 2, "num_attention_heads": 4,
        "intermediate_size": 64, "max_position_embeddings": 64,
        "hidden_dropout_prob": 0.0, "attention_probs_dropout_prob": 0.0,
        "fused_ops": False, "attention_impl": "xla", "lowercase": True,
        "tokenizer": "wordpiece", "vocab_file": str(vocab),
    }
    cfg_path = tmp_path / "model_config.json"
    cfg_path.write_text(json.dumps(cfg))

    rng = np.random.RandomState(0)
    words = [w for w in VOCAB if not w.startswith("[")]
    sent = lambda n: " ".join(rng.choice(words, n))
    cls_files = {}
    for split, n in (("train", 32), ("test", 12)):
        path = tmp_path / f"cls_{split}.tsv"
        with open(path, "w") as f:
            for i in range(n):
                lab = i % 2
                marker = "cat cat cat" if lab else "dog dog dog"
                f.write(f"{'positive' if lab else 'negative'}\t"
                        f"{marker} {sent(2 + i % 8)}\n")
        cls_files[split] = str(path)
    mc_path = tmp_path / "mc_train.jsonl"
    with open(mc_path, "w") as f:
        for i in range(16):
            lab = i % 2
            choices = [sent(2 + i % 4), sent(2 + (i + 1) % 4)]
            choices[lab] = "cat cat " + choices[lab]
            f.write(json.dumps({"question": sent(2), "choices": choices,
                                "label": lab}) + "\n")
    return tmp_path, str(cfg_path), cls_files, str(mc_path)


def _perf_records(path):
    return [json.loads(line) for line in
            open(path, encoding="utf-8").read().splitlines()
            if json.loads(line).get("tag") == "perf"]


def test_run_finetune_classify_packed_e2e(finetune_env):
    """The new-head acceptance pin: classification trains through
    run_finetune.py with --packing, LEARNS the marker task, and its perf
    records carry real_tokens_per_sec / pad_fraction end to end (plus
    the FINETUNE artifact for the perfboard gate)."""
    import run_finetune

    from bert_pytorch_tpu.telemetry import PERF_RECORD_CORE_KEYS

    tmp_path, cfg_path, cls_files, _ = finetune_env
    out = tmp_path / "out_cls"
    artifact = tmp_path / "FINETUNE_test.json"
    results = run_finetune.main([
        "--task", "classify",
        "--train_file", cls_files["train"],
        "--test_file", cls_files["test"],
        "--model_config_file", cfg_path,
        "--output_dir", str(out), "--epochs", "14", "--lr", "1e-3",
        "--batch_size", "8", "--max_seq_len", "32", "--dtype", "float32",
        "--packing", "--packing_max_segments", "4",
        "--perf_artifact", str(artifact)])
    assert results["test_accuracy"] > 0.8, results

    perf = _perf_records(out / "classify_log.jsonl")
    assert perf, "no perf records reached the classify jsonl sink"
    rec = perf[-1]
    assert set(PERF_RECORD_CORE_KEYS) <= set(rec), rec
    for key in ("real_tokens_per_sec", "pad_fraction",
                "packing_efficiency"):
        assert key in rec, key
    assert 0.0 < rec["packing_efficiency"] <= 1.0

    doc = json.loads(artifact.read_text())
    assert doc["kind"] == "finetune"
    task_rec = doc["tasks"]["classify"]
    assert task_rec["packing"] is True
    assert task_rec["real_tokens_per_sec"] > 0
    assert 0.0 <= task_rec["pad_fraction"] < 1.0

    # the saved checkpoint restores through the serving path (strict)
    import jax.numpy as jnp

    from bert_pytorch_tpu.config import BertConfig, pad_vocab_size
    from bert_pytorch_tpu.models import BertForSequenceClassification
    from bert_pytorch_tpu.serving.engine import restore_serving_params

    config = BertConfig.from_json_file(cfg_path)
    config = config.replace(vocab_size=pad_vocab_size(config.vocab_size, 8))
    model = BertForSequenceClassification(config, num_labels=2,
                                          max_segments=4,
                                          dtype=jnp.float32)
    _params, step = restore_serving_params(
        str(out / "ckpt"), model, 32, log=lambda m: None)
    assert step > 0


def test_run_finetune_embed_and_choice_packed_smoke(finetune_env):
    """The other two new heads through the same driver: short packed
    runs, perf records + artifact rows present (learning quality is
    classify's job — these pin the wiring)."""
    import run_finetune

    tmp_path, cfg_path, cls_files, mc_path = finetune_env
    artifact = tmp_path / "FINETUNE_test2.json"
    results = run_finetune.main([
        "--task", "embed", "--train_file", cls_files["train"],
        "--model_config_file", cfg_path,
        "--output_dir", str(tmp_path / "out_emb"),
        "--epochs", "1", "--lr", "1e-3", "--batch_size", "8",
        "--max_seq_len", "32", "--dtype", "float32", "--packing",
        "--perf_artifact", str(artifact)])
    assert results["embedding_norm_err"] < 1e-4

    run_finetune.main([
        "--task", "choice", "--train_file", mc_path,
        "--model_config_file", cfg_path, "--num_choices", "2",
        "--output_dir", str(tmp_path / "out_mc"),
        "--epochs", "1", "--lr", "1e-3", "--batch_size", "4",
        "--max_seq_len", "32", "--dtype", "float32", "--packing",
        "--packing_max_segments", "4",
        "--perf_artifact", str(artifact)])

    doc = json.loads(artifact.read_text())
    assert set(doc["tasks"]) == {"embed", "choice"}
    for rec in doc["tasks"].values():
        assert rec["real_tokens_per_sec"] > 0
        assert rec["packing"] is True
