"""Ring attention (sequence parallelism over the `seq` mesh axis) vs the
dense XLA reference path — forward, gradients, padding mask, dropout
semantics, and the dot_product_attention dispatch."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from bert_pytorch_tpu.ops import attention
from bert_pytorch_tpu.ops.ring_attention import ring_sharded
from bert_pytorch_tpu.parallel import mesh as mesh_lib

B, S, H, D = 4, 64, 4, 8


def _inputs(seed=0, masked=True):
    rng = np.random.RandomState(seed)
    q = jnp.asarray(rng.randn(B, S, H, D), jnp.float32)
    k = jnp.asarray(rng.randn(B, S, H, D), jnp.float32)
    v = jnp.asarray(rng.randn(B, S, H, D), jnp.float32)
    if masked:
        # realistic padding: each row attends to a prefix of 3/4..full length
        lens = rng.randint(3 * S // 4, S + 1, size=(B,))
        mask = (np.arange(S)[None, :] < lens[:, None]).astype(np.int32)
    else:
        mask = np.ones((B, S), np.int32)
    bias = attention.make_attention_bias(jnp.asarray(mask))
    return q, k, v, bias


def _dense(q, k, v, bias):
    return attention._xla_attention(q, k, v, bias, None, None, 0.0, True)


@pytest.mark.parametrize("shape", [
    {"data": 2, "seq": 4},
    {"data": 1, "fsdp": 2, "model": 2, "seq": 2},
])
def test_ring_matches_dense_forward(shape):
    mesh = mesh_lib.make_mesh(shape)
    q, k, v, bias = _inputs()
    want = _dense(q, k, v, bias)
    got = ring_sharded(mesh, q, k, v, bias, None, 0.0)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)


def test_ring_matches_dense_grads():
    mesh = mesh_lib.make_mesh({"data": 2, "seq": 4})
    q, k, v, bias = _inputs(seed=1)
    w = jnp.asarray(np.random.RandomState(9).randn(B, S, H, D), jnp.float32)

    def loss_ring(q, k, v):
        return jnp.sum(ring_sharded(mesh, q, k, v, bias, None, 0.0) * w)

    def loss_dense(q, k, v):
        return jnp.sum(_dense(q, k, v, bias) * w)

    gr = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
    gd = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gr, gd):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-5)


def test_ring_dropout_deterministic_and_scaled():
    """Same key -> same output; dropout zeroes value contributions without
    touching the softmax normalizer (dense semantics), so the output stays
    finite and differs from the no-dropout result."""
    mesh = mesh_lib.make_mesh({"data": 2, "seq": 4})
    q, k, v, bias = _inputs(seed=2)
    key = jax.random.PRNGKey(7)
    a1 = ring_sharded(mesh, q, k, v, bias, key, 0.5)
    a2 = ring_sharded(mesh, q, k, v, bias, key, 0.5)
    b1 = ring_sharded(mesh, q, k, v, bias, jax.random.PRNGKey(8), 0.5)
    clean = ring_sharded(mesh, q, k, v, bias, None, 0.0)
    np.testing.assert_array_equal(np.asarray(a1), np.asarray(a2))
    assert np.all(np.isfinite(np.asarray(a1)))
    assert not np.allclose(np.asarray(a1), np.asarray(clean))
    assert not np.allclose(np.asarray(a1), np.asarray(b1))
    # with the keep probability at 0.5 the expected magnitude is preserved;
    # a gross scaling bug (e.g. dividing l as well) would show up here
    ratio = float(jnp.mean(jnp.abs(a1)) / jnp.mean(jnp.abs(clean)))
    assert 0.5 < ratio < 2.0, ratio


def test_dispatch_routes_seq_sharded_mesh_to_ring():
    """dot_product_attention(impl='ring') under a seq-sharded ambient mesh
    must produce dense-exact output (and actually go through shard_map: a
    wrong out_spec or missing bias rotation would break parity)."""
    mesh = mesh_lib.make_mesh({"data": 2, "seq": 4})
    q, k, v, bias = _inputs(seed=3)
    want = _dense(q, k, v, bias)
    with mesh:
        got = attention.dot_product_attention(q, k, v, bias=bias,
                                              impl="ring")
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)


def test_ring_impl_without_mesh_falls_back_dense():
    q, k, v, bias = _inputs(seed=4)
    got = attention.dot_product_attention(q, k, v, bias=bias, impl="ring")
    want = _dense(q, k, v, bias)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-6, atol=1e-6)


# -- packed sequences on the ring (round 11) --------------------------------

def _packed_inputs(seed=0):
    """Multi-segment rows with a pad tail: segments deliberately straddle
    the S/4 = 16-wide ring-shard boundaries so masking must survive the
    K/V+segment slab rotation, not just local tiles."""
    rng = np.random.RandomState(seed)
    q = jnp.asarray(rng.randn(B, S, H, D), jnp.float32) * 0.5
    k = jnp.asarray(rng.randn(B, S, H, D), jnp.float32) * 0.5
    v = jnp.asarray(rng.randn(B, S, H, D), jnp.float32) * 0.5
    seg = np.zeros((B, S), np.int32)
    seg[0, :30] = 1
    seg[0, 30:50] = 2
    seg[0, 50:60] = 3   # row 0: pad tail from 60
    seg[1, :20] = 1
    seg[1, 20:64] = 2
    seg[2, :37] = 1     # odd split straddling shard 2
    seg[2, 37:55] = 2
    seg[3, :10] = 1
    seg[3, 10:22] = 2
    seg[3, 22:40] = 3
    return q, k, v, jnp.asarray(seg)


def _dense_seg(q, k, v, seg, bias=None):
    """Dense reference: additive q_seg==k_seg mask (the kernels' -1e30
    constant via make_segment_attention_bias), pad-query rows zeroed —
    the contract every other impl pins to."""
    b = attention.make_segment_attention_bias(seg)
    if bias is not None:
        b = b + bias
    out = attention._xla_attention(q, k, v, b, None, None, 0.0, True)
    return out * (seg > 0).astype(out.dtype)[:, :, None, None]


def test_ring_segments_match_dense_forward():
    """Packed rows through the ring (segment slab rotating with K/V) vs
    the block-diagonal dense reference, with and without an extra padding
    bias riding along."""
    mesh = mesh_lib.make_mesh({"data": 2, "seq": 4})
    q, k, v, seg = _packed_inputs()
    want = _dense_seg(q, k, v, seg)
    got = ring_sharded(mesh, q, k, v, None, None, 0.0, segment_ids=seg)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)
    # pad (segment-0) queries exact-zero — the flash kernels' pad contract
    pad = np.asarray(seg) == 0
    assert pad.any() and (np.asarray(got)[pad] == 0.0).all()
    # padding bias + segments compose (both rotate around the ring)
    bias = attention.make_attention_bias(jnp.asarray((np.asarray(seg) > 0)
                                                     .astype(np.int32)))
    want_b = _dense_seg(q, k, v, seg, bias)
    got_b = ring_sharded(mesh, q, k, v, bias, None, 0.0, segment_ids=seg)
    np.testing.assert_allclose(np.asarray(got_b), np.asarray(want_b),
                               rtol=1e-5, atol=1e-5)


def test_ring_segments_grads_match_dense():
    """Backward through the checkpointed ring scan with the segment slab:
    q/k/v grads vs the dense block-diagonal reference."""
    mesh = mesh_lib.make_mesh({"data": 2, "seq": 4})
    q, k, v, seg = _packed_inputs(seed=1)
    w = jnp.asarray(np.random.RandomState(9).randn(B, S, H, D), jnp.float32)

    def loss_ring(q, k, v):
        return jnp.sum(ring_sharded(mesh, q, k, v, None, None, 0.0,
                                    segment_ids=seg) * w)

    def loss_dense(q, k, v):
        return jnp.sum(_dense_seg(q, k, v, seg) * w)

    gr = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
    gd = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gr, gd):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-5)


def test_ring_segments_no_cross_contamination_bit_identical():
    """Rewriting every K/V position of segment 1 leaves the other
    segments' ring outputs BIT-identical — cross-segment probabilities
    underflow to exact 0.0 (the -1e30 constant), they are not merely
    small."""
    mesh = mesh_lib.make_mesh({"data": 2, "seq": 4})
    q, k, v, seg = _packed_inputs(seed=2)
    seg_np = np.asarray(seg)
    k2, v2 = np.asarray(k).copy(), np.asarray(v).copy()
    k2[seg_np == 1] = 3.3
    v2[seg_np == 1] = -2.7
    a = np.asarray(ring_sharded(mesh, q, k, v, None, None, 0.0,
                                segment_ids=seg))
    b = np.asarray(ring_sharded(mesh, q, jnp.asarray(k2), jnp.asarray(v2),
                                None, None, 0.0, segment_ids=seg))
    other = seg_np > 1
    np.testing.assert_array_equal(a[other], b[other])
    assert not np.allclose(a[seg_np == 1], b[seg_np == 1])


def test_dispatch_routes_packed_seq_sharded_mesh_to_ring():
    """dot_product_attention with segment_ids under a seq-sharded ambient
    mesh — the composition that raised NotImplementedError through round
    10 — now dispatches to the ring and matches the dense reference."""
    mesh = mesh_lib.make_mesh({"data": 2, "seq": 4})
    q, k, v, seg = _packed_inputs(seed=3)
    want = _dense_seg(q, k, v, seg)
    with mesh:
        got = attention.dot_product_attention(q, k, v, segment_ids=seg,
                                              impl="ring")
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)


def test_ring_under_jit_and_value_and_grad():
    """The production step jits the whole train step; ring attention must
    trace/compile under jit with grads (checkpointed scan + ppermute)."""
    mesh = mesh_lib.make_mesh({"data": 2, "seq": 4})
    q, k, v, bias = _inputs(seed=5)

    @jax.jit
    def step(q, k, v):
        def loss(q, k, v):
            return jnp.sum(ring_sharded(mesh, q, k, v, bias, None, 0.0) ** 2)
        return jax.value_and_grad(loss)(q, k, v)

    val, grad = step(q, k, v)
    assert np.isfinite(float(val))
    assert all(np.all(np.isfinite(np.asarray(g))) for g in (grad,))
