"""Tier-1 flight-recorder + replay tests on the 8-device CPU mesh.

The acceptance path: an injected-NaN pretraining run trips the health pack,
the flight recorder dumps a repro bundle next to the checkpoints, the run
halts NONZERO printing the bundle path, and tools/replay.py re-executes the
offending step from bundle + checkpoint reproducing the recorded loss and
health flags BIT-identically, with --bisect naming the first non-finite
model scope — under unpacked and packed batches, stacked and unstacked
encoder layouts. Plus: the ring-buffer memory bound (incl. under
prefetch+packing), crash-safe flush on exception and signal, and the
--validate schema check failing loudly on a corrupted bundle.
"""

import json
import math
import os
import shutil
import signal
import sys

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from bert_pytorch_tpu.telemetry.flight_recorder import (FlightRecorder,
                                                        validate_bundle)
from tests.test_data import write_shard  # noqa: E402

MODEL_CFG = {
    "vocab_size": 128, "hidden_size": 32, "num_hidden_layers": 2,
    "num_attention_heads": 4, "intermediate_size": 64,
    "max_position_embeddings": 64, "next_sentence": True,
    "hidden_dropout_prob": 0.0, "attention_probs_dropout_prob": 0.0,
    "tokenizer": "wordpiece", "fused_ops": False, "attention_impl": "xla",
}


def _workdir(root, varied=False, stacked=None):
    data = root / "data"
    data.mkdir(parents=True)
    for i in range(2):
        write_shard(data / f"shard_{i}.hdf5", 48 if varied else 32,
                    seed=i, varied=varied)
    cfg = dict(MODEL_CFG)
    if stacked is not None:
        cfg["stacked_params"] = stacked
    cfg_path = root / "model_config.json"
    cfg_path.write_text(json.dumps(cfg))
    return data, cfg_path


def _nan_argv(data, cfg_path, out, extra=()):
    """A run wired to blow up at step 3: the fault-injection drill poisons
    layer 0's attention output kernel in-graph, the health pack flags it,
    and --nonfinite_action=halt stops the run after the recorder dumps.
    Checkpoints every step so replay has a base within the ring."""
    return ["--model_config_file", str(cfg_path),
            "--input_dir", str(data), "--output_dir", str(out),
            "--mask_token_index", "3", "--dtype", "float32",
            "--vocab_pad_multiple", "8", "--learning_rate", "1e-3",
            "--global_batch_size", "32", "--local_batch_size", "2",
            "--max_steps", "5", "--max_predictions_per_seq", "5",
            "--num_steps_per_checkpoint", "1", "--log_freq", "2",
            "--zero1", "false", "--recorder_window", "4",
            "--inject_nonfinite_step", "3",
            "--nonfinite_action", "halt"] + list(extra)


def _bundles(out):
    d = os.path.join(out, "repro_bundles")
    return sorted(os.path.join(d, b) for b in os.listdir(d)) \
        if os.path.isdir(d) else []


@pytest.fixture(scope="module")
def nan_run(tmp_path_factory):
    """One injected-NaN e2e run (unpacked, stacked layout), shared by the
    replay / bisect / validate / halt tests below."""
    root = tmp_path_factory.mktemp("fr_nan")
    data, cfg_path = _workdir(root)
    out = root / "out"
    import run_pretraining

    rc = run_pretraining._cli(_nan_argv(data, cfg_path, out))
    bundles = _bundles(out)
    return {"rc": rc, "out": out, "bundles": bundles,
            "log": (out / "logfile.txt").read_text()}


# -- e2e: alarm -> dump -> nonzero halt --------------------------------------

def test_halt_exits_nonzero_and_prints_bundle(nan_run):
    """Satellite: --nonfinite_action=halt exits with the DISTINCT code 71
    (EXIT_NONFINITE_HALT — tools/supervise.py refuses to retry it; clean
    FATAL instead of a traceback) and the dumped bundle's path is in the
    logs."""
    from bert_pytorch_tpu.resilience import EXIT_NONFINITE_HALT

    assert nan_run["rc"] == EXIT_NONFINITE_HALT
    assert len(nan_run["bundles"]) == 1
    bundle = nan_run["bundles"][0]
    assert os.path.basename(bundle).startswith("step00000003_nonfinite")
    assert bundle in nan_run["log"]  # operator can copy-paste the path
    assert os.path.isfile(os.path.join(bundle, "manifest.json"))
    assert os.path.isfile(os.path.join(bundle, "batches.npz"))


def test_bundle_contents(nan_run):
    bundle = nan_run["bundles"][0]
    manifest = json.load(open(os.path.join(bundle, "manifest.json")))
    assert manifest["trigger_step"] == 3
    assert manifest["reason"] == "nonfinite"
    assert manifest["run"]["accum_steps"] == 2
    assert manifest["provenance"]["platform"] == "cpu"
    assert manifest["model_config"]["hidden_size"] == 32
    # ring window 4 held steps 1..3 (only 3 dispatched before the halt)
    assert [r["step"] for r in manifest["records"]] == [1, 2, 3]
    # the metrics tail recorded the flagged step; the NaN loss is
    # serialized as the string 'nan' so manifest.json stays STRICT json
    # (parse_constant fires only on the lenient NaN/Infinity tokens)
    flagged = [m for m in manifest["metrics_tail"] if m["step"] == 3]
    assert flagged and flagged[0]["loss_nonfinite"] == 1
    assert math.isnan(float(flagged[0]["loss"]))
    raw = open(os.path.join(bundle, "manifest.json")).read()
    json.loads(raw, parse_constant=lambda s: pytest.fail(
        f"manifest.json is not strict JSON: bare {s} token"))


def test_bundle_manifest_v2_registry_and_tail_source(nan_run):
    """Manifest schema v2 (satellite): the bundle cross-refs the jsonl
    sink its metrics tail mirrors and carries the metrics-registry
    snapshot at dump time — the run's cumulative counters ride along,
    not just the last few records."""
    bundle = nan_run["bundles"][0]
    manifest = json.load(open(os.path.join(bundle, "manifest.json")))
    assert manifest["schema_version"] == 2
    src = manifest["metrics_tail_source"]
    assert src and src.endswith(".jsonl") and os.path.isfile(src)
    reg = manifest["registry"]
    assert isinstance(reg, dict) and reg, "registry snapshot missing"

    def series_value(name):
        (s,) = reg[name]["series"]
        assert s["labels"]["phase"] == "pretrain"
        return s["value"]

    # the run halted on step 3: the counters saw 3 steps, and the flagged
    # step had been counted by the time the alarm path dumped
    assert series_value("bert_train_steps_total") == 3
    assert series_value("bert_nonfinite_steps_total") >= 1
    assert reg["bert_xla_compiles_total"]["series"][0]["value"] > 0


def test_validate_fails_on_missing_v2_keys(nan_run, tmp_path):
    """--validate schema-checks the v2 cross-refs: a manifest stripped of
    its registry snapshot fails loudly at the door."""
    import tools.replay as replay

    stripped = tmp_path / "stripped_bundle"
    shutil.copytree(nan_run["bundles"][0], stripped)
    manifest = json.load(open(stripped / "manifest.json"))
    del manifest["registry"]
    manifest["metrics_tail_source"] = 12345  # wrong type
    (stripped / "manifest.json").write_text(json.dumps(manifest))
    res = replay.main(["--bundle", str(stripped), "--validate"])
    assert res["valid"] is False
    joined = " ".join(res["errors"])
    assert "registry" in joined
    assert replay._cli(["--bundle", str(stripped), "--validate"]) == 2


@pytest.fixture(scope="module")
def nan_replayed(nan_run):
    """One replay+bisect pass over the shared bundle (--bisect performs
    the full replay first), shared by the assertions below — every
    replay.main call re-jits the whole step program, so fold them."""
    import tools.replay as replay

    return replay.main(["--bundle", nan_run["bundles"][0], "--bisect"])


def test_replay_reproduces_bit_identically(nan_replayed):
    """THE acceptance property: replay from bundle + checkpoint reproduces
    the recorded loss and health flags bit-identically on CPU."""
    res = nan_replayed
    assert res["match"] is True, res["mismatches"]
    assert res["base_checkpoint"] == 2
    assert res["replayed"]["loss_nonfinite"] == 1
    assert res["replayed"]["grad_nonfinite"] > 0
    assert math.isnan(res["replayed"]["loss"])
    # recorded was NaN too (strict-json string), and _values_equal
    # treated NaN==NaN as reproduced
    assert math.isnan(float(res["recorded"]["loss"]))


def test_replay_bisect_names_guilty_scope(nan_replayed):
    """--bisect re-runs the offending forward with debug taps and blames
    layer 0's attention block — exactly where the drill injected the NaN
    (attention output kernel)."""
    res = nan_replayed
    bad = res["bisect"]["first_nonfinite"]
    assert bad is not None
    assert bad["scope"] == "layer_0/attention"
    # execution-order scope list says everything before it was finite
    scopes = res["bisect"]["scopes"]
    names = [s["scope"] for s in scopes]
    assert names.index("embeddings") < names.index("layer_0/attention")
    assert scopes[names.index("embeddings")]["finite"] is True


def test_replay_earlier_clean_step_matches(nan_run):
    """Replay is not NaN-specific: a clean recorded step (2) reproduces
    its finite loss bit-identically from checkpoint 1."""
    import tools.replay as replay

    res = replay.main(["--bundle", nan_run["bundles"][0], "--step", "2"])
    assert res["match"] is True, res["mismatches"]
    assert res["replayed"]["loss_nonfinite"] == 0
    assert math.isfinite(res["replayed"]["loss"])


# -- --validate schema check -------------------------------------------------

def test_validate_ok(nan_run):
    import tools.replay as replay

    res = replay.main(["--bundle", nan_run["bundles"][0], "--validate"])
    assert res["valid"] is True and res["errors"] == []
    assert replay._cli(["--bundle", nan_run["bundles"][0],
                        "--validate"]) == 0


def test_validate_fails_loudly_on_corrupt_bundle(nan_run, tmp_path):
    """Satellite: stale/corrupt bundles fail at the door with named
    errors, not mysteriously inside replay."""
    import tools.replay as replay

    corrupt = tmp_path / "corrupt_bundle"
    shutil.copytree(nan_run["bundles"][0], corrupt)
    manifest = json.load(open(corrupt / "manifest.json"))
    del manifest["run"]["accum_steps"]           # missing run key
    manifest["records"][0]["fields"].append("ghost_field")  # npz mismatch
    (corrupt / "manifest.json").write_text(json.dumps(manifest))

    res = replay.main(["--bundle", str(corrupt), "--validate"])
    assert res["valid"] is False
    joined = " ".join(res["errors"])
    assert "accum_steps" in joined and "ghost_field" in joined
    assert replay._cli(["--bundle", str(corrupt), "--validate"]) == 2
    # and a non-validate replay refuses up front with the same errors
    with pytest.raises(replay.ReplayError, match="schema"):
        replay.main(["--bundle", str(corrupt)])

    # a bundle missing its arrays entirely is caught too
    (corrupt / "batches.npz").unlink()
    assert validate_bundle(str(corrupt)) == \
        [f"no batches.npz under {corrupt}"]


# -- packed + unstacked acceptance variants ----------------------------------

def test_nan_e2e_replay_packed(tmp_path):
    """Acceptance: the same alarm -> dump -> replay -> bisect loop under
    --packing (segment fields ride the bundle and thread back through
    _packed_kwargs on replay)."""
    data, cfg_path = _workdir(tmp_path, varied=True)
    out = tmp_path / "out_packed"
    import run_pretraining
    import tools.replay as replay

    rc = run_pretraining._cli(_nan_argv(
        data, cfg_path, out,
        extra=["--packing", "--packing_max_segments", "4"]))
    assert rc == 71  # EXIT_NONFINITE_HALT (docs/RESILIENCE.md)
    (bundle,) = _bundles(out)
    manifest = json.load(open(os.path.join(bundle, "manifest.json")))
    assert manifest["run"]["packing"] is True
    assert "segment_ids" in manifest["records"][0]["fields"]
    assert "nsp_positions" in manifest["records"][0]["fields"]

    res = replay.main(["--bundle", bundle, "--bisect"])
    assert res["match"] is True, res["mismatches"]
    assert res["replayed"]["loss_nonfinite"] == 1
    assert res["bisect"]["first_nonfinite"]["scope"] == "layer_0/attention"


@pytest.mark.slow  # re-tiered out of tier-1's 870s wall-clock budget
def test_nan_e2e_chunked_dispatch_unstacked(tmp_path):
    """--steps_per_loop > 1, under the UNSTACKED encoder layout (the
    bundle round-trips through restore_either_layout and the per-layer
    debug taps): the window auto-clamps to 2 chunks so the one-dispatch
    metric lag cannot evict the flagged chunk; the sticky trigger step
    (chunk-final) replays bit-identically through the same chain_steps
    program; and --step reaches the INNER chunk step where the NaN
    actually fired, including --bisect."""
    data, cfg_path = _workdir(tmp_path, stacked=False)
    out = tmp_path / "out_chunked"
    import run_pretraining
    import tools.replay as replay

    # inject at step 3 = inner step of chunk {3,4}; window 1 forces the
    # clamp to 2*steps_per_loop=4; global batch 16 = accum 1 (accum>1
    # replay is the module fixture's job — keep this run's compiles lean)
    rc = run_pretraining._cli(_nan_argv(
        data, cfg_path, out,
        extra=["--steps_per_loop", "2", "--recorder_window", "1",
               "--global_batch_size", "16"]))
    assert rc == 71  # EXIT_NONFINITE_HALT (docs/RESILIENCE.md)
    (bundle,) = _bundles(out)
    manifest = json.load(open(os.path.join(bundle, "manifest.json")))
    assert manifest["model_config"]["stacked_params"] is False
    # sticky chain flags land on the chunk-final step
    assert manifest["trigger_step"] == 4
    recs = {r["step"]: r for r in manifest["records"]}
    # clamp held chunk {3,4} intact despite the step-5 partial dispatch
    assert {3, 4} <= set(recs) and recs[3]["pos"] == 0 \
        and recs[3]["n_steps"] == 2
    # chunk-final target: dispatch-faithful replay with bit-identical
    # sticky metrics — and bisect there sees only the CONSEQUENCE: step
    # 3's applied NaN update poisoned the params (halt != skip), so step
    # 4's forward dies at the first scope
    res = replay.main(["--bundle", bundle, "--bisect"])
    assert res["match"] is True, res["mismatches"]
    assert res["replayed"]["loss_nonfinite"] == 1
    assert res["bisect"]["first_nonfinite"]["scope"] == "embeddings"
    # inner chunk step: reachable via --step (no recorded per-step
    # metrics to compare — match stays None); the NaN fired right there,
    # and bisect names the CAUSE. This asymmetry is exactly why --step
    # must reach inner chunk steps.
    res = replay.main(["--bundle", bundle, "--step", "3", "--bisect"])
    assert res["match"] is None and res["recorded"] is None
    assert res["replayed"]["loss_nonfinite"] == 1
    assert res["bisect"]["first_nonfinite"]["scope"] == "layer_0/attention"


# -- ring-buffer memory bound ------------------------------------------------

def _fake_batch(i, batch=4, seq=8):
    return {"input_ids": np.full((batch, seq), i, np.int32),
            "attention_mask": np.ones((batch, seq), np.int32)}


def test_ring_buffer_bound():
    rec = FlightRecorder("/tmp/unused_fr", window=3)
    per_batch = sum(v.nbytes for v in _fake_batch(0).values())
    for i in range(10):
        rec.capture_batch(_fake_batch(i))
        rec.record_dispatch(i + 1, 1, np.zeros(2, np.uint32))
    assert [r["step"] for r in rec._records] == [8, 9, 10]
    assert rec.nbytes() <= 3 * per_batch
    # staging is cleared by every dispatch bind
    assert rec._staged == []
    # newest batch data survived, oldest evicted
    assert rec._records[-1]["batch"]["input_ids"][0, 0] == 9


def test_ring_buffer_bound_chunked_dispatch():
    """--steps_per_loop n consumes n ring slots per dispatch; the bound is
    still in BATCHES."""
    rec = FlightRecorder("/tmp/unused_fr", window=4)
    step = 0
    for _ in range(3):
        for _ in range(2):
            rec.capture_batch(_fake_batch(step))
            step += 1
        rec.record_dispatch(step - 1, 2, np.zeros(2, np.uint32))
    assert len(rec._records) == 4
    assert [r["pos"] for r in rec._records] == [0, 1, 0, 1]


def test_ring_buffer_bound_under_prefetch_and_packing(tmp_path):
    """Satellite: the bound holds against the real loader with the
    prefetch executor running ahead and the packer's carry-over buffer in
    play — the tap fires at yield, so the ring never sees more than
    `window` batches no matter how far assembly runs ahead."""
    from bert_pytorch_tpu.data.sharded import (HostShardSampler,
                                               PretrainingDataLoader,
                                               ShardIndex)

    for i in range(2):
        write_shard(tmp_path / f"shard_{i}.hdf5", 48, seed=i, varied=True)
    index = ShardIndex(sorted(str(p) for p in tmp_path.glob("*.hdf5")))
    sampler = HostShardSampler(len(index), world_size=1, rank=0, seed=0)
    rec = FlightRecorder(str(tmp_path / "fr"), window=2)
    loader = PretrainingDataLoader(
        index, sampler, batch_size=8, mask_token_index=3,
        max_pred_per_seq=5, masked_lm_prob=0.15, vocab_size=128, seed=0,
        prefetch_batches=2, packing=True, packing_max_segments=4,
        batch_tap=rec.capture_batch)
    try:
        it = iter(loader)
        per_batch = None
        for step in range(1, 6):
            batch = next(it)
            if per_batch is None:
                per_batch = sum(np.asarray(v).nbytes
                                for v in batch.values())
            rec.record_dispatch(step, 1, np.zeros(2, np.uint32))
            assert len(rec._records) <= 2
            # staging + ring together stay within one extra batch of the
            # window (at most one staged batch awaits its dispatch bind)
            assert rec.nbytes() <= 3 * per_batch
    finally:
        loader.close()


# -- crash safety ------------------------------------------------------------

def test_crash_flush_dumps_bundle_and_metrics(tmp_path, monkeypatch):
    """Satellite: a mid-run crash (any exception unwinding main) flushes
    the buffered metric record AND dumps a crash bundle before teardown."""
    import run_pretraining
    from bert_pytorch_tpu.parallel import mesh as mesh_lib

    data, cfg_path = _workdir(tmp_path)
    out = tmp_path / "out_crash"
    calls = {"n": 0}
    real = mesh_lib.host_to_device_batch

    def boom(*a, **kw):
        calls["n"] += 1
        if calls["n"] == 3:
            raise RuntimeError("simulated mid-run crash")
        return real(*a, **kw)

    monkeypatch.setattr(mesh_lib, "host_to_device_batch", boom)
    argv = ["--model_config_file", str(cfg_path), "--input_dir", str(data),
            "--output_dir", str(out), "--mask_token_index", "3",
            "--dtype", "float32", "--vocab_pad_multiple", "8",
            "--learning_rate", "1e-3", "--global_batch_size", "32",
            "--local_batch_size", "2", "--max_steps", "5",
            "--max_predictions_per_seq", "5", "--skip_checkpoint",
            "--log_freq", "10", "--zero1", "false"]
    with pytest.raises(RuntimeError, match="simulated"):
        run_pretraining.main(argv)

    log = (out / "logfile.txt").read_text()
    # pending metrics of the last dispatched step landed (step 2 was in
    # flight when the crash hit before dispatch 3)
    assert "step 2" in log
    # the partial StepWatch interval flushed (log_freq 10 never reached)
    assert "[perf]" in log
    (bundle,) = _bundles(out)
    assert "runtimeerror" in os.path.basename(bundle)
    manifest = json.load(open(os.path.join(bundle, "manifest.json")))
    assert manifest["reason"] == "runtimeerror"
    assert [r["step"] for r in manifest["records"]] == [1, 2]


def test_signal_handler_maps_to_systemexit(tmp_path):
    """SIGTERM/SIGINT become SystemExit(128+sig) so the crash-flush except
    path runs; handlers restore on close()."""
    old_term = signal.getsignal(signal.SIGTERM)
    rec = FlightRecorder(str(tmp_path / "fr"))
    rec.install_crash_handlers()
    try:
        handler = signal.getsignal(signal.SIGTERM)
        assert handler == rec._on_signal
        with pytest.raises(SystemExit) as e:
            handler(signal.SIGTERM, None)
        assert e.value.code == 128 + signal.SIGTERM
    finally:
        rec.close()
    assert signal.getsignal(signal.SIGTERM) == old_term


def test_atexit_backstop_only_when_armed(tmp_path):
    rec = FlightRecorder(str(tmp_path / "fr"), window=2)
    rec.capture_batch(_fake_batch(0))
    rec.record_dispatch(1, 1, np.zeros(2, np.uint32))
    rec._atexit_dump()           # not armed: no dump
    assert rec.last_dump is None
    rec.arm()
    rec._atexit_dump()
    assert rec.last_dump is not None
    assert os.path.isdir(rec.last_dump)
    rec.close()


# -- StepWatch.flush (crash-safe partial interval) ---------------------------

def test_stepwatch_flush_partial_interval():
    from bert_pytorch_tpu.telemetry import StepWatch

    clock = [0.0]
    sw = StepWatch(flops_per_step=1e9, seqs_per_step=8, seq_len=64,
                   peak_flops=1e12, log_freq=10, time_fn=lambda: clock[0])
    assert sw.flush() is None          # nothing buffered
    with sw.phase("dispatch"):
        clock[0] += 0.5
    assert sw.step_done() is None      # below log_freq: buffered
    rec = sw.flush()
    assert rec is not None and rec["steps"] == 1
    assert rec["step_time_ms"] == pytest.approx(500.0)
    assert sw.flush() is None          # flushed: interval reset


def test_stepwatch_pause_excludes_eval_time():
    """sw.pause() keeps an epoch-boundary eval out of the next interval's
    wall clock (run_ner's val eval would otherwise inflate step_time_ms
    and deflate MFU for every epoch after the first)."""
    from bert_pytorch_tpu.telemetry import StepWatch

    clock = [0.0]
    sw = StepWatch(flops_per_step=1e9, seqs_per_step=8, seq_len=64,
                   peak_flops=1e12, log_freq=1, time_fn=lambda: clock[0])
    with sw.pause():
        clock[0] += 9.0                # eval: must not count
    with sw.phase("dispatch"):
        clock[0] += 0.25
    rec = sw.step_done()
    assert rec["step_time_ms"] == pytest.approx(250.0)


# -- multi-host bundle dirs (round 11) ---------------------------------------

def test_per_host_dir_suffixes_only_multiprocess(monkeypatch):
    """Single-process runs keep the round-10 bundle layout; multi-host runs
    get a per-process subdirectory so two hosts dumping the same trigger
    step never race the same bundle path."""
    import jax

    from bert_pytorch_tpu.telemetry.flight_recorder import per_host_dir

    assert per_host_dir("/out/repro_bundles") == "/out/repro_bundles"
    monkeypatch.setattr(jax, "process_count", lambda: 4)
    monkeypatch.setattr(jax, "process_index", lambda: 2)
    assert per_host_dir("/out/repro_bundles") == \
        "/out/repro_bundles/host00002"
