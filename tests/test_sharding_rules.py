"""The logical-axis-rules table (parallel/rules.py) + the sharding_rules
static-analysis pass.

Fast half: table resolution/overrides, the property-style derivation
test (every param/moment/K-FAC leaf resolves to a spec under all four
mesh shapes in both encoder layouts), the divisibility fallback at prime
shard counts, jax-free pass units, budget-schema coverage, and the
REFACTOR-NEUTRALITY pin: every pre-existing graphcheck combo's program
fingerprint (collective counts + donation hash) must be byte-identical
to its pre-rules-table value.

Slow-ish half: the wrong_axis gate drill — ONE leaf's expected spec
derived with a deliberately swapped mesh axis must make graphcheck exit
1 naming the rule, the leaf path, and both shardings.
"""

import json
import os
import sys
import types

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
from jax.sharding import NamedSharding, PartitionSpec as P  # noqa: E402

from bert_pytorch_tpu.analysis import hlo, passes  # noqa: E402
from bert_pytorch_tpu.parallel import mesh as mesh_lib  # noqa: E402
from bert_pytorch_tpu.parallel import rules  # noqa: E402
from tools import graphcheck  # noqa: E402

# the four production mesh configs the table must compose through
MESH_SHAPES = {
    "dp": {"data": 8},
    "dp_fsdp": {"data": 4, "fsdp": 2},
    "dp_mp": {"data": 2, "model": 4},
    "dp_seq": {"data": 2, "seq": 4},
}

# program fingerprints of every combo that existed BEFORE the rules-table
# refactor (round 15), computed from the round-13/14 graph_report.json.
# The refactor's contract is that the table re-derives EXACTLY the specs
# the scattered hand-written sites produced — so these may never move
# without an intentional, explained re-baseline.
PRE_RULES_FINGERPRINTS = {
    "pretrain_dp8": "2176737b2d666f7d",
    "pretrain_bf16_dp8": "2176737b2d666f7d",
    "zero1_dp8": "ec5b0319741e42bb",
    "zero1_overlap_dp8": "ec5b0319741e42bb",
    "kfac_zero1_dp8": "54b9780bcd9f851e",
    "serve_qa_b4_s64": "da12ecbcbb5c504d",
}


# --- table resolution ----------------------------------------------------


def test_base_table_is_the_legacy_flax_export():
    """mesh.DEFAULT_LOGICAL_AXIS_RULES is the resolved base view of the
    table — byte-for-byte the tuple the model/training code consumed
    before the refactor."""
    assert mesh_lib.DEFAULT_LOGICAL_AXIS_RULES == rules.resolve()
    assert rules.resolve()[0] == ("vocab", ("model", "fsdp"))
    assert dict(rules.resolve())["data"] == ("data", "fsdp")
    with pytest.raises(KeyError):
        rules.rule_for("no_such_logical_axis")


def test_mesh_config_names():
    assert rules.mesh_config(None) == "replicated"
    devs = jax.devices()
    assert rules.mesh_config(mesh_lib.make_mesh({"data": 8})) == "dp"
    assert rules.mesh_config(
        mesh_lib.make_mesh({"data": 4, "fsdp": 2})) == "dp_fsdp"
    assert rules.mesh_config(
        mesh_lib.make_mesh({"data": 2, "model": 4})) == "dp_mp"
    assert rules.mesh_config(
        mesh_lib.make_mesh({"data": 2, "seq": 4})) == "dp_seq"
    assert len(devs) >= 8


def test_config_override_machinery():
    """An override replaces its logical row on the named config ONLY;
    unknown logical names append; other configs see the base table."""
    over = {"dp_mp": (rules.Rule("embed_head", "model", "test override"),
                      rules.Rule("brand_new_axis", "seq"))}
    dp_mp = mesh_lib.make_mesh({"data": 2, "model": 4})
    dp = mesh_lib.make_mesh({"data": 8})
    resolved = dict(rules.resolve(dp_mp, overrides=over))
    assert resolved["embed_head"] == "model"
    assert resolved["brand_new_axis"] == "seq"
    # same table length + 1 (replace is in-place, append at the end)
    assert len(rules.resolve(dp_mp, overrides=over)) \
        == len(rules.BASE_RULES) + 1
    # a dp-only mesh is untouched by the dp_mp override
    assert dict(rules.resolve(dp, overrides=over))["embed_head"] is None
    # the only shipped override entry is the named production config,
    # whose RULE rows are identical to base (the name carries the feature
    # pack, not a different mapping)
    assert set(rules.CONFIG_OVERRIDES) == {rules.PRODUCTION_CONFIG}
    assert rules.CONFIG_OVERRIDES[rules.PRODUCTION_CONFIG] == ()


# --- the named production config (round 15) ------------------------------


@pytest.mark.parametrize("config", sorted(MESH_SHAPES))
def test_production_config_resolves_via_the_table(config):
    """The `production` mesh_config resolves through CONFIG_OVERRIDES
    under all four mesh shapes: rule rows identical to the mesh-derived
    base resolution (the override tuple is empty by design), and the
    feature pack engages exactly the axes the mesh can express."""
    mesh = mesh_lib.make_mesh(MESH_SHAPES[config])
    assert rules.resolve(mesh, config=rules.PRODUCTION_CONFIG) \
        == rules.resolve(mesh)
    feats = rules.production_features(mesh)
    sizes = dict(mesh.shape)
    assert feats["packing"] is True
    assert feats["zero1"] == feats["zero1_overlap"] \
        == (sizes.get("data", 1) > 1)
    assert feats["fsdp_overlap"] == (sizes.get("fsdp", 1) > 1)
    assert feats["ring_attention"] == (sizes.get("seq", 1) > 1)
    assert rules.production_qualifies(mesh)
    # every one of these meshes both qualifies AND resolves its state
    # shardings identically through the named config (construction under
    # production cannot diverge from the verified base derivation)
    abstract = _tiny_abstract_state(True)
    base = rules.train_state_shardings(abstract, mesh, zero1=True)
    prod = rules.train_state_shardings(
        abstract, mesh, zero1=True,
        table=rules.resolve(mesh, config=rules.PRODUCTION_CONFIG))
    for a, b in zip(jax.tree.leaves(base), jax.tree.leaves(prod)):
        assert a == b


def test_production_qualification_edges():
    """Qualification needs a non-trivial parallel axis the pack can use:
    no mesh / single-device meshes stay on base under --mesh_config=auto."""
    assert not rules.production_qualifies(None)
    one = mesh_lib.make_mesh({"data": 1}, devices=jax.devices()[:1])
    assert not rules.production_qualifies(one)
    feats = rules.production_features(one)
    assert feats["zero1_overlap"] is False \
        and feats["fsdp_overlap"] is False
    # a model-parallel-only mesh has nothing for the pack either (mp is
    # not a pack feature), but fsdp/seq/data each qualify
    mp_only = mesh_lib.make_mesh({"model": 8})
    assert not rules.production_qualifies(mp_only)
    assert rules.production_qualifies(mesh_lib.make_mesh({"fsdp": 8}))


# --- the property test: every leaf resolves under every config ----------


def _tiny_abstract_state(stacked: bool):
    from bert_pytorch_tpu.config import BertConfig
    from bert_pytorch_tpu.models import BertForPreTraining
    from bert_pytorch_tpu.optim.lamb import (default_trust_batch_axes,
                                             default_weight_decay_mask,
                                             lamb)
    from bert_pytorch_tpu.training.state import abstract_train_state

    cfg = BertConfig(
        vocab_size=128, hidden_size=32, num_hidden_layers=2,
        num_attention_heads=4, intermediate_size=64,
        max_position_embeddings=64, next_sentence=True,
        fused_ops=False, attention_impl="xla",
        hidden_dropout_prob=0.0, attention_probs_dropout_prob=0.0,
        stacked_params=stacked)
    model = BertForPreTraining(cfg)
    ids = jnp.zeros((2, 8), jnp.int32)

    def init_fn(r):
        return model.init(r, ids, ids, ids)

    tx = lamb(1e-3, weight_decay=0.01,
              weight_decay_mask=default_weight_decay_mask,
              trust_batch_axes=default_trust_batch_axes)
    with mesh_lib.logical_rules():
        return abstract_train_state(jax.random.PRNGKey(0), init_fn, tx)


@pytest.mark.parametrize("stacked", [True, False],
                         ids=["stacked", "unstacked"])
@pytest.mark.parametrize("config", sorted(MESH_SHAPES))
def test_every_state_leaf_resolves(config, stacked):
    """Under all four mesh shapes and both encoder layouts, EVERY
    param/moment leaf resolves through the table to a concrete
    NamedSharding with a rule label, specs only reference that mesh's
    axes, and the ZeRO-1 appended axis lands somewhere."""
    mesh = mesh_lib.make_mesh(MESH_SHAPES[config])
    abstract = _tiny_abstract_state(stacked)
    expected, labels = rules.train_state_expectations(
        abstract, mesh, zero1=True)
    assert len(expected) == len(labels) > 40
    axis_names = set(rules.MESH_AXES)
    for sh, label in zip(expected, labels):
        assert isinstance(sh, NamedSharding), (label, sh)
        assert label
        for entry in tuple(sh.spec):
            for ax in (entry if isinstance(entry, tuple) else (entry,)):
                assert ax is None or ax in axis_names
    # the appended-axis derivation fired (data >= 2 on every config)
    n_zero1 = sum("+zero1[data]" in lb for lb in labels)
    assert n_zero1 > 10, f"only {n_zero1} zero1-appended leaves"


def test_dp_mp_composition_vocab_moment():
    """On the mixed dp x mp mesh the tied-embedding moment composes the
    base (model, fsdp) vocab sharding WITH the appended data axis — the
    case the pre-table ad-hoc specs never covered (now also compiled and
    gated as the zero1_dp2_mp4 graphcheck combo)."""
    mesh = mesh_lib.make_mesh(MESH_SHAPES["dp_mp"])
    abstract = _tiny_abstract_state(True)
    expected, labels = rules.train_state_expectations(
        abstract, mesh, zero1=True)
    vocab_moments = [str(sh.spec) for sh, lb in zip(expected, labels)
                     if lb == "logical(vocab,embed_out)+zero1[data]"]
    assert vocab_moments, "no vocab-table moment leaf resolved"
    for spec in vocab_moments:
        assert "model" in spec and "data" in spec, spec


@pytest.mark.parametrize("config", sorted(MESH_SHAPES))
def test_kfac_leaves_resolve(config):
    """K-FAC factor/inverse placement resolves from the same table:
    divisible stacked leaves get the L-axis spec over KFAC_SHARD_AXES,
    2D sites and prime stacks stay replicated by design (None)."""
    from bert_pytorch_tpu.optim.kfac import state_shardings

    mesh = mesh_lib.make_mesh(MESH_SHAPES[config])
    tree = {
        "layers": {"site": {"A": jax.ShapeDtypeStruct((8, 5, 5), jnp.float32),
                            "G": jax.ShapeDtypeStruct((8, 4, 4), jnp.float32)}},
        "pooler": {"A": jax.ShapeDtypeStruct((5, 5), jnp.float32),
                   "G": jax.ShapeDtypeStruct((4, 4), jnp.float32)},
        "prime": {"A": jax.ShapeDtypeStruct((7, 5, 5), jnp.float32)},
    }
    flat = jax.tree.leaves(tree)
    placements = state_shardings(tree, mesh)
    assert len(placements) == len(flat)
    by_shape = {tuple(leaf.shape): sh
                for leaf, sh in zip(flat, placements)}
    shards = rules.shard_count(mesh, rules.KFAC_SHARD_AXES)
    if 8 % shards == 0 and shards > 1:
        assert isinstance(by_shape[(8, 5, 5)], NamedSharding)
        assert by_shape[(8, 5, 5)].spec == P(rules.KFAC_SHARD_AXES)
    assert by_shape[(5, 5)] is None       # 2D: replicated by design
    assert by_shape[(7, 5, 5)] is None    # prime stack: fallback


def test_strip_axis_spec_fsdp_use_layout():
    """The fsdp gather-on-use USE-layout derivation: fsdp stripped from
    every entry, joint shardings keep their other axes, trailing Nones
    trimmed (canonical PartitionSpec), non-fsdp specs untouched."""
    assert rules.strip_axis_spec(P("fsdp", None)) == P()
    assert rules.strip_axis_spec(P(("model", "fsdp"), None)) \
        == P("model")
    assert rules.strip_axis_spec(P(None, ("fsdp", "data"))) \
        == P(None, "data")
    assert rules.strip_axis_spec(P("data", None)) == P("data")
    assert rules.strip_axis_spec(None) is None
    # tree form: NamedShardings re-wrapped on the same mesh
    mesh = mesh_lib.make_mesh(MESH_SHAPES["dp_fsdp"])
    tree = {"w": NamedSharding(mesh, P("fsdp", None)),
            "b": NamedSharding(mesh, P())}
    out = rules.strip_axis_tree(tree, mesh)
    assert out["w"].spec == P() and out["b"].spec == P()


def test_divisibility_fallback_prime_shard_counts():
    """shard_append_spec at PRIME shard counts: nothing divides -> the
    base spec survives untouched (no ragged GSPMD split); divisible dims
    still take the axis. A stub mesh (only .shape is consulted) lets the
    test probe shard counts no 8-device mesh can express."""
    for n in (5, 7, 11):
        stub = types.SimpleNamespace(shape={"data": n})
        # prime-sized leaf: fallback keeps the base spec
        assert rules.shard_append_spec((13, 3), P(None, None), stub) \
            == P(None, None)
        # divisible dim: the axis lands on it
        assert rules.shard_append_spec((13, 3 * n), P(None, None), stub) \
            == P(None, "data")
        # already-used axis: untouched
        assert rules.shard_append_spec((3 * n,), P("data"), stub) \
            == P("data")
    # free-dim-first: data avoids stacking onto the model-sharded dim
    stub = types.SimpleNamespace(shape={"data": 2, "model": 2})
    assert rules.shard_append_spec((4, 4), P("model", None), stub) \
        == P("model", "data")


# --- refactor neutrality: fingerprints may not move ---------------------


def test_preexisting_combo_fingerprints_unchanged():
    """The rules table must re-derive EXACTLY the specs the hand-written
    sites produced: collective counts + donation hash of every
    pre-existing combo in the checked-in graph report are pinned to
    their pre-refactor values."""
    report = json.load(open(os.path.join(REPO, "results",
                                         "graph_report.json")))
    for name, want_hash in sorted(PRE_RULES_FINGERPRINTS.items()):
        assert name in report["combos"], f"combo {name} disappeared"
        fp = hlo.fingerprint_of(report["combos"][name])
        assert fp["hash"] == want_hash, (
            f"{name}: program fingerprint moved "
            f"({fp['hash']} != pinned {want_hash}) — the refactor is no "
            "longer behavior-neutral; if intentional, re-baseline AND "
            "update this pin with an explanation")
    # the new dp x mp combo exists alongside (not pinned: born this round)
    assert "zero1_dp2_mp4" in report["combos"]


# --- the pass itself (jax-free dict work) -------------------------------


def test_sharding_rules_pass_units():
    rows = [
        {"path": ".opt_state.mu['w']", "spec": "PartitionSpec('data',)",
         "expected_spec": "PartitionSpec('model',)",
         "rule": "logical(norm)+zero1[data]", "matches_expected": False},
        {"path": ".params['w']", "spec": "PartitionSpec()",
         "expected_spec": "PartitionSpec()", "rule": "replicated",
         "matches_expected": True},
        {"path": ".batch", "spec": None},  # no expectation: skipped
    ]
    findings = passes.check_sharding_rules({"inputs": rows},
                                           {"min_verified": 2})
    errs = [f for f in findings if f.severity == "error"]
    assert len(errs) == 1
    assert errs[0].leaf == ".opt_state.mu['w']"
    assert "logical(norm)+zero1[data]" in errs[0].message
    assert "PartitionSpec('data',)" in errs[0].message
    assert "PartitionSpec('model',)" in errs[0].message
    # the verified-leaf floor catches expectations failing open
    floor = passes.check_sharding_rules({"inputs": rows[2:]},
                                        {"min_verified": 2})
    assert passes.has_errors(floor)
    assert any("failed open" in f.message for f in floor)
    # clean report: one info naming the count
    ok = passes.check_sharding_rules({"inputs": rows[1:]},
                                     {"min_verified": 1})
    assert not passes.has_errors(ok)
    assert any("1 input leaves match" in f.message for f in ok)


def test_budgets_declare_sharding_rules_for_every_combo():
    """scripts/check_graph.sh runs the pass on every combo because every
    checked-in budget block declares it — and the jax-free schema check
    rejects a damaged block."""
    budgets = json.load(open(os.path.join(REPO, "results",
                                          "graph_budgets.json")))
    for name, combo in sorted(budgets["combos"].items()):
        sr = combo["expect"].get("sharding_rules")
        assert isinstance(sr, dict), f"{name}: no sharding_rules block"
        assert isinstance(sr.get("min_verified"), int) \
            and sr["min_verified"] > 0, (name, sr)
    assert graphcheck.validate_budgets(budgets) == []
    broken = json.loads(json.dumps(budgets))
    broken["combos"]["zero1_dp8"]["expect"]["sharding_rules"][
        "min_verified"] = -3
    errs = graphcheck.validate_budgets(broken)
    assert any("sharding_rules.min_verified" in e for e in errs)


def test_checked_in_report_verifies_cleanly():
    """The checked-in report's leaf tables pass the sharding_rules gate
    against the checked-in budgets — zero mismatches, floors met — via
    the same jax-free diff --validate-budgets runs."""
    report = json.load(open(os.path.join(REPO, "results",
                                         "graph_report.json")))
    budgets = json.load(open(os.path.join(REPO, "results",
                                          "graph_budgets.json")))
    per_combo = graphcheck.diff_reports(report["combos"], budgets)
    errs = [f for combo in per_combo.values() for f in combo
            if f.severity == "error"]
    assert errs == [], [str(e) for e in errs]
    # and the serve combo's per-bucket expectations were derived (not
    # skipped): its budget floor covers the param + batch leaves
    n = sum(1 for r in report["combos"]["serve_qa_b4_s64"]["inputs"]
            if r.get("matches_expected") is not None)
    assert n >= 20
    # K-FAC placement is NOT vacuously verified: the l8 combo (stacked
    # axis divides the dp8 shard count) must carry stacked-factor
    # expectations that all hold — the 2-layer kfac combo's factors
    # legitimately fall back to replicated (no expectation there)
    kf = [r for r in report["combos"]["kfac_zero1_l8_dp8"]["inputs"]
          if r.get("rule", "").startswith("kfac_stacked")]
    assert len(kf) >= 16
    assert all(r.get("matches_expected") for r in kf)


# --- the acceptance drill: compiled program vs swapped expectation ------


def test_wrong_axis_drill_names_rule_leaf_and_both_shardings(
        tmp_path, capsys):
    """graphcheck --inject wrong_axis derives ONE leaf's spec with
    data<->model swapped; the sharding_rules pass must exit 1 naming the
    deriving rule, the exact leaf path, and both shardings."""
    rc = graphcheck.main([
        "--combos", "zero1_dp8", "--report",
        str(tmp_path / "graph_report.json"),
        "--budgets", os.path.join(REPO, "results", "graph_budgets.json"),
        "--inject", "wrong_axis"])
    out = capsys.readouterr().out
    assert rc == 1
    assert "ERROR [sharding_rules]" in out
    assert "wrong_axis_drill[data<->model]" in out   # the rule label
    assert ".opt_state.mu" in out                    # the leaf path
    assert "PartitionSpec('data',)" in out           # compiled sharding
    assert "PartitionSpec('model',)" in out          # table-derived spec


def test_serve_bucket_expectations_are_derived_replicated():
    """The serving engine's per-bucket specs come from the table: on the
    default single-device engine every leaf resolves to a replicated
    placement (derived — the same call changes meaning on a sharded
    serving mesh), with the batch rows labeled by the 'data' rule."""
    from bert_pytorch_tpu.config import BertConfig
    from bert_pytorch_tpu.models import BertForQuestionAnswering
    from bert_pytorch_tpu.serving.engine import (BATCH_FIELDS,
                                                 bucket_input_expectations)

    cfg = BertConfig(
        vocab_size=64, hidden_size=16, num_hidden_layers=1,
        num_attention_heads=2, intermediate_size=32,
        max_position_embeddings=64, next_sentence=False,
        fused_ops=False, attention_impl="xla",
        hidden_dropout_prob=0.0, attention_probs_dropout_prob=0.0)
    model = BertForQuestionAnswering(cfg)
    expected, labels = bucket_input_expectations(model, 64)
    assert len(expected) == len(labels)
    assert labels.count("batch(data+fsdp)") == len(BATCH_FIELDS)
    for sh in expected:
        assert sh.is_fully_replicated  # 1-dev mesh: table says replicated
