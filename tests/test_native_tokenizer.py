"""Native (C++) WordPiece encoder: byte-exact parity with the Python spec in
data/tokenization.py, factory auto-selection, batch/array APIs, and the
throughput claim (SURVEY §2.3#7 — the reference's Rust `tokenizers` role)."""

import random

import pytest

from bert_pytorch_tpu.data.tokenization import (
    BertWordPieceTokenizer,
    get_wordpiece_tokenizer,
)

native = pytest.importorskip("bert_pytorch_tpu.native")
if not native.native_available():
    pytest.skip("native library not buildable here", allow_module_level=True)

VOCAB = {t: i for i, t in enumerate(
    ["[PAD]", "[UNK]", "[CLS]", "[SEP]", "[MASK]",
     "the", "quick", "brown", "fox", "jump", "##ed", "##s", "over", "lazy",
     "dog", "un", "##aff", "##able", "run", "##ning", ",", ".", "!", "?",
     "h", "##e", "##l", "##o", "caf", "你", "好"])}

CURATED = [
    "The quick brown fox jumped over the lazy dog.",
    "unaffable, running!  hello?",
    "Café CAFÉ café",                   # precomposed + combining accents
    "你好 world",                        # CJK spacing
    "  weird\tspacing and​ stuff ",  # nbsp/zero-width format chars
    "İstanbul İ",                       # one-to-many lowercase expansion
    "", "   ", "!!!",
    "x" * 250,                          # > max_input_chars_per_word
    "a\x00b � c",                  # NUL + replacement char mid-text
]


@pytest.fixture(scope="module")
def both():
    return (BertWordPieceTokenizer(VOCAB, lowercase=True),
            native.NativeWordPieceTokenizer(VOCAB, lowercase=True))


def assert_same(a, b, ctx=""):
    assert a.ids == b.ids, ctx
    assert a.tokens == b.tokens, ctx
    assert a.offsets == b.offsets, ctx
    assert a.type_ids == b.type_ids, ctx


def test_curated_parity(both):
    py, nat = both
    for txt in CURATED:
        assert_same(py.encode(txt), nat.encode(txt), repr(txt))
    # pair encoding: second sequence gets type_id 1 + its own [SEP]
    assert_same(py.encode("the fox", pair="lazy dog"),
                nat.encode("the fox", pair="lazy dog"))
    # no-specials mode (the NER/pipeline path)
    assert_same(py.encode("running dog", add_special_tokens=False),
                nat.encode("running dog", add_special_tokens=False))


def test_fuzz_parity(both):
    py, nat = both
    rng = random.Random(0)
    pools = [list(range(32, 127)),
             [0x00E9, 0x0130, 0x00DF, 0x4E2D, 0x6587, 0x0301, 0x05D0,
              0x0416, 0x1F600, 0x2014, 0xA0, 0x200B, 0x3000, 0xFFFD, 0x0]]
    for _ in range(300):
        s = "".join(chr(rng.choice(rng.choice(pools)))
                    for _ in range(rng.randint(0, 60)))
        assert_same(py.encode(s), nat.encode(s), repr(s))


def test_fuzz_parity_cased():
    py = BertWordPieceTokenizer(VOCAB, lowercase=False)
    nat = native.NativeWordPieceTokenizer(VOCAB, lowercase=False)
    rng = random.Random(1)
    for _ in range(100):
        s = "".join(chr(rng.choice(list(range(32, 127)) + [0x00C9, 0x4E2D]))
                    for _ in range(rng.randint(0, 40)))
        assert_same(py.encode(s), nat.encode(s), repr(s))


def test_factory_prefers_native(tmp_path):
    vocab_file = tmp_path / "vocab.txt"
    vocab_file.write_text(
        "\n".join(sorted(VOCAB, key=VOCAB.get)) + "\n", encoding="utf-8")
    tok = get_wordpiece_tokenizer(str(vocab_file))
    assert isinstance(tok, native.NativeWordPieceTokenizer)
    assert tok.encode("the fox").ids == \
        BertWordPieceTokenizer(VOCAB, lowercase=True).encode("the fox").ids


def test_encode_batch_arrays(both):
    py, nat = both
    texts = ["the quick fox", "unaffable dog!", ""]
    lens, ids, type_ids, starts, ends = nat.encode_batch_arrays(texts)
    assert lens.tolist() == [len(py.encode(t).ids) for t in texts]
    off = 0
    for t, ln in zip(texts, lens.tolist()):
        e = py.encode(t)
        assert ids[off:off + ln].tolist() == e.ids
        assert list(zip(starts[off:off + ln].tolist(),
                        ends[off:off + ln].tolist())) == e.offsets
        off += ln
    assert off == len(ids)


def test_batch_throughput_speedup(both):
    """The reason this module exists: batch encode must beat the Python spec
    substantially. Raw C++ measures ~13x single-core on wiki-like text; the
    Encoding-building wrapper keeps >= 2x even on the slowest CI box."""
    import string
    import time

    py, nat = both
    rng = random.Random(0)
    words = ["".join(rng.choice(string.ascii_lowercase)
                     for _ in range(rng.randint(2, 9))) for _ in range(300)]
    texts = [" ".join(rng.choice(words) for _ in range(20)) + "."
             for _ in range(600)]
    for t in texts[:5]:  # warm both paths
        py.encode(t)
    nat.encode_batch(texts[:5])

    t0 = time.time()
    py_out = [py.encode(t) for t in texts]
    t_py = time.time() - t0
    t0 = time.time()
    nat_out = nat.encode_batch(texts)
    t_nat = time.time() - t0
    for a, b in zip(py_out, nat_out):
        assert a.ids == b.ids
    assert t_py / t_nat >= 2.0, (t_py, t_nat)

    t0 = time.time()
    nat.encode_batch_arrays(texts)
    t_arr = time.time() - t0
    print(f"\nspeedup: encode_batch {t_py / t_nat:.1f}x, "
          f"arrays {t_py / t_arr:.1f}x")
    assert t_py / t_arr >= 4.0, (t_py, t_arr)
