"""Serving stack tests: bucket selection, packed-vs-single bit-identity,
queue overflow shedding, admission timeout, zero-recompile steady state,
checkpoint restore contracts, and the HTTP frontend end to end.

The acceptance pins (ISSUE round 14): responses from a packed
multi-request batch are BIT-identical to the same requests served
one-per-batch; the compile count is flat after warmup across buckets;
len == bucket boundary rides that bucket and len > max bucket is shed
with 413."""

import json
import os
import sys
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

from bert_pytorch_tpu.serving.batcher import (  # noqa: E402
    Overloaded, RequestTimeout, Scheduler, TooLong)
from bert_pytorch_tpu.serving.engine import (  # noqa: E402
    ServingEngine, restore_serving_params, select_bucket, zero_batch)
from bert_pytorch_tpu.tasks import predict  # noqa: E402


def _tiny_config(**kw):
    from bert_pytorch_tpu.config import BertConfig

    base = dict(vocab_size=64, hidden_size=32, num_hidden_layers=2,
                num_attention_heads=4, intermediate_size=64,
                max_position_embeddings=64, hidden_dropout_prob=0.0,
                attention_probs_dropout_prob=0.0, fused_ops=False,
                attention_impl="xla")
    base.update(kw)
    return BertConfig(**base)


def _qa_model_params(config=None):
    import jax
    import jax.numpy as jnp

    from bert_pytorch_tpu.models import BertForQuestionAnswering
    from bert_pytorch_tpu.training.state import unbox

    config = config or _tiny_config()
    model = BertForQuestionAnswering(config, dtype=jnp.float32)
    s = jnp.zeros((1, 32), jnp.int32)
    params = unbox(model.init(jax.random.PRNGKey(0), s, s, s)["params"])
    return model, params


@pytest.fixture(scope="module")
def qa_engine():
    """One compiled two-bucket QA engine shared by the batching tests."""
    model, params = _qa_model_params()
    engine = ServingEngine({"squad": predict.build_qa_forward(model)},
                           {"squad": params}, buckets=(16, 32),
                           batch_rows=4, max_segments=4)
    engine.warmup()
    return engine


def _single_reference(engine, ids):
    """Serve one request alone in a batch — the bit-identity reference."""
    bucket = engine.select_bucket(len(ids))
    batch = zero_batch(engine.batch_rows, bucket)
    batch["input_ids"][0, :len(ids)] = ids
    batch["attention_mask"][0, :len(ids)] = 1
    batch["segment_ids"][0, :len(ids)] = 1
    batch["position_ids"][0, :len(ids)] = np.arange(len(ids))
    start, end = engine.forward("squad", batch)
    return start[0, :len(ids)].copy(), end[0, :len(ids)].copy()


# -- bucket selection ---------------------------------------------------------


def test_select_bucket_edges():
    buckets = (64, 128, 256, 512)
    assert select_bucket(1, buckets) == 64
    assert select_bucket(64, buckets) == 64      # boundary rides the bucket
    assert select_bucket(65, buckets) == 128
    assert select_bucket(512, buckets) == 512
    assert select_bucket(513, buckets) is None   # frontend turns into 413
    assert select_bucket(5, (128, 64)) == 64     # unsorted input tolerated


def test_submit_too_long_rejected(qa_engine):
    sch = Scheduler(qa_engine, packing=True)
    with pytest.raises(TooLong):
        sch.submit("squad", np.arange(33, dtype=np.int32) + 5)
    # counted as an outcome, not silently dropped
    assert sch.registry.counter(
        "bert_serve_requests_total",
        labels=("task", "outcome")).value(task="squad",
                                          outcome="too_long") == 1


# -- packed bit-identity ------------------------------------------------------


def test_packed_bit_identical_to_single_requests(qa_engine):
    """The acceptance pin: packed multi-request batches return the exact
    bits one-per-batch serving returns — segment masking is exact-zero,
    reductions keep the row length, every served head is token-local.
    Lengths cover a bucket boundary (16) and a full-capacity row (32)."""
    rng = np.random.RandomState(0)
    lengths = [7, 9, 16, 12, 3, 32, 5]
    reqs = [rng.randint(5, 64, (ln,)).astype(np.int32) for ln in lengths]
    singles = [_single_reference(qa_engine, ids) for ids in reqs]

    sch = Scheduler(qa_engine, packing=True, batch_wait_ms=1.0).start()
    try:
        handles = [sch.submit("squad", ids) for ids in reqs]
        packed = [sch.result(h, timeout=60) for h in handles]
    finally:
        sch.close()
    for i, ((s1, e1), (s2, e2)) in enumerate(zip(singles, packed)):
        assert np.array_equal(s1, s2) and np.array_equal(e1, e2), \
            f"request {i} (len {lengths[i]}) differs packed vs single"


def test_padded_mode_bit_identical_too(qa_engine):
    """packing=off runs the SAME compiled program with one segment per
    row — responses must also be bit-identical to the packed ones."""
    rng = np.random.RandomState(1)
    reqs = [rng.randint(5, 64, (ln,)).astype(np.int32)
            for ln in (4, 11, 16, 8)]
    singles = [_single_reference(qa_engine, ids) for ids in reqs]
    sch = Scheduler(qa_engine, packing=False, batch_wait_ms=1.0).start()
    try:
        handles = [sch.submit("squad", ids) for ids in reqs]
        padded = [sch.result(h, timeout=60) for h in handles]
    finally:
        sch.close()
    for (s1, e1), (s2, e2) in zip(singles, padded):
        assert np.array_equal(s1, s2) and np.array_equal(e1, e2)


# -- flow control -------------------------------------------------------------


def test_queue_overflow_sheds(qa_engine):
    """No consumer thread: the bounded queue fills, then submit sheds
    with Overloaded (the frontend's 503)."""
    sch = Scheduler(qa_engine, queue_size=4, packing=True)  # not started
    ids = np.arange(8, dtype=np.int32) + 5
    for _ in range(4):
        sch.submit("squad", ids)
    with pytest.raises(Overloaded):
        sch.submit("squad", ids)
    assert sch.registry.counter(
        "bert_serve_requests_total",
        labels=("task", "outcome")).value(task="squad",
                                          outcome="overloaded") == 1


class _StallEngine:
    """Engine stub whose forward blocks — admission-timeout fuel."""

    buckets = (16,)
    batch_rows = 2
    max_segments = 2
    max_bucket = 16

    def __init__(self, stall_s: float):
        self.stall_s = stall_s

    def select_bucket(self, length):
        return 16 if length <= 16 else None

    def forward(self, task, batch):
        time.sleep(self.stall_s)
        b, s = np.shape(batch["input_ids"])
        return np.zeros((b, s)), np.zeros((b, s))


def test_admission_timeout_expires_queued_requests():
    """Requests older than the admission budget resolve with
    RequestTimeout (the frontend's 504) instead of consuming batch
    slots."""
    sch = Scheduler(_StallEngine(stall_s=0.25), admission_timeout_s=0.1,
                    batch_wait_ms=0.0, packing=True).start()
    try:
        ids = np.arange(10, dtype=np.int32)
        handles = [sch.submit("squad", ids) for _ in range(12)]
        outcomes = []
        for h in handles:
            try:
                sch.result(h, timeout=10)
                outcomes.append("ok")
            except RequestTimeout:
                outcomes.append("timeout")
        # the first wave(s) are served; requests stuck behind the stalled
        # forward age past 0.1s and expire
        assert "ok" in outcomes
        assert "timeout" in outcomes
    finally:
        sch.close()


def test_result_timeout_without_scheduler(qa_engine):
    sch = Scheduler(qa_engine, packing=True)  # never started
    req = sch.submit("squad", np.arange(6, dtype=np.int32) + 5)
    with pytest.raises(RequestTimeout):
        sch.result(req, timeout=0.1)


# -- zero-recompile steady state ----------------------------------------------


def test_zero_recompile_after_warmup_across_buckets():
    """The acceptance pin: CompileWatch's count is flat after warmup no
    matter how traffic mixes the buckets — steady-state serving never
    touches the compiler."""
    from bert_pytorch_tpu.telemetry.compile_watch import CompileWatch

    cw = CompileWatch().install()
    try:
        model, params = _qa_model_params()
        engine = ServingEngine({"squad": predict.build_qa_forward(model)},
                               {"squad": params}, buckets=(16, 32),
                               batch_rows=2, max_segments=2,
                               compile_watch=cw)
        engine.warmup()
        warm = cw.compiles
        assert warm >= 2  # both buckets actually compiled
        sch = Scheduler(engine, packing=True, batch_wait_ms=0.5).start()
        try:
            rng = np.random.RandomState(2)
            for round_ in range(3):
                handles = [
                    sch.submit("squad",
                               rng.randint(5, 64, (ln,)).astype(np.int32))
                    for ln in (3, 16, 9, 32, 12, 7)]  # hits BOTH buckets
                for h in handles:
                    sch.result(h, timeout=60)
        finally:
            sch.close()
        assert cw.compiles == warm, (
            f"steady-state traffic recompiled: {warm} compiles after "
            f"warmup, {cw.compiles} after serving")
    finally:
        cw.uninstall()


# -- checkpoint restore -------------------------------------------------------


def test_restore_params_only_and_finetune_layouts(tmp_path):
    """Both serving restore contracts: a params-only checkpoint (the
    restore_either_layout path) and a full finetune TrainState dict (the
    strict-merge path) round-trip bit-exactly; a checkpoint missing the
    task head fails LOUDLY instead of serving random weights."""
    import jax

    from bert_pytorch_tpu.training.checkpoint import CheckpointManager

    model, params = _qa_model_params()

    mgr = CheckpointManager(str(tmp_path / "params_only"))
    mgr.save(0, {"params": params})
    mgr.close()
    restored, step = restore_serving_params(
        str(tmp_path / "params_only"), model, 32, log=lambda m: None)
    assert step == 0
    for a, b in zip(jax.tree.leaves(params), jax.tree.leaves(restored)):
        assert np.array_equal(np.asarray(a), np.asarray(b))

    # finetune-shaped save: a TrainState-like dict with extra subtrees
    mgr = CheckpointManager(str(tmp_path / "finetune"))
    mgr.save(7, {"step": 7, "params": params,
                 "opt_state": {"mu": {"x": np.zeros(3, np.float32)}}})
    mgr.close()
    restored, step = restore_serving_params(
        str(tmp_path / "finetune"), model, 32, log=lambda m: None)
    assert step == 7
    for a, b in zip(jax.tree.leaves(params), jax.tree.leaves(restored)):
        assert np.array_equal(np.asarray(a), np.asarray(b))

    # missing head: drop qa_outputs and expect a loud failure
    headless = {k: v for k, v in params.items() if k != "qa_outputs"}
    mgr = CheckpointManager(str(tmp_path / "headless"))
    mgr.save(0, {"step": 0, "params": headless, "opt_state": {}})
    mgr.close()
    with pytest.raises(ValueError, match="qa_outputs"):
        restore_serving_params(str(tmp_path / "headless"), model, 32,
                               log=lambda m: None)


# -- HTTP frontend e2e --------------------------------------------------------


def _get(url, timeout=10):
    with urllib.request.urlopen(url, timeout=timeout) as r:
        return r.status, json.loads(r.read().decode("utf-8"))


def _post(url, body, timeout=30):
    data = json.dumps(body).encode("utf-8")
    req = urllib.request.Request(
        url, data=data, headers={"Content-Type": "application/json"})
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, json.loads(r.read().decode("utf-8"))
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read().decode("utf-8"))


@pytest.fixture(scope="module")
def live_server(serving_fixture):
    """The full run_server.serve() stack on a fixture checkpoint: both
    tasks, ephemeral port, packed batching."""
    import run_server

    msf, _root, paths = serving_fixture
    args = run_server.parse_arguments([
        "--model_config_file", paths["model_config"],
        "--vocab_file", paths["vocab"],
        "--squad_checkpoint", paths["squad_ckpt"],
        "--ner_checkpoint", paths["ner_ckpt"],
        "--labels", *msf.NER_LABELS,
        "--buckets", "16,32", "--batch_rows", "2", "--max_segments", "2",
        "--serve_dtype", "float32", "--packing", "on",
        "--port", "0", "--host", "127.0.0.1",
        "--queue_size", "64", "--admission_timeout", "30"])
    handle = run_server.serve(args)
    yield handle
    handle.close()


def test_http_squad_and_ner_roundtrip(live_server):
    url = live_server.url
    code, out = _post(url + "/v1/squad", {
        "question": "who sat on the mat ?",
        "context": "the cat sat on the mat"})
    assert code == 200
    assert isinstance(out["answer"], str)
    assert out["n_windows"] >= 1 and out["real_tokens"] > 0
    assert isinstance(out["nbest"], list) and out["nbest"]

    code, out = _post(url + "/v1/ner", {
        "tokens": ["the", "cat", "sat"]})
    assert code == 200
    assert out["labels"] and len(out["labels"]) == 3
    assert all(isinstance(l, str) for l in out["labels"])


def test_http_error_mapping(live_server):
    url = live_server.url
    # 413: tokenizes past the largest bucket (32 pieces incl CLS/SEP)
    code, out = _post(url + "/v1/ner", {"tokens": ["cat"] * 80})
    assert code == 413 and "error" in out
    # 400: malformed / missing fields
    code, _ = _post(url + "/v1/squad", {"question": "q"})
    assert code == 400
    # 404: unknown route
    code, _ = _post(url + "/v1/nope", {})
    assert code == 404


def test_http_metrics_and_healthz(live_server):
    from bert_pytorch_tpu.telemetry.registry import parse_prometheus

    url = live_server.url
    # drive at least one request so the counters are nonzero
    _post(url + "/v1/ner", {"tokens": ["cat", "sat"]})
    with urllib.request.urlopen(url + "/metrics", timeout=10) as r:
        text = r.read().decode("utf-8")
    parsed = parse_prometheus(text)
    lab = '{phase="serve"'
    ok_series = [v for k, v in parsed.get(
        "bert_serve_requests_total", {}).items()
        if k.startswith(lab) and 'outcome="ok"' in k]
    assert ok_series and sum(ok_series) >= 1
    assert any(k.startswith("bert_serve_request_latency_ms")
               for k in parsed)
    assert "bert_serve_queue_depth" in parsed
    assert "bert_serve_batch_occupancy" in parsed

    code, hz = _get(url + "/healthz")
    assert code == 200
    assert hz["phase"] == "serve"
    assert hz["packing"] is True
    assert set(hz["tasks"]) == {"squad", "ner"}
    assert hz["buckets"] == [16, 32]


def test_http_concurrent_mixed_burst(live_server):
    """A threaded mixed squad/ner burst — every response 2xx, no
    cross-request contamination in shapes (labels match token counts)."""
    url = live_server.url
    results = []
    lock = threading.Lock()

    def one(i):
        if i % 2:
            code, out = _post(url + "/v1/ner",
                              {"tokens": ["the", "cat", "sat"][:1 + i % 3]})
            good = code == 200 and len(out["labels"]) == 1 + i % 3
        else:
            code, out = _post(url + "/v1/squad", {
                "question": "who ?",
                "context": "the cat sat on the mat " * (1 + i % 3)})
            good = code == 200 and isinstance(out["answer"], str)
        with lock:
            results.append(good)

    threads = [threading.Thread(target=one, args=(i,)) for i in range(12)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(60)
    assert len(results) == 12 and all(results)


def test_http_trace_header_and_traces_endpoint(live_server):
    """Round 18 request tracing through the live stack: every admitted
    POST's reply carries X-Trace-Id, /v1/traces serves the retained span
    timelines as strict Chrome-trace JSON, and /healthz reports the
    flight-recorder retention stats. A tokenize-stage 413 (rejected
    BEFORE admission) correctly carries no trace id — the timeline
    starts at scheduler admission, and the submit-side too_long terminal
    span is pinned in tests/test_request_tracing.py."""
    url = live_server.url
    data = json.dumps({"tokens": ["the", "cat"]}).encode("utf-8")
    req = urllib.request.Request(
        url + "/v1/ner", data=data,
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=30) as r:
        assert r.status == 200
        tid = r.headers.get("X-Trace-Id")
    assert tid, "2xx reply missing X-Trace-Id"

    # pre-admission 413: no trace was minted, so no header
    data = json.dumps({"tokens": ["cat"] * 80}).encode("utf-8")
    req = urllib.request.Request(
        url + "/v1/ner", data=data,
        headers={"Content-Type": "application/json"})
    with pytest.raises(urllib.error.HTTPError) as ei:
        urllib.request.urlopen(req, timeout=30)
    assert ei.value.code == 413
    assert ei.value.headers.get("X-Trace-Id") is None

    # targeted fetch by id: the completed request's full span timeline
    with urllib.request.urlopen(url + f"/v1/traces?id={tid}",
                                timeout=10) as r:
        doc = json.loads(r.read().decode("utf-8"))
    names = {ev["name"] for ev in doc["traceEvents"]
             if ev["args"]["trace_id"] == tid}
    assert {"req/admit", "req/queue_wait", "req/dispatch", "req/compute",
            "req/respond"} <= names, names

    code, hz = _get(url + "/healthz")
    assert code == 200
    rt = hz["request_tracing"]
    assert rt["seen"] >= 1 and rt["retained_slowest"] >= 1
    assert rt["cost_per_device_hour"] > 0
