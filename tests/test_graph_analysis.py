"""Static graph analysis (bert_pytorch_tpu/analysis + tools/graphcheck.py).

Fast half: parser + pass-framework units on synthetic HLO text fixtures
(no compile, no jax beyond import) — budget regression names the op,
donation miss detected, replicated-moment leaf detected, fingerprint
compare semantics, budget-file schema, the jax-free --validate-budgets
contract, the repolint fallback, and perfboard's graph_report indexing.

Slow half (the acceptance drill): the REAL production step compiled on
the forced 8-device CPU mesh passes the checked-in budgets, and injected
program regressions (dropped donate_argnums; ZeRO-1 state sharding failed
open) make the gate exit nonzero naming the exact rule, op, and leaf.
"""

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from bert_pytorch_tpu.analysis import hlo, passes  # noqa: E402
from tools import graphcheck  # noqa: E402

# a tiny synthetic compiled-HLO module: 2 all-gathers, 1 all-reduce,
# 1 reduce-scatter, donation table with one aliased and one missed param
FIXTURE_HLO = """\
HloModule jit_step, is_scheduled=true, input_output_alias={ {0}: (0, {}, \
may-alias), {1}: (1, {}, may-alias) }, buffer_donor={ (2, {}) }, \
entry_computation_layout={(f32[4,8]{1,0}, f32[4,8]{1,0}, f32[64,8]{1,0}, \
f32[16,8]{1,0})->(f32[4,8]{1,0}, f32[4,8]{1,0}, f32[])}, num_partitions=8

  %ag1 = f32[32,8]{1,0} all-gather(f32[4,8]{1,0} %p0), channel_id=1, \
replica_groups=[1,8]<=[8], dimensions={0}
  %ag2-start = (f32[4,8]{1,0}, f32[32,8]{1,0}) all-gather-start(\
f32[4,8]{1,0} %p1), replica_groups=[1,8]<=[8], dimensions={0}
  %ag2-done = f32[32,8]{1,0} all-gather-done((f32[4,8]{1,0}, \
f32[32,8]{1,0}) %ag2-start)
  %ar = f32[8]{0} all-reduce(f32[8]{0} %x), channel_id=2, \
replica_groups={{0,1,2,3,4,5,6,7}}, to_apply=%sum
  %rs = f32[4,8]{1,0} reduce-scatter(f32[32,8]{1,0} %y), channel_id=3, \
replica_groups=[1,8]<=[8], dimensions={0}
  %cp = f32[8,8]{1,0} copy(f32[8,8]{0,1} %q)
  %tr = f32[8,8]{1,0} transpose(f32[8,8]{1,0} %q), dimensions={1,0}
  %red_fusion = f32[] fusion(f32[32,8]{1,0} %ag1), kind=kLoop, calls=%fc
  ROOT %out = (f32[4,8]{1,0}, f32[4,8]{1,0}, f32[]) tuple(%rs, %p1, \
%red_fusion)
"""


# --- parser units -------------------------------------------------------


def test_parse_hlo_counts_collectives_and_ops():
    rep = hlo.parse_hlo_module(FIXTURE_HLO)
    assert rep["collective_counts"] == {
        "all-gather": 2, "all-reduce": 1, "reduce-scatter": 1,
        "collective-permute": 0, "all-to-all": 0}
    assert rep["op_counts"]["copy"] == 1
    assert rep["op_counts"]["transpose"] == 1
    assert rep["op_counts"]["fusion"] == 1
    assert rep["num_partitions"] == 8
    # bytes: each all-gather OUTPUT is 32*8*4 = 1024 B — the async
    # `-start`'s `(operand, output)` tuple counts only its output half
    assert rep["collective_bytes"]["all-gather"] == 2048
    # ring estimate: (g-1)/g of the output per participant
    assert rep["collective_est_bytes_moved"]["all-gather"] == 2 * 896
    assert rep["collective_shapes"]["all-gather f32[32,8]"] == 2


def test_parse_hlo_donation_table():
    don = hlo.parse_hlo_module(FIXTURE_HLO)["donation"]
    assert don["aliased"] == [0, 1]
    assert don["donated_unaliased"] == [2]  # the miss
    assert don["n_aliased"] == 2 and don["n_donated_unaliased"] == 1


def test_stablehlo_dot_dtype_census():
    text = """
      %2 = stablehlo.dot_general %0, %1, contracting_dims = [1] x [0] :
        (tensor<8x8xbf16>, tensor<8x8xbf16>) -> tensor<8x8xbf16>
      %5 = stablehlo.dot_general %3, %4, contracting_dims = [1] x [0] : \
(tensor<4x8xf32>, tensor<8x2xf32>) -> tensor<4x2xf32>
    """
    # multiline form (result type on the next line) is counted only when
    # the arrow is on the op line — the census is line-based; both ops
    # here carry an arrow on an op line
    dd = hlo.stablehlo_dot_dtypes(text)
    assert dd.get("f32") == 1


# --- pass framework on fixtures ----------------------------------------


def test_budget_regression_exits_nonzero_naming_the_op():
    rep = hlo.parse_hlo_module(FIXTURE_HLO)
    budget = {"all-gather": 1, "all-reduce": 1, "reduce-scatter": 1}
    findings = passes.check_collective_budget(rep, budget)
    errs = [f for f in findings if f.severity == "error"]
    assert len(errs) == 1
    assert errs[0].op == "all-gather"
    assert "2 ops compiled, budget is 1" in errs[0].message
    # run through the driver + CLI printer: nonzero error count
    per_combo = {"fix": passes.run_passes(
        rep, {"collective_budget": budget})}
    assert graphcheck.print_findings(
        per_combo, stream=open(os.devnull, "w")) == 1


def test_donation_miss_detected_with_leaf_name():
    rep = hlo.parse_hlo_module(FIXTURE_HLO)
    rep["inputs"] = [
        {"path": ".params['w']", "param": 0, "bytes": 128, "aliased": True},
        {"path": ".opt_state.mu['w']", "param": 1, "bytes": 128,
         "aliased": True},
        {"path": ".opt_state.nu['w']", "param": 2, "bytes": 2048,
         "aliased": False, "donated_unaliased": True},
        {"path": ".batch['x']", "param": 3, "bytes": 512, "aliased": False},
    ]
    findings = passes.check_donation(rep, {"min_aliased": 2})
    errs = [f for f in findings if f.severity == "error"]
    assert len(errs) == 1
    assert errs[0].leaf == ".opt_state.nu['w']"
    assert "never aliased" in errs[0].message
    # min_aliased floor trips when the whole table loses donation
    rep2 = dict(rep, donation=dict(rep["donation"], n_aliased=0))
    errs2 = passes.check_donation(rep2, {"min_aliased": 2})
    assert any("donate_argnums" in f.message for f in errs2)


def test_replicated_moment_leaf_detected():
    leaves = [
        {"path": ".opt_state.mu['embedding']", "shape": [64, 32],
         "replicated": True, "expected_sharded": True,
         "expected_spec": "PartitionSpec('data', None)"},
        {"path": ".params['embedding']", "shape": [64, 32],
         "replicated": True, "expected_sharded": False,
         "expected_spec": None},
        {"path": ".opt_state.nu['embedding']", "shape": [64, 32],
         "replicated": False, "expected_sharded": True,
         "expected_spec": "PartitionSpec('data', None)"},
    ]
    findings = passes.replication_findings(leaves)
    assert len(findings) == 1
    assert findings[0].leaf == ".opt_state.mu['embedding']"
    assert "PartitionSpec('data', None)" in findings[0].message
    # the count floor fires independently of per-leaf expectations
    rep = {"inputs": [dict(r, expected_sharded=False) for r in leaves]}
    errs = passes.check_replication(rep, {"min_sharded_inputs": 2})
    assert any("failed open" in f.message for f in errs)


def test_dtype_and_memory_passes():
    rep = {"dot_dtypes": {"bf16": 30, "f32": 3},
           "memory": {"argument_size_in_bytes": 2**20,
                      "output_size_in_bytes": 2**20,
                      "temp_size_in_bytes": 2**20,
                      "alias_size_in_bytes": 2**20}}
    errs = passes.check_dtype(rep, {"compute_dtype": "bf16",
                                    "max_f32_dots": 0})
    assert errs and errs[0].op == "dot" and "3 f32 matmul" in errs[0].message
    assert not passes.check_dtype(rep, {"compute_dtype": "bf16",
                                        "max_f32_dots": 3})
    assert not passes.check_dtype(rep, {"compute_dtype": "f32"})
    # memory estimate = args + temps + outputs - aliased = 2 MB
    assert passes.estimate_device_bytes(rep) == 2 * 2**20
    bad = passes.check_memory(rep, {"budget_mb": 1})
    assert bad[0].severity == "error" and "exceeds" in bad[0].message
    ok = passes.check_memory(rep, {"budget_mb": 4})
    assert ok[0].severity == "info"


def test_unknown_expectation_key_is_loud():
    findings = passes.run_passes({}, {"collectve_budget": {}})  # typo
    assert passes.has_errors(findings)
    assert "unknown expectation key" in findings[0].message


def test_fingerprint_compare_semantics():
    rep = hlo.parse_hlo_module(FIXTURE_HLO)
    fp = dict(hlo.fingerprint_of(rep), platform="cpu")
    same = dict(fp)
    comparable, diffs = hlo.compare_fingerprints(fp, same)
    assert comparable and not diffs
    # a structural change shows up as a named diff
    drifted = dict(fp, collective_counts=dict(fp["collective_counts"],
                                              **{"all-gather": 5}))
    comparable, diffs = hlo.compare_fingerprints(fp, drifted)
    assert comparable and any("all-gather" in d for d in diffs)
    # cross-platform: not comparable, never a false alarm
    other = dict(fp, platform="tpu")
    comparable, _ = hlo.compare_fingerprints(fp, other)
    assert not comparable
    assert hlo.compare_fingerprints(fp, None) == (False, [])


def test_manifest_fingerprint_schema():
    from bert_pytorch_tpu.telemetry.flight_recorder import (
        MANIFEST_SCHEMA_VERSION, REQUIRED_MANIFEST_KEYS, REQUIRED_RUN_KEYS,
        validate_manifest)

    manifest = {k: {} for k in REQUIRED_MANIFEST_KEYS}
    manifest.update(
        schema_version=MANIFEST_SCHEMA_VERSION, reason="nonfinite",
        trigger_step=3, created_unix=0.0,
        model_config={"hidden_size": 8, "num_hidden_layers": 1},
        run={k: None for k in REQUIRED_RUN_KEYS},
        records=[{"step": 3, "pos": 0, "n_steps": 1, "fields": []}],
        metrics_tail=[], metrics_tail_source=None, registry={})
    # absent key entirely is fine (round-12 bundles) and None is fine
    assert validate_manifest(dict(manifest)) == []
    assert validate_manifest(dict(manifest, program_fingerprint=None)) == []
    good_fp = {"collective_counts": {"all-reduce": 3},
               "donation_hash": "abc", "hash": "x", "platform": "cpu"}
    assert validate_manifest(
        dict(manifest, program_fingerprint=good_fp)) == []
    errs = validate_manifest(dict(manifest, program_fingerprint={"x": 1}))
    assert any("program_fingerprint" in e for e in errs)


# --- budget-file schema + jax-free contract ----------------------------


def test_checked_in_budgets_validate():
    budgets = json.load(open(os.path.join(REPO, "results",
                                          "graph_budgets.json")))
    assert graphcheck.validate_budgets(budgets) == []
    # and the schema check catches real damage
    assert graphcheck.validate_budgets({"schema_version": 99})
    broken = json.loads(json.dumps(budgets))
    broken["combos"]["zero1_dp8"]["expect"]["collective_budget"][
        "all-gather"] = -1
    assert any("all-gather" in e for e in graphcheck.validate_budgets(broken))


def test_validate_budgets_is_jax_free():
    """`graphcheck --validate-budgets` must run on a login host with no
    jax: execute it in a subprocess where importing jax raises."""
    code = (
        "import builtins\n"
        "real = builtins.__import__\n"
        "def guard(name, *a, **k):\n"
        "    if name == 'jax' or name.startswith('jax.'):\n"
        "        raise AssertionError('jax imported in --validate-budgets')\n"
        "    return real(name, *a, **k)\n"
        "builtins.__import__ = guard\n"
        "import sys\n"
        f"sys.path.insert(0, {REPO!r})\n"
        "from tools import graphcheck\n"
        "sys.exit(graphcheck.main(['--validate-budgets']))\n")
    proc = subprocess.run([sys.executable, "-c", code], cwd=REPO,
                          capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "schema ok" in proc.stdout


def test_repolint_catches_planted_bugs(tmp_path):
    bad = tmp_path / "bad.py"
    bad.write_text(
        "import os\n"                      # unused
        "x = f\"no placeholders\"\n"        # F541
        "y = (x is 'literal')\n"            # F632
        "z = undefined_thing + 1\n"         # F821
        "def f(d):\n"
        "    dead = d.pop('k')\n"           # F841: never used
        "    return d\n")
    from tools import repolint

    findings = repolint.lint_file(str(bad))
    codes = {c for _, c, _ in findings}
    assert {"F401", "F541", "F632", "F821", "F841"} <= codes
    # `is None/True/False`, format specs, underscore locals, and
    # assign-then-del (Del is a use, matching pyflakes — ruff stays
    # strictly stronger than the fallback) are NOT flagged
    ok = tmp_path / "ok.py"
    ok.write_text(
        "import math\n"
        "v = math.pi\n"
        "s = f\"{v:.2f}\"\n"
        "t = v is None\n"
        "def f(d):\n"
        "    gone = d.pop('k')\n"
        "    del gone\n"
        "    _scratch = d.copy()\n"
        "    n = 0\n"
        "    n += len(d)\n"        # augmented assign = an implicit load
        "    return d\n")
    assert repolint.lint_file(str(ok)) == []


def test_repo_is_lint_clean():
    """The satellite's 'fix the findings' stays fixed."""
    from tools import repolint

    assert repolint.main(list(repolint.DEFAULT_TARGETS)) == 0


def test_perfboard_indexes_graph_report(tmp_path):
    from tools import perfboard

    kind, metrics, _ = perfboard.extract(
        os.path.join(REPO, "results", "graph_report.json"))
    assert kind == "graph"
    assert metrics.get("zero1_dp8.collectives.all-gather", 0) > 0
    assert metrics.get("zero1_dp8.donation_aliased", 0) >= 80
    assert metrics.get("zero1_dp8.sharded_inputs", 0) > 0
    # direction: collectives regress upward, donation downward
    assert perfboard.metric_direction(
        "zero1_dp8.collectives.all-gather") == "lower"
    assert perfboard.metric_direction(
        "zero1_dp8.donation_aliased") == "higher"
    # an extra all-gather fails the graph-kind perf gate
    cur = json.load(open(os.path.join(REPO, "results",
                                      "graph_report.json")))
    cur["combos"]["zero1_dp8"]["collective_counts"]["all-gather"] += 30
    # ...and a kind growing from ZERO (the GSPMD-forked-collective class)
    # must trip the gate too — zero baselines are recorded, not skipped
    assert cur["combos"]["zero1_dp8"]["collective_counts"][
        "collective-permute"] == 0
    cur["combos"]["zero1_dp8"]["collective_counts"][
        "collective-permute"] = 4
    cur_path = tmp_path / "graph_report.json"
    cur_path.write_text(json.dumps(cur))
    regs, _ = perfboard.check_artifacts(
        os.path.join(REPO, "results", "graph_report.json"), str(cur_path),
        tolerance=0.1)
    assert any("all-gather" in r for r in regs)
    assert any("collective-permute" in r and "left zero" in r
               for r in regs)


def test_perfboard_reduce_scatter_gate_is_direction_aware(tmp_path):
    """round 16: reduce-scatter is the one collective whose appearance is
    progress (the rs grad path), so it gates 'nonzero' — regression ONLY
    when a combo that compiled reduce-scatters drops back to zero (the rs
    path silently reverting to all-reduce-then-slice)."""
    from tools import perfboard

    assert perfboard.metric_direction(
        "zero1_rs_dp8.collectives.reduce-scatter") == "nonzero"
    # the other collectives stay lower-better — all-reduce growing or a
    # kind leaving zero still trips the gate (pinned above)
    assert perfboard.metric_direction(
        "zero1_rs_dp8.collectives.all-reduce") == "lower"

    base = json.load(open(os.path.join(REPO, "results",
                                       "graph_report.json")))
    assert base["combos"]["zero1_rs_dp8"]["collective_counts"][
        "reduce-scatter"] > 0
    base_path = tmp_path / "base.json"
    base_path.write_text(json.dumps(base))

    # rs count collapsing to zero: regression, named as the rs path
    # disappearing
    cur = json.loads(json.dumps(base))
    cur["combos"]["zero1_rs_dp8"]["collective_counts"]["reduce-scatter"] = 0
    cur_path = tmp_path / "cur.json"
    cur_path.write_text(json.dumps(cur))
    regs, _ = perfboard.check_artifacts(str(base_path), str(cur_path),
                                        tolerance=0.1)
    assert any("reduce-scatter" in r and "disappeared" in r for r in regs)

    # rs appearing from zero (legacy baseline -> rs current) is NOT a
    # regression — the exact move the old lower-better rule would have
    # flagged
    legacy = json.loads(json.dumps(base))
    legacy["combos"]["zero1_rs_dp8"]["collective_counts"][
        "reduce-scatter"] = 0
    legacy_path = tmp_path / "legacy.json"
    legacy_path.write_text(json.dumps(legacy))
    regs, _ = perfboard.check_artifacts(str(legacy_path), str(base_path),
                                        tolerance=0.1)
    assert not any("reduce-scatter" in r for r in regs)


# --- the acceptance drill: real compiled programs ----------------------


def test_gate_passes_on_checked_in_budgets_and_names_injected_regressions(
        tmp_path, capsys):
    """ONE combo (zero1_dp8) compiled three ways on the 8-device CPU mesh:
    clean -> exit 0 against the checked-in budgets; donation dropped ->
    exit 1 naming the donation rule; ZeRO-1 state sharding failed open ->
    exit 1 naming the replication rule and the exact moment leaf."""
    report = str(tmp_path / "graph_report.json")
    budgets = os.path.join(REPO, "results", "graph_budgets.json")

    rc = graphcheck.main(["--combos", "zero1_dp8", "--report", report,
                          "--budgets", budgets])
    out = capsys.readouterr().out
    assert rc == 0, out
    assert "within budget" in out

    rc = graphcheck.main(["--combos", "zero1_dp8", "--report", report,
                          "--budgets", budgets, "--inject", "no_donate"])
    out = capsys.readouterr().out
    assert rc == 1
    assert "ERROR [donation]" in out
    assert "donate_argnums" in out

    rc = graphcheck.main(["--combos", "zero1_dp8", "--report", report,
                          "--budgets", budgets,
                          "--inject", "replicated_state"])
    out = capsys.readouterr().out
    assert rc == 1
    assert "ERROR [replication]" in out
    # the exact regressed leaf is named: a ZeRO-1 moment, by path
    assert ".opt_state.mu" in out and "failed open" in out


@pytest.mark.slow
def test_rs_gate_catches_injected_allreduce(tmp_path, capsys):
    """The round-16 acceptance drill: zero1_rs_dp8's checked-in budget
    pins all-reduce as an EXACT ceiling (11 — under half of zero1_dp8's),
    so one smuggled full-tree reduction over a sharded moment leaf must
    flip the gate. Clean compile passes first — proving the failure below
    is the injection, not baseline drift."""
    report = str(tmp_path / "graph_report.json")
    budgets = os.path.join(REPO, "results", "graph_budgets.json")

    rc = graphcheck.main(["--combos", "zero1_rs_dp8", "--report", report,
                          "--budgets", budgets])
    out = capsys.readouterr().out
    assert rc == 0, out

    rc = graphcheck.main(["--combos", "zero1_rs_dp8", "--report", report,
                          "--budgets", budgets,
                          "--inject", "extra_allreduce"])
    out = capsys.readouterr().out
    assert rc == 1
    assert "ERROR [collective_budget]" in out
    assert "all-reduce" in out and "extra all-reduce" in out


def test_step_program_aot_dispatch_and_fingerprint():
    """StepProgram: one AOT compile, compiled dispatch, graceful jit
    fallback on signature drift, and a fingerprint that reflects the
    compiled program."""
    import jax
    import jax.numpy as jnp

    from bert_pytorch_tpu.training.pretrain import StepProgram

    calls = []

    def step(state, batch, rng):
        calls.append(1)
        return {"w": state["w"] + batch.sum()}, {"loss": batch.sum()}

    prog = StepProgram(step)
    state = {"w": jnp.zeros((4,))}
    out_state, m = prog(state, jnp.ones((2, 2)), jax.random.PRNGKey(0))
    assert prog.compiled is not None
    assert prog.as_text() and "HloModule" in prog.as_text()
    fp = prog.fingerprint()
    assert fp is not None and "collective_counts" in fp \
        and "donation_hash" in fp
    # donated state: the carried buffer aliases in
    assert fp["n_aliased"] >= 1
    # same signature -> AOT path (no retrace)
    traces_before = len(calls)
    out_state, m = prog(out_state, jnp.ones((2, 2)), jax.random.PRNGKey(1))
    assert len(calls) == traces_before
    # different shape -> falls back to the jit cache, still correct
    out2, m2 = prog({"w": jnp.zeros((4,))}, jnp.ones((3, 2)),
                    jax.random.PRNGKey(0))
    assert float(m2["loss"]) == 6.0


@pytest.mark.slow
def test_full_combo_matrix_within_budget(tmp_path):
    """Every shipped combo (incl. K-FAC and bf16) against the checked-in
    budgets — the whole scripts/check_graph.sh gate, minus the shell."""
    rc = graphcheck.main(["--report", str(tmp_path / "r.json")])
    assert rc == 0
