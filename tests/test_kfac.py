"""K-FAC tests: factor statistics against hand computation, Cholesky inverse
correctness, preconditioning math on a single linear layer, kl_clip, and the
full tapped-BERT K-FAC train step reducing loss."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from bert_pytorch_tpu.config import BertConfig
from bert_pytorch_tpu.models import BertForPreTraining
from bert_pytorch_tpu.optim.kfac import (
    KFAC,
    KFACConfig,
    KFACState,
    _chol_inverse,
)
from bert_pytorch_tpu.optim.lamb import default_weight_decay_mask, lamb
from bert_pytorch_tpu.optim import schedulers
from bert_pytorch_tpu.training import (
    init_kfac_state,
    make_sharded_state,
)
from bert_pytorch_tpu.training.pretrain import (
    build_kfac_pretrain_step,
    stack_microbatches,
)

KFAC_TINY = BertConfig(
    vocab_size=128, hidden_size=32, num_hidden_layers=2,
    num_attention_heads=4, intermediate_size=64,
    max_position_embeddings=64, next_sentence=True,
    dtype="float32", fused_ops=False, attention_impl="xla",
    hidden_dropout_prob=0.0, attention_probs_dropout_prob=0.0,
    kfac_taps=True,
)


def test_chol_inverse():
    rng = np.random.RandomState(0)
    m = rng.randn(16, 16).astype(np.float32)
    spd = m @ m.T + 16 * np.eye(16, dtype=np.float32)
    inv = _chol_inverse(jnp.array(spd))
    np.testing.assert_allclose(np.asarray(inv @ spd), np.eye(16),
                               rtol=1e-3, atol=1e-3)


def test_compute_stats_matches_manual():
    kfac = KFAC(KFACConfig())
    rng = np.random.RandomState(0)
    B, S, DIN, DOUT = 4, 8, 16, 12
    a = rng.randn(B, S, DIN).astype(np.float32)
    g = rng.randn(B, S, DOUT).astype(np.float32)
    acts = {"site": (jnp.array(a),)}          # sown values are 1-tuples
    perts = {"site": jnp.array(g)}
    stats = kfac.compute_stats(acts, perts)["site"]

    rows = B * S
    a2 = np.concatenate([a.reshape(rows, DIN), np.ones((rows, 1))], axis=1)
    want_A = a2.T @ a2 / rows
    g2 = g.reshape(rows, DOUT)
    want_G = g2.T @ g2 * rows
    np.testing.assert_allclose(np.asarray(stats["A"]), want_A, rtol=1e-4)
    np.testing.assert_allclose(np.asarray(stats["G"]), want_G, rtol=1e-4)


def test_compute_stats_stacked_layers():
    kfac = KFAC(KFACConfig())
    rng = np.random.RandomState(0)
    L, B, S, DIN, DOUT = 3, 2, 4, 8, 6
    a = rng.randn(L, B, S, DIN).astype(np.float32)
    g = rng.randn(L, B, S, DOUT).astype(np.float32)
    stats = kfac.compute_stats({"x": (jnp.array(a),)},
                               {"x": jnp.array(g)})["x"]
    assert stats["A"].shape == (L, DIN + 1, DIN + 1)
    assert stats["G"].shape == (L, DOUT, DOUT)
    # layer 1 matches the per-layer manual computation
    rows = B * S
    a1 = np.concatenate([a[1].reshape(rows, DIN), np.ones((rows, 1))], axis=1)
    np.testing.assert_allclose(np.asarray(stats["A"][1]), a1.T @ a1 / rows,
                               rtol=1e-4)


def test_precondition_identity_factors_is_firstorder():
    """With A=G=I inverses, preconditioning only applies the kl_clip scale."""
    cfg = KFACConfig(kl_clip=1e9)  # effectively no clip
    kfac = KFAC(cfg)
    din, dout = 8, 6
    rng = np.random.RandomState(0)
    kg = jnp.array(rng.randn(din, dout).astype(np.float32))
    bg = jnp.array(rng.randn(dout).astype(np.float32))
    grads = {"site": {"kernel": kg, "bias": bg}}
    state = KFACState(
        factors={"site": {"A": jnp.zeros((din + 1, din + 1)),
                          "G": jnp.zeros((dout, dout))}},
        inverses={"site": {"A": jnp.eye(din + 1, dtype=jnp.float32),
                           "G": jnp.eye(dout, dtype=jnp.float32)}},
        count=jnp.zeros([], jnp.int32))
    out = kfac.precondition(state, grads, lr=1.0)
    np.testing.assert_allclose(np.asarray(out["site"]["kernel"]),
                               np.asarray(kg), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(out["site"]["bias"]),
                               np.asarray(bg), rtol=1e-5)


def test_kl_clip_scales_down():
    cfg = KFACConfig(kl_clip=1e-4)
    kfac = KFAC(cfg)
    din, dout = 4, 4
    grads = {"site": {"kernel": jnp.full((din, dout), 10.0),
                      "bias": jnp.full((dout,), 10.0)}}
    state = KFACState(
        factors={"site": {"A": jnp.zeros((din + 1, din + 1)),
                          "G": jnp.zeros((dout, dout))}},
        inverses={"site": {"A": jnp.eye(din + 1), "G": jnp.eye(dout)}},
        count=jnp.zeros([], jnp.int32))
    out = kfac.precondition(state, grads, lr=1.0)
    # nu = sqrt(kl_clip / (lr^2 * sum(pre*grad))) = sqrt(1e-4 / 2000) << 1
    want_nu = np.sqrt(1e-4 / (10.0 * 10.0 * (16 + 4)))
    np.testing.assert_allclose(np.asarray(out["site"]["kernel"][0, 0]),
                               10.0 * want_nu, rtol=1e-4)


def test_kfac_preconditioning_whitens_single_layer():
    """For a pure linear regression layer, K-FAC's F^{-1} g should equal the
    Gauss-Newton direction for correlated inputs (up to damping)."""
    rng = np.random.RandomState(0)
    N, DIN, DOUT = 4096, 8, 4
    # strongly correlated inputs
    mix = rng.randn(DIN, DIN).astype(np.float32)
    a = (rng.randn(N, DIN).astype(np.float32) @ mix)
    g = rng.randn(N, DOUT).astype(np.float32) / N  # mean-loss scale

    kfac = KFAC(KFACConfig(damping=1e-4, kl_clip=1e9, stat_decay=0.0,
                           inverse_dtype=jnp.float32))
    acts = {"lin": (jnp.array(a).reshape(1, N, DIN),)}
    perts = {"lin": jnp.array(g).reshape(1, N, DOUT)}
    stats = kfac.compute_stats(acts, perts)
    state = kfac.init(acts, perts)
    state, _ = kfac.step(state, stats, {"lin": {
        "kernel": jnp.zeros((DIN, DOUT)), "bias": jnp.zeros((DOUT,))}}, 1.0)

    # preconditioned grad of W_grad: A^-1 Wg G^-1
    Wg = jnp.array(rng.randn(DIN, DOUT).astype(np.float32))
    bgr = jnp.array(rng.randn(DOUT).astype(np.float32))
    out = kfac.precondition(state, {"lin": {"kernel": Wg, "bias": bgr}}, 1.0)

    rows = N
    a_aug = np.concatenate([a, np.ones((N, 1), np.float32)], 1)
    A = a_aug.T @ a_aug / rows * (1.0)  # stat_decay 0 -> factors == stats
    G = (g.T @ g) * rows
    tr_a = np.trace(A) / A.shape[0]
    tr_g = np.trace(G) / G.shape[0]
    pi = np.sqrt(tr_a / tr_g)
    lam = np.sqrt(1e-4)
    A_inv = np.linalg.inv(A + lam * pi * np.eye(DIN + 1))
    G_inv = np.linalg.inv(G + lam / pi * np.eye(DOUT))
    aug = np.concatenate([np.asarray(Wg), np.asarray(bgr)[None]], 0)
    want = A_inv @ aug @ G_inv
    np.testing.assert_allclose(np.asarray(out["lin"]["kernel"]), want[:-1],
                               rtol=2e-2, atol=1e-4)
    np.testing.assert_allclose(np.asarray(out["lin"]["bias"]), want[-1],
                               rtol=2e-2, atol=1e-4)


def _kfac_setup(accum=1, cfg=None, mesh=None):
    """One K-FAC BERT training setup; with `mesh`, the state is sharded
    under it and the batch is placed per its data sharding — the
    hyperparameters are defined exactly once so mesh/no-mesh runs are
    comparable."""
    from bert_pytorch_tpu.parallel import mesh as mesh_lib

    model = BertForPreTraining(cfg if cfg is not None else KFAC_TINY,
                               dtype=jnp.float32)
    sched = schedulers.poly_warmup_schedule(0.02, total_steps=100, warmup=0.1)
    tx = lamb(sched, weight_decay=0.01,
              weight_decay_mask=default_weight_decay_mask)
    kfac = KFAC(KFACConfig(inv_interval=2, factor_interval=1,
                           stat_decay=0.5, damping=0.003, kl_clip=0.001,
                           learning_rate=sched,
                           inverse_dtype=jnp.float32))

    rng = np.random.RandomState(0)
    B, S = 8, 16
    ids = rng.randint(5, 128, (B, S)).astype(np.int32)
    labels = np.full((B, S), -1, np.int32)
    for b in range(B):
        p = rng.randint(1, S - 1, 2)
        labels[b, p] = ids[b, p]
        ids[b, p] = 3
    batch = stack_microbatches({
        "input_ids": ids,
        "token_type_ids": np.zeros((B, S), np.int32),
        "attention_mask": np.ones((B, S), np.int32),
        "masked_lm_labels": labels,
        "next_sentence_labels": rng.randint(0, 2, (B,)).astype(np.int32),
    }, accum)

    init_fn = lambda r: model.init(r, jnp.asarray(batch["input_ids"][0]),
                                   jnp.asarray(batch["token_type_ids"][0]),
                                   jnp.asarray(batch["attention_mask"][0]))
    if mesh is not None:
        with mesh_lib.logical_rules():
            state, _ = make_sharded_state(jax.random.PRNGKey(0), init_fn, tx,
                                          mesh=mesh)
        batch = mesh_lib.host_to_device_batch(mesh, batch)
    else:
        state, _ = make_sharded_state(jax.random.PRNGKey(0), init_fn, tx)
        batch = {k: jnp.asarray(v) for k, v in batch.items()}
    state, pert_template = init_kfac_state(
        model, kfac, state, (batch["input_ids"][0],
                             batch["token_type_ids"][0],
                             batch["attention_mask"][0]))
    step_fn = build_kfac_pretrain_step(model, tx, kfac, pert_template,
                                       schedule=sched, accum_steps=accum)
    return model, kfac, step_fn, state, batch


def test_kfac_bert_step_runs_and_reduces_loss():
    _, kfac, step_fn, state, batch = _kfac_setup()
    jit_step = jax.jit(step_fn, donate_argnums=(0,))
    losses = []
    for i in range(8):
        state, metrics = jit_step(state, batch, jax.random.PRNGKey(i))
        losses.append(float(metrics["loss"]))
    assert np.isfinite(losses).all(), losses
    assert losses[-1] < losses[0], losses
    assert int(state.precond_state.count) == 8
    # factors actually accumulated (non-zero after EMA updates)
    a_leaf = jax.tree.leaves(state.precond_state.factors)[0]
    assert float(jnp.abs(a_leaf).sum()) > 0


@pytest.mark.slow  # re-tiered out of tier-1's 870s wall-clock budget
def test_kfac_step_invariant_to_data_sharding():
    """Multi-chip K-FAC correctness: the factor statistics contract over the
    batch dimension, which is sharded under SPMD — XLA must turn the local
    a^T a partial products into a global psum, so an 8-way data mesh on the
    same global batch must produce the same factors and the same parameter
    update as a single device (the reference allreduced factors explicitly
    through its comm backend; here the collective falls out of the einsum's
    sharding)."""
    from bert_pytorch_tpu.parallel import mesh as mesh_lib
    import contextlib

    def run(mesh_shape):
        mesh = (mesh_lib.make_mesh(mesh_shape)
                if mesh_shape is not None else None)
        _, _, step_fn, state, batch = _kfac_setup(mesh=mesh)
        jit_step = jax.jit(step_fn, donate_argnums=(0,))
        ctx = (contextlib.nullcontext() if mesh is None
               else contextlib.ExitStack())
        with ctx as stack:
            if mesh is not None:
                stack.enter_context(mesh)
                stack.enter_context(mesh_lib.logical_rules())
            for i in range(3):
                state, metrics = jit_step(state, batch, jax.random.PRNGKey(i))
            jax.block_until_ready(state.params)
        return state, float(metrics["loss"])

    state_1, loss_1 = run(None)
    state_8, loss_8 = run({"data": 8, "fsdp": 1, "model": 1, "seq": 1})

    assert abs(loss_1 - loss_8) < 1e-4, (loss_1, loss_8)
    for (pa, a), (_, b) in zip(
            jax.tree_util.tree_flatten_with_path(state_1.params)[0],
            jax.tree_util.tree_flatten_with_path(state_8.params)[0]):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=5e-4, atol=5e-5,
            err_msg=f"params diverge at {jax.tree_util.keystr(pa)}")
    for (pa, a), (_, b) in zip(
            jax.tree_util.tree_flatten_with_path(state_1.precond_state.factors)[0],
            jax.tree_util.tree_flatten_with_path(state_8.precond_state.factors)[0]):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=5e-4, atol=5e-5,
            err_msg=f"factors diverge at {jax.tree_util.keystr(pa)}")


def test_kfac_taps_present_only_when_enabled():
    model_on = BertForPreTraining(KFAC_TINY, dtype=jnp.float32)
    v = model_on.init(jax.random.PRNGKey(0), jnp.ones((2, 8), jnp.int32),
                      jnp.zeros((2, 8), jnp.int32), jnp.ones((2, 8), jnp.int32))
    assert "perturbations" in v
    sites = jax.tree.leaves(v["perturbations"])
    # qkv, attn output, mlp in, mlp out (stacked over layers) + pooler dense
    # and NSP head (unstacked) — reference preconditioned every supported
    # layer minus its skip-list (run_pretraining.py:311-345)
    assert len(sites) == 6
    flat = {"/".join(str(k.key) for k in p): x.shape
            for p, x in jax.tree_util.tree_flatten_with_path(
                v["perturbations"])[0]}
    assert any("pooler" in k for k in flat), flat
    assert any("cls_seq_relationship" in k for k in flat), flat

    model_off = BertForPreTraining(KFAC_TINY.replace(kfac_taps=False),
                                   dtype=jnp.float32)
    v2 = model_off.init(jax.random.PRNGKey(0), jnp.ones((2, 8), jnp.int32),
                        jnp.zeros((2, 8), jnp.int32),
                        jnp.ones((2, 8), jnp.int32))
    assert "perturbations" not in v2


@pytest.mark.slow  # re-tiered out of tier-1's 870s wall-clock budget
def test_kfac_taps_under_remat():
    """sow/perturb taps re-fire during nn.remat's recomputed forward:
    K-FAC under activation checkpointing must produce the same loss, grads,
    factor statistics and updated params as the un-rematted model (the
    reference ran K-FAC and checkpointing together,
    run_pretraining.py:257-258,311-345)."""
    def one_step(remat):
        cfg = KFAC_TINY.replace(checkpoint_activations=remat,
                                remat_policy="nothing",
                                hidden_dropout_prob=0.0,
                                attention_probs_dropout_prob=0.0)
        _, _, step_fn, state, batch = _kfac_setup(accum=2, cfg=cfg)
        state, metrics = jax.jit(step_fn)(state, batch, jax.random.PRNGKey(1))
        return state, metrics

    s0, m0 = one_step(False)
    s1, m1 = one_step(True)
    assert float(m0["loss"]) == pytest.approx(float(m1["loss"]), abs=1e-6)
    fd = jax.tree.map(lambda a, b: float(jnp.max(jnp.abs(a - b))),
                      s0.precond_state.factors, s1.precond_state.factors)
    # recomputed forwards can fuse differently; anything beyond fp32
    # round-off noise means a tap mis-fired under remat
    assert max(jax.tree.leaves(fd)) < 1e-6, "factor stats differ under remat"
    pd = jax.tree.map(lambda a, b: float(jnp.max(jnp.abs(a - b))),
                      s0.params, s1.params)
    assert max(jax.tree.leaves(pd)) < 1e-6, "params diverged under remat"


# --- coalesced factor reductions (--coalesce_reductions, round 15) -------


def _bucketed_setup(factor_bucket_bytes, sync_freq=1, coalesce_norms=True):
    """The kfac_zero1_dp8_bucketed wiring at test scale: zero1 plan +
    NormReducer + bucketed KFAC, exactly as run_pretraining/graphcheck
    build it."""
    from bert_pytorch_tpu.optim.lamb import default_trust_batch_axes
    from bert_pytorch_tpu.parallel import mesh as mesh_lib
    from bert_pytorch_tpu.parallel.coalesce import NormReducer
    from bert_pytorch_tpu.parallel.zero import make_zero1_plan

    mesh = mesh_lib.make_mesh()  # data=8
    model = BertForPreTraining(KFAC_TINY, dtype=jnp.float32)
    sched = schedulers.poly_warmup_schedule(1e-3, total_steps=100,
                                            warmup=0.1)
    rng = np.random.RandomState(0)
    B, S = 16, 16
    ids = rng.randint(5, 128, (B, S)).astype(np.int32)
    labels = np.full((B, S), -1, np.int32)
    for b in range(B):
        for p in rng.choice(np.arange(1, S - 1), 4, replace=False):
            labels[b, p] = ids[b, p]
            ids[b, p] = 3
    batch_np = stack_microbatches({
        "input_ids": ids,
        "token_type_ids": np.zeros((B, S), np.int32),
        "attention_mask": np.ones((B, S), np.int32),
        "masked_lm_labels": labels,
        "next_sentence_labels": rng.randint(0, 2, (B,)).astype(np.int32),
    }, 1)

    def init_fn(r):
        return model.init(r, jnp.asarray(batch_np["input_ids"][0]),
                          jnp.asarray(batch_np["token_type_ids"][0]),
                          jnp.asarray(batch_np["attention_mask"][0]))

    tx = lamb(sched, weight_decay=0.01,
              weight_decay_mask=default_weight_decay_mask,
              trust_batch_axes=default_trust_batch_axes)
    with mesh_lib.logical_rules():
        state, shardings = make_sharded_state(
            jax.random.PRNGKey(0), init_fn, tx, mesh=mesh, zero1=True)
    plan = make_zero1_plan(state.params, shardings.params, mesh,
                           warn_skipped=False)
    reducer = None
    if coalesce_norms and factor_bucket_bytes is not None:
        reducer = NormReducer(plan.grad_shardings, mesh)
        tx = lamb(sched, weight_decay=0.01,
                  weight_decay_mask=default_weight_decay_mask,
                  trust_batch_axes=default_trust_batch_axes,
                  norm_reducer=reducer)
    kfac = KFAC(KFACConfig(learning_rate=sched), mesh=mesh,
                factor_bucket_bytes=factor_bucket_bytes,
                factor_sync_freq=sync_freq)
    state, pert = init_kfac_state(
        model, kfac, state,
        (batch_np["input_ids"][0], batch_np["token_type_ids"][0],
         batch_np["attention_mask"][0]))
    step = build_kfac_pretrain_step(
        model, tx, kfac, pert, schedule=sched, max_predictions=4,
        zero1=plan, norm_reducer=reducer)
    batch = mesh_lib.host_to_device_batch(mesh, batch_np)
    return (mesh, state, jax.jit(step, donate_argnums=(0,)), kfac, batch)


def test_kfac_bucketed_stats_unit_parity():
    """The eager core of the coalescing claim, at unit scale (no XLA BERT
    compile — tier-1 cheap): partial contraction + bucketed psum equals
    the plain reduced statistics (allclose — the plain path's global dot
    groups its summation differently), bucket GRANULARITY is value-free
    bit for bit (psum of a concatenation IS the concatenation of psums),
    and the bucket assignment is deterministic, in site order, recorded
    for the run header. The full train-step restatement (loss
    trajectories, compiled all-reduce <= half) runs as the slow-marked
    test below; the compiled-count criterion is ALSO enforced tier-1 by
    the checked-in kfac_zero1_dp8_bucketed budget
    (tests/test_sharding_rules.py::test_checked_in_report_verifies_cleanly).
    """
    from bert_pytorch_tpu.parallel import mesh as mesh_lib

    mesh = mesh_lib.make_mesh()  # data=8
    rng = np.random.RandomState(0)
    B, S, DIN, DOUT, L = 16, 8, 16, 12, 2
    acts = {
        "site": (jnp.array(rng.randn(B, S, DIN).astype(np.float32)),),
        "layers": {"x": (jnp.array(
            rng.randn(L, B, S, DIN).astype(np.float32)),)},
    }
    perts = {
        "site": jnp.array(rng.randn(B, S, DOUT).astype(np.float32)),
        "layers": {"x": jnp.array(
            rng.randn(L, B, S, DOUT).astype(np.float32))},
    }
    plain = KFAC(KFACConfig()).compute_stats(acts, perts)

    def reduced(cap):
        k = KFAC(KFACConfig(), mesh=mesh, factor_bucket_bytes=cap)
        assert k.bucketed
        with mesh:
            partial = k.compute_stats(acts, perts)
            # every partial leaf grew the leading batch-shard axis and
            # compiled/executed ZERO collectives (pure local contraction)
            assert all(x.shape[0] == 8
                       for x in jax.tree.leaves(partial))
            return k, k._reduce_stats(partial)

    k_one, red_one = reduced(1)          # every factor its own bucket
    k_big, red_big = reduced(4 << 20)    # one coalesced bucket
    assert len(k_one.bucket_assignment) == 4  # A+G per site, 2 sites
    assert len(k_big.bucket_assignment) == 1
    assert k_big.bucket_assignment[0]["factors"][0].startswith("['layers']")
    for a, b in zip(jax.tree.leaves(red_one), jax.tree.leaves(red_big)):
        np.testing.assert_array_equal(
            np.asarray(a), np.asarray(b),
            err_msg="bucket granularity changed a reduced factor")
    for a, b in zip(jax.tree.leaves(plain), jax.tree.leaves(red_big)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-5)


@pytest.mark.slow
def test_kfac_bucketed_reduction_parity():
    """The round-15 acceptance pin, three claims at sync_freq=1:

    1. BUCKETED vs UNBUCKETED reductions bit-identical: cap=1 byte gives
       every factor its own reduction (one psum per factor — the
       unbucketed layout) vs the default cap packing them into one
       bucket; params AND factor state bit-equal over 3 steps, because
       psum of a concatenation IS the concatenation of psums.
    2. vs the LEGACY program (factor_bucket_bytes=None — GSPMD's own
       per-site reductions, which replicate activations for some sites
       and therefore sum in a different grouping): loss trajectory equal
       step for step, factor state allclose at reduction-reorder
       tolerance. Deliberately not bit-equal — docs/PERF.md round 15.
    3. the compiled all-reduce count of the bucketed program is <= HALF
       the legacy one (the collective_budget ceiling checked in for
       kfac_zero1_dp8_bucketed enforces the same on the production gate
       model).
    """
    from bert_pytorch_tpu.analysis import collective_counts
    from bert_pytorch_tpu.parallel import mesh as mesh_lib

    mesh, s_leg, step_leg, _, batch = _bucketed_setup(None)
    _, s_one, step_one, k_one, _ = _bucketed_setup(1)
    _, s_big, step_big, k_big, _ = _bucketed_setup(4 << 20)
    assert len(k_one.bucket_assignment) > 1  # per-factor reductions
    assert len(k_big.bucket_assignment) == 1  # one coalesced bucket
    counts = {}
    with mesh, mesh_lib.logical_rules():
        for name, st, fn in (("legacy", s_leg, step_leg),
                             ("bucketed", s_big, step_big)):
            counts[name] = collective_counts(
                fn.lower(st, batch, jax.random.PRNGKey(0))
                .compile().as_text())
        for i in range(3):
            s_leg, m_leg = step_leg(s_leg, batch, jax.random.PRNGKey(i))
            s_one, m_one = step_one(s_one, batch, jax.random.PRNGKey(i))
            s_big, m_big = step_big(s_big, batch, jax.random.PRNGKey(i))
            assert float(m_leg["loss"]) == float(m_big["loss"]), f"step {i}"
            assert float(m_one["loss"]) == float(m_big["loss"]), f"step {i}"
    assert counts["bucketed"]["all-reduce"] \
        <= counts["legacy"]["all-reduce"] // 2, counts
    # claim 1: bucket granularity cannot change a bit
    for what, ta, tb in (
            ("params", s_one.params, s_big.params),
            ("factors", s_one.precond_state.factors,
             s_big.precond_state.factors),
            ("inverses", s_one.precond_state.inverses,
             s_big.precond_state.inverses),
            ("mu", s_one.opt_state.mu, s_big.opt_state.mu)):
        for a, b in zip(jax.tree.leaves(ta), jax.tree.leaves(tb)):
            np.testing.assert_array_equal(
                np.asarray(a), np.asarray(b),
                err_msg=f"{what}: bucket cap changed the update")
    # claim 2: vs legacy — reduction-reorder tolerance
    for a, b in zip(jax.tree.leaves(s_leg.precond_state.factors),
                    jax.tree.leaves(s_big.precond_state.factors)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=1e-6)
    for a, b in zip(jax.tree.leaves(s_leg.params),
                    jax.tree.leaves(s_big.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-5, atol=1e-6)


def test_kfac_factor_sync_freq_skips_offstep_ema():
    """--kfac_factor_sync_freq=2: factors sync (reduce + EMA) on even
    counts only; the off step leaves the factor state bit-unchanged, the
    on step applies the bucketed reduction inside the cond's true
    branch. Eager at unit scale — the full-step restatement rides the
    slow parity test."""
    from bert_pytorch_tpu.parallel import mesh as mesh_lib

    mesh = mesh_lib.make_mesh()  # data=8
    rng = np.random.RandomState(1)
    B, S, DIN, DOUT = 16, 4, 6, 5
    acts = {"x": (jnp.array(rng.randn(B, S, DIN).astype(np.float32)),)}
    perts = {"x": jnp.array(rng.randn(B, S, DOUT).astype(np.float32))}
    grads = {"x": {"kernel": jnp.array(
        rng.randn(DIN, DOUT).astype(np.float32)),
        "bias": jnp.array(rng.randn(DOUT).astype(np.float32))}}
    kfac = KFAC(KFACConfig(), mesh=mesh, factor_bucket_bytes=4 << 20,
                factor_sync_freq=2)
    with mesh:
        # tap name 'x' (no _tap suffix needed at unit scale): precondition
        # strips the suffix only when present
        state = kfac.init(acts, perts)
        stats = kfac.compute_stats(acts, perts)
        s1, _ = kfac.step(state, stats, grads, lr=1.0)   # count 0: sync
        s2, _ = kfac.step(s1, stats, grads, lr=1.0)      # count 1: skip
        s3, _ = kfac.step(s2, stats, grads, lr=1.0)      # count 2: sync
    f1 = jax.tree.leaves(jax.tree.map(np.asarray, s1.factors))
    f2 = jax.tree.leaves(jax.tree.map(np.asarray, s2.factors))
    f3 = jax.tree.leaves(jax.tree.map(np.asarray, s3.factors))
    for a, b in zip(f1, f2):
        np.testing.assert_array_equal(a, b)  # off step: EMA skipped
    assert any(not np.array_equal(a, b) for a, b in zip(f2, f3)), \
        "on step must update the factor EMA"
    assert int(s3.count) == 3


def test_kfac_bucketed_nondivisible_fallback_warns(capsys):
    """Rows that don't divide the batch-shard count cannot bucket: the
    instance falls back to the per-factor path with ONE loud warning
    naming the site, keeps producing REDUCED stats (training continues),
    and stays fallen back (the batch shape is fixed per run)."""
    from bert_pytorch_tpu.parallel import mesh as mesh_lib

    mesh = mesh_lib.make_mesh()  # data=8
    kfac = KFAC(KFACConfig(), mesh=mesh, factor_bucket_bytes=4 << 20)
    assert kfac.bucketed
    rng = np.random.RandomState(0)
    B, S, DIN, DOUT = 12, 8, 16, 12  # 12 % 8 != 0
    acts = {"site": (jnp.array(rng.randn(B, S, DIN).astype(np.float32)),)}
    perts = {"site": jnp.array(rng.randn(B, S, DOUT).astype(np.float32))}
    stats = kfac.compute_stats(acts, perts)
    err = capsys.readouterr().err
    assert "WARNING: kfac: bucketed factor reductions DISABLED" in err
    assert "site" in err
    assert not kfac.bucketed
    # the fallback produced REDUCED stats identical to a plain instance's
    plain = KFAC(KFACConfig()).compute_stats(acts, perts)
    np.testing.assert_array_equal(np.asarray(stats["site"]["A"]),
                                  np.asarray(plain["site"]["A"]))
    # the warning is once-per-instance
    kfac.compute_stats(acts, perts)
    assert "DISABLED" not in capsys.readouterr().err


# -- bf16 factor statistics (--kfac_stats_dtype, round 16) ------------------


def test_kfac_bf16_stats_keep_f32_trajectory():
    """--kfac_stats_dtype bf16 halves the statistics bytes on the wire;
    this pins everything the thinning is NOT allowed to change:

    1. stats_dtype=None emits statistics in factor_dtype — the literal
       round-15 tree (bit for bit), so the default program cannot move
       (the compiled-identity half of that claim is the graphcheck
       budgets staying byte-identical).
    2. bf16 statistics land as bf16 arrays (the cast is on the wire, not
       cosmetic) and agree with the f32 statistics to bf16 rounding.
    3. The EMA accumulator never thins: factors driven by bf16 stats rest
       in f32 and track the f32-stats trajectory within bf16 rounding —
       no drift accumulation, because each step's error enters through a
       (1 - stat_decay)-weighted term.
    4. The bucketed reduction upcasts BEFORE summing: reduced factors of
       bf16 partials come back f32 and match the plain f32 reduction to
       input-rounding tolerance (no bf16 partial-sum cascade).
    """
    from bert_pytorch_tpu.parallel import mesh as mesh_lib

    rng = np.random.RandomState(3)
    B, S, DIN, DOUT, L = 16, 8, 16, 12, 2
    acts = {
        "site": (jnp.array(rng.randn(B, S, DIN).astype(np.float32)),),
        "layers": {"x": (jnp.array(
            rng.randn(L, B, S, DIN).astype(np.float32)),)},
    }
    perts = {
        "site": jnp.array(rng.randn(B, S, DOUT).astype(np.float32)),
        "layers": {"x": jnp.array(
            rng.randn(L, B, S, DOUT).astype(np.float32))},
    }
    k32 = KFAC(KFACConfig())
    kbf = KFAC(KFACConfig(stats_dtype=jnp.bfloat16))

    s32 = k32.compute_stats(acts, perts)
    sbf = kbf.compute_stats(acts, perts)
    sdefault = KFAC(KFACConfig(stats_dtype=None)).compute_stats(acts, perts)
    for a, b in zip(jax.tree.leaves(s32), jax.tree.leaves(sdefault)):
        assert a.dtype == b.dtype == jnp.float32
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    for a, b in zip(jax.tree.leaves(s32), jax.tree.leaves(sbf)):
        assert b.dtype == jnp.bfloat16
        np.testing.assert_allclose(np.asarray(a),
                                   np.asarray(b, dtype=np.float32),
                                   rtol=2e-2, atol=2e-2)

    # 3-step factor EMA, each step on a fresh stats draw
    f32 = jax.tree.map(lambda s: jnp.zeros_like(s), s32)
    fbf = jax.tree.map(
        lambda s: jnp.zeros_like(s, dtype=jnp.float32), sbf)
    for i in range(3):
        scale = 1.0 + 0.25 * i
        a_i = jax.tree.map(lambda x: x * scale, acts)
        f32 = k32._update_factors(f32, k32.compute_stats(a_i, perts))
        fbf = kbf._update_factors(fbf, kbf.compute_stats(a_i, perts))
    for a, b in zip(jax.tree.leaves(f32), jax.tree.leaves(fbf)):
        assert b.dtype == jnp.float32, "bf16 stats thinned the EMA rest"
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-2, atol=2e-2)

    mesh = mesh_lib.make_mesh()  # data=8
    kb32 = KFAC(KFACConfig(), mesh=mesh, factor_bucket_bytes=4 << 20)
    kbbf = KFAC(KFACConfig(stats_dtype=jnp.bfloat16), mesh=mesh,
                factor_bucket_bytes=4 << 20)
    assert kb32.bucketed and kbbf.bucketed
    with mesh:
        red32 = kb32._reduce_stats(kb32.compute_stats(acts, perts))
        redbf = kbbf._reduce_stats(kbbf.compute_stats(acts, perts))
    for a, b in zip(jax.tree.leaves(red32), jax.tree.leaves(redbf)):
        assert b.dtype == jnp.float32, "reduction failed to upcast"
        # the contraction of bf16-rounded inputs cancels on the small
        # off-diagonal entries, so the bound is relative to the factor's
        # SCALE (its largest entry), not elementwise — a bf16 partial-sum
        # cascade would blow through this by orders of magnitude
        a, b = np.asarray(a), np.asarray(b)
        assert np.max(np.abs(a - b)) <= 3e-2 * np.max(np.abs(a)) + 1e-6, (
            np.max(np.abs(a - b)), np.max(np.abs(a)))


@pytest.mark.slow
def test_kfac_zero1_rs_bit_identical():
    """--zero1_rs under the full K-FAC step: the psum_scatter gradient
    exit vs the rs_mode='allreduce' arm of the SAME shard_map program —
    params/mu/nu/loss bit-identical over 3 steps while the HLO trades
    all-reduces for reduce-scatters at an unchanged all-gather count.
    This is the budget-combo kfac_zero1_rs_dp8's value-level complement:
    graphcheck pins the counts, this pins that the cheaper program is the
    same training run. (The factor-statistics psums are untouched by the
    rs rewrite — they live outside the shard_map region — which is why
    bucketed K-FAC composes with rs at all.)"""
    from bert_pytorch_tpu.analysis import collective_counts
    from bert_pytorch_tpu.optim.lamb import default_trust_batch_axes
    from bert_pytorch_tpu.parallel import mesh as mesh_lib
    from bert_pytorch_tpu.parallel.coalesce import NormReducer
    from bert_pytorch_tpu.parallel.zero import make_zero1_plan

    mesh = mesh_lib.make_mesh()  # data=8
    model = BertForPreTraining(KFAC_TINY, dtype=jnp.float32)
    sched = schedulers.poly_warmup_schedule(1e-3, total_steps=100,
                                            warmup=0.1)
    rng = np.random.RandomState(0)
    B, S = 16, 16
    ids = rng.randint(5, 128, (B, S)).astype(np.int32)
    labels = np.full((B, S), -1, np.int32)
    for b in range(B):
        p = rng.randint(1, S - 1, 2)
        labels[b, p] = ids[b, p]
        ids[b, p] = 3
    sample = stack_microbatches({
        "input_ids": ids,
        "token_type_ids": np.zeros((B, S), np.int32),
        "attention_mask": np.ones((B, S), np.int32),
        "masked_lm_labels": labels,
        "next_sentence_labels": rng.randint(0, 2, (B,)).astype(np.int32),
    }, 1)
    init_fn = lambda r: model.init(
        r, jnp.asarray(sample["input_ids"][0]),
        jnp.asarray(sample["token_type_ids"][0]),
        jnp.asarray(sample["attention_mask"][0]))

    def make(rs_mode):
        with mesh_lib.logical_rules():
            state, shardings = make_sharded_state(
                jax.random.PRNGKey(0), init_fn, tx=lamb(
                    sched, weight_decay=0.01,
                    weight_decay_mask=default_weight_decay_mask,
                    trust_batch_axes=default_trust_batch_axes),
                mesh=mesh, zero1=True, zero1_params=True)
        plan = make_zero1_plan(state.params, shardings.params, mesh,
                               gather_on_use=True, reduce_scatter=True,
                               warn_skipped=False)
        plan = plan._replace(rs_mode=rs_mode)
        reducer = NormReducer(plan.grad_shardings, mesh)
        tx = lamb(sched, weight_decay=0.01,
                  weight_decay_mask=default_weight_decay_mask,
                  trust_batch_axes=default_trust_batch_axes,
                  norm_reducer=reducer)
        kfac = KFAC(KFACConfig(learning_rate=sched), mesh=mesh,
                    factor_bucket_bytes=4 << 20)
        st, pert = init_kfac_state(
            model, kfac, state,
            (sample["input_ids"][0], sample["token_type_ids"][0],
             sample["attention_mask"][0]))
        step = build_kfac_pretrain_step(
            model, tx, kfac, pert, schedule=sched, max_predictions=4,
            zero1=plan, norm_reducer=reducer)
        return st, jax.jit(step, donate_argnums=(0,))

    batch = mesh_lib.host_to_device_batch(mesh, sample)
    states, steps, counts, losses = {}, {}, {}, {}
    with mesh, mesh_lib.logical_rules():
        for mode in ("scatter", "allreduce"):
            st, fn = make(mode)
            compiled = fn.lower(st, batch, jax.random.PRNGKey(0)).compile()
            counts[mode] = collective_counts(compiled.as_text())
            states[mode], steps[mode] = st, fn
        for i in range(3):
            for mode in states:
                states[mode], m = steps[mode](states[mode], batch,
                                              jax.random.PRNGKey(i))
                losses.setdefault(mode, []).append(float(m["loss"]))

    assert counts["scatter"]["reduce-scatter"] > 0, counts["scatter"]
    assert counts["allreduce"]["reduce-scatter"] == 0, counts["allreduce"]
    assert counts["scatter"]["all-reduce"] < \
        counts["allreduce"]["all-reduce"], counts
    assert counts["scatter"]["all-gather"] == \
        counts["allreduce"]["all-gather"], counts

    assert losses["scatter"] == losses["allreduce"], losses
    sc, ar = states["scatter"], states["allreduce"]
    for what, a_tree, b_tree in (
            ("params", sc.params, ar.params),
            ("mu", sc.opt_state.mu, ar.opt_state.mu),
            ("nu", sc.opt_state.nu, ar.opt_state.nu),
            ("factors", sc.precond_state.factors,
             ar.precond_state.factors)):
        for a, b in zip(jax.tree.leaves(a_tree), jax.tree.leaves(b_tree)):
            np.testing.assert_array_equal(
                np.asarray(a), np.asarray(b),
                err_msg=f"{what} not bit-identical after 3 steps")
