"""Tokenization tests: WordPiece greedy matching, basic tokenizer unicode
handling, encode() framing/offsets, byte-level BPE roundtrip."""

import pytest

from bert_pytorch_tpu.data.tokenization import (
    BasicTokenizer,
    BertWordPieceTokenizer,
    ByteLevelBPETokenizer,
    WordpieceTokenizer,
    bytes_to_unicode,
    load_vocab,
    whitespace_tokenize,
)

VOCAB_TOKENS = [
    "[PAD]", "[UNK]", "[CLS]", "[SEP]", "[MASK]",
    "the", "quick", "brown", "fox", "jump", "##ed", "##s", "over", "lazy",
    "dog", ",", ".", "un", "##want", "##ed", "runn", "##ing", "hello",
    "world", "!",
]


@pytest.fixture
def vocab(tmp_path):
    p = tmp_path / "vocab.txt"
    p.write_text("\n".join(VOCAB_TOKENS) + "\n")
    return str(p)


def test_load_vocab_order(vocab):
    v = load_vocab(vocab)
    assert v["[PAD]"] == 0 and v["[MASK]"] == 4 and v["the"] == 5


def test_basic_tokenizer_lower_punct_accents():
    bt = BasicTokenizer(do_lower_case=True)
    assert bt.tokenize("Hello, World!") == ["hello", ",", "world", "!"]
    assert bt.tokenize("  héllo ") == ["hello"]
    assert bt.tokenize("ah博推zz") == ["ah", "博", "推", "zz"]
    bt2 = BasicTokenizer(do_lower_case=False)
    assert bt2.tokenize("HeLLo") == ["HeLLo"]
    # control chars stripped, whitespace normalized
    assert bt.tokenize("a\x00b c") == ["ab", "c"]


def test_wordpiece_greedy_longest_match(vocab):
    wp = WordpieceTokenizer(load_vocab(vocab))
    assert wp.tokenize("unwanted") == ["un", "##want", "##ed"]
    assert wp.tokenize("running") == ["runn", "##ing"]
    assert wp.tokenize("jumped") == ["jump", "##ed"]
    assert wp.tokenize("unwantedx") == ["[UNK]"]  # no match for tail -> UNK
    assert wp.tokenize("") == []


def test_full_tokenizer_and_encode(vocab):
    tok = BertWordPieceTokenizer(vocab, lowercase=True)
    assert tok.tokenize("Unwanted, running!") == \
        ["un", "##want", "##ed", ",", "runn", "##ing", "!"]

    enc = tok.encode("the quick fox")
    assert enc.tokens[0] == "[CLS]" and enc.tokens[-1] == "[SEP]"
    assert enc.ids == [tok.token_to_id(t) for t in enc.tokens]
    assert enc.type_ids == [0] * len(enc.ids)

    pair = tok.encode("the fox", pair="lazy dog")
    assert pair.tokens.count("[SEP]") == 2
    # type_ids: 0 for first seq + its SEP, 1 for second
    sep1 = pair.tokens.index("[SEP]")
    assert all(t == 0 for t in pair.type_ids[:sep1 + 1])
    assert all(t == 1 for t in pair.type_ids[sep1 + 1:])


def test_encode_offsets_point_into_original_text(vocab):
    tok = BertWordPieceTokenizer(vocab, lowercase=True)
    text = "The unwanted dog."
    enc = tok.encode(text)
    # find the wordpieces of "unwanted": all three share the word span
    i = enc.tokens.index("un")
    for j in (i, i + 1, i + 2):
        s, e = enc.offsets[j]
        assert text[s:e] == "unwanted"
    # "dog" span
    k = enc.tokens.index("dog")
    s, e = enc.offsets[k]
    assert text[s:e] == "dog"


def test_unknown_word_maps_to_unk(vocab):
    tok = BertWordPieceTokenizer(vocab, lowercase=True)
    enc = tok.encode("xyzzy")
    assert "[UNK]" in enc.tokens


def test_bytes_to_unicode_bijection():
    table = bytes_to_unicode()
    assert len(table) == 256
    assert len(set(table.values())) == 256


def _tiny_bpe():
    # vocab over the byte-encoded alphabet; 'Ġ' is the space marker
    base = bytes_to_unicode()
    sp = base[ord(" ")]
    tokens = [sp + "hello", sp + "world", sp, "h", "e", "l", "o", "w", "r",
              "d", "he", "hel", "hell", "hello", "wo", "wor", "worl",
              "world", "<unk>"]
    vocab = {t: i for i, t in enumerate(tokens)}
    merges = [("h", "e"), ("he", "l"), ("hel", "l"), ("hell", "o"),
              ("w", "o"), ("wo", "r"), ("wor", "l"), ("worl", "d"),
              (sp, "hello"), (sp, "world")]
    return vocab, merges


def test_byte_level_bpe_encode_decode():
    vocab, merges = _tiny_bpe()
    tok = ByteLevelBPETokenizer(vocab, merges, add_prefix_space=True)
    enc = tok.encode("hello world")
    sp = bytes_to_unicode()[ord(" ")]
    assert enc.tokens == [sp + "hello", sp + "world"]
    assert tok.decode(enc.ids) == " hello world"


def test_whitespace_tokenize():
    assert whitespace_tokenize("  a  b \n c ") == ["a", "b", "c"]
    assert whitespace_tokenize("   ") == []
