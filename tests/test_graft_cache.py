"""The multichip dryrun's compile-cache hygiene: cached XLA executables may
only ever come from runs that passed the zero-reshard gate, because the
"Involuntary full rematerialization" warning fires at compile time and a
warm cache hit skips the compile (and the warning) entirely.

These tests drive dryrun_multichip's parent branch with a monkeypatched
child so no real compilation happens; the real child path is covered by the
driver's MULTICHIP run and the standalone dryrun."""

import os
import subprocess
import sys

import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import __graft_entry__ as graft


class _FakeProc:
    def __init__(self, rc=0, stdout="", stderr=""):
        self.returncode = rc
        self.stdout = stdout
        self.stderr = stderr


@pytest.fixture
def cachedir(tmp_path, monkeypatch):
    """Point the dryrun at a scratch repo dir with a pre-populated cache."""
    here = tmp_path / "repo"
    here.mkdir()
    cache = here / ".jax_cache"
    cache.mkdir()
    (cache / "jit_entry-cache").write_text("fake executable")
    monkeypatch.setattr(graft, "__file__", str(here / "__graft_entry__.py"))
    monkeypatch.setenv("BPT_DRYRUN_FORCE_VIRTUAL", "1")
    monkeypatch.delenv(graft._CHILD_MARKER, raising=False)
    monkeypatch.setattr(graft, "_assert_reshard_gate_alive", lambda: None)
    return cache


def _run(monkeypatch, rc=0, stderr=""):
    monkeypatch.setattr(
        subprocess, "run",
        lambda *a, **kw: _FakeProc(rc=rc, stderr=stderr))
    graft.dryrun_multichip(8)


def test_pass_keeps_cache_and_clears_marker(cachedir, monkeypatch):
    _run(monkeypatch, rc=0)
    assert (cachedir / "jit_entry-cache").exists()
    assert not os.path.exists(str(cachedir) + ".dirty")


def test_child_failure_wipes_cache(cachedir, monkeypatch):
    with pytest.raises(RuntimeError, match="child failed"):
        _run(monkeypatch, rc=1)
    assert not cachedir.exists()
    assert not os.path.exists(str(cachedir) + ".dirty")


def test_reshard_warning_wipes_cache(cachedir, monkeypatch):
    with pytest.raises(RuntimeError, match="resharding warnings"):
        _run(monkeypatch, rc=0,
             stderr=f"blah {graft._RESHARD_WARNING} of op %foo\n")
    assert not cachedir.exists()


def test_stale_dirty_marker_wipes_at_launch(cachedir, monkeypatch):
    """A previous run that died before its gate verdict (Ctrl-C, OOM-kill)
    leaves the marker; the next run must not trust the cache."""
    with open(str(cachedir) + ".dirty", "w"):
        pass
    seen = {}

    def fake_run(*a, **kw):
        # by child-launch time the tainted cache must already be gone
        # (recreated empty) — the fake "executable" must not survive
        seen["entry_gone"] = not (cachedir / "jit_entry-cache").exists()
        return _FakeProc(rc=0)

    monkeypatch.setattr(subprocess, "run", fake_run)
    graft.dryrun_multichip(8)
    assert seen["entry_gone"]
    assert not os.path.exists(str(cachedir) + ".dirty")


def test_timeout_wipes_cache(cachedir, monkeypatch):
    def fake_run(*a, **kw):
        raise subprocess.TimeoutExpired(cmd="x", timeout=1800)

    monkeypatch.setattr(subprocess, "run", fake_run)
    with pytest.raises(RuntimeError, match="timed out"):
        graft.dryrun_multichip(8)
    assert not cachedir.exists()
