"""Encoder parameter-layout tests: stacked (nn.scan, leading (L, ...) axis)
vs unstacked (per-layer encoder/layer_{i} modules, config.stacked_params=
False). Covers bit-exact conversion round trips in BOTH directions —
including LAMB moments and K-FAC factor state — forward/grad parity between
the two encoder builds, cross-layout checkpoint restore, and TF-checkpoint
import straight into the unstacked layout."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from bert_pytorch_tpu.config import BertConfig
from bert_pytorch_tpu.models import BertForPreTraining, losses
from bert_pytorch_tpu.models.pretrained import (
    convert_tree_layout,
    stack_layer_tree,
    tree_layout,
    unstack_layer_tree,
)
from bert_pytorch_tpu.optim.lamb import (
    default_trust_batch_axes,
    default_weight_decay_mask,
    lamb,
)
from bert_pytorch_tpu.training import (
    CheckpointManager,
    TrainState,
    build_pretrain_step,
    make_sharded_state,
)
from bert_pytorch_tpu.training.pretrain import stack_microbatches
from bert_pytorch_tpu.training.state import unbox

TINY = BertConfig(
    vocab_size=128, hidden_size=32, num_hidden_layers=3,
    num_attention_heads=4, intermediate_size=64,
    max_position_embeddings=64, next_sentence=True,
    dtype="float32", fused_ops=False, attention_impl="xla",
    hidden_dropout_prob=0.0, attention_probs_dropout_prob=0.0,
)
UNSTACKED = TINY.replace(stacked_params=False)


def _inputs(batch=2, seq=16, seed=0):
    rng = np.random.RandomState(seed)
    ids = rng.randint(5, TINY.vocab_size, (batch, seq)).astype(np.int32)
    types = rng.randint(0, 2, (batch, seq)).astype(np.int32)
    mask = np.ones((batch, seq), np.int32)
    return jnp.array(ids), jnp.array(types), jnp.array(mask)


def _init_params(cfg, seed=0):
    ids, types, mask = _inputs()
    model = BertForPreTraining(cfg, dtype=jnp.float32)
    params = unbox(model.init(jax.random.PRNGKey(seed), ids, types, mask)
                   ["params"])
    return model, params


def _assert_trees_equal(a, b, exact=True):
    assert (jax.tree_util.tree_structure(a)
            == jax.tree_util.tree_structure(b))
    if exact:
        jax.tree.map(lambda x, y: np.testing.assert_array_equal(
            np.asarray(x), np.asarray(y)), a, b)
    else:
        jax.tree.map(lambda x, y: np.testing.assert_allclose(
            np.asarray(x), np.asarray(y), rtol=1e-6, atol=1e-7), a, b)


def test_param_layout_roundtrip_bit_exact_both_directions():
    _, ps = _init_params(TINY)
    _, pu = _init_params(UNSTACKED)
    assert tree_layout(ps) == "stacked"
    assert tree_layout(pu) == "unstacked"

    # stacked -> unstacked: structure matches a fresh unstacked init
    conv = unstack_layer_tree(ps)
    assert (jax.tree_util.tree_structure(conv)
            == jax.tree_util.tree_structure(pu))
    # -> back: bit-exact
    _assert_trees_equal(stack_layer_tree(conv), ps)

    # unstacked -> stacked -> back: bit-exact the other way round
    conv2 = stack_layer_tree(pu)
    assert (jax.tree_util.tree_structure(conv2)
            == jax.tree_util.tree_structure(ps))
    _assert_trees_equal(unstack_layer_tree(conv2), pu)


def test_boxed_init_roundtrip_preserves_partition_metadata():
    """Converting the BOXED init tree must strip/restore the leading
    'layers' logical-axis name so sharding annotations stay valid."""
    ids, types, mask = _inputs()
    boxed_s = BertForPreTraining(TINY, dtype=jnp.float32).init(
        jax.random.PRNGKey(0), ids, types, mask)["params"]
    boxed_u = BertForPreTraining(UNSTACKED, dtype=jnp.float32).init(
        jax.random.PRNGKey(0), ids, types, mask)["params"]
    conv = unstack_layer_tree(boxed_s)
    # structure equality covers the partition names (they live in the
    # pytree treedef of flax's Partitioned boxes)
    assert (jax.tree_util.tree_structure(conv)
            == jax.tree_util.tree_structure(boxed_u))
    back = stack_layer_tree(conv)
    assert (jax.tree_util.tree_structure(back)
            == jax.tree_util.tree_structure(boxed_s))
    jax.tree.map(lambda a, b: np.testing.assert_array_equal(
        np.asarray(a), np.asarray(b)), boxed_s, back)


def test_forward_and_grad_parity_between_layouts():
    """Same weights through both encoder builds: identical forward, grads
    equal to float tolerance (the unrolled Python loop and the unrolled
    scan may schedule reductions differently)."""
    ids, types, mask = _inputs()
    m_s, ps = _init_params(TINY)
    m_u = BertForPreTraining(UNSTACKED, dtype=jnp.float32)
    pu = unstack_layer_tree(ps)

    out_s, nsp_s = m_s.apply({"params": ps}, ids, types, mask)
    out_u, nsp_u = m_u.apply({"params": pu}, ids, types, mask)
    np.testing.assert_array_equal(np.asarray(out_s), np.asarray(out_u))
    np.testing.assert_array_equal(np.asarray(nsp_s), np.asarray(nsp_u))

    labels = np.full((2, 16), -1, np.int32)
    labels[0, 3], labels[1, 5] = 7, 11
    labels = jnp.array(labels)
    nsl = jnp.array([0, 1], np.int32)

    def make_loss(model):
        def loss(p):
            ml, nl = model.apply({"params": p}, ids, types, mask)
            return losses.pretraining_loss(ml, labels, nl, nsl)
        return loss

    gs = jax.grad(make_loss(m_s))(ps)
    gu = jax.grad(make_loss(m_u))(pu)
    _assert_trees_equal(stack_layer_tree(gu), gs, exact=False)


def test_train_step_parity_between_layouts_on_mesh():
    """One jitted LAMB train step per layout on the 8-device CPU mesh:
    losses match and the updated params agree (converted for comparison).
    Exercises the logical-rule resolution without the 'layers' axis and the
    per-layer trust ratios of the unstacked path."""
    from bert_pytorch_tpu.parallel import mesh as mesh_lib

    mesh = mesh_lib.make_mesh()
    rng = np.random.RandomState(3)
    gb, seq = 16, 16
    ids = rng.randint(5, TINY.vocab_size, (gb, seq)).astype(np.int32)
    labels = np.full((gb, seq), -1, np.int32)
    for b in range(gb):
        p = rng.randint(1, seq - 1)
        labels[b, p] = ids[b, p]
    batch = stack_microbatches({
        "input_ids": ids,
        "token_type_ids": np.zeros((gb, seq), np.int32),
        "attention_mask": np.ones((gb, seq), np.int32),
        "masked_lm_labels": labels,
        "next_sentence_labels": rng.randint(0, 2, (gb,)).astype(np.int32),
    }, 1)
    batch = {k: jnp.asarray(v) for k, v in batch.items()}

    results = {}
    for name, cfg in (("stacked", TINY), ("unstacked", UNSTACKED)):
        model = BertForPreTraining(cfg, dtype=jnp.float32)
        tx = lamb(1e-3, weight_decay=0.01,
                  weight_decay_mask=default_weight_decay_mask,
                  trust_batch_axes=default_trust_batch_axes)
        step_fn = build_pretrain_step(model, tx)

        def init_fn(r, model=model):
            return model.init(r, batch["input_ids"][0],
                              batch["token_type_ids"][0],
                              batch["attention_mask"][0])

        with mesh_lib.logical_rules():
            state, _ = make_sharded_state(jax.random.PRNGKey(0), init_fn,
                                          tx, mesh=mesh)
        if name == "unstacked":
            # same starting weights as the stacked run, converted
            state = TrainState(step=state.step,
                               params=unstack_layer_tree(
                                   results["stacked"][2]),
                               opt_state=convert_tree_layout(
                                   results["stacked"][3], stacked=False))
        start_params, start_opt = state.params, state.opt_state
        with mesh, mesh_lib.logical_rules():
            state, metrics = jax.jit(step_fn)(state, batch,
                                              jax.random.PRNGKey(1))
        results[name] = (float(metrics["loss"]), state.params,
                         start_params, start_opt)

    loss_s, new_s = results["stacked"][0], results["stacked"][1]
    loss_u, new_u = results["unstacked"][0], results["unstacked"][1]
    np.testing.assert_allclose(loss_u, loss_s, rtol=1e-6)
    _assert_trees_equal(stack_layer_tree(new_u), new_s, exact=False)


def test_optimizer_state_conversion_roundtrip():
    _, ps = _init_params(TINY)
    tx = lamb(1e-3, weight_decay_mask=default_weight_decay_mask,
              trust_batch_axes=default_trust_batch_axes)
    opt = tx.init(ps)
    # put nonzero content into the moments so the test is not vacuous
    grads = jax.tree.map(lambda p: jnp.full_like(p, 0.01), ps)
    _, opt = tx.update(grads, opt, ps)

    down = convert_tree_layout(opt, stacked=False)
    assert tree_layout(down.mu) == "unstacked"
    _assert_trees_equal(convert_tree_layout(down, stacked=True), opt)


@pytest.mark.slow  # re-tiered out of tier-1's 870s wall-clock budget
def test_kfac_state_conversion_and_unstacked_step():
    """K-FAC taps/factors work per layer under the unstacked layout, and a
    stacked KFACState converts to the unstacked tap-tree structure and back
    bit-exact."""
    from bert_pytorch_tpu.optim.kfac import KFAC, KFACConfig
    from bert_pytorch_tpu.training import init_kfac_state
    from bert_pytorch_tpu.training.pretrain import build_kfac_pretrain_step

    ids, types, mask = _inputs()
    rng = np.random.RandomState(5)
    labels = np.full((2, 16), -1, np.int32)
    labels[0, 3], labels[1, 5] = 7, 11
    batch = stack_microbatches({
        "input_ids": np.asarray(ids),
        "token_type_ids": np.asarray(types),
        "attention_mask": np.asarray(mask),
        "masked_lm_labels": labels,
        "next_sentence_labels": rng.randint(0, 2, (2,)).astype(np.int32),
    }, 1)
    batch = {k: jnp.asarray(v) for k, v in batch.items()}

    start = {}  # same starting weights for both layouts
    states = {}
    for name, cfg in (("stacked", TINY), ("unstacked", UNSTACKED)):
        model = BertForPreTraining(cfg.replace(kfac_taps=True),
                                   dtype=jnp.float32)
        kfac = KFAC(KFACConfig(learning_rate=1e-3))
        tx = lamb(1e-3, weight_decay_mask=default_weight_decay_mask,
                  trust_batch_axes=default_trust_batch_axes)

        def init_fn(r, model=model):
            return model.init(r, ids, types, mask)

        state, _ = make_sharded_state(jax.random.PRNGKey(0), init_fn, tx)
        if name == "stacked":
            start["params"] = state.params
            start["opt"] = state.opt_state
        else:
            state = TrainState(
                step=state.step,
                params=unstack_layer_tree(start["params"]),
                opt_state=convert_tree_layout(start["opt"], stacked=False))
        state, pert = init_kfac_state(model, kfac, state,
                                      (ids, types, mask))
        step_fn = build_kfac_pretrain_step(model, tx, kfac, pert,
                                           accum_steps=1)
        new_state, metrics = jax.jit(step_fn)(state, batch,
                                              jax.random.PRNGKey(2))
        assert np.isfinite(float(metrics["loss"]))
        states[name] = new_state

    # the two runs optimize the same function: same loss trajectory start
    # and the stacked KFACState converts to the unstacked structure + back
    kstate_s = states["stacked"].precond_state
    kstate_u = states["unstacked"].precond_state
    down = convert_tree_layout(kstate_s, stacked=False)
    assert (jax.tree_util.tree_structure(down.factors)
            == jax.tree_util.tree_structure(kstate_u.factors))
    _assert_trees_equal(convert_tree_layout(down, stacked=True), kstate_s)
    # factor values agree between the natively-unstacked run and the
    # converted stacked run (same taps, different tree shapes)
    _assert_trees_equal(down.factors, kstate_u.factors, exact=False)


@pytest.mark.parametrize("save_layout", ["stacked", "unstacked"])
def test_checkpoint_cross_layout_restore(tmp_path, save_layout):
    """A checkpoint written under either layout resumes bit-exact into a
    model built with the other (restore_either_layout)."""
    cfg = TINY if save_layout == "stacked" else UNSTACKED
    model, params = _init_params(cfg)
    tx = lamb(1e-3, weight_decay_mask=default_weight_decay_mask,
              trust_batch_axes=default_trust_batch_axes)
    state = TrainState(step=jnp.asarray(7, jnp.int32), params=params,
                       opt_state=tx.init(params))

    mgr = CheckpointManager(str(tmp_path / "ckpts"))
    mgr.save(7, state, extra={"epoch": 1})
    mgr.wait()

    # same-layout restore still works through the tolerant entry point
    same = jax.eval_shape(lambda: state)
    restored, extra, step = mgr.restore_either_layout(same)
    assert step == 7 and extra["epoch"] == 1
    _assert_trees_equal(restored.params, state.params)

    # cross-layout: abstract template in the OTHER layout
    other = convert_tree_layout(state, stacked=(save_layout == "unstacked"))
    abstract = jax.eval_shape(lambda: other)
    restored2, _, _ = mgr.restore_either_layout(abstract)
    assert (tree_layout(restored2.params)
            == ("unstacked" if save_layout == "stacked" else "stacked"))
    _assert_trees_equal(restored2.params, other.params)
    _assert_trees_equal(restored2.opt_state, other.opt_state)
    mgr.close()


def test_tf_conversion_emits_unstacked_layout():
    """convert_tf_to_flax targets whichever layout the config asks for, and
    the two results are each other's conversions."""
    from bert_pytorch_tpu.models import convert_tf_to_flax
    from tests.test_pretrained import CFG, make_tf_vars

    tf_vars = make_tf_vars()
    got_s = convert_tf_to_flax(tf_vars, CFG)
    got_u = convert_tf_to_flax(tf_vars, CFG.replace(stacked_params=False))
    assert tree_layout(got_s) == "stacked"
    assert tree_layout(got_u) == "unstacked"
    jax.tree.map(lambda a, b: np.testing.assert_array_equal(
        np.asarray(a), np.asarray(b)), unstack_layer_tree(got_s), got_u)

    # the unstacked tree drops straight into the unstacked model
    model = BertForPreTraining(CFG.replace(stacked_params=False),
                               dtype=jnp.float32)
    ids, types, mask = _inputs()
    want = unbox(model.init(jax.random.PRNGKey(0),
                            jnp.asarray(np.asarray(ids) % CFG.vocab_size),
                            types, mask)["params"])
    assert (jax.tree_util.tree_structure(jax.tree.map(np.shape, got_u))
            == jax.tree_util.tree_structure(jax.tree.map(np.shape, want)))


def test_unstacked_remat_matches_no_remat():
    ids, types, mask = _inputs()
    m1 = BertForPreTraining(UNSTACKED, dtype=jnp.float32)
    m2 = BertForPreTraining(UNSTACKED.replace(checkpoint_activations=True),
                            dtype=jnp.float32)
    params = m1.init(jax.random.PRNGKey(0), ids, types, mask)
    out1, _ = m1.apply(params, ids, types, mask)
    out2, _ = m2.apply(params, ids, types, mask)
    np.testing.assert_allclose(np.asarray(out1), np.asarray(out2),
                               rtol=1e-5, atol=1e-5)
