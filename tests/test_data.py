"""Data-layer golden tests: masking semantics, segment/mask derivation,
sampler chunking + resume, shard streaming across file boundaries, legacy
premasked format."""

import os

import h5py
import numpy as np
import pytest

from bert_pytorch_tpu.data import masking
from bert_pytorch_tpu.data.sharded import (
    HostShardSampler,
    PretrainingDataLoader,
    ShardIndex,
)

SEQ = 32
MASK_ID = 3


def write_shard(path, n, seq=SEQ, seed=0, nsp=True, legacy=False,
                varied=False):
    """`varied=True` draws a different real length per row (the corpus shape
    sequence packing exists for); fixed-length otherwise."""
    rng = np.random.RandomState(seed)
    ids = rng.randint(5, 100, (n, seq)).astype(np.int32)
    ids[:, 0] = 1  # [CLS]
    if varied:
        specials = np.zeros((n, 3), np.int32)
        for i in range(n):
            last = rng.randint(7, seq - 1)  # second [SEP]
            sep1 = rng.randint(2, last - 2)
            ids[i, sep1] = 2
            ids[i, last] = 2
            ids[i, last + 1:] = 0
            specials[i] = [0, sep1, last]
    elif nsp:
        sep1, sep2 = seq // 2, seq - 4
        ids[:, sep1] = 2
        ids[:, sep2] = 2
        ids[:, sep2 + 1:] = 0
        specials = np.tile([0, sep1, sep2], (n, 1)).astype(np.int32)
    else:
        sep = seq - 4
        ids[:, sep] = 2
        ids[:, sep + 1:] = 0
        specials = np.tile([0, sep], (n, 1)).astype(np.int32)
    labels = rng.randint(0, 2, (n,)).astype(np.int8)
    with h5py.File(path, "w") as f:
        if legacy:
            # NVIDIA premasked schema (reference src/dataset.py:183-192)
            f.create_dataset("input_ids", data=ids)
            f.create_dataset("segment_ids", data=np.zeros_like(ids))
            f.create_dataset("input_mask", data=(ids != 0).astype(np.int32))
            pos = np.zeros((n, 5), np.int32)
            mids = np.zeros((n, 5), np.int32)
            pos[:, 0] = 2
            mids[:, 0] = ids[:, 2]
            f.create_dataset("masked_lm_positions", data=pos)
            f.create_dataset("masked_lm_ids", data=mids)
            f.create_dataset("next_sentence_labels", data=labels)
        else:
            f.create_dataset("input_ids", data=ids, compression="gzip")
            f.create_dataset("special_token_positions", data=specials,
                             compression="gzip")
            f.create_dataset("next_sentence_labels", data=labels,
                             compression="gzip")
    return ids, specials


# -- masking golden tests ---------------------------------------------------

def test_segment_ids_nsp_pair():
    ids = np.zeros((2, 12), np.int32)
    specials = np.array([[0, 4, 9], [0, 5, 10]], np.int32)
    seg = masking.segment_ids_from_specials(ids, specials)
    # segment 1 spans (first_sep, second_sep] (reference src/dataset.py:224-238)
    want0 = [0] * 5 + [1] * 5 + [0] * 2
    np.testing.assert_array_equal(seg[0], want0)
    assert seg[1, 5] == 0 and seg[1, 6] == 1 and seg[1, 10] == 1 \
        and seg[1, 11] == 0


def test_segment_ids_single_segment_all_zero():
    ids = np.zeros((2, 12), np.int32)
    specials = np.array([[0, 9], [0, 10]], np.int32)
    seg = masking.segment_ids_from_specials(ids, specials)
    assert (seg == 0).all()


def test_input_mask_covers_through_last_special():
    ids = np.zeros((1, 12), np.int32)
    specials = np.array([[0, 4, 9]], np.int32)
    m = masking.input_mask_from_specials(ids, specials)
    np.testing.assert_array_equal(m[0], [1] * 10 + [0] * 2)


def test_dynamic_mask_batch_semantics():
    rng = np.random.default_rng(0)
    B, S = 64, SEQ
    ids = np.random.RandomState(1).randint(5, 100, (B, S)).astype(np.int32)
    specials = np.tile([0, S // 2, S - 4], (B, 1)).astype(np.int32)
    masked, labels = masking.dynamic_mask_batch(
        ids, specials, mask_token_index=MASK_ID, max_pred_per_seq=5,
        masked_lm_prob=0.15, vocab_size=100, rng=rng)

    chosen = labels != -1
    # count per row: min(max_pred, max(1, floor(n_maskable * prob)))
    n_maskable = (S - 4 - 1) - 2  # positions < last special, minus specials
    want = min(5, max(1, int(n_maskable * 0.15)))
    np.testing.assert_array_equal(chosen.sum(1), want)

    # specials and padding never chosen
    assert not chosen[:, 0].any()
    assert not chosen[:, S // 2].any()
    assert not chosen[:, S - 4:].any()

    # labels hold ORIGINAL tokens; unchosen positions untouched
    np.testing.assert_array_equal(masked[~chosen], ids[~chosen])
    np.testing.assert_array_equal(labels[chosen], ids[chosen])

    # 80/10/10: over many positions, ~80% became [MASK]
    frac_mask = (masked[chosen] == MASK_ID).mean()
    assert 0.6 < frac_mask < 0.95


def test_dynamic_mask_deterministic_with_seed():
    ids = np.random.RandomState(1).randint(5, 100, (4, SEQ)).astype(np.int32)
    specials = np.tile([0, SEQ // 2, SEQ - 4], (4, 1)).astype(np.int32)
    out1 = masking.dynamic_mask_batch(ids, specials, MASK_ID, 5, 0.15, 100,
                                      np.random.default_rng(7))
    out2 = masking.dynamic_mask_batch(ids, specials, MASK_ID, 5, 0.15, 100,
                                      np.random.default_rng(7))
    np.testing.assert_array_equal(out1[0], out2[0])
    np.testing.assert_array_equal(out1[1], out2[1])


def test_labels_from_premasked():
    ids = np.zeros((2, 10), np.int32)
    pos = np.array([[2, 5, 0], [1, 0, 0]], np.int32)
    mids = np.array([[11, 22, 0], [33, 0, 0]], np.int32)
    labels = masking.labels_from_premasked(ids, pos, mids)
    assert labels[0, 2] == 11 and labels[0, 5] == 22
    assert (labels[0] != -1).sum() == 2
    assert labels[1, 1] == 33 and (labels[1] != -1).sum() == 1


# -- sampler ---------------------------------------------------------------

def test_sampler_contiguous_chunks_and_resume():
    s0 = HostShardSampler(100, world_size=4, rank=0)
    s3 = HostShardSampler(100, world_size=4, rank=3)
    assert s0.num_samples == 25
    i0 = s0.next_indices(5)
    i3 = s3.next_indices(5)
    np.testing.assert_array_equal(i0, np.arange(5))
    np.testing.assert_array_equal(i3, np.arange(75, 80))

    # resume mid-epoch
    state = s0.state_dict()
    s0b = HostShardSampler(100, world_size=4, rank=0)
    s0b.load_state_dict(state)
    np.testing.assert_array_equal(s0b.next_indices(5), s0.next_indices(5))

    # changed world size -> warn + skip restore (reference
    # src/dataset.py:410-422)
    s_other = HostShardSampler(100, world_size=2, rank=0)
    with pytest.warns(UserWarning):
        s_other.load_state_dict(state)
    assert s_other.index == 0


def test_sampler_epoch_end_and_wraparound():
    s = HostShardSampler(10, world_size=4, rank=3)  # padded: 3 samples/host
    idx = s.next_indices(3)
    # rank 3 chunk [9, 12) wraps to [9, 0, 1]
    np.testing.assert_array_equal(idx, [9, 0, 1])
    assert s.next_indices(1) is None  # epoch exhausted
    s.reset_epoch()
    assert s.epoch == 1 and s.index == 0


# -- loader ----------------------------------------------------------------

def test_loader_streams_across_shards(tmp_path):
    write_shard(tmp_path / "a.hdf5", 20, seed=0)
    write_shard(tmp_path / "b.hdf5", 20, seed=1)
    index = ShardIndex([str(tmp_path / "a.hdf5"), str(tmp_path / "b.hdf5")])
    assert len(index) == 40
    sampler = HostShardSampler(40, world_size=1, rank=0)
    loader = PretrainingDataLoader(index, sampler, batch_size=16,
                                   mask_token_index=MASK_ID,
                                   max_pred_per_seq=5, masked_lm_prob=0.15,
                                   vocab_size=100, seed=0)
    batches = list(loader)
    assert len(batches) == 2  # 40//16, tail dropped
    for b in batches:
        assert b["input_ids"].shape == (16, SEQ)
        assert b["masked_lm_labels"].shape == (16, SEQ)
        assert b["next_sentence_labels"].shape == (16,)
        assert (b["masked_lm_labels"] != -1).sum() > 0
    # second batch spans the a/b shard boundary (rows 16..31)
    loader.close()


def test_loader_prefetch_matches_sync(tmp_path):
    """prefetch_batches must change pacing only: identical batch stream
    (assembly is serialized on one thread, so the rng sequence matches the
    synchronous path), and state_dict reports the last YIELDED batch so a
    checkpoint taken mid-stream resumes exactly."""
    write_shard(tmp_path / "a.hdf5", 24, seed=0)
    write_shard(tmp_path / "b.hdf5", 24, seed=1)
    files = [str(tmp_path / "a.hdf5"), str(tmp_path / "b.hdf5")]

    def make(prefetch):
        index = ShardIndex(files)
        sampler = HostShardSampler(48, world_size=1, rank=0)
        return PretrainingDataLoader(
            index, sampler, batch_size=8, mask_token_index=MASK_ID,
            max_pred_per_seq=5, masked_lm_prob=0.15, vocab_size=100,
            seed=0, prefetch_batches=prefetch)

    sync, pre = make(0), make(3)
    sync_batches = list(sync)
    pre_batches = list(pre)
    assert len(sync_batches) == len(pre_batches) == 6
    for bs, bp in zip(sync_batches, pre_batches):
        for k in bs:
            np.testing.assert_array_equal(bs[k], bp[k])

    # state_dict must lag to the yielded position, not the assembled-ahead
    # sampler cursor
    pre2 = make(3)
    it = iter(pre2)
    next(it)
    next(it)
    state = pre2.state_dict()
    assert state["index"] == 16  # 2 batches of 8 yielded
    # a fresh loader restored from that state continues with batch 3's ROWS
    # (mask randomness legitimately differs — the rng is not checkpointed,
    # same as the sync path; compare the rng-independent fields)
    pre3 = make(2)
    pre3.load_state_dict(state)
    b3 = next(iter(pre3))
    np.testing.assert_array_equal(b3["next_sentence_labels"],
                                  sync_batches[2]["next_sentence_labels"])
    np.testing.assert_array_equal(b3["token_type_ids"],
                                  sync_batches[2]["token_type_ids"])
    # second epoch after reset re-yields from the chunk start
    pre4 = make(2)
    list(pre4)
    pre4.reset_epoch()
    again = next(iter(pre4))
    assert again["input_ids"].shape == (8, SEQ)
    for lo in (sync, pre, pre2, pre3, pre4):
        lo.close()


def test_loader_legacy_premasked(tmp_path):
    write_shard(tmp_path / "legacy.hdf5", 8, legacy=True)
    index = ShardIndex([str(tmp_path / "legacy.hdf5")])
    sampler = HostShardSampler(8, world_size=1, rank=0)
    loader = PretrainingDataLoader(index, sampler, batch_size=8,
                                   mask_token_index=MASK_ID,
                                   max_pred_per_seq=5, masked_lm_prob=0.15,
                                   vocab_size=100, seed=0)
    b = next(iter(loader))
    assert (b["masked_lm_labels"] != -1).sum() == 8  # one mask per row
    assert "token_type_ids" in b and "attention_mask" in b
    loader.close()


def test_reference_golden_files():
    """Cross-stack golden test: shards + expected tensors produced by the
    REFERENCE'S OWN CODE (scripts/make_reference_fixtures.py, run offline
    against /root/reference and committed under tests/fixtures). This
    framework's loader must reproduce the reference dataset's tensors from
    the same bytes (src/dataset.py:141-199 semantics) — the drop-in data
    compatibility claim, proven."""
    fixdir = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "fixtures")
    exp = np.load(os.path.join(fixdir, "ref_expected.npz"))

    # --- legacy premasked NVIDIA shard: everything is deterministic --------
    index = ShardIndex([os.path.join(fixdir, "ref_legacy.hdf5")])
    assert index.premasked_width == 5
    sampler = HostShardSampler(len(index), world_size=1, rank=0)
    loader = PretrainingDataLoader(index, sampler, batch_size=len(index),
                                   mask_token_index=3, max_pred_per_seq=5,
                                   masked_lm_prob=0.15, vocab_size=64, seed=0)
    b = next(iter(loader))
    np.testing.assert_array_equal(b["input_ids"],
                                  exp["legacy_masked_input_ids"])
    np.testing.assert_array_equal(b["token_type_ids"],
                                  exp["legacy_segment_ids"])
    np.testing.assert_array_equal(b["attention_mask"],
                                  exp["legacy_input_mask"])
    np.testing.assert_array_equal(b["masked_lm_labels"],
                                  exp["legacy_masked_lm_labels"])
    np.testing.assert_array_equal(b["next_sentence_labels"],
                                  exp["legacy_next_sentence_labels"])
    loader.close()

    # --- dynamic shard written by the reference's encode_data writer -------
    # Mask SELECTION is random on both sides (not comparable); the derived
    # fields and the raw stream must match the reference reader exactly.
    index = ShardIndex([os.path.join(fixdir, "ref_dynamic.hdf5")])
    sampler = HostShardSampler(len(index), world_size=1, rank=0)
    loader = PretrainingDataLoader(index, sampler, batch_size=len(index),
                                   mask_token_index=3, max_pred_per_seq=5,
                                   masked_lm_prob=0.15, vocab_size=64, seed=0)
    b = next(iter(loader))
    np.testing.assert_array_equal(b["token_type_ids"],
                                  exp["dynamic_segment_ids"])
    np.testing.assert_array_equal(b["attention_mask"],
                                  exp["dynamic_input_mask"])
    np.testing.assert_array_equal(b["next_sentence_labels"],
                                  exp["dynamic_next_sentence_labels"])
    # both sides reconstruct the ORIGINAL token stream exactly by undoing
    # their own masking via the labels (label != -1 holds the true token) —
    # so the underlying sample stream must agree bit-for-bit even though
    # the random mask selections differ
    ours = np.where(b["masked_lm_labels"] != -1, b["masked_lm_labels"],
                    b["input_ids"])
    ref = np.where(exp["dynamic_masked_lm_labels"] != -1,
                   exp["dynamic_masked_lm_labels"],
                   exp["dynamic_masked_input_ids"])
    np.testing.assert_array_equal(ours, ref)
    loader.close()


def _reconstruct_originals(batch):
    """Undo masking via the labels (label != -1 holds the true token) — the
    rng-independent view of the example stream, same trick as
    test_reference_golden_files."""
    return np.where(batch["masked_lm_labels"] != -1,
                    batch["masked_lm_labels"], batch["input_ids"])


# keys of a packed batch that do not depend on the (uncheckpointed) masking
# rng: the bin layout, segment structure and NSP fields
_PACKED_RNG_FREE = ("token_type_ids", "attention_mask", "segment_ids",
                    "position_ids", "next_sentence_labels", "nsp_positions")


def _make_packed_loader(files, n_samples, prefetch, batch_size=4,
                        lookahead=2, max_segments=4):
    index = ShardIndex(files)
    sampler = HostShardSampler(n_samples, world_size=1, rank=0)
    return PretrainingDataLoader(
        index, sampler, batch_size=batch_size, mask_token_index=MASK_ID,
        max_pred_per_seq=5, masked_lm_prob=0.15, vocab_size=100, seed=0,
        prefetch_batches=prefetch, packing=True,
        packing_max_segments=max_segments, packing_lookahead=lookahead)


def test_packed_loader_prefetch_matches_sync(tmp_path):
    """Packing + prefetch must change pacing only: assembly serializes on
    one thread in sampler order, so the packed batch stream (bins, masks,
    everything) is identical to the synchronous path's."""
    write_shard(tmp_path / "a.hdf5", 24, seed=0, varied=True)
    write_shard(tmp_path / "b.hdf5", 24, seed=1, varied=True)
    files = [str(tmp_path / "a.hdf5"), str(tmp_path / "b.hdf5")]

    sync = _make_packed_loader(files, 48, prefetch=0)
    pre = _make_packed_loader(files, 48, prefetch=3)
    sync_batches = list(sync)
    pre_batches = list(pre)
    assert len(sync_batches) == len(pre_batches) >= 2
    for bs, bp in zip(sync_batches, pre_batches):
        assert set(bs) == set(bp)
        for k in bs:
            np.testing.assert_array_equal(bs[k], bp[k])
    # rows genuinely packed (some row holds >= 2 segments)
    assert max(b["segment_ids"].max() for b in sync_batches) >= 2
    sync.close()
    pre.close()


def test_packed_loader_resume_determinism(tmp_path):
    """Satellite: a sampler-state checkpoint round-trip with
    prefetch_batches > 0 under packing produces the identical batch stream
    as an unbroken run — the pending-example buffer rides in state_dict, so
    the restored packer rebuilds the exact same bins. Mask randomness is
    legitimately uncheckpointed (same as the unpacked loader); everything
    rng-independent must match bit-for-bit, including the reconstructed
    original token stream."""
    write_shard(tmp_path / "a.hdf5", 24, seed=0, varied=True)
    write_shard(tmp_path / "b.hdf5", 24, seed=1, varied=True)
    files = [str(tmp_path / "a.hdf5"), str(tmp_path / "b.hdf5")]

    unbroken = _make_packed_loader(files, 48, prefetch=2)
    full_stream = list(unbroken)
    assert len(full_stream) >= 3
    unbroken.close()

    first = _make_packed_loader(files, 48, prefetch=2)
    it = iter(first)
    next(it)
    next(it)
    state = first.state_dict()
    first.close()
    # the packer was mid-buffer: pending indices are part of the state
    assert "pending" in state

    resumed = _make_packed_loader(files, 48, prefetch=2)
    resumed.load_state_dict(state)
    rest = list(resumed)
    resumed.close()
    assert len(rest) == len(full_stream) - 2
    for want, got in zip(full_stream[2:], rest):
        for k in _PACKED_RNG_FREE:
            np.testing.assert_array_equal(want[k], got[k], err_msg=k)
        np.testing.assert_array_equal(_reconstruct_originals(want),
                                      _reconstruct_originals(got))


def test_packed_loader_drops_pending_when_sampler_refuses(tmp_path):
    """If the sampler refuses its checkpoint (dataset/world-size changed,
    warned and reset), the packed pending buffer must be dropped with it —
    the checkpointed indices belong to the OLD index space and would gather
    wrong (or out-of-range) samples."""
    write_shard(tmp_path / "a.hdf5", 24, seed=0, varied=True)
    files = [str(tmp_path / "a.hdf5")]
    loader = _make_packed_loader(files, 24, prefetch=0)
    next(iter(loader))
    state = loader.state_dict()
    assert state["pending"]
    loader.close()

    # same dataset: pending restores
    same = _make_packed_loader(files, 24, prefetch=0)
    same.load_state_dict(state)
    assert same._pending_examples == [int(i) for i in state["pending"]]
    same.close()

    # "grown dataset" (different total_size): sampler warns + resets, and
    # the stale pending indices must go too
    grown = _make_packed_loader(files, 30, prefetch=0)
    with pytest.warns(UserWarning, match="total_size"):
        grown.load_state_dict(state)
    assert grown._pending_examples == []
    grown.close()


def test_packed_loader_close_idempotent_on_early_abort(tmp_path):
    """Satellite: close() is idempotent and safe while prefetch futures are
    in flight (consumer dropped mid-epoch) — a second close and a close
    after partial iteration must not hang or raise."""
    write_shard(tmp_path / "a.hdf5", 24, seed=0, varied=True)
    loader = _make_packed_loader([str(tmp_path / "a.hdf5")], 24, prefetch=3)
    it = iter(loader)
    next(it)  # prefetch queue now holds live futures
    loader.close()
    loader.close()  # idempotent
    assert loader._closed


def test_shard_index_skips_bad_files(tmp_path):
    write_shard(tmp_path / "good.hdf5", 8)
    (tmp_path / "bad.hdf5").write_bytes(b"not an hdf5 file")
    with pytest.warns(UserWarning):
        index = ShardIndex([str(tmp_path / "good.hdf5"),
                            str(tmp_path / "bad.hdf5")])
    assert len(index.files) == 1 and len(index) == 8


def test_loader_ctor_validation(tmp_path):
    write_shard(tmp_path / "x.hdf5", 4)
    index = ShardIndex([str(tmp_path / "x.hdf5")])
    sampler = HostShardSampler(4)
    with pytest.raises(ValueError):
        PretrainingDataLoader(index, sampler, 2, MASK_ID, 5,
                              masked_lm_prob=1.5, vocab_size=100)
    with pytest.raises(ValueError):
        PretrainingDataLoader(index, sampler, 2, MASK_ID, 5, 0.15,
                              vocab_size=100, original_token_prob=0.6,
                              random_token_prob=0.6)


# -- double-buffered h2d staging (round 11) ----------------------------------

def test_device_prefetcher_order_state_lag_and_tap():
    """DevicePrefetcher pulls `depth` units ahead (issuing the put early)
    but yields in order, fires the recorder tap at YIELD time (dispatch
    order, not loader order), and reports the upstream state snapshot of
    the last yielded pair — the checkpoint-coherence contract
    run_pretraining relies on under --h2d_prefetch."""
    from bert_pytorch_tpu.data.sharded import DevicePrefetcher

    state = {"i": 0}
    put_log, taps = [], []

    def source():
        for i in range(5):
            state["i"] = i + 1  # loader state advances at ITS yield
            yield {"x": i}

    def put(b):
        put_log.append(b["x"])
        return ("dev", b["x"])

    pf = DevicePrefetcher(source(), put, depth=2,
                          state_fn=lambda: dict(state),
                          batch_tap=lambda b: taps.append(b["x"]))
    assert pf.state_dict() == {"i": 0}  # nothing yielded yet

    it = iter(pf)
    first = next(it)
    assert first == ({"x": 0}, ("dev", 0))
    # depth=2: units 0..2 already pulled AND put before unit 0 was yielded
    assert put_log == [0, 1, 2]
    assert taps == [0]
    # state lags to the last YIELDED unit, not the loader's read-ahead
    assert pf.state_dict() == {"i": 1}
    assert state["i"] == 3

    rest = list(it)
    assert [b["x"] for b, _ in rest] == [1, 2, 3, 4]
    assert [d for _, d in rest] == [("dev", i) for i in range(1, 5)]
    assert taps == list(range(5))
    assert put_log == list(range(5))  # every unit put exactly once
    assert pf.state_dict() == {"i": 5}


def test_device_prefetcher_depth_zero_is_synchronous():
    from bert_pytorch_tpu.data.sharded import DevicePrefetcher

    order = []

    def source():
        for i in range(3):
            order.append(f"pull{i}")
            yield i

    pf = DevicePrefetcher(source(), lambda b: order.append(f"put{b}") or b,
                          depth=0)
    for np_b, dev_b in pf:
        order.append(f"use{np_b}")
    # strict pull -> put -> use interleaving: no read-ahead at depth 0
    assert order == ["pull0", "put0", "use0", "pull1", "put1", "use1",
                     "pull2", "put2", "use2"]
