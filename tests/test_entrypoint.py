"""End-to-end CLI test: run_pretraining.main() over synthesized shards on the
8-device CPU mesh — training runs, logs metrics, checkpoints, auto-resumes."""

import json
import os
import re
import sys

import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from tests.test_data import write_shard  # noqa: E402


@pytest.fixture
def workdir(tmp_path):
    data = tmp_path / "data"
    data.mkdir()
    for i in range(2):
        write_shard(data / f"shard_{i}.hdf5", 32, seed=i)
    model_cfg = {
        "vocab_size": 128, "hidden_size": 32, "num_hidden_layers": 2,
        "num_attention_heads": 4, "intermediate_size": 64,
        "max_position_embeddings": 64, "next_sentence": True,
        "hidden_dropout_prob": 0.0, "attention_probs_dropout_prob": 0.0,
        "tokenizer": "wordpiece", "fused_ops": False,
        "attention_impl": "xla",
    }
    cfg_path = tmp_path / "model_config.json"
    cfg_path.write_text(json.dumps(model_cfg))
    run_cfg = {
        "model_config_file": str(cfg_path),
        "learning_rate": 1e-3,
        "global_batch_size": 32,
        "local_batch_size": 2,       # 8 data shards -> micro_global 16, accum 2
        "max_steps": 3,
        "warmup_proportion": 0.1,
        "masked_token_fraction": 0.15,
        "max_predictions_per_seq": 5,
        "num_steps_per_checkpoint": 2,
        "log_prefix": "testlog",
    }
    run_path = tmp_path / "run_config.json"
    run_path.write_text(json.dumps(run_cfg))
    return tmp_path, data, run_path


def test_run_pretraining_end_to_end_and_resume(workdir):
    tmp_path, data, run_path = workdir
    import run_pretraining

    out = tmp_path / "out"
    argv = ["--config_file", str(run_path), "--input_dir", str(data),
            "--output_dir", str(out), "--mask_token_index", "3",
            "--dtype", "float32", "--vocab_pad_multiple", "8"]
    final_step, _ = run_pretraining.main(argv)
    assert final_step == 3

    log = (out / "testlog.txt").read_text()
    assert "step 1" in log and "step 3" in log
    assert "training_seq_per_sec" in log
    csv_rows = (out / "testlog_metrics.csv").read_text().strip().splitlines()
    assert len(csv_rows) >= 4  # header + 3 steps

    ckpts = os.listdir(out / "pretrain_ckpts")
    assert any("2" in c or "3" in c for c in ckpts)

    # auto-resume: bump max_steps, rerun -> continues from 3, not 0
    run_cfg = json.loads(run_path.read_text())
    run_cfg["max_steps"] = 5
    run_path.write_text(json.dumps(run_cfg))
    final_step2, _ = run_pretraining.main(argv)
    assert final_step2 == 5
    assert "auto-resumed from step 3" in (out / "testlog.txt").read_text()


@pytest.mark.slow
def test_run_pretraining_zero1_rs_smoke(workdir):
    """--zero1_rs + --fused_optim xla through the real entrypoint on the
    8-device CPU mesh: the plan reports the psum_scatter exit, training
    completes, metrics flow. Value parity and collective counts are pinned
    elsewhere (tests/test_zero1.py, the zero1_rs_dp8 budget) — this is the
    CLI wiring proof."""
    tmp_path, data, run_path = workdir
    import run_pretraining

    out = tmp_path / "out_rs"
    argv = ["--config_file", str(run_path), "--input_dir", str(data),
            "--output_dir", str(out), "--mask_token_index", "3",
            "--dtype", "float32", "--vocab_pad_multiple", "8",
            "--zero1", "true", "--zero1_rs", "--fused_optim", "xla",
            "--coalesce_reductions", "on"]
    final_step, _ = run_pretraining.main(argv)
    assert final_step == 3
    log = (tmp_path / "out_rs" / "testlog.txt").read_text()
    assert "psum_scatter grads" in log
    assert "--zero1_rs forces --zero1_overlap" in log

    # the K-FAC arm: the rs region emits partial factor statistics, so
    # the CLI must force bucketed factor reductions rather than surface
    # the step builder's ValueError
    out2 = tmp_path / "out_rs_kfac"
    final_step, _ = run_pretraining.main(
        ["--config_file", str(run_path), "--input_dir", str(data),
         "--output_dir", str(out2), "--mask_token_index", "3",
         "--dtype", "float32", "--vocab_pad_multiple", "8",
         "--zero1", "true", "--zero1_rs", "--kfac",
         "--kfac_stats_dtype", "bf16"])
    assert final_step == 3
    log2 = (out2 / "testlog.txt").read_text()
    assert "psum_scatter grads" in log2
    assert "--zero1_rs with --kfac forces --coalesce_reductions on" in log2


def test_init_checkpoint_seeds_weights(workdir):
    """--init_checkpoint seeds pretraining from a reference torch save
    (the GPU->TPU migration path): weights load and are reported, training
    proceeds from step 0, and auto-resume still wins on rerun."""
    torch = pytest.importorskip("torch")
    from tests.test_pretrained import make_tf_vars, tf_vars_to_torch_state

    tmp_path, data, run_path = workdir
    import run_pretraining

    ckdir = tmp_path / "reference_ckpt"
    ckdir.mkdir()
    tf_vars = make_tf_vars()
    state = {f"module.{k}": torch.tensor(v)
             for k, v in tf_vars_to_torch_state(tf_vars).items()}
    torch.save({"model": state}, ckdir / "ckpt_7038.pt")
    # reference layout: bert_config.json next to the .pt (vocab 100 — the
    # loader re-pads to this run's padded 128)
    (ckdir / "bert_config.json").write_text(json.dumps(
        {"vocab_size": 100, "hidden_size": 32, "num_hidden_layers": 2,
         "num_attention_heads": 4, "intermediate_size": 64,
         "max_position_embeddings": 64, "type_vocab_size": 2,
         "hidden_act": "gelu", "hidden_dropout_prob": 0.0,
         "attention_probs_dropout_prob": 0.0}))

    out = tmp_path / "out_seeded"
    argv = ["--config_file", str(run_path), "--input_dir", str(data),
            "--output_dir", str(out), "--mask_token_index", "3",
            "--dtype", "float32", "--vocab_pad_multiple", "8",
            "--init_checkpoint", str(ckdir / "ckpt_7038.pt")]
    final_step, _ = run_pretraining.main(argv)
    assert final_step == 3
    log = (out / "testlog.txt").read_text()
    m = re.search(r"loaded (\d+) param leaves, (\d+) fresh", log)
    assert m, log
    assert int(m.group(1)) > 20  # encoder + heads came across
    assert int(m.group(2)) == 0  # pretraining model: every subtree matched

    # rerun: the existing checkpoint wins over --init_checkpoint
    run_cfg = json.loads(run_path.read_text())
    run_cfg["max_steps"] = 4
    run_path.write_text(json.dumps(run_cfg))
    final2, _ = run_pretraining.main(argv)
    assert final2 == 4
    assert "auto-resumed from step 3" in (out / "testlog.txt").read_text()


@pytest.mark.slow  # re-tiered out of tier-1's 870s wall-clock budget
def test_two_phase_handoff(workdir):
    """Phase-2 resumes phase-1 state from the same output_dir, switches to a
    different-seq dataset (sampler resets via the total_size guard instead of
    restoring a stale cursor), and its schedule restarts warmup at
    previous_phase_end_step — the reference's seq128→seq512 handoff
    (run_pretraining.py:288-299, config/bert_pretraining_phase2_config.json)."""
    tmp_path, data128, run_path = workdir
    import run_pretraining

    data512 = tmp_path / "data512"
    data512.mkdir()
    for i in range(2):
        write_shard(data512 / f"shard_{i}.hdf5", 48, seq=64, seed=10 + i)

    out = tmp_path / "out_2phase"
    base = ["--config_file", str(run_path), "--output_dir", str(out),
            "--mask_token_index", "3", "--dtype", "float32",
            "--vocab_pad_multiple", "8"]
    final1, _ = run_pretraining.main(
        base + ["--input_dir", str(data128)])
    assert final1 == 3

    with pytest.warns(UserWarning, match="total_size"):
        final2, _ = run_pretraining.main(
            base + ["--input_dir", str(data512),
                    "--previous_phase_end_step", "3", "--max_steps", "4",
                    "--learning_rate", "2e-3", "--warmup_proportion", "0.5"])
    assert final2 == 7  # global step: 3 phase-1 + 4 phase-2

    log = (out / "testlog.txt").read_text()
    assert "auto-resumed from step 3" in log
    # schedule offset: the update logged at global step 5 consumed
    # schedule(4) = phase-local step 1 of a 2-step warmup -> lr = 2e-3 / 2;
    # without the offset phase 2 would already be deep into decay
    lr_by_step = {}
    for line in log.splitlines():
        m = re.search(r"step (\d+) .*learning_rate=([0-9.e+-]+)", line)
        if m:
            lr_by_step[int(m.group(1))] = float(m.group(2))
    assert lr_by_step[5] == pytest.approx(1e-3, rel=1e-2)


@pytest.mark.slow  # re-tiered out of tier-1's 870s wall-clock budget
def test_run_pretraining_with_kfac(workdir):
    tmp_path, data, run_path = workdir
    import run_pretraining

    out = tmp_path / "out_kfac"
    argv = ["--config_file", str(run_path), "--input_dir", str(data),
            "--output_dir", str(out), "--mask_token_index", "3",
            "--dtype", "float32", "--vocab_pad_multiple", "8",
            "--kfac", "--kfac_inv_interval", "2", "--max_steps", "2",
            "--skip_checkpoint"]
    final_step, _ = run_pretraining.main(argv)
    assert final_step == 2
    log = (out / "testlog.txt").read_text()
    assert "step 2" in log


def test_run_pretraining_production_pack_smoke(workdir):
    """ONE e2e smoke for the whole round-15 collective pack:
    --mesh_config production on a dp2 x fsdp4 mesh (explicit — 'auto'
    deliberately keeps the forced-CPU harness on base) engages packing +
    ZeRO-1 overlap + fsdp gather-on-use at once, --coalesce_reductions
    buckets the norm all-reduces, the run header records the named
    config, and a short run trains end to end."""
    tmp_path, data, run_path = workdir
    import run_pretraining

    out = tmp_path / "out_prod"
    argv = ["--config_file", str(run_path), "--input_dir", str(data),
            "--output_dir", str(out), "--mask_token_index", "3",
            "--dtype", "float32", "--vocab_pad_multiple", "8",
            "--mesh", "data=2,fsdp=4",
            "--mesh_config", "production",
            "--coalesce_reductions", "on"]
    final_step, _ = run_pretraining.main(argv)
    assert final_step == 3
    log = (out / "testlog.txt").read_text()
    assert "mesh_config=production" in log
    assert "packing=on" in log and "zero1_overlap=on" in log \
        and "fsdp_overlap=on" in log
    assert "fsdp_overlap: per-leaf gather-on-use over the 4-way fsdp " \
           "axis composed with the zero1 overlap" in log
    assert "coalesce_reductions: trust-norm/global-norm all-reduces " \
           "bucketed" in log
    # training completed under the combined plan (the jsonl metric
    # stream carries the per-step records; the run block's round-15 keys
    # are what tools/replay.py rebuilds the program from)
    jsonl = (out / "testlog.jsonl").read_text()
    assert '"step": 3' in jsonl


@pytest.mark.slow  # re-tiered out of tier-1's 870s wall-clock budget
def test_run_pretraining_packing_smoke(tmp_path):
    """Satellite: `run_pretraining.py --packing` over a varied-length corpus
    on the CPU mesh — trains for a few steps, checkpoints the packer state,
    and lands the health-pack and packing-efficiency fields in the metric
    sinks (jsonl + csv)."""
    import run_pretraining

    data = tmp_path / "data"
    data.mkdir()
    for i in range(2):
        write_shard(data / f"shard_{i}.hdf5", 48, seed=i, varied=True)
    model_cfg = {
        "vocab_size": 128, "hidden_size": 32, "num_hidden_layers": 2,
        "num_attention_heads": 4, "intermediate_size": 64,
        "max_position_embeddings": 64, "next_sentence": True,
        "hidden_dropout_prob": 0.0, "attention_probs_dropout_prob": 0.0,
        "tokenizer": "wordpiece", "fused_ops": False,
        "attention_impl": "xla",
    }
    cfg_path = tmp_path / "model_config.json"
    cfg_path.write_text(json.dumps(model_cfg))

    out = tmp_path / "out_packed"
    argv = ["--model_config_file", str(cfg_path),
            "--input_dir", str(data), "--output_dir", str(out),
            "--mask_token_index", "3", "--dtype", "float32",
            "--vocab_pad_multiple", "8", "--packing",
            "--packing_max_segments", "4", "--learning_rate", "1e-3",
            "--global_batch_size", "32", "--local_batch_size", "2",
            "--max_steps", "3", "--max_predictions_per_seq", "5",
            "--num_steps_per_checkpoint", "2", "--log_freq", "1",
            "--log_prefix", "testlog"]
    final_step, _ = run_pretraining.main(argv)
    assert final_step == 3

    log = (out / "testlog.txt").read_text()
    assert "packing on" in log
    assert "step 3" in log

    # perf records carry the packing-efficiency triple; with a
    # varied-length corpus packed rows beat the unpacked pad fraction
    perf = [json.loads(line)
            for line in (out / "testlog.jsonl").read_text().splitlines()
            if json.loads(line).get("tag") == "perf"]
    assert perf, "no perf records reached the jsonl sink"
    rec = perf[-1]
    # phase-agnostic schema contract: the pretrain perf record carries the
    # same core keys run_squad / run_ner assert on (telemetry/run.py —
    # every entry point wires through the one init_run path)
    from bert_pytorch_tpu.telemetry import PERF_RECORD_CORE_KEYS

    assert set(PERF_RECORD_CORE_KEYS) <= set(rec), rec
    for key in ("packing_efficiency", "pad_fraction",
                "real_tokens_per_sec"):
        assert key in rec, key
    assert 0.0 < rec["packing_efficiency"] <= 1.0
    assert abs(rec["packing_efficiency"] + rec["pad_fraction"] - 1.0) < 1e-5

    # health pack flows through the same sinks on the packed path
    train = [json.loads(line)
             for line in (out / "testlog.jsonl").read_text().splitlines()
             if json.loads(line).get("tag") == "train"]
    assert train and "loss_nonfinite" in train[-1]
    assert train[-1]["loss_nonfinite"] == 0
    csv_header = (out / "testlog_metrics.csv").read_text() \
        .splitlines()[0].split(",")
    assert "loss_nonfinite" in csv_header

    # resume restores the packer (pending buffer rides the checkpoint)
    final2, _ = run_pretraining.main(argv + ["--steps", "1",
                                             "--max_steps", "4"])
    assert final2 == 4
    assert "auto-resumed from step 3" in (out / "testlog.txt").read_text()


def test_cli_precedence(workdir):
    tmp_path, data, run_path = workdir
    import run_pretraining

    # CLI flag overrides run-config value (reference run_pretraining.py:152-166)
    args = run_pretraining.parse_arguments(
        ["--config_file", str(run_path), "--learning_rate", "9e-4"])
    assert args.learning_rate == 9e-4
    assert args.global_batch_size == 32  # from config
    assert args.lr_decay == "poly"       # parser default


def test_mesh_arg_parsing():
    import run_pretraining

    assert run_pretraining.parse_mesh_arg("") is None
    assert run_pretraining.parse_mesh_arg("data=4,model=2") == \
        {"data": 4, "model": 2}
